/*
 * R binding for lightgbm_tpu — .Call entry points over the C API
 * (include/lightgbm_tpu/c_api.h), the role the reference's
 * src/lightgbm_R.cpp:627 plays for its R package.
 *
 * Design differs from the reference deliberately: handles are R external
 * pointers with finalizers (no caller-managed handle SEXPs), errors
 * surface through Rf_error straight from LGBM_GetLastError, and the
 * surface is the subset the R front end in R/ actually drives.
 */
#include <stdlib.h>
#include <string.h>

#include <R.h>
#include <Rinternals.h>
#include <R_ext/Rdynload.h>

#include "lightgbm_tpu/c_api.h"

#define CHECK_CALL(x)                                      \
  if ((x) != 0) {                                          \
    Rf_error("lightgbm_tpu: %s", LGBM_GetLastError());     \
  }

static void* get_handle(SEXP ptr) {
  void* h = R_ExternalPtrAddr(ptr);
  if (h == NULL) {
    Rf_error("lightgbm_tpu: handle is NULL (already freed?)");
  }
  return h;
}

/* ---------- finalizers ---------- */

static void dataset_finalizer(SEXP ptr) {
  void* h = R_ExternalPtrAddr(ptr);
  if (h != NULL) {
    LGBM_DatasetFree(h);
    R_ClearExternalPtr(ptr);
  }
}

static void booster_finalizer(SEXP ptr) {
  void* h = R_ExternalPtrAddr(ptr);
  if (h != NULL) {
    LGBM_BoosterFree(h);
    R_ClearExternalPtr(ptr);
  }
}

static SEXP wrap_handle(void* h, void (*fin)(SEXP)) {
  SEXP ptr = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ptr, fin, TRUE);
  UNPROTECT(1);
  return ptr;
}

/* ---------- error ---------- */

SEXP LGBMTPU_GetLastError_R(void) {
  return Rf_mkString(LGBM_GetLastError());
}

/* ---------- Dataset ---------- */

SEXP LGBMTPU_DatasetCreateFromFile_R(SEXP filename, SEXP params,
                                     SEXP reference) {
  DatasetHandle h = NULL;
  DatasetHandle ref = reference == R_NilValue ? NULL
                                              : get_handle(reference);
  CHECK_CALL(LGBM_DatasetCreateFromFile(
      CHAR(STRING_ELT(filename, 0)), CHAR(STRING_ELT(params, 0)), ref,
      &h));
  return wrap_handle(h, dataset_finalizer);
}

SEXP LGBMTPU_DatasetCreateFromMat_R(SEXP mat, SEXP params, SEXP reference) {
  SEXP dim = Rf_getAttrib(mat, R_DimSymbol);
  if (dim == R_NilValue || Rf_length(dim) != 2) {
    Rf_error("lightgbm_tpu: data must be a numeric matrix");
  }
  int nrow = INTEGER(dim)[0];
  int ncol = INTEGER(dim)[1];
  DatasetHandle ref =
      Rf_isNull(reference) ? NULL : get_handle(reference);
  DatasetHandle h = NULL;
  /* R matrices are column-major doubles */
  CHECK_CALL(LGBM_DatasetCreateFromMat(
      REAL(mat), C_API_DTYPE_FLOAT64, nrow, ncol, 0,
      CHAR(STRING_ELT(params, 0)), ref, &h));
  return wrap_handle(h, dataset_finalizer);
}

SEXP LGBMTPU_DatasetSetField_R(SEXP handle, SEXP name, SEXP vec) {
  const char* field = CHAR(STRING_ELT(name, 0));
  int n = Rf_length(vec);
  /* group/query boundaries are int32; everything else float32 */
  if (strcmp(field, "group") == 0 || strcmp(field, "query") == 0) {
    int* buf = (int*)R_alloc(n, sizeof(int));
    for (int i = 0; i < n; ++i) buf[i] = INTEGER(vec)[i];
    CHECK_CALL(LGBM_DatasetSetField(get_handle(handle), field, buf, n,
                                    C_API_DTYPE_INT32));
  } else {
    float* buf = (float*)R_alloc(n, sizeof(float));
    double* src = REAL(vec);
    for (int i = 0; i < n; ++i) buf[i] = (float)src[i];
    CHECK_CALL(LGBM_DatasetSetField(get_handle(handle), field, buf, n,
                                    C_API_DTYPE_FLOAT32));
  }
  return R_NilValue;
}

SEXP LGBMTPU_DatasetGetNumData_R(SEXP handle) {
  int n = 0;
  CHECK_CALL(LGBM_DatasetGetNumData(get_handle(handle), &n));
  return Rf_ScalarInteger(n);
}

SEXP LGBMTPU_DatasetGetNumFeature_R(SEXP handle) {
  int n = 0;
  CHECK_CALL(LGBM_DatasetGetNumFeature(get_handle(handle), &n));
  return Rf_ScalarInteger(n);
}

/* The C API's name getters strcpy into caller buffers with no length
 * parameter (the reference contract, c_api.cpp:712), so the set path
 * must enforce the bound the get path allocates. */
#define LGBMTPU_MAX_NAME 4096

SEXP LGBMTPU_DatasetSetFeatureNames_R(SEXP handle, SEXP names) {
  int n = Rf_length(names);
  const char** arr =
      (const char**)R_alloc(n, sizeof(const char*));
  for (int i = 0; i < n; ++i) {
    const char* s = CHAR(STRING_ELT(names, i));
    if (strlen(s) >= LGBMTPU_MAX_NAME) {
      Rf_error("lightgbm_tpu: feature name %d exceeds %d characters",
               i + 1, LGBMTPU_MAX_NAME - 1);
    }
    arr[i] = s;
  }
  CHECK_CALL(LGBM_DatasetSetFeatureNames(get_handle(handle), arr, n));
  return R_NilValue;
}

SEXP LGBMTPU_DatasetGetFeatureNames_R(SEXP handle) {
  int n = 0;
  CHECK_CALL(LGBM_DatasetGetNumFeature(get_handle(handle), &n));
  char** buf = (char**)R_alloc(n, sizeof(char*));
  for (int i = 0; i < n; ++i) {
    buf[i] = (char*)R_alloc(LGBMTPU_MAX_NAME, 1);
    buf[i][0] = '\0';
  }
  int got = 0;
  CHECK_CALL(LGBM_DatasetGetFeatureNames(get_handle(handle), buf, &got));
  SEXP out = PROTECT(Rf_allocVector(STRSXP, got));
  for (int i = 0; i < got; ++i) {
    SET_STRING_ELT(out, i, Rf_mkChar(buf[i]));
  }
  UNPROTECT(1);
  return out;
}

SEXP LGBMTPU_DatasetGetField_R(SEXP handle, SEXP name) {
  int len = 0, dtype = -1;
  const void* ptr = NULL;
  CHECK_CALL(LGBM_DatasetGetField(get_handle(handle),
                                  CHAR(STRING_ELT(name, 0)), &len, &ptr,
                                  &dtype));
  SEXP out;
  if (dtype == 0) {                       /* C_API_DTYPE_FLOAT32 */
    out = PROTECT(Rf_allocVector(REALSXP, len));
    for (int i = 0; i < len; ++i)
      REAL(out)[i] = (double)((const float*)ptr)[i];
  } else if (dtype == 1) {                /* FLOAT64 */
    out = PROTECT(Rf_allocVector(REALSXP, len));
    for (int i = 0; i < len; ++i)
      REAL(out)[i] = ((const double*)ptr)[i];
  } else {                                /* INT32 (group boundaries) */
    out = PROTECT(Rf_allocVector(INTSXP, len));
    for (int i = 0; i < len; ++i)
      INTEGER(out)[i] = ((const int*)ptr)[i];
  }
  UNPROTECT(1);
  return out;
}

SEXP LGBMTPU_BoosterGetFeatureNames_R(SEXP handle) {
  int n = 0;
  CHECK_CALL(LGBM_BoosterGetNumFeature(get_handle(handle), &n));
  char** buf = (char**)R_alloc(n, sizeof(char*));
  for (int i = 0; i < n; ++i) {
    buf[i] = (char*)R_alloc(LGBMTPU_MAX_NAME, 1);
    buf[i][0] = '\0';
  }
  int got = 0;
  CHECK_CALL(LGBM_BoosterGetFeatureNames(get_handle(handle), &got, buf));
  SEXP out = PROTECT(Rf_allocVector(STRSXP, got));
  for (int i = 0; i < got; ++i) {
    SET_STRING_ELT(out, i, Rf_mkChar(buf[i]));
  }
  UNPROTECT(1);
  return out;
}

SEXP LGBMTPU_DatasetSaveBinary_R(SEXP handle, SEXP filename) {
  CHECK_CALL(LGBM_DatasetSaveBinary(get_handle(handle),
                                    CHAR(STRING_ELT(filename, 0))));
  return R_NilValue;
}

SEXP LGBMTPU_DatasetFree_R(SEXP handle) {
  dataset_finalizer(handle);
  return R_NilValue;
}

/* ---------- Booster ---------- */

SEXP LGBMTPU_BoosterCreate_R(SEXP train, SEXP params) {
  BoosterHandle h = NULL;
  CHECK_CALL(LGBM_BoosterCreate(get_handle(train),
                                CHAR(STRING_ELT(params, 0)), &h));
  return wrap_handle(h, booster_finalizer);
}

SEXP LGBMTPU_BoosterCreateFromModelfile_R(SEXP filename) {
  BoosterHandle h = NULL;
  int iters = 0;
  CHECK_CALL(LGBM_BoosterCreateFromModelfile(
      CHAR(STRING_ELT(filename, 0)), &iters, &h));
  SEXP ptr = PROTECT(wrap_handle(h, booster_finalizer));
  Rf_setAttrib(ptr, Rf_install("num_iterations"),
               Rf_ScalarInteger(iters));
  UNPROTECT(1);
  return ptr;
}

SEXP LGBMTPU_BoosterLoadModelFromString_R(SEXP model_str) {
  BoosterHandle h = NULL;
  int iters = 0;
  CHECK_CALL(LGBM_BoosterLoadModelFromString(
      CHAR(STRING_ELT(model_str, 0)), &iters, &h));
  return wrap_handle(h, booster_finalizer);
}

SEXP LGBMTPU_BoosterAddValidData_R(SEXP handle, SEXP valid) {
  CHECK_CALL(LGBM_BoosterAddValidData(get_handle(handle),
                                      get_handle(valid)));
  return R_NilValue;
}

SEXP LGBMTPU_BoosterResetParameter_R(SEXP handle, SEXP params) {
  CHECK_CALL(LGBM_BoosterResetParameter(get_handle(handle),
                                        CHAR(STRING_ELT(params, 0))));
  return R_NilValue;
}

SEXP LGBMTPU_BoosterUpdateOneIter_R(SEXP handle) {
  int finished = 0;
  CHECK_CALL(LGBM_BoosterUpdateOneIter(get_handle(handle), &finished));
  return Rf_ScalarLogical(finished);
}

SEXP LGBMTPU_BoosterRollbackOneIter_R(SEXP handle) {
  CHECK_CALL(LGBM_BoosterRollbackOneIter(get_handle(handle)));
  return R_NilValue;
}

SEXP LGBMTPU_BoosterGetCurrentIteration_R(SEXP handle) {
  int it = 0;
  CHECK_CALL(LGBM_BoosterGetCurrentIteration(get_handle(handle), &it));
  return Rf_ScalarInteger(it);
}

SEXP LGBMTPU_BoosterGetNumClasses_R(SEXP handle) {
  int n = 0;
  CHECK_CALL(LGBM_BoosterGetNumClasses(get_handle(handle), &n));
  return Rf_ScalarInteger(n);
}

SEXP LGBMTPU_BoosterGetEvalNames_R(SEXP handle) {
  int n = 0;
  CHECK_CALL(LGBM_BoosterGetEvalCounts(get_handle(handle), &n));
  char** buf = (char**)R_alloc(n > 0 ? n : 1, sizeof(char*));
  for (int i = 0; i < n; ++i) {
    buf[i] = (char*)R_alloc(LGBMTPU_MAX_NAME, 1);
    buf[i][0] = '\0';
  }
  int got = 0;
  CHECK_CALL(LGBM_BoosterGetEvalNames(get_handle(handle), &got, buf));
  SEXP out = PROTECT(Rf_allocVector(STRSXP, got));
  for (int i = 0; i < got; ++i) {
    SET_STRING_ELT(out, i, Rf_mkChar(buf[i]));
  }
  UNPROTECT(1);
  return out;
}

SEXP LGBMTPU_BoosterGetEval_R(SEXP handle, SEXP data_idx) {
  int n = 0;
  CHECK_CALL(LGBM_BoosterGetEvalCounts(get_handle(handle), &n));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, n));
  int got = 0;
  CHECK_CALL(LGBM_BoosterGetEval(get_handle(handle),
                                 Rf_asInteger(data_idx), &got,
                                 REAL(out)));
  SEXP trimmed = out;
  if (got != n) {
    trimmed = PROTECT(Rf_allocVector(REALSXP, got));
    memcpy(REAL(trimmed), REAL(out), got * sizeof(double));
    UNPROTECT(2);
    return trimmed;
  }
  UNPROTECT(1);
  return out;
}

SEXP LGBMTPU_BoosterPredictForMat_R(SEXP handle, SEXP mat,
                                    SEXP predict_type,
                                    SEXP num_iteration, SEXP params) {
  SEXP dim = Rf_getAttrib(mat, R_DimSymbol);
  if (dim == R_NilValue || Rf_length(dim) != 2) {
    Rf_error("lightgbm_tpu: data must be a numeric matrix");
  }
  int nrow = INTEGER(dim)[0];
  int ncol = INTEGER(dim)[1];
  int ptype = Rf_asInteger(predict_type);
  int niter = Rf_asInteger(num_iteration);
  int64_t want = 0;
  CHECK_CALL(LGBM_BoosterCalcNumPredict(get_handle(handle), nrow, ptype,
                                        niter, &want));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, (R_xlen_t)want));
  int64_t got = 0;
  CHECK_CALL(LGBM_BoosterPredictForMat(
      get_handle(handle), REAL(mat), C_API_DTYPE_FLOAT64, nrow, ncol, 0,
      ptype, niter, CHAR(STRING_ELT(params, 0)), &got, REAL(out)));
  UNPROTECT(1);
  return out;
}

SEXP LGBMTPU_BoosterSaveModel_R(SEXP handle, SEXP num_iteration,
                                SEXP filename) {
  CHECK_CALL(LGBM_BoosterSaveModel(get_handle(handle), 0,
                                   Rf_asInteger(num_iteration),
                                   CHAR(STRING_ELT(filename, 0))));
  return R_NilValue;
}

SEXP LGBMTPU_BoosterSaveModelToString_R(SEXP handle, SEXP num_iteration) {
  int niter = Rf_asInteger(num_iteration);
  int64_t len = 0;
  /* first call sizes the buffer, second fills it */
  CHECK_CALL(LGBM_BoosterSaveModelToString(get_handle(handle), 0, niter,
                                           0, &len, NULL));
  char* buf = (char*)R_alloc((size_t)len + 1, 1);
  int64_t got = 0;
  CHECK_CALL(LGBM_BoosterSaveModelToString(get_handle(handle), 0, niter,
                                           len + 1, &got, buf));
  return Rf_mkString(buf);
}

SEXP LGBMTPU_BoosterGetNumFeature_R(SEXP handle) {
  int n = 0;
  CHECK_CALL(LGBM_BoosterGetNumFeature(get_handle(handle), &n));
  return Rf_ScalarInteger(n);
}

SEXP LGBMTPU_BoosterFeatureImportance_R(SEXP handle, SEXP num_iteration,
                                        SEXP importance_type) {
  int n = 0;
  CHECK_CALL(LGBM_BoosterGetNumFeature(get_handle(handle), &n));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, n));
  CHECK_CALL(LGBM_BoosterFeatureImportance(get_handle(handle),
                                           Rf_asInteger(num_iteration),
                                           Rf_asInteger(importance_type),
                                           REAL(out)));
  UNPROTECT(1);
  return out;
}

SEXP LGBMTPU_BoosterDumpModel_R(SEXP handle, SEXP num_iteration) {
  int niter = Rf_asInteger(num_iteration);
  int64_t len = 0;
  /* first call sizes the JSON, second fills it */
  CHECK_CALL(LGBM_BoosterDumpModel(get_handle(handle), 0, niter, 0, &len,
                                   NULL));
  char* buf = (char*)R_alloc((size_t)len + 1, 1);
  int64_t got = 0;
  CHECK_CALL(LGBM_BoosterDumpModel(get_handle(handle), 0, niter, len + 1,
                                   &got, buf));
  return Rf_mkString(buf);
}

SEXP LGBMTPU_BoosterFree_R(SEXP handle) {
  booster_finalizer(handle);
  return R_NilValue;
}

/* ---------- registration ---------- */

#define CALLDEF(name, n) {#name, (DL_FUNC)&name, n}

static const R_CallMethodDef CallEntries[] = {
    CALLDEF(LGBMTPU_GetLastError_R, 0),
    CALLDEF(LGBMTPU_DatasetCreateFromFile_R, 3),
    CALLDEF(LGBMTPU_DatasetCreateFromMat_R, 3),
    CALLDEF(LGBMTPU_DatasetSetField_R, 3),
    CALLDEF(LGBMTPU_DatasetGetNumData_R, 1),
    CALLDEF(LGBMTPU_DatasetGetNumFeature_R, 1),
    CALLDEF(LGBMTPU_DatasetSetFeatureNames_R, 2),
    CALLDEF(LGBMTPU_DatasetGetFeatureNames_R, 1),
    CALLDEF(LGBMTPU_DatasetSaveBinary_R, 2),
    CALLDEF(LGBMTPU_DatasetFree_R, 1),
    CALLDEF(LGBMTPU_BoosterCreate_R, 2),
    CALLDEF(LGBMTPU_BoosterCreateFromModelfile_R, 1),
    CALLDEF(LGBMTPU_BoosterLoadModelFromString_R, 1),
    CALLDEF(LGBMTPU_BoosterAddValidData_R, 2),
    CALLDEF(LGBMTPU_BoosterResetParameter_R, 2),
    CALLDEF(LGBMTPU_BoosterUpdateOneIter_R, 1),
    CALLDEF(LGBMTPU_BoosterRollbackOneIter_R, 1),
    CALLDEF(LGBMTPU_BoosterGetCurrentIteration_R, 1),
    CALLDEF(LGBMTPU_BoosterGetNumClasses_R, 1),
    CALLDEF(LGBMTPU_BoosterGetEvalNames_R, 1),
    CALLDEF(LGBMTPU_BoosterGetEval_R, 2),
    CALLDEF(LGBMTPU_BoosterPredictForMat_R, 5),
    CALLDEF(LGBMTPU_BoosterSaveModel_R, 3),
    CALLDEF(LGBMTPU_BoosterSaveModelToString_R, 2),
    CALLDEF(LGBMTPU_BoosterGetNumFeature_R, 1),
    CALLDEF(LGBMTPU_BoosterGetFeatureNames_R, 1),
    CALLDEF(LGBMTPU_DatasetGetField_R, 2),
    CALLDEF(LGBMTPU_BoosterFeatureImportance_R, 3),
    CALLDEF(LGBMTPU_BoosterDumpModel_R, 2),
    CALLDEF(LGBMTPU_BoosterFree_R, 1),
    {NULL, NULL, 0}};

void R_init_lightgbm_tpu(DllInfo* dll) {
  R_registerRoutines(dll, NULL, CallEntries, NULL, NULL);
  R_useDynamicSymbols(dll, FALSE);
}
