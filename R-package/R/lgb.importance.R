# Feature importance and model introspection (the role of the reference
# R-package's lgb.importance.R / lgb.dump.R over
# LGBM_BoosterFeatureImportance; reference R surface:
# /root/reference/R-package/R/lgb.importance.R).

#' Feature importance of a trained model
#'
#' @param booster lgb.Booster.tpu.
#' @param percentage normalize each column to sum to 1.
#' @param num_iteration iterations to credit (-1 = all).
#' @return data.frame with Feature / Gain / Split columns, sorted by
#'   Gain descending (the reference returns the same three columns).
lgb.importance <- function(booster, percentage = TRUE,
                           num_iteration = -1L) {
  stopifnot(inherits(booster, "lgb.Booster.tpu"))
  niter <- as.integer(num_iteration)
  splits <- .Call(LGBMTPU_BoosterFeatureImportance_R, booster$ptr,
                  niter, 0L)   # C_API_FEATURE_IMPORTANCE_SPLIT
  gains <- .Call(LGBMTPU_BoosterFeatureImportance_R, booster$ptr,
                 niter, 1L)    # C_API_FEATURE_IMPORTANCE_GAIN
  feats <- NULL
  if (!is.null(booster$train_set)) {
    feats <- tryCatch(
      .Call(LGBMTPU_DatasetGetFeatureNames_R, booster$train_set$ptr),
      error = function(e) NULL)
  }
  if (is.null(feats) || length(feats) != length(splits)) {
    feats <- paste0("Column_", seq_along(splits) - 1L)
  }
  if (isTRUE(percentage)) {
    if (sum(gains) > 0) gains <- gains / sum(gains)
    if (sum(splits) > 0) splits <- splits / sum(splits)
  }
  out <- data.frame(Feature = feats, Gain = gains, Split = splits,
                    stringsAsFactors = FALSE)
  out[order(-out$Gain), , drop = FALSE]
}

#' Dump a model to a JSON string
#'
#' @param booster lgb.Booster.tpu.
#' @param num_iteration iterations to dump (-1 = all).
lgb.dump <- function(booster, num_iteration = -1L) {
  stopifnot(inherits(booster, "lgb.Booster.tpu"))
  .Call(LGBMTPU_BoosterDumpModel_R, booster$ptr,
        as.integer(num_iteration))
}

#' Plot feature importance as a horizontal bar chart
#'
#' @param tree_imp data.frame from lgb.importance().
#' @param top_n number of features to show.
#' @param measure importance column to plot ("Gain" or "Split" — the
#'   columns lgb.importance produces).
lgb.plot.importance <- function(tree_imp, top_n = 10L,
                                measure = "Gain", ...) {
  if (!measure %in% setdiff(colnames(tree_imp), "Feature")) {
    stop("measure must be one of ", paste(setdiff(
      colnames(tree_imp), "Feature"), collapse = ", "))
  }
  df <- tree_imp[order(tree_imp[[measure]], decreasing = TRUE), ]
  df <- utils::head(df, top_n)
  df <- df[rev(seq_len(nrow(df))), ]
  graphics::barplot(df[[measure]], names.arg = df$Feature, horiz = TRUE,
                    las = 1L, main = "Feature importance",
                    xlab = measure, ...)
  invisible(df)
}
