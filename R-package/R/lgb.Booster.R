# Booster training / prediction / model IO over the C API (the role of
# the reference R-package's lgb.Booster.R + lgb.train.R, redesigned:
# plain lists + external pointers, errors via Rf_error from the shim).

#' Train a lightgbm_tpu model
#'
#' @param params named list of training parameters (objective,
#'   num_leaves, learning_rate, ...).
#' @param data lgb.Dataset with the training data.
#' @param nrounds number of boosting iterations.
#' @param valids named list of lgb.Dataset objects to evaluate.
#' @param verbose print evaluation results every `eval_freq` rounds.
#' @param eval_freq evaluation print frequency.
#' @param early_stopping_rounds stop when the first valid's first metric
#'   has not improved for this many rounds (NULL = never); the kept
#'   model is rolled back to the best iteration, mirroring the
#'   reference's early-stopping callback semantics.
#' @param record keep per-round eval values in `$record_evals`.
lgb.train <- function(params = list(), data, nrounds = 100L,
                      valids = list(), verbose = 1L, eval_freq = 1L,
                      early_stopping_rounds = NULL, record = TRUE) {
  stopifnot(inherits(data, "lgb.Dataset.tpu"))
  pstr <- .params_to_string(params)
  ptr <- .Call(LGBMTPU_BoosterCreate_R, data$ptr, pstr)
  bst <- list(ptr = ptr, train_set = data, valids = valids)
  class(bst) <- "lgb.Booster.tpu"
  for (vd in valids) {
    stopifnot(inherits(vd, "lgb.Dataset.tpu"))
    .Call(LGBMTPU_BoosterAddValidData_R, ptr, vd$ptr)
  }
  vnames <- names(valids)
  if (is.null(vnames)) vnames <- rep("", length(valids))
  blank <- !nzchar(vnames)
  vnames[blank] <- paste0("valid_", seq_along(valids))[blank]
  eval_names <- NULL
  record_evals <- list()
  es <- .es_new()
  watch_early <- !is.null(early_stopping_rounds) && length(valids) > 0L
  for (i in seq_len(nrounds)) {
    finished <- .Call(LGBMTPU_BoosterUpdateOneIter_R, ptr)
    if (length(valids) > 0L &&
        (watch_early || isTRUE(record) ||
         (verbose > 0L && i %% eval_freq == 0L))) {
      if (is.null(eval_names)) {
        eval_names <- .Call(LGBMTPU_BoosterGetEvalNames_R, ptr)
        if (watch_early && length(eval_names) == 0L) {
          stop("early_stopping_rounds requires at least one eval ",
               "metric (the booster was configured with no metric)")
        }
      }
      for (j in seq_along(valids)) {
        ev <- .Call(LGBMTPU_BoosterGetEval_R, ptr, j)  # 1-based: valid_j
        vname <- vnames[j]
        if (isTRUE(record)) {
          if (is.null(record_evals[[vname]])) {
            record_evals[[vname]] <-
              matrix(NA_real_, nrounds, length(eval_names),
                     dimnames = list(NULL, eval_names))
          }
          record_evals[[vname]][i, ] <- ev
        }
        if (verbose > 0L && (i %% eval_freq == 0L)) {
          message(sprintf("[%d] %s: %s", i, vname,
                          paste(eval_names, signif(ev, 6),
                                sep = "=", collapse = " ")))
        }
        if (watch_early && j == 1L) {
          es <- .es_step(es, ev[1L],
                         .metric_higher_better(eval_names[1L]), i)
        }
      }
      if (watch_early && es$stale >= early_stopping_rounds) {
        if (verbose > 0L) {
          message(sprintf("early stop at round %d (best %d: %s=%g)",
                          i, es$best_iter, eval_names[1L], es$best))
        }
        # discard the trailing non-improving trees, the reference
        # callback's best_iteration contract
        for (k in seq_len(i - es$best_iter)) {
          .Call(LGBMTPU_BoosterRollbackOneIter_R, ptr)
        }
        break
      }
    }
    if (isTRUE(finished)) {
      break
    }
  }
  bst$best_iter <-
    if (watch_early && es$best_iter > 0L) es$best_iter else
      .Call(LGBMTPU_BoosterGetCurrentIteration_R, ptr)
  bst$record_evals <- record_evals
  bst
}

#' Predict with a trained model
#'
#' @param object lgb.Booster.tpu.
#' @param newdata numeric matrix.
#' @param rawscore return margins instead of transformed scores.
#' @param predleaf return per-tree leaf indices.
#' @param num_iteration number of iterations to use (-1 = all).
predict.lgb.Booster.tpu <- function(object, newdata, rawscore = FALSE,
                                    predleaf = FALSE,
                                    num_iteration = -1L, ...) {
  newdata <- as.matrix(newdata)
  storage.mode(newdata) <- "double"
  ptype <- 0L                      # C_API_PREDICT_NORMAL
  if (isTRUE(rawscore)) ptype <- 1L
  if (isTRUE(predleaf)) ptype <- 2L
  out <- .Call(LGBMTPU_BoosterPredictForMat_R, object$ptr, newdata,
               ptype, as.integer(num_iteration), "")
  n <- nrow(newdata)
  if (length(out) > n && length(out) %% n == 0L) {
    # multiclass / leaf-index outputs come back row-major [n, k]
    matrix(out, nrow = n, byrow = TRUE)
  } else {
    out
  }
}

#' Save a model to the reference text format
lgb.save <- function(booster, filename, num_iteration = -1L) {
  stopifnot(inherits(booster, "lgb.Booster.tpu"))
  .Call(LGBMTPU_BoosterSaveModel_R, booster$ptr,
        as.integer(num_iteration), filename)
  invisible(booster)
}

#' Load a model from a text model file
lgb.load <- function(filename) {
  ptr <- .Call(LGBMTPU_BoosterCreateFromModelfile_R, filename)
  bst <- list(ptr = ptr)
  class(bst) <- "lgb.Booster.tpu"
  bst
}

#' Serialize a model to a string
lgb.model.to.string <- function(booster, num_iteration = -1L) {
  .Call(LGBMTPU_BoosterSaveModelToString_R, booster$ptr,
        as.integer(num_iteration))
}

#' Evaluation results for a data index (0 = train, 1.. = valids)
lgb.get.eval <- function(booster, data_idx = 0L) {
  ev <- .Call(LGBMTPU_BoosterGetEval_R, booster$ptr,
              as.integer(data_idx))
  names(ev) <- .Call(LGBMTPU_BoosterGetEvalNames_R, booster$ptr)
  ev
}
