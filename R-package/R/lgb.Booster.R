# Booster training / prediction / model IO over the C API (the role of
# the reference R-package's lgb.Booster.R + lgb.train.R, redesigned:
# plain lists + external pointers, errors via Rf_error from the shim).

#' Train a lightgbm_tpu model
#'
#' @param params named list of training parameters (objective,
#'   num_leaves, learning_rate, ...).
#' @param data lgb.Dataset with the training data.
#' @param nrounds number of boosting iterations.
#' @param valids named list of lgb.Dataset objects to evaluate.
#' @param verbose print evaluation results every `eval_freq` rounds.
#' @param eval_freq evaluation print frequency.
lgb.train <- function(params = list(), data, nrounds = 100L,
                      valids = list(), verbose = 1L, eval_freq = 1L) {
  stopifnot(inherits(data, "lgb.Dataset.tpu"))
  pstr <- .params_to_string(params)
  ptr <- .Call(LGBMTPU_BoosterCreate_R, data$ptr, pstr)
  bst <- list(ptr = ptr, train_set = data, valids = valids)
  class(bst) <- "lgb.Booster.tpu"
  for (vd in valids) {
    stopifnot(inherits(vd, "lgb.Dataset.tpu"))
    .Call(LGBMTPU_BoosterAddValidData_R, ptr, vd$ptr)
  }
  eval_names <- NULL
  for (i in seq_len(nrounds)) {
    finished <- .Call(LGBMTPU_BoosterUpdateOneIter_R, ptr)
    if (verbose > 0L && length(valids) > 0L &&
        (i %% eval_freq == 0L)) {
      if (is.null(eval_names)) {
        eval_names <- .Call(LGBMTPU_BoosterGetEvalNames_R, ptr)
      }
      for (j in seq_along(valids)) {
        ev <- .Call(LGBMTPU_BoosterGetEval_R, ptr, j)  # 1-based: valid_j
        message(sprintf("[%d] %s: %s", i, names(valids)[j],
                        paste(eval_names, signif(ev, 6),
                              sep = "=", collapse = " ")))
      }
    }
    if (isTRUE(finished)) {
      break
    }
  }
  bst
}

#' Predict with a trained model
#'
#' @param object lgb.Booster.tpu.
#' @param newdata numeric matrix.
#' @param rawscore return margins instead of transformed scores.
#' @param predleaf return per-tree leaf indices.
#' @param num_iteration number of iterations to use (-1 = all).
predict.lgb.Booster.tpu <- function(object, newdata, rawscore = FALSE,
                                    predleaf = FALSE,
                                    num_iteration = -1L, ...) {
  newdata <- as.matrix(newdata)
  storage.mode(newdata) <- "double"
  ptype <- 0L                      # C_API_PREDICT_NORMAL
  if (isTRUE(rawscore)) ptype <- 1L
  if (isTRUE(predleaf)) ptype <- 2L
  out <- .Call(LGBMTPU_BoosterPredictForMat_R, object$ptr, newdata,
               ptype, as.integer(num_iteration), "")
  n <- nrow(newdata)
  if (length(out) > n && length(out) %% n == 0L) {
    # multiclass / leaf-index outputs come back row-major [n, k]
    matrix(out, nrow = n, byrow = TRUE)
  } else {
    out
  }
}

#' Save a model to the reference text format
lgb.save <- function(booster, filename, num_iteration = -1L) {
  stopifnot(inherits(booster, "lgb.Booster.tpu"))
  .Call(LGBMTPU_BoosterSaveModel_R, booster$ptr,
        as.integer(num_iteration), filename)
  invisible(booster)
}

#' Load a model from a text model file
lgb.load <- function(filename) {
  ptr <- .Call(LGBMTPU_BoosterCreateFromModelfile_R, filename)
  bst <- list(ptr = ptr)
  class(bst) <- "lgb.Booster.tpu"
  bst
}

#' Serialize a model to a string
lgb.model.to.string <- function(booster, num_iteration = -1L) {
  .Call(LGBMTPU_BoosterSaveModelToString_R, booster$ptr,
        as.integer(num_iteration))
}

#' Evaluation results for a data index (0 = train, 1.. = valids)
lgb.get.eval <- function(booster, data_idx = 0L) {
  ev <- .Call(LGBMTPU_BoosterGetEval_R, booster$ptr,
              as.integer(data_idx))
  names(ev) <- .Call(LGBMTPU_BoosterGetEvalNames_R, booster$ptr)
  ev
}
