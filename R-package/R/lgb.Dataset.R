# Dataset construction over the C API (the role of the reference
# R-package's lgb.Dataset.R, re-designed around external-pointer handles
# with finalizers instead of handle-slot R6 objects).

.params_to_string <- function(params) {
  if (is.null(params) || length(params) == 0L) {
    return("")
  }
  paste(vapply(names(params), function(k) {
    v <- params[[k]]
    paste0(k, "=", paste(as.character(v), collapse = ","))
  }, character(1L)), collapse = " ")
}

#' Create a lightgbm_tpu Dataset
#'
#' @param data numeric matrix (rows = observations) or path to a data
#'   file (CSV/TSV/LibSVM or a saved binary dataset).
#' @param label numeric response vector (ignored for file input when the
#'   file carries its own label column).
#' @param params named list of dataset parameters (max_bin, ...).
#' @param weight optional per-row weights.
#' @param group optional query sizes for ranking.
#' @param reference optional lgb.Dataset whose bin mappers to reuse
#'   (validation data).
lgb.Dataset <- function(data, label = NULL, params = list(),
                        weight = NULL, group = NULL, reference = NULL) {
  pstr <- .params_to_string(params)
  ref_ptr <- if (is.null(reference)) {
    NULL
  } else {
    stopifnot(inherits(reference, "lgb.Dataset.tpu"))
    reference$ptr
  }
  if (is.character(data)) {
    ptr <- .Call(LGBMTPU_DatasetCreateFromFile_R, data, pstr, ref_ptr)
  } else {
    data <- as.matrix(data)
    storage.mode(data) <- "double"
    ptr <- .Call(LGBMTPU_DatasetCreateFromMat_R, data, pstr,
                 ref_ptr)
  }
  ds <- list(ptr = ptr)
  class(ds) <- "lgb.Dataset.tpu"
  if (!is.null(label)) {
    lgb.Dataset.set.field(ds, "label", label)
  }
  if (!is.null(weight)) {
    lgb.Dataset.set.field(ds, "weight", weight)
  }
  if (!is.null(group)) {
    lgb.Dataset.set.field(ds, "group", group)
  }
  if (!is.null(colnames(data))) {
    .Call(LGBMTPU_DatasetSetFeatureNames_R, ds$ptr,
          as.character(colnames(data)))
  }
  ds
}

#' Set a metadata field (label / weight / group / init_score)
lgb.Dataset.set.field <- function(dataset, field, values) {
  stopifnot(inherits(dataset, "lgb.Dataset.tpu"))
  if (field %in% c("group", "query")) {
    values <- as.integer(values)
  } else {
    values <- as.double(values)
  }
  .Call(LGBMTPU_DatasetSetField_R, dataset$ptr, field, values)
  invisible(dataset)
}

dim.lgb.Dataset.tpu <- function(x) {
  c(.Call(LGBMTPU_DatasetGetNumData_R, x$ptr),
    .Call(LGBMTPU_DatasetGetNumFeature_R, x$ptr))
}

#' Save the binned dataset to the reference binary format
lgb.Dataset.save <- function(dataset, fname) {
  stopifnot(inherits(dataset, "lgb.Dataset.tpu"))
  .Call(LGBMTPU_DatasetSaveBinary_R, dataset$ptr, fname)
  invisible(dataset)
}

#' Validation data binned with the training data's mappers
lgb.Dataset.create.valid <- function(dataset, data, label = NULL,
                                     params = list(), ...) {
  stopifnot(inherits(dataset, "lgb.Dataset.tpu"))
  lgb.Dataset(data, label = label, params = params,
              reference = dataset, ...)
}

#' Feature names of a constructed Dataset
dimnames.lgb.Dataset.tpu <- function(x) {
  list(NULL, .Call(LGBMTPU_DatasetGetFeatureNames_R, x$ptr))
}

#' Read a metadata field back (label / weight / group / init_score)
lgb.Dataset.get.field <- function(dataset, field) {
  stopifnot(inherits(dataset, "lgb.Dataset.tpu"))
  .Call(LGBMTPU_DatasetGetField_R, dataset$ptr, field)
}
