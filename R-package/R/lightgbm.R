# Convenience entry point + RDS persistence (the role of the reference
# R-package's lightgbm.R / saveRDS.lgb.Booster.R / readRDS.lgb.Booster.R:
# external-pointer handles do not survive serialize(), so RDS round-trips
# go through the reference text model format).

#' One-call training from a matrix
#'
#' @param data numeric matrix, or lgb.Dataset.
#' @param label response vector (ignored when data is an lgb.Dataset).
#' @param params named list of training parameters.
#' @param nrounds boosting iterations.
#' @param ... forwarded to lgb.train.
lightgbm <- function(data, label = NULL, params = list(),
                     nrounds = 100L, ...) {
  if (!inherits(data, "lgb.Dataset.tpu")) {
    data <- lgb.Dataset(data, label = label, params = params)
  }
  lgb.train(params = params, data = data, nrounds = nrounds, ...)
}

#' Load a model from a model-string (inverse of lgb.model.to.string)
lgb.load.from.string <- function(model_str) {
  ptr <- .Call(LGBMTPU_BoosterLoadModelFromString_R, model_str)
  bst <- list(ptr = ptr)
  class(bst) <- "lgb.Booster.tpu"
  bst
}

#' Save a Booster to an RDS file (handle-safe)
#'
#' The reference ships saveRDS.lgb.Booster for the same reason: the
#' booster's external pointer dies with the session, so the RDS payload
#' carries the text model instead.
saveRDS.lgb.Booster <- function(object, file, ...) {
  stopifnot(inherits(object, "lgb.Booster.tpu"))
  payload <- list(class = "lgb.Booster.tpu",
                  model_str = lgb.model.to.string(object))
  saveRDS(payload, file = file, ...)
}

#' Restore a Booster from an RDS file written by saveRDS.lgb.Booster
readRDS.lgb.Booster <- function(file, ...) {
  payload <- readRDS(file, ...)
  if (!is.list(payload) || is.null(payload$model_str)) {
    stop("file does not contain a saved lightgbm_tpu booster")
  }
  lgb.load.from.string(payload$model_str)
}
