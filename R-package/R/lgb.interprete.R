# Per-prediction feature contributions (the role of the reference
# R-package's lgb.interprete.R / lgb.plot.interpretation.R, rebuilt in
# base R over the TEXT model format instead of jsonlite+data.table over
# lgb.dump: the path-walk attribution only needs the per-tree arrays the
# text format already carries).

#' Parse a Booster's trees into one data.frame per tree
#'
#' Columns: kind ("node"/"leaf"), index, parent (node id, -1 for the
#' root), feature (split feature for nodes, NA for leaves), value
#' (internal_value for nodes, leaf_value for leaves).  Node child
#' references in the text format encode leaves as ~leaf (negative);
#' parents are reconstructed by scanning the child arrays.
lgb.model.dt.tree <- function(booster, num_iteration = -1L) {
  model_str <- if (is.character(booster)) booster else
    lgb.model.to.string(booster, num_iteration)
  blocks <- strsplit(model_str, "\nTree=", fixed = TRUE)[[1L]]
  if (length(blocks) < 2L) {
    stop("model string carries no trees")
  }
  lapply(blocks[-1L], function(block) {
    lines <- strsplit(block, "\n", fixed = TRUE)[[1L]]
    get_arr <- function(key, mode) {
      row <- grep(paste0("^", key, "="), lines, value = TRUE)
      if (length(row) == 0L) return(vector(mode, 0L))
      vals <- strsplit(sub(paste0("^", key, "="), "", row[1L]),
                       " ", fixed = TRUE)[[1L]]
      storage.mode(vals) <- mode
      vals
    }
    num_leaves <- get_arr("num_leaves", "integer")[1L]
    leaf_value <- get_arr("leaf_value", "double")
    if (num_leaves <= 1L) {
      return(data.frame(kind = "leaf", index = 0L, parent = -1L,
                        feature = NA_integer_, value = leaf_value[1L]))
    }
    split_feature <- get_arr("split_feature", "integer")
    internal_value <- get_arr("internal_value", "double")
    left_child <- get_arr("left_child", "integer")
    right_child <- get_arr("right_child", "integer")
    n_nodes <- num_leaves - 1L
    node_parent <- rep(-1L, n_nodes)
    leaf_parent <- rep(-1L, num_leaves)
    for (p in seq_len(n_nodes)) {
      for (child in c(left_child[p], right_child[p])) {
        if (child >= 0L) {
          node_parent[child + 1L] <- p - 1L
        } else {
          leaf_parent[-child] <- p - 1L    # ~leaf == -(leaf)-1
        }
      }
    }
    rbind(
      data.frame(kind = "node", index = seq_len(n_nodes) - 1L,
                 parent = node_parent, feature = split_feature,
                 value = internal_value),
      data.frame(kind = "leaf", index = seq_len(num_leaves) - 1L,
                 parent = leaf_parent, feature = NA_integer_,
                 value = leaf_value)
    )
  })
}

.single_tree_interprete <- function(tree_df, leaf_idx, n_features) {
  contrib <- numeric(n_features)
  leaves <- tree_df[tree_df$kind == "leaf", ]
  nodes <- tree_df[tree_df$kind == "node", ]
  row <- leaves[leaves$index == leaf_idx, ]
  if (nrow(row) == 0L || row$parent < 0L) {
    return(contrib)                      # stump: no split to attribute
  }
  value <- row$value
  p <- row$parent
  while (p >= 0L) {
    prow <- nodes[nodes$index == p, ]
    f <- prow$feature + 1L
    contrib[f] <- contrib[f] + (value - prow$value)
    value <- prow$value
    p <- prow$parent
  }
  contrib
}

#' Feature contributions of individual predictions (path attribution)
#'
#' For each requested row, walks every tree from the predicted leaf to
#' the root; each split contributes the change in expected value across
#' it, attributed to the split feature (the reference lgb.interprete
#' contract, R-package/R/lgb.interprete.R).  Returns one data.frame per
#' row with a Feature column and one Contribution column per class.
#'
#' @param model lgb.Booster.tpu.
#' @param data numeric matrix.
#' @param idxset integer row indices (1-based) to interpret.
#' @param num_iteration iterations to use (-1 = all).
lgb.interprete <- function(model, data, idxset, num_iteration = -1L) {
  data <- as.matrix(data)
  trees <- lgb.model.dt.tree(model, num_iteration)
  num_class <- .Call(LGBMTPU_BoosterGetNumClasses_R, model$ptr)
  n_features <- ncol(data)
  feature_names <- tryCatch(
    .Call(LGBMTPU_BoosterGetFeatureNames_R, model$ptr),
    error = function(e) NULL)
  if (is.null(feature_names) || length(feature_names) != n_features) {
    feature_names <- paste0("Column_", seq_len(n_features) - 1L)
  }
  leaf_mat <- predict(model, data[idxset, , drop = FALSE],
                      predleaf = TRUE, num_iteration = num_iteration)
  leaf_mat <- matrix(leaf_mat, nrow = length(idxset))
  lapply(seq_along(idxset), function(i) {
    contrib <- matrix(0.0, n_features, num_class)
    for (t in seq_along(trees)) {
      cls <- (t - 1L) %% num_class + 1L
      contrib[, cls] <- contrib[, cls] +
        .single_tree_interprete(trees[[t]], leaf_mat[i, t], n_features)
    }
    out <- data.frame(Feature = feature_names)
    for (cls in seq_len(num_class)) {
      col <- if (num_class == 1L) "Contribution" else
        paste0("Class_", cls - 1L)
      out[[col]] <- contrib[, cls]
    }
    # rank by total attribution magnitude across classes — ordering by
    # class 0 alone buries features dominant for other classes
    ord <- order(-rowSums(abs(contrib)))
    out[ord, , drop = FALSE]
  })
}

#' Plot one row's interpretation as a horizontal bar chart
#'
#' @param tree_interpretation one element of lgb.interprete()'s result.
#' @param top_n number of features to show.
#' @param cols reserved for multiclass layouts (reference signature).
lgb.plot.interpretation <- function(tree_interpretation, top_n = 10L,
                                    cols = 1L, ...) {
  df <- tree_interpretation
  valcol <- setdiff(colnames(df), "Feature")[1L]
  df <- df[order(abs(df[[valcol]]), decreasing = TRUE), ]
  df <- utils::head(df, top_n)
  df <- df[rev(seq_len(nrow(df))), ]
  graphics::barplot(df[[valcol]], names.arg = df$Feature, horiz = TRUE,
                    las = 1L, main = "Feature contribution",
                    xlab = valcol, ...)
  invisible(df)
}
