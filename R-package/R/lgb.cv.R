# K-fold cross-validation (the role of the reference R-package's
# lgb.cv.R, re-designed: folds are materialized as per-fold matrices on
# the R side instead of Dataset subset handles, because the TPU dataset
# re-bins per shard anyway; reference surface:
# /root/reference/R-package/R/lgb.cv.R).

#' Cross-validated training
#'
#' @param params named list of training parameters.
#' @param data numeric matrix (rows = observations).
#' @param label response vector.
#' @param nrounds boosting iterations per fold.
#' @param nfold number of folds.
#' @param stratified stratify fold assignment by label (classification).
#' @param folds optional explicit list of test-index vectors; overrides
#'   nfold/stratified.
#' @param early_stopping_rounds stop when the first eval metric has not
#'   improved for this many rounds (NULL = never).
#' @param verbose print per-round aggregated eval.
#' @param eval_freq print frequency.
#' @return list with `best_iter`, `record_evals` (metric -> matrix of
#'   [round, fold] values), and `boosters` (the per-fold models).
lgb.cv <- function(params = list(), data, label, nrounds = 100L,
                   nfold = 5L, stratified = TRUE, folds = NULL,
                   early_stopping_rounds = NULL, verbose = 1L,
                   eval_freq = 1L) {
  data <- as.matrix(data)
  storage.mode(data) <- "double"
  n <- nrow(data)
  if (is.null(folds)) {
    if (isTRUE(stratified) && length(unique(label)) <= 32L) {
      # per-class round-robin keeps label balance inside each fold
      assign <- integer(n)
      for (cls in unique(label)) {
        idx <- which(label == cls)
        assign[idx] <- rep_len(seq_len(nfold), length(idx))
      }
    } else {
      assign <- rep_len(seq_len(nfold), n)
    }
    folds <- lapply(seq_len(nfold), function(k) which(assign == k))
  }
  nfold <- length(folds)
  boosters <- vector("list", nfold)
  valid_sets <- vector("list", nfold)
  for (k in seq_len(nfold)) {
    test_idx <- folds[[k]]
    # R pitfall: data[-integer(0), ] selects ZERO rows, so an empty fold
    # would silently train on an empty dataset instead of all rows
    if (length(test_idx) == 0L) {
      stop(sprintf("lgb.cv: fold %d is empty (too many folds for the data?)",
                   k))
    }
    dtrain <- lgb.Dataset(data[-test_idx, , drop = FALSE],
                          label = label[-test_idx], params = params)
    dvalid <- lgb.Dataset(data[test_idx, , drop = FALSE],
                          label = label[test_idx], params = params,
                          reference = dtrain)
    ptr <- .Call(LGBMTPU_BoosterCreate_R, dtrain$ptr,
                 .params_to_string(params))
    .Call(LGBMTPU_BoosterAddValidData_R, ptr, dvalid$ptr)
    boosters[[k]] <- list(ptr = ptr, train_set = dtrain)
    class(boosters[[k]]) <- "lgb.Booster.tpu"
    valid_sets[[k]] <- dvalid
  }
  eval_names <- NULL
  record <- NULL
  es <- .es_new()
  for (i in seq_len(nrounds)) {
    for (k in seq_len(nfold)) {
      .Call(LGBMTPU_BoosterUpdateOneIter_R, boosters[[k]]$ptr)
      ev <- .Call(LGBMTPU_BoosterGetEval_R, boosters[[k]]$ptr, 1L)
      if (is.null(eval_names)) {
        eval_names <- .Call(LGBMTPU_BoosterGetEvalNames_R,
                            boosters[[k]]$ptr)
        record <- lapply(eval_names,
                         function(.) matrix(NA_real_, nrounds, nfold))
        names(record) <- eval_names
      }
      for (j in seq_along(eval_names)) {
        record[[j]][i, k] <- ev[j]
      }
    }
    means <- vapply(record, function(m) mean(m[i, ]), numeric(1L))
    if (verbose > 0L && (i %% eval_freq == 0L)) {
      message(sprintf("[%d] cv %s", i,
                      paste(eval_names,
                            signif(means, 6), sep = "=",
                            collapse = " ")))
    }
    if (!is.null(early_stopping_rounds)) {
      if (length(eval_names) == 0L) {
        stop("early_stopping_rounds requires at least one eval metric ",
             "(the booster was configured with no metric)")
      }
      es <- .es_step(es, means[1L],
                     .metric_higher_better(eval_names[1L]), i)
      if (es$stale >= early_stopping_rounds) {
        if (verbose > 0L) {
          message(sprintf(
            "early stop at round %d (best %d: %s=%g)", i,
            es$best_iter, eval_names[1L], es$best))
        }
        break
      }
    } else {
      es$best_iter <- i
    }
  }
  list(best_iter = es$best_iter, record_evals = record,
       boosters = boosters)
}

# metric direction table (mirrors the reference's maximize sets in
# callback.R / basic.R); anchored so "mape" (lower-better) is not caught
# by the "map" (ranking, higher-better) prefix
.metric_higher_better <- function(name) {
  grepl("^(auc|ndcg|map)($|@)", name)
}

# direction-aware improvement tracker shared by lgb.train and lgb.cv
.es_new <- function() {
  list(best = NA_real_, best_iter = 0L, stale = 0L)
}

.es_step <- function(st, value, higher, iter) {
  if (is.na(value)) {
    # no usable metric value: count as non-improving so a booster with
    # metric="none" cannot silently run forever under early stopping
    st$stale <- st$stale + 1L
    return(st)
  }
  improved <- is.na(st$best) ||
    (if (higher) value > st$best else value < st$best)
  if (improved) {
    st$best <- value
    st$best_iter <- iter
    st$stale <- 0L
  } else {
    st$stale <- st$stale + 1L
  }
  st
}
