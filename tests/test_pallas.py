"""Pallas histogram kernel correctness (interpret mode on CPU).

The real-TPU compiled path is exercised by bench.py and the driver's
entry-point checks; here we pin down numerics against the XLA one-hot
reference implementation.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import histogram_chunked
from lightgbm_tpu.ops.pallas_histogram import (histogram_all,
                                               histogram_segment,
                                               leaf_histogram_pallas,
                                               pack_channels, unpack_hist)


def _ref_hist(bins, g, h, m, B):
    F = bins.shape[1]
    out = np.zeros((F, B, 3))
    for f in range(F):
        out[f, :, 0] = np.bincount(bins[:, f], weights=g * m, minlength=B)
        out[f, :, 1] = np.bincount(bins[:, f], weights=h * m, minlength=B)
        out[f, :, 2] = np.bincount(bins[:, f], weights=m, minlength=B)
    return out


def test_pack_channels_split_accuracy(rng):
    g = rng.normal(size=1000).astype(np.float32) * 7.3
    w8 = np.asarray(pack_channels(jnp.asarray(g), jnp.asarray(g),
                                  jnp.ones(1000, jnp.float32)))
    recon = w8[0].astype(np.float64) + w8[1].astype(np.float64)
    # hi+lo bf16 split carries ~16 mantissa bits
    assert np.abs(recon - g).max() <= np.abs(g).max() * 2 ** -15


@pytest.mark.parametrize("n,f,b", [(600, 5, 16), (1024, 3, 64)])
def test_histogram_all_matches_reference(rng, n, f, b):
    rb = 256
    npad = (-n) % rb
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
    m = (rng.uniform(size=n) < 0.8).astype(np.float32)
    binsT = np.pad(bins.T, ((0, 0), (0, npad)))
    gp, hp, mp = (np.pad(x, (0, npad)) for x in (g, h, m))
    w8 = pack_channels(jnp.asarray(gp), jnp.asarray(hp), jnp.asarray(mp))
    out = unpack_hist(histogram_all(jnp.asarray(binsT), w8, b,
                                    block_rows=rb, interpret=True))
    exp = _ref_hist(bins, g, h, m, b)
    got = np.asarray(out, np.float64)
    assert np.abs(got[..., 2] - exp[..., 2]).max() < 1e-3       # counts exact
    scale = np.abs(exp).max()
    assert np.abs(got - exp).max() < max(1e-6, scale * 3e-4)


def test_histogram_all_packed4_matches_unpacked(rng):
    from lightgbm_tpu.ops.pallas_histogram import pack_bins_4bit
    n, f, b, rb = 1024, 6, 16, 256
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
    m = np.ones(n, np.float32)
    w8 = pack_channels(jnp.asarray(g), jnp.asarray(h), jnp.asarray(m))
    plain = unpack_hist(histogram_all(jnp.asarray(bins.T.copy()), w8, b,
                                      block_rows=rb, interpret=True))
    packedT = pack_bins_4bit(bins.T)
    assert packedT.shape == (f // 2, n)
    packed = unpack_hist(histogram_all(jnp.asarray(packedT), w8, b,
                                       block_rows=rb, interpret=True,
                                       packed4=True))
    np.testing.assert_allclose(np.asarray(packed)[:f], np.asarray(plain),
                               rtol=1e-6, atol=1e-6)


def test_histogram_segment_restricts_to_leaf(rng):
    n, f, b, rb = 1024, 4, 16, 256
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
    m = np.ones(n, np.float32)
    # 4 leaves striped across 4 blocks: leaf = block index
    lid = (np.arange(n) // rb).astype(np.int32)
    w8 = pack_channels(jnp.asarray(g), jnp.asarray(h), jnp.asarray(m))
    out = histogram_segment(jnp.asarray(bins.T.copy()), w8,
                            jnp.asarray(lid), jnp.int32(2), jnp.int32(2),
                            jnp.int32(2), b, block_rows=rb, interpret=True)
    got = np.asarray(unpack_hist(out), np.float64)
    sel = lid == 2
    exp = _ref_hist(bins[sel], g[sel], h[sel], m[sel], b)
    assert np.abs(got - exp).max() < max(1e-6, np.abs(exp).max() * 3e-4)


def test_grower_pallas_matches_onehot_tree(rng):
    """Same tiny problem grown with both backends: same structure, near-same
    outputs (bf16 hi/lo histogram vs f32)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.core.dataset import TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objective import create_objective

    n = 700
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)

    def train(backend):
        cfg = Config(objective="binary", num_leaves=8, max_bin=31,
                     min_data_in_leaf=10, num_iterations=3, verbosity=-1,
                     tpu_histogram_backend=backend)
        ds = TpuDataset.from_numpy(X, y, config=cfg)
        obj = create_objective(cfg)
        obj.init(ds.metadata, ds.num_data)
        bst = GBDT(cfg, ds, obj)
        for _ in range(3):
            bst.train_one_iter()
        return bst

    b_ref = train("onehot")
    b_pal = train("pallas")
    assert b_pal.grower_params.hist_backend == "pallas"
    p_ref = b_ref._raw_predict(X)
    p_pal = b_pal._raw_predict(X)
    # structure parity: same leaf counts per tree
    for t_ref, t_pal in zip(b_ref.models, b_pal.models):
        assert t_ref.num_leaves == t_pal.num_leaves
    assert np.abs(p_ref - p_pal).max() < 5e-3


def test_histogram_frontier_matches_segment(rng):
    """K-leaf batched kernel == K separate segment scans; -1 targets are
    zero; the block list restricts the scan to the union of intervals."""
    from lightgbm_tpu.ops.pallas_histogram import histogram_frontier

    n, f, b, rb, K = 2048, 5, 16, 256, 4
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
    m = np.ones(n, np.float32)
    # 8 leaves striped across 8 blocks: leaf = block index
    lid = (np.arange(n) // rb).astype(np.int32)
    w8 = pack_channels(jnp.asarray(g), jnp.asarray(h), jnp.asarray(m))
    binsT = jnp.asarray(bins.T.copy())

    targets = jnp.asarray([1, 3, 6, -1], jnp.int32)
    block_list = jnp.asarray([1, 3, 6, 0, 0, 0, 0, 0], jnp.int32)
    out = histogram_frontier(binsT, w8, jnp.asarray(lid), block_list,
                             jnp.int32(3), targets, b, block_rows=rb,
                             interpret=True)
    assert out.shape == (K, f, b, 8)
    for k, t in enumerate([1, 3, 6]):
        sel = lid == t
        exp = _ref_hist(bins[sel], g[sel], h[sel], m[sel], b)
        got = np.asarray(unpack_hist(out[k]), np.float64)
        assert np.abs(got - exp).max() < max(1e-6,
                                             np.abs(exp).max() * 3e-4), t
    # -1 target -> exactly zero
    assert float(jnp.abs(out[3]).max()) == 0.0
    # blocks outside the list contribute nothing even if the leaf strays
    # into them: leaf 1 rows exist only in block 1, which IS listed; now
    # ask for leaf 0 but list only block 3 -> zero histogram
    out2 = histogram_frontier(binsT, w8, jnp.asarray(lid),
                              jnp.asarray([3], jnp.int32), jnp.int32(1),
                              jnp.asarray([0, -1, -1, -1], jnp.int32), b,
                              block_rows=rb, interpret=True)
    assert float(jnp.abs(out2[0]).max()) == 0.0


def test_histogram_frontier_packed4(rng):
    from lightgbm_tpu.ops.pallas_histogram import (histogram_frontier,
                                                   pack_bins_4bit)
    n, f, b, rb = 1024, 6, 16, 256
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    m = np.ones(n, np.float32)
    lid = (np.arange(n) // rb).astype(np.int32)
    w8 = pack_channels(jnp.asarray(g), jnp.asarray(g), jnp.asarray(m))
    packedT = jnp.asarray(pack_bins_4bit(bins.T))
    out = histogram_frontier(packedT, w8, jnp.asarray(lid),
                             jnp.asarray([0, 1, 2, 3], jnp.int32),
                             jnp.int32(4),
                             jnp.asarray([2, 0, -1, -1], jnp.int32), b,
                             block_rows=rb, interpret=True, packed4=True)
    sel = lid == 2
    exp = _ref_hist(bins[sel], g[sel], g[sel], m[sel], b)
    got = np.asarray(unpack_hist(out[0]), np.float64)[:f]
    assert np.abs(got - exp).max() < max(1e-6, np.abs(exp).max() * 3e-4)


@pytest.mark.parametrize("packed4", [False, True])
def test_histogram_all_multi_channel_sets(rng, packed4):
    """histogram_all with C stacked 8-channel sets == C separate calls
    (multiclass batched roots), in both byte and 4-bit packed layouts."""
    n, f, b, rb, C = 1024, 4, 16, 256, 3
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    gs = [rng.normal(size=n).astype(np.float32) for _ in range(C)]
    hs = [rng.uniform(0.1, 1.0, size=n).astype(np.float32)
          for _ in range(C)]
    m = (rng.uniform(size=n) < 0.7).astype(np.float32)
    from lightgbm_tpu.ops.pallas_histogram import pack_bins_4bit
    binsT = (jnp.asarray(pack_bins_4bit(bins.T)) if packed4
             else jnp.asarray(bins.T.copy()))
    w8m = jnp.concatenate([pack_channels(jnp.asarray(gs[c]),
                                         jnp.asarray(hs[c]),
                                         jnp.asarray(m)) for c in range(C)])
    multi = histogram_all(binsT, w8m, b, block_rows=rb, interpret=True,
                          packed4=packed4)
    assert multi.shape == (C, f, b, 8)
    for c in range(C):
        single = histogram_all(
            binsT, pack_channels(jnp.asarray(gs[c]), jnp.asarray(hs[c]),
                                 jnp.asarray(m)), b, block_rows=rb,
            interpret=True, packed4=packed4)
        np.testing.assert_allclose(np.asarray(multi[c]),
                                   np.asarray(single), rtol=1e-6,
                                   atol=1e-6)


def test_score_gather_add_matches_gather(rng):
    """One-hot-matmul scorer == plain table gather, exactly (f32)."""
    from lightgbm_tpu.ops.pallas_score import score_gather_add
    for n, L in ((1000, 7), (70000, 255), (32768, 300)):
        score = jnp.asarray(rng.normal(size=n).astype(np.float32))
        lid = jnp.asarray(rng.randint(0, L, size=n).astype(np.int32))
        table = jnp.asarray(rng.normal(size=L).astype(np.float32))
        got = np.asarray(score_gather_add(score, lid, table,
                                          interpret=True))
        want = np.asarray(score) + np.asarray(table)[np.asarray(lid)]
        np.testing.assert_array_equal(got, want)
