"""Frontier-batched grower (models/grower_frontier.py).

K=1 must reproduce the strict best-first segment tree exactly; K>1 is
"batched best-first" — same locally-greedy family, trees may differ
slightly, so quality (not structure) is asserted.  The K-leaf batched
kernel itself is pinned against per-leaf scans in test_pallas.py.
"""

import numpy as np

from lightgbm_tpu.config import Config
from lightgbm_tpu.core.dataset import TpuDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objective import create_objective


def _train(X, y, impl, n_iters=3, **params):
    cfg = Config(verbosity=-1, tpu_histogram_backend="pallas",
                 tpu_tree_impl=impl, **params)
    ds = TpuDataset.from_numpy(X, y, config=cfg)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    bst = GBDT(cfg, ds, obj)
    for _ in range(n_iters):
        bst.train_one_iter()
    return bst


def test_frontier_k1_matches_segment_exactly(rng):
    """With a 1-leaf batch every round is one strict best-first split, so
    the trees must be identical."""
    n = 2500
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.3 * X[:, 2] ** 2
         + rng.normal(size=n) * 0.2 > 0).astype(np.float64)
    seg = _train(X, y, "segment", objective="binary", num_leaves=15,
                 min_data_in_leaf=5, tpu_row_chunk=256)
    fro = _train(X, y, "frontier", objective="binary", num_leaves=15,
                 min_data_in_leaf=5, tpu_row_chunk=256,
                 tpu_frontier_width=1)
    assert len(seg.models) == len(fro.models)
    for i, (ts, tf) in enumerate(zip(seg.models, fro.models)):
        assert ts.num_leaves == tf.num_leaves, f"tree {i}"
        nsp = ts.num_leaves - 1
        assert np.array_equal(ts.split_feature[:nsp],
                              tf.split_feature[:nsp]), f"tree {i}"
        assert np.array_equal(ts.threshold_in_bin[:nsp],
                              tf.threshold_in_bin[:nsp]), f"tree {i}"
        np.testing.assert_allclose(ts.leaf_value[:ts.num_leaves],
                                   tf.leaf_value[:tf.num_leaves],
                                   rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(seg._raw_predict(X), fro._raw_predict(X),
                               rtol=1e-5, atol=1e-6)


def test_frontier_batched_quality(rng):
    """K=4 batched rounds: the tree fills its leaf budget, every split is
    locally optimal, and fit quality matches strict best-first closely."""
    n = 4000
    X = rng.normal(size=(n, 8))
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 2) + (X[:, 2] > 0.5)
         + rng.normal(size=n) * 0.1)
    seg = _train(X, y, "segment", objective="regression", num_leaves=31,
                 min_data_in_leaf=5, tpu_row_chunk=256, n_iters=10,
                 learning_rate=0.3)
    fro = _train(X, y, "frontier", objective="regression", num_leaves=31,
                 min_data_in_leaf=5, tpu_row_chunk=256,
                 tpu_frontier_width=4, n_iters=10, learning_rate=0.3)
    assert fro.models[0].num_leaves == 31
    mse_seg = float(np.mean((seg._raw_predict(X).ravel() - y) ** 2))
    mse_fro = float(np.mean((fro._raw_predict(X).ravel() - y) ** 2))
    assert mse_fro < mse_seg * 1.15, (mse_fro, mse_seg)
    assert mse_fro < 0.1 * y.var()


def test_frontier_respects_leaf_budget_and_gain_floor(rng):
    """A round near the leaf budget must not overshoot num_leaves, and a
    separable-in-one-split target stops early (gain prefix logic)."""
    n = 1200
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] > 0).astype(np.float64)      # one split suffices
    fro = _train(X, y, "frontier", objective="regression", num_leaves=12,
                 min_data_in_leaf=5, min_gain_to_split=1e-3,
                 tpu_row_chunk=256, tpu_frontier_width=8, n_iters=1)
    t = fro.models[0]
    assert t.num_leaves <= 12
    # the dominant first split must be on feature 0
    assert t.split_feature[0] == 0


def test_frontier_binary_accuracy_default_width(rng):
    """Auto width caps K at ~num_leaves/16, so a 31-leaf tree batches
    only 1-2 leaves per round and fit stays at strict-best-first level."""
    n = 3000
    X = rng.normal(size=(n, 10))
    logit = 2 * X[:, 0] + X[:, 1] - X[:, 2] * X[:, 3]
    y = (logit + rng.normal(size=n) * 0.3 > 0).astype(np.float64)
    fro = _train(X, y, "frontier", objective="binary", num_leaves=31,
                 min_data_in_leaf=5, tpu_row_chunk=256, n_iters=8)
    p = 1.0 / (1.0 + np.exp(-fro._raw_predict(X).ravel()))
    acc = float(np.mean((p > 0.5) == y))
    assert acc > 0.92, acc


def test_frontier_with_efb_bundles(rng):
    """Frontier grower over an EFB-bundled dataset: group-space batched
    histograms expand to feature space in the scan, and split application
    maps features back to physical columns."""
    n, width, blocks = 2000, 8, 5
    X = np.zeros((n, width * blocks))
    picks = rng.randint(0, width, size=(n, blocks))
    for b in range(blocks):
        X[np.arange(n), b * width + picks[:, b]] = rng.normal(2, 1, n)
    y = (X[:, :width].sum(1) - X[:, width:2 * width].sum(1)
         + rng.normal(size=n) * 0.1)
    seg = _train(X, y, "segment", objective="regression", num_leaves=15,
                 min_data_in_leaf=5, tpu_row_chunk=256, n_iters=4)
    fro = _train(X, y, "frontier", objective="regression", num_leaves=15,
                 min_data_in_leaf=5, tpu_row_chunk=256,
                 tpu_frontier_width=1, n_iters=4)
    assert fro.train_set.bundle is not None
    # K=1 frontier == strict segment even through bundling
    np.testing.assert_allclose(seg._raw_predict(X), fro._raw_predict(X),
                               rtol=1e-5, atol=1e-6)


def test_frontier_gain_ratio_gate(rng):
    """With a dominant-gain target and a high gain ratio, rounds batch
    only comparable leaves — quality approaches strict best-first even at
    large K; ratio=0 batches everything with positive gain."""
    n = 3000
    X = rng.normal(size=(n, 8))
    y = (X[:, 0] * 3                      # one dominant direction
         + 0.1 * np.sin(X[:, 1]) + rng.normal(size=n) * 0.05)
    strict = _train(X, y, "segment", objective="regression", num_leaves=31,
                    min_data_in_leaf=5, tpu_row_chunk=256, n_iters=3)
    gated = _train(X, y, "frontier", objective="regression", num_leaves=31,
                   min_data_in_leaf=5, tpu_row_chunk=256,
                   tpu_frontier_width=16, tpu_frontier_gain_ratio=0.5,
                   n_iters=3)
    wide = _train(X, y, "frontier", objective="regression", num_leaves=31,
                  min_data_in_leaf=5, tpu_row_chunk=256,
                  tpu_frontier_width=16, tpu_frontier_gain_ratio=0.0,
                  n_iters=3)
    mse = lambda b: float(np.mean((b._raw_predict(X).ravel() - y) ** 2))
    m_strict, m_gated, m_wide = mse(strict), mse(gated), mse(wide)
    # the gate must not be WORSE than ungated batching, and must stay
    # close to strict
    assert m_gated <= m_wide * 1.02, (m_gated, m_wide)
    assert m_gated < m_strict * 1.10, (m_gated, m_strict)


def test_frontier_with_bagging_and_goss(rng):
    """Frontier grower under row subsampling: bagging masks rows via the
    member channel; GOSS amplifies small-gradient rows — both flow
    through the batched kernel's weight channels unchanged."""
    n = 3000
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    bag = _train(X, y, "frontier", objective="binary", num_leaves=15,
                 min_data_in_leaf=5, tpu_row_chunk=256, n_iters=6,
                 bagging_fraction=0.6, bagging_freq=1)
    p = 1.0 / (1.0 + np.exp(-bag._raw_predict(X).ravel()))
    assert float(np.mean((p > 0.5) == y)) > 0.9

    from lightgbm_tpu.models.boosting_factory import create_boosting
    from lightgbm_tpu.objective import create_objective
    cfg = Config(verbosity=-1, tpu_histogram_backend="pallas",
                 tpu_tree_impl="frontier", objective="binary",
                 boosting="goss", num_leaves=15, min_data_in_leaf=5,
                 tpu_row_chunk=256, top_rate=0.3, other_rate=0.2)
    ds = TpuDataset.from_numpy(X, y, config=cfg)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    goss = create_boosting(cfg, ds, obj)
    for _ in range(6):
        goss.train_one_iter()
    p = 1.0 / (1.0 + np.exp(-goss._raw_predict(X).ravel()))
    assert float(np.mean((p > 0.5) == y)) > 0.9


def test_frontier_multiclass_batched_roots_parity(rng):
    """Batched roots feed the FRONTIER grower's external-root branch
    (gbdt gates on _use_segment, which covers frontier too)."""
    n, C = 1200, 3
    X = rng.normal(size=(n, 5))
    y = np.argmax(X[:, :C] + rng.normal(size=(n, C)) * 0.3, axis=1)

    def train(force_eager):
        cfg = Config(verbosity=-1, objective="multiclass", num_class=C,
                     tpu_histogram_backend="pallas",
                     tpu_tree_impl="frontier", num_leaves=7,
                     min_data_in_leaf=5, tpu_row_chunk=256,
                     tpu_frontier_width=2)
        ds = TpuDataset.from_numpy(X, y.astype(np.float64), config=cfg)
        obj = create_objective(cfg)
        obj.init(ds.metadata, ds.num_data)
        bst = GBDT(cfg, ds, obj)
        if force_eager:
            bst._fused_ok = False
        for _ in range(2):
            bst.train_one_iter()
        return bst

    fused = train(False)
    eager = train(True)
    assert fused._fused_fns[2] is not None
    np.testing.assert_allclose(fused._raw_predict(X),
                               eager._raw_predict(X),
                               rtol=1e-4, atol=1e-5)


def test_seg_stats_counters_via_outputs(rng, monkeypatch, capfd):
    """LIGHTGBM_TPU_SEG_STATS threads the scan/compaction counters out of
    the jit as a third output (the axon PJRT backend rejects in-jit host
    callbacks, so they must NOT be debug.print'ed) and prints them
    host-side."""
    monkeypatch.setenv("LIGHTGBM_TPU_SEG_STATS", "1")
    n = 2500
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    for impl, k_expect in (("segment", 1), ("frontier", None)):
        bst = _train(X, y, impl, n_iters=2, objective="binary",
                     num_leaves=15, min_data_in_leaf=5, tpu_row_chunk=256)
        assert bst._raw_predict(X).size == n
        err = capfd.readouterr().err
        lines = [ln for ln in err.splitlines() if "seg stats" in ln]
        assert len(lines) >= 2, (impl, err)
        # counters are sane: scanned >= 1 N-equivalent, K as configured
        assert "N-equivalents" in lines[-1]
        if k_expect is not None:
            assert f"K={k_expect}" in lines[-1]
