"""Fleet observability plane tests (metrics v6): the NTP-midpoint
clock-skew estimator, the pure wait-vs-work attribution core, the
shared stream-tailing machinery, the skew-corrected Chrome-trace merge,
the fleet summary rollup + gate, and — slow-marked — a real 2-process
``jax.distributed`` CPU fleet with an injected ``dist/slow`` straggler
exercising the ISSUE acceptance criteria: the armed rank is NAMED in
the ``dist_window`` health records, the merged trace holds one
monotone lane per rank joined by flow arrows, and the trained models
stay byte-identical with the plane on vs off.
"""

import io
import json
import os
import sys
import threading

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.obs import clockskew, fleet
from lightgbm_tpu.parallel import network
from lightgbm_tpu.utils.telemetry import HealthStream, TELEMETRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_gate           # noqa: E402
import fleet_monitor        # noqa: E402
import fleet_trace          # noqa: E402
import streamtail           # noqa: E402
import trace_report         # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    TELEMETRY.reset()
    fleet.reset()
    yield
    TELEMETRY.reset()
    fleet.reset()


# ------------------------------------------------------------- clock skew
def _ping(theta, d_up, d_down, t1=100.0, proc=0.0):
    """One synthetic exchange: server clock = client clock + theta."""
    t2 = t1 + d_up + theta
    t3 = t2 + proc
    t4 = t1 + d_up + proc + d_down
    return (t1, t2, t3, t4)


def test_midpoint_offset_recovers_symmetric_offset():
    # symmetric path delay: the midpoint estimate is exact
    off, bound = clockskew.midpoint_offset(*_ping(3.5, 0.01, 0.01))
    assert off == pytest.approx(3.5, abs=1e-9)
    assert bound == pytest.approx(0.01, abs=1e-9)


def test_midpoint_offset_error_within_rtt_bound():
    # asymmetric delay biases the estimate, but never past the bound
    theta = -2.0
    off, bound = clockskew.midpoint_offset(*_ping(theta, 0.030, 0.002))
    assert abs(off - theta) <= bound + 1e-12
    assert bound == pytest.approx(0.016, abs=1e-9)


def test_combine_pings_min_rtt_sample_wins():
    noisy = _ping(1.0, 0.5, 0.001)      # queued: huge RTT, biased
    clean = _ping(1.0, 0.002, 0.002)    # fast: tight, accurate
    off, bound, rtt = clockskew.combine_pings([noisy, clean, noisy])
    assert off == pytest.approx(1.0, abs=1e-9)
    assert rtt == pytest.approx(0.004, abs=1e-9)
    assert bound <= 0.01


def test_combine_pings_rejects_empty():
    with pytest.raises(ValueError):
        clockskew.combine_pings([])


def test_correct_maps_onto_rank0_clock():
    table = {1: {"offset_s": -5.0, "bound_s": 0.001, "rtt_s": 0.002}}
    assert clockskew.correct(10.0, 1, table) == pytest.approx(5.0)
    # str keys (JSON round-trip) resolve the same way
    assert clockskew.correct(10.0, 1, {"1": {"offset_s": -5.0}}) \
        == pytest.approx(5.0)
    # identity: no table, or a rank the table does not know
    assert clockskew.correct(10.0, 1, None) == 10.0
    assert clockskew.correct(10.0, 7, table) == 10.0


# ------------------------------------------------------- wait/work split
def _tables(slow_rank=1, delay=0.2):
    """Two ranks, two barrier calls: ``slow_rank`` enters late, so the
    other rank's measured wall is pure waiting."""
    fast, slow = (0, 1) if slow_rank == 1 else (1, 0)
    return {
        fast: {"barrier": [(0, 10.0, delay + 0.01),
                           (1, 20.0, delay + 0.01)]},
        slow: {"barrier": [(0, 10.0 + delay, 0.01),
                           (1, 20.0 + delay, 0.01)]},
    }


def test_attribute_window_splits_wait_vs_work_exactly():
    report = fleet.attribute_window(_tables())
    assert report["calls"] == 2
    assert report["straggler"] == 1
    # wait + work == that rank's own measured wall, by construction
    walls = {0: 2 * 0.21, 1: 2 * 0.01}
    for r in (0, 1):
        v = report["per_rank"][r]
        assert v["wait_s"] + v["work_s"] == pytest.approx(walls[r],
                                                          abs=1e-6)
        assert v["calls"] == 2
    # the early rank's wall is (almost) all waiting for the straggler
    assert report["per_rank"][0]["wait_s"] == pytest.approx(0.4, abs=1e-6)
    assert report["per_rank"][1]["wait_s"] == pytest.approx(0.0, abs=1e-6)
    assert report["lateness_s"][1] == pytest.approx(0.4, abs=1e-6)


def test_attribute_window_applies_clock_offsets():
    # rank 1's clock runs 100s behind rank 0's: uncorrected it looks
    # like rank 1 entered ages early; the offset table flips the story
    tables = {0: {"barrier": [(0, 10.0, 0.21)]},
              1: {"barrier": [(0, -89.8, 0.01)]}}
    offsets = {0: {"offset_s": 0.0}, 1: {"offset_s": 100.0}}
    report = fleet.attribute_window(tables, offsets)
    assert report["straggler"] == 1
    assert report["per_rank"][0]["wait_s"] == pytest.approx(0.2, abs=1e-6)


def test_attribute_window_skips_unpaired_calls():
    tables = {0: {"barrier": [(0, 10.0, 0.1), (1, 20.0, 0.1)],
                  "allgather": [(0, 30.0, 0.1)]},
              1: {"barrier": [(1, 20.0, 0.1)]}}
    report = fleet.attribute_window(tables)
    assert report["calls"] == 1          # only barrier#1 pairs
    tables = {0: {"barrier": [(0, 10.0, 0.1)]},
              1: {"allgather": [(0, 10.0, 0.1)]}}
    assert fleet.attribute_window(tables) is None
    assert fleet.attribute_window({0: {"barrier": [(0, 1.0, 0.1)]}}) \
        is None                          # < 2 ranks


def test_attribute_window_simultaneous_entry_names_no_straggler():
    tables = {0: {"barrier": [(0, 10.0, 0.05)]},
              1: {"barrier": [(0, 10.0, 0.05)]}}
    report = fleet.attribute_window(tables)
    assert report["straggler"] is None
    assert report["per_rank"][0]["wait_s"] == 0.0


# ------------------------------------------------- collective window drain
def test_take_collective_window_drains_and_indexes():
    TELEMETRY.set_config_level(2)
    network.reset_collective_stats()
    try:
        network.record_collective("barrier", 10, 0.5, enter_mono=1.0)
        network.record_collective("barrier", 10, 0.5, enter_mono=2.0)
        # no enter stamp -> counters only, never the window
        network.record_collective("allgather", 99, 0.1)
        win = network.take_collective_window()
        assert set(win) == {"barrier"}
        assert [(i, e) for i, e, _s in win["barrier"]] == [(0, 1.0),
                                                           (1, 2.0)]
        # drained: the next window starts empty but keeps indexing
        assert network.take_collective_window() == {}
        network.record_collective("barrier", 10, 0.5, enter_mono=3.0)
        win = network.take_collective_window()
        assert [i for i, _e, _s in win["barrier"]] == [2]
        # counters saw everything regardless of the window
        assert network.collective_stats()["barrier"]["calls"] == 3
    finally:
        network.reset_collective_stats()
        TELEMETRY.set_config_level(None)


# ------------------------------------------------------ health clock stamps
def test_every_health_record_kind_carries_clock_pair(tmp_path):
    path = str(tmp_path / "h.jsonl")
    hs = HealthStream()
    hs.open(path, meta={"stream": "train", "rank": 0, "world": 1})
    hs.record("iter", {"iter": 0})
    hs.record("fault", {"site": "x", "event": "armed"})
    hs.record("dist", {"event": "clock", "offset_s": 0.0})
    hs.record("dist_clock", {"rank": 0, "world": 1, "offsets": {}})
    hs.record("dist_window", {"rank": 0, "seq": 0, "wait_s": 0.0,
                              "work_s": 0.0})
    hs.close()
    recs = [json.loads(line) for line in open(path)]
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "start" and kinds[-1] == "summary"
    for rec in recs:
        assert isinstance(rec.get("wall_ts"), float), rec["kind"]
        assert isinstance(rec.get("mono_ts"), float), rec["kind"]
    # mono stamps never reorder within one process
    monos = [r["mono_ts"] for r in recs]
    assert monos == sorted(monos)


# ------------------------------------------------------------- streamtail
class _State(streamtail.JsonlFolder):
    def __init__(self):
        super().__init__()
        self.kinds = []
        self.recent = []

    def on_record(self, rec):
        self.kinds.append(rec.get("kind"))
        if rec.get("t") is not None:
            self.recent.append((rec["t"], rec.get("kind")))
        if rec.get("kind") == "summary":
            self.summary = rec


def test_jsonl_folder_tolerates_torn_and_corrupt_lines():
    st = _State()
    st.feed(b'{"kind":"start"}\n{"ki')      # torn mid-record
    assert st.kinds == ["start"]
    st.feed(b'nd":"iter","t":1}\nnot json\n')
    assert st.kinds == ["start", "iter"]    # torn line healed, junk skipped
    assert st.records == 2


def test_stream_stale_is_pace_relative():
    st = _State()
    for t in (0.0, 1.0, 2.0, 3.0):
        st.feed(json.dumps({"kind": "iter", "t": t}).encode() + b"\n")
    assert streamtail.median_record_gap(st) == pytest.approx(1.0)
    assert streamtail.stream_stale(st, age_s=1.5) is None
    age, gap = streamtail.stream_stale(st, age_s=5.0)
    assert (age, gap) == (5.0, 1.0)
    # a finished stream is never stale, however old the file
    st.feed(b'{"kind":"summary","t":4}\n')
    assert streamtail.stream_stale(st, age_s=500.0) is None
    # too young to judge a pace
    young = _State()
    young.feed(b'{"kind":"iter","t":0}\n')
    assert streamtail.stream_stale(young, age_s=500.0) is None


def test_follow_stream_exit_codes(tmp_path):
    render = lambda state, path: f"{state.records} records"  # noqa: E731
    out = io.StringIO()
    # 2: the file never appears before the deadline
    rc = streamtail.follow_stream(str(tmp_path / "never.jsonl"), _State,
                                  render, interval=0.01, timeout=0.05,
                                  out=out, name="t")
    assert rc == 2 and "never appeared" in out.getvalue()
    # 3: records flow but no terminal record before the deadline
    p = tmp_path / "wedged.jsonl"
    p.write_text('{"kind":"start"}\n')
    rc = streamtail.follow_stream(str(p), _State, render, interval=0.01,
                                  timeout=0.05, out=io.StringIO(),
                                  name="t", timeout_msg="custom\n")
    assert rc == 3
    # 0: summary lands while tailing (written from a helper thread)
    p2 = tmp_path / "done.jsonl"
    p2.write_text('{"kind":"start"}\n')

    def _finish():
        with open(p2, "a") as fh:
            fh.write('{"kind":"summary"}\n')

    t = threading.Timer(0.05, _finish)
    t.start()
    try:
        rc = streamtail.follow_stream(str(p2), _State, render,
                                      interval=0.01, timeout=10.0,
                                      out=io.StringIO(), name="t")
    finally:
        t.join()
    assert rc == 0


def test_follow_stream_restarts_after_truncation(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text('{"kind":"start"}\n{"kind":"iter","t":1}\n')
    seen = []

    def _render(state, path):
        seen.append(list(state.kinds))
        if len(seen) == 1:              # a fresh run recreated the file
            p.write_text('{"kind":"start"}\n{"kind":"summary"}\n')
        return "."

    rc = streamtail.follow_stream(str(p), _State, _render, interval=0.01,
                                  timeout=10.0, out=io.StringIO(),
                                  name="t")
    assert rc == 0
    assert seen[0] == ["start", "iter"]
    assert seen[-1] == ["start", "summary"]    # state restarted, not merged


# ------------------------------------------------------------- trace merge
def _trace(rank, mono_epoch, events):
    return (rank, {"traceEvents": events, "displayTimeUnit": "ms",
                   "otherData": {"mono_epoch": mono_epoch,
                                 "wall_epoch": 1e9, "rank": rank,
                                 "world": 2}})


def test_merge_traces_skew_corrects_and_draws_flow_arrows():
    # rank 1's monotonic clock runs 100s behind; its true epoch starts
    # 0.1s after rank 0's once the offset table is applied
    ev0 = [{"name": "net/barrier", "ph": "X", "ts": 1000.0, "dur": 250.0,
            "tid": "net", "args": {"seq": 0}},
           {"name": "grow", "ph": "X", "ts": 0.0, "dur": 900.0,
            "tid": "train"}]
    ev1 = [{"name": "net/barrier", "ph": "X", "ts": 1200.0, "dur": 50.0,
            "tid": "net", "args": {"seq": 0}}]
    offsets = {0: {"offset_s": 0.0}, 1: {"offset_s": 100.0}}
    merged = fleet_trace.merge_traces(
        [_trace(0, 500.0, ev0), _trace(1, 400.1, ev1)], offsets)

    other = merged["otherData"]
    assert other["schema"] == fleet_trace.FLEET_TRACE_SCHEMA
    assert other["ranks"] == [0, 1]
    assert other["base_mono_s"] == pytest.approx(500.0)
    assert other["flows"] == 1

    evs = merged["traceEvents"]
    names = {(ev["pid"], ev["args"]["name"]) for ev in evs
             if ev.get("ph") == "M" and ev["name"] == "process_name"}
    assert names == {(0, "rank0"), (1, "rank1")}  # one named lane per rank

    xs = {(ev["pid"], ev["name"]): ev for ev in evs
          if ev.get("ph") == "X"}
    # rank 0 anchors the timeline; rank 1's lane lands 0.1s later
    assert xs[(0, "net/barrier")]["ts"] == pytest.approx(1000.0)
    assert xs[(1, "net/barrier")]["ts"] == pytest.approx(
        1200.0 + 0.1 * 1e6)
    # flow arrow: starts at the first-entering rank, finishes bound to
    # the straggler's enclosing span
    flows = sorted((ev for ev in evs if ev.get("cat") == "fleet-flow"),
                   key=lambda ev: ev["ts"])
    assert [ev["ph"] for ev in flows] == ["s", "f"]
    assert flows[0]["pid"] == 0 and flows[-1]["pid"] == 1
    assert flows[-1]["bp"] == "e"
    assert len({ev["id"] for ev in flows}) == 1

    # merged stream is time-ordered (metadata first), hence monotone
    # within every lane too
    ts = [float(ev.get("ts", 0.0)) for ev in evs if ev.get("ph") != "M"]
    assert ts == sorted(ts)
    assert all(ev.get("ph") == "M" for ev in evs[:len(evs) - len(ts)])


def test_merge_traces_unanchored_lane_is_labelled():
    merged = fleet_trace.merge_traces(
        [(0, {"traceEvents": [], "otherData": {}})], None)
    (meta,) = [ev for ev in merged["traceEvents"]
               if ev["name"] == "process_name"]
    assert "(unanchored)" in meta["args"]["name"]


# --------------------------------------------------- fleet summary + gate
def _feed_stream(lines):
    st = fleet_monitor.FleetStream()
    for rec in lines:
        st.feed(json.dumps(rec).encode() + b"\n")
    return st


def _fleet_states(complete=True):
    win = {"kind": "dist_window", "seq": 0, "iter": 3, "calls": 4,
           "straggler": 1, "t": 1.0, "mono_ts": 1.0}
    r0 = [{"kind": "start", "stream": "train", "rank": 0, "world": 2,
           "mono_ts": 0.0},
          {"kind": "dist_clock", "rank": 0, "world": 2,
           "offsets": {"0": {"offset_s": 0.0, "bound_s": 0.0,
                             "rtt_s": 0.0},
                       "1": {"offset_s": 0.5, "bound_s": 0.001,
                             "rtt_s": 0.002}}, "mono_ts": 0.5},
          dict(win, rank=0, wait_s=0.6, work_s=0.2)]
    r1 = [{"kind": "start", "stream": "train", "rank": 1, "world": 2,
           "mono_ts": 0.0},
          {"kind": "fault", "site": "dist/slow", "event": "armed",
           "mono_ts": 0.2},
          dict(win, rank=1, wait_s=0.0, work_s=0.8)]
    if complete:
        r0.append({"kind": "summary", "mono_ts": 2.0})
        r1.append({"kind": "summary", "mono_ts": 2.0})
    return {"/obs/rank0.health.jsonl": _feed_stream(r0),
            "/obs/rank1.health.jsonl": _feed_stream(r1)}


def test_build_summary_folds_per_rank_and_dedupes_windows():
    summary = fleet_monitor.build_summary(_fleet_states())
    assert summary["schema"] == fleet_monitor.FLEET_SUMMARY_SCHEMA
    # each rank's own split, folded from its OWN stream
    assert summary["per_rank"]["0"]["wait_s"] == pytest.approx(0.6)
    assert summary["per_rank"]["0"]["wait_fraction"] == pytest.approx(
        0.75)
    assert summary["per_rank"]["1"]["wait_s"] == pytest.approx(0.0)
    # the shared window fields fold ONCE per seq, not once per stream
    assert summary["windows"] == 1
    assert summary["collective_calls"] == 4
    assert summary["straggler_hist"] == {"1": 1}
    assert summary["faults"] == {"train": 1}
    assert summary["clock_offsets"]["1"]["offset_s"] == pytest.approx(0.5)
    assert summary["complete"] is True
    assert summary["streams"]["rank1.health.jsonl"]["rank"] == 1
    # the gate accepts what the monitor writes
    assert bench_gate.validate_fleet_summary(summary) == []


def test_build_summary_incomplete_until_every_terminal_record():
    summary = fleet_monitor.build_summary(_fleet_states(complete=False))
    assert summary["complete"] is False
    assert bench_gate.validate_fleet_summary(summary) == []


def test_validate_fleet_summary_rejects_malformed():
    good = fleet_monitor.build_summary(_fleet_states())
    assert bench_gate.validate_fleet_summary(
        dict(good, schema="nope")), "wrong schema must be rejected"
    bad = json.loads(json.dumps(good))
    bad["per_rank"]["0"]["wait_fraction"] = 1.5
    assert bench_gate.validate_fleet_summary(bad)
    bad = json.loads(json.dumps(good))
    bad["straggler_hist"] = {"1": 99}     # more wins than windows
    assert bench_gate.validate_fleet_summary(bad)
    assert bench_gate.validate_fleet_summary({}), \
        "empty dict must be rejected"


def test_fleet_render_names_straggler_and_wait_bound_rank():
    out = fleet_monitor.render(_fleet_states(), "/obs")
    assert "straggler: rank1 slowest in 1 of 1 window(s)" in out
    assert "WAIT-BOUND rank0" in out


# ------------------------------------------------------------ trace report
def test_trace_report_fleet_lines_na_on_v5_blob():
    lines = trace_report._fleet_lines({"spans": []})
    assert len(lines) == 1 and "n/a" in lines[0]


def test_trace_report_fleet_lines_render_v6_section():
    stats = {"fleet": {
        "windows": 2, "sync_iters": 3,
        "per_rank": {"0": {"wait_s": 0.6, "work_s": 0.2, "calls": 8,
                           "wait_fraction": 0.75},
                     "1": {"wait_s": 0.0, "work_s": 0.8, "calls": 8,
                           "wait_fraction": 0.0}},
        "straggler_hist": {"1": 2}}}
    text = "\n".join(trace_report._fleet_lines(stats))
    assert "2 attributed window(s)" in text
    assert "rank0: wait 0.600s / work 0.200s" in text
    assert "75% waiting" in text
    assert "rank1 slowest most often" in text


# ------------------------------------------------------------ config knobs
def test_fleet_obs_config_knobs_validate():
    cfg = Config(task="train", data="d.csv")
    assert cfg.fleet_obs_sync_iters == 0
    assert cfg.fleet_obs_clock_pings == 5
    cfg = Config(task="train", data="d.csv", fleet_obs_sync_iters=3,
                 fleet_obs_clock_pings=2)
    assert cfg.fleet_obs_sync_iters == 3
    with pytest.raises(Exception, match="fleet_obs_sync_iters"):
        Config(task="train", data="d.csv", fleet_obs_sync_iters=-1)
    with pytest.raises(Exception, match="fleet_obs_clock_pings"):
        Config(task="train", data="d.csv", fleet_obs_clock_pings=0)


def test_configure_binds_knobs_and_section_stays_v5_shaped():
    cfg = Config(task="train", data="d.csv", fleet_obs_sync_iters=4)
    fleet.configure(cfg)
    assert fleet._sync_iters == 4 and fleet._next_sync == 4
    # no window synced yet: the stats blob must stay v5-shaped
    assert fleet.fleet_section() is None
    assert fleet.summary_line() == ""
    fleet.configure(None)
    assert fleet._next_sync is None


# ---------------------------------------------- 2-process acceptance (slow)
def _write_csv(path, seed, n=300):
    r = np.random.RandomState(seed)
    X = r.rand(n, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * r.rand(n)
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.6f")


def _fleet_argv(extra=()):
    # relative paths + per-rank cwd: identical argv across runs keeps
    # the saved model byte-comparable (parameters section included)
    return [sys.executable, "-m", "lightgbm_tpu", "task=train",
            "data=train.csv", "label_column=0", "objective=regression",
            "num_iterations=8", "num_leaves=7", "min_data_in_leaf=5",
            "verbosity=1", "snapshot_freq=2", "tpu_boost_chunk=1",
            "seed=7", "collective_timeout_s=60",
            "output_model=model.txt", *extra]


def _run_fleet(dirs, argvs, extra_env, timeout_s=240.0):
    from launch_multihost import launch
    logs = [open(os.path.join(d, "run.log"), "a") for d in dirs]
    try:
        run = launch(argvs, cwds=[str(d) for d in dirs],
                     extra_env=extra_env, stdouts=logs)
        return run.wait(timeout_s=timeout_s)
    finally:
        for fh in logs:
            fh.close()


@pytest.mark.slow
def test_fleet_plane_names_injected_straggler_byte_identical(tmp_path):
    """ISSUE acceptance: a 2-rank CPU fleet with ``dist/slow`` armed on
    rank 1 produces (a) ``dist_window`` records naming rank 1 as the
    straggler with rank 0's wall dominated by waiting, (b) a merged
    skew-corrected trace with one monotone lane per rank and flow
    arrows, (c) a complete gate-accepted fleet summary, and (d) a model
    byte-identical to the same fleet with the plane disabled."""
    obs = tmp_path / "obs"
    obs.mkdir()
    dirs = {}
    for mode in ("on", "off"):
        for r in (0, 1):
            d = tmp_path / f"{mode}{r}"
            d.mkdir()
            _write_csv(d / "train.csv", 4321)
            dirs[mode, r] = d

    slow = "fault_injection=dist/slow@0x*"
    plane = ["telemetry_level=2", "fleet_obs_sync_iters=3",
             f"health_out={obs}/rank{{rank}}.health.jsonl"]
    slow_env = {"LIGHTGBM_TPU_SLOW_MS": "150"}

    # plane ON, rank 1 sleeps 150ms before every collective entry
    codes = _run_fleet(
        [dirs["on", 0], dirs["on", 1]],
        [_fleet_argv(plane), _fleet_argv(plane + [slow])],
        [{"LIGHTGBM_TPU_TRACE_JSON": str(obs / "rank0.trace.json")},
         dict(slow_env, LIGHTGBM_TPU_TRACE_JSON=str(
             obs / "rank1.trace.json"))])
    assert codes == [0, 0]

    # (a) the armed rank is the NAMED straggler, and the fast rank's
    # collective wall is dominated by waiting for it
    recs = [json.loads(line)
            for line in open(obs / "rank0.health.jsonl")]
    for rec in recs:
        assert "wall_ts" in rec and "mono_ts" in rec, rec["kind"]
    clocks = [r for r in recs if r["kind"] == "dist_clock"]
    assert clocks and set(clocks[-1]["offsets"]) == {"0", "1"}
    windows = [r for r in recs if r["kind"] == "dist_window"]
    assert windows, "no dist_window records synced"
    named = [w["straggler"] for w in windows if w["straggler"] is not None]
    assert named and max(set(named), key=named.count) == 1
    wait0 = sum(w["per_rank"]["0"]["wait_s"] for w in windows)
    wait1 = sum(w["per_rank"]["1"]["wait_s"] for w in windows)
    assert wait0 > 0.2, f"rank0 barely waited ({wait0:.3f}s)"
    assert wait0 > 2 * wait1, (wait0, wait1)
    # wait + work sums to each window's attributed collective wall
    for w in windows:
        for r in ("0", "1"):
            v = w["per_rank"][r]
            assert v["wait_s"] >= 0 and v["work_s"] >= 0

    # (b) merged trace: a lane per rank, monotone, flow arrows present
    merged_path = obs / "fleet.merged.json"
    assert fleet_trace.main([str(obs), "-o", str(merged_path)]) == 0
    merged = json.load(open(merged_path))
    assert merged["otherData"]["schema"] == fleet_trace.FLEET_TRACE_SCHEMA
    assert merged["otherData"]["ranks"] == [0, 1]
    assert merged["otherData"]["flows"] >= 1
    evs = merged["traceEvents"]
    assert {ev["pid"] for ev in evs if ev.get("ph") == "X"} == {0, 1}
    for lane in (0, 1):
        ts = [float(ev["ts"]) for ev in evs
              if ev.get("pid") == lane and ev.get("ph") != "M"]
        assert ts == sorted(ts), f"lane {lane} not monotone"

    # (c) fleet summary: complete, straggler attributed, gate-accepted
    states = fleet_monitor.load_dir(str(obs))
    summary = fleet_monitor.build_summary(states)
    assert summary["complete"] is True
    assert summary["windows"] >= 1
    assert max(summary["straggler_hist"],
               key=summary["straggler_hist"].get) == "1"
    assert bench_gate.validate_fleet_summary(summary) == []

    # (d) plane OFF (no telemetry, no syncs, no streams), same fault:
    # the trained models must be byte-identical — observability can
    # never leak into the model
    codes = _run_fleet(
        [dirs["off", 0], dirs["off", 1]],
        [_fleet_argv(), _fleet_argv([slow])],
        [{}, dict(slow_env)])
    assert codes == [0, 0]
    for r in (0, 1):
        on = (dirs["on", r] / "model.txt").read_bytes()
        off = (dirs["off", r] / "model.txt").read_bytes()
        assert on == off, f"rank {r} model differs with plane on/off"
