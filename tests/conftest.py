"""Test harness config: run JAX on a virtual 8-device CPU mesh.

The reference has no single-process distributed test seam (SURVEY.md §4); we
get one for free by forcing the CPU platform with 8 virtual devices so the
data-/feature-parallel learners run their real collective paths in-process.
"""

import os

# Hermetic env: the perf knobs (LIGHTGBM_TPU_*) change traced shapes,
# dispatch policies and module-level defaults at import time; a knob
# leaked from a concurrently-running bench/probe (the driver runs them
# side by side) must not reconfigure the test suite.  Tests that WANT a
# knob set it explicitly via monkeypatch after import.  Test-control
# gates (not perf knobs) are kept.
_KEEP = {"LIGHTGBM_TPU_SKIP_CAPI"}
_scrubbed = [k for k in os.environ
             if k.startswith("LIGHTGBM_TPU_") and k not in _KEEP]
for _k in _scrubbed:
    del os.environ[_k]
if _scrubbed:
    import sys as _sys
    _sys.stderr.write(
        "conftest: scrubbed env knobs: " + ", ".join(sorted(_scrubbed))
        + "\n")

# Must happen before the first backend init.  The axon sitecustomize imports
# jax at interpreter start with JAX_PLATFORMS=axon already captured, so the
# env var alone is not enough — override through jax.config instead.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
