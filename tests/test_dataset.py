import os

import numpy as np

from lightgbm_tpu.config import Config
from lightgbm_tpu.core.dataset import TpuDataset


def _make(rng, n=500, f=5):
    X = rng.normal(size=(n, f))
    y = rng.normal(size=n)
    return X, y


def test_from_numpy_basic(rng):
    X, y = _make(rng)
    ds = TpuDataset.from_numpy(X, y, config=Config(max_bin=63))
    assert ds.num_data == 500
    assert ds.num_used_features == 5
    assert ds.binned.shape == (500, 5)
    assert ds.binned.dtype == np.uint8
    assert ds.max_num_bin <= 64
    np.testing.assert_allclose(ds.metadata.label, y.astype(np.float32))


def test_trivial_feature_dropped(rng):
    X, y = _make(rng)
    X[:, 2] = 1.5  # constant
    ds = TpuDataset.from_numpy(X, y)
    assert ds.num_used_features == 4
    assert 2 not in ds.used_feature_indices


def test_valid_aligns_with_train(rng):
    X, y = _make(rng)
    ds = TpuDataset.from_numpy(X, y, config=Config(max_bin=63))
    Xv, yv = _make(rng, n=100)
    vs = ds.create_valid(Xv, yv)
    assert vs.bin_mappers is ds.bin_mappers
    # same value -> same bin under both datasets
    col = ds.bin_mappers[0].value_to_bin(Xv[:, 0])
    np.testing.assert_array_equal(vs.binned[:, 0], col.astype(vs.binned.dtype))


def test_categorical_feature(rng):
    X, y = _make(rng)
    X[:, 1] = rng.choice([0, 1, 2, 3], size=len(X))
    ds = TpuDataset.from_numpy(X, y, categorical_features=[1])
    infos = ds.feature_infos()
    j = ds.inner_feature_index(1)
    assert infos[j].is_categorical


def test_weights_group_init_score(rng):
    X, y = _make(rng, n=100)
    w = rng.uniform(0.5, 2.0, size=100)
    group = np.array([30, 30, 40])
    ds = TpuDataset.from_numpy(X, y, weights=w, group=group)
    assert ds.metadata.num_queries == 3
    assert ds.metadata.query_boundaries[-1] == 100
    assert ds.metadata.query_weights is not None


def test_binary_roundtrip(tmp_path, rng):
    X, y = _make(rng, n=200)
    w = rng.uniform(size=200)
    ds = TpuDataset.from_numpy(X, y, weights=w, config=Config(max_bin=31))
    path = os.path.join(tmp_path, "ds.bin")
    ds.save_binary(path)
    ds2 = TpuDataset.load_binary(path)
    np.testing.assert_array_equal(ds.binned, ds2.binned)
    np.testing.assert_allclose(ds.metadata.label, ds2.metadata.label)
    np.testing.assert_allclose(ds.metadata.weights, ds2.metadata.weights)
    assert ds2.max_num_bin == ds.max_num_bin
    assert [m.num_bin for m in ds2.bin_mappers] == \
           [m.num_bin for m in ds.bin_mappers]


def test_check_align_rejects_foreign_valid(rng):
    """Dataset::CheckAlign (dataset.h:301): a valid set built WITHOUT the
    training reference must be rejected, not silently mis-routed."""
    import pytest
    from lightgbm_tpu.utils.log import LightGBMError
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objective import create_objective
    from lightgbm_tpu.config import Config
    X = rng.normal(size=(500, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = Config(objective="binary", verbosity=-1)
    train = TpuDataset.from_numpy(X, y, config=cfg)
    obj = create_objective(cfg)
    obj.init(train.metadata, train.num_data)
    bst = GBDT(cfg, train, obj)
    ok = train.create_valid(X[:100], y[:100])
    bst.add_valid_data("ok", ok)            # aligned: accepted
    foreign = TpuDataset.from_numpy(X[:100] * 1.7, y[:100], config=cfg)
    with pytest.raises(LightGBMError):
        bst.add_valid_data("bad", foreign)
