import pytest

from lightgbm_tpu.config import Config, default_params, resolve_alias, str2map


def test_defaults():
    c = Config()
    assert c.num_iterations == 100
    assert c.learning_rate == 0.1
    assert c.num_leaves == 31
    assert c.max_bin == 255
    assert c.min_data_in_leaf == 20
    assert c.min_sum_hessian_in_leaf == 1e-3
    assert c.objective == "regression"
    assert c.boosting == "gbdt"
    assert c.tree_learner == "serial"


def test_alias_resolution():
    assert resolve_alias("n_estimators") == "num_iterations"
    assert resolve_alias("eta") == "learning_rate"
    assert resolve_alias("min_child_samples") == "min_data_in_leaf"
    assert resolve_alias("subsample") == "bagging_fraction"
    assert resolve_alias("colsample_bytree") == "feature_fraction"
    assert resolve_alias("reg_alpha") == "lambda_l1"
    assert resolve_alias("reg_lambda") == "lambda_l2"
    assert resolve_alias("random_state") == "seed"
    assert resolve_alias("workers") == "machines"


def test_aliases_apply():
    c = Config(n_estimators=50, eta=0.3, num_leaf=15)
    assert c.num_iterations == 50
    assert c.learning_rate == 0.3
    assert c.num_leaves == 15


def test_objective_aliases():
    assert Config(objective="mse").objective == "regression"
    assert Config(objective="mae").objective == "regression_l1"
    assert Config(app="binary").objective == "binary"
    assert Config(objective="softmax", num_class=3).objective == "multiclass"


def test_str2map_and_config_file_syntax():
    m = str2map("task=train objective=binary num_trees=10")
    assert m == {"task": "train", "objective": "binary", "num_trees": "10"}
    c = Config(**m)
    assert c.num_iterations == 10
    assert c.objective == "binary"


def test_type_coercion():
    c = Config(num_iterations="25", learning_rate="0.05", is_unbalance="true",
               metric="auc,binary_logloss")
    assert c.num_iterations == 25
    assert c.learning_rate == 0.05
    assert c.is_unbalance is True
    assert c.metric == ["auc", "binary_logloss"]


def test_conflicts():
    with pytest.raises(ValueError):
        Config(objective="multiclass", num_class=1)
    with pytest.raises(ValueError):
        Config(objective="binary", num_class=3)
    with pytest.raises(ValueError):
        Config(feature_fraction=0.0)
    with pytest.raises(ValueError):
        Config(tree_learner="bogus")


def test_default_params_covers_reference_set():
    # spot-check the reference's Config::parameter_set membership
    p = default_params()
    for name in ["max_cat_threshold", "cat_l2", "cat_smooth", "top_k",
                 "sparse_threshold", "snapshot_freq", "machines",
                 "tweedie_variance_power", "label_gain", "eval_at",
                 "num_machines", "gpu_use_dp", "refit_decay_rate"]:
        assert name in p, name


def test_parameters_doc_is_current():
    """docs/PARAMETERS.md is generated from the _PARAMS registry and must
    be regenerated when the registry changes (the reference keeps
    docs/Parameters.rst in sync the same way via its generator)."""
    import subprocess
    import sys
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "gen_params_doc.py"),
         "--check"], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
