import math

import numpy as np
import pytest

from lightgbm_tpu.core.binning import (BIN_TYPE_CATEGORICAL, BinMapper,
                                       MISSING_NAN, MISSING_NONE, MISSING_ZERO,
                                       greedy_find_bin)


def test_greedy_find_bin_few_distinct():
    vals = np.array([1.0, 2.0, 3.0], dtype=np.float64)
    cnts = np.array([10, 10, 10])
    bounds = greedy_find_bin(vals, cnts, max_bin=255, total_cnt=30,
                             min_data_in_bin=3)
    # one bound between each pair of distinct values, then +inf
    assert len(bounds) == 3
    assert bounds[0] == pytest.approx(1.5)
    assert bounds[1] == pytest.approx(2.5)
    assert math.isinf(bounds[2])


def test_greedy_find_bin_min_data_in_bin():
    vals = np.array([1.0, 2.0, 3.0], dtype=np.float64)
    cnts = np.array([2, 2, 30])
    bounds = greedy_find_bin(vals, cnts, max_bin=255, total_cnt=34,
                             min_data_in_bin=3)
    # 1.0 alone can't fill a bin (2 < 3); it merges with 2.0, then the
    # accumulated 4 >= 3 places one bound between 2.0 and 3.0
    assert len(bounds) == 2
    assert bounds[0] == pytest.approx(2.5)


def test_uniform_binning_partitions_evenly():
    rng = np.random.RandomState(0)
    x = rng.uniform(size=10000)
    m = BinMapper().find_bin(x, total_sample_cnt=len(x), max_bin=16,
                             min_data_in_bin=3)
    assert m.num_bin <= 16
    assert not m.is_trivial
    bins = m.value_to_bin(x)
    counts = np.bincount(bins, minlength=m.num_bin)
    # equal-frequency-ish: no bin is more than 3x the mean
    assert counts.max() < 3 * len(x) / m.num_bin


def test_value_to_bin_monotone():
    rng = np.random.RandomState(1)
    x = rng.normal(size=5000)
    m = BinMapper().find_bin(x, len(x), max_bin=63)
    xs = np.sort(rng.normal(size=1000))
    b = m.value_to_bin(xs)
    assert (np.diff(b) >= 0).all()
    assert b.min() >= 0 and b.max() < m.num_bin


def test_trivial_constant_feature():
    x = np.full(100, 7.0)
    m = BinMapper().find_bin(x, len(x), max_bin=255)
    assert m.is_trivial


def test_missing_nan_gets_own_bin():
    rng = np.random.RandomState(2)
    x = rng.normal(size=1000)
    x[::10] = np.nan
    m = BinMapper().find_bin(x, len(x), max_bin=255)
    assert m.missing_type == MISSING_NAN
    b = m.value_to_bin(x)
    assert (b[::10] == m.num_bin - 1).all()
    assert (b[1::10] < m.num_bin - 1).all()


def test_no_missing():
    x = np.linspace(-1, 1, 1000)
    m = BinMapper().find_bin(x, len(x), max_bin=255)
    assert m.missing_type == MISSING_NONE


def test_zero_as_missing():
    rng = np.random.RandomState(3)
    x = rng.normal(size=1000)
    x[:500] = 0.0
    m = BinMapper().find_bin(x, len(x), max_bin=63, zero_as_missing=True)
    assert m.missing_type == MISSING_ZERO
    # zero maps to its own bin == default_bin
    zb = m.value_to_bin(np.array([0.0]))[0]
    assert zb == m.default_bin


def test_zero_bin_boundary():
    # values on both sides of zero: zero gets a dedicated bin
    x = np.concatenate([np.linspace(-5, -1, 400), np.zeros(200),
                        np.linspace(1, 5, 400)])
    m = BinMapper().find_bin(x, len(x), max_bin=63)
    zb = int(m.value_to_bin(np.array([0.0]))[0])
    nb = int(m.value_to_bin(np.array([-1.0]))[0])
    pb = int(m.value_to_bin(np.array([1.0]))[0])
    assert nb < zb < pb


def test_categorical_binning():
    rng = np.random.RandomState(4)
    # category frequencies: 0 is most common but must not land in bin 0
    x = rng.choice([0, 1, 2, 3, 4], p=[0.5, 0.2, 0.15, 0.1, 0.05],
                   size=2000).astype(np.float64)
    m = BinMapper().find_bin(x, len(x), max_bin=255,
                             bin_type=BIN_TYPE_CATEGORICAL)
    assert m.is_categorical
    assert not m.is_trivial
    assert m.default_bin > 0  # category 0 never in bin 0
    b = m.value_to_bin(x)
    # same category -> same bin, distinct categories -> distinct bins
    for cat in [0, 1, 2, 3, 4]:
        bb = b[x == cat]
        assert (bb == bb[0]).all()
    assert len(np.unique(b)) == 5


def test_categorical_unseen_goes_to_last_bin():
    x = np.array([1, 1, 2, 2, 3, 3] * 20, dtype=np.float64)
    m = BinMapper().find_bin(x, len(x), max_bin=255,
                             bin_type=BIN_TYPE_CATEGORICAL)
    b = m.value_to_bin(np.array([99.0]))
    assert b[0] == m.num_bin - 1


def test_sparse_column_implicit_zeros():
    # only non-zero entries passed; total count includes implicit zeros
    nonzero = np.array([1.0, 2.0, 3.0] * 10)
    m = BinMapper().find_bin(nonzero, total_sample_cnt=1000, max_bin=63)
    assert not m.is_trivial
    assert m.sparse_rate > 0.9
    zb = int(m.value_to_bin(np.array([0.0]))[0])
    assert zb == m.default_bin


def test_roundtrip_serialization():
    rng = np.random.RandomState(5)
    x = rng.normal(size=1000)
    x[::7] = np.nan
    m = BinMapper().find_bin(x, len(x), max_bin=63)
    m2 = BinMapper.from_dict(m.to_dict())
    xs = rng.normal(size=100)
    np.testing.assert_array_equal(m.value_to_bin(xs), m2.value_to_bin(xs))
    assert m2.num_bin == m.num_bin
    assert m2.missing_type == m.missing_type


def test_native_matrix_quantizer_parity(rng):
    """lgbmtpu_quantize_rows must reproduce value_to_bin bit-for-bit
    over a matrix with NaNs, zeros, ties-on-bounds, and mixed
    missing types, in both f32 and f64 inputs."""
    import pytest

    from lightgbm_tpu.core.binning import BinMapper
    from lightgbm_tpu.core.native import lib, quantize_rows_native

    if lib() is None:
        pytest.skip("no C++ toolchain")
    n, F = 5000, 6
    X = rng.normal(size=(n, F))
    X[rng.random(size=(n, F)) < 0.05] = np.nan
    X[:, 2] = np.round(X[:, 2] * 2)        # heavy ties
    X[rng.random(size=n) < 0.3, 3] = 0.0   # zero mass -> MISSING_ZERO
    mappers = [BinMapper().find_bin(X[:, f], n, max_bin=31,
                                    min_data_in_bin=3)
               for f in range(F)]
    for dt in (np.float32, np.float64):
        Xd = np.ascontiguousarray(X.astype(dt))
        got = quantize_rows_native(Xd, list(range(F)), mappers, np.uint8)
        assert got is not None
        for f in range(F):
            exp = mappers[f].value_to_bin(
                Xd[:, f].astype(np.float64)).astype(np.uint8)
            np.testing.assert_array_equal(got[:, f], exp, err_msg=str(f))
