"""Serve observability plane tests (metrics schema v5).

What must hold: every request through the micro-batching queue leaves
a complete lifecycle trail — the four stage distributions
(``serve/t_queue``/``t_coalesce``/``t_dispatch``/``t_reply``) in the
telemetry timing section with ordered quantiles, the sliding-window
QPS/p50/p99 in ``stats()["serve"]``, queue-depth/inflight gauges, and
the coalesce-slack signal.  A session opened with ``serve_health_out=``
(env wins) writes a parseable never-torn JSONL stream whose windows
account for every request and whose terminal ``serve_summary`` (plus
the ``serve/closed`` counter) separates an orderly close from a wedged
server.  The open-loop load generator must show the coalescing window
engaging at high arrival rate and NOT at a trickle — the numbers
ROADMAP item 1 demanded.  And none of it may touch training: models
stay byte-identical with the serve stream enabled.
"""

import json
import math
import os
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import ServeSession, resolve_serve_health_path
from lightgbm_tpu.serve.health import SERVE_HEALTH_ENV
from lightgbm_tpu.utils.faults import FAULTS
from lightgbm_tpu.utils.telemetry import TELEMETRY

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import loadgen  # noqa: E402
import serve_monitor  # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    TELEMETRY.reset()
    TELEMETRY.set_config_level(1)
    TELEMETRY.install_jax_listeners()
    yield
    FAULTS.configure()


def _train(rng, rounds=8):
    X = rng.normal(size=(400, 8))
    X[:, 3] = rng.randint(0, 6, size=400)
    y = (np.nan_to_num(X[:, 0] + X[:, 1]) > 0.3).astype(np.float64)
    ds = lgb.Dataset(X, y, categorical_feature=[3])
    return lgb.train({"objective": "binary", "verbose": -1,
                      "num_leaves": 15}, ds,
                     num_boost_round=rounds), X


def _records(path):
    out = []
    with open(path, "rb") as fh:
        for raw in fh.read().split(b"\n"):
            if raw.strip():
                out.append(json.loads(raw))    # torn line would raise
    return out


# ------------------------------------------------- lifecycle tracing
def test_lifecycle_stage_distributions(rng):
    bst, X = _train(rng)
    with ServeSession(max_batch=32, max_delay_ms=2.0) as sess:
        mid = sess.load(bst)
        futs = [sess.submit(mid, X[i:i + 1]) for i in range(40)]
        for f in futs:
            f.result(timeout=30)
        # the worker records the last batch's stage walls just after
        # resolving its futures — poll for the full count
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            stats = TELEMETRY.stats()
            labels = stats.get("timing", {}).get("labels", {})
            if labels.get("serve/t_reply", {}).get("count", 0) >= 40:
                break
            time.sleep(0.01)
    for stage in ("serve/t_queue", "serve/t_coalesce",
                  "serve/t_dispatch", "serve/t_reply",
                  "serve/queue_wait"):
        assert stage in labels, f"missing stage distribution {stage}"
        d = labels[stage]
        assert d["count"] >= 40
        assert 0 <= d["p50_s"] <= d["p99_s"], stage
        assert math.isfinite(d["p99_s"])
    gauges = stats["gauges"]
    assert gauges["serve/queue_depth"] == 0          # all drained
    assert gauges["serve/inflight_batches"] == 0
    assert isinstance(gauges["serve/coalesce_slack_ms"], float)
    assert gauges["serve/max_batch"] == 32


def test_sliding_window_serve_stats(rng):
    assert TELEMETRY.serve_window_stats() is None    # idle: no section
    bst, X = _train(rng)
    with ServeSession(max_batch=16, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        for i in range(12):
            sess.predict(mid, X[i:i + 1])
        # the last request's window sample lands just after its future
        # resolves — poll for the full count
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            stats = TELEMETRY.stats()
            if stats.get("serve", {}).get("requests", 0) >= 12:
                break
            time.sleep(0.01)
    assert stats["version"] == 7
    assert stats["schema"] == "lightgbm_tpu.metrics/v7"
    win = stats["serve"]
    assert win["requests"] == 12
    assert win["qps"] > 0
    assert 0 <= win["p50_s"] <= win["p99_s"]
    # outside the 10s window nothing remains
    assert TELEMETRY.serve_window_stats(
        now=TELEMETRY._epoch + 3600.0) is None


def test_spans_on_serve_track(rng):
    bst, X = _train(rng)
    # after training: lgb.train binds the config's telemetry_level (1)
    TELEMETRY.set_config_level(2)
    with ServeSession(max_batch=16, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        sess.predict(mid, X[:4])
        # the worker records the batch's spans just after resolving the
        # future — poll briefly instead of racing it
        deadline = time.monotonic() + 5.0
        events, trace = [], {"traceEvents": []}
        while time.monotonic() < deadline and len(events) < 4:
            trace = TELEMETRY.chrome_trace()
            events = [e for e in trace["traceEvents"]
                      if e.get("ph") == "X"
                      and str(e.get("name", "")).startswith("serve/t_")]
            time.sleep(0.01)
    names = {e["name"] for e in events}
    assert names == {"serve/t_queue", "serve/t_coalesce",
                     "serve/t_dispatch", "serve/t_reply"}
    # all four stages live on the dedicated "serve" track: one numeric
    # tid whose thread_name metadata event names it
    serve_tids = {m["tid"] for m in trace["traceEvents"]
                  if m.get("ph") == "M" and m.get("name") == "thread_name"
                  and m["args"]["name"] == "serve"}
    assert len(serve_tids) == 1
    assert {e["tid"] for e in events} == serve_tids


# ---------------------------------------------------- health stream
def test_serve_health_stream_full_lifecycle(rng, tmp_path):
    path = str(tmp_path / "svc.serve.health.jsonl")
    bst, X = _train(rng)
    with ServeSession(max_batch=32, max_delay_ms=1.0, health_out=path,
                      health_window_s=0.2) as sess:
        mid = sess.load(bst)
        futs = [sess.submit(mid, X[i:i + 2]) for i in range(0, 60, 2)]
        for f in futs:
            f.result(timeout=30)
    recs = _records(path)
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "serve_start"
    assert kinds[-1] == "serve_summary"
    assert "serve_admit" in kinds
    wins = [r for r in recs if r["kind"] == "serve_window"]
    assert sum(w.get("requests", 0) for w in wins) == 30
    assert sum(w.get("rows", 0) for w in wins) == 60
    summary = recs[-1]
    assert summary["requests"] == 30
    assert summary["rows"] == 60
    assert summary["pending_failed"] == 0
    live = [w for w in wins if w.get("requests")]
    assert live, "no window captured the traffic"
    saw_stages = set()
    for w in live:
        assert 0 <= w["p50_s"] <= w["p99_s"]
        assert math.isfinite(w["p99_s"])
        for name, d in w.get("stages", {}).items():
            saw_stages.add(name)
            assert 0 <= d["p50_s"] <= d["p99_s"], name
    assert saw_stages == {"t_queue", "t_coalesce", "t_dispatch",
                          "t_reply"}
    for w in live:
        if w.get("fill_ratio") is not None:
            assert 0 < w["fill_ratio"] <= 1.0


def test_close_emits_summary_and_counter(rng, tmp_path):
    path = str(tmp_path / "close.serve.health.jsonl")
    bst, X = _train(rng)
    sess = ServeSession(max_batch=16, health_out=path,
                        health_window_s=60.0)
    mid = sess.load(bst)
    sess.predict(mid, X[:2])
    sess.close()
    sess.close()                                     # idempotent
    assert TELEMETRY.stats()["counters"]["serve/closed"] == 1
    recs = _records(path)
    assert [r["kind"] for r in recs].count("serve_summary") == 1
    assert recs[-1]["kind"] == "serve_summary"
    assert recs[-1]["requests"] == 1


def test_serve_fault_recorded(rng, tmp_path):
    path = str(tmp_path / "fault.serve.health.jsonl")
    bst, X = _train(rng)
    with ServeSession(max_batch=16, health_out=path,
                      health_window_s=60.0) as sess:
        mid = sess.load(bst)
        # wrong feature count passes submit but fails in the worker
        bad = sess.submit(mid, np.zeros((1, 3), dtype=np.float32))
        with pytest.raises(Exception):
            bad.result(timeout=30)
    assert TELEMETRY.stats()["counters"]["serve/errors"] == 1
    faults = [r for r in _records(path) if r["kind"] == "serve_fault"]
    assert len(faults) == 1
    assert "features" in faults[0]["error"]
    assert _records(path)[-1]["faults"] == 1         # summary total


def test_env_override_wins(rng, tmp_path, monkeypatch):
    env_path = str(tmp_path / "env.serve.health.jsonl")
    kw_path = str(tmp_path / "kw.serve.health.jsonl")
    monkeypatch.setenv(SERVE_HEALTH_ENV, env_path)
    assert resolve_serve_health_path(override=kw_path) == env_path
    bst, X = _train(rng, rounds=4)
    with ServeSession(max_batch=16, health_out=kw_path) as sess:
        mid = sess.load(bst)
        sess.predict(mid, X[:1])
    assert os.path.exists(env_path)
    assert not os.path.exists(kw_path)
    monkeypatch.delenv(SERVE_HEALTH_ENV)
    assert resolve_serve_health_path(override=kw_path) == kw_path
    assert resolve_serve_health_path() == ""


def test_training_byte_identical_with_serve_obs(rng, tmp_path,
                                                monkeypatch):
    """The serve plane must not touch the training path: same seed,
    same data -> byte-identical model with the serve stream enabled."""
    X = rng.normal(size=(300, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 7,
              "deterministic": True}

    def fit():
        ds = lgb.Dataset(X.copy(), y.copy())
        return lgb.train(params, ds,
                         num_boost_round=6).model_to_string()

    base = fit()
    monkeypatch.setenv(SERVE_HEALTH_ENV,
                       str(tmp_path / "t.serve.health.jsonl"))
    with_env = fit()
    assert with_env == base
    # and with a live serve session next to the training run
    bst, Xs = _train(rng, rounds=4)
    with ServeSession(max_batch=16) as sess:
        mid = sess.load(bst)
        sess.predict(mid, Xs[:1])
        during = fit()
    assert during == base


# --------------------------------------------------- open-loop loadgen
def test_loadgen_coalesces_at_high_rate_not_at_trickle(rng, tmp_path):
    bst, X = _train(rng)
    hot = loadgen.run_cell(
        bst, X, "t", rate=250.0, delay_ms=25.0, duration_s=0.9,
        max_batch=64, window_s=0.3,
        health_path=str(tmp_path / "hot.serve.health.jsonl"))
    trickle = loadgen.run_cell(
        bst, X, "t", rate=12.0, delay_ms=0.0, duration_s=0.8,
        max_batch=64, window_s=0.3,
        health_path=str(tmp_path / "trk.serve.health.jsonl"))
    for rec in (hot, trickle):
        assert rec["errors"] == 0
        assert rec["completed"] == rec["requests"] > 0
        assert rec["quality_ok"], "reply diverged under coalescing"
        assert 0 <= rec["p50_s"] <= rec["p99_s"]
    assert hot["rows_per_batch"] > 1.5, \
        f"coalescing never engaged: {hot['rows_per_batch']}"
    assert trickle["rows_per_batch"] < 1.5
    # health streams: counts match, kinds present, quantiles ordered
    assert loadgen._check_health_stream(
        str(tmp_path / "hot.serve.health.jsonl"), hot["completed"]) == []
    assert loadgen._check_health_stream(
        str(tmp_path / "trk.serve.health.jsonl"),
        trickle["completed"]) == []


def test_loadgen_merge_bench_serve(tmp_path):
    path = str(tmp_path / "BENCH_SERVE.json")
    with open(path, "w") as fh:
        json.dump([{"config": "serve-small-b16-d0", "p99_s": 0.01},
                   {"config": "loadgen-small-r50-d0", "p99_s": 0.9}], fh)
    loadgen.merge_bench_serve(
        [{"config": "loadgen-small-r50-d0", "p99_s": 0.1}], path=path)
    merged = json.load(open(path))
    assert {r["config"] for r in merged} == {
        "serve-small-b16-d0", "loadgen-small-r50-d0"}
    assert [r for r in merged
            if r["config"] == "loadgen-small-r50-d0"][0]["p99_s"] == 0.1


# ------------------------------------------------------ serve_monitor
def test_serve_monitor_render_and_follow(rng, tmp_path, capsys):
    path = str(tmp_path / "mon.serve.health.jsonl")
    bst, X = _train(rng)
    with ServeSession(max_batch=16, max_delay_ms=0.0, health_out=path,
                      health_window_s=0.2) as sess:
        mid = sess.load(bst)
        for i in range(8):
            sess.predict(mid, X[i:i + 1])
    assert serve_monitor.main([path]) == 0
    out = capsys.readouterr().out
    assert "[closed]" in out
    assert "summary: 8 requests" in out
    assert "qps" in out
    # follow on a finished stream returns immediately with 0
    assert serve_monitor.follow(path, interval=0.05, timeout=10,
                                out=sys.stderr) == 0
    assert serve_monitor.main([str(tmp_path / "nope.jsonl")]) == 2
