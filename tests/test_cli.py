"""CLI application tests: train/predict/convert_model/refit from conf files
(mirrors the reference's tests/cpp_test CLI parity harness and
test_consistency.py conf-file loading)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.cli import Application, load_parameters


@pytest.fixture
def data_files(tmp_path, rng):
    n, f = 600, 5
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    train = tmp_path / "train.csv"
    rows = np.column_stack([y, X])
    np.savetxt(train, rows, delimiter=",", fmt="%.6f")
    valid = tmp_path / "valid.csv"
    np.savetxt(valid, rows[:200], delimiter=",", fmt="%.6f")
    return tmp_path, str(train), str(valid)


def test_load_parameters_conf_file(tmp_path):
    conf = tmp_path / "train.conf"
    conf.write_text(
        "task = train\n"
        "objective = binary\n"
        "# a comment line\n"
        "num_trees = 7\n"
        "learning_rate = 0.2\n")
    params = load_parameters([str(conf), "num_leaves=9"])
    assert params["task"] == "train"
    assert params["objective"] == "binary"
    assert params["num_trees"] == "7"
    assert params["num_leaves"] == "9"


def test_cli_override_beats_conf(tmp_path):
    conf = tmp_path / "c.conf"
    conf.write_text("num_trees = 7\n")
    params = load_parameters(["num_trees=3", f"config={conf}"])
    assert params["num_trees"] == "3"


def test_train_and_predict(data_files):
    tmp_path, train, valid = data_files
    model = str(tmp_path / "model.txt")
    out = str(tmp_path / "preds.txt")
    Application([
        "task=train", f"data={train}", f"valid={valid}",
        "objective=binary", "num_trees=10", "num_leaves=7",
        f"output_model={model}", "metric=binary_logloss", "verbosity=-1",
    ]).run()
    assert os.path.exists(model)
    with open(model) as fh:
        content = fh.read()
    assert content.startswith("tree")
    assert "objective=binary" in content

    Application([
        "task=predict", f"data={train}", f"input_model={model}",
        f"output_result={out}", "verbosity=-1",
    ]).run()
    preds = np.loadtxt(out)
    assert preds.shape[0] == 600
    assert (preds >= 0).all() and (preds <= 1).all()
    # predictions should separate the classes
    y = np.loadtxt(train, delimiter=",")[:, 0]
    assert np.mean((preds > 0.5) == y) > 0.9


def test_snapshot_and_continue(data_files):
    tmp_path, train, valid = data_files
    model = str(tmp_path / "m.txt")
    Application([
        "task=train", f"data={train}", "objective=binary", "num_trees=6",
        f"output_model={model}", "snapshot_freq=2", "verbosity=-1",
    ]).run()
    assert os.path.exists(model + ".snapshot_iter_2")
    assert os.path.exists(model + ".snapshot_iter_4")
    # continued training from the saved model
    model2 = str(tmp_path / "m2.txt")
    Application([
        "task=train", f"data={train}", "objective=binary", "num_trees=4",
        f"input_model={model}", f"output_model={model2}", "verbosity=-1",
    ]).run()
    from lightgbm_tpu.basic import Booster
    b = Booster(model_file=model2)
    assert b.num_trees() == 10


def test_convert_model(data_files):
    tmp_path, train, _ = data_files
    model = str(tmp_path / "m.txt")
    cpp = str(tmp_path / "pred.cpp")
    Application(["task=train", f"data={train}", "objective=binary",
                 "num_trees=3", f"output_model={model}",
                 "verbosity=-1"]).run()
    Application(["task=convert_model", f"input_model={model}",
                 f"convert_model={cpp}", "verbosity=-1"]).run()
    code = open(cpp).read()
    assert "PredictTree0" in code
    assert "PredictRaw" in code


def test_convert_model_compiles(data_files):
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    tmp_path, train, _ = data_files
    model = str(tmp_path / "m.txt")
    cpp = str(tmp_path / "pred.cpp")
    Application(["task=train", f"data={train}", "objective=binary",
                 "num_trees=3", f"output_model={model}",
                 "verbosity=-1"]).run()
    Application(["task=convert_model", f"input_model={model}",
                 f"convert_model={cpp}", "verbosity=-1"]).run()
    r = subprocess.run(["g++", "-fsyntax-only", "-std=c++11", cpp],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


def test_refit(data_files):
    tmp_path, train, _ = data_files
    model = str(tmp_path / "m.txt")
    refitted = str(tmp_path / "refit.txt")
    Application(["task=train", f"data={train}", "objective=binary",
                 "num_trees=5", f"output_model={model}",
                 "verbosity=-1"]).run()
    Application(["task=refit", f"data={train}", f"input_model={model}",
                 f"output_model={refitted}", "refit_decay_rate=0.5",
                 "verbosity=-1"]).run()
    assert os.path.exists(refitted)
    from lightgbm_tpu.basic import Booster
    b1 = Booster(model_file=model)
    b2 = Booster(model_file=refitted)
    X = np.loadtxt(train, delimiter=",")[:, 1:]
    p1, p2 = b1.predict(X), b2.predict(X)
    assert p1.shape == p2.shape
    assert not np.allclose(p1, p2)  # refit changed the leaves
