"""C API smoke test — drives lib_lightgbm_tpu.so through raw ctypes in the
style of the reference's tests/c_api_test/test_.py:1-277 (dataset create
from mat/CSR, SetField, booster train/eval loop, save/load, predict).

The shared library embeds CPython; loading it from inside this Python
process attaches it to the running interpreter, which is the same path the
python package binding uses.
"""

import ctypes
import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("LIGHTGBM_TPU_SKIP_CAPI") == "1",
    reason="C API test disabled")


@pytest.fixture(scope="module")
def LIB(tmp_path_factory):
    from lightgbm_tpu.build_capi import build_capi
    try:
        path = build_capi(str(tmp_path_factory.mktemp("capi")))
    except RuntimeError as e:
        pytest.skip(f"cannot build C API library: {e}")
    lib = ctypes.cdll.LoadLibrary(path)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def c_str(s):
    return ctypes.c_char_p(s.encode("utf-8"))


def _check(lib, rc):
    assert rc == 0, lib.LGBM_GetLastError().decode()


def _make_data(n=600, f=6, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return np.ascontiguousarray(X, dtype=np.float64), y


def _dataset_from_mat(lib, X, y, params="max_bin=31", ref=None):
    handle = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, X.shape[0], X.shape[1], 1,
        c_str(params), ref if ref is not None else None,
        ctypes.byref(handle)))
    _check(lib, lib.LGBM_DatasetSetField(
        handle, c_str("label"),
        np.ascontiguousarray(y, np.float32).ctypes.data_as(ctypes.c_void_p),
        len(y), 0))
    return handle


def test_dataset_roundtrip(LIB, tmp_path):
    X, y = _make_data()
    train = _dataset_from_mat(LIB, X, y)
    num_data = ctypes.c_int()
    num_feat = ctypes.c_int()
    _check(LIB, LIB.LGBM_DatasetGetNumData(train, ctypes.byref(num_data)))
    _check(LIB, LIB.LGBM_DatasetGetNumFeature(train, ctypes.byref(num_feat)))
    assert num_data.value == X.shape[0]
    assert num_feat.value == X.shape[1]

    # GetField returns the label buffer
    out_len = ctypes.c_int()
    out_ptr = ctypes.c_void_p()
    out_type = ctypes.c_int()
    _check(LIB, LIB.LGBM_DatasetGetField(
        train, c_str("label"), ctypes.byref(out_len),
        ctypes.byref(out_ptr), ctypes.byref(out_type)))
    assert out_len.value == len(y)
    assert out_type.value == 0   # float32
    got = np.frombuffer(
        (ctypes.c_char * (4 * out_len.value)).from_address(out_ptr.value),
        dtype=np.float32)
    assert np.allclose(got, y)

    # CSR creation aligned with the train mappers
    import scipy.sparse as sp
    csr = sp.csr_matrix(X)
    h2 = ctypes.c_void_p()
    _check(LIB, LIB.LGBM_DatasetCreateFromCSR(
        np.ascontiguousarray(csr.indptr, np.int32).ctypes.data_as(
            ctypes.c_void_p), 2,
        np.ascontiguousarray(csr.indices, np.int32).ctypes.data_as(
            ctypes.c_void_p),
        np.ascontiguousarray(csr.data, np.float64).ctypes.data_as(
            ctypes.c_void_p), 1,
        ctypes.c_int64(len(csr.indptr)), ctypes.c_int64(len(csr.data)),
        ctypes.c_int64(X.shape[1]),
        c_str("max_bin=31"), train, ctypes.byref(h2)))
    _check(LIB, LIB.LGBM_DatasetFree(h2))

    # binary save/load
    binpath = str(tmp_path / "train.bin")
    _check(LIB, LIB.LGBM_DatasetSaveBinary(train, c_str(binpath)))
    h3 = ctypes.c_void_p()
    _check(LIB, LIB.LGBM_DatasetCreateFromFile(
        c_str(binpath), c_str(""), None, ctypes.byref(h3)))
    _check(LIB, LIB.LGBM_DatasetGetNumData(h3, ctypes.byref(num_data)))
    assert num_data.value == X.shape[0]
    _check(LIB, LIB.LGBM_DatasetFree(h3))
    _check(LIB, LIB.LGBM_DatasetFree(train))


def test_booster_train_eval_predict(LIB, tmp_path):
    X, y = _make_data()
    Xt, yt = _make_data(seed=11)
    train = _dataset_from_mat(LIB, X, y)
    test = _dataset_from_mat(LIB, Xt, yt, ref=train)

    booster = ctypes.c_void_p()
    _check(LIB, LIB.LGBM_BoosterCreate(
        train, c_str("objective=binary metric=auc num_leaves=15 "
                     "min_data_in_leaf=5 verbose=-1"),
        ctypes.byref(booster)))
    _check(LIB, LIB.LGBM_BoosterAddValidData(booster, test))

    n_classes = ctypes.c_int()
    _check(LIB, LIB.LGBM_BoosterGetNumClasses(booster,
                                              ctypes.byref(n_classes)))
    assert n_classes.value == 1

    is_finished = ctypes.c_int(0)
    for _ in range(20):
        _check(LIB, LIB.LGBM_BoosterUpdateOneIter(
            booster, ctypes.byref(is_finished)))
    it = ctypes.c_int()
    _check(LIB, LIB.LGBM_BoosterGetCurrentIteration(booster,
                                                    ctypes.byref(it)))
    assert it.value == 20

    # eval names + valid-set AUC
    n_ev = ctypes.c_int()
    _check(LIB, LIB.LGBM_BoosterGetEvalCounts(booster, ctypes.byref(n_ev)))
    assert n_ev.value >= 1
    bufs = [ctypes.create_string_buffer(64) for _ in range(n_ev.value)]
    arr = (ctypes.c_char_p * n_ev.value)(
        *[ctypes.cast(b, ctypes.c_char_p) for b in bufs])
    _check(LIB, LIB.LGBM_BoosterGetEvalNames(booster, ctypes.byref(n_ev),
                                             arr))
    assert b"auc" in arr[0]
    result = np.zeros(n_ev.value, dtype=np.float64)
    out_len = ctypes.c_int()
    _check(LIB, LIB.LGBM_BoosterGetEval(
        booster, 1, ctypes.byref(out_len),
        result.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == n_ev.value
    assert result[0] > 0.8   # separable problem

    # save / reload / predict parity
    model_path = str(tmp_path / "model.txt")
    _check(LIB, LIB.LGBM_BoosterSaveModel(booster, 0, -1, c_str(model_path)))

    pred0 = np.zeros(X.shape[0], dtype=np.float64)
    out_len64 = ctypes.c_int64()
    _check(LIB, LIB.LGBM_BoosterPredictForMat(
        booster, X.ctypes.data_as(ctypes.c_void_p), 1, X.shape[0],
        X.shape[1], 1, 0, -1, c_str(""), ctypes.byref(out_len64),
        pred0.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len64.value == X.shape[0]
    assert 0.0 <= pred0.min() and pred0.max() <= 1.0

    booster2 = ctypes.c_void_p()
    niter = ctypes.c_int()
    _check(LIB, LIB.LGBM_BoosterCreateFromModelfile(
        c_str(model_path), ctypes.byref(niter), ctypes.byref(booster2)))
    assert niter.value == 20
    pred1 = np.zeros(X.shape[0], dtype=np.float64)
    _check(LIB, LIB.LGBM_BoosterPredictForMat(
        booster2, X.ctypes.data_as(ctypes.c_void_p), 1, X.shape[0],
        X.shape[1], 1, 0, -1, c_str(""), ctypes.byref(out_len64),
        pred1.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert np.abs(pred0 - pred1).max() < 1e-6

    # model string round trip
    out_sz = ctypes.c_int64()
    _check(LIB, LIB.LGBM_BoosterSaveModelToString(
        booster, 0, -1, ctypes.c_int64(0), ctypes.byref(out_sz), None))
    buf = ctypes.create_string_buffer(out_sz.value)
    _check(LIB, LIB.LGBM_BoosterSaveModelToString(
        booster, 0, -1, ctypes.c_int64(out_sz.value), ctypes.byref(out_sz),
        buf))
    assert b"tree" in buf.value

    # feature importance
    imp = np.zeros(X.shape[1], dtype=np.float64)
    _check(LIB, LIB.LGBM_BoosterFeatureImportance(
        booster, -1, 0,
        imp.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert imp.sum() > 0

    _check(LIB, LIB.LGBM_BoosterFree(booster2))
    _check(LIB, LIB.LGBM_BoosterFree(booster))
    _check(LIB, LIB.LGBM_DatasetFree(train))
    _check(LIB, LIB.LGBM_DatasetFree(test))


def test_custom_objective_and_errors(LIB):
    X, y = _make_data(n=400, f=4)
    train = _dataset_from_mat(LIB, X, y)
    booster = ctypes.c_void_p()
    _check(LIB, LIB.LGBM_BoosterCreate(
        train, c_str("objective=none num_leaves=7 min_data_in_leaf=5 "
                     "verbose=-1"),
        ctypes.byref(booster)))
    # custom logistic gradients (UpdateOneIterCustom)
    score = np.zeros(len(y), dtype=np.float64)
    for _ in range(5):
        p = 1.0 / (1.0 + np.exp(-score))
        grad = (p - y).astype(np.float32)
        hess = (p * (1 - p)).astype(np.float32)
        fin = ctypes.c_int()
        _check(LIB, LIB.LGBM_BoosterUpdateOneIterCustom(
            booster,
            grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            hess.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.byref(fin)))
        out_len = ctypes.c_int64()
        raw = np.zeros(len(y), dtype=np.float64)
        _check(LIB, LIB.LGBM_BoosterPredictForMat(
            booster, X.ctypes.data_as(ctypes.c_void_p), 1, X.shape[0],
            X.shape[1], 1, 1, -1, c_str(""), ctypes.byref(out_len),
            raw.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        score = raw
    ll0 = np.log(1 + np.exp(-(2 * y - 1) * 0.0)).mean()
    ll = np.log(1 + np.exp(-(2 * y - 1) * score)).mean()
    assert ll < ll0   # loss actually decreased

    # invalid handle reports through the last-error ring
    bad = ctypes.c_void_p(987654)
    n = ctypes.c_int()
    rc = LIB.LGBM_DatasetGetNumData(bad, ctypes.byref(n))
    assert rc == -1
    assert b"Invalid handle" in LIB.LGBM_GetLastError()

    _check(LIB, LIB.LGBM_BoosterFree(booster))
    _check(LIB, LIB.LGBM_DatasetFree(train))
