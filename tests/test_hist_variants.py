"""Histogram kernel variants (packed accumulator, round-carry staging,
one-hot builds — ops/pallas_histogram.py r6).

Three independently env-gated variants with distinct contracts:

  * packed int16 accumulator (LIGHTGBM_TPU_PACKED_ACC): the count
    channel is EXACT, grad/hess per bin carry stochastic-rounding
    quantization error bounded by scale x (count + 1) — trained models
    must reach quality parity, not bit-identity;
  * round-carry leaf-hist staging (LIGHTGBM_TPU_HIST_STAGE): pure data
    movement, must be BIT-identical;
  * one-hot build alternatives (LIGHTGBM_TPU_ONEHOT_BUILD): same
    [nf*B, chunk] matrix into the same dot_general, must be
    BIT-identical.

Every gate falls back to the baseline path when its self-check fails.
"""

import numpy as np
import pytest

import lightgbm_tpu.ops.pallas_histogram as ph
from lightgbm_tpu.config import Config
from lightgbm_tpu.core.dataset import TpuDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objective import create_objective


def _train(X, y, impl, monkeypatch, env=(), cat_feats=(), n_iters=3,
           **params):
    for k, v in env:
        monkeypatch.setenv(k, v)
    cfg = Config(verbosity=-1, tpu_histogram_backend="pallas",
                 tpu_tree_impl=impl, **params)
    ds = TpuDataset.from_numpy(X, y, config=cfg,
                               categorical_features=list(cat_feats))
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    bst = GBDT(cfg, ds, obj)
    for _ in range(n_iters):
        bst.train_one_iter()
    for k, _ in env:
        monkeypatch.delenv(k, raising=False)
    return bst


def _rand_stream(rng, n):
    grad = rng.standard_normal(n).astype(np.float32)
    hess = rng.uniform(0.5, 1.5, n).astype(np.float32)
    # fractional member exercises the f32-bitcast count lane (GOSS)
    member = np.where(rng.random(n) < 0.2, 0.0,
                      np.where(rng.random(n) < 0.3, 0.25,
                               1.0)).astype(np.float32)
    return grad, hess, member


def test_quantize_count_exact_and_error_bound(rng):
    """Count channel exact; grad/hess per-bin error within the
    stochastic-rounding bound scale x (count + 1)."""
    import jax.numpy as jnp
    nrng = np.random.default_rng(5)
    F, B, rb, n = 6, 32, 512, 2048
    binsT = jnp.asarray(nrng.integers(0, B, (F, n)), jnp.uint8)
    grad, hess, member = _rand_stream(nrng, n)
    g, h, m = map(jnp.asarray, (grad, hess, member))
    w8 = ph.pack_channels(g, h, m)
    ref = np.asarray(ph.unpack_hist(ph.histogram_all(binsT, w8, B, rb)))
    w2, scales, clips = ph.quantize_pack_channels(g, h, m)
    got = np.asarray(ph.unpack_hist_packed(
        ph.histogram_all(binsT, w2, B, rb), scales))
    assert np.array_equal(got[..., 2], ref[..., 2]), "count must be exact"
    sc = np.asarray(scales)
    cnt = ref[..., 2]
    for ch in (0, 1):
        bound = sc[ch] * (cnt + 1.0) + 1e-4
        assert np.all(np.abs(got[..., ch] - ref[..., ch]) <= bound), ch
    assert int(clips) >= 1   # saturated-lane count (max lane by scale)


def test_quantize_zero_weight_rows_stay_zero():
    """member == 0 rows (bagging/pad rows) must quantize to exact zero in
    every lane — otherwise pad rows would leak into bin 0."""
    import jax.numpy as jnp
    g = jnp.asarray([1.0, -2.0, 0.5, 3.0], jnp.float32)
    h = jnp.ones(4, jnp.float32)
    m = jnp.asarray([1.0, 0.0, 0.0, 1.0], jnp.float32)
    w2, scales, _ = ph.quantize_pack_channels(g, h, m)
    w = np.asarray(w2)
    assert w[0, 1] == 0 and w[0, 2] == 0      # packed (gq, hq) pair
    assert w[1, 1] == 0 and w[1, 2] == 0      # bitcast member


def test_packed_self_check_covers_all_legs():
    assert ph._packed_acc_self_check()


@pytest.mark.parametrize("build", ["gather", "twolevel"])
def test_onehot_builds_bit_identical(build):
    assert ph._onehot_build_self_check(build)


@pytest.mark.parametrize("build", ["gather", "twolevel"])
def test_onehot_env_routes_through_wrapper(rng, monkeypatch, build):
    """The non-jit wrappers resolve LIGHTGBM_TPU_ONEHOT_BUILD and the
    result is bitwise equal to the iota baseline."""
    import jax.numpy as jnp
    nrng = np.random.default_rng(11)
    F, B, rb, n = 4, 16, 256, 1024
    binsT = jnp.asarray(nrng.integers(0, B, (F, n)), jnp.uint8)
    g, h, m = map(jnp.asarray, _rand_stream(nrng, n))
    w8 = ph.pack_channels(g, h, m)
    base = np.asarray(ph.histogram_all(binsT, w8, B, rb))
    monkeypatch.setenv("LIGHTGBM_TPU_ONEHOT_BUILD", build)
    got = np.asarray(ph.histogram_all(binsT, w8, B, rb))
    assert np.array_equal(base, got)


def test_onehot_twolevel_requires_pow2_bins():
    """Non-power-of-two B falls back to the iota build statically (the
    high/low split only tiles cleanly for power-of-two widths) — the
    public wrapper must still run and match."""
    import jax.numpy as jnp
    nrng = np.random.default_rng(12)
    F, B, rb, n = 4, 12, 256, 1024
    binsT = jnp.asarray(nrng.integers(0, B, (F, n)), jnp.uint8)
    g, h, m = map(jnp.asarray, _rand_stream(nrng, n))
    w8 = ph.pack_channels(g, h, m)
    a = np.asarray(ph._histogram_all(binsT, w8, B, rb,
                                     onehot_build="iota"))
    b = np.asarray(ph._histogram_all(binsT, w8, B, rb,
                                     onehot_build="twolevel"))
    assert np.array_equal(a, b)


def test_staging_self_check_bit_identity():
    from lightgbm_tpu.models.grower_frontier import _hist_stage_self_check
    assert _hist_stage_self_check()


def test_staging_trained_model_bit_identical(rng, monkeypatch):
    """End-to-end: LIGHTGBM_TPU_HIST_STAGE=force through GBDT training
    must give byte-identical trees and predictions (missing values and
    a categorical feature included)."""
    n = 3000
    X = rng.normal(size=(n, 5))
    X[rng.random(size=n) < 0.1, 2] = np.nan
    X[:, 4] = rng.randint(0, 8, size=n)
    y = ((X[:, 0] + 0.4 * X[:, 1] > 0) | (X[:, 4] > 5)).astype(np.float64)
    kw = dict(objective="binary", num_leaves=15, min_data_in_leaf=5)
    base = _train(X, y, "frontier", monkeypatch,
                  env=[("LIGHTGBM_TPU_HIST_STAGE", "0")],
                  cat_feats=[4], **kw)
    staged = _train(X, y, "frontier", monkeypatch,
                    env=[("LIGHTGBM_TPU_HIST_STAGE", "force")],
                    cat_feats=[4], **kw)
    for i, (ta, tb) in enumerate(zip(base.models, staged.models)):
        assert ta.num_leaves == tb.num_leaves, i
        assert np.array_equal(ta.split_feature, tb.split_feature), i
        assert np.array_equal(ta.threshold_in_bin, tb.threshold_in_bin), i
        np.testing.assert_array_equal(ta.leaf_value, tb.leaf_value)
    np.testing.assert_array_equal(base._raw_predict(X),
                                  staged._raw_predict(X))


@pytest.mark.parametrize("impl", ["segment", "frontier"])
def test_packed_trained_model_quality_parity(rng, monkeypatch, impl):
    """Packed accumulator through GBDT training: same-quality model (not
    bit-identical — quantization may permute tie-break split order).
    Covers missing values, a categorical feature, and bagging."""
    n = 4000
    X = rng.normal(size=(n, 6))
    X[rng.random(size=n) < 0.1, 3] = np.nan
    X[:, 5] = rng.randint(0, 10, size=n)
    p = (X[:, 0] + 0.5 * X[:, 1] > 0) | (X[:, 5] > 7)
    y = p.astype(np.float64)
    kw = dict(objective="binary", num_leaves=15, min_data_in_leaf=5,
              bagging_fraction=0.8, bagging_freq=1, bagging_seed=3)
    base = _train(X, y, impl, monkeypatch,
                  env=[("LIGHTGBM_TPU_PACKED_ACC", "0")],
                  cat_feats=[5], **kw)
    packed = _train(X, y, impl, monkeypatch,
                    env=[("LIGHTGBM_TPU_PACKED_ACC", "force")],
                    cat_feats=[5], **kw)
    pb = 1.0 / (1.0 + np.exp(-base._raw_predict(X)))
    pp = 1.0 / (1.0 + np.exp(-packed._raw_predict(X)))
    acc_b = np.mean((pb > 0.5) == p)
    acc_p = np.mean((pp > 0.5) == p)
    assert acc_b > 0.9, acc_b
    assert acc_p >= acc_b - 0.01, (acc_b, acc_p)
    np.testing.assert_allclose(pp, pb, atol=0.12)


def test_packed_packed4_leg(rng, monkeypatch):
    """max_bin <= 15 (packed4 nibble layout) + packed accumulator."""
    n = 2500
    X = rng.normal(size=(n, 4))
    p = X[:, 0] - 0.6 * X[:, 2] > 0
    y = p.astype(np.float64)
    kw = dict(objective="binary", num_leaves=15, max_bin=15,
              min_data_in_leaf=5)
    packed = _train(X, y, "segment", monkeypatch,
                    env=[("LIGHTGBM_TPU_PACKED_ACC", "force")], **kw)
    assert packed.grower_params.packed4
    pp = 1.0 / (1.0 + np.exp(-packed._raw_predict(X)))
    assert np.mean((pp > 0.5) == p) > 0.9


def test_packed_acc_fallback_on_self_check_failure(monkeypatch):
    """Env =1 runs the self-check; a failing/raising check must fall
    back to the f32 path, and the failure must be memoized."""
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("synthetic lowering failure")

    monkeypatch.setattr(ph, "_PACKED_ACC_CHECK", None)
    monkeypatch.setattr(ph, "_packed_acc_self_check", boom)
    monkeypatch.setenv("LIGHTGBM_TPU_PACKED_ACC", "1")
    assert ph.packed_acc_enabled() is False
    assert ph.packed_acc_enabled() is False
    assert len(calls) == 1, "self-check must be memoized"
    # force bypasses the (failing) check; off never consults it
    monkeypatch.setenv("LIGHTGBM_TPU_PACKED_ACC", "force")
    assert ph.packed_acc_enabled() is True
    monkeypatch.setenv("LIGHTGBM_TPU_PACKED_ACC", "0")
    assert ph.packed_acc_enabled() is False


def test_onehot_fallback_on_self_check_failure(monkeypatch):
    monkeypatch.setattr(ph, "_ONEHOT_BUILD_CHECKS", {})
    monkeypatch.setattr(ph, "_onehot_build_self_check",
                        lambda mode: False)
    monkeypatch.setenv("LIGHTGBM_TPU_ONEHOT_BUILD", "gather")
    assert ph.onehot_build_mode() == "iota"
    # trailing '!' bypasses the check (on-chip A/B plumbing)
    monkeypatch.setenv("LIGHTGBM_TPU_ONEHOT_BUILD", "gather!")
    assert ph.onehot_build_mode() == "gather"
    monkeypatch.setenv("LIGHTGBM_TPU_ONEHOT_BUILD", "nonsense")
    assert ph.onehot_build_mode() == "iota"


def test_hist_stage_fallback_on_self_check_failure(monkeypatch):
    import lightgbm_tpu.models.grower_frontier as gf
    monkeypatch.setattr(gf, "_HIST_STAGE_CHECK", None)
    monkeypatch.setattr(gf, "_hist_stage_self_check",
                        lambda: (_ for _ in ()).throw(RuntimeError("x")))
    monkeypatch.setenv("LIGHTGBM_TPU_HIST_STAGE", "1")
    assert gf.hist_stage_enabled() is False
    monkeypatch.setenv("LIGHTGBM_TPU_HIST_STAGE", "force")
    assert gf.hist_stage_enabled() is True
    monkeypatch.setenv("LIGHTGBM_TPU_HIST_STAGE", "0")
    assert gf.hist_stage_enabled() is False


def test_run_kernel_self_checks_green(capsys):
    """The verify_t1 --with-kernel-checks leg: every variant self-check
    passes on the interpret backend."""
    assert ph.run_kernel_self_checks() == 0
    out = capsys.readouterr().out
    assert "kernel self-checks: PASS" in out
    for name in ("packed_acc", "onehot_gather", "onehot_twolevel",
                 "hist_stage", "fused_route", "fused_k"):
        assert f"ok {name}" in out, name


def test_vmem_limit_autosize():
    """Derived vmem_limit_bytes: calibrated above the measured 17.14 MB
    K=16/F=28/rb=32768 scoped need, at the 16 MB Mosaic default for
    small shapes, never past the 64 MB cap; recorded as a gauge."""
    mb = 1024 * 1024
    big = ph.fused_vmem_limit(28, 64, 16, 32768)
    assert big > int(17.14 * mb)
    assert big <= 64 * mb
    assert ph.fused_vmem_limit(4, 16, 1, 512) == 16 * mb
    from lightgbm_tpu.utils.telemetry import TELEMETRY
    gauges = getattr(TELEMETRY, "_gauges", None)
    if gauges is not None:
        assert gauges.get("hist/vmem_limit_bytes") == 16 * mb


def test_vmem_est_fused_k_and_memoized():
    """The fused-K pass carries a 2K-target accumulator: the estimate
    (and hence the auto limit) must grow with targets_k, stay clamped to
    the 64 MB cap, and the per-shape estimate is lru_cache-memoized so
    every grower build at a repeated shape skips the arithmetic."""
    mb = 1024 * 1024
    base = ph._fused_vmem_est(28, 64, 16, 32768)
    wide = ph._fused_vmem_est(28, 64, 16, 32768, targets_k=32)
    assert wide > base
    # the 2K carry at the calibration shape still fits under the cap
    assert ph.fused_vmem_limit(28, 64, 16, 32768, targets_k=32) <= 64 * mb
    info_before = ph._fused_vmem_est_cached.cache_info()
    ph._fused_vmem_est(28, 64, 16, 32768, targets_k=32)
    ph._fused_vmem_est(28, 64, 16, 32768, targets_k=32)
    info_after = ph._fused_vmem_est_cached.cache_info()
    assert info_after.misses == info_before.misses
    assert info_after.hits >= info_before.hits + 2
    # the fit veto consults the same estimate at the wide carry
    assert isinstance(ph.fused_route_fits(28, 64, 16, 32768, False,
                                          targets_k=32), bool)
