"""Host-spill (out-of-core) tier tests: the HostSpillStore block
reassembly, proactive admission (data_in_hbm=auto against a reported
HBM budget), forced-spill byte-identity against resident training at
chunk sizes 1 and 4, kill+resume mid-spill via the CLI, and the tier's
observability surface (health-stream iter records + run_monitor).
"""

import json
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import Application
from lightgbm_tpu.data.hostspill import HostSpillStore
from lightgbm_tpu.utils.faults import ENV_FAULTS, FAULTS, InjectedFault
from lightgbm_tpu.utils.telemetry import TELEMETRY, TelemetryRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import run_monitor  # noqa: E402

PARAMS = {"objective": "regression", "num_leaves": 7, "verbose": -1,
          "min_data_in_leaf": 5, "seed": 7}


@pytest.fixture(autouse=True)
def _clean():
    TELEMETRY.reset()
    yield
    os.environ.pop(ENV_FAULTS, None)
    FAULTS.configure()


def _make_data(rng, n=240):
    X = rng.rand(n, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.rand(n)
    return X, y


def _fake_mem(monkeypatch, bytes_limit):
    """Pretend the backend reports allocator stats with the given HBM
    capacity (the CPU backend's memory_stats() is None, so the real
    admission path can't be exercised here)."""
    ms = {"bytes_in_use": 0, "peak_bytes_in_use": 0,
          "largest_alloc_size": 0, "bytes_limit": int(bytes_limit)}
    monkeypatch.setattr(TelemetryRegistry, "_device_memory_stats",
                        lambda self: dict(ms))


# ----------------------------------------------------------- the store
def test_store_blocks_rows_layout(rng):
    """Row-major [N, F]: blocked streaming reassembles the exact bytes,
    tail block included (101 rows is not a multiple of 16)."""
    mat = rng.randint(0, 256, size=(101, 7)).astype(np.uint8)
    store = HostSpillStore.from_matrix(mat, row_axis=0, block_bytes=7 * 16)
    assert store.block_rows == 16
    assert store.num_blocks == 7              # 6 full blocks + 5-row tail
    assert store.block_bounds(6) == (96, 101)
    assert store.block(6).shape == (5, 7)
    out = np.asarray(store.stream_to_device())
    assert out.dtype == mat.dtype
    np.testing.assert_array_equal(out, mat)


def test_store_blocks_feature_major_layout(rng):
    """Feature-major [F, Npad] (the pallas training layout): rows are
    axis 1, blocks slice columns of the transposed image."""
    mat = rng.randint(0, 16, size=(5, 64)).astype(np.int32)
    store = HostSpillStore.from_matrix(mat, row_axis=1,
                                       block_bytes=5 * 4 * 10)
    assert store.num_rows == 64
    assert store.block_rows == 10
    assert store.num_blocks == 7
    out = np.asarray(store.stream_to_device())
    np.testing.assert_array_equal(out, mat)


def test_store_default_block_size_is_one_block(rng):
    """The 64MiB default comfortably holds a small matrix in one block —
    the spill machinery must not fragment tiny datasets."""
    mat = rng.randint(0, 256, size=(240, 4)).astype(np.uint8)
    store = HostSpillStore.from_matrix(mat, row_axis=0)
    assert store.num_blocks == 1
    np.testing.assert_array_equal(np.asarray(store.stream_to_device()), mat)


def test_store_mmap_roundtrip(rng, tmp_path):
    """mmap backing: same bytes, file unlinked immediately (the mapping
    keeps it alive), nothing left behind in the spill dir."""
    mat = rng.randint(0, 256, size=(50, 3)).astype(np.uint8)
    store = HostSpillStore.from_matrix(mat, row_axis=0, block_bytes=3 * 8,
                                       mmap_dir=str(tmp_path))
    assert isinstance(store.mat, np.memmap)
    assert list(tmp_path.iterdir()) == []     # unlinked at construction
    np.testing.assert_array_equal(np.asarray(store.stream_to_device()), mat)


def test_store_transfer_counters(rng):
    mat = rng.randint(0, 256, size=(32, 4)).astype(np.uint8)
    store = HostSpillStore.from_matrix(mat, row_axis=0, block_bytes=4 * 8)
    store.stream_to_device()
    counters = TELEMETRY.stats()["counters"]
    assert counters["oocore/h2d_blocks"] == store.num_blocks == 4
    assert counters["oocore/h2d_bytes"] == mat.nbytes


# ------------------------------------------- forced spill == resident
@pytest.mark.parametrize("chunk", [1, 4])
def test_forced_spill_bitidentical_to_resident(rng, chunk):
    """ISSUE acceptance: data_in_hbm=spill streams the matrix per
    dispatch window and the trained model is byte-identical to the
    resident run at both chunk sizes."""
    X, y = _make_data(rng)
    resident = lgb.train(dict(PARAMS, tpu_boost_chunk=chunk),
                         lgb.Dataset(X, label=y), num_boost_round=8)
    assert "memory" not in resident.train_stats  # CPU resident: unchanged
    spilled = lgb.train(dict(PARAMS, tpu_boost_chunk=chunk,
                             data_in_hbm="spill"),
                        lgb.Dataset(X, label=y), num_boost_round=8)
    assert spilled.model_to_string() == resident.model_to_string()
    stats = spilled.train_stats
    assert stats["memory"]["data_tier"] == "spill"
    counts = stats["faults"]["counts"]
    assert counts["oocore_admit"] == 1        # the forced decision logged
    assert "oom_degrade" not in counts and "oom_spill" not in counts
    assert stats["counters"]["oocore/h2d_blocks"] >= 1
    assert stats["gauges"]["oocore/spill_bytes"] > 0


def test_data_in_hbm_validation():
    from lightgbm_tpu.config import Config
    with pytest.raises(ValueError, match="data_in_hbm must be one of"):
        Config(data_in_hbm="hbm2")
    assert Config(data_in_hbm="RESIDENT").data_in_hbm == "resident"
    assert Config().data_in_hbm == "auto"


# --------------------------------------------------- proactive admission
def test_admission_check_selects_spill(rng, monkeypatch):
    """Satellite: a device whose reported HBM cannot hold the estimated
    working set starts out-of-core PROACTIVELY — the run completes with
    zero RESOURCE_EXHAUSTED events in the faults section."""
    X, y = _make_data(rng)
    resident = lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                         lgb.Dataset(X, label=y), num_boost_round=8)
    _fake_mem(monkeypatch, bytes_limit=4096)  # matrix can never fit
    bst = lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                    lgb.Dataset(X, label=y), num_boost_round=8)
    assert bst.current_iteration() == 8
    counts = bst.train_stats["faults"]["counts"]
    assert counts["oocore_admit"] == 1
    for oom_kind in ("oom_degrade", "oom_spill", "injected"):
        assert oom_kind not in counts         # zero RESOURCE_EXHAUSTED
    assert bst.train_stats["memory"]["data_tier"] == "spill"
    assert bst.model_to_string() == resident.model_to_string()


def test_admission_resident_override(rng, monkeypatch):
    """data_in_hbm=resident overrides the admission check: the matrix is
    pinned in HBM even when the reported budget says it won't fit."""
    X, y = _make_data(rng)
    _fake_mem(monkeypatch, bytes_limit=4096)
    bst = lgb.train(dict(PARAMS, tpu_boost_chunk=4,
                         data_in_hbm="resident"),
                    lgb.Dataset(X, label=y), num_boost_round=8)
    assert bst.current_iteration() == 8
    # no fault events at all -> the faults section is cleanly absent
    counts = bst.train_stats.get("faults", {}).get("counts", {})
    assert "oocore_admit" not in counts
    assert bst.train_stats["memory"]["data_tier"] == "resident"


def test_admission_passes_with_headroom(rng, monkeypatch):
    """A roomy budget keeps the run resident — auto must not spill for
    no reason."""
    X, y = _make_data(rng)
    _fake_mem(monkeypatch, bytes_limit=1 << 40)
    bst = lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                    lgb.Dataset(X, label=y), num_boost_round=8)
    counts = bst.train_stats.get("faults", {}).get("counts", {})
    assert "oocore_admit" not in counts
    assert bst.train_stats["memory"]["data_tier"] == "resident"


# ------------------------------------------------ CLI: kill+resume mid-spill
def _write_csv(path, rng, n=300):
    X = rng.rand(n, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.rand(n)
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.6f")


def _cli_argv(extra=()):
    return ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "num_iterations=8", "num_leaves=7",
            "min_data_in_leaf=5", "verbosity=-1", "snapshot_freq=2",
            "tpu_boost_chunk=4", "output_model=model.txt",
            "metrics_out=metrics.json", *extra]


def test_kill_and_resume_mid_spill_bitexact(tmp_path, rng, monkeypatch):
    """ISSUE acceptance: a spill-mode run killed mid-training resumes
    from its snapshot still in spill mode and lands byte-identical to an
    uninterrupted RESIDENT run — data_in_hbm is runtime-only, so even
    the serialized parameters sections match."""
    seed = rng.randint(1 << 30)
    a, b = tmp_path / "a", tmp_path / "b"
    for d in (a, b):
        d.mkdir()
        _write_csv(d / "train.csv", np.random.RandomState(seed))

    monkeypatch.chdir(a)
    Application(_cli_argv()).run()            # uninterrupted, resident

    monkeypatch.chdir(b)
    argv = _cli_argv(["data_in_hbm=spill"])
    monkeypatch.setenv(ENV_FAULTS, "train/kill@4")
    FAULTS.configure()
    with pytest.raises(InjectedFault):
        Application(argv).run()
    assert (b / "model.txt.partial").exists()

    monkeypatch.delenv(ENV_FAULTS)
    FAULTS.configure()
    Application(argv + ["resume=true"]).run()
    assert (b / "model.txt").read_bytes() == (a / "model.txt").read_bytes()
    blob = json.loads((b / "metrics.json").read_text())
    assert blob["faults"]["counts"]["resume"] == 1
    # once per process run: the killed run AND the resume each resolved
    # the forced tier (telemetry counts span both in-process runs)
    assert blob["faults"]["counts"]["oocore_admit"] == 2
    assert blob["memory"]["data_tier"] == "spill"


# ----------------------------------------------------- observability
def test_health_stream_carries_data_tier(tmp_path, rng):
    path = str(tmp_path / "run.health.jsonl")
    X, y = _make_data(rng)
    lgb.train(dict(PARAMS, tpu_boost_chunk=4, data_in_hbm="spill",
                   health_out=path),
              lgb.Dataset(X, label=y), num_boost_round=6)
    with open(path) as fh:
        recs = [json.loads(line) for line in fh if line.strip()]
    iters = [r for r in recs if r["kind"] == "iter"]
    assert iters and all(r["data_tier"] == "spill" for r in iters)

    state = run_monitor.StreamState()
    with open(path, "rb") as fh:
        state.feed(fh.read())
    assert "tier=spill" in run_monitor.render(state, path)


def test_run_monitor_tier_na_safe():
    """Older streams have no data_tier field; the monitor renders them
    unchanged."""
    state = run_monitor.StreamState()
    state.feed(json.dumps({"kind": "iter", "iter": 0, "chunk": 2,
                           "t": 1.0}).encode() + b"\n")
    out = run_monitor.render(state, "x.jsonl")
    assert "tier=" not in out
    assert "chunk=2" in out
