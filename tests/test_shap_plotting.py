"""SHAP (predict_contrib) and plotting tests — reference coverage:
test_engine.py predict_contrib assertions + test_plotting.py."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture
def binary_booster(rng):
    X = rng.normal(size=(1200, 6))
    y = (X[:, 0] * 2 + X[:, 1] - X[:, 2] > 0).astype(np.float64)
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "num_leaves": 15}
    res = {}
    ds = lgb.Dataset(X, y)
    bst = lgb.train(params, ds, num_boost_round=10,
                    valid_sets=[ds.create_valid(X, y)], verbose_eval=False,
                    evals_result=res)
    return bst, X, y, res


def test_contrib_local_accuracy(binary_booster):
    """TreeSHAP local accuracy: contributions (+ bias) sum to the raw
    score for every row (Tree::PredictContrib contract)."""
    bst, X, y, _ = binary_booster
    contrib = bst.predict(X[:50], pred_contrib=True)
    assert contrib.shape == (50, X.shape[1] + 1)
    raw = bst.predict(X[:50], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-6,
                               atol=1e-6)
    # the dominant feature must carry the largest mean |contribution|
    mean_abs = np.abs(contrib[:, :-1]).mean(axis=0)
    assert int(np.argmax(mean_abs)) == 0


def test_contrib_multiclass_shape(rng):
    X = rng.normal(size=(900, 5))
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    params = {"objective": "multiclass", "num_class": 3, "verbose": -1,
              "num_leaves": 7}
    bst = lgb.train(params, lgb.Dataset(X, y.astype(float)),
                    num_boost_round=5)
    contrib = bst.predict(X[:20], pred_contrib=True)
    assert contrib.shape == (20, 3 * (X.shape[1] + 1))
    raw = bst.predict(X[:20], raw_score=True)
    sums = contrib.reshape(20, 3, X.shape[1] + 1).sum(axis=2)
    np.testing.assert_allclose(sums, raw, rtol=1e-6, atol=1e-6)


def test_plot_importance_and_metric(binary_booster):
    mpl = pytest.importorskip("matplotlib")
    mpl.use("Agg")
    bst, X, y, res = binary_booster
    ax = lgb.plot_importance(bst)
    labels = [t.get_text() for t in ax.get_yticklabels()]
    assert any("Column_0" in l for l in labels)
    ax2 = lgb.plot_metric(res, metric="binary_logloss")
    assert ax2.get_lines()
    import matplotlib.pyplot as plt
    plt.close("all")


def test_plot_tree_runs(binary_booster):
    import shutil
    if not shutil.which("dot"):
        pytest.skip("graphviz `dot` binary not installed")
    mpl = pytest.importorskip("matplotlib")
    mpl.use("Agg")
    bst, _, _, _ = binary_booster
    ax = lgb.plot_tree(bst, tree_index=0)
    assert ax is not None
    import matplotlib.pyplot as plt
    plt.close("all")


def test_plot_split_value_histogram(rng):
    matplotlib = pytest.importorskip("matplotlib")
    matplotlib.use("Agg")
    X = rng.normal(size=(600, 4))
    y = X[:, 0] * 2 + rng.normal(size=600) * 0.1
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "min_data_in_leaf": 5}, lgb.Dataset(X, y),
                    num_boost_round=10, verbose_eval=False)
    ax = lgb.plot_split_value_histogram(bst, 0)
    assert len(ax.patches) > 0
    with pytest.raises(ValueError, match="never splits"):
        # train only ever splits features with signal; an all-noise
        # feature may split occasionally, so probe one that cannot exist
        bst2 = lgb.train({"objective": "regression", "verbose": -1,
                          "min_data_in_leaf": 600},
                         lgb.Dataset(X, y), num_boost_round=1,
                         verbose_eval=False)
        lgb.plot_split_value_histogram(bst2, 1)
