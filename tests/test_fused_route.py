"""Fused route+histogram kernels (ops/pallas_histogram.py r5).

The fused kernels fold the split's leaf_id routing into the histogram
pass (the reference's routing likewise rides the partition work,
src/treelearner/data_partition.hpp:111).  They must reproduce the
unfused route_split_windowed + histogram_segment/frontier pair exactly:
same leaf ids (including untouched blocks through the input/output
alias), same histograms, hence identical trees.
"""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.core.dataset import TpuDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objective import create_objective


def _train(X, y, impl, fused, monkeypatch, cat_feats=(), n_iters=3,
           **params):
    monkeypatch.setenv("LIGHTGBM_TPU_FUSED_ROUTE", "1" if fused else "0")
    cfg = Config(verbosity=-1, tpu_histogram_backend="pallas",
                 tpu_tree_impl=impl, **params)
    ds = TpuDataset.from_numpy(X, y, config=cfg,
                               categorical_features=list(cat_feats))
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    bst = GBDT(cfg, ds, obj)
    for _ in range(n_iters):
        bst.train_one_iter()
    return bst


def _assert_identical(a, b, X):
    assert len(a.models) == len(b.models)
    for i, (ta, tb) in enumerate(zip(a.models, b.models)):
        assert ta.num_leaves == tb.num_leaves, f"tree {i}"
        assert np.array_equal(ta.split_feature, tb.split_feature), i
        assert np.array_equal(ta.threshold_in_bin, tb.threshold_in_bin), i
        np.testing.assert_allclose(ta.leaf_value, tb.leaf_value,
                                   rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(a._raw_predict(X), b._raw_predict(X),
                               rtol=1e-6, atol=1e-7)


def test_kernel_self_check():
    from lightgbm_tpu.ops.pallas_histogram import _fused_route_self_check
    assert _fused_route_self_check()


@pytest.mark.parametrize("impl", ["segment", "frontier"])
def test_fused_matches_unfused(rng, monkeypatch, impl):
    """Numerical + categorical + NaN routing, multi-block, compaction."""
    n = 4000
    X = rng.normal(size=(n, 6))
    X[rng.random(size=n) < 0.1, 3] = np.nan
    X[:, 5] = rng.randint(0, 12, size=n)
    y = ((X[:, 0] + 0.5 * X[:, 1] > 0)
         | (X[:, 5] > 8)).astype(np.float64)
    kw = dict(objective="binary", num_leaves=31, max_bin=63,
              min_data_in_leaf=5)
    unfused = _train(X, y, impl, False, monkeypatch, cat_feats=[5], **kw)
    fused = _train(X, y, impl, True, monkeypatch, cat_feats=[5], **kw)
    assert fused._use_segment or impl == "frontier"
    _assert_identical(unfused, fused, X)


def test_fused_matches_unfused_packed4(rng, monkeypatch):
    """max_bin <= 15 selects the packed4 nibble layout; the in-kernel
    route must unpack the split column by parity."""
    n = 3000
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] - 0.7 * X[:, 2] > 0).astype(np.float64)
    kw = dict(objective="binary", num_leaves=15, max_bin=15,
              min_data_in_leaf=5)
    unfused = _train(X, y, "segment", False, monkeypatch, **kw)
    fused = _train(X, y, "segment", True, monkeypatch, **kw)
    assert fused.grower_params.packed4
    _assert_identical(unfused, fused, X)


def test_route_kernel_matches_xla_route(monkeypatch, rng):
    """route_window (aliased pallas window kernel) must reproduce the
    XLA windowed route bit-for-bit through a trained model: same trees,
    same predictions (LIGHTGBM_TPU_ROUTE_KERNEL=1 forces the kernel on
    the CPU interpret path; auto only engages on a real accelerator)."""
    import subprocess
    import sys

    import numpy as np

    code = """
import numpy as np, lightgbm_tpu as lgb, os
rng = np.random.RandomState(3)
X = rng.normal(size=(4000, 8)); y = (X[:,0] - 0.5*X[:,1] > 0).astype(float)
params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
          "tpu_histogram_backend": "pallas",
          "tpu_tree_impl": os.environ["IMPL"]}
bst = lgb.train(params, lgb.Dataset(X, y, params=params), 4)
np.save(os.environ["OUT"], bst.predict(X))
"""
    import os
    preds = {}
    for impl in ("segment", "frontier"):
        for tag, rk in (("xla", "0"), ("kernel", "1")):
            out = f"/tmp/route_ab_{impl}_{tag}.npy"
            # DYN_GRID pinned on: =0 would silently veto the forced
            # kernel leg and both legs would compare the XLA path
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PALLAS_AXON_POOL_IPS="",
                       LIGHTGBM_TPU_DYN_GRID="1",
                       LIGHTGBM_TPU_ROUTE_KERNEL=rk, IMPL=impl, OUT=out)
            r = subprocess.run([sys.executable, "-c", code], env=env,
                               capture_output=True, text=True)
            assert r.returncode == 0, r.stderr[-500:]
            preds[(impl, tag)] = np.load(out)
        d = np.abs(preds[(impl, "xla")] - preds[(impl, "kernel")]).max()
        assert d == 0.0, (impl, d)


# ------------------------------------------------------------------ fused-K
# PR 16: histogram_frontier_fusedk routes the round's K splits AND
# accumulates ALL 2K children in one pass.  Bit-identity contract: the
# fused pass must equal routing the ids first (numpy reference) and
# running histogram_frontier over the SAME 2K targets — both concat the
# same masked channel sets into the same one-hot matmul in the same
# chunk order, so every accumulator column is the identical f32 dot.


def test_fused_k_kernel_self_check():
    from lightgbm_tpu.ops.pallas_histogram import _fused_k_self_check
    assert _fused_k_self_check()


@pytest.mark.parametrize("K", [1, 4, 16])
def test_fused_k_bit_identity_kernel(K):
    """K routes cycling the flavor set — numeric zero-missing rows,
    NaN-missing rows, categorical bitset, plain numeric — plus a null
    tail slot at K>1 (the grower's invalid-prefix shape)."""
    import jax.numpy as jnp
    import numpy as np

    from lightgbm_tpu.ops.pallas_histogram import (histogram_frontier,
                                                   histogram_frontier_fusedk,
                                                   null_route,
                                                   pack_channels, pack_route)

    rng = np.random.RandomState(17)
    F, B, rb, nblk = 6, 16, 256, 8
    n = rb * nblk
    binsT_np = rng.randint(0, B, size=(F, n)).astype(np.uint8)
    # zero-missing rows: feature 0 carries its default bin often enough
    # that every parent routes some missing rows
    binsT_np[0, rng.random(n) < 0.3] = 2
    # NaN-missing rows: feature 2's NaN bin is B - 1
    binsT_np[2, rng.random(n) < 0.2] = B - 1
    binsT = jnp.asarray(binsT_np)
    w8 = pack_channels(jnp.asarray(rng.randn(n), jnp.float32),
                       jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32),
                       jnp.asarray((rng.random(n) < 0.9), jnp.float32))
    parents = 10 + np.arange(K, dtype=np.int32)
    news = 100 + np.arange(K, dtype=np.int32)
    lid_np = parents[rng.randint(0, K, size=n)].astype(np.int32)
    bl = jnp.arange(nblk, dtype=jnp.int32)
    nb = jnp.int32(nblk)
    bitset = jnp.asarray(
        rng.randint(0, 2**32, size=8, dtype=np.uint64).astype(np.uint32))

    class _M:
        feat_group = None
        feat_offset = None
        missing_type = jnp.asarray([1, 0, 2, 0, 0, 0], jnp.int32)
        default_bin = jnp.asarray([2, 0, 0, 0, 0, 0], jnp.int32)
        num_bin = jnp.full((F,), B, jnp.int32)

    def np_go_left(f, thr, dl, cat):
        fcol = binsT_np[f].astype(np.int64)
        mt = int(_M.missing_type[f])
        miss = ((mt == 1) & (fcol == int(_M.default_bin[f]))
                | (mt == 2) & (fcol == B - 1))
        if cat:
            w = np.asarray(bitset)[np.clip(fcol, 0, 255) // 32]
            return (w >> (np.clip(fcol, 0, 255) % 32)) & 1 > 0
        return np.where(miss, dl, fcol <= thr)

    # flavor cycle: (feature, cat, default_left); the tail slot of any
    # K > 1 case is a null route with -1 targets (invalid prefix slot)
    flavors = [(0, False, True), (1, True, False), (2, False, False),
               (3, False, True)]
    routes, exp = [], lid_np.copy()
    t2 = np.concatenate([parents, news]).astype(np.int32)
    for j in range(K):
        if K > 1 and j == K - 1:
            routes.append(null_route())
            t2[j] = t2[K + j] = -1
            continue
        f, cat, dl = flavors[j % len(flavors)]
        thr = B // 2 + (j % 3)
        routes.append(pack_route(int(parents[j]), int(news[j]), f, thr,
                                 dl, cat, bitset, _M, False))
        exp[(exp == parents[j]) & ~np_go_left(f, thr, dl, cat)] = news[j]
    lid2, hist = histogram_frontier_fusedk(
        binsT, w8, jnp.asarray(lid_np), bl, nb, jnp.asarray(t2),
        jnp.stack(routes), B, rb, K)
    assert np.array_equal(np.asarray(lid2), exp)
    ref = histogram_frontier(binsT, w8, jnp.asarray(exp), bl, nb,
                             jnp.asarray(t2), B, rb)
    assert np.array_equal(np.asarray(hist), np.asarray(ref))


def test_fused_k_fallback_on_self_check_failure(monkeypatch):
    """Env =1 runs the self-check; a raising check falls back cleanly,
    the failure is memoized, '!'/force bypass, =0 never consults it —
    and a vetoed K>1 policy request counts a fused_k_fallbacks event."""
    import lightgbm_tpu.ops.pallas_histogram as ph
    from lightgbm_tpu.utils.telemetry import TELEMETRY

    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("synthetic lowering failure")

    monkeypatch.setattr(ph, "_FUSED_K_CHECK", None)
    monkeypatch.setattr(ph, "_fused_k_self_check", boom)
    monkeypatch.setenv("LIGHTGBM_TPU_DYN_GRID", "1")
    monkeypatch.setenv("LIGHTGBM_TPU_FUSED_K", "1")
    assert ph.fused_k_enabled() is False
    assert ph.fused_k_enabled() is False
    assert len(calls) == 1, "self-check must be memoized"
    before = TELEMETRY.stats()["counters"].get("hist/fused_k_fallbacks",
                                               0)
    assert ph.fused_route_policy(8, 28, 64, 32768, False) != "fusedk"
    after = TELEMETRY.stats()["counters"].get("hist/fused_k_fallbacks", 0)
    assert after == before + 1
    # trailing '!' and force bypass the (failing) check; off never
    # consults it
    monkeypatch.setenv("LIGHTGBM_TPU_FUSED_K", "1!")
    assert ph.fused_k_enabled() is True
    assert ph.fused_route_policy(8, 28, 64, 32768, False) == "fusedk"
    monkeypatch.setenv("LIGHTGBM_TPU_FUSED_K", "force")
    assert ph.fused_k_enabled() is True
    monkeypatch.setenv("LIGHTGBM_TPU_FUSED_K", "0")
    assert ph.fused_k_enabled() is False
    assert len(calls) == 1


def test_fused_k_grower_matches_no_subtract(rng):
    """The fused-K round computes BOTH children from data — the same
    arithmetic family as CommHooks(no_subtract=True).  Same tree, same
    leaf ids, bit-exact (the subtraction-trick default differs in f32
    rounding, which is why that is not the comparison here)."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.models.grower import CommHooks, GrowerParams
    from lightgbm_tpu.models.grower_frontier import make_grow_tree_frontier
    from lightgbm_tpu.ops.split import FeatureMeta, SplitParams

    F, B, L, rb, K, n = 4, 16, 8, 256, 3, 2048
    binsT = jnp.asarray(rng.randint(0, B, size=(F, n)), jnp.uint8)
    grad = jnp.asarray(rng.randn(n), jnp.float32)
    hess = jnp.ones(n, jnp.float32)
    member = jnp.ones(n, jnp.float32)
    fmeta = FeatureMeta(num_bin=jnp.full(F, B, jnp.int32),
                        missing_type=jnp.zeros(F, jnp.int32),
                        default_bin=jnp.zeros(F, jnp.int32),
                        is_cat=jnp.zeros(F, bool),
                        monotone=jnp.zeros(F, jnp.int32),
                        penalty=jnp.ones(F, jnp.float32))
    gp = GrowerParams(num_leaves=L, hist_backend="pallas",
                      split=SplitParams(min_data_in_leaf=2.0))
    fmask = jnp.ones(F, jnp.float32)
    key = jax.random.PRNGKey(0)
    g_fk = make_grow_tree_frontier(B, gp, rb, batch_k=K, fused_k=True)
    g_ns = make_grow_tree_frontier(B, gp, rb, batch_k=K,
                                   comm=CommHooks(no_subtract=True))
    ta, la, sa = g_fk(binsT, grad, hess, member, fmeta, fmask, key)
    tb, lb, _ = g_ns(binsT, grad, hess, member, fmeta, fmask, key)
    assert np.array_equal(np.asarray(la), np.asarray(lb))
    import jax.tree_util as jtu
    for fa, fb in zip(jtu.tree_leaves(ta), jtu.tree_leaves(tb)):
        assert np.array_equal(np.asarray(fa), np.asarray(fb))
    # stats slot 5 counts the fused rounds (telemetry hist/fused_k_rounds)
    assert int(np.asarray(sa)[5]) > 0


def test_fused_packed_optin_decision(monkeypatch):
    """packed_acc forces the unfused pair unless LIGHTGBM_TPU_FUSED_PACKED
    opts the combined variant in (build-time decision, no training)."""
    import jax.numpy as jnp

    from lightgbm_tpu.models.grower import GrowerParams
    from lightgbm_tpu.models.grower_frontier import make_grow_tree_frontier
    from lightgbm_tpu.ops.pallas_histogram import fused_route_decisions
    from lightgbm_tpu.ops.split import SplitParams

    gp = GrowerParams(num_leaves=31, hist_backend="pallas",
                      split=SplitParams(min_data_in_leaf=2.0))
    monkeypatch.setenv("LIGHTGBM_TPU_DYN_GRID", "1")
    monkeypatch.setenv("LIGHTGBM_TPU_PACKED_ACC", "force")
    monkeypatch.setenv("LIGHTGBM_TPU_FUSED_K", "force")
    monkeypatch.delenv("LIGHTGBM_TPU_FUSED_PACKED", raising=False)
    make_grow_tree_frontier(16, gp, 256, batch_k=4)
    assert fused_route_decisions["frontier"] is False
    monkeypatch.setenv("LIGHTGBM_TPU_FUSED_PACKED", "1")
    make_grow_tree_frontier(16, gp, 256, batch_k=4)
    assert fused_route_decisions["frontier"] == "fusedk"
