"""Fused route+histogram kernels (ops/pallas_histogram.py r5).

The fused kernels fold the split's leaf_id routing into the histogram
pass (the reference's routing likewise rides the partition work,
src/treelearner/data_partition.hpp:111).  They must reproduce the
unfused route_split_windowed + histogram_segment/frontier pair exactly:
same leaf ids (including untouched blocks through the input/output
alias), same histograms, hence identical trees.
"""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.core.dataset import TpuDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objective import create_objective


def _train(X, y, impl, fused, monkeypatch, cat_feats=(), n_iters=3,
           **params):
    monkeypatch.setenv("LIGHTGBM_TPU_FUSED_ROUTE", "1" if fused else "0")
    cfg = Config(verbosity=-1, tpu_histogram_backend="pallas",
                 tpu_tree_impl=impl, **params)
    ds = TpuDataset.from_numpy(X, y, config=cfg,
                               categorical_features=list(cat_feats))
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    bst = GBDT(cfg, ds, obj)
    for _ in range(n_iters):
        bst.train_one_iter()
    return bst


def _assert_identical(a, b, X):
    assert len(a.models) == len(b.models)
    for i, (ta, tb) in enumerate(zip(a.models, b.models)):
        assert ta.num_leaves == tb.num_leaves, f"tree {i}"
        assert np.array_equal(ta.split_feature, tb.split_feature), i
        assert np.array_equal(ta.threshold_in_bin, tb.threshold_in_bin), i
        np.testing.assert_allclose(ta.leaf_value, tb.leaf_value,
                                   rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(a._raw_predict(X), b._raw_predict(X),
                               rtol=1e-6, atol=1e-7)


def test_kernel_self_check():
    from lightgbm_tpu.ops.pallas_histogram import _fused_route_self_check
    assert _fused_route_self_check()


@pytest.mark.parametrize("impl", ["segment", "frontier"])
def test_fused_matches_unfused(rng, monkeypatch, impl):
    """Numerical + categorical + NaN routing, multi-block, compaction."""
    n = 4000
    X = rng.normal(size=(n, 6))
    X[rng.random(size=n) < 0.1, 3] = np.nan
    X[:, 5] = rng.randint(0, 12, size=n)
    y = ((X[:, 0] + 0.5 * X[:, 1] > 0)
         | (X[:, 5] > 8)).astype(np.float64)
    kw = dict(objective="binary", num_leaves=31, max_bin=63,
              min_data_in_leaf=5)
    unfused = _train(X, y, impl, False, monkeypatch, cat_feats=[5], **kw)
    fused = _train(X, y, impl, True, monkeypatch, cat_feats=[5], **kw)
    assert fused._use_segment or impl == "frontier"
    _assert_identical(unfused, fused, X)


def test_fused_matches_unfused_packed4(rng, monkeypatch):
    """max_bin <= 15 selects the packed4 nibble layout; the in-kernel
    route must unpack the split column by parity."""
    n = 3000
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] - 0.7 * X[:, 2] > 0).astype(np.float64)
    kw = dict(objective="binary", num_leaves=15, max_bin=15,
              min_data_in_leaf=5)
    unfused = _train(X, y, "segment", False, monkeypatch, **kw)
    fused = _train(X, y, "segment", True, monkeypatch, **kw)
    assert fused.grower_params.packed4
    _assert_identical(unfused, fused, X)


def test_route_kernel_matches_xla_route(monkeypatch, rng):
    """route_window (aliased pallas window kernel) must reproduce the
    XLA windowed route bit-for-bit through a trained model: same trees,
    same predictions (LIGHTGBM_TPU_ROUTE_KERNEL=1 forces the kernel on
    the CPU interpret path; auto only engages on a real accelerator)."""
    import subprocess
    import sys

    import numpy as np

    code = """
import numpy as np, lightgbm_tpu as lgb, os
rng = np.random.RandomState(3)
X = rng.normal(size=(4000, 8)); y = (X[:,0] - 0.5*X[:,1] > 0).astype(float)
params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
          "tpu_histogram_backend": "pallas",
          "tpu_tree_impl": os.environ["IMPL"]}
bst = lgb.train(params, lgb.Dataset(X, y, params=params), 4)
np.save(os.environ["OUT"], bst.predict(X))
"""
    import os
    preds = {}
    for impl in ("segment", "frontier"):
        for tag, rk in (("xla", "0"), ("kernel", "1")):
            out = f"/tmp/route_ab_{impl}_{tag}.npy"
            # DYN_GRID pinned on: =0 would silently veto the forced
            # kernel leg and both legs would compare the XLA path
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PALLAS_AXON_POOL_IPS="",
                       LIGHTGBM_TPU_DYN_GRID="1",
                       LIGHTGBM_TPU_ROUTE_KERNEL=rk, IMPL=impl, OUT=out)
            r = subprocess.run([sys.executable, "-c", code], env=env,
                               capture_output=True, text=True)
            assert r.returncode == 0, r.stderr[-500:]
            preds[(impl, tag)] = np.load(out)
        d = np.abs(preds[(impl, "xla")] - preds[(impl, "kernel")]).max()
        assert d == 0.0, (impl, d)
