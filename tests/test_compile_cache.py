"""Persistent compilation cache behavior (utils.enable_jax_compilation_cache).

The warm-start wall-clock lever (VERDICT r4 item 3): executables must
survive process boundaries through the on-disk cache so a second run
skips recompilation.
"""
def test_persistent_compile_cache_round_trip(tmp_path):
    """The persistent executable cache must actually store and re-serve
    compiles across processes (the warm-start wall-clock lever, VERDICT
    r4 item 3): a second identical training process must HIT the cache
    populated by the first, not recompile."""
    import subprocess
    import sys

    from lightgbm_tpu.utils import cpu_subprocess_env

    code = """
import jax
jax.config.update("jax_platforms", "cpu")
from lightgbm_tpu.utils import enable_jax_compilation_cache
enable_jax_compilation_cache({root!r})
import numpy as np
import lightgbm_tpu as lgb
rng = np.random.RandomState(0)
X = rng.normal(size=(2000, 6))
y = (X[:, 0] > 0).astype(float)
bst = lgb.train({{"objective": "binary", "verbose": -1,
                  "num_leaves": 15}}, lgb.Dataset(X, y),
                num_boost_round=2, verbose_eval=False)
print("TRAINED", float(bst.predict(X[:1]).item()))
""".format(root=str(tmp_path))
    env = cpu_subprocess_env()
    import os
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    for run in range(2):
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "TRAINED" in proc.stdout
        cache = tmp_path / ".jax_cache"
        entries = list(cache.glob("*")) if cache.exists() else []
        assert entries, f"run {run}: no cache entries written"
        if run == 0:
            first = {p.name for p in entries}
        else:
            # the second process re-used the first's executables: no
            # (or almost no) new entries — a cold second process that
            # recompiled everything would roughly double the dir
            second = {p.name for p in entries}
            new = second - first
            assert len(new) <= max(2, len(first) // 4), (
                len(first), len(new))

