"""Dynamic-grid histogram variants (LIGHTGBM_TPU_DYN_GRID=1).

The gated dispatch sizes the pallas grid to the traced interval length
instead of lax.switching over the static bucket ladder
(ops/pallas_histogram.{_histogram_segment_dyn,_histogram_frontier_dyn}).
These tests pin exact parity with the ladder path on the same inputs —
the variants must be drop-in interchangeable because the on-chip driver
A/Bs them via env alone.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.pallas_histogram import (histogram_frontier,
                                               histogram_segment,
                                               pack_channels, unpack_hist)


@pytest.fixture()
def data():
    rng = np.random.RandomState(11)
    F, B, rb = 6, 32, 256
    n = rb * 5
    binsT = jnp.asarray(rng.randint(0, B, size=(F, n)).astype(np.uint8))
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.5, 1.0, n).astype(np.float32))
    m = jnp.asarray((rng.rand(n) > 0.25).astype(np.float32))
    lid = jnp.asarray(rng.randint(0, 4, size=n).astype(np.int32))
    return F, B, rb, n, binsT, pack_channels(g, h, m), lid


def _seg(monkeypatch, dyn, *args, **kw):
    monkeypatch.setenv("LIGHTGBM_TPU_DYN_GRID", "1" if dyn else "")
    return np.asarray(unpack_hist(histogram_segment(*args, **kw)))


def test_segment_dyn_matches_ladder(monkeypatch, data):
    F, B, rb, n, binsT, w8, lid = data
    for lo, nb, leaf in [(0, 5, 2), (1, 3, 0), (4, 1, 3), (0, 0, 1)]:
        a = _seg(monkeypatch, False, binsT, w8, lid, jnp.int32(lo),
                 jnp.int32(nb), jnp.int32(leaf), B, rb)
        b = _seg(monkeypatch, True, binsT, w8, lid, jnp.int32(lo),
                 jnp.int32(nb), jnp.int32(leaf), B, rb)
        np.testing.assert_allclose(a, b, rtol=0, atol=0,
                                   err_msg=f"lo={lo} nb={nb} leaf={leaf}")


def test_frontier_dyn_matches_ladder(monkeypatch, data):
    F, B, rb, n, binsT, w8, lid = data
    bl = jnp.asarray(np.r_[0, 2, 3, np.zeros(2)].astype(np.int32))
    tg = jnp.asarray([3, 1, -1, 0], jnp.int32)

    monkeypatch.setenv("LIGHTGBM_TPU_DYN_GRID", "")
    a = np.asarray(histogram_frontier(binsT, w8, lid, bl, jnp.int32(3),
                                      tg, B, rb))
    monkeypatch.setenv("LIGHTGBM_TPU_DYN_GRID", "1")
    b = np.asarray(histogram_frontier(binsT, w8, lid, bl, jnp.int32(3),
                                      tg, B, rb))
    np.testing.assert_array_equal(a, b)
    # -1 targets stay zero in both
    assert np.asarray(b)[2].sum() == 0
