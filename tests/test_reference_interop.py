"""Interop proof against the ACTUAL reference binary (round-3 verdict
item 3): build the reference CLI from /root/reference with cmake, train
models with it, cross-load the model files in both directions, and
assert prediction parity.

The fork's CMakeLists hard-requires two vendored dependencies that are
absent from the source drop (the easy_profiler submodule and the PHub
parameter-server library, CMakeLists.txt:42,253).  Neither is used on a
single-machine CPU run, so the build fixture copies the tree to a scratch
dir and installs no-op stand-ins before building.  Skips cleanly when the
reference tree or toolchain is unavailable.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = os.environ.get("LIGHTGBM_REFERENCE_DIR", "/root/reference")
CACHE_DIR = os.environ.get("LIGHTGBM_REF_BUILD_CACHE",
                           "/tmp/lightgbm_tpu_ref_build")

EASY_PROFILER_STUB = """\
#pragma once
#include <cstdint>
#define EASY_FUNCTION(...)
#define EASY_BLOCK(...)
#define EASY_END_BLOCK
#define EASY_PROFILER_ENABLE
#define EASY_PROFILER_DISABLE
namespace profiler {
namespace colors {
typedef uint32_t color_t;
const color_t Blue500 = 0, BlueA700 = 0, Cyan = 0, Green = 0,
    Green200 = 0, Magenta = 0, Orange = 0, PaleGold = 0, Purple = 0,
    Red50 = 0, Yellow100 = 0;
}
inline int dumpBlocksToFile(const char*) { return 0; }
inline void startListen(int = 0) {}
}
"""

PHUB_STUB = """\
#pragma once
#include <cstdlib>
#include <cstring>
#include <cstddef>
#include <iostream>
#include <memory>
#include <string>
#include <vector>
#define PHUB_CHECK(x) if (!(x)) ::abort(); else std::cerr << ""
#define COMPILER_BARRIER() asm volatile("" ::: "memory")
typedef int PLinkKey;
enum class PHubDataType { CUSTOM, FLOAT };
class PHub {
 public:
  std::vector<int> keySizes;
  std::vector<void*> ApplicationSuppliedAddrs;
  std::vector<void*> ApplicationSuppliedOutputAddrs;
  void SetReductionFunction(void (*)(char*, char*)) { ::abort(); }
  void Reduce() { ::abort(); }
  void Reduce(const std::vector<PLinkKey>&) { ::abort(); }
  void FastTerminate() {}
};
inline std::shared_ptr<PHub> createPHubInstance(
    void*, size_t, int, int, int, PHubDataType, size_t,
    const std::string& = std::string()) {
  ::abort();
  return nullptr;
}
inline std::string pHubGetOptionalEnvironmentVariable(
    const std::string& name, const std::string& dflt = std::string()) {
  const char* v = std::getenv(name.c_str());
  return v ? std::string(v) : dflt;
}
inline std::string pHubGetMandatoryEnvironmemtVariable(
    const std::string& name) {
  const char* v = std::getenv(name.c_str());
  if (v == NULL) ::abort();
  return std::string(v);
}
template <typename T, typename U>
inline T RoundUp(T value, U multiple) {
  T m = (T)multiple;
  return m == 0 ? value : ((value + m - 1) / m) * m;
}
"""


def _build_reference() -> str:
    """Copy + patch + build the reference CLI; returns the binary path."""
    binary = os.path.join(CACHE_DIR, "src", "lightgbm")
    if os.path.exists(binary):
        return binary
    if not os.path.exists(os.path.join(REFERENCE, "CMakeLists.txt")):
        pytest.skip(f"reference tree not found at {REFERENCE}")
    if shutil.which("cmake") is None or shutil.which("make") is None:
        pytest.skip("cmake/make not available")
    src = os.path.join(CACHE_DIR, "src")
    bld = os.path.join(CACHE_DIR, "build")
    shutil.rmtree(CACHE_DIR, ignore_errors=True)
    shutil.copytree(REFERENCE, src)
    subprocess.run(["chmod", "-R", "u+w", src], check=True)
    stub = os.path.join(src, "stub_deps")
    os.makedirs(os.path.join(stub, "easy"))
    with open(os.path.join(stub, "easy", "profiler.h"), "w") as fh:
        fh.write(EASY_PROFILER_STUB)
    with open(os.path.join(stub, "Integration.h"), "w") as fh:
        fh.write(PHUB_STUB)
    cml = os.path.join(src, "CMakeLists.txt")
    text = open(cml).read()
    text = text.replace(
        "ADD_DEFINITIONS(-DBUILD_WITH_EASY_PROFILER)\n"
        "include_directories(easy_profiler/easy_profiler_core/include)\n"
        "add_subdirectory(easy_profiler)",
        "include_directories(stub_deps)")
    text = text.replace("TARGET_LINK_LIBRARIES(lightgbm PHub)", "")
    # the profiler submodule is absent from the source drop; the header
    # stub above replaces its macros, so the link lines must go too
    text = text.replace("target_link_libraries(_lightgbm easy_profiler)",
                        "")
    text = text.replace("target_link_libraries(lightgbm easy_profiler)",
                        "")
    with open(cml, "w") as fh:
        fh.write(text)
    os.makedirs(bld)
    try:
        subprocess.run(["cmake", "-S", src, "-B", bld,
                        "-DCMAKE_BUILD_TYPE=Release"],
                       check=True, capture_output=True, timeout=300)
        subprocess.run(["make", "-C", bld, "-j8", "lightgbm"],
                       check=True, capture_output=True, timeout=1200)
    except subprocess.CalledProcessError as e:
        pytest.skip(f"reference build failed: "
                    f"{e.stderr.decode(errors='replace')[-500:]}")
    assert os.path.exists(binary)
    return binary


@pytest.fixture(scope="module")
def ref_cli():
    return _build_reference()


def _run_ref(binary, workdir, **params):
    args = [binary] + [f"{k}={v}" for k, v in params.items()]
    proc = subprocess.run(args, cwd=workdir, capture_output=True,
                          text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc


def _example(name):
    return os.path.join(REFERENCE, "examples", name)


def _load_examples_data(example, train_file, n_features):
    data = np.loadtxt(os.path.join(_example(example), train_file),
                      delimiter="\t")
    y = data[:, 0]
    X = data[:, 1:1 + n_features]
    return X, y


def test_reference_model_loads_and_matches(ref_cli, tmp_path):
    """Reference-trained binary model -> our Booster: identical preds."""
    import lightgbm_tpu as lgb

    ex = _example("binary_classification")
    model = tmp_path / "ref_model.txt"
    _run_ref(ref_cli, ex, task="train", config="train.conf",
             num_trees=10, output_model=str(model), verbosity=-1)
    pred_file = tmp_path / "ref_preds.txt"
    _run_ref(ref_cli, ex, task="predict", data="binary.test",
             input_model=str(model), output_result=str(pred_file),
             verbosity=-1)
    ref_preds = np.loadtxt(pred_file)

    X, _ = _load_examples_data("binary_classification", "binary.test", 28)
    bst = lgb.Booster(model_file=str(model))
    ours = bst.predict(X)
    np.testing.assert_allclose(ours, ref_preds, rtol=1e-5, atol=1e-6)


def test_our_model_loads_in_reference(ref_cli, tmp_path):
    """Our trained model file -> reference CLI predict: identical preds."""
    import lightgbm_tpu as lgb

    X, y = _load_examples_data("binary_classification", "binary.train", 28)
    params = {"objective": "binary", "num_leaves": 31, "max_bin": 255,
              "learning_rate": 0.1, "verbose": -1, "min_data_in_leaf": 20}
    ds = lgb.Dataset(X, y)
    bst = lgb.train(params, ds, num_boost_round=10, verbose_eval=False)
    model = tmp_path / "tpu_model.txt"
    bst.save_model(str(model))

    Xt, _ = _load_examples_data("binary_classification", "binary.test", 28)
    ours = bst.predict(Xt)

    pred_file = tmp_path / "ref_preds.txt"
    _run_ref(ref_cli, _example("binary_classification"), task="predict",
             data="binary.test", input_model=str(model),
             output_result=str(pred_file), verbosity=-1)
    ref_preds = np.loadtxt(pred_file)
    np.testing.assert_allclose(ref_preds, ours, rtol=1e-5, atol=1e-6)


def test_reference_multiclass_model_matches(ref_cli, tmp_path):
    """Multiclass softmax cross-load (reference -> ours)."""
    import lightgbm_tpu as lgb

    ex = _example("multiclass_classification")
    model = tmp_path / "ref_model.txt"
    _run_ref(ref_cli, ex, task="train", config="train.conf",
             num_trees=8, output_model=str(model), verbosity=-1)
    pred_file = tmp_path / "ref_preds.txt"
    _run_ref(ref_cli, ex, task="predict", data="multiclass.test",
             input_model=str(model), output_result=str(pred_file),
             verbosity=-1)
    ref_preds = np.loadtxt(pred_file)

    data = np.loadtxt(os.path.join(ex, "multiclass.test"), delimiter="\t")
    X = data[:, 1:]
    bst = lgb.Booster(model_file=str(model))
    ours = bst.predict(X)
    np.testing.assert_allclose(ours, ref_preds, rtol=1e-5, atol=1e-6)


def test_reference_lambdarank_model_matches(ref_cli, tmp_path):
    """Lambdarank cross-load (reference -> ours), raw ranking scores."""
    import lightgbm_tpu as lgb

    ex = _example("lambdarank")
    model = tmp_path / "ref_model.txt"
    _run_ref(ref_cli, ex, task="train", config="train.conf",
             num_trees=8, output_model=str(model), verbosity=-1)
    pred_file = tmp_path / "ref_preds.txt"
    _run_ref(ref_cli, ex, task="predict", data="rank.test",
             input_model=str(model), output_result=str(pred_file),
             verbosity=-1)
    ref_preds = np.loadtxt(pred_file)

    from lightgbm_tpu.core.parser import parse_file_to_matrix
    bst = lgb.Booster(model_file=str(model))
    n_feat = bst.gbdt.max_feature_idx + 1   # libsvm tails under-read
    X, _ = parse_file_to_matrix(os.path.join(ex, "rank.test"), False,
                                n_feat)
    ours = bst.predict(X)
    np.testing.assert_allclose(ours, ref_preds, rtol=1e-5, atol=1e-6)


def test_our_multiclass_model_loads_in_reference(ref_cli, tmp_path):
    """Our multiclass softmax model file -> reference CLI predict."""
    import lightgbm_tpu as lgb

    ex = _example("multiclass_classification")
    data = np.loadtxt(os.path.join(ex, "multiclass.train"), delimiter="\t")
    X, y = data[:, 1:], data[:, 0]
    params = {"objective": "multiclass", "num_class": 5, "num_leaves": 31,
              "verbose": -1, "min_data_in_leaf": 20}
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=5,
                    verbose_eval=False)
    model = tmp_path / "tpu_mc.txt"
    bst.save_model(str(model))

    test = np.loadtxt(os.path.join(ex, "multiclass.test"), delimiter="\t")
    ours = bst.predict(test[:, 1:])

    pred_file = tmp_path / "ref_preds.txt"
    _run_ref(ref_cli, ex, task="predict", data="multiclass.test",
             input_model=str(model), output_result=str(pred_file),
             verbosity=-1)
    ref_preds = np.loadtxt(pred_file)
    np.testing.assert_allclose(ref_preds, ours, rtol=1e-5, atol=1e-6)


def test_our_lambdarank_model_loads_in_reference(ref_cli, tmp_path):
    """Our lambdarank model file -> reference CLI predict (raw scores)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.core.parser import parse_file_to_matrix

    ex = _example("lambdarank")
    X, y = parse_file_to_matrix(os.path.join(ex, "rank.train"), False, 301)
    groups = np.loadtxt(os.path.join(ex, "rank.train.query"),
                        dtype=np.int64)
    params = {"objective": "lambdarank", "num_leaves": 31, "verbose": -1,
              "min_data_in_leaf": 20}
    ds = lgb.Dataset(X, y, group=groups)
    bst = lgb.train(params, ds, num_boost_round=5, verbose_eval=False)
    model = tmp_path / "tpu_rank.txt"
    bst.save_model(str(model))

    Xt, _ = parse_file_to_matrix(os.path.join(ex, "rank.test"), False, 301)
    ours = bst.predict(Xt)

    pred_file = tmp_path / "ref_preds.txt"
    _run_ref(ref_cli, ex, task="predict", data="rank.test",
             input_model=str(model), output_result=str(pred_file),
             verbosity=-1)
    ref_preds = np.loadtxt(pred_file)
    np.testing.assert_allclose(ref_preds, ours, rtol=1e-5, atol=1e-6)
