/* Mock of R_ext/Rdynload.h — registration becomes a no-op. */
#ifndef LGBMTPU_R_MOCK_RDYNLOAD_H_
#define LGBMTPU_R_MOCK_RDYNLOAD_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef void* (*DL_FUNC)(void);
typedef struct {
  const char* name;
  DL_FUNC fun;
  int numArgs;
} R_CallMethodDef;
typedef struct mock_dllinfo {
  int unused;
} DllInfo;

static inline int R_registerRoutines(DllInfo* dll, const void* c,
                                     const R_CallMethodDef* call,
                                     const void* f, const void* ext) {
  (void)dll; (void)c; (void)call; (void)f; (void)ext;
  return 0;
}
static inline int R_useDynamicSymbols(DllInfo* dll, int v) {
  (void)dll; (void)v;
  return 0;
}

#ifdef __cplusplus
}
#endif

#endif
