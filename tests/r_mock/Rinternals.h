/*
 * Minimal functional mock of the R C API — just enough to compile AND
 * RUN R-package/src/lightgbm_tpu_R.c without an R installation, so the
 * test suite exercises the .Call shim's real behavior (tests/
 * test_r_package.py drives a train/predict round trip through it).
 *
 * SEXP here is a tagged heap object; "protection" is a no-op (the
 * driver never triggers GC because there is none).  This is a test
 * double, NOT an R reimplementation.
 */
#ifndef LGBMTPU_R_MOCK_INTERNALS_H_
#define LGBMTPU_R_MOCK_INTERNALS_H_

#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#ifdef __cplusplus
extern "C" {
#endif

#define NILSXP 0
#define REALSXP 14
#define INTSXP 13
#define STRSXP 16
#define CHARSXP 9
#define LGLSXP 10
#define EXTPTRSXP 22

typedef long R_xlen_t;

typedef struct mock_sexp {
  int type;
  R_xlen_t length;
  double* reals;
  int* ints;
  char* chars;                   /* CHARSXP payload */
  struct mock_sexp** strs;       /* STRSXP elements (CHARSXPs) */
  void* extptr;
  void (*finalizer)(struct mock_sexp*);
  /* one attribute slot is all the shim uses (dim / num_iterations) */
  const char* attr_name;
  struct mock_sexp* attr_value;
} mock_sexp;

typedef mock_sexp* SEXP;

extern SEXP R_NilValue;
extern const char* R_DimSymbol;

/* ---- allocation ---- */

static inline SEXP mock_alloc_sexp(int type) {
  SEXP s = (SEXP)calloc(1, sizeof(mock_sexp));
  s->type = type;
  return s;
}

static inline SEXP Rf_allocVector(int type, R_xlen_t n) {
  SEXP s = mock_alloc_sexp(type);
  s->length = n;
  if (type == REALSXP) {
    s->reals = (double*)calloc(n > 0 ? n : 1, sizeof(double));
  } else if (type == INTSXP || type == LGLSXP) {
    s->ints = (int*)calloc(n > 0 ? n : 1, sizeof(int));
  } else if (type == STRSXP) {
    s->strs = (mock_sexp**)calloc(n > 0 ? n : 1, sizeof(mock_sexp*));
  }
  return s;
}

static inline SEXP Rf_mkChar(const char* str) {
  SEXP s = mock_alloc_sexp(CHARSXP);
  s->length = (R_xlen_t)strlen(str);
  s->chars = strdup(str);
  return s;
}

static inline SEXP Rf_mkString(const char* str) {
  SEXP v = Rf_allocVector(STRSXP, 1);
  v->strs[0] = Rf_mkChar(str);
  return v;
}

/* ---- accessors ---- */

static inline double* REAL(SEXP s) { return s->reals; }
static inline int* INTEGER(SEXP s) { return s->ints; }
static inline const char* CHAR(SEXP s) { return s->chars; }
static inline SEXP STRING_ELT(SEXP s, R_xlen_t i) { return s->strs[i]; }
static inline void SET_STRING_ELT(SEXP s, R_xlen_t i, SEXP v) {
  s->strs[i] = v;
}
static inline R_xlen_t Rf_length(SEXP s) { return s->length; }
static inline int Rf_isNull(SEXP s) {
  return s == NULL || s->type == NILSXP;
}
static inline int Rf_asInteger(SEXP s) {
  if (s->type == REALSXP) return (int)s->reals[0];
  return s->ints[0];
}
static inline SEXP Rf_ScalarInteger(int v) {
  SEXP s = Rf_allocVector(INTSXP, 1);
  s->ints[0] = v;
  return s;
}
static inline SEXP Rf_ScalarLogical(int v) {
  SEXP s = Rf_allocVector(LGLSXP, 1);
  s->ints[0] = v;
  return s;
}

/* ---- attributes (single slot) ---- */

static inline const char* Rf_install(const char* name) { return name; }
static inline SEXP Rf_getAttrib(SEXP s, const char* name) {
  if (s->attr_name != NULL && strcmp(s->attr_name, name) == 0) {
    return s->attr_value;
  }
  return R_NilValue;
}
static inline void Rf_setAttrib(SEXP s, const char* name, SEXP v) {
  s->attr_name = name;
  s->attr_value = v;
}

/* ---- protection: no GC in the mock ---- */

#define PROTECT(x) (x)
#define UNPROTECT(n) ((void)(n))

/* ---- error: print + abort (the driver treats abort as failure) ---- */

static inline void Rf_error(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  fprintf(stderr, "R mock error: ");
  vfprintf(stderr, fmt, ap);
  fprintf(stderr, "\n");
  va_end(ap);
  exit(77);
}

/* ---- external pointers ---- */

typedef int Rboolean;
#ifndef TRUE
#define TRUE 1
#define FALSE 0
#endif

static inline SEXP R_MakeExternalPtr(void* p, SEXP tag, SEXP prot) {
  (void)tag;
  (void)prot;
  SEXP s = mock_alloc_sexp(EXTPTRSXP);
  s->extptr = p;
  return s;
}
static inline void* R_ExternalPtrAddr(SEXP s) { return s->extptr; }
static inline void R_ClearExternalPtr(SEXP s) { s->extptr = NULL; }
static inline void R_RegisterCFinalizerEx(SEXP s, void (*fin)(SEXP),
                                          Rboolean onexit) {
  (void)onexit;
  s->finalizer = fin;
}

/* ---- transient allocation: leaked by the mock (no R heap) ---- */

static inline char* R_alloc(size_t n, int size) {
  return (char*)calloc(n > 0 ? n : 1, (size_t)size);
}

#ifdef __cplusplus
}
#endif

#endif /* LGBMTPU_R_MOCK_INTERNALS_H_ */
