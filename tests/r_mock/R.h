/* Mock of R.h — everything lives in the mock Rinternals.h. */
#ifndef LGBMTPU_R_MOCK_R_H_
#define LGBMTPU_R_MOCK_R_H_
#include "Rinternals.h"
#endif
