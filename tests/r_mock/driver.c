/*
 * Behavioral test driver for the R .Call shim, run without R: builds
 * mock SEXPs (tests/r_mock/Rinternals.h), then drives dataset
 * construction, training, prediction, eval introspection, and model
 * save/load through R-package/src/lightgbm_tpu_R.c exactly as the R
 * front end would.  Exit 0 = pass; any Rf_error exits 77.
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "Rinternals.h"

SEXP R_NilValue = NULL;
const char* R_DimSymbol = "dim";

/* shim entry points (R-package/src/lightgbm_tpu_R.c) */
extern SEXP LGBMTPU_GetLastError_R(void);
extern SEXP LGBMTPU_DatasetCreateFromMat_R(SEXP, SEXP, SEXP);
extern SEXP LGBMTPU_DatasetSetField_R(SEXP, SEXP, SEXP);
extern SEXP LGBMTPU_DatasetGetNumData_R(SEXP);
extern SEXP LGBMTPU_DatasetGetNumFeature_R(SEXP);
extern SEXP LGBMTPU_DatasetSetFeatureNames_R(SEXP, SEXP);
extern SEXP LGBMTPU_DatasetGetFeatureNames_R(SEXP);
extern SEXP LGBMTPU_DatasetFree_R(SEXP);
extern SEXP LGBMTPU_BoosterCreate_R(SEXP, SEXP);
extern SEXP LGBMTPU_BoosterCreateFromModelfile_R(SEXP);
extern SEXP LGBMTPU_BoosterUpdateOneIter_R(SEXP);
extern SEXP LGBMTPU_BoosterGetCurrentIteration_R(SEXP);
extern SEXP LGBMTPU_BoosterGetEvalNames_R(SEXP);
extern SEXP LGBMTPU_BoosterGetEval_R(SEXP, SEXP);
extern SEXP LGBMTPU_BoosterPredictForMat_R(SEXP, SEXP, SEXP, SEXP, SEXP);
extern SEXP LGBMTPU_BoosterSaveModel_R(SEXP, SEXP, SEXP);
extern SEXP LGBMTPU_BoosterSaveModelToString_R(SEXP, SEXP);
extern SEXP LGBMTPU_BoosterLoadModelFromString_R(SEXP);
extern SEXP LGBMTPU_BoosterGetNumFeature_R(SEXP);
extern SEXP LGBMTPU_BoosterGetFeatureNames_R(SEXP);
extern SEXP LGBMTPU_DatasetGetField_R(SEXP, SEXP);
extern SEXP LGBMTPU_BoosterFeatureImportance_R(SEXP, SEXP, SEXP);
extern SEXP LGBMTPU_BoosterDumpModel_R(SEXP, SEXP);
extern SEXP LGBMTPU_BoosterFree_R(SEXP);

#define N 400
#define F 4

static SEXP make_matrix(const double* colmajor, int nrow, int ncol) {
  SEXP m = Rf_allocVector(REALSXP, (R_xlen_t)nrow * ncol);
  for (long i = 0; i < (long)nrow * ncol; ++i) {
    m->reals[i] = colmajor[i];
  }
  SEXP dim = Rf_allocVector(INTSXP, 2);
  dim->ints[0] = nrow;
  dim->ints[1] = ncol;
  Rf_setAttrib(m, R_DimSymbol, dim);
  return m;
}

int main(int argc, char** argv) {
  const char* model_path = argc > 1 ? argv[1] : "/tmp/r_mock_model.txt";
  /* deterministic column-major data; label = x0 > 0 */
  static double X[N * F];
  static double y[N];
  unsigned s = 123456789u;
  for (int i = 0; i < N * F; ++i) {
    s = s * 1103515245u + 12345u;
    X[i] = ((double)(s >> 8) / (double)(1u << 24)) * 4.0 - 2.0;
  }
  for (int i = 0; i < N; ++i) {
    y[i] = X[i] > 0.0 ? 1.0 : 0.0;   /* column 0 is X[0..N-1] */
  }

  SEXP params = Rf_mkString(
      "objective=binary verbosity=-1 min_data_in_leaf=5 num_leaves=15");
  SEXP mat = make_matrix(X, N, F);
  SEXP ds = LGBMTPU_DatasetCreateFromMat_R(mat, params, R_NilValue);

  SEXP lab = Rf_allocVector(REALSXP, N);
  for (int i = 0; i < N; ++i) lab->reals[i] = y[i];
  LGBMTPU_DatasetSetField_R(ds, Rf_mkString("label"), lab);

  if (Rf_asInteger(LGBMTPU_DatasetGetNumData_R(ds)) != N) {
    fprintf(stderr, "num_data mismatch\n");
    return 1;
  }
  if (Rf_asInteger(LGBMTPU_DatasetGetNumFeature_R(ds)) != F) {
    fprintf(stderr, "num_feature mismatch\n");
    return 1;
  }
  SEXP fn = Rf_allocVector(STRSXP, F);
  SET_STRING_ELT(fn, 0, Rf_mkChar("alpha"));
  SET_STRING_ELT(fn, 1, Rf_mkChar("beta"));
  SET_STRING_ELT(fn, 2, Rf_mkChar("gamma"));
  SET_STRING_ELT(fn, 3, Rf_mkChar("delta"));
  LGBMTPU_DatasetSetFeatureNames_R(ds, fn);
  SEXP back = LGBMTPU_DatasetGetFeatureNames_R(ds);
  if (Rf_length(back) != F ||
      strcmp(CHAR(STRING_ELT(back, 0)), "alpha") != 0) {
    fprintf(stderr, "feature-name round trip failed\n");
    return 1;
  }

  SEXP bst = LGBMTPU_BoosterCreate_R(ds, params);
  for (int i = 0; i < 8; ++i) {
    LGBMTPU_BoosterUpdateOneIter_R(bst);
  }
  if (Rf_asInteger(LGBMTPU_BoosterGetCurrentIteration_R(bst)) != 8) {
    fprintf(stderr, "iteration count mismatch\n");
    return 1;
  }
  SEXP enames = LGBMTPU_BoosterGetEvalNames_R(bst);
  if (Rf_length(enames) < 1) {
    fprintf(stderr, "no eval names\n");
    return 1;
  }
  SEXP ev = LGBMTPU_BoosterGetEval_R(bst, Rf_ScalarInteger(0));
  if (Rf_length(ev) != Rf_length(enames)) {
    fprintf(stderr, "eval length mismatch\n");
    return 1;
  }

  SEXP zero = Rf_ScalarInteger(0);
  SEXP all_iters = Rf_ScalarInteger(-1);
  SEXP empty = Rf_mkString("");
  SEXP pred = LGBMTPU_BoosterPredictForMat_R(bst, mat, zero, all_iters,
                                             empty);
  if (Rf_length(pred) != N) {
    fprintf(stderr, "prediction length mismatch\n");
    return 1;
  }
  int correct = 0;
  for (int i = 0; i < N; ++i) {
    correct += (pred->reals[i] > 0.5) == (y[i] > 0.5);
  }
  double acc = (double)correct / N;
  if (acc < 0.9) {
    fprintf(stderr, "accuracy too low: %.3f\n", acc);
    return 1;
  }

  /* model file round trip through the shim's load path */
  LGBMTPU_BoosterSaveModel_R(bst, all_iters, Rf_mkString(model_path));
  SEXP bst2 = LGBMTPU_BoosterCreateFromModelfile_R(
      Rf_mkString(model_path));
  SEXP pred2 = LGBMTPU_BoosterPredictForMat_R(bst2, mat, zero, all_iters,
                                              empty);
  for (int i = 0; i < N; ++i) {
    if (fabs(pred->reals[i] - pred2->reals[i]) > 1e-6) {
      fprintf(stderr, "loaded-model prediction mismatch at %d\n", i);
      return 1;
    }
  }

  /* importance: the label is a threshold on feature 0, so the split
   * counts must concentrate there */
  if (Rf_asInteger(LGBMTPU_BoosterGetNumFeature_R(bst)) != F) {
    fprintf(stderr, "booster num_feature mismatch\n");
    return 1;
  }
  SEXP imp_split = LGBMTPU_BoosterFeatureImportance_R(bst, all_iters,
                                                      Rf_ScalarInteger(0));
  SEXP imp_gain = LGBMTPU_BoosterFeatureImportance_R(bst, all_iters,
                                                     Rf_ScalarInteger(1));
  if (Rf_length(imp_split) != F || Rf_length(imp_gain) != F) {
    fprintf(stderr, "importance length mismatch\n");
    return 1;
  }
  for (int j = 1; j < F; ++j) {
    if (imp_split->reals[0] < imp_split->reals[j] ||
        imp_gain->reals[0] < imp_gain->reals[j]) {
      fprintf(stderr, "importance did not favor feature 0\n");
      return 1;
    }
  }

  /* JSON dump sanity */
  SEXP dump = LGBMTPU_BoosterDumpModel_R(bst, all_iters);
  const char* js = CHAR(STRING_ELT(dump, 0));
  if (js[0] != '{' || strstr(js, "tree_info") == NULL) {
    fprintf(stderr, "dump is not a model JSON\n");
    return 1;
  }

  /* model-string round trip (the RDS persistence path) */
  SEXP mstr = LGBMTPU_BoosterSaveModelToString_R(bst, all_iters);
  SEXP bst3 = LGBMTPU_BoosterLoadModelFromString_R(mstr);
  SEXP pred3 = LGBMTPU_BoosterPredictForMat_R(bst3, mat, zero, all_iters,
                                              empty);
  for (int i = 0; i < N; ++i) {
    if (fabs(pred->reals[i] - pred3->reals[i]) > 1e-6) {
      fprintf(stderr, "string-loaded prediction mismatch at %d\n", i);
      return 1;
    }
  }
  LGBMTPU_BoosterFree_R(bst3);

  /* booster feature names (lgb.interprete's label source) */
  SEXP bfn = LGBMTPU_BoosterGetFeatureNames_R(bst);
  if (Rf_length(bfn) != F ||
      strcmp(CHAR(STRING_ELT(bfn, 0)), CHAR(STRING_ELT(back, 0))) != 0) {
    fprintf(stderr, "booster feature names mismatch\n");
    return 1;
  }

  /* metadata read-back (lgb.Dataset.get.field) */
  SEXP lab_back = LGBMTPU_DatasetGetField_R(ds, Rf_mkString("label"));
  if (Rf_length(lab_back) != N) {
    fprintf(stderr, "label read-back length mismatch\n");
    return 1;
  }
  for (int i = 0; i < N; ++i) {
    if (fabs(lab_back->reals[i] - y[i]) > 1e-7) {
      fprintf(stderr, "label read-back value mismatch at %d\n", i);
      return 1;
    }
  }

  /* leaf-index prediction (ptype 2): one index per (row, tree), each a
   * valid leaf — what lgb.interprete's path walk consumes */
  SEXP two = Rf_ScalarInteger(2);
  SEXP leaves = LGBMTPU_BoosterPredictForMat_R(bst, mat, two, all_iters,
                                               empty);
  if (Rf_length(leaves) != (R_xlen_t)N * 8) {
    fprintf(stderr, "predleaf length mismatch: %ld\n",
            (long)Rf_length(leaves));
    return 1;
  }
  for (long i = 0; i < (long)N * 8; ++i) {
    if (leaves->reals[i] < 0 || leaves->reals[i] > 1024) {
      fprintf(stderr, "predleaf out of range at %ld\n", i);
      return 1;
    }
  }

  LGBMTPU_BoosterFree_R(bst);
  LGBMTPU_BoosterFree_R(bst2);
  LGBMTPU_DatasetFree_R(ds);
  printf("r_mock driver OK: acc=%.3f evals=%ld\n", acc,
         (long)Rf_length(enames));
  return 0;
}
