"""Split-finding completeness: monotone constraint propagation, CEGB
penalties, forced splits, prediction early stop (VERDICT r2 item 6).

Reference behaviors:
  * monotone: per-leaf [min,max] output bounds handed to children at
    mid=(left+right)/2 (serial_tree_learner.cpp:892-903) — descendant
    leaves can never violate the constraint, which local child-ordering
    rejection alone would not guarantee;
  * CEGB (serial_tree_learner.cpp:527-618): per-row split penalty +
    coupled/lazy feature penalties subtracted from gains;
  * forced splits (ForceSplits :642): JSON-specified top-of-tree splits;
  * prediction early stop (prediction_early_stop.cpp:30-73).
"""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _monotone_sweep(bst, f, n_contexts, n_points, nf, rng):
    """Model output over a sweep of feature f with all else fixed."""
    out = []
    grid = np.linspace(-3, 3, n_points)
    for _ in range(n_contexts):
        ctx = rng.normal(size=nf)
        X = np.tile(ctx, (n_points, 1))
        X[:, f] = grid
        out.append(bst.predict(X, raw_score=True))
    return np.asarray(out)


@pytest.mark.parametrize("impl", ["fused", "segment"])
def test_monotone_constraints_hold_globally(rng, impl):
    """Train deep enough that descendants re-split the monotone feature;
    the full model function must be monotone, not just sibling-ordered."""
    n, nf = 4000, 4
    X = rng.normal(size=(n, nf))
    # strong interaction so the tree re-splits feature 0 deep in the tree
    y = (np.sin(2 * X[:, 0]) + 0.8 * X[:, 1] * (X[:, 0] > 0)
         + 0.3 * X[:, 2] + rng.normal(size=n) * 0.05)
    params = {"objective": "regression", "verbose": -1, "num_leaves": 63,
              "min_data_in_leaf": 5, "max_bin": 63,
              "monotone_constraints": [1, 0, 0, 0]}
    if impl == "segment":
        params.update(tpu_histogram_backend="pallas",
                      tpu_tree_impl="segment", tpu_row_chunk=256)
    else:
        params.update(tpu_tree_impl="fused")
    bst = lgb.train(params, lgb.Dataset(X, y), 25, verbose_eval=False)
    if impl == "segment":
        assert bst.gbdt._use_segment
    sweeps = _monotone_sweep(bst, 0, 8, 60, nf, rng)
    diffs = np.diff(sweeps, axis=1)
    assert diffs.min() >= -1e-10, \
        f"monotone violation: min step {diffs.min()}"
    # and the unconstrained model DOES violate (the test can detect)
    params.pop("monotone_constraints")
    bst_free = lgb.train(params, lgb.Dataset(X, y), 25, verbose_eval=False)
    sweeps_free = _monotone_sweep(bst_free, 0, 8, 60, nf, rng)
    assert np.diff(sweeps_free, axis=1).min() < -1e-6


def test_cegb_split_penalty_shrinks_trees(rng):
    n, nf = 2000, 5
    X = rng.normal(size=(n, nf))
    y = X[:, 0] + 0.5 * np.sin(X[:, 1]) + rng.normal(size=n) * 0.2
    base = {"objective": "regression", "verbose": -1, "num_leaves": 63,
            "min_data_in_leaf": 5}
    b0 = lgb.train(dict(base), lgb.Dataset(X, y), 5, verbose_eval=False)
    b1 = lgb.train(dict(base, cegb_penalty_split=0.01),
                   lgb.Dataset(X, y), 5, verbose_eval=False)
    leaves0 = sum(t.num_leaves for t in b0.gbdt.models)
    leaves1 = sum(t.num_leaves for t in b1.gbdt.models)
    assert leaves1 < leaves0


def test_cegb_coupled_penalty_discourages_new_features(rng):
    n, nf = 2000, 6
    X = rng.normal(size=(n, nf))
    # every feature mildly useful
    y = X.sum(axis=1) * 0.3 + rng.normal(size=n) * 0.1
    base = {"objective": "regression", "verbose": -1, "num_leaves": 31,
            "min_data_in_leaf": 5}
    b0 = lgb.train(dict(base), lgb.Dataset(X, y), 8, verbose_eval=False)
    b1 = lgb.train(dict(base,
                        cegb_penalty_feature_coupled=[100.0] * nf),
                   lgb.Dataset(X, y), 8, verbose_eval=False)
    used0 = (b0.feature_importance() > 0).sum()
    used1 = (b1.feature_importance() > 0).sum()
    assert used0 == nf         # unpenalized model buys every feature
    assert 0 < used1 < nf      # the penalty kept some features out


def test_cegb_lazy_penalty_reuses_feature_rows(rng):
    n, nf = 1500, 5
    X = rng.normal(size=(n, nf))
    y = X.sum(axis=1) * 0.3 + rng.normal(size=n) * 0.1
    base = {"objective": "regression", "verbose": -1, "num_leaves": 31,
            "min_data_in_leaf": 5, "tpu_tree_impl": "fused"}
    b1 = lgb.train(dict(base, cegb_penalty_feature_lazy=[0.05] * nf),
                   lgb.Dataset(X, y), 5, verbose_eval=False)
    b0 = lgb.train(dict(base), lgb.Dataset(X, y), 5, verbose_eval=False)
    used0 = (b0.feature_importance() > 0).sum()
    used1 = (b1.feature_importance() > 0).sum()
    assert used1 <= used0
    # training still learns something
    mse = float(np.mean((b1.predict(X) - y) ** 2))
    assert mse < y.var()


def test_forced_splits(rng, tmp_path):
    n, nf = 1200, 4
    X = rng.normal(size=(n, nf))
    y = X[:, 0] * 2 + X[:, 1] + rng.normal(size=n) * 0.1
    fs = {"feature": 3, "threshold": 0.5,
          "left": {"feature": 2, "threshold": -0.25}}
    path = tmp_path / "forced.json"
    path.write_text(json.dumps(fs))
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5,
                     "forcedsplits_filename": str(path)},
                    lgb.Dataset(X, y), 3, verbose_eval=False)
    for tree in bst.gbdt.models:
        # node 0 is the root: forced to feature 3 near threshold 0.5
        assert tree.split_feature[0] == 3
        assert abs(tree.threshold[0] - 0.5) < 0.2
        # second split (node 1) forced on feature 2 (left child of root)
        assert tree.split_feature[1] == 2
        assert abs(tree.threshold[1] + 0.25) < 0.2
    # the model still fits
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < y.var()


def test_prediction_early_stop_binary(rng):
    n, nf = 3000, 5
    X = rng.normal(size=(n, nf))
    y = (X[:, 0] * 3 + X[:, 1] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5},
                    lgb.Dataset(X, y), 40, verbose_eval=False)
    full = bst.predict(X)
    bst.gbdt.config.pred_early_stop = True
    bst.gbdt.config.pred_early_stop_freq = 5
    bst.gbdt.config.pred_early_stop_margin = 4.0
    es = bst.predict(X)
    # decisions unchanged, confident rows allowed to deviate in magnitude
    assert np.all((full > 0.5) == (es > 0.5))
    assert np.abs(full - es).max() < 0.12    # margin 4 => p near 0/1
    # some rows actually stopped early (outputs differ)
    assert np.any(full != es)
