"""Distributed bin finding (dataset_loader.cpp:933-1034): each rank fits
BinMappers for its modulo feature stripe, the serialized mappers are
allgathered and merged.  Faked in-process via the injected-collective seam
(network.init_with_functions, the LGBM_NetworkInitWithFunctions
equivalent)."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.core.binning import BinMapper
from lightgbm_tpu.core.dataset import TpuDataset
from lightgbm_tpu.parallel import network


class _NeedOtherRank(Exception):
    pass


def test_feature_sharded_binning_matches_serial(rng, monkeypatch):
    X = rng.normal(size=(3000, 10))
    X[:, 3] = (X[:, 3] > 0.5)          # a sparse-ish column
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = Config(objective="binary", verbosity=-1)

    serial = TpuDataset.from_numpy(X, y, config=cfg)

    calls = []
    orig = BinMapper.find_bin

    def counting(self, *a, **k):
        calls.append(1)
        return orig(self, *a, **k)

    monkeypatch.setattr(BinMapper, "find_bin", counting)

    store = {}

    def run_rank(rank):
        def ag(blob):
            store[rank] = blob
            if len(store) < 2:
                raise _NeedOtherRank
            return [store[0], store[1]]
        network.init_with_functions(lambda *a: None, ag, rank=rank,
                                    num_machines=2)
        try:
            return TpuDataset.from_numpy(X, y, config=cfg)
        finally:
            network.dispose()

    # rank 1 first: fits only its stripe, stops at the allgather
    calls.clear()
    with pytest.raises(_NeedOtherRank):
        run_rank(1)
    assert len(calls) == 5              # 10 features / 2 ranks

    # rank 0 completes with both blobs present
    calls.clear()
    ds = run_rank(0)
    assert len(calls) == 5

    # merged mappers and the quantized matrix match the serial build
    for ms, md in zip(serial.bin_mappers, ds.bin_mappers):
        assert ms.num_bin == md.num_bin
        np.testing.assert_allclose(ms.bin_upper_bound, md.bin_upper_bound)
        assert ms.default_bin == md.default_bin
    np.testing.assert_array_equal(serial.binned, ds.binned)
