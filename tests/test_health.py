"""Run-health stream tests (PR 6 tentpole): JSONL schema and record
counts at chunk sizes 1 and 4, grad-stat bit-equality between chunked
and unchunked runs, kill+resume stream contiguity, SIGTERM flush, the
stats() v3 surface, and the tools that consume the stream
(run_monitor, trace_report health digest, bench_gate).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import Application
from lightgbm_tpu.utils.faults import ENV_FAULTS, FAULTS, InjectedFault
from lightgbm_tpu.utils.telemetry import (HEALTH, HEALTH_ENV,
                                          HEALTH_SCHEMA, METRICS_SCHEMA,
                                          TELEMETRY)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import bench_gate  # noqa: E402
import run_monitor  # noqa: E402
import trace_report  # noqa: E402

PARAMS = {"objective": "regression", "num_leaves": 7, "verbose": -1,
          "min_data_in_leaf": 5, "seed": 7}


@pytest.fixture(autouse=True)
def _clean():
    TELEMETRY.reset()
    yield
    os.environ.pop(ENV_FAULTS, None)
    FAULTS.configure()
    HEALTH.reset()


def _make_data(rng, n=240):
    X = rng.rand(n, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.rand(n)
    return X, y


def _records(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def _train_stream(tmp_path, rng, chunk, rounds=6, name="run"):
    path = str(tmp_path / f"{name}.health.jsonl")
    X, y = _make_data(rng)
    params = dict(PARAMS, tpu_boost_chunk=chunk, health_out=path)
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=rounds)
    return bst, _records(path), path


# ------------------------------------------------------------- the stream
@pytest.mark.parametrize("chunk", [1, 4])
def test_stream_schema_and_counts(tmp_path, rng, chunk):
    rounds = 6
    bst, recs, _ = _train_stream(tmp_path, rng, chunk, rounds)
    assert recs[0]["kind"] == "start"
    assert recs[0]["schema"] == HEALTH_SCHEMA
    assert recs[0]["num_iterations"] == rounds
    assert recs[-1]["kind"] == "summary"
    assert recs[-1]["aborted"] is False
    assert recs[-1]["iterations"] == rounds

    iters = [r for r in recs if r["kind"] == "iter"]
    # exactly one record per boosting iteration, in order, even when the
    # device ran them as lax.scan chunks
    assert [r["iter"] for r in iters] == list(range(rounds))
    for r in iters:
        assert r["chunk"] >= 1
        for sec in ("grad", "hess"):
            stats = r[sec]
            assert set(stats) == {"min", "max", "l2", "nonfinite"}
            assert len(stats["min"]) == 1          # one tree class
            assert stats["nonfinite"] == [0]
        (tree,) = r["trees"]
        assert tree["leaves"] >= 2
        assert tree["depth"] >= 1
        assert tree["gain_sum"] >= tree["gain_max"] > 0


def test_grad_stats_bitexact_chunked_vs_unchunked(tmp_path):
    """The tentpole acceptance property: grad/hess/tree records are
    bit-identical between tpu_boost_chunk=4 and =1 because the stats are
    folded into the same device computation (same PRNG stream, same
    trees) rather than recomputed host-side."""
    seed = 1234

    def run(chunk):
        rng = np.random.RandomState(seed)
        _, recs, _ = _train_stream(tmp_path, rng, chunk,
                                   name=f"c{chunk}")
        return [{k: r[k] for k in ("iter", "trees", "grad", "hess")}
                for r in recs if r["kind"] == "iter"]

    assert run(4) == run(1)


def test_stats_v3_surface(tmp_path, rng):
    bst, _, path = _train_stream(tmp_path, rng, chunk=2)
    stats = bst.get_stats()
    assert stats["schema"] == METRICS_SCHEMA
    assert stats["version"] == 7
    assert stats["telemetry_level"] == stats["level"]
    health = stats["health"]
    assert health["schema"] == HEALTH_SCHEMA
    assert health["path"] == path
    assert health["active"] is False               # stream closed
    assert health["by_kind"]["iter"] == 6
    assert health["last_iter"]["iter"] == 5


def test_env_var_overrides_param(tmp_path, rng, monkeypatch):
    env_path = str(tmp_path / "env.health.jsonl")
    monkeypatch.setenv(HEALTH_ENV, env_path)
    X, y = _make_data(rng)
    lgb.train(dict(PARAMS, health_out=str(tmp_path / "param.jsonl")),
              lgb.Dataset(X, y), num_boost_round=2)
    assert os.path.exists(env_path)
    assert not os.path.exists(tmp_path / "param.jsonl")


# ------------------------------------------------------- CLI kill+resume
def _write_csv(path, rng, n=300):
    X = rng.rand(n, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.rand(n)
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.6f")


def _cli_argv(extra=()):
    return ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "num_iterations=8", "num_leaves=7",
            "min_data_in_leaf=5", "verbosity=-1", "snapshot_freq=2",
            "output_model=model.txt", "metrics_out=metrics.json",
            "health_out=run.health.jsonl", *extra]


@pytest.mark.parametrize("chunk", [1, 4])
def test_kill_resume_one_contiguous_stream(tmp_path, rng, monkeypatch,
                                           chunk):
    """ISSUE acceptance: a killed-and-resumed chunked run produces ONE
    contiguous health stream whose per-iteration records are
    bit-identical to an uninterrupted run's."""
    seed = rng.randint(1 << 30)
    a, b = tmp_path / "a", tmp_path / "b"
    for d in (a, b):
        d.mkdir()
        _write_csv(d / "train.csv", np.random.RandomState(seed))
    argv = _cli_argv([f"tpu_boost_chunk={chunk}"])

    monkeypatch.chdir(a)
    Application(argv).run()                   # uninterrupted reference
    ref = _records(a / "run.health.jsonl")

    monkeypatch.chdir(b)
    monkeypatch.setenv(ENV_FAULTS, "train/kill@4")
    FAULTS.configure()
    with pytest.raises(InjectedFault):
        Application(argv).run()
    killed = _records(b / "run.health.jsonl")
    assert killed[-1]["kind"] == "summary"
    assert killed[-1]["aborted"] is True      # abort still flushed

    monkeypatch.delenv(ENV_FAULTS)
    FAULTS.configure()
    Application(argv + ["resume=true"]).run()
    assert (b / "model.txt").read_bytes() == (a / "model.txt").read_bytes()

    recs = _records(b / "run.health.jsonl")
    resumes = [r for r in recs if r["kind"] == "resume"]
    assert len(resumes) == 1                  # one stream, one resume

    def iter_view(records):
        out = {}
        for r in records:
            if r["kind"] == "iter":           # resume overwrite wins
                out[r["iter"]] = {k: r[k] for k in
                                  ("iter", "trees", "grad", "hess")}
        return out

    resumed = iter_view(recs)
    assert sorted(resumed) == list(range(8))  # contiguous, no gaps
    assert resumed == iter_view(ref)          # bit-identical content
    # exactly one record per iteration survives compaction
    assert len([r for r in recs if r["kind"] == "iter"]) == 8
    assert len([r for r in recs if r["kind"] == "summary"]) == 1
    assert recs[-1]["aborted"] is False


# ------------------------------------------------- in-scan eval records
def test_eval_records_carry_in_scan_flag(tmp_path, rng):
    """PR 7: eval records say which path produced them — in_scan: true
    when the scan body computed the metric on device, false on the
    legacy per-iteration host path (here forced by a custom feval)."""
    X, y = _make_data(rng)
    Xv, yv = _make_data(rng, n=120)
    path = str(tmp_path / "inscan.health.jsonl")
    params = dict(PARAMS, tpu_boost_chunk=4, health_out=path)
    lgb.train(params, lgb.Dataset(X, y), num_boost_round=6,
              valid_sets=[lgb.Dataset(Xv, yv)], valid_names=["v"],
              verbose_eval=False)
    evals = [r for r in _records(path) if r["kind"] == "eval"]
    assert [r["iter"] for r in evals] == list(range(6))
    assert all(r["in_scan"] is True for r in evals)
    assert all(set(r["metrics"]) == {"v/l2"} for r in evals)

    def fv(preds, ds):
        return "c", float(np.mean((preds - ds.get_label()) ** 2)), False

    path2 = str(tmp_path / "legacy.health.jsonl")
    lgb.train(dict(params, health_out=path2), lgb.Dataset(X, y),
              num_boost_round=6, valid_sets=[lgb.Dataset(Xv, yv)],
              valid_names=["v"], verbose_eval=False, feval=fv)
    evals = [r for r in _records(path2) if r["kind"] == "eval"]
    assert [r["iter"] for r in evals] == list(range(6))
    assert all(r["in_scan"] is False for r in evals)
    assert all(set(r["metrics"]) == {"v/l2", "v/c"} for r in evals)


def test_kill_resume_eval_cadence_with_valid_set(tmp_path, rng,
                                                 monkeypatch):
    """With a valid set attached (in-scan eval keeps chunk=4), a
    killed-and-resumed run still yields exactly ONE eval record per
    cadence point — no duplicates, no gaps — after stream compaction.
    Values are asserted by cadence, not cross-resume bit-equality: the
    resumed f32 valid-score carry is re-uploaded from the host f64
    sidecar, which can differ in the last bit mid-stream."""
    seed = rng.randint(1 << 30)
    a, b = tmp_path / "a", tmp_path / "b"
    for d in (a, b):
        d.mkdir()
        _write_csv(d / "train.csv", np.random.RandomState(seed))
        _write_csv(d / "valid.csv", np.random.RandomState(seed + 1),
                   n=120)
    argv = _cli_argv(["tpu_boost_chunk=4", "valid=valid.csv",
                      "metric=l2", "metric_freq=1"])

    def eval_view(records):
        evals = [r for r in records if r["kind"] == "eval"]
        assert all(r["in_scan"] is True for r in evals)
        assert all(set(r["metrics"]) == {"valid_1/l2"} for r in evals)
        return [r["iter"] for r in evals]

    monkeypatch.chdir(a)
    Application(argv).run()                   # uninterrupted reference
    assert eval_view(_records(a / "run.health.jsonl")) == list(range(8))

    monkeypatch.chdir(b)
    monkeypatch.setenv(ENV_FAULTS, "train/kill@4")
    FAULTS.configure()
    with pytest.raises(InjectedFault):
        Application(argv).run()
    monkeypatch.delenv(ENV_FAULTS)
    FAULTS.configure()
    Application(argv + ["resume=true"]).run()

    iters = eval_view(_records(b / "run.health.jsonl"))
    assert sorted(iters) == list(range(8))    # no gaps...
    assert len(iters) == len(set(iters))      # ...and no duplicates
    # the trees themselves resume bit-exactly (the f32 eval carry is
    # observability, not model state)
    assert (b / "model.txt").read_bytes() == (a / "model.txt").read_bytes()


def test_compile_cache_second_run_hits(tmp_path, rng, monkeypatch):
    """compile_cache= knob: the second same-config run warm-starts from
    the persistent XLA cache and the metrics blob shows the hits."""
    jax = pytest.importorskip("jax")
    d = tmp_path / "run"
    d.mkdir()
    _write_csv(d / "train.csv", rng)
    argv = _cli_argv([f"compile_cache={tmp_path / 'cc'}"])
    monkeypatch.chdir(d)
    prev = (jax.config.jax_compilation_cache_dir,
            jax.config.jax_persistent_cache_min_compile_time_secs,
            jax.config.jax_persistent_cache_min_entry_size_bytes)
    try:
        jax.clear_caches()                    # force real compiles
        Application(argv).run()
        blob1 = json.loads((d / "metrics.json").read_text())
        TELEMETRY.reset()
        HEALTH.reset()
        jax.clear_caches()
        Application(argv).run()
        blob2 = json.loads((d / "metrics.json").read_text())
    finally:
        jax.config.update("jax_compilation_cache_dir", prev[0])
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev[1])
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          prev[2])
    assert blob1["counters"].get("compile/cache_misses", 0) > 0
    assert blob2["counters"].get("compile/cache_hits", 0) > 0


# ------------------------------------------------------------ SIGTERM
@pytest.mark.skipif(sys.platform == "win32", reason="POSIX signals")
def test_sigterm_flushes_health_and_metrics(tmp_path, rng):
    _write_csv(tmp_path / "train.csv", rng)
    health = tmp_path / "run.health.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "task=train",
         "data=train.csv", "label_column=0", "objective=regression",
         "num_iterations=100000", "num_leaves=7", "min_data_in_leaf=5",
         "verbosity=-1", "output_model=model.txt",
         "metrics_out=metrics.json", "health_out=run.health.jsonl"],
        cwd=tmp_path, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if health.exists() and any(
                    r["kind"] == "iter" for r in _records(health)):
                break
            if proc.poll() is not None:
                pytest.fail(f"run exited early rc={proc.returncode}")
            time.sleep(0.25)
        else:
            pytest.fail("no iter record before deadline")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert rc == 128 + signal.SIGTERM
    recs = _records(health)
    assert recs[-1]["kind"] == "summary"      # stream flushed on the way
    assert recs[-1]["aborted"] is True        # out, not torn mid-record
    blob = json.loads((tmp_path / "metrics.json").read_text())
    assert blob["version"] == 7
    assert (tmp_path / "model.txt.partial").exists()


# ----------------------------------------------------------- consumers
def test_run_monitor_posthoc(tmp_path, rng, capsys):
    _, recs, path = _train_stream(tmp_path, rng, chunk=4)
    assert run_monitor.main([path]) == 0
    out = capsys.readouterr().out
    assert "[finished]" in out
    assert "6/6 (100%)" in out
    assert "grad@5" in out
    assert run_monitor.main([str(tmp_path / "nope.jsonl")]) == 2


def test_run_monitor_follow_live(tmp_path):
    """--follow tails a growing stream and exits 0 once the summary
    record lands — the 'live' half of the acceptance criterion."""
    path = str(tmp_path / "live.health.jsonl")

    def writer():
        with open(path, "w") as fh:
            fh.write(json.dumps({"kind": "start", "t": 0.0,
                                 "schema": HEALTH_SCHEMA,
                                 "num_iterations": 3}) + "\n")
            fh.flush()
            for i in range(3):
                time.sleep(0.15)
                fh.write(json.dumps(
                    {"kind": "iter", "iter": i, "t": 0.1 * (i + 1),
                     "chunk": 1}) + "\n")
                fh.flush()
            fh.write(json.dumps({"kind": "summary", "records": 5,
                                 "iterations": 3, "aborted": False,
                                 "t": 1.0}) + "\n")

    t = threading.Thread(target=writer)
    t.start()
    try:
        rc = run_monitor.follow(path, interval=0.05, timeout=30,
                                out=open(os.devnull, "w"))
    finally:
        t.join()
    assert rc == 0
    state = run_monitor.StreamState()
    with open(path, "rb") as fh:
        state.feed(fh.read())
    assert len(state.iters) == 3 and state.summary is not None


def test_trace_report_health_digest(tmp_path, rng):
    bst, _, path = _train_stream(tmp_path, rng, chunk=2)
    text = trace_report.summarize(bst.get_stats())
    assert f"health: 8 records -> {path}" in text
    assert "last iter 5" in text
    assert "health: n/a" in trace_report.summarize({"version": 2})


def test_bench_gate_verdicts(tmp_path):
    hist = [{"config": "c", "value": 10.0, "unit": "s",
             "quality_ok": True, "peak_hbm_bytes": 1000}
            for _ in range(4)]
    ok = dict(hist[0], value=10.5)
    bad_wall = dict(hist[0], value=20.0)
    bad_hbm = dict(hist[0], peak_hbm_bytes=9000)
    bad_quality = dict(hist[0], quality_ok=False)
    assert not bench_gate.evaluate(hist + [ok])[0]
    assert bench_gate.evaluate(hist + [bad_wall])[0]
    assert bench_gate.evaluate(hist + [bad_hbm])[0]
    assert bench_gate.evaluate(hist + [bad_quality])[0]
    # empty / first-record / null-field trajectories pass with a notice
    failures, notes = bench_gate.evaluate([])
    assert not failures and any("no history" in n for n in notes)
    assert not bench_gate.evaluate([ok])[0]

    path = tmp_path / "traj.jsonl"
    path.write_text("".join(json.dumps(r) + "\n"
                            for r in hist + [bad_wall]))
    assert bench_gate.gate(str(path), out=open(os.devnull, "w")) == 1
    path.write_text("".join(json.dumps(r) + "\n" for r in hist + [ok]))
    assert bench_gate.gate(str(path), out=open(os.devnull, "w")) == 0
    assert bench_gate.gate(str(tmp_path / "absent.jsonl"),
                           out=open(os.devnull, "w")) == 0


def test_bench_gate_self_test_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         "--self-test"], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS" in proc.stdout


# --------------------------------------------------------- fleet merge
def _write_rank_stream(dirpath, rank, world, iters, summary=False,
                       t_step=0.5, t_skew=0.0):
    """One synthetic per-rank health stream with rank/world start meta,
    the shape cli.py writes under distributed training."""
    path = os.path.join(str(dirpath), f"rank{rank}.health.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"kind": "start", "t": 0.0,
                             "schema": HEALTH_SCHEMA, "rank": rank,
                             "world": world,
                             "num_iterations": 20}) + "\n")
        for i in range(iters):
            fh.write(json.dumps({"kind": "iter", "iter": i,
                                 "t": t_step * i + t_skew,
                                 "chunk": 1}) + "\n")
        if summary:
            fh.write(json.dumps({"kind": "summary", "records": iters,
                                 "iterations": iters, "aborted": False,
                                 "t": t_step * iters}) + "\n")
    return path


def test_fleet_merge_attribution_and_ordering(tmp_path, capsys):
    """--fleet over two synthetic rank streams: both ranks attributed
    by their start meta, and the interleaved tail ordered by stream
    time across ranks."""
    _write_rank_stream(tmp_path, 0, 2, iters=6, t_skew=0.0)
    _write_rank_stream(tmp_path, 1, 2, iters=6, t_skew=0.1)
    assert run_monitor.main(["--fleet", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "2 stream(s)" in out
    assert "rank0/2" in out and "rank1/2" in out
    assert "STALL" not in out             # even pace: nobody flagged
    # the tail interleaves both ranks, sorted by stream time
    tail = [ln for ln in out.splitlines() if ln.strip().startswith("[")]
    assert any("rank0/2" in ln for ln in tail)
    assert any("rank1/2" in ln for ln in tail)
    times = [float(ln.split("[")[1].split("s]")[0]) for ln in tail]
    assert times == sorted(times)


def test_fleet_stall_flag_when_one_stream_stops(tmp_path, capsys):
    """The loud flag: one rank's stream stops appending while the rest
    of the fleet advances past it."""
    _write_rank_stream(tmp_path, 0, 3, iters=12)
    _write_rank_stream(tmp_path, 1, 3, iters=12)
    _write_rank_stream(tmp_path, 2, 3, iters=4)       # wedged rank
    states = run_monitor.load_fleet(str(tmp_path))
    stalled = run_monitor.fleet_stalled(states)
    assert [s[0] for s in stalled] == ["rank2/3"]
    assert run_monitor.main(["--fleet", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "!! STALL rank2/3" in out
    assert "lags the fleet median" in out
    # a FINISHED rank behind the median is not a stall: its summary
    # record already explains why it stopped appending
    _write_rank_stream(tmp_path, 2, 3, iters=4, summary=True)
    states = run_monitor.load_fleet(str(tmp_path))
    assert run_monitor.fleet_stalled(states) == []


def test_fleet_follow_until_all_summaries(tmp_path):
    """--fleet --follow exits 0 once every rank's summary lands, and
    labels fall back to filenames for streams without rank meta."""
    _write_rank_stream(tmp_path, 0, 2, iters=3, summary=True)

    def late_writer():
        time.sleep(0.3)
        _write_rank_stream(tmp_path, 1, 2, iters=3, summary=True)

    t = threading.Thread(target=late_writer)
    t.start()
    try:
        rc = run_monitor.follow_fleet(str(tmp_path), interval=0.05,
                                      timeout=30,
                                      out=open(os.devnull, "w"))
    finally:
        t.join()
    assert rc == 0
    # no streams at all: exit 2 after the timeout
    empty = tmp_path / "empty"
    empty.mkdir()
    assert run_monitor.follow_fleet(str(empty), interval=0.05,
                                    timeout=0.2,
                                    out=open(os.devnull, "w")) == 2
    # meta-less stream falls back to its filename as the label
    other = tmp_path / "other"
    other.mkdir()
    with open(other / "plain.health.jsonl", "w") as fh:
        fh.write(json.dumps({"kind": "iter", "iter": 0, "t": 0.1}) + "\n")
    states = run_monitor.load_fleet(str(other))
    (path, state), = states.items()
    assert run_monitor._rank_label(path, state) == "plain.health.jsonl"
