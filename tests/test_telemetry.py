"""Training telemetry subsystem (utils/telemetry.py).

Covers the four ISSUE acceptance surfaces: the Chrome trace export
schema (valid trace-event JSON with the required span names), exact
fetch-byte counters for a deterministic 2-chunk run, compaction
counters under LIGHTGBM_TPU_SEG_STATS, and the ``telemetry_level=0``
off switch (no spans, no counters, no timeline).  Plus the registry's
thread-safety / single-writer check (the reference Network keeps all
collectives on one thread; here a second writer is flagged, not
fatal), the parallel/network.py collective counters, the CLI
``metrics_out=`` path and the tools/trace_report.py digest.
"""

import json
import os
import sys
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import Application
from lightgbm_tpu.parallel import network
from lightgbm_tpu.utils.phase import GLOBAL_TIMER
from lightgbm_tpu.utils.telemetry import (METRICS_SCHEMA, TELEMETRY,
                                          TelemetryRegistry)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import trace_report  # noqa: E402


@pytest.fixture(autouse=True)
def clean_telemetry():
    """TELEMETRY is process-global: start every test from a clean window
    (reset also clears the network counters and re-reads the level)."""
    GLOBAL_TIMER.reset()
    TELEMETRY.reset()
    yield
    GLOBAL_TIMER.reset()
    TELEMETRY.reset()


def make_binary(rng, n=500, f=5):
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    return X, y


def _params(**kw):
    p = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
         "min_data_in_leaf": 5, "verbose": -1}
    p.update(kw)
    return p


# ---------------------------------------------------------------- trace


def test_trace_export_schema(rng, tmp_path, monkeypatch):
    trace_path = tmp_path / "trace.json"
    monkeypatch.setenv("LIGHTGBM_TPU_TRACE_JSON", str(trace_path))
    TELEMETRY.refresh_level()
    assert TELEMETRY.level >= 2, "TRACE_JSON must force span recording"

    X, y = make_binary(rng)
    bst = lgb.train(_params(), lgb.Dataset(X, y), num_boost_round=3)

    assert trace_path.exists(), "engine.train must export the trace"
    blob = json.loads(trace_path.read_text())
    events = blob["traceEvents"]
    assert isinstance(events, list) and events
    assert blob["otherData"]["schema"] == METRICS_SCHEMA

    span_names = set()
    for ev in events:
        assert {"name", "ph", "pid", "tid"} <= set(ev)
        assert ev["ph"] in ("X", "C", "M", "i")
        if ev["ph"] == "X":        # complete event: microsecond ts + dur
            assert {"ts", "dur", "cat"} <= set(ev)
            assert isinstance(ev["tid"], int)
            assert ev["dur"] >= 0
            span_names.add(ev["name"])
        elif ev["ph"] == "i":      # fault instant event: global scope
            assert ev["s"] == "g"
            assert ev["name"].startswith("fault/")
    assert {"boost", "grow", "fetch"} <= span_names

    # the same data is reachable through the stats API
    stats = bst.get_stats()
    assert stats["version"] == 7
    assert stats["level"] >= 2
    assert stats["spans"]["recorded"] > 0
    assert stats["spans"]["dropped"] == 0
    assert bst.train_stats["counters"] == stats["counters"]


# ------------------------------------------------------------- counters


def test_fetch_counters_exact_for_two_chunk_run(rng):
    """4 iterations at tpu_boost_chunk=2 -> exactly 2 chunk fetches, and
    the byte count matches the packed tree-buffer layout: for L leaves
    (n = L-1 internal nodes) the int32 block is 1+14n+2L words and the
    float32 block 4n+3L words (models/grower.py pack layout)."""
    L = 7
    X, y = make_binary(rng, n=600)
    bst = lgb.train(_params(num_leaves=L, tpu_boost_chunk=2),
                    lgb.Dataset(X, y), num_boost_round=4)
    stats = bst.get_stats()
    c = stats["counters"]
    assert c["transfer/fetch_calls"] == 2

    n = L - 1
    per_tree = (1 + 14 * n + 2 * L) * 4 + (4 * n + 3 * L) * 4
    assert c["transfer/fetch_bytes"] == 4 * per_tree
    assert c["transfer/h2d_bytes"] > 0

    assert stats["gauges"]["boost/chunk_size"] == 2
    timeline = stats["timeline"]
    assert sum(e["count"] for e in timeline) == 4
    # every timeline entry carries the counter deltas for its window
    assert any("transfer/fetch_bytes" in e["counters"] for e in timeline)


def test_compaction_counters_under_seg_stats(rng, monkeypatch):
    """LIGHTGBM_TPU_SEG_STATS opts into fetching the segment grower's
    device counters; the training shape crosses the compaction
    milestones (test_grower_seg.py) so at least one compaction lands in
    seg/compactions."""
    monkeypatch.setenv("LIGHTGBM_TPU_SEG_STATS", "1")
    X, y = make_binary(rng, n=800, f=8)
    bst = lgb.train(_params(num_leaves=15, tpu_tree_impl="segment",
                            tpu_histogram_backend="pallas"),
                    lgb.Dataset(X, y), num_boost_round=3)
    c = bst.get_stats()["counters"]
    assert c.get("seg/compactions", 0) >= 1
    assert c.get("seg/scanned_blocks", 0) > 0


def test_level0_adds_nothing(rng):
    X, y = make_binary(rng)
    bst = lgb.train(_params(telemetry_level=0), lgb.Dataset(X, y),
                    num_boost_round=2)
    stats = bst.get_stats()
    assert stats["level"] == 0
    assert stats["counters"] == {}
    assert stats["gauges"] == {}
    assert stats["timeline"] == []
    assert stats["spans"]["recorded"] == 0
    # v2 device-side sections record nothing at level 0 either
    assert "memory" not in stats
    assert "cost" not in stats


def test_compile_listeners_count_retraces(rng):
    X, y = make_binary(rng)
    bst = lgb.train(_params(), lgb.Dataset(X, y), num_boost_round=2)
    c = bst.get_stats()["counters"]
    # a cold 2-iteration run traces and compiles at least once
    assert c.get("compile/retraces", 0) >= 1
    assert c.get("compile/retrace_seconds", 0) > 0
    assert c.get("compile/backend_compiles", 0) >= 1


# ------------------------------------------------------- thread safety


def test_registry_thread_safety_and_writer_check(monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_TELEMETRY", "2")
    reg = TelemetryRegistry(span_capacity=64)
    nthreads, per = 8, 400

    def work():
        for _ in range(per):
            reg.counter_add("t/hits")
            with reg.span("t_span"):
                pass

    threads = [threading.Thread(target=work) for _ in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    stats = reg.stats()
    assert stats["counters"]["t/hits"] == nthreads * per
    # single-writer check: the second thread is flagged exactly once
    assert stats["counters"]["telemetry/writer_races"] == 1
    # ring buffer: all spans counted, only the last `capacity` kept
    assert stats["spans"]["recorded"] == nthreads * per
    assert stats["spans"]["kept"] == 64
    assert stats["spans"]["dropped"] == nthreads * per - 64


# -------------------------------------------------------------- network


def test_network_allgather_obj_counters():
    def fake_allgather(blob):
        return [blob, blob]

    network.init_with_functions(lambda *a: None, fake_allgather,
                                rank=0, num_machines=2)
    try:
        out = network.allgather_obj({"mapper": 7})
        # read BEFORE dispose(): teardown resets the counters
        st = network.collective_stats()
        summary = network.collective_summary()
        timer_line = GLOBAL_TIMER.summary()
        net_stats = TELEMETRY.stats()["network"]
    finally:
        network.dispose()
    assert out == [{"mapper": 7}, {"mapper": 7}]

    assert st["allgather_obj"]["calls"] == 1
    assert st["allgather_obj"]["bytes"] > 0
    assert st["allgather_obj"]["seconds"] >= 0.0

    # rendered into the phase summary line and the stats blob
    assert "allgather_obj=1x" in summary
    assert "allgather_obj=1x" in timer_line
    assert net_stats["allgather_obj"]["calls"] == 1

    # dispose() zeroed the counters so a back-to-back run starts clean
    assert network.collective_stats() == {}
    assert "allgather_obj" not in GLOBAL_TIMER.summary()
    assert network.collective_summary() == ""


def test_network_single_writer_check():
    network.record_collective("main_kind", 10, 0.001)

    def other():
        network.record_collective("other_kind", 20, 0.002)

    t = threading.Thread(target=other)
    t.start()
    t.join()
    st = network.collective_stats()
    assert st["main_kind"]["calls"] == 1
    assert st["other_kind"]["calls"] == 1   # consistent despite the race
    assert network._coll_race_warned


def test_network_disabled_at_level0(monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_TELEMETRY", "0")
    TELEMETRY.refresh_level()
    network.record_collective("nope", 100, 1.0)
    assert network.collective_stats() == {}


def test_parallel_learner_records_collectives(rng):
    X, y = make_binary(rng, n=1000, f=8)
    bst = lgb.train(_params(num_leaves=15, tree_learner="data"),
                    lgb.Dataset(X, y), num_boost_round=3)
    net = bst.get_stats()["network"]
    assert net, "data-parallel training must record collectives"
    assert sum(v["calls"] for v in net.values()) >= 3   # one per tree
    assert sum(v["bytes"] for v in net.values()) > 0    # mesh-math estimate


# ------------------------------------------------------------- surfaces


def test_cli_metrics_out(tmp_path, rng):
    X, y = make_binary(rng, n=300)
    train = tmp_path / "train.csv"
    np.savetxt(train, np.column_stack([y, X]), delimiter=",", fmt="%.6f")
    model = tmp_path / "model.txt"
    metrics = tmp_path / "metrics.json"
    Application([
        "task=train", f"data={train}", "objective=binary",
        "num_trees=2", "num_leaves=7", f"output_model={model}",
        f"metrics_out={metrics}", "verbosity=-1",
    ]).run()
    assert metrics.exists()
    blob = json.loads(metrics.read_text())
    assert blob["schema"] == METRICS_SCHEMA
    assert blob["version"] == 7
    assert blob["phases"], "the CLI run must have recorded phases"
    assert blob["cost"]["labels"], "CLI train must harvest seam costs"
    assert blob["counters"]["transfer/fetch_calls"] >= 1


def test_trace_report_summarize(rng, tmp_path, capsys):
    X, y = make_binary(rng)
    bst = lgb.train(_params(), lgb.Dataset(X, y), num_boost_round=2)
    blob = bst.get_stats()

    text = trace_report.summarize(blob)
    assert "telemetry summary" in text
    assert "phases" in text
    assert "transfers:" in text

    # also accepts a bench record wrapping the blob under "metrics"
    record = tmp_path / "bench_record.json"
    record.write_text(json.dumps({"wall": 1.0, "metrics": blob}))
    assert trace_report.main([str(record)]) == 0
    assert "telemetry summary" in capsys.readouterr().out


# ------------------------------------------------- device-side (v2)


_FAKE_MS = {"bytes_in_use": 1 << 20, "peak_bytes_in_use": 3 << 20,
            "largest_alloc_size": 1 << 19, "bytes_limit": 1 << 30}


def _fake_mem(monkeypatch, ms=None):
    """Pretend the backend reports allocator stats (the CPU backend's
    memory_stats() is None, so the real path can't be exercised here)."""
    monkeypatch.setattr(TelemetryRegistry, "_device_memory_stats",
                        lambda self: dict(ms or _FAKE_MS))


def test_cost_section_populated_on_cpu(rng):
    """The acceptance-criteria path: a plain CPU training run harvests
    Compiled.cost_analysis() at the fused jit seams and multiplies it
    out by dispatch counts."""
    X, y = make_binary(rng)
    bst = lgb.train(_params(), lgb.Dataset(X, y), num_boost_round=3)
    stats = bst.get_stats()
    assert stats["version"] == 7
    cost = stats["cost"]
    labels = cost["labels"]
    assert "boost/gradients" in labels
    assert "grow/fused_step" in labels
    g = labels["boost/gradients"]
    assert g["compiles"] >= 1
    assert g["calls"] == 3                      # one dispatch per iter
    assert g["flops"] > 0
    assert g["flops_total"] == pytest.approx(g["flops"] * g["calls"])
    assert cost["flops_total"] == pytest.approx(
        sum(e["flops_total"] for e in labels.values()))
    assert cost["window_seconds"] > 0
    assert cost["est_flops_per_s"] > 0
    # the digest renders the cost + utilization lines from the same blob
    text = trace_report.summarize(stats)
    assert "cost (" in text
    assert "utilization:" in text


def test_chunked_run_costs_the_scan(rng):
    X, y = make_binary(rng, n=600)
    bst = lgb.train(_params(tpu_boost_chunk=2), lgb.Dataset(X, y),
                    num_boost_round=4)
    labels = bst.get_stats()["cost"]["labels"]
    assert labels["boost/chunk[2]"]["calls"] == 2
    # the whole 2-iteration scan is one program: its per-call flops
    # must dwarf a single gradient pass
    assert (labels["boost/chunk[2]"]["flops"]
            > labels.get("boost/gradients", {}).get("flops", 0))


def test_memory_absent_on_cpu_without_warnings(rng):
    """CPU memory_stats() is None -> the section is cleanly absent, no
    warnings, and the probe latches off after the first miss."""
    import warnings
    X, y = make_binary(rng)
    with warnings.catch_warnings():
        warnings.simplefilter("error")          # any warning -> failure
        bst = lgb.train(_params(), lgb.Dataset(X, y), num_boost_round=2)
        stats = bst.get_stats()
    assert "memory" not in stats
    assert TELEMETRY._mem_supported is False    # latched: later samples
    TELEMETRY.sample_memory("x")                # are one attribute check
    assert "memory" not in TELEMETRY.stats()
    assert "memory: n/a" in trace_report.summarize(stats)


def test_memory_section_when_backend_reports(rng, monkeypatch):
    _fake_mem(monkeypatch)
    X, y = make_binary(rng)
    bst = lgb.train(_params(), lgb.Dataset(X, y), num_boost_round=2)
    mem = bst.get_stats()["memory"]
    assert mem["bytes_in_use"] == _FAKE_MS["bytes_in_use"]
    assert mem["peak_bytes_in_use"] == _FAKE_MS["peak_bytes_in_use"]
    assert mem["largest_alloc"] == _FAKE_MS["largest_alloc_size"]
    assert mem["bytes_limit"] == _FAKE_MS["bytes_limit"]
    # phase boundaries attributed samples (engine wraps the loop in a
    # memory_session; utils/phase.py samples at every phase exit)
    assert mem["phases"]["session"]["samples"] >= 2
    assert "grow" in mem["phases"]
    assert "sampler" not in mem          # env knob off by default
    text = trace_report.summarize(bst.get_stats())
    assert "memory: peak 3.0MB" in text
    assert "% peak" in text


def test_mem_sampler_lifecycle(monkeypatch):
    _fake_mem(monkeypatch)
    monkeypatch.setenv("LIGHTGBM_TPU_MEM_SAMPLE_MS", "2")
    import time as _time
    with TELEMETRY.memory_session():
        thread = TELEMETRY._mem_thread
        assert thread is not None and thread.is_alive()
        deadline = _time.time() + 5.0
        while (not TELEMETRY._mem_track) and _time.time() < deadline:
            _time.sleep(0.01)
    # cleanly stopped and joined on exit
    assert TELEMETRY._mem_thread is None
    assert not thread.is_alive()
    mem = TELEMETRY.stats()["memory"]
    assert mem["sampler"]["interval_ms"] == 2.0
    assert mem["sampler"]["samples"] >= 1
    # the sampler feeds a counter track into the Chrome trace
    trace = TELEMETRY.chrome_trace()
    mem_events = [e for e in trace["traceEvents"]
                  if e["name"] == "mem/bytes_in_use"]
    assert mem_events and all(e["ph"] == "C" for e in mem_events)
    assert mem_events[0]["args"]["value"] == _FAKE_MS["bytes_in_use"]


def test_sampler_never_outlives_training_on_error(rng, monkeypatch):
    """engine.train wraps the loop in memory_session(); a callback
    exception must still stop and join the sampler thread."""
    _fake_mem(monkeypatch)
    monkeypatch.setenv("LIGHTGBM_TPU_MEM_SAMPLE_MS", "2")
    X, y = make_binary(rng)

    def boom(env):
        raise RuntimeError("callback exploded")

    with pytest.raises(RuntimeError, match="callback exploded"):
        lgb.train(_params(), lgb.Dataset(X, y), num_boost_round=5,
                  callbacks=[boom])
    assert TELEMETRY._mem_thread is None
    for t in threading.enumerate():
        assert t.name != "mem-sampler"


def test_sampler_noop_without_env(monkeypatch):
    _fake_mem(monkeypatch)
    with TELEMETRY.memory_session():
        assert TELEMETRY._mem_thread is None


def test_trace_report_handles_v1_blob():
    """Older blobs lack network/timeline/memory/cost: every section must
    render as n/a, never KeyError."""
    v1 = {"version": 1, "level": 1, "mode": "dispatch",
          "phases": {"grow": {"seconds": 1.5, "count": 3}},
          "counters": {}, "gauges": {}, "timeline": [],
          "spans": {"recorded": 0, "kept": 0, "dropped": 0,
                    "capacity": 4096}}
    text = trace_report.summarize(v1)
    assert "memory: n/a" in text
    assert "cost: n/a" in text
    # a pathologically bare blob (no sections at all) still renders
    bare = trace_report.summarize({})
    assert "phases: n/a" in bare


def test_trace_report_diff(tmp_path, capsys):
    a = {"version": 2, "phases": {"grow": {"seconds": 1.0, "count": 4},
                                  "boost": {"seconds": 0.5, "count": 4}},
         "counters": {"transfer/fetch_bytes": 1000},
         "memory": {"peak_bytes_in_use": 1 << 20, "bytes_in_use": 1000,
                    "largest_alloc": 512},
         "cost": {"flops_total": 100.0, "bytes_total": 10.0,
                  "labels": {"grow/fused_step":
                             {"calls": 4, "flops_total": 100.0}}}}
    b = {"version": 2, "phases": {"grow": {"seconds": 0.8, "count": 4}},
         "counters": {"transfer/fetch_bytes": 800},
         "cost": {"flops_total": 100.0, "bytes_total": 10.0,
                  "labels": {"grow/fused_step":
                             {"calls": 4, "flops_total": 100.0}}}}
    text = trace_report.diff(a, b)
    assert "grow: 1.000s -> 0.800s" in text
    assert "-20.0%" in text
    assert "boost: 0.500s -> n/a" in text
    assert "transfer/fetch_bytes: 1000 -> 800" in text
    assert "peak_bytes_in_use: 1.0MB -> n/a" in text

    # the CLI path: --diff a.json b.json
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    pa.write_text(json.dumps(a))
    pb.write_text(json.dumps(b))
    assert trace_report.main(["--diff", str(pa), str(pb)]) == 0
    assert "metrics diff" in capsys.readouterr().out
    # diffing against a v1 blob (no memory/cost) stays n/a-tolerant
    assert "memory (bytes): n/a" in trace_report.diff(
        {"version": 1}, {"version": 1})


def test_profile_session_is_exception_safe(monkeypatch, tmp_path):
    """An exception inside the profiler window must still stop the
    trace (a leaked session poisons every later start_trace)."""
    from lightgbm_tpu.utils import phase

    started, stopped = [], []
    monkeypatch.setattr(phase, "maybe_start_profile",
                        lambda: started.append(1))
    monkeypatch.setattr(phase, "maybe_stop_profile",
                        lambda: stopped.append(1))
    with pytest.raises(RuntimeError):
        with phase.profile_session():
            raise RuntimeError("boom")
    assert started == [1] and stopped == [1]
