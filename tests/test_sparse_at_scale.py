"""Sparse-at-scale evidence (VERDICT r4 item 9).

The declared design: wide-sparse input is ingested host-side from
CSR/CSC WITHOUT densifying (core/dataset.py:253-277), EFB bundles
exclusive features into dense columns (core/bundle.py, the reference's
Dataset::FindGroups path, src/io/dataset.cpp:68-138), and only the
bundled [G, Npad] matrix ever exists in full — so memory scales with
bundles, not features.  This file pins that contract at 100k+ features,
and documents the failure mode when bundling cannot compress.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb

sp = pytest.importorskip("scipy.sparse")


def _block_onehot(rng, n, blocks, width):
    """One nonzero per (row, block): the EFB-ideal exclusive profile of
    one-hot encoded categoricals (the workload EFB was designed for)."""
    F = blocks * width
    cols = (np.arange(blocks) * width
            + rng.randint(0, width, size=(n, blocks))).ravel()
    rows = np.repeat(np.arange(n), blocks)
    vals = rng.uniform(1.0, 2.0, size=n * blocks)
    return sp.csr_matrix((vals, (rows, cols)), shape=(n, F))


def test_efb_100k_features_under_memory_bound(rng):
    """100k features at 0.5% density train end-to-end, with the bundled
    device matrix bounded by BUNDLES, not features.  Group count is set
    by the 255-bins-per-group cap of u8 bin storage (core/bundle.py
    MAX_BINS_PER_GROUP, = the reference's offset-packed u8 bins): ~15
    bins/feature at max_bin=15 packs ~16 features/group, so ~6k groups
    — a 17x compression over the naive n*F = 1 GB dense binned
    matrix, which must stay under 80 MB here."""
    n, blocks, width = 10_000, 500, 200          # F = 100,000; d = 0.5%
    X = _block_onehot(rng, n, blocks, width)
    assert X.shape == (n, 100_000)
    y = np.asarray(
        X[:, :width].sum(axis=1) - X[:, width:2 * width].sum(axis=1)
    ).ravel()
    yb = (y > np.median(y)).astype(float)

    ds = lgb.Dataset(X, yb, params={"verbose": -1, "max_bin": 15,
                                    "min_data_in_leaf": 5})
    ds.construct()
    h = ds._handle
    assert h.bundle is not None, "EFB did not engage on 0.5% density"
    G = len(h.bundle.groups)
    assert G <= 6500, f"bundling barely compressed: {G} groups"
    assert h.binned.nbytes <= 80 * 1024 * 1024, h.binned.nbytes
    # and the model actually learns through the bundled representation
    # (tiny budget: full-N histograms over ~6k bundled columns are CPU
    # work here; the claim under test is memory + correctness, not
    # wall-clock)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 7, "max_bin": 15,
                     "min_data_in_leaf": 5}, ds,
                    num_boost_round=4, verbose_eval=False)
    p = bst.predict(X[:1000])
    ll = -np.mean(yb[:1000] * np.log(p + 1e-9)
                  + (1 - yb[:1000]) * np.log(1 - p + 1e-9))
    assert ll < 0.6915   # strictly below the 0.6931 coin-flip prior


def test_efb_incompressible_failure_mode(rng):
    """When features conflict everywhere (dense random sparsity over the
    conflict budget), bundling degenerates to singleton groups and the
    binned matrix scales with F — the DOCUMENTED failure mode: memory is
    then n*F bytes, exactly the reference's behavior when
    max_conflict_rate is exhausted (src/io/dataset.cpp:110-130).  The
    framework must still train correctly, just without compression."""
    n, F = 2000, 64
    # ~60% density: every pair of features conflicts on ~36% of rows
    mask = rng.random(size=(n, F)) < 0.6
    X = sp.csr_matrix(np.where(mask, rng.normal(size=(n, F)), 0.0))
    yb = (np.asarray(X[:, 0].todense()).ravel() > 0).astype(float)
    ds = lgb.Dataset(X, yb, params={"verbose": -1})
    ds.construct()
    h = ds._handle
    groups = h.bundle.groups if h.bundle is not None else None
    if groups is not None:
        # no multi-feature bundle should have formed
        assert max(len(g) for g in groups) <= 2
    # memory is feature-scaled now — the documented cost of no bundling
    assert h.binned.nbytes >= n * F * 0.9
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 15}, ds, num_boost_round=5,
                    verbose_eval=False)
    assert np.mean((bst.predict(X) > 0.5) == yb) > 0.9
