"""Multi-host robustness tests: the shared retry policy, the hardened
collective seam, distributed launch detection, snapshot election, and —
slow-marked — real 2-process ``jax.distributed`` runs on the CPU
backend exercising the ISSUE acceptance criteria: coordinated
preemption with bit-exact resume, and a dead host tripping the barrier
timeout with an error naming the missing rank instead of hanging.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.cli import Application
from lightgbm_tpu.parallel import distributed, network
from lightgbm_tpu.utils.faults import ENV_FAULTS, FAULTS, InjectedFault
from lightgbm_tpu.utils.log import LightGBMError
from lightgbm_tpu.utils.retry import (RetryTimeout, _deterministic_jitter,
                                      call_with_timeout, retry_call)
from lightgbm_tpu.utils.telemetry import TELEMETRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

_MARKER_VARS = (
    "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
    "SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE", "SLURM_PROCID",
    "OMPI_COMM_WORLD_RANK",
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Marker env vars and fault state must not leak between tests (or
    in from the machine running the suite)."""
    for var in _MARKER_VARS + (distributed.ENV_COORDINATOR,
                               distributed.ENV_NUM_HOSTS,
                               distributed.ENV_HOST_RANK):
        monkeypatch.delenv(var, raising=False)
    TELEMETRY.reset()
    yield
    os.environ.pop(ENV_FAULTS, None)
    FAULTS.configure()
    network._policy.update(retries=1, timeout_s=120.0, backoff_s=0.05)


def _arm(monkeypatch, spec):
    monkeypatch.setenv(ENV_FAULTS, spec)
    FAULTS.configure()


# ------------------------------------------------------------ retry policy
def test_retry_call_recovers_from_transient():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    retried = []
    out = retry_call(flaky, attempts=4, backoff_s=0.001,
                     on_retry=lambda k, e: retried.append((k, str(e))))
    assert out == "ok"
    assert len(calls) == 3
    assert [k for k, _ in retried] == [0, 1]


def test_retry_call_exhausts_and_propagates_last():
    with pytest.raises(OSError, match="always"):
        retry_call(lambda: (_ for _ in ()).throw(OSError("always")),
                   attempts=3, backoff_s=0.001)


def test_retry_call_fatal_skips_retry():
    calls = []

    def fatal():
        calls.append(1)
        raise LightGBMError("config error")

    with pytest.raises(LightGBMError):
        retry_call(fatal, attempts=5, backoff_s=0.001,
                   fatal=(LightGBMError,))
    assert len(calls) == 1               # not transient: no second try


def test_call_with_timeout():
    assert call_with_timeout(lambda: 42, None) == 42
    assert call_with_timeout(lambda: 42, 5.0) == 42
    import time as _time
    with pytest.raises(RetryTimeout, match="per-attempt limit"):
        call_with_timeout(lambda: _time.sleep(10), 0.05, label="stuck")
    # exceptions inside the timed thread re-raise in the caller
    with pytest.raises(ValueError, match="inner"):
        call_with_timeout(
            lambda: (_ for _ in ()).throw(ValueError("inner")), 5.0)


def test_jitter_is_deterministic():
    a = _deterministic_jitter("allgather_obj", 1, 0.25, 0.1)
    b = _deterministic_jitter("allgather_obj", 1, 0.25, 0.1)
    assert a == b                        # replayable: no global RNG
    assert 0.0 <= a < 0.025
    assert _deterministic_jitter("allgather_obj", 2, 0.25, 0.1) != a


# ----------------------------------------------- hardened collective seam
def test_collective_retries_configurable(monkeypatch):
    """collective_retries=3 survives three consecutive failures where
    the historical retry-once would have died."""
    from lightgbm_tpu.config import Config
    network.configure(Config.from_params({"collective_retries": "3",
                                          "collective_timeout_s": "30"}))
    _arm(monkeypatch, "collective/allgather@0x3")
    assert network.allgather_obj({"r": 0}) == [{"r": 0}]
    counts = TELEMETRY.stats()["faults"]["counts"]
    assert counts["collective_retry"] == 3


def test_collective_retries_zero_disables_retry(monkeypatch):
    from lightgbm_tpu.config import Config
    network.configure(Config.from_params({"collective_retries": "0"}))
    _arm(monkeypatch, "collective/allgather")   # single fire
    with pytest.raises(InjectedFault):
        network.allgather_obj({"r": 0})


def test_config_rejects_bad_collective_knobs():
    from lightgbm_tpu.config import Config
    with pytest.raises(ValueError, match="collective_retries"):
        Config.from_params({"collective_retries": "-1"})
    with pytest.raises(ValueError, match="collective_timeout_s"):
        Config.from_params({"collective_timeout_s": "0"})
    with pytest.raises(ValueError, match="host_rank"):
        Config.from_params({"coordinator_address": "h:1",
                            "num_hosts": "2", "host_rank": "2"})


def test_snapshot_write_retries_transient_io(tmp_path, rng, monkeypatch):
    """A single-fire snapshot/io fault is now absorbed by the shared
    retry (snapshot_retry event, snapshot still written) instead of
    costing the snapshot."""
    X = rng.rand(300, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.rand(300)
    np.savetxt(tmp_path / "train.csv", np.column_stack([y, X]),
               delimiter=",", fmt="%.6f")
    monkeypatch.chdir(tmp_path)
    _arm(monkeypatch, "snapshot/io")          # first write attempt only
    Application(["task=train", "data=train.csv", "label_column=0",
                 "objective=regression", "num_iterations=4",
                 "num_leaves=7", "min_data_in_leaf=5", "verbosity=-1",
                 "snapshot_freq=2", "output_model=model.txt",
                 "metrics_out=metrics.json"]).run()
    assert (tmp_path / "model.txt.snapshot_iter_2").exists()
    assert (tmp_path / "model.txt.snapshot_iter_4").exists()
    blob = json.loads((tmp_path / "metrics.json").read_text())
    counts = blob["faults"]["counts"]
    assert counts["snapshot_retry"] == 1
    assert "snapshot_io" not in counts        # nothing was lost


# ------------------------------------------------- mesh/dispose regression
class _FakeDev:
    def __init__(self, i, proc=0):
        self.id = i
        self.process_index = proc


def test_mesh_rebuilds_when_device_set_changes(monkeypatch):
    import jax
    network.dispose()
    monkeypatch.setattr(jax, "devices",
                        lambda *a: [_FakeDev(i) for i in range(4)])
    m1 = network.init()
    assert m1.devices.size == 4
    assert network.mesh() is m1              # unchanged world: cached
    # a fresh jax.distributed world after dispose(): different device
    # identity/order — mesh() must rebuild, not reuse stale ordering
    monkeypatch.setattr(jax, "devices",
                        lambda *a: [_FakeDev(i, proc=i % 2)
                                    for i in range(8)])
    m2 = network.mesh()
    assert m2 is not m1
    assert m2.devices.size == 8              # spanned-all meshes re-span
    network.dispose()


def test_dispose_shuts_down_owned_distributed_client(monkeypatch):
    calls = []
    monkeypatch.setattr(distributed, "_state", distributed._State())
    distributed._state.owned = True
    import jax
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: calls.append(1))
    network.dispose()
    assert calls == [1]
    assert not distributed._state.owned
    # an adopted (externally initialized) world is never torn down
    network.dispose()
    assert calls == [1]


# ------------------------------------------- launch detection / binning_world
@pytest.mark.parametrize("var,val,fatal", [
    ("SLURM_JOB_NUM_NODES", "1", False),      # single node: serial is right
    ("SLURM_JOB_NUM_NODES", "2", True),
    ("SLURM_JOB_NUM_NODES", "weird", True),   # unparsable: assume multi
    ("OMPI_COMM_WORLD_SIZE", "1", False),
    ("OMPI_COMM_WORLD_SIZE", "4", True),
    ("TPU_WORKER_HOSTNAMES", "host-0", False),  # single-host pod slice
    ("TPU_WORKER_HOSTNAMES", "host-0,host-1", True),
    ("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234", True),
    ("COORDINATOR_ADDRESS", "10.0.0.1:1234", True),
    ("MEGASCALE_COORDINATOR_ADDRESS", "10.0.0.1:1234", True),
])
def test_binning_world_launch_markers(monkeypatch, var, val, fatal):
    """With the jax distributed-state API unavailable, binning_world
    must refuse to silently run serial when a multi-process launch
    marker is present — and must NOT die on single-node markers."""
    import jax._src.distributed
    monkeypatch.setattr(jax._src.distributed, "global_state", object())
    monkeypatch.setenv(var, val)
    if fatal:
        with pytest.raises(LightGBMError, match=var):
            network.binning_world()
    else:
        assert network.binning_world() == (1, 0)


def test_binning_world_no_markers_warns_serial(monkeypatch):
    import jax._src.distributed
    monkeypatch.setattr(jax._src.distributed, "global_state", object())
    assert network.binning_world() == (1, 0)


def test_detect_launch_env_and_config(monkeypatch):
    assert distributed.detect_launch(None) is None
    from lightgbm_tpu.config import Config
    cfg = Config.from_params({"coordinator_address": "10.0.0.1:9999",
                              "num_hosts": "4", "host_rank": "2"})
    assert distributed.detect_launch(cfg) == ("10.0.0.1:9999", 4, 2)
    # env fallbacks win over config (launcher-controlled)
    monkeypatch.setenv(distributed.ENV_COORDINATOR, "10.0.0.2:1111")
    monkeypatch.setenv(distributed.ENV_NUM_HOSTS, "2")
    monkeypatch.setenv(distributed.ENV_HOST_RANK, "1")
    assert distributed.detect_launch(cfg) == ("10.0.0.2:1111", 2, 1)


def test_detect_launch_infers_rank_from_slurm(monkeypatch):
    from lightgbm_tpu.config import Config
    cfg = Config.from_params({"coordinator_address": "10.0.0.1:9999"})
    monkeypatch.setenv("SLURM_JOB_NUM_NODES", "2")
    monkeypatch.setenv("SLURM_PROCID", "1")
    assert distributed.detect_launch(cfg) == ("10.0.0.1:9999", 2, 1)


def test_detect_launch_partial_spec_is_actionable():
    from lightgbm_tpu.config import Config
    cfg = Config.from_params({"coordinator_address": "10.0.0.1:9999"})
    with pytest.raises(LightGBMError, match="num_hosts"):
        distributed.detect_launch(cfg)


# ----------------------------------------------------- election / barrier
def test_elect_common_iteration():
    elect = distributed.elect_common_iteration
    assert elect([[2, 4, 6], [4, 6], [2, 4]]) == 4
    assert elect([[2, 4], [6]]) == 0          # nothing shared
    assert elect([[], [2]]) == 0
    assert elect([]) == 0


def test_local_snapshot_manifest_requires_sidecar(tmp_path):
    model = str(tmp_path / "m.txt")
    for it in (2, 4, 6):
        (tmp_path / f"m.txt.snapshot_iter_{it}").write_text("x")
        if it != 6:                           # 6 is torn: model, no state
            (tmp_path / f"m.txt.snapshot_iter_{it}.state.npz").write_bytes(
                b"x")
    assert distributed.local_snapshot_manifest(model) == [2, 4]


def test_single_process_noops():
    assert not distributed.is_active()
    assert distributed.barrier("anything") == 0.0
    assert distributed.negotiate_preempt_target(7) == 7
    path, it = distributed.elect_snapshot("/nonexistent/m.txt")
    assert path is None and it == 0


# ---------------------------------------------- 2-process acceptance (slow)
def _write_csv(path, seed, n=300):
    r = np.random.RandomState(seed)
    X = r.rand(n, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * r.rand(n)
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.6f")


def _fleet_argv(extra=()):
    # relative paths + per-rank cwd: identical argv across runs keeps
    # the saved model byte-comparable (parameters section included)
    return [sys.executable, "-m", "lightgbm_tpu", "task=train",
            "data=train.csv", "label_column=0", "objective=regression",
            "num_iterations=8", "num_leaves=7", "min_data_in_leaf=5",
            "verbosity=1", "snapshot_freq=2", "tpu_boost_chunk=1",
            "seed=7", "collective_timeout_s=60",
            "output_model=model.txt", "metrics_out=metrics.json",
            "health_out=health.jsonl", *extra]


def _run_fleet(dirs, argvs, timeout_s=240.0):
    from launch_multihost import launch
    logs = [open(os.path.join(d, "run.log"), "a") for d in dirs]
    try:
        run = launch(argvs, cwds=[str(d) for d in dirs], stdouts=logs)
        return run.wait(timeout_s=timeout_s)
    finally:
        for fh in logs:
            fh.close()


@pytest.mark.slow
def test_preempt_and_resume_bitexact_across_hosts(tmp_path):
    """ISSUE acceptance: dist/preempt on one host drains BOTH hosts to
    one synchronized snapshot (exit 75); restarting with resume=true
    elects that snapshot on both hosts and the final models are
    byte-identical to an uninterrupted 2-host run."""
    seed = 1234
    dirs = {}
    for run_name in ("a", "b"):
        for r in (0, 1):
            d = tmp_path / f"{run_name}{r}"
            d.mkdir()
            _write_csv(d / "train.csv", seed)
            dirs[run_name, r] = d

    # uninterrupted reference fleet
    codes = _run_fleet([dirs["a", 0], dirs["a", 1]],
                       [_fleet_argv(), _fleet_argv()])
    assert codes == [0, 0]

    # rank 0 is preempted at iteration 3: both ranks must drain to the
    # same agreed iteration, snapshot, and leave with the preempt code
    codes = _run_fleet(
        [dirs["b", 0], dirs["b", 1]],
        [_fleet_argv(["fault_injection=dist/preempt@3"]), _fleet_argv()])
    assert codes == [distributed.PREEMPT_EXIT_CODE,
                     distributed.PREEMPT_EXIT_CODE]
    for r in (0, 1):
        assert not (dirs["b", r] / "model.txt").exists()

    # both hosts must hold a common snapshot generation; the restart
    # elects it, resumes, and finishes bit-exactly
    codes = _run_fleet(
        [dirs["b", 0], dirs["b", 1]],
        [_fleet_argv(["resume=true"]), _fleet_argv(["resume=true"])])
    assert codes == [0, 0]
    for r in (0, 1):
        log = (dirs["b", r] / "run.log").read_text()
        assert "elected snapshot iteration" in log
        assert ((dirs["b", r] / "model.txt").read_bytes()
                == (dirs["a", r] / "model.txt").read_bytes())


@pytest.mark.slow
def test_dead_host_trips_barrier_timeout_naming_rank(tmp_path):
    """ISSUE acceptance: a permanently-dead host surfaces as a barrier
    timeout naming the missing rank — an actionable error, not a hang."""
    dirs = []
    for r in (0, 1):
        d = tmp_path / f"d{r}"
        d.mkdir()
        _write_csv(d / "train.csv", 99)
        dirs.append(d)
    # rank 1 dies at iteration 3 (train/kill); rank 0's next snapshot
    # barrier must expire within collective_timeout_s naming rank 1
    codes = _run_fleet(
        dirs,
        [_fleet_argv(["collective_timeout_s=10"]),
         _fleet_argv(["collective_timeout_s=10",
                      "fault_injection=train/kill@3"])])
    assert codes[0] != 0 and codes[1] != 0
    log0 = (dirs[0] / "run.log").read_text()
    assert "missing rank(s) [1]" in log0
    assert "barrier 'snapshot' timed out" in log0


@pytest.mark.slow
def test_launch_multihost_cli(tmp_path):
    """The tool's CLI mode: {rank} substitution + per-rank env."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "launch_multihost.py"),
         "--hosts", "2", "--",
         sys.executable, "-c",
         "import os; print('R', os.environ['LIGHTGBM_TPU_HOST_RANK'], "
         "'{rank}')"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert "rank 0: exit 0" in out.stdout
    assert "rank 1: exit 0" in out.stdout
