"""Missing-value and categorical handling at the REFERENCE suite's own
crafted configs (tests/python_package_test/test_engine.py:103-296): tiny
hand-built datasets where correct missing routing / categorical splits
must reach near-perfect fit in one or twenty rounds.  These pin the
missing_type machinery (MISSING_NAN / MISSING_ZERO / use_missing=false)
and one-hot categorical splits functionally, far tighter than the
statistical engine gates.
"""

import numpy as np

import lightgbm_tpu as lgb


def _auc(y, p):
    order = np.argsort(p, kind="stable")
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    uniq, inv, cnt = np.unique(p, return_inverse=True, return_counts=True)
    rs = np.zeros(len(uniq))
    np.add.at(rs, inv, ranks)
    ranks = (rs / cnt)[inv]
    pos = float(np.sum(y))
    neg = len(y) - pos
    return (ranks[y > 0.5].sum() - pos * (pos + 1) / 2) / max(pos * neg, 1)


def test_missing_value_handle(rng):
    """reference :103-126 — all-zero feature with NaN marking the
    positives: 20 rounds must reach l2 < 0.005."""
    X = np.zeros((1000, 1))
    y = np.zeros(1000)
    trues = rng.choice(1000, size=200, replace=False)
    X[trues, 0] = np.nan
    y[trues] = 1
    bst = lgb.train({"metric": "l2", "verbose": -1,
                     "boost_from_average": False},
                    lgb.Dataset(X, y), num_boost_round=20,
                    verbose_eval=False)
    ret = float(np.mean((bst.predict(X) - y) ** 2))
    assert ret < 0.005, ret


def test_missing_value_handle_na():
    """reference :128-158 — NaN joins the positive side in ONE round."""
    x = np.array([0, 1, 2, 3, 4, 5, 6, 7, np.nan]).reshape(-1, 1)
    y = np.array([1, 1, 1, 1, 0, 0, 0, 0, 1.0])
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "boost_from_average": False, "min_data": 1,
                     "num_leaves": 2, "learning_rate": 1,
                     "min_data_in_bin": 1, "zero_as_missing": False},
                    lgb.Dataset(x, y), num_boost_round=1,
                    verbose_eval=False)
    pred = bst.predict(x)
    np.testing.assert_allclose(pred, y)
    assert _auc(y, pred) > 0.999


def test_missing_value_handle_zero():
    """reference :160-190 — zero_as_missing: 0 AND NaN route together."""
    x = np.array([0, 1, 2, 3, 4, 5, 6, 7, np.nan]).reshape(-1, 1)
    y = np.array([0, 1, 1, 1, 0, 0, 0, 0, 0.0])
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "boost_from_average": False, "min_data": 1,
                     "num_leaves": 2, "learning_rate": 1,
                     "min_data_in_bin": 1, "zero_as_missing": True},
                    lgb.Dataset(x, y), num_boost_round=1,
                    verbose_eval=False)
    pred = bst.predict(x)
    np.testing.assert_allclose(pred, y)
    assert _auc(y, pred) > 0.999


def test_missing_value_handle_none():
    """reference :192-224 — use_missing=false: NaN quantizes like 0, so
    rows 0 and NaN must predict identically and AUC only reaches ~0.83."""
    x = np.array([0, 1, 2, 3, 4, 5, 6, 7, np.nan]).reshape(-1, 1)
    y = np.array([0, 1, 1, 1, 0, 0, 0, 0, 0.0])
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "boost_from_average": False, "min_data": 1,
                     "num_leaves": 2, "learning_rate": 1,
                     "min_data_in_bin": 1, "use_missing": False},
                    lgb.Dataset(x, y), num_boost_round=1,
                    verbose_eval=False)
    pred = bst.predict(x)
    assert pred[0] == pytest_approx(pred[1])
    assert pred[-1] == pytest_approx(pred[0])
    assert _auc(y, pred) > 0.83


def pytest_approx(v, eps=1e-5):
    import pytest
    return pytest.approx(v, abs=eps)


def test_categorical_handle():
    """reference :225-261 — 8 one-hot categories fit odd/even exactly in
    one round (max_cat_to_onehot=1 forces sorted-subset splits)."""
    x = np.arange(8, dtype=np.float64).reshape(-1, 1)
    y = np.array([0, 1, 0, 1, 0, 1, 0, 1.0])
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "boost_from_average": False, "min_data": 1,
                     "num_leaves": 2, "learning_rate": 1,
                     "min_data_in_bin": 1, "min_data_per_group": 1,
                     "cat_smooth": 1, "cat_l2": 0,
                     "max_cat_to_onehot": 1, "zero_as_missing": True,
                     "categorical_column": 0},
                    lgb.Dataset(x, y), num_boost_round=1,
                    verbose_eval=False)
    pred = bst.predict(x)
    np.testing.assert_allclose(pred, y)
    assert _auc(y, pred) > 0.999


def test_categorical_handle_na():
    """reference :262-296 — NaN as its own category."""
    x = np.array([0, np.nan, 0, np.nan, 0, np.nan]).reshape(-1, 1)
    y = np.array([0, 1, 0, 1, 0, 1.0])
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "boost_from_average": False, "min_data": 1,
                     "num_leaves": 2, "learning_rate": 1,
                     "min_data_in_bin": 1, "min_data_per_group": 1,
                     "cat_smooth": 1, "cat_l2": 0,
                     "max_cat_to_onehot": 1, "zero_as_missing": False,
                     "categorical_column": 0},
                    lgb.Dataset(x, y), num_boost_round=1,
                    verbose_eval=False)
    pred = bst.predict(x)
    np.testing.assert_allclose(pred, y)
    assert _auc(y, pred) > 0.999
