"""Constant-feature datasets must predict the objective's base rate
exactly (reference test_engine.py:992-1040): with no splittable
feature, two boosting rounds leave the model at boost_from_average's
init score, and each objective transforms it to the label mean / class
priors.  Pins BoostFromScore + the no-split early-exit path per
objective family.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _check(y_true, expected_pred, more_params):
    X = np.ones((len(y_true), 1))
    params = {"objective": "regression", "num_class": 1, "verbose": -1,
              "min_data": 1, "num_leaves": 2, "learning_rate": 1,
              "min_data_in_bin": 1, "boost_from_average": True}
    params.update(more_params)
    bst = lgb.train(params, lgb.Dataset(X, np.array(y_true),
                                        params=params),
                    num_boost_round=2, verbose_eval=False)
    pred = bst.predict(X)
    assert np.allclose(pred, expected_pred, rtol=1e-5, atol=1e-6), \
        (pred, expected_pred)


def test_constant_features_regression():
    params = {"objective": "regression"}
    _check([0.0, 10.0, 0.0, 10.0], 5.0, params)
    _check([0.0, 1.0, 2.0, 3.0], 1.5, params)
    _check([-1.0, 1.0, -2.0, 2.0], 0.0, params)


def test_constant_features_binary():
    params = {"objective": "binary"}
    _check([0.0, 10.0, 0.0, 10.0], 0.5, params)
    _check([0.0, 1.0, 2.0, 3.0], 0.75, params)


def test_constant_features_multiclass():
    params = {"objective": "multiclass", "num_class": 3}
    _check([0.0, 1.0, 2.0, 0.0], [0.5, 0.25, 0.25], params)
    _check([0.0, 1.0, 2.0, 1.0], [0.25, 0.5, 0.25], params)


def test_constant_features_multiclassova():
    params = {"objective": "multiclassova", "num_class": 3}
    _check([0.0, 1.0, 2.0, 0.0], [0.5, 0.25, 0.25], params)
    _check([0.0, 1.0, 2.0, 1.0], [0.25, 0.5, 0.25], params)


def test_continue_train_custom_eval_parity(rng, tmp_path):
    """reference :448-475 minus the retired load_boston dataset: continued
    training from a saved model with a custom feval must track the
    built-in l1 metric value exactly at every round."""
    X = rng.normal(size=(2000, 8))
    y = 3 * X[:, 0] - X[:, 1] ** 2 + 0.1 * rng.normal(size=2000)
    Xt, yt = X[1800:], y[1800:]
    params = {"objective": "regression", "metric": "l1", "verbose": -1}
    train = lgb.Dataset(X[:1800], y[:1800], free_raw_data=False)
    init = lgb.train(params, train, num_boost_round=20, verbose_eval=False)
    init.save_model(str(tmp_path / "cont_model.txt"))
    evals_result = {}

    def mae_feval(p, d):
        return "mae", float(np.mean(np.abs(p - d.get_label()))), False

    bst = lgb.train(params, train, num_boost_round=30,
                    valid_sets=[train.create_valid(Xt, yt)],
                    verbose_eval=False, feval=mae_feval,
                    evals_result=evals_result,
                    init_model=str(tmp_path / "cont_model.txt"))
    ret = float(np.mean(np.abs(bst.predict(Xt) - yt)))
    assert ret < 0.5 * float(np.mean(np.abs(yt - yt.mean())))
    assert evals_result["valid_0"]["l1"][-1] == pytest.approx(ret, abs=1e-5)
    for l1, mae in zip(evals_result["valid_0"]["l1"],
                       evals_result["valid_0"]["mae"]):
        assert l1 == pytest.approx(mae, abs=1e-5)
