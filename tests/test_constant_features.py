"""Constant-feature datasets must predict the objective's base rate
exactly (reference test_engine.py:992-1040): with no splittable
feature, two boosting rounds leave the model at boost_from_average's
init score, and each objective transforms it to the label mean / class
priors.  Pins BoostFromScore + the no-split early-exit path per
objective family.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _check(y_true, expected_pred, more_params):
    X = np.ones((len(y_true), 1))
    params = {"objective": "regression", "num_class": 1, "verbose": -1,
              "min_data": 1, "num_leaves": 2, "learning_rate": 1,
              "min_data_in_bin": 1, "boost_from_average": True}
    params.update(more_params)
    bst = lgb.train(params, lgb.Dataset(X, np.array(y_true),
                                        params=params),
                    num_boost_round=2, verbose_eval=False)
    pred = bst.predict(X)
    assert np.allclose(pred, expected_pred, rtol=1e-5, atol=1e-6), \
        (pred, expected_pred)


def test_constant_features_regression():
    params = {"objective": "regression"}
    _check([0.0, 10.0, 0.0, 10.0], 5.0, params)
    _check([0.0, 1.0, 2.0, 3.0], 1.5, params)
    _check([-1.0, 1.0, -2.0, 2.0], 0.0, params)


def test_constant_features_binary():
    params = {"objective": "binary"}
    _check([0.0, 10.0, 0.0, 10.0], 0.5, params)
    _check([0.0, 1.0, 2.0, 3.0], 0.75, params)


def test_constant_features_multiclass():
    params = {"objective": "multiclass", "num_class": 3}
    _check([0.0, 1.0, 2.0, 0.0], [0.5, 0.25, 0.25], params)
    _check([0.0, 1.0, 2.0, 1.0], [0.25, 0.5, 0.25], params)


def test_constant_features_multiclassova():
    params = {"objective": "multiclassova", "num_class": 3}
    _check([0.0, 1.0, 2.0, 0.0], [0.5, 0.25, 0.25], params)
    _check([0.0, 1.0, 2.0, 1.0], [0.25, 0.5, 0.25], params)


def test_continue_train_custom_eval_parity(rng, tmp_path):
    """reference :448-475 minus the retired load_boston dataset: continued
    training from a saved model with a custom feval must track the
    built-in l1 metric value exactly at every round."""
    X = rng.normal(size=(2000, 8))
    y = 3 * X[:, 0] - X[:, 1] ** 2 + 0.1 * rng.normal(size=2000)
    Xt, yt = X[1800:], y[1800:]
    params = {"objective": "regression", "metric": "l1", "verbose": -1}
    train = lgb.Dataset(X[:1800], y[:1800], free_raw_data=False)
    init = lgb.train(params, train, num_boost_round=20, verbose_eval=False)
    init.save_model(str(tmp_path / "cont_model.txt"))
    evals_result = {}

    def mae_feval(p, d):
        return "mae", float(np.mean(np.abs(p - d.get_label()))), False

    bst = lgb.train(params, train, num_boost_round=30,
                    valid_sets=[train.create_valid(Xt, yt)],
                    verbose_eval=False, feval=mae_feval,
                    evals_result=evals_result,
                    init_model=str(tmp_path / "cont_model.txt"))
    ret = float(np.mean(np.abs(bst.predict(Xt) - yt)))
    assert ret < 0.5 * float(np.mean(np.abs(yt - yt.mean())))
    assert evals_result["valid_0"]["l1"][-1] == pytest.approx(ret, abs=1e-5)
    for l1, mae in zip(evals_result["valid_0"]["l1"],
                       evals_result["valid_0"]["mae"]):
        assert l1 == pytest.approx(mae, abs=1e-5)


def test_max_bin_by_feature():
    """reference test_engine.py:899-920 — per-feature bin budgets decide
    which feature can express the target exactly."""
    col1 = np.arange(0, 100)[:, np.newaxis]
    col2 = np.zeros((100, 1))
    col2[20:] = 1
    X = np.concatenate([col1, col2], axis=1)
    y = np.arange(0, 100).astype(np.float64)
    params = {"objective": "regression_l2", "verbose": -1,
              "num_leaves": 100, "min_data_in_leaf": 1,
              "min_sum_hessian_in_leaf": 0, "min_data_in_bin": 1,
              "max_bin_by_feature": [100, 2]}
    est = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=1,
                    verbose_eval=False)
    assert len(np.unique(est.predict(X))) == 100
    params["max_bin_by_feature"] = [2, 100]
    est = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=1,
                    verbose_eval=False)
    assert len(np.unique(est.predict(X))) == 3


def test_small_max_bin():
    """reference test_engine.py:922-940 — max_bin=2 (and 3 with a NaN)
    must bin and train without error."""
    rng = np.random.RandomState(0)
    y = rng.choice([0, 1], 100).astype(np.float64)
    x = np.zeros((100, 1))
    x[:30, 0] = -1
    x[30:60, 0] = 1
    x[60:, 0] = 2
    params = {"objective": "binary", "seed": 0, "min_data_in_leaf": 1,
              "verbose": -1, "max_bin": 2}
    lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=5,
              verbose_eval=False)
    x[0, 0] = np.nan
    params["max_bin"] = 3
    lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=5,
              verbose_eval=False)
