"""Multi-tenant training scheduler tests (lightgbm_tpu/sched).

The load-bearing contract: a job trained under the scheduler —
arbitrarily interleaved with other tenants, preempted to disk and
rebuilt mid-run — writes a model file BYTE-identical to the same
params trained standalone.  Around it: admission control rejects an
over-budget tenant with a named event while siblings run, a fault in
one tenant's slice or preemption snapshot retries once then fails
THAT JOB ONLY, cross-tenant compile-cache hits are counted, telemetry
counter deltas attribute to the tenant whose slice moved them, and
the spec-file/CLI/monitor surfaces hold together.
"""

import json
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.sched import (POLICIES, Job, JobSpec,
                                SchedAdmissionError, Scheduler,
                                parse_spec_file, peek_data_shape,
                                run_spec_file)
from lightgbm_tpu.utils.faults import FAULTS
from lightgbm_tpu.utils.log import LightGBMError
from lightgbm_tpu.utils.telemetry import TELEMETRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _clean():
    TELEMETRY.reset()
    yield
    FAULTS.configure()


def _write_csv(path, n=240, kind="binary", seed=0):
    r = np.random.RandomState(seed)
    X = r.rand(n, 5)
    if kind == "binary":
        y = (X[:, 0] + 0.3 * r.rand(n) > 0.6).astype(int)
    else:
        y = np.digitize(X[:, 1], [0.33, 0.66])
    np.savetxt(path, np.column_stack([y, X]), delimiter=",",
               fmt="%.6f")
    return str(path)


def _params(data, out, **kw):
    p = {"data": data, "objective": "binary", "num_iterations": 8,
         "num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1,
         "output_model": out}
    p.update(kw)
    return p


def _two_jobs(tmp_path, **sched_kw):
    """A ready scheduler with two small binary tenants A and B."""
    a = _write_csv(tmp_path / "a.csv", seed=1)
    b = _write_csv(tmp_path / "b.csv", seed=2)
    sched = Scheduler(quantum_chunks=2, **sched_kw)
    ja = sched.submit(JobSpec(
        "A", _params(a, str(tmp_path / "A.txt"))))
    jb = sched.submit(JobSpec(
        "B", _params(b, str(tmp_path / "B.txt"))))
    return sched, ja, jb


# ------------------------------------------------------ byte identity
def test_scheduled_matches_standalone_bytes(tmp_path):
    """Fair-policy interleaving + a forced mid-run preemption (with
    bagging armed, so PRNG state must survive the snapshot round
    trip) produce byte-identical final models.  The standalone runs
    use the IDENTICAL param dicts — the saved ``parameters:`` section
    preserves dict order and literal paths."""
    from lightgbm_tpu.cli import Application

    a = _write_csv(tmp_path / "a.csv", n=300, seed=1)
    b = _write_csv(tmp_path / "b.csv", n=300, kind="multi", seed=2)
    out_a, out_b = str(tmp_path / "A.txt"), str(tmp_path / "B.txt")
    params_a = _params(a, out_a, num_iterations=9,
                       bagging_fraction=0.8, bagging_freq=1)
    params_b = _params(b, out_b, num_iterations=9,
                       objective="multiclass", num_class=3)

    Application([f"{k}={v}" for k, v in params_a.items()]).run()
    solo_a = open(out_a).read()
    os.remove(out_a)
    Application([f"{k}={v}" for k, v in params_b.items()]).run()
    solo_b = open(out_b).read()
    os.remove(out_b)

    sched = Scheduler(quantum_chunks=2, policy="fair")
    ja = sched.submit(JobSpec("A", params_a))
    jb = sched.submit(JobSpec("B", params_b, weight=2.0))
    for _ in range(3):
        sched.step()
    sched.preempt_job("A", reason="test")
    assert ja.state == "preempted" and ja.preemptions == 1
    summary = sched.run()

    assert ja.state == "done" and jb.state == "done"
    assert open(out_a).read() == solo_a
    assert open(out_b).read() == solo_b
    assert summary["fairness_index"] is not None
    # a finished job's preemption snapshots are superseded + deleted
    assert not [f for f in os.listdir(tmp_path) if "snapshot" in f]


# ---------------------------------------------------------- admission
def test_admission_rejects_over_budget_fourth_job(tmp_path):
    """Three small tenants time-slice to completion; a 4th whose
    pre-load working-set estimate exceeds the budget is rejected with
    a named error and a ``sched_admit`` rejected record — without
    disturbing the siblings."""
    stream = tmp_path / "sched.jsonl"
    datasets = [_write_csv(tmp_path / f"d{i}.csv", seed=i)
                for i in range(3)]
    big = _write_csv(tmp_path / "big.csv", n=6000, seed=9)
    small_est = lgb.estimate_working_set(
        _params(datasets[0], "x"), data_shape=(240, 5))
    sched = Scheduler(quantum_chunks=2, health_out=str(stream),
                      hbm_budget_bytes=int(4 * small_est))
    jobs = [sched.submit(JobSpec(
        f"j{i}", _params(d, str(tmp_path / f"m{i}.txt"))))
        for i, d in enumerate(datasets)]
    with pytest.raises(SchedAdmissionError, match="big"):
        sched.submit(JobSpec(
            "big", _params(big, str(tmp_path / "big.txt"))))
    out = sched.run()
    assert out["done"] == 3 and out["failed"] == 0
    assert all(j.state == "done" for j in jobs)
    admits = [json.loads(ln) for ln in open(stream)
              if json.loads(ln)["kind"] == "sched_admit"]
    rejected = [r for r in admits if r["decision"] == "rejected"]
    assert len(rejected) == 1 and rejected[0]["job"] == "big"
    assert rejected[0]["estimate_bytes"] > 4 * small_est
    counters = TELEMETRY.stats()["counters"]
    assert counters.get("sched/admit_rejected") == 1


def test_residency_cap_queues_then_preempts(tmp_path):
    """max_jobs=1: the second tenant is queued at submit, and slicing
    it preempts the first to a byte-exact snapshot; both finish."""
    stream = tmp_path / "sched.jsonl"
    sched, ja, jb = _two_jobs(tmp_path, max_jobs=1,
                              health_out=str(stream))
    out = sched.run()
    assert ja.state == "done" and jb.state == "done"
    assert ja.preemptions + jb.preemptions >= 1
    admits = [json.loads(ln) for ln in open(stream)
              if json.loads(ln)["kind"] == "sched_admit"]
    assert [r["decision"] for r in admits] == ["admitted", "queued"]
    preempts = [json.loads(ln) for ln in open(stream)
                if json.loads(ln)["kind"] == "sched_preempt_job"]
    assert preempts and all(r["snapshot"] for r in preempts)
    assert out["done"] == 2
    # preemption snapshots were cleaned up after completion
    assert not [f for f in os.listdir(tmp_path) if "snapshot" in f]


# ----------------------------------------------- fault isolation
def test_slice_fault_retry_then_success(tmp_path):
    """One armed ``sched/slice`` fault: the slice retries once and
    every tenant still completes."""
    sched, ja, jb = _two_jobs(tmp_path, fault_spec="sched/slice@1x1")
    out = sched.run()
    assert ja.state == "done" and jb.state == "done"
    assert ja.slice_retries + jb.slice_retries == 1
    assert out["failed"] == 0
    counters = TELEMETRY.stats()["counters"]
    assert counters.get("sched/slice_retries") == 1


def test_slice_fault_fails_only_that_tenant(tmp_path):
    """An exhausted ``sched/slice`` retry fails the tenant whose
    slice hit it — the scheduler and the sibling run to completion,
    and the failure is a named ``job_done`` record."""
    stream = tmp_path / "sched.jsonl"
    sched, ja, jb = _two_jobs(tmp_path, health_out=str(stream),
                              fault_spec="sched/slice@1x2")
    out = sched.run()
    states = sorted([ja.state, jb.state])
    assert states == ["done", "failed"]
    failed = ja if ja.state == "failed" else jb
    ok = jb if failed is ja else ja
    assert "InjectedFault" in failed.error
    assert not os.path.exists(str(failed.config.output_model))
    assert os.path.exists(str(ok.config.output_model))
    assert out["done"] == 1 and out["failed"] == 1
    dones = [json.loads(ln) for ln in open(stream)
             if json.loads(ln)["kind"] == "job_done"]
    by_job = {r["job"]: r for r in dones}
    assert by_job[failed.name]["failed"] is True
    assert "InjectedFault" in by_job[failed.name]["error"]
    assert not by_job[ok.name].get("failed")


def test_snapshot_fault_fails_only_that_tenant(tmp_path):
    """An exhausted ``sched/snapshot`` retry during preemption fails
    the preempted tenant only; the sibling completes."""
    sched, ja, jb = _two_jobs(tmp_path,
                              fault_spec="sched/snapshot@0x2")
    sched.step()                       # job A trains a first slice
    sched.preempt_job("A", reason="test")
    assert ja.state == "failed" and "InjectedFault" in ja.error
    out = sched.run()
    assert jb.state == "done"
    assert out["done"] == 1 and out["failed"] == 1


def test_snapshot_fault_retry_once_succeeds(tmp_path):
    """A single armed ``sched/snapshot`` fault is absorbed by the
    retry: the preemption lands and the tenant later resumes to a
    normal finish."""
    sched, ja, jb = _two_jobs(tmp_path,
                              fault_spec="sched/snapshot@0x1")
    sched.step()
    sched.preempt_job("A", reason="test")
    assert ja.state == "preempted"
    out = sched.run()
    assert ja.state == "done" and jb.state == "done"
    assert out["failed"] == 0


# --------------------------------------------- shared compile cache
def test_cross_job_compile_cache_hits(tmp_path):
    """Two same-shaped tenants behind one persistent compile cache:
    the second job's compiles hit entries the first populated, and
    the scheduler counts them as cross-job hits."""
    cache = tmp_path / "cache"
    sched, ja, jb = _two_jobs(tmp_path, compile_cache=str(cache))
    out = sched.run()
    assert ja.state == "done" and jb.state == "done"
    assert out["cross_job_cache_hits"] >= 1
    counters = TELEMETRY.stats()["counters"]
    assert counters.get("sched/cross_job_cache_hits", 0) >= 1


# -------------------------------------------- telemetry attribution
def test_per_job_counter_attribution(tmp_path, monkeypatch):
    """Counter deltas land on the tenant whose slice moved them —
    including the SEG_STATS grower counters, which must attribute to
    the segment-impl tenant and never to the fused-impl sibling."""
    monkeypatch.setenv("LIGHTGBM_TPU_SEG_STATS", "1")
    a = _write_csv(tmp_path / "a.csv", seed=1)
    b = _write_csv(tmp_path / "b.csv", seed=2)
    sched = Scheduler(quantum_chunks=2)
    ja = sched.submit(JobSpec("seg", _params(
        a, str(tmp_path / "A.txt"), tpu_tree_impl="segment",
        tpu_histogram_backend="pallas")))
    jb = sched.submit(JobSpec("fused", _params(
        b, str(tmp_path / "B.txt"), tpu_tree_impl="fused")))
    sched.run()
    assert ja.state == "done" and jb.state == "done"
    assert ja.counters.get("seg/scanned_blocks", 0) > 0
    assert jb.counters.get("seg/scanned_blocks", 0) == 0


# ------------------------------------------------------------ policy
def test_round_robin_interleaves_in_submit_order(tmp_path):
    stream = tmp_path / "sched.jsonl"
    sched, ja, jb = _two_jobs(tmp_path, policy="round_robin",
                              health_out=str(stream))
    sched.run()
    slices = [json.loads(ln)["job"] for ln in open(stream)
              if json.loads(ln)["kind"] == "sched_slice"]
    # both jobs are the same length, so slices strictly alternate
    assert slices[:4] == ["A", "B", "A", "B"]


def test_fair_policy_feeds_the_underserved(tmp_path):
    """The fair policy picks the tenant with the least device-seconds
    per unit weight; starving one job on the accounting makes it the
    next pick."""
    sched, ja, jb = _two_jobs(tmp_path, policy="fair")
    sched.step()                        # first slice goes to A
    first = ja if ja.slices else jb
    other = jb if first is ja else ja
    # inflate the sliced job's accounted device time: the other
    # tenant is now strictly underserved and must be picked next
    first.device_s += 100.0
    sched.step()
    assert other.slices == 1
    out = sched.run()
    assert out["done"] == 2 and out["fairness_index"] is not None


def test_policy_validation():
    cfg_bad = {"sched_policy": "lottery"}
    with pytest.raises(ValueError, match="sched_policy"):
        Config.from_params(cfg_bad)
    cfg = Config.from_params({"sched_policy": "rr",
                              "sched_quantum_chunks": 2})
    assert cfg.sched_policy == "round_robin"
    cfg = Config.from_params({"sched_policy": "deficit"})
    assert cfg.sched_policy == "fair"
    assert set(POLICIES) == {"round_robin", "fair"}
    with pytest.raises(LightGBMError, match="weight"):
        JobSpec("x", {}, weight=0)


# --------------------------------------------------------- spec files
def test_spec_file_parse(tmp_path):
    _write_csv(tmp_path / "a.csv", seed=1)
    spec = tmp_path / "jobs.spec"
    spec.write_text(
        "sched_policy = fair\n"
        "sched_quantum_chunks = 3\n"
        "compile_cache = 1\n"
        "num_iterations = 5\n"
        "\n"
        "job = alpha\n"
        "data = a.csv\n"
        "objective = binary\n"
        "output_model = alpha.txt\n"
        "weight = 2\n"
        "\n"
        "job = beta\n"
        "data = /abs/b.csv\n"
        "objective = multiclass\n"
        "num_class = 3\n"
        "num_iterations = 7\n"
        "output_model = beta.txt\n")
    sched_params, jobs = parse_spec_file(str(spec))
    assert sched_params == {"sched_policy": "fair",
                            "sched_quantum_chunks": "3",
                            "compile_cache": "1"}
    assert [j.name for j in jobs] == ["alpha", "beta"]
    alpha, beta = jobs
    assert alpha.weight == 2.0 and beta.weight == 1.0
    # relative paths resolve against the spec dir; absolute pass through
    assert alpha.params["data"] == str(tmp_path / "a.csv")
    assert beta.params["data"] == "/abs/b.csv"
    # defaults inherit per job, sections override, sched knobs never leak
    assert alpha.params["num_iterations"] == "5"
    assert beta.params["num_iterations"] == "7"
    assert "sched_policy" not in alpha.params
    assert "weight" not in alpha.params


def test_spec_file_errors(tmp_path):
    empty = tmp_path / "empty.spec"
    empty.write_text("num_iterations = 5\n")
    with pytest.raises(LightGBMError, match="no 'job ='"):
        parse_spec_file(str(empty))
    dup = tmp_path / "dup.spec"
    dup.write_text("job = x\ndata = a\noutput_model = m\n"
                   "job = x\ndata = b\noutput_model = n\n")
    with pytest.raises(LightGBMError, match="duplicate job name"):
        parse_spec_file(str(dup))
    with pytest.raises(LightGBMError, match="doesn't exist"):
        parse_spec_file(str(tmp_path / "missing.spec"))


def test_run_spec_file_and_cli_entry(tmp_path):
    """``python -m lightgbm_tpu sched=jobs.spec`` trains every job of
    the spec to completion with the scheduler knobs applied."""
    from lightgbm_tpu.cli import Application

    _write_csv(tmp_path / "a.csv", seed=1)
    _write_csv(tmp_path / "b.csv", kind="multi", seed=2)
    spec = tmp_path / "jobs.spec"
    spec.write_text(
        "sched_policy = fair\n"
        "sched_quantum_chunks = 2\n"
        f"sched_health_out = {tmp_path / 'sched.jsonl'}\n"
        "num_iterations = 6\n"
        "num_leaves = 7\n"
        "min_data_in_leaf = 5\n"
        "verbosity = -1\n"
        "job = alpha\n"
        "data = a.csv\n"
        "objective = binary\n"
        "output_model = alpha.txt\n"
        "job = beta\n"
        "data = b.csv\n"
        "objective = multiclass\n"
        "num_class = 3\n"
        "output_model = beta.txt\n")
    out = run_spec_file(str(spec))
    assert out["done"] == 2 and out["failed"] == 0
    assert os.path.exists(tmp_path / "alpha.txt")
    os.remove(tmp_path / "alpha.txt")
    os.remove(tmp_path / "beta.txt")

    Application([f"sched={spec}"]).run()
    assert os.path.exists(tmp_path / "alpha.txt")
    assert os.path.exists(tmp_path / "beta.txt")
    # the stream closed with a sched_summary both times
    kinds = [json.loads(ln)["kind"]
             for ln in open(tmp_path / "sched.jsonl")]
    assert kinds.count("sched_summary") >= 1


# ------------------------------------------- estimate_working_set API
def test_estimate_working_set_public_api(tmp_path):
    """The public pre-load estimator scales with shape and class
    count, accepts dicts and Configs, and the Booster method reports
    the trained model's measured layout."""
    est = lgb.estimate_working_set({"objective": "binary"},
                                   data_shape=(600, 5))
    assert isinstance(est, int) and est > 0
    est3 = lgb.estimate_working_set(
        {"objective": "multiclass", "num_class": 3},
        data_shape=(600, 5))
    assert est3 > est
    assert lgb.estimate_working_set(
        {"objective": "binary"}, data_shape=(6000, 5)) > est
    cfg = Config.from_params({"objective": "binary"})
    assert lgb.estimate_working_set(cfg, (600, 5)) == est

    rng = np.random.RandomState(3)
    X = rng.rand(200, 4)
    y = (X[:, 0] > 0.5).astype(int)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 7}, lgb.Dataset(X, y),
                    num_boost_round=3)
    measured = bst.estimate_working_set()
    assert isinstance(measured, int) and measured > 0


def test_peek_data_shape(tmp_path):
    path = _write_csv(tmp_path / "d.csv", n=123)
    assert peek_data_shape(path) == (123, 6)
    job = Job(JobSpec("x", _params(path, str(tmp_path / "m.txt"))))
    assert job.data_shape() == (123, 5)
    with pytest.raises(LightGBMError, match="doesn't exist"):
        peek_data_shape(str(tmp_path / "nope.csv"))


# ------------------------------------------------- monitors / stalls
def _synthetic_stream_state(ts, summary=False):
    from run_monitor import StreamState

    state = StreamState()
    recs = [{"kind": "iter", "t": t, "iter": i}
            for i, t in enumerate(ts)]
    if summary:
        recs.append({"kind": "summary", "t": ts[-1] + 1.0})
    state.feed(("\n".join(json.dumps(r) for r in recs) + "\n")
               .encode())
    return state


def test_stall_detector_median_gap():
    """The pace-relative staleness detector: an unfinished stream
    whose file has gone quiet for > 2x its own median inter-record
    gap is flagged; finished or young streams never are."""
    from run_monitor import fleet_stale, median_record_gap, stream_stale

    steady = _synthetic_stream_state([0.0, 1.0, 2.0, 3.0, 4.0])
    assert median_record_gap(steady) == 1.0
    assert stream_stale(steady, age_s=1.5) is None      # within 2x
    assert stream_stale(steady, age_s=2.5) == (2.5, 1.0)
    finished = _synthetic_stream_state([0.0, 1.0, 2.0, 3.0],
                                       summary=True)
    assert stream_stale(finished, age_s=100.0) is None
    young = _synthetic_stream_state([0.0, 1.0])
    assert median_record_gap(young) is None
    assert stream_stale(young, age_s=100.0) is None
    # fleet view: only the quiet unfinished stream is reported
    states = {"/r0.jsonl": steady, "/r1.jsonl": finished}
    hits = fleet_stale(states, ages={"/r0.jsonl": 9.0,
                                     "/r1.jsonl": 9.0})
    assert [h[0] for h in hits] == ["r0.jsonl"]
    assert hits[0][1] == 9.0 and hits[0][2] == 1.0


def test_fleet_render_flags_stale_stream():
    from run_monitor import render_fleet

    slow = _synthetic_stream_state([0.0, 0.5, 1.0, 1.5, 2.0])
    # mtime-based age of a fake path is None -> never flagged, so the
    # render path exercises the no-flag branch without touching disk
    out = render_fleet({"/none.jsonl": slow}, "/tmp/fleet")
    assert "STALE" not in out


def test_sched_monitor_folds_and_flags(tmp_path):
    """sched_monitor folds a real scheduler stream (per-job progress,
    admissions, summary) and shares the staleness detector."""
    from sched_monitor import SchedStreamState, render
    from run_monitor import stream_stale

    stream = tmp_path / "sched.jsonl"
    sched, ja, jb = _two_jobs(tmp_path, health_out=str(stream))
    sched.run()
    state = SchedStreamState()
    state.feed(open(stream, "rb").read())
    assert state.summary is not None
    assert set(state.jobs) == {"A", "B"}
    assert all(v.get("terminal") == "done"
               for v in state.jobs.values())
    text = render(state, str(stream))
    assert "[closed]" in text and "A" in text and "B" in text
    assert "summary: 2 done / 0 failed" in text
    # a closed stream is never stale, whatever its age
    assert stream_stale(state, age_s=1e6) is None
