"""Model-file interop: golden reference-format fixture, bin re-alignment of
loaded trees, and CLI<->Python parity (the reference's
tests/python_package_test/test_consistency.py:103 pattern)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import Application


# A stock LightGBM 2.2.4-format model (gbdt_model_text.cpp:250 key order;
# no init_scores line — that is this package's extension).  Binary
# objective, 3 features, 2 trees:
#   tree 0: x0<=0.5 ? (x1<=-0.25 ? -0.4 : 0.55) : 0.3
#   tree 1: x2<=1.25 ? -0.2 : 0.1
GOLDEN_MODEL = """tree
version=v2
num_class=1
num_tree_per_iteration=1
label_index=0
max_feature_idx=2
objective=binary sigmoid:1
feature_names=f0 f1 f2
feature_infos=[-5:5] [-5:5] [-5:5]
tree_sizes=480 340

Tree=0
num_leaves=3
num_cat=0
split_feature=0 1
split_gain=10 5
threshold=0.5 -0.25
decision_type=2 2
left_child=1 -1
right_child=-2 -3
leaf_value=-0.4 0.3 0.55
leaf_weight=100 120 80
leaf_count=100 120 80
internal_value=0 0.1
internal_weight=300 180
internal_count=300 180
shrinkage=0.1

Tree=1
num_leaves=2
num_cat=0
split_feature=2
split_gain=4
threshold=1.25
decision_type=2
left_child=-1
right_child=-2
leaf_value=-0.2 0.1
leaf_weight=150 150
leaf_count=150 150
internal_value=0
internal_weight=300
internal_count=300
shrinkage=0.1

end of trees

feature importances:
f0=1
f1=1
f2=1

parameters:
end of parameters
"""


def _golden_raw(X):
    t0 = np.where(X[:, 0] <= 0.5,
                  np.where(X[:, 1] <= -0.25, -0.4, 0.55), 0.3)
    t1 = np.where(X[:, 2] <= 1.25, -0.2, 0.1)
    return t0 + t1


def test_golden_reference_model_predicts(tmp_path, rng):
    path = tmp_path / "golden.txt"
    path.write_text(GOLDEN_MODEL)
    bst = lgb.Booster(model_file=str(path))
    assert bst.num_trees() == 2
    X = rng.normal(size=(500, 3)) * 2
    raw = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(raw, _golden_raw(X), rtol=1e-12)
    prob = bst.predict(X)
    np.testing.assert_allclose(prob, 1.0 / (1.0 + np.exp(-_golden_raw(X))),
                               rtol=1e-9)
    # save -> reload reproduces the predictions exactly
    out = tmp_path / "resaved.txt"
    bst.save_model(str(out))
    bst2 = lgb.Booster(model_file=str(out))
    np.testing.assert_array_equal(bst2.predict(X, raw_score=True), raw)


def test_loaded_tree_binned_routing_guarded(tmp_path, rng):
    """A tree parsed from a model file must refuse BINNED routing until its
    thresholds are re-mapped through a dataset's BinMappers
    (serialization.py placeholder thresholds would route on garbage)."""
    path = tmp_path / "golden.txt"
    path.write_text(GOLDEN_MODEL)
    bst = lgb.Booster(model_file=str(path))
    tree = bst.gbdt.models[0]
    assert not tree.bins_aligned
    X = rng.normal(size=(100, 3))
    ds = lgb.Dataset(X, (X[:, 0] > 0).astype(float)).construct()._handle
    with pytest.raises(lgb.LightGBMError):
        tree.predict_binned(ds.binned, ds.feature_infos())


def test_continued_training_realigns_loaded_trees(tmp_path, rng):
    X = rng.normal(size=(1500, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "num_leaves": 7}
    b1 = lgb.train(params, lgb.Dataset(X, y), num_boost_round=5)
    mf = str(tmp_path / "m.txt")
    b1.save_model(mf)
    # continue WITHOUT raw data binding: trees must be re-mapped to bins
    b2 = lgb.train(params, lgb.Dataset(X, y), num_boost_round=5,
                   init_model=mf)
    assert b2.num_trees() == 10
    assert all(t.bins_aligned for t in b2.gbdt.models)
    # the re-mapped thresholds route identically to the raw thresholds
    ds = lgb.Dataset(X, y).construct()._handle
    infos = ds.feature_infos()
    for t in b2.gbdt.models[:5]:
        np.testing.assert_allclose(
            t.predict_binned(ds.binned, infos), t.predict_raw(X),
            rtol=1e-12)


def test_cli_python_parity(tmp_path, rng):
    """CLI and Python API trained on the SAME file with the SAME params
    must produce identical predictions (test_consistency.py:103)."""
    n, f = 800, 5
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    train = str(tmp_path / "train.csv")
    np.savetxt(train, np.column_stack([y, X]), delimiter=",", fmt="%.6f")

    model_cli = str(tmp_path / "cli.txt")
    Application([
        "task=train", f"data={train}", "objective=binary", "num_trees=12",
        "num_leaves=7", "min_data_in_leaf=5", f"output_model={model_cli}",
        "verbosity=-1",
    ]).run()

    params = {"objective": "binary", "num_trees": 12, "num_leaves": 7,
              "min_data_in_leaf": 5, "verbose": -1}
    bst_py = lgb.train(params, lgb.Dataset(train), num_boost_round=12)

    Xr = np.loadtxt(train, delimiter=",")[:, 1:]
    p_cli = lgb.Booster(model_file=model_cli).predict(Xr)
    p_py = bst_py.predict(Xr)
    np.testing.assert_allclose(p_cli, p_py, rtol=1e-9, atol=1e-12)
