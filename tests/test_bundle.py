"""EFB (Exclusive Feature Bundling) + sparse ingestion tests.

Reference behavior: Dataset::FindGroups / FastFeatureBundling
(src/io/dataset.cpp:68-213) bundle mutually-exclusive sparse features into
shared bin columns; LGBM_DatasetCreateFromCSR (c_api.cpp:560) ingests
sparse input without densifying.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.core.bundle import BundleSpec, build_bundle, find_groups
from lightgbm_tpu.core.dataset import TpuDataset


def log_loss(y, p):
    p = np.clip(p, 1e-15, 1 - 1e-15)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def make_sparse_binary(rng, n=4000, blocks=50, width=20):
    """[n, blocks*width] matrix where each block of `width` features is
    one-hot-ish (mutually exclusive within the block): ideal EFB input."""
    F = blocks * width
    X = np.zeros((n, F), dtype=np.float64)
    picks = rng.randint(0, width, size=(n, blocks))
    vals = rng.normal(loc=2.0, scale=1.0, size=(n, blocks))
    for b in range(blocks):
        X[np.arange(n), b * width + picks[:, b]] = vals[:, b]
    # block sums are dense signals (each row has one nonzero per block),
    # so the problem is learnable even though every feature is 95% sparse
    logit = (X[:, :width].sum(axis=1) - X[:, width:2 * width].sum(axis=1)
             + 0.5 * X[:, 2 * width:3 * width].sum(axis=1) - 1.0)
    y = (logit + rng.normal(size=n) * 0.3 > 0).astype(np.float64)
    return X, y


# ------------------------------------------------------------- unit: groups
def test_find_groups_exclusive_features_bundle():
    # 4 perfectly exclusive features -> one group
    masks = np.zeros((4, 100), dtype=bool)
    for f in range(4):
        masks[f, f * 25:(f + 1) * 25] = True
    packed = np.packbits(masks, axis=1)
    nnz = masks.sum(axis=1)
    num_bins = np.full(4, 10)
    groups = find_groups(packed, nnz, num_bins, np.ones(4, bool),
                         max_conflict_cnt=0)
    assert len(groups) == 1 and sorted(groups[0]) == [0, 1, 2, 3]


def test_find_groups_conflicts_respected():
    # features 0 and 1 overlap on 30 rows -> cannot share a group at
    # conflict budget 0, can at budget 30
    masks = np.zeros((2, 100), dtype=bool)
    masks[0, :50] = True
    masks[1, 20:70] = True
    packed = np.packbits(masks, axis=1)
    nnz = masks.sum(axis=1)
    nb = np.full(2, 10)
    g0 = find_groups(packed, nnz, nb, np.ones(2, bool), max_conflict_cnt=0)
    assert len(g0) == 2
    g1 = find_groups(packed, nnz, nb, np.ones(2, bool), max_conflict_cnt=30)
    assert len(g1) == 1


def test_find_groups_bin_budget():
    # 3 exclusive features of 120 bins each: only two fit in a 256-bin
    # group (1 + 120 + 120 = 241; adding the third exceeds the cap)
    masks = np.zeros((3, 300), dtype=bool)
    for f in range(3):
        masks[f, f * 100:(f + 1) * 100] = True
    packed = np.packbits(masks, axis=1)
    groups = find_groups(packed, masks.sum(axis=1), np.full(3, 120),
                         np.ones(3, bool), max_conflict_cnt=0)
    sizes = sorted(len(g) for g in groups)
    assert sizes == [1, 2]


def test_bundle_spec_offsets_disjoint():
    spec = BundleSpec([[0, 2], [1]], np.asarray([5, 7, 9]))
    # group 0 holds features 0 and 2 with non-overlapping ranges after the
    # shared all-default slot 0
    assert spec.feat_group.tolist() == [0, 1, 0]
    assert spec.feat_offset[0] == 1
    assert spec.feat_offset[2] == 1 + 5
    assert spec.group_num_bin[0] == 1 + 5 + 9
    assert spec.group_num_bin[1] == 7


# ------------------------------------------------------- dataset-level EFB
@pytest.mark.slow
def test_dataset_bundles_and_matches_dense(rng):
    # the VERDICT acceptance shape: ~1000 features, 95% sparse.  The two
    # 30-round trains at F=1000 are ~10 min of CPU histogram compute —
    # slow tier; test_dataset_bundles_smoke keeps the same on/off parity
    # assertion in tier-1 at a small shape.
    X, y = make_sparse_binary(rng)
    F = X.shape[1]
    assert F == 1000 and (X == 0).mean() > 0.94
    cfg_on = Config(objective="binary", verbosity=-1)
    cfg_off = Config(objective="binary", verbosity=-1, enable_bundle=False)
    ds_on = TpuDataset.from_numpy(X, y, config=cfg_on)
    ds_off = TpuDataset.from_numpy(X, y, config=cfg_off)

    assert ds_on.bundle is not None
    # 50 exclusive blocks of 4 -> far fewer columns than features
    assert ds_on.num_columns < F // 2
    assert ds_on.binned.shape == (X.shape[0], ds_on.num_columns)
    assert ds_off.binned.shape[1] == len(ds_off.used_feature_indices)
    assert ds_on.binned.dtype == np.uint8

    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "num_leaves": 15, "min_data_in_leaf": 5}
    out = {}
    for name, flag in (("on", True), ("off", False)):
        p = dict(params, enable_bundle=flag)
        d = lgb.Dataset(X, y, params=p)
        bst = lgb.train(p, d, num_boost_round=30, verbose_eval=False)
        out[name] = log_loss(y, bst.predict(X))
    # exclusive blocks + conflict budget 0 => bundling is lossless; the
    # bundled run must track the dense run, and both must beat the prior
    # (p=0.509 -> logloss ~0.693)
    assert abs(out["on"] - out["off"]) < 0.02
    assert out["on"] < 0.55


def test_dataset_bundles_smoke(rng):
    # tier-1 version of the VERDICT-shape test above: same generator and
    # same bundled-vs-dense logloss parity assertion at a shape whose two
    # trains are seconds, not minutes.
    X, y = make_sparse_binary(rng, n=2000, blocks=12, width=10)
    F = X.shape[1]
    cfg_on = Config(objective="binary", verbosity=-1)
    ds_on = TpuDataset.from_numpy(X, y, config=cfg_on)
    assert ds_on.bundle is not None
    assert ds_on.num_columns < F // 2
    assert ds_on.binned.shape == (X.shape[0], ds_on.num_columns)

    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "num_leaves": 15, "min_data_in_leaf": 5}
    out = {}
    for name, flag in (("on", True), ("off", False)):
        p = dict(params, enable_bundle=flag)
        d = lgb.Dataset(X, y, params=p)
        bst = lgb.train(p, d, num_boost_round=10, verbose_eval=False)
        out[name] = log_loss(y, bst.predict(X))
    assert abs(out["on"] - out["off"]) < 0.02
    assert out["on"] < 0.60


def test_bundled_valid_set_and_binary_cache(rng, tmp_path):
    X, y = make_sparse_binary(rng, n=2000)
    Xt, yt = make_sparse_binary(rng, n=500)
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "num_leaves": 15, "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, y, params=params)
    vs = ds.create_valid(Xt, yt)
    res = {}
    bst = lgb.train(params, ds, num_boost_round=20, valid_sets=[vs],
                    verbose_eval=False, evals_result=res)
    assert ds._handle.bundle is not None
    # valid set shares the exact bundling
    assert vs._handle.bundle is ds._handle.bundle
    assert vs._handle.binned.shape[1] == ds._handle.num_columns
    ll = log_loss(yt, bst.predict(Xt))
    # binned eval loses conflicting bundle members on UNSEEN rows (the
    # reference's max_conflict_rate tradeoff, dataset.cpp:93-101) while raw
    # predict sees true values — a ~0.1% metric gap is inherent to EFB
    assert res["valid_0"]["binary_logloss"][-1] == pytest.approx(ll, rel=1e-2)

    # binary cache round-trips the bundle
    path = str(tmp_path / "bundled.bin")
    ds._handle.save_binary(path)
    back = TpuDataset.load_binary(path)
    assert back.bundle is not None
    assert back.num_columns == ds._handle.num_columns
    np.testing.assert_array_equal(back.binned, ds._handle.binned)
    np.testing.assert_array_equal(back.bundle.feat_offset,
                                  ds._handle.bundle.feat_offset)


# --------------------------------------------------------- sparse ingestion
def test_from_scipy_matches_dense(rng):
    sp = pytest.importorskip("scipy.sparse")
    X, y = make_sparse_binary(rng, n=2000)
    Xs = sp.csr_matrix(X)
    cfg = Config(objective="binary", verbosity=-1)
    ds_dense = TpuDataset.from_numpy(X, y, config=cfg)
    ds_sparse = TpuDataset.from_scipy(Xs, y, config=cfg)
    assert ds_sparse.bundle is not None
    # same bin boundaries and same packed matrix as the dense path
    for md, ms in zip(ds_dense.bin_mappers, ds_sparse.bin_mappers):
        np.testing.assert_allclose(md.bin_upper_bound, ms.bin_upper_bound)
    np.testing.assert_array_equal(ds_dense.binned, ds_sparse.binned)


def test_python_api_accepts_scipy_without_densify(rng, monkeypatch):
    sp = pytest.importorskip("scipy.sparse")
    X, y = make_sparse_binary(rng, n=2000)
    Xs = sp.csr_matrix(X)
    # make densification fail loudly if anything calls it
    monkeypatch.setattr(Xs, "toarray",
                        lambda *a, **k: (_ for _ in ()).throw(
                            MemoryError("densified sparse input")),
                        raising=False)
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "num_leaves": 15, "min_data_in_leaf": 5}
    ds = lgb.Dataset(Xs, y, params=params)
    bst = lgb.train(params, ds, num_boost_round=20, verbose_eval=False)
    # must beat the prior (~0.693) — 20 rounds over 1000 sparse features
    assert log_loss(y, bst.predict(X)) < 0.6
