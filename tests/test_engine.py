"""End-to-end training tests.

Mirrors the reference's tests/python_package_test/test_engine.py strategy:
small synthetic data, few iterations, assert metric thresholds and
evals_result bookkeeping.

NOTE on thresholds: gates here run on SYNTHETIC generators sized for CI
speed, so their absolute values are calibrated to those generators, not
to the reference suite's datasets.  The reference's own configs AND
numbers (breast_cancer logloss < 0.15, digits multi_logloss < 0.2, rf,
bynode subcol < 0.13, ...) are enforced verbatim in
tests/test_engine_reference_thresholds.py.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def make_binary(rng, n=2000, f=10):
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 2 + X[:, 1] - X[:, 2] * 0.5
    y = (logit + rng.normal(size=n) * 0.5 > 0).astype(np.float64)
    return X, y


def make_regression(rng, n=2000, f=10):
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 3 + np.abs(X[:, 1]) + rng.normal(size=n) * 0.1
    return X, y


def log_loss(y, p):
    p = np.clip(p, 1e-15, 1 - 1e-15)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def test_binary(rng):
    X, y = make_binary(rng)
    Xt, yt = make_binary(rng, n=500)
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "num_leaves": 15}
    ds = lgb.Dataset(X, y)
    vs = ds.create_valid(Xt, yt)
    evals_result = {}
    bst = lgb.train(params, ds, num_boost_round=50, valid_sets=[vs],
                    verbose_eval=False, evals_result=evals_result)
    pred = bst.predict(Xt)
    ll = log_loss(yt, pred)
    assert ll < 0.25
    assert "valid_0" in evals_result
    assert evals_result["valid_0"]["binary_logloss"][-1] == \
        pytest.approx(ll, rel=1e-3)
    # logloss decreasing overall
    curve = evals_result["valid_0"]["binary_logloss"]
    assert curve[-1] < curve[0]


def test_regression(rng):
    X, y = make_regression(rng)
    Xt, yt = make_regression(rng, n=500)
    params = {"objective": "regression", "metric": "l2", "verbose": -1}
    ds = lgb.Dataset(X, y)
    vs = ds.create_valid(Xt, yt)
    evals_result = {}
    bst = lgb.train(params, ds, num_boost_round=50, valid_sets=[vs],
                    verbose_eval=False, evals_result=evals_result)
    mse = float(np.mean((bst.predict(Xt) - yt) ** 2))
    assert mse < 0.8
    assert evals_result["valid_0"]["l2"][-1] == pytest.approx(mse, rel=1e-3)


def test_regression_l1_and_huber(rng):
    X, y = make_regression(rng, n=1500)
    for obj in ["regression_l1", "huber", "fair", "quantile", "mape"]:
        params = {"objective": obj, "verbose": -1, "num_leaves": 15}
        ds = lgb.Dataset(X, y)
        bst = lgb.train(params, ds, num_boost_round=30, verbose_eval=False)
        pred = bst.predict(X)
        mae = float(np.mean(np.abs(pred - y)))
        base = float(np.mean(np.abs(np.median(y) - y)))
        assert mae < base * 0.6, (obj, mae, base)


def test_poisson_gamma_tweedie(rng):
    X = rng.normal(size=(1500, 5))
    mu = np.exp(0.5 * X[:, 0] + 0.2 * X[:, 1])
    y = rng.poisson(mu).astype(np.float64)
    for obj in ["poisson", "tweedie"]:
        ds = lgb.Dataset(X, y)
        bst = lgb.train({"objective": obj, "verbose": -1}, ds,
                        num_boost_round=40, verbose_eval=False)
        pred = bst.predict(X)
        assert (pred >= 0).all()
        corr = np.corrcoef(pred, mu)[0, 1]
        assert corr > 0.8, (obj, corr)
    yg = mu + 0.1
    ds = lgb.Dataset(X, yg)
    bst = lgb.train({"objective": "gamma", "verbose": -1}, ds,
                    num_boost_round=40, verbose_eval=False)
    assert np.corrcoef(bst.predict(X), mu)[0, 1] > 0.8


def test_multiclass(rng):
    n, f, C = 3000, 8, 4
    X = rng.normal(size=(n, f))
    centers = rng.normal(size=(C, f)) * 2
    logits = X @ centers.T
    y = np.argmax(logits + rng.normal(size=(n, C)) * 0.5, axis=1)
    params = {"objective": "multiclass", "num_class": C,
              "metric": "multi_logloss", "verbose": -1, "num_leaves": 15}
    ds = lgb.Dataset(X, y.astype(np.float64))
    bst = lgb.train(params, ds, num_boost_round=30, verbose_eval=False)
    pred = bst.predict(X)
    assert pred.shape == (n, C)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-4)
    acc = float(np.mean(np.argmax(pred, axis=1) == y))
    assert acc > 0.85


def test_multiclassova(rng):
    n, f, C = 2000, 6, 3
    X = rng.normal(size=(n, f))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)
    params = {"objective": "multiclassova", "num_class": C, "verbose": -1}
    ds = lgb.Dataset(X, y.astype(np.float64))
    bst = lgb.train(params, ds, num_boost_round=30, verbose_eval=False)
    pred = bst.predict(X)
    acc = float(np.mean(np.argmax(pred, axis=1) == y))
    assert acc > 0.8


def test_early_stopping(rng):
    X, y = make_binary(rng)
    Xt, yt = make_binary(rng, n=500)
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "learning_rate": 0.3, "num_leaves": 63}
    ds = lgb.Dataset(X, y)
    vs = ds.create_valid(Xt, yt)
    bst = lgb.train(params, ds, num_boost_round=300, valid_sets=[vs],
                    early_stopping_rounds=5, verbose_eval=False)
    assert bst.best_iteration > 0
    assert bst.best_iteration < 300
    assert bst.gbdt.current_iteration() < 300


def test_continued_training(rng):
    X, y = make_regression(rng)
    params = {"objective": "regression", "verbose": -1}
    ds = lgb.Dataset(X, y)
    bst1 = lgb.train(params, ds, num_boost_round=10, verbose_eval=False)
    mse1 = float(np.mean((bst1.predict(X) - y) ** 2))
    ds2 = lgb.Dataset(X, y)
    bst2 = lgb.train(params, ds2, num_boost_round=10, verbose_eval=False,
                     init_model=bst1)
    assert bst2.num_trees() == 20
    mse2 = float(np.mean((bst2.predict(X) - y) ** 2))
    assert mse2 < mse1


def test_save_load_roundtrip(tmp_path, rng):
    X, y = make_binary(rng)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15}
    ds = lgb.Dataset(X, y)
    bst = lgb.train(params, ds, num_boost_round=20, verbose_eval=False)
    pred = bst.predict(X)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    pred2 = bst2.predict(X)
    np.testing.assert_allclose(pred, pred2, rtol=1e-5, atol=1e-7)
    # model string roundtrip
    s = bst.model_to_string()
    bst3 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(pred, bst3.predict(X), rtol=1e-5, atol=1e-7)


def test_model_dump_json(rng):
    X, y = make_regression(rng, n=800)
    ds = lgb.Dataset(X, y)
    bst = lgb.train({"objective": "regression", "verbose": -1}, ds,
                    num_boost_round=5, verbose_eval=False)
    d = bst.dump_model()
    assert d["num_tree_per_iteration"] == 1
    assert len(d["tree_info"]) == 5
    assert "tree_structure" in d["tree_info"][0]
    node = d["tree_info"][0]["tree_structure"]
    assert "split_feature" in node


def test_cv(rng):
    X, y = make_binary(rng, n=1500)
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "num_leaves": 15}
    res = lgb.cv(params, lgb.Dataset(X, y), num_boost_round=10, nfold=3,
                 stratified=True, shuffle=True, seed=7)
    key = "valid binary_logloss-mean"
    assert key in res
    assert len(res[key]) == 10
    assert res[key][-1] < res[key][0]


def test_feature_importance(rng):
    X, y = make_regression(rng)
    ds = lgb.Dataset(X, y)
    bst = lgb.train({"objective": "regression", "verbose": -1}, ds,
                    num_boost_round=20, verbose_eval=False)
    imp = bst.feature_importance("split")
    assert imp.shape == (X.shape[1],)
    # features 0 and 1 carry all the signal
    assert imp[0] + imp[1] > imp[2:].sum()
    gains = bst.feature_importance("gain")
    assert gains[0] > 0


def test_custom_objective_fobj(rng):
    X, y = make_regression(rng)
    ds = lgb.Dataset(X, y)

    def l2_obj(preds, dataset):
        labels = dataset.get_label()
        return preds - labels, np.ones_like(preds)

    bst = lgb.train({"verbose": -1, "metric": "l2"}, ds, num_boost_round=20,
                    fobj=l2_obj, verbose_eval=False)
    # raw predictions (no objective transform)
    pred = bst.predict(X, raw_score=True)
    assert float(np.mean((pred - y) ** 2)) < 1.5


def test_weights_affect_training(rng):
    X, y = make_regression(rng, n=1000)
    w = np.where(X[:, 0] > 0, 10.0, 0.1)
    ds = lgb.Dataset(X, y, weight=w)
    bst = lgb.train({"objective": "regression", "verbose": -1}, ds,
                    num_boost_round=20, verbose_eval=False)
    pred = bst.predict(X)
    err_hi = np.mean((pred - y)[X[:, 0] > 0] ** 2)
    err_lo = np.mean((pred - y)[X[:, 0] <= 0] ** 2)
    assert err_hi < err_lo


def test_lambdarank(rng):
    # 60 queries x 20 docs with a learnable relevance signal
    nq, per = 60, 20
    n = nq * per
    X = rng.normal(size=(n, 5))
    rel = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(size=n) * 0.3)
    y = np.digitize(rel, np.quantile(rel, [0.5, 0.75, 0.9])).astype(np.float64)
    group = np.full(nq, per)
    params = {"objective": "lambdarank", "metric": "ndcg",
              "eval_at": [3, 5], "verbose": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, y, group=group)
    vs = lgb.Dataset(X, y, group=group, reference=ds)
    evals_result = {}
    bst = lgb.train(params, ds, num_boost_round=30, valid_sets=[vs],
                    verbose_eval=False, evals_result=evals_result)
    ndcg3 = evals_result["valid_0"]["ndcg@3"]
    assert ndcg3[-1] > 0.85
    assert ndcg3[-1] > ndcg3[0]


def test_missing_values(rng):
    X, y = make_regression(rng, n=1500)
    X[rng.uniform(size=X.shape) < 0.2] = np.nan
    ds = lgb.Dataset(X, y)
    bst = lgb.train({"objective": "regression", "verbose": -1}, ds,
                    num_boost_round=30, verbose_eval=False)
    pred = bst.predict(X)
    assert np.isfinite(pred).all()
    assert float(np.mean((pred - y) ** 2)) < 0.5 * y.var()


def test_categorical_features(rng):
    n = 2000
    cat = rng.randint(0, 6, size=n)
    Xnum = rng.normal(size=(n, 3))
    effects = np.array([0.0, 2.0, -1.0, 4.0, 0.5, -3.0])
    y = effects[cat] + Xnum[:, 0] + rng.normal(size=n) * 0.1
    X = np.column_stack([cat.astype(np.float64), Xnum])
    ds = lgb.Dataset(X, y, categorical_feature=[0])
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "min_data_in_leaf": 5}, ds,
                    num_boost_round=40, verbose_eval=False)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.1 * y.var()


def test_predict_leaf_index(rng):
    X, y = make_regression(rng, n=500)
    ds = lgb.Dataset(X, y)
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "num_leaves": 7}, ds, num_boost_round=5,
                    verbose_eval=False)
    leaves = bst.predict(X, pred_leaf=True)
    assert leaves.shape == (500, 5)
    assert leaves.max() < 7
    assert leaves.min() >= 0


def test_update_with_new_train_set(rng):
    """Booster.update(train_set=...) swaps training data mid-boosting
    (LGBM_BoosterResetTrainingData; aligned bins required)."""
    X, y = make_binary(rng)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15}
    ds = lgb.Dataset(X, y, params=params)
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(3):
        bst.update()
    # aligned swap: same bins via reference
    ds2 = lgb.Dataset(X[:1200], y[:1200], reference=ds, params=params)
    bst.update(train_set=ds2)
    assert bst.gbdt.num_data == 1200
    # the swapped score buffer must equal the model's raw prediction on
    # the new rows (GBDT::ResetTrainingData replays existing trees)
    np.testing.assert_allclose(
        np.asarray(bst.gbdt.train_score)[0],
        bst.predict(X[:1200], raw_score=True), rtol=1e-4, atol=1e-5)
    pred = bst.predict(X)
    assert np.mean((pred > 0.5) == y) > 0.85
    # misaligned swap is rejected ATOMICALLY: booster still trains after
    bad = lgb.Dataset(X * 1.7, y, params=params)
    with pytest.raises(lgb.LightGBMError):
        bst.update(train_set=bad)
    assert bst.gbdt.num_data == 1200
    bst.update()
    assert bst.num_trees() == 5


def test_booster_pickle_round_trip(rng):
    """Pickled Booster predicts identically after restore (reference
    pickles via the text model; training state does not survive)."""
    import pickle

    X = rng.normal(size=(800, 5))
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "min_data_in_leaf": 5}, lgb.Dataset(X, y),
                    num_boost_round=8, verbose_eval=False)
    blob = pickle.dumps(bst)
    bst2 = pickle.loads(blob)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-7, atol=1e-9)


def test_sklearn_estimator_pickle(rng):
    import pickle

    X = rng.normal(size=(600, 4))
    y = X[:, 0] * 2 + rng.normal(size=600) * 0.1
    model = lgb.LGBMRegressor(n_estimators=10, num_leaves=15,
                              min_child_samples=5).fit(X, y)
    m2 = pickle.loads(pickle.dumps(model))
    np.testing.assert_allclose(model.predict(X), m2.predict(X),
                               rtol=1e-7, atol=1e-9)


def test_get_split_value_histogram(rng):
    """Threshold histogram per feature (reference test_engine
    split-value-histogram pattern)."""
    X = rng.normal(size=(1000, 4))
    y = X[:, 0] * 3 + np.sin(X[:, 1]) + rng.normal(size=1000) * 0.1
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "min_data_in_leaf": 5}, lgb.Dataset(X, y),
                    num_boost_round=10, verbose_eval=False)
    counts, edges = bst.get_split_value_histogram(0)
    assert counts.sum() > 0 and len(edges) == len(counts) + 1
    # by feature name too
    c2, _ = bst.get_split_value_histogram("Column_0")
    assert c2.sum() == counts.sum()
    # the dominant feature must carry more splits than a noise feature
    c3, _ = bst.get_split_value_histogram(3)
    assert counts.sum() >= c3.sum()
    # xgboost-style [k, 2] non-empty bins
    tab = bst.get_split_value_histogram(0, xgboost_style=True)
    assert tab.ndim == 2 and tab.shape[1] == 2
    assert tab[:, 1].sum() == counts.sum()
    import pytest as _pytest
    with _pytest.raises(Exception):
        bst.get_split_value_histogram("nope")


def test_pandas_categorical_round_trip(rng):
    """DataFrame with category dtype columns: auto-detected as
    categorical features, codes used for binning, predict on the same
    dtype frame works (reference test_engine pandas-categorical)."""
    import pandas as pd

    n = 1200
    cat = rng.choice(["a", "b", "c", "d"], size=n)
    x1 = rng.normal(size=n)
    effect = {"a": 2.0, "b": -1.0, "c": 0.5, "d": -2.5}
    y = np.asarray([effect[c] for c in cat]) + 0.3 * x1 \
        + rng.normal(size=n) * 0.1
    df = pd.DataFrame({"c0": pd.Categorical(cat), "x1": x1})
    ds = lgb.Dataset(df, y)
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "min_data_in_leaf": 5}, ds, num_boost_round=20,
                    verbose_eval=False)
    # the category column was auto-detected as a CATEGORICAL feature
    from lightgbm_tpu.core.binning import BIN_TYPE_CATEGORICAL
    assert ds._handle.bin_mappers[0].bin_type == BIN_TYPE_CATEGORICAL
    pred = bst.predict(df)
    mse = float(np.mean((pred - y) ** 2))
    assert mse < 0.1 * y.var(), mse


def test_pandas_categorical_reordered_predict_frame(rng):
    """A predict frame whose inferred category ORDER differs from the
    training frame still encodes through the persisted
    pandas_categorical mapping (reference model-file contract: trailing
    pandas_categorical: JSON line)."""
    import pandas as pd

    n = 1000
    cat = rng.choice(["a", "b", "c", "d"], size=n)
    effect = {"a": 2.0, "b": -1.0, "c": 0.5, "d": -2.5}
    y = np.asarray([effect[c] for c in cat]) + rng.normal(size=n) * 0.05
    df = pd.DataFrame({"c0": pd.Categorical(cat)})
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "min_data_in_leaf": 5}, lgb.Dataset(df, y),
                    num_boost_round=20, verbose_eval=False)
    base = bst.predict(df)

    # same values, shuffled category ORDER (what pandas infers from a
    # freshly-read subset); codes differ from training codes
    df2 = pd.DataFrame({"c0": pd.Categorical(
        cat, categories=["d", "c", "b", "a"])})
    np.testing.assert_allclose(bst.predict(df2), base, rtol=1e-7)

    # survives the model file (trailing pandas_categorical line)
    import tempfile, os
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "m.txt")
        bst.save_model(path)
        text = open(path).read()
        assert "pandas_categorical:" in text
        bst2 = lgb.Booster(model_file=path)
        assert bst2.pandas_categorical == [["a", "b", "c", "d"]]
        np.testing.assert_allclose(bst2.predict(df2), base, rtol=1e-7)

    # and pickling
    import pickle
    bst3 = pickle.loads(pickle.dumps(bst))
    np.testing.assert_allclose(bst3.predict(df2), base, rtol=1e-7)
