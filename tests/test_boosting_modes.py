"""Boosting-mode tests: bagging, GOSS, DART, RF (+ sklearn API)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def make_regression(rng, n=2000, f=8):
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 3 + np.abs(X[:, 1]) + rng.normal(size=n) * 0.1
    return X, y


def make_binary(rng, n=2000, f=8):
    X = rng.normal(size=(n, f))
    y = (X[:, 0] * 2 + X[:, 1] + rng.normal(size=n) * 0.5 > 0).astype(float)
    return X, y


def test_bagging(rng):
    X, y = make_regression(rng)
    params = {"objective": "regression", "bagging_fraction": 0.5,
              "bagging_freq": 1, "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=30,
                    verbose_eval=False)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.2 * y.var()


def test_balanced_bagging(rng):
    X, y = make_binary(rng)
    params = {"objective": "binary", "pos_bagging_fraction": 0.5,
              "neg_bagging_fraction": 0.9, "bagging_freq": 1, "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=20,
                    verbose_eval=False)
    p = bst.predict(X)
    acc = np.mean((p > 0.5) == y)
    assert acc > 0.85


def test_goss(rng):
    X, y = make_regression(rng, n=3000)
    params = {"objective": "regression", "boosting": "goss",
              "top_rate": 0.2, "other_rate": 0.1, "learning_rate": 0.2,
              "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=40,
                    verbose_eval=False)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.1 * y.var()


def test_dart(rng):
    X, y = make_regression(rng)
    params = {"objective": "regression", "boosting": "dart",
              "drop_rate": 0.3, "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=40,
                    verbose_eval=False)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.3 * y.var()


def test_dart_xgboost_mode(rng):
    X, y = make_regression(rng, n=1000)
    params = {"objective": "regression", "boosting": "dart",
              "xgboost_dart_mode": True, "drop_rate": 0.2, "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=20,
                    verbose_eval=False)
    assert np.isfinite(bst.predict(X)).all()


def test_rf(rng):
    X, y = make_binary(rng, n=3000)
    params = {"objective": "binary", "boosting": "rf",
              "bagging_fraction": 0.6, "bagging_freq": 1,
              "feature_fraction": 0.8, "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=30,
                    verbose_eval=False)
    p = bst.predict(X)
    assert (p >= 0).all() and (p <= 1).all()
    acc = np.mean((p > 0.5) == y)
    assert acc > 0.85


def test_rf_requires_bagging(rng):
    X, y = make_binary(rng, n=500)
    with pytest.raises(Exception):
        lgb.train({"objective": "binary", "boosting": "rf", "verbose": -1},
                  lgb.Dataset(X, y), num_boost_round=3, verbose_eval=False)


# ------------------------------------------------------------------ sklearn
def test_sklearn_regressor(rng):
    X, y = make_regression(rng)
    model = lgb.LGBMRegressor(n_estimators=30, num_leaves=15)
    model.fit(X, y)
    pred = model.predict(X)
    assert float(np.mean((pred - y) ** 2)) < 0.2 * y.var()
    assert model.feature_importances_.shape == (X.shape[1],)
    assert model.n_features_ == X.shape[1]


def test_sklearn_classifier_binary(rng):
    X, y = make_binary(rng)
    ylab = np.where(y > 0, "pos", "neg")
    model = lgb.LGBMClassifier(n_estimators=30, num_leaves=15)
    model.fit(X, ylab)
    pred = model.predict(X)
    assert set(pred) <= {"pos", "neg"}
    acc = np.mean(pred == ylab)
    assert acc > 0.9
    proba = model.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)


def test_sklearn_classifier_multiclass(rng):
    n, f = 2000, 6
    X = rng.normal(size=(n, f))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)
    model = lgb.LGBMClassifier(n_estimators=30, num_leaves=15)
    model.fit(X, y)
    assert model.n_classes_ == 3
    proba = model.predict_proba(X)
    assert proba.shape == (n, 3)
    acc = np.mean(model.predict(X) == y)
    assert acc > 0.8


def test_sklearn_early_stopping(rng):
    X, y = make_binary(rng)
    Xt, yt = make_binary(rng, n=400)
    model = lgb.LGBMClassifier(n_estimators=200, learning_rate=0.3)
    model.fit(X, y, eval_set=[(Xt, yt)], early_stopping_rounds=5,
              eval_metric="binary_logloss", verbose=False)
    assert model.best_iteration_ > 0
    assert model.best_iteration_ < 200


def test_sklearn_ranker(rng):
    nq, per = 40, 25
    n = nq * per
    X = rng.normal(size=(n, 5))
    y = np.clip((X[:, 0] + rng.normal(size=n) * 0.3 > 0.5).astype(int)
                + (X[:, 0] > 1.2).astype(int), 0, 2).astype(float)
    model = lgb.LGBMRanker(n_estimators=20, num_leaves=7,
                           min_child_samples=5)
    model.fit(X, y, group=np.full(nq, per))
    s = model.predict(X)
    # higher label -> higher average score
    assert s[y == 2].mean() > s[y == 0].mean()


def test_sklearn_get_set_params(rng):
    model = lgb.LGBMRegressor(n_estimators=10, num_leaves=5)
    p = model.get_params()
    assert p["n_estimators"] == 10
    model.set_params(n_estimators=20)
    assert model.n_estimators == 20


def test_goss_fused_matches_eager(rng):
    """GOSS now rides the fused 2-dispatch pipeline; same seed must grow
    identical trees through the fused and eager paths (the sampling key
    stream is shared)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.core.dataset import TpuDataset
    from lightgbm_tpu.models.boosting_factory import create_boosting
    from lightgbm_tpu.objective import create_objective

    n = 2000
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)

    def train(force_eager):
        cfg = Config(verbosity=-1, objective="binary", boosting="goss",
                     num_leaves=15, min_data_in_leaf=5, top_rate=0.3,
                     other_rate=0.2, learning_rate=0.5)  # short warm-up
        ds = TpuDataset.from_numpy(X, y, config=cfg)
        obj = create_objective(cfg)
        obj.init(ds.metadata, ds.num_data)
        bst = create_boosting(cfg, ds, obj)
        if force_eager:
            bst._fused_ok = False
        for _ in range(8):       # iterations 2+ actually sample
            bst.train_one_iter()
        return bst

    fused = train(False)
    eager = train(True)
    assert len(fused.models) == len(eager.models) == 8
    for i, (tf, te) in enumerate(zip(fused.models, eager.models)):
        assert tf.num_leaves == te.num_leaves, f"tree {i}"
        nsp = tf.num_leaves - 1
        assert np.array_equal(tf.split_feature[:nsp],
                              te.split_feature[:nsp]), f"tree {i}"
    np.testing.assert_allclose(fused._raw_predict(X),
                               eager._raw_predict(X),
                               rtol=1e-5, atol=1e-6)
