"""bench.py impl A/B selection logic (pure-function tests; the on-chip
tiers themselves run only on real hardware)."""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ab_picks_faster_when_quality_holds(monkeypatch):
    bench = _load_bench()
    monkeypatch.delenv("LIGHTGBM_TPU_IMPL", raising=False)
    base = {"per_iter": 0.5, "rows": 100, "backend": "tpu",
            "impl": "segment", "auc": 0.900}
    monkeypatch.setattr(bench, "run_tier",
                        lambda *a, **k: {"per_iter": 0.2, "rows": 100,
                                         "backend": "tpu",
                                         "impl": "frontier",
                                         "auc": 0.899})
    out = bench.maybe_ab_frontier(base, "tpu", 100, 1, 2, 60)
    assert out["impl"] == "frontier"


def test_ab_rejects_quality_regression(monkeypatch):
    bench = _load_bench()
    monkeypatch.delenv("LIGHTGBM_TPU_IMPL", raising=False)
    base = {"per_iter": 0.5, "rows": 100, "backend": "tpu",
            "impl": "segment", "auc": 0.900}
    monkeypatch.setattr(bench, "run_tier",
                        lambda *a, **k: {"per_iter": 0.2, "rows": 100,
                                         "backend": "tpu",
                                         "impl": "frontier",
                                         "auc": 0.850})
    out = bench.maybe_ab_frontier(base, "tpu", 100, 1, 2, 60)
    assert out["impl"] == "segment"


def test_ab_rejects_slower_frontier(monkeypatch):
    bench = _load_bench()
    monkeypatch.delenv("LIGHTGBM_TPU_IMPL", raising=False)
    base = {"per_iter": 0.5, "rows": 100, "backend": "tpu",
            "impl": "segment", "auc": 0.900}
    monkeypatch.setattr(bench, "run_tier",
                        lambda *a, **k: {"per_iter": 0.9, "rows": 100,
                                         "backend": "tpu",
                                         "impl": "frontier",
                                         "auc": 0.905})
    out = bench.maybe_ab_frontier(base, "tpu", 100, 1, 2, 60)
    assert out["impl"] == "segment"


def test_ab_skips_cpu_and_pinned_impl(monkeypatch):
    bench = _load_bench()
    base = {"per_iter": 0.5, "rows": 100, "backend": "cpu",
            "impl": "fused-onehot", "auc": 0.9}
    calls = []
    monkeypatch.setattr(bench, "run_tier",
                        lambda *a, **k: calls.append(1))
    assert bench.maybe_ab_frontier(base, "cpu", 100, 1, 2, 60) is base
    monkeypatch.setenv("LIGHTGBM_TPU_IMPL", "segment")
    assert bench.maybe_ab_frontier(base, "tpu", 100, 1, 2, 60) is base
    assert not calls


def test_ab_survives_child_failure(monkeypatch):
    bench = _load_bench()
    monkeypatch.delenv("LIGHTGBM_TPU_IMPL", raising=False)
    base = {"per_iter": 0.5, "rows": 100, "backend": "tpu",
            "impl": "segment", "auc": 0.9}

    def boom(*a, **k):
        raise RuntimeError("tier child rc=1")
    monkeypatch.setattr(bench, "run_tier", boom)
    assert bench.maybe_ab_frontier(base, "tpu", 100, 1, 2, 60) is base


def test_ab_skips_when_measured_backend_is_cpu(monkeypatch):
    """A tpu tier whose child silently fell back to the CPU backend must
    not trigger a second meaningless CPU A/B run."""
    bench = _load_bench()
    monkeypatch.delenv("LIGHTGBM_TPU_IMPL", raising=False)
    base = {"per_iter": 30.0, "rows": 100, "backend": "cpu",
            "impl": "fused-onehot", "auc": 0.9}
    calls = []
    monkeypatch.setattr(bench, "run_tier",
                        lambda *a, **k: calls.append(1))
    assert bench.maybe_ab_frontier(base, "tpu", 100, 1, 2, 60) is base
    assert not calls


def test_ab_chunked_picks_faster_and_pins_impl(monkeypatch):
    bench = _load_bench()
    monkeypatch.delenv("LIGHTGBM_TPU_BOOST_CHUNK", raising=False)
    monkeypatch.delenv("LIGHTGBM_TPU_IMPL", raising=False)
    base = {"per_iter": 0.5, "rows": 100, "backend": "tpu",
            "impl": "frontier", "auc": 0.900, "chunk": 1}
    seen = {}

    def fake_run_tier(*a, **k):
        seen.update(k)
        return {"per_iter": 0.3, "rows": 100, "backend": "tpu",
                "impl": "frontier", "auc": 0.900, "chunk": 4}
    monkeypatch.setattr(bench, "run_tier", fake_run_tier)
    out = bench.maybe_ab_chunked(base, "tpu", 100, 2, 4, 60)
    assert out["chunk"] == 4
    # both sides of the comparison must run the same grower
    assert seen["impl_env"] == "frontier"
    assert seen["chunk_env"] == "4"


def test_ab_chunked_skips_pinned_env_and_rejects_slower(monkeypatch):
    bench = _load_bench()
    monkeypatch.delenv("LIGHTGBM_TPU_IMPL", raising=False)
    base = {"per_iter": 0.5, "rows": 100, "backend": "cpu",
            "impl": "fused-onehot", "auc": 0.9, "chunk": 1}
    calls = []
    monkeypatch.setenv("LIGHTGBM_TPU_BOOST_CHUNK", "4")
    monkeypatch.setattr(bench, "run_tier",
                        lambda *a, **k: calls.append(1))
    assert bench.maybe_ab_chunked(base, "cpu", 100, 1, 2, 60) is base
    assert not calls
    monkeypatch.delenv("LIGHTGBM_TPU_BOOST_CHUNK")
    monkeypatch.setattr(
        bench, "run_tier",
        lambda *a, **k: {"per_iter": 0.8, "rows": 100, "backend": "cpu",
                         "impl": "fused-onehot", "auc": 0.9, "chunk": 2})
    assert bench.maybe_ab_chunked(base, "cpu", 100, 1, 2, 60) is base
