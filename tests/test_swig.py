"""SWIG binding smoke test: generate the wrapper from swig/lightgbmlib.i,
compile it against lib_lightgbm_tpu.so, and drive a dataset->train->predict
round trip through the SWIG pointer/array helpers (the reference wraps its
c_api.h the same way for the JNI consumer; the Python generator proves the
interface file and the C contract without a JDK)."""

import os
import shutil
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def swig_module(tmp_path_factory):
    if not shutil.which("swig"):
        pytest.skip("swig not installed")
    out = tmp_path_factory.mktemp("swig")
    wrap_c = str(out / "lightgbmlib_wrap.c")
    subprocess.run(
        ["swig", "-python", f"-I{REPO}/include", "-outdir", str(out),
         "-o", wrap_c, os.path.join(REPO, "swig", "lightgbmlib.i")],
        check=True)
    from lightgbm_tpu.build_capi import build_capi
    so = build_capi()
    include = sysconfig.get_path("include")
    ext = str(out / "_lightgbmlib.so")
    subprocess.run(
        ["g++", "-O1", "-fPIC", "-shared", f"-I{include}",
         f"-I{REPO}/include", wrap_c, so, f"-Wl,-rpath,{os.path.dirname(so)}",
         "-o", ext], check=True)
    sys.path.insert(0, str(out))
    try:
        import lightgbmlib
        yield lightgbmlib
    finally:
        sys.path.remove(str(out))


def test_swig_round_trip(swig_module, rng, tmp_path):
    lib = swig_module
    assert isinstance(lib.LGBM_GetLastError(), str)

    n, f = 400, 4
    X = rng.normal(size=(n, f))
    y = (X[:, 0] > 0).astype(np.float64)
    arr = lib.new_doubleArray(n * f)
    for i, v in enumerate(X.ravel()):
        lib.doubleArray_setitem(arr, i, float(v))
    hdl = lib.new_voidpp()
    rc = lib.LGBM_DatasetCreateFromMat(
        lib.voidpp_value_as_void(arr) if hasattr(lib, "voidpp_value_as_void")
        else arr, lib.C_API_DTYPE_FLOAT64, n, f, 1,
        "objective=binary verbosity=-1 min_data_in_leaf=5",
        None, hdl)
    assert rc == 0, lib.LGBM_GetLastError()
    ds = lib.voidpp_value(hdl)

    lab = lib.new_floatArray(n)
    for i, v in enumerate(y):
        lib.floatArray_setitem(lab, i, float(v))
    assert lib.LGBM_DatasetSetField(ds, "label", lab, n,
                                    lib.C_API_DTYPE_FLOAT32) == 0

    bh = lib.new_voidpp()
    assert lib.LGBM_BoosterCreate(
        ds, "objective=binary verbosity=-1 min_data_in_leaf=5", bh) == 0
    booster = lib.voidpp_value(bh)
    fin = lib.new_intp()
    for _ in range(5):
        assert lib.LGBM_BoosterUpdateOneIter(booster, fin) == 0

    out_len = lib.new_int64_tp()
    preds = lib.new_doubleArray(n)
    assert lib.LGBM_BoosterPredictForMat(
        booster, arr, lib.C_API_DTYPE_FLOAT64, n, f, 1,
        lib.C_API_PREDICT_NORMAL, -1, "", out_len, preds) == 0
    assert lib.int64_tp_value(out_len) == n
    p = np.asarray([lib.doubleArray_getitem(preds, i) for i in range(n)])
    acc = float(np.mean((p > 0.5) == y))
    assert acc > 0.9, acc

    # string-array helpers: eval/feature names through the
    # caller-pre-allocates char** contract (reference .i's StringArray
    # machinery; ours is the stringBuffers table)
    W = 128
    cnt = lib.new_intp()
    assert lib.LGBM_BoosterGetEvalCounts(booster, cnt) == 0
    n_eval = lib.intp_value(cnt)
    assert n_eval >= 1
    names = lib.new_stringBuffers(n_eval, W)
    got = lib.new_intp()
    assert lib.LGBM_BoosterGetEvalNames(booster, got,
                                        lib.stringBuffers_ptr(names)) == 0
    assert lib.intp_value(got) == n_eval
    evals = [lib.stringBuffers_getitem(names, i) for i in range(n_eval)]
    assert "binary_logloss" in evals, evals
    # out-of-range access is bounds-checked, not memory-unsafe
    assert lib.stringBuffers_getitem(names, n_eval) is None
    assert lib.stringBuffers_getitem(names, -1) is None
    lib.delete_stringBuffers(names)

    fnames = lib.new_stringBuffers(f, W)
    assert lib.LGBM_BoosterGetFeatureNames(
        booster, got, lib.stringBuffers_ptr(fnames)) == 0
    assert lib.intp_value(got) == f
    feats = [lib.stringBuffers_getitem(fnames, i) for i in range(f)]
    assert feats == [f"Column_{i}" for i in range(f)], feats
    lib.delete_stringBuffers(fnames)

    assert lib.LGBM_BoosterFree(booster) == 0

    # writable direction: rename dataset features through the same table
    # (width stored at allocation; oversize values truncate safely)
    custom = lib.new_stringBuffers(f, 8)
    for i in range(f):
        lib.stringBuffers_setitem(custom, i, f"feat_{i}" + "x" * 40)
    assert lib.LGBM_DatasetSetFeatureNames(
        ds, lib.stringBuffers_ptr(custom), f) == 0
    back = lib.new_stringBuffers(f, W)
    nf = lib.new_intp()
    assert lib.LGBM_DatasetGetFeatureNames(
        ds, lib.stringBuffers_ptr(back), nf) == 0
    assert lib.intp_value(nf) == f
    assert [lib.stringBuffers_getitem(back, i)
            for i in range(f)] == [(f"feat_{i}" + "x" * 40)[:7]
                                   for i in range(f)]
    lib.delete_stringBuffers(custom)
    lib.delete_stringBuffers(back)

    # degenerate allocations are rejected, not corrupted
    assert lib.new_stringBuffers(0, W) is None
    assert lib.new_stringBuffers(4, 0) is None

    assert lib.LGBM_DatasetFree(ds) == 0


def _train_booster(lib, rng, n, f, n_iters):
    """doubleArray-filled dataset + booster trained through the raw
    entry points (shared by the round-trip and helper-battery tests)."""
    X = rng.normal(size=(n, f))
    y = (X[:, 1] > 0).astype(np.float64)
    arr = lib.new_doubleArray(n * f)
    for i, v in enumerate(X.ravel()):
        lib.doubleArray_setitem(arr, i, float(v))
    hdl = lib.new_voidpp()
    assert lib.LGBM_DatasetCreateFromMat(
        arr, lib.C_API_DTYPE_FLOAT64, n, f, 1,
        "objective=binary verbosity=-1 min_data_in_leaf=5", None, hdl) == 0
    ds = lib.voidpp_value(hdl)
    lab = lib.new_floatArray(n)
    for i, v in enumerate(y):
        lib.floatArray_setitem(lab, i, float(v))
    assert lib.LGBM_DatasetSetField(ds, "label", lab, n,
                                    lib.C_API_DTYPE_FLOAT32) == 0
    bh = lib.new_voidpp()
    assert lib.LGBM_BoosterCreate(
        ds, "objective=binary verbosity=-1 min_data_in_leaf=5", bh) == 0
    booster = lib.voidpp_value(bh)
    fin = lib.new_intp()
    for _ in range(n_iters):
        assert lib.LGBM_BoosterUpdateOneIter(booster, fin) == 0
    return X, y, arr, ds, booster


def test_swig_typed_helper_battery(swig_module, rng):
    """The reference .i's JNI helper battery, language-neutral: grow-on-
    short-buffer model-to-string, allocating eval names, and dense/CSR
    single-row predict helpers (reference swig/lightgbmlib.i:35-200)."""
    lib = swig_module
    n, f = 300, 4
    X, y, arr, ds, booster = _train_booster(lib, rng, n, f, 4)

    # model-to-string: a 16-byte initial buffer MUST trigger the grow path
    s = lib.LGBM_BoosterSaveModelToStringSWIG(booster, 0, -1, 16)
    assert s is not None and "Tree=0" in s

    cnt = lib.new_intp()
    assert lib.LGBM_BoosterGetEvalCounts(booster, cnt) == 0
    names = lib.LGBM_BoosterGetEvalNamesSWIG(booster, lib.intp_value(cnt))
    assert lib.stringBuffers_getitem(names, 0) == "binary_logloss"
    lib.delete_stringBuffers(names)

    # single-row dense helper == the full-matrix predict row 0
    out_len = lib.new_int64_tp()
    full = lib.new_doubleArray(n)
    assert lib.LGBM_BoosterPredictForMat(
        booster, arr, lib.C_API_DTYPE_FLOAT64, n, f, 1,
        lib.C_API_PREDICT_NORMAL, -1, "", out_len, full) == 0
    row = lib.new_doubleArray(f)
    for j in range(f):
        lib.doubleArray_setitem(row, j, float(X[0, j]))
    one = lib.new_doubleArray(1)
    assert lib.LGBM_BoosterPredictForMatSingleSWIG(
        booster, row, f, lib.C_API_PREDICT_NORMAL, -1, "", out_len,
        one) == 0
    assert abs(lib.doubleArray_getitem(one, 0)
               - lib.doubleArray_getitem(full, 0)) < 1e-12

    # sparse single-row helper: same row as (indices, values) pairs
    idx = lib.new_intArray(f)
    vals = lib.new_doubleArray(f)
    for j in range(f):
        lib.intArray_setitem(idx, j, j)
        lib.doubleArray_setitem(vals, j, float(X[0, j]))
    one2 = lib.new_doubleArray(1)
    assert lib.LGBM_BoosterPredictForCSRSingleSWIG(
        booster, idx, vals, f, f, lib.C_API_PREDICT_NORMAL, -1, "",
        out_len, one2) == 0
    assert abs(lib.doubleArray_getitem(one2, 0)
               - lib.doubleArray_getitem(full, 0)) < 1e-12
    assert lib.LGBM_BoosterFree(booster) == 0
    assert lib.LGBM_DatasetFree(ds) == 0
