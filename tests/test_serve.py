"""Prediction-service tests (lightgbm_tpu/serve + the predict routing).

The load-bearing contract everywhere: the serve path — device binning of
raw floats, bucketed compiled routing, host float64 leaf gather — is
BIT-identical to ``Booster.predict``, across missing types, categorical
bitset splits (in- and out-of-vocabulary) and multiclass.  On top of
that: padded rows are inert, bucket reuse never recompiles, multi-model
packs stay correct through eviction, admission rejects over-budget
loads with an actionable error, fault sites give up by name instead of
hanging, and Booster.refit re-estimates leaves like a from-scratch fit.
"""

import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import (MicroBatchQueue, ModelRegistry,
                                ServeAdmissionError, ServeError,
                                ServeSession)
from lightgbm_tpu.utils.faults import FAULTS
from lightgbm_tpu.utils.telemetry import TELEMETRY, TelemetryRegistry


@pytest.fixture(autouse=True)
def _clean():
    TELEMETRY.reset()
    TELEMETRY.set_config_level(1)
    TELEMETRY.install_jax_listeners()
    yield
    FAULTS.configure()


def _fake_mem(monkeypatch, bytes_limit):
    """Pretend the device reports ``bytes_limit`` of HBM; returns the
    mutable stats dict so a test can shrink the budget mid-flight."""
    ms = {"bytes_in_use": 0, "peak_bytes_in_use": 0,
          "largest_alloc_size": 0, "bytes_limit": int(bytes_limit)}
    monkeypatch.setattr(TelemetryRegistry, "_device_memory_stats",
                        lambda self: dict(ms))
    return ms


def _make_mixed(rng, n=600, f=8):
    """NaN-missing, zero-missing and two categorical columns."""
    X = rng.normal(size=(n, f))
    X[:, 3] = rng.randint(0, 6, size=n)           # categorical
    X[:, 4] = rng.randint(0, 11, size=n)          # categorical
    X[rng.rand(n) < 0.2, 1] = np.nan              # MISSING_NAN column
    X[:, 2] = np.where(rng.rand(n) < 0.4, 0.0, X[:, 2])  # MISSING_ZERO
    y = (np.nan_to_num(X[:, 0] + X[:, 1]) + (X[:, 3] % 2)
         + 0.5 * (X[:, 4] % 3 == 0) > 0.8).astype(np.float64)
    return X, y


def _train(rng, objective="binary", num_class=1, rounds=12):
    X, y = _make_mixed(rng)
    params = {"objective": objective, "verbose": -1, "num_leaves": 15}
    if num_class > 1:
        params["num_class"] = num_class
        y = np.minimum(y + (X[:, 0] > 1.0), num_class - 1)
    ds = lgb.Dataset(X, y, categorical_feature=[3, 4])
    return lgb.train(params, ds, num_boost_round=rounds), X, y


def _queries(rng, X, n=77):
    """Query rows exercising every corner: training rows, NaN, exact
    zeros, and OUT-of-vocabulary categories (unseen during training)."""
    Xq = X[rng.choice(len(X), n, replace=False)].copy()
    Xq[rng.rand(n) < 0.3, 1] = np.nan
    Xq[rng.rand(n) < 0.3, 2] = 0.0
    oov = rng.rand(n) < 0.25
    Xq[oov, 3] = rng.choice([-1, 6, 7, 99], size=int(oov.sum()))
    return Xq


# ------------------------------------------------------- bit-identity
def test_serve_bit_identical_binary(rng):
    bst, X, _ = _train(rng)
    Xq = _queries(rng, X)
    ref = bst.predict(Xq)
    with ServeSession(max_batch=64, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        np.testing.assert_array_equal(ref, sess.predict_direct(mid, Xq))
        np.testing.assert_array_equal(ref, sess.predict(mid, Xq))
        raw = sess.predict_direct(mid, Xq, raw_score=True)
        np.testing.assert_array_equal(bst.predict(Xq, raw_score=True), raw)


def test_serve_bit_identical_multiclass(rng):
    bst, X, _ = _train(rng, objective="multiclass", num_class=3, rounds=6)
    Xq = _queries(rng, X)
    ref = bst.predict(Xq)
    assert ref.shape == (len(Xq), 3)
    with ServeSession(max_batch=32, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        np.testing.assert_array_equal(ref, sess.predict_direct(mid, Xq))


def test_booster_serve_handle(rng):
    bst, X, _ = _train(rng)
    Xq = _queries(rng, X, n=20)
    with bst.serve(serve_max_delay_ms=0.0) as handle:
        np.testing.assert_array_equal(bst.predict(Xq),
                                      handle.predict(Xq))
        fut = handle.submit(Xq[:5])
        np.testing.assert_array_equal(bst.predict(Xq[:5]),
                                      fut.result(timeout=30))


# --------------------------------------------------- shape bucketing
def test_padded_rows_inert_across_buckets(rng):
    """The same rows predicted inside different-size batches (hence
    different pad counts and buckets) give identical outputs."""
    bst, X, _ = _train(rng)
    Xq = _queries(rng, X, n=50)
    with ServeSession(max_batch=64, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        full = sess.predict_direct(mid, Xq)          # bucket 64
        for cut in (1, 5, 9, 17, 33):                # buckets 8..64
            part = sess.predict_direct(mid, Xq[:cut])
            np.testing.assert_array_equal(full[:cut], part)
    g = TELEMETRY.stats()["gauges"]
    assert "serve/pad_ratio" in g and 0.0 <= g["serve/pad_ratio"] < 1.0


def test_bucket_reuse_zero_recompiles(rng):
    bst, X, _ = _train(rng)
    Xq = _queries(rng, X, n=48)
    with ServeSession(max_batch=64, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        sess.predict_direct(mid, Xq)                 # compiles bucket 64
        c0 = dict(TELEMETRY.stats()["counters"])
        for i in range(5):                           # same bucket again
            sess.predict_direct(mid, Xq[: 48 - i])
        c1 = TELEMETRY.stats()["counters"]
        assert c1.get("compile/retraces", 0) == c0.get(
            "compile/retraces", 0)
        assert c1["serve/batches"] == c0["serve/batches"] + 5


def test_serve_counters(rng):
    bst, X, _ = _train(rng)
    with ServeSession(max_batch=32, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        sess.predict(mid, X[:10])
        sess.predict(mid, X[:3])
    c = TELEMETRY.stats()["counters"]
    assert c["serve/requests"] == 2
    assert c["serve/rows"] == 13
    assert c["serve/padded_rows"] >= (16 - 10) + (8 - 3)


# ------------------------------------------------------ micro-batching
def test_queue_coalesces_requests(rng):
    bst, X, _ = _train(rng)
    ref = bst.predict(X[:32])
    with ServeSession(max_batch=64, max_delay_ms=150.0) as sess:
        mid = sess.load(bst)
        sess.predict(mid, X[:1])                     # compile first
        TELEMETRY.reset()
        futs = [sess.submit(mid, X[i * 8:(i + 1) * 8]) for i in range(4)]
        outs = [f.result(timeout=30) for f in futs]
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(ref[i * 8:(i + 1) * 8], out)
    c = TELEMETRY.stats()["counters"]
    assert c["serve/requests"] == 4
    # the 150ms window coalesced the burst into one padded dispatch
    assert c["serve/batches"] == 1


def test_queue_interleaves_models(rng):
    b1, X, _ = _train(rng)
    b2, _, _ = _train(rng, rounds=5)
    with ServeSession(max_batch=32, max_delay_ms=0.0) as sess:
        m1, m2 = sess.load(b1, model_id="a"), sess.load(b2, model_id="b")
        f1 = sess.submit(m1, X[:8])
        f2 = sess.submit(m2, X[:8])
        np.testing.assert_array_equal(b1.predict(X[:8]),
                                      f1.result(timeout=30))
        np.testing.assert_array_equal(b2.predict(X[:8]),
                                      f2.result(timeout=30))


def test_queue_close_fails_pending(rng):
    bst, X, _ = _train(rng)
    sess = ServeSession(max_batch=16, max_delay_ms=0.0)
    mid = sess.load(bst)
    sess.predict(mid, X[:4])
    sess.close()
    with pytest.raises(ServeError, match="closed"):
        sess.submit(mid, X[:4])


# ------------------------------------------------------------ admission
def test_admission_rejects_over_budget(rng, monkeypatch):
    bst, X, _ = _train(rng)
    _fake_mem(monkeypatch, 10_000)                   # 10 kB "HBM"
    reg = ModelRegistry(max_batch=64)
    with pytest.raises(ServeAdmissionError) as ei:
        reg.load(bst, model_id="big")
    msg = str(ei.value)
    assert "10000" in msg and "budget" in msg and "residents" in msg
    ev = TELEMETRY.stats()["faults"]["events"]
    admits = [e for e in ev if e.get("kind") == "serve_admit"]
    assert admits and "rejected big" in admits[-1]["detail"]


def test_admission_names_residents(rng, monkeypatch):
    bst, X, _ = _train(rng, rounds=4)
    big, _, _ = _train(rng, rounds=60)
    ms = _fake_mem(monkeypatch, 1 << 30)
    reg = ModelRegistry(max_batch=64)
    reg.load(bst, model_id="resident0")              # admits under 1 GiB
    ms["bytes_limit"] = 10_000                       # budget collapses
    with pytest.raises(ServeAdmissionError, match="resident0"):
        reg.load(big, model_id="big")
    assert "resident0" in reg.residents()
    assert "big" not in reg.residents()


def test_admission_and_eviction_lifecycle(rng, monkeypatch):
    bst, X, _ = _train(rng, rounds=4)
    _fake_mem(monkeypatch, 1 << 30)
    sess = ServeSession(max_batch=16, max_delay_ms=0.0)
    try:
        mid = sess.load(bst, model_id="m")
        ref = sess.predict_direct(mid, X[:8])
        sess.evict(mid)
        with pytest.raises(ServeError, match="not resident"):
            sess.predict_direct(mid, X[:8])
        mid2 = sess.load(bst, model_id="m")          # re-admit
        np.testing.assert_array_equal(ref, sess.predict_direct(mid2,
                                                               X[:8]))
    finally:
        sess.close()
    ev = [e for e in TELEMETRY.stats()["faults"]["events"]
          if e.get("kind") == "serve_admit"]
    details = " | ".join(e["detail"] for e in ev)
    assert "admitted m" in details and "evicted m" in details


def test_multi_model_pack_correct_after_evict(rng):
    b1, X, _ = _train(rng)
    b2, _, _ = _train(rng, rounds=5)
    b3, _, _ = _train(rng, objective="multiclass", num_class=3, rounds=4)
    Xq = X[:20]
    with ServeSession(max_batch=32, max_delay_ms=0.0) as sess:
        ids = [sess.load(b, model_id=f"m{i}")
               for i, b in enumerate((b1, b2, b3))]
        for b, mid in zip((b1, b2, b3), ids):
            np.testing.assert_array_equal(b.predict(Xq),
                                          sess.predict_direct(mid, Xq))
        sess.evict(ids[1])                           # repack
        np.testing.assert_array_equal(b1.predict(Xq),
                                      sess.predict_direct(ids[0], Xq))
        np.testing.assert_array_equal(b3.predict(Xq),
                                      sess.predict_direct(ids[2], Xq))


# ---------------------------------------------------------- fault sites
def test_fault_enqueue_named_giveup(rng):
    bst, X, _ = _train(rng)
    with ServeSession(max_batch=16, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        FAULTS.configure("serve/enqueue")
        with pytest.raises(ServeError, match="serve/enqueue"):
            sess.predict(mid, X[:4])
        # the site healed (count=1): the queue keeps serving
        np.testing.assert_array_equal(bst.predict(X[:4]),
                                      sess.predict(mid, X[:4]))


def test_fault_compile_named_giveup_no_hang(rng):
    bst, X, _ = _train(rng)
    with ServeSession(max_batch=16, max_delay_ms=0.0,
                      queue_timeout_s=30.0) as sess:
        mid = sess.load(bst)
        FAULTS.configure("serve/compile")
        # the injected compile failure propagates to the request future
        # as a NAMED error (never a hang), then the site heals
        with pytest.raises(ServeError, match="serve/compile"):
            sess.predict(mid, X[:4])
        np.testing.assert_array_equal(bst.predict(X[:4]),
                                      sess.predict(mid, X[:4]))


def test_fault_queue_timeout_named_giveup(rng):
    bst, X, _ = _train(rng)
    with ServeSession(max_batch=16, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        # a predictor wedged mid-dispatch: the request gives up by name
        ev = threading.Event()
        sess.predictor.predict = lambda *a, **k: ev.wait(20)
        try:
            with pytest.raises(ServeError, match="gave up"):
                sess.queue.predict(mid, X[:4], timeout=0.3)
        finally:
            ev.set()


# ----------------------------------------------------- predict routing
def test_predict_device_route_bit_identical(rng):
    bst, X, _ = _train(rng)
    Xq = _queries(rng, X)
    off = bst.predict(Xq)
    bst.config.predict_device = "on"
    on = bst.predict(Xq)
    np.testing.assert_array_equal(off, on)


def test_predict_device_route_multiclass(rng):
    bst, X, _ = _train(rng, objective="multiclass", num_class=3, rounds=5)
    Xq = _queries(rng, X)
    off = bst.predict(Xq)
    bst.config.predict_device = "on"
    np.testing.assert_array_equal(off, bst.predict(Xq))


def test_predict_device_route_reuses_executable(rng):
    bst, X, _ = _train(rng)
    bst.config.predict_device = "on"
    bst.predict(X[:40])                              # compile bucket 64
    c0 = TELEMETRY.stats()["counters"].get("compile/retraces", 0)
    bst.predict(X[:50])                              # same bucket
    assert TELEMETRY.stats()["counters"].get("compile/retraces",
                                             0) == c0


def test_predict_device_auto_is_host_on_cpu(rng):
    """predict_device=auto must not engage the jit path on CPU-only
    backends (dispatch overhead would swamp the walk)."""
    bst, X, _ = _train(rng, rounds=3)
    assert bst.config.predict_device == "auto"
    assert not bst.gbdt._device_route_ok()
    bst.config.predict_device = "on"
    assert bst.gbdt._device_route_ok()


# ----------------------------------------------------------------- refit
def test_refit_parity_from_scratch_leaf_estimate(rng):
    """decay=0 refit == from-scratch leaf re-estimate: for one L2 tree,
    the refitted leaf value must equal shrinkage * mean residual of the
    rows landing in that leaf (the gradient-optimal L2 leaf)."""
    X = rng.rand(400, 4)
    y = X[:, 0] * 2 + 0.1 * rng.rand(400)
    params = {"objective": "regression", "verbose": -1, "num_leaves": 7,
              "min_data_in_leaf": 5, "lambda_l2": 0.0}
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=1)
    X2 = rng.rand(300, 4)
    y2 = X2[:, 0] * 2 + 0.1 * rng.rand(300)
    leaves = bst.predict(X2, pred_leaf=True).ravel()
    bst.refit(X2, y2, decay_rate=0.0)
    tree = bst.gbdt.models[0]
    init = bst.gbdt.init_scores[0]
    for leaf in np.unique(leaves):
        sel = leaves == leaf
        resid = np.mean(y2[sel].astype(np.float32) - np.float32(init))
        expect = tree.shrinkage * resid
        assert abs(tree.leaf_value[leaf] - expect) < 5e-4


def test_refit_decay_one_is_identity(rng):
    bst, X, y = _train(rng, rounds=5)
    before = bst.predict(X[:50])
    lv0 = [t.leaf_value.copy() for t in bst.gbdt.models]
    bst.refit(X, y, decay_rate=1.0)
    for t, lv in zip(bst.gbdt.models, lv0):
        np.testing.assert_array_equal(t.leaf_value, lv)
    np.testing.assert_array_equal(before, bst.predict(X[:50]))


def test_refit_moves_toward_new_labels(rng):
    bst, X, y = _train(rng)
    rng2 = np.random.RandomState(7)
    X2, _ = _make_mixed(rng2, n=500)
    y2 = 1.0 - (np.nan_to_num(X2[:, 0]) > 0)         # contrarian labels
    before = float(np.mean((bst.predict(X2) - y2) ** 2))
    bst.refit(X2, y2, decay_rate=0.1)
    after = float(np.mean((bst.predict(X2) - y2) ** 2))
    assert after < before


# --------------------------------------------------------------- errors
def test_serve_rejects_wrong_width(rng):
    bst, X, _ = _train(rng)
    with ServeSession(max_batch=16, max_delay_ms=0.0) as sess:
        mid = sess.load(bst)
        with pytest.raises(ServeError, match="features"):
            sess.predict_direct(mid, X[:4, :5])


def test_serve_duplicate_model_id(rng):
    bst, _, _ = _train(rng, rounds=3)
    reg = ModelRegistry()
    reg.load(bst, model_id="m")
    with pytest.raises(ServeError, match="already"):
        reg.load(bst, model_id="m")


def test_registry_unknown_model_names_loaded(rng):
    bst, _, _ = _train(rng, rounds=3)
    reg = ModelRegistry()
    reg.load(bst, model_id="alpha")
    with pytest.raises(ServeError, match="alpha"):
        reg.entry("beta")
