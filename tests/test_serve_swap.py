"""Hot-swap, overload-shedding and refit-loop tests (serve robustness).

The contracts under test, from ISSUE 20:

  * ``ServeSession.swap`` replaces a resident model with zero request
    failures: in-flight work completes against the version live at its
    dispatch (bit-identical to that generation's ``Booster.predict``),
    and swapping one model never retraces the executables of untouched
    residents.
  * The quality gate keeps a bad candidate out (non-finite outputs,
    holdout-metric regression, or an injected ``serve/swap`` fault at
    the flip) — the old model keeps serving bit-identically and a
    ``swap_rejected`` record lands in the health stream.
    ``rollback()`` restores the retained previous generation exactly.
  * The bounded queue sheds overload with a named
    ``ServeOverloadError`` while admitted requests still complete; an
    injected RESOURCE_EXHAUSTED at dispatch is retried at half batch
    with replies bit-identical to the unsplit dispatch.
  * ``evict()`` fails still-queued requests eagerly by name; a worker
    wedged at ``close()`` fails its futures by name instead of
    dropping them.
  * ``RefitLoop`` closes the drift→refit→gated-swap loop and survives
    faulted attempts.
"""

import json
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import (RefitLoop, ServeError, ServeOverloadError,
                                ServeSession, SwapRejectedError)
from lightgbm_tpu.utils.faults import FAULTS
from lightgbm_tpu.utils.telemetry import TELEMETRY


@pytest.fixture(autouse=True)
def _clean():
    TELEMETRY.reset()
    TELEMETRY.set_config_level(1)
    TELEMETRY.install_jax_listeners()
    yield
    FAULTS.configure()


def _make(rng, n=500, f=8):
    X = rng.normal(size=(n, f))
    X[:, 3] = rng.randint(0, 6, size=n)
    X[rng.rand(n) < 0.15, 1] = np.nan
    y = (np.nan_to_num(X[:, 0] + X[:, 1]) + (X[:, 3] % 2) > 0.6
         ).astype(np.float64)
    return X, y


def _train(rng, rounds=10, n=500):
    X, y = _make(rng, n=n)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15}
    ds = lgb.Dataset(X, y, categorical_feature=[3])
    return lgb.train(params, ds, num_boost_round=rounds), X, y


def _counters():
    return TELEMETRY.stats()["counters"]


# --------------------------------------------------------- atomic swap
def test_swap_bit_identical_and_flat_retraces_for_untouched(rng):
    """Three refit→swap cycles on model A while predicting model B:
    B's compiled executables never retrace (same pack shapes, per-model
    epoch bump only), and after each flip A serves the NEW generation
    bit-identically."""
    bstA, X, y = _train(rng)
    bstB, _, _ = _train(rng, rounds=6)
    Xq = X[:48].copy()
    refB = bstB.predict(Xq)
    with ServeSession(max_batch=64, max_delay_ms=0.0) as sess:
        a = sess.load(bstA, model_id="a")
        b = sess.load(bstB, model_id="b")
        # warm one FULL cycle: executables for both models, refit's
        # one-time jits, and the in-place pack-row update.  (The
        # swapped model itself recompiles once per epoch by design —
        # the flat-retrace contract is for UNTOUCHED residents.)
        sess.predict_direct(a, Xq)
        sess.predict_direct(b, Xq)
        Xw, yw = _make(rng, n=300)
        bstA.refit(Xw, yw, decay_rate=0.3)
        sess.swap(a, bstA, gated=False)              # warmup swap
        sess.predict_direct(a, Xq)
        sess.predict_direct(b, Xq)
        for i in range(3):
            X2, y2 = _make(rng, n=300)
            bstA.refit(X2, y2, decay_rate=0.3)
            ref_new = bstA.predict(Xq)
            pause = sess.swap(a, bstA, gated=False)
            assert pause >= 0.0
            # untouched model B: bit-identical, zero retraces
            c0 = _counters().get("compile/retraces", 0)
            np.testing.assert_array_equal(refB, sess.predict_direct(b, Xq))
            assert _counters().get("compile/retraces", 0) == c0
            # A serves the freshly flipped generation exactly
            np.testing.assert_array_equal(ref_new,
                                          sess.predict_direct(a, Xq))
        assert sess.registry.epoch_of(a) == 4
        assert sess.registry.epoch_of(b) == 0
        assert len(sess.registry.swap_pauses) == 4
    assert _counters()["serve/swaps"] == 4


def test_swap_under_load_zero_failures(rng):
    """Worker threads hammer model A through the queue while the main
    thread runs 3 refit→swap cycles: zero failed replies, and every
    reply is bit-identical to SOME generation that was live (requests
    complete against the snapshot pinned at their dispatch)."""
    bstA, X, _ = _train(rng)
    Xq = X[:32].copy()
    with ServeSession(max_batch=64, max_delay_ms=0.0) as sess:
        a = sess.load(bstA, model_id="a")
        refs = [bstA.predict(Xq)]
        sess.predict(a, Xq)                          # compile before load
        errors, mismatches, stop = [], [], threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    out = sess.predict(a, Xq, timeout=30)
                except Exception as exc:             # pragma: no cover
                    errors.append(exc)
                    return
                if not any(np.array_equal(out, r) for r in refs):
                    mismatches.append(out)           # pragma: no cover
                    return

        workers = [threading.Thread(target=hammer) for _ in range(3)]
        for w in workers:
            w.start()
        try:
            for _ in range(3):
                X2, y2 = _make(rng, n=300)
                bstA.refit(X2, y2, decay_rate=0.3)
                refs.append(bstA.predict(Xq))        # before the flip
                sess.swap(a, bstA, gated=False)
        finally:
            stop.set()
            for w in workers:
                w.join(timeout=30)
        assert not errors
        assert not mismatches
        assert all(p >= 0.0 for p in sess.registry.swap_pauses)


# --------------------------------------------------------- quality gate
def test_swap_rejected_nonfinite_candidate(rng, tmp_path):
    bstA, X, _ = _train(rng)
    bad, _, _ = _train(rng, rounds=6)
    bad.gbdt.models[0].leaf_value = np.array(
        bad.gbdt.models[0].leaf_value, dtype=np.float64)
    bad.gbdt.models[0].leaf_value[0] = np.nan
    Xq = X[:24].copy()
    ref = bstA.predict(Xq)
    hpath = tmp_path / "serve_health.jsonl"
    with ServeSession(max_batch=32, max_delay_ms=0.0,
                      health_out=str(hpath)) as sess:
        a = sess.load(bstA, model_id="a")
        with pytest.raises(SwapRejectedError, match="non-finite"):
            sess.swap(a, bad, holdout=Xq)
        # the old generation never stopped serving
        np.testing.assert_array_equal(ref, sess.predict_direct(a, Xq))
        assert sess.registry.epoch_of(a) == 0
    kinds = [json.loads(line)["kind"]
             for line in hpath.read_text().splitlines()]
    assert "swap_begin" in kinds and "swap_rejected" in kinds
    assert "swap_flip" not in kinds
    assert _counters()["serve/swap_rejected"] == 1


def test_swap_rejected_metric_regression(rng):
    bstA, X, y = _train(rng)
    # a candidate fit to SHUFFLED labels: finite but strictly worse
    yr = y.copy()
    rng.shuffle(yr)
    ds = lgb.Dataset(X, yr, categorical_feature=[3])
    worse = lgb.train({"objective": "binary", "verbose": -1,
                       "num_leaves": 15}, ds, num_boost_round=10)
    Xq, yq = X[:200], y[:200]
    ref = bstA.predict(Xq)
    with ServeSession(max_batch=32, max_delay_ms=0.0) as sess:
        a = sess.load(bstA, model_id="a")
        with pytest.raises(SwapRejectedError, match="regressed"):
            sess.swap(a, worse, holdout=Xq, label=yq,
                      quality_threshold=0.05)
        np.testing.assert_array_equal(ref, sess.predict_direct(a, Xq))


def test_swap_gate_uses_replay_reservoir(rng):
    """With no explicit holdout the gate shadow-scores on the
    deterministic reservoir of recently served rows."""
    bstA, X, _ = _train(rng)
    cand, _, _ = _train(rng, rounds=8)
    with ServeSession(max_batch=32, max_delay_ms=0.0) as sess:
        a = sess.load(bstA, model_id="a")
        sess.predict_direct(a, X[:100])              # feeds the reservoir
        assert sess.registry.replay_rows(a) is not None
        sess.swap(a, cand)                           # gated, finite: flips
        np.testing.assert_array_equal(cand.predict(X[:16]),
                                      sess.predict_direct(a, X[:16]))


def test_swap_fault_at_flip_keeps_old_serving(rng):
    bstA, X, _ = _train(rng)
    cand, _, _ = _train(rng, rounds=6)
    Xq = X[:24].copy()
    ref = bstA.predict(Xq)
    with ServeSession(max_batch=32, max_delay_ms=0.0) as sess:
        a = sess.load(bstA, model_id="a")
        FAULTS.configure("serve/swap")
        with pytest.raises(SwapRejectedError, match="serve/swap"):
            sess.swap(a, cand, gated=False)
        np.testing.assert_array_equal(ref, sess.predict_direct(a, Xq))
        # the site healed: the next swap goes through
        sess.swap(a, cand, gated=False)
        np.testing.assert_array_equal(cand.predict(Xq),
                                      sess.predict_direct(a, Xq))


def test_rollback_restores_previous_generation(rng):
    bstA, X, _ = _train(rng)
    cand, _, _ = _train(rng, rounds=6)
    Xq = X[:24].copy()
    ref0 = bstA.predict(Xq)
    with ServeSession(max_batch=32, max_delay_ms=0.0) as sess:
        a = sess.load(bstA, model_id="a")
        sess.predict_direct(a, Xq)
        sess.swap(a, cand, gated=False)
        np.testing.assert_array_equal(cand.predict(Xq),
                                      sess.predict_direct(a, Xq))
        sess.rollback(a)
        np.testing.assert_array_equal(ref0, sess.predict_direct(a, Xq))
        # ping-pong: the rollback retained the swapped-in generation
        sess.rollback(a)
        np.testing.assert_array_equal(cand.predict(Xq),
                                      sess.predict_direct(a, Xq))
    assert _counters()["serve/rollbacks"] == 2


def test_rollback_without_previous_generation_errors(rng):
    bstA, _, _ = _train(rng, rounds=4)
    with ServeSession(max_batch=16, max_delay_ms=0.0) as sess:
        a = sess.load(bstA, model_id="a")
        with pytest.raises(ServeError, match="no retained"):
            sess.rollback(a)


# ------------------------------------------------------------- overload
def test_overload_sheds_excess_admits_complete(rng):
    bstA, X, _ = _train(rng)
    with ServeSession(max_batch=256, max_delay_ms=400.0,
                      max_queue_rows=8) as sess:
        a = sess.load(bstA, model_id="a")
        # 8 rows fill the bound while the 400ms coalescing window holds
        # them queued; the next submit must shed, not block or drop
        f1 = sess.submit(a, X[:8])
        with pytest.raises(ServeOverloadError, match="serve_max_queue_rows"):
            sess.submit(a, X[8:12])
        np.testing.assert_array_equal(bstA.predict(X[:8]),
                                      f1.result(timeout=30))
        # capacity freed: the queue admits again
        np.testing.assert_array_equal(bstA.predict(X[:4]),
                                      sess.predict(a, X[:4]))
    c = _counters()
    assert c["serve/shed_requests"] == 1
    assert c["serve/shed_rows"] == 4


def test_forced_shed_fault_site(rng):
    bstA, X, _ = _train(rng)
    with ServeSession(max_batch=32, max_delay_ms=0.0) as sess:
        a = sess.load(bstA, model_id="a")
        FAULTS.configure("serve/shed")
        with pytest.raises(ServeOverloadError, match="serve/shed"):
            sess.predict(a, X[:4])
        np.testing.assert_array_equal(bstA.predict(X[:4]),
                                      sess.predict(a, X[:4]))


def test_oom_retry_halves_batch_bit_identical(rng):
    """An injected RESOURCE_EXHAUSTED at dispatch: the ladder halves
    the batch, retries, and the stitched replies are bit-identical to
    the unsplit dispatch."""
    bstA, X, _ = _train(rng)
    Xq = X[:16].copy()
    ref = bstA.predict(Xq)
    with ServeSession(max_batch=16, max_delay_ms=0.0) as sess:
        a = sess.load(bstA, model_id="a")
        FAULTS.configure("serve/oom")
        np.testing.assert_array_equal(ref, sess.predict_direct(a, Xq))
        assert sess.predictor._batch_cap == 8        # sticky half
        # subsequent traffic keeps working at the reduced cap
        np.testing.assert_array_equal(ref, sess.predict_direct(a, Xq))
    c = _counters()
    assert c["serve/oom_halvings"] == 1
    ev = [e for e in TELEMETRY.stats()["faults"]["events"]
          if e.get("kind") == "serve_oom"]
    assert ev and "serve/oom" in ev[-1].get("site", "")


# --------------------------------------------------- queue degradation
def test_evict_fails_queued_requests_by_name(rng):
    bstA, X, _ = _train(rng)
    with ServeSession(max_batch=256, max_delay_ms=400.0) as sess:
        a = sess.load(bstA, model_id="a")
        fut = sess.submit(a, X[:8])                  # held by the window
        sess.evict(a)
        with pytest.raises(ServeError, match="evicted while queued"):
            fut.result(timeout=30)
    assert _counters()["serve/evicted_queued"] == 1


def test_close_wedged_worker_fails_futures_by_name(rng):
    bstA, X, _ = _train(rng)
    sess = ServeSession(max_batch=16, max_delay_ms=0.0)
    release = threading.Event()
    try:
        a = sess.load(bstA, model_id="a")
        sess.predict(a, X[:4])                       # healthy first

        def wedge(*args, **kwargs):
            release.wait(30)
            raise ServeError("released after close")

        sess.predictor.predict = wedge
        fut = sess.submit(a, X[:4])
        # wait until the worker has actually taken the batch (close()
        # would otherwise win the race and fail it as merely pending)
        for _ in range(200):
            if sess.queue._current is not None:
                break
            time.sleep(0.01)
        assert sess.queue._current is not None
        sess.queue.join_timeout_s = 0.3
        sess.close()
        with pytest.raises(ServeError, match="wedged at close"):
            fut.result(timeout=30)
        assert _counters()["serve/wedged_close"] == 1
    finally:
        release.set()


# ------------------------------------------------------------ refit loop
def _drifted_session(rng, psi_threshold=0.05):
    bst, X, y = _train(rng)
    sess = ServeSession(max_batch=256, max_delay_ms=0.0,
                        drift_detect=True,
                        drift_psi_threshold=psi_threshold)
    mid = sess.load(bst, model_id="m")
    # shift the numeric columns hard: served occupancy piles into the
    # extreme bins, PSI blows past any sane threshold
    Xs = X[:256].copy()
    Xs[:, [0, 1, 2, 5, 6, 7]] += 4.0
    ys = (np.nan_to_num(Xs[:, 0] + Xs[:, 1]) + (Xs[:, 3] % 2) > 0.6
          ).astype(np.float64)
    sess.predict_direct(mid, Xs)                     # accumulate drift
    return bst, sess, mid, Xs, ys


def test_refit_loop_requires_drift_gate(rng):
    bst, X, _ = _train(rng, rounds=4)
    with ServeSession(max_batch=16, max_delay_ms=0.0) as sess:
        sess.load(bst, model_id="m")
        with pytest.raises(ServeError, match="drift_detect"):
            RefitLoop(sess, "m", bst, lambda: None)


def test_refit_loop_drift_to_swap_end_to_end(rng):
    bst, sess, mid, Xs, ys = _drifted_session(rng)
    try:
        assert sess.drift_gate.drifted(mid)
        loop = RefitLoop(sess, mid, bst, lambda: (Xs, ys),
                         quality_threshold=5.0)
        assert loop.run_once() == "swapped"
        # the swap re-registered the drift state: with no traffic since
        # the flip, the trigger does not immediately re-fire.  (Checked
        # BEFORE any further predicts — the traffic really is shifted,
        # so new rows legitimately re-arm the gate.)
        assert loop.run_once() == "idle"
        assert loop.swaps == 1
        # the refitted generation is live and bit-identical
        np.testing.assert_array_equal(bst.predict(Xs[:16]),
                                      sess.predict_direct(mid, Xs[:16]))
        assert sess.registry.epoch_of(mid) == 1
    finally:
        sess.close()
    assert _counters()["serve/refits"] == 1


def test_refit_loop_survives_injected_fault(rng):
    bst, sess, mid, Xs, ys = _drifted_session(rng)
    try:
        lv0 = [np.array(t.leaf_value) for t in bst.gbdt.models]
        ref = sess.predict_direct(mid, Xs[:16])
        loop = RefitLoop(sess, mid, bst, lambda: (Xs, ys),
                         quality_threshold=5.0)
        FAULTS.configure("serve/refit")
        assert loop.run_once() == "fault"
        # the booster and the served model are both untouched
        for t, lv in zip(bst.gbdt.models, lv0):
            np.testing.assert_array_equal(t.leaf_value, lv)
        np.testing.assert_array_equal(ref,
                                      sess.predict_direct(mid, Xs[:16]))
        # the site healed and the drift signal is still armed
        assert loop.run_once() == "swapped"
        assert (loop.faults, loop.swaps) == (1, 1)
    finally:
        sess.close()
    assert _counters()["serve/refit_faults"] == 1


def test_refit_loop_thread_lifecycle(rng):
    bst, sess, mid, Xs, ys = _drifted_session(rng)
    try:
        loop = sess.start_refit_loop(mid, bst, lambda: (Xs, ys),
                                     poll_s=0.02, quality_threshold=5.0,
                                     max_refits=1)
        deadline = threading.Event()
        for _ in range(200):                         # ≤ 4s
            if loop.swaps >= 1:
                break
            deadline.wait(0.02)
        assert loop.swaps == 1
        assert sess.registry.epoch_of(mid) == 1
    finally:
        sess.close()                                 # stops the loop
    assert not loop._thread.is_alive()
