"""Distributed tree-learner tests on the virtual 8-device CPU mesh.

The reference cannot test its parallel learners in one process (SURVEY.md
§4: no mock network; real multi-machine launches only).  Here the same
shard_map code path that runs on a TPU pod runs on 8 virtual CPU devices,
so data-/feature-/voting-parallel are exercised in-process and compared
against the serial learner.
"""

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb


def make_data(rng, n=2000, f=10):
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + (X[:, 2] > 0) + \
        rng.normal(size=n) * 0.1
    return X, y


@pytest.fixture(scope="module")
def devices():
    return jax.devices()


def _train(X, y, tree_learner, **extra):
    params = {"objective": "regression", "verbose": -1, "num_leaves": 15,
              "tree_learner": tree_learner, "max_bin": 63, "seed": 5}
    params.update(extra)
    ds = lgb.Dataset(X, y)
    return lgb.train(params, ds, num_boost_round=20, verbose_eval=False)


def test_mesh_available(devices):
    assert len(devices) == 8, "conftest should provide 8 virtual devices"


def test_data_parallel_matches_serial(rng):
    X, y = make_data(rng)
    serial = _train(X, y, "serial")
    data = _train(X, y, "data")
    ps = serial.predict(X)
    pd = data.predict(X)
    # identical split decisions up to float reduction order
    np.testing.assert_allclose(ps, pd, rtol=1e-3, atol=1e-4)
    mse = float(np.mean((pd - y) ** 2))
    assert mse < 0.1 * y.var()


def test_feature_parallel_matches_serial(rng):
    X, y = make_data(rng)
    serial = _train(X, y, "serial")
    feat = _train(X, y, "feature")
    np.testing.assert_allclose(serial.predict(X), feat.predict(X),
                               rtol=1e-3, atol=1e-4)


def test_voting_parallel_trains(rng):
    X, y = make_data(rng, n=4000)
    vot = _train(X, y, "voting", top_k=5)
    mse = float(np.mean((vot.predict(X) - y) ** 2))
    assert mse < 0.15 * y.var()


def test_voting_parallel_active_mask(rng):
    """top_k small enough that 2*top_k < num_features, so the election
    mask actually restricts candidates (the regression that shipped with
    an all-ones mask went unseen)."""
    X, y = make_data(rng, n=4000)
    vot = _train(X, y, "voting", top_k=2)
    mse = float(np.mean((vot.predict(X) - y) ** 2))
    assert mse < 0.2 * y.var()


def test_data_parallel_uneven_rows(rng):
    # 2003 % 8 != 0: exercises the zero-member row padding
    X, y = make_data(rng, n=2003)
    data = _train(X, y, "data")
    assert float(np.mean((data.predict(X) - y) ** 2)) < 0.1 * y.var()


def test_data_parallel_binary(rng):
    X = rng.normal(size=(2000, 8))
    yb = (X[:, 0] + X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
              "tree_learner": "data"}
    bst = lgb.train(params, lgb.Dataset(X, yb), num_boost_round=15,
                    verbose_eval=False)
    acc = np.mean((bst.predict(X) > 0.5) == yb)
    assert acc > 0.9


def test_data_parallel_segment_matches_serial_segment(rng):
    """The distributed segment grower (psum_scatter stripes + max-gain
    SplitInfo merge) must grow the same trees as the serial segment grower
    (VERDICT r2 item 3: O(leaf) per-split cost must survive sharding)."""
    X, y = make_data(rng, n=3000, f=9)
    serial = _train(X, y, "serial", tpu_histogram_backend="pallas",
                    tpu_tree_impl="segment", tpu_row_chunk=256)
    assert serial.gbdt._use_segment
    data = _train(X, y, "data", tpu_histogram_backend="pallas",
                  tpu_tree_impl="segment", tpu_row_chunk=256)
    assert data.gbdt._use_segment
    np.testing.assert_allclose(serial.predict(X), data.predict(X),
                               rtol=1e-3, atol=1e-4)
    # same tree shapes — the split decisions matched, not just the fit
    for ts, td in zip(serial.gbdt.models, data.gbdt.models):
        assert ts.num_leaves == td.num_leaves


def test_data_parallel_segment_binary_uneven(rng):
    X, y = make_data(rng, n=2507, f=6)
    yb = (y > np.median(y)).astype(float)
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1, "num_leaves": 15, "tree_learner": "data",
              "max_bin": 31, "tpu_histogram_backend": "pallas",
              "tpu_tree_impl": "segment", "tpu_row_chunk": 128}
    ds = lgb.Dataset(X, yb)
    bst = lgb.train(params, ds, num_boost_round=15, verbose_eval=False)
    assert bst.gbdt._use_segment
    p = bst.predict(X)
    ll = -np.mean(yb * np.log(p + 1e-9) + (1 - yb) * np.log(1 - p + 1e-9))
    assert ll < 0.6   # better than chance on a learnable target


def test_data_parallel_segment_packed4(rng):
    """Sharded segment grower with the 4-bit packed layout (max_bin<=15
    activates packing; rows shard, packed columns replicate per shard)."""
    X, y = make_data(rng, n=2600, f=7)
    serial = _train(X, y, "serial", tpu_histogram_backend="pallas",
                    tpu_tree_impl="segment", tpu_row_chunk=128, max_bin=15)
    assert serial.gbdt.grower_params.packed4
    data = _train(X, y, "data", tpu_histogram_backend="pallas",
                  tpu_tree_impl="segment", tpu_row_chunk=128, max_bin=15)
    assert data.gbdt._use_segment and data.gbdt.grower_params.packed4
    np.testing.assert_allclose(serial.predict(X), data.predict(X),
                               rtol=1e-3, atol=1e-4)


def test_voting_parallel_with_bundling(rng):
    """Voting election over an EFB-bundled dataset: votes are cast in
    feature space on locally-expanded histograms, reduced in column
    space (learners.reduce_voted)."""
    n, width, blocks = 2400, 10, 6
    X = np.zeros((n, width * blocks))
    picks = rng.randint(0, width, size=(n, blocks))
    for b in range(blocks):
        X[np.arange(n), b * width + picks[:, b]] = rng.normal(2, 1, n)
    yb = (X[:, :width].sum(1) - X[:, width:2 * width].sum(1) > 0).astype(float)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
              "tree_learner": "voting", "min_data_in_leaf": 5, "top_k": 8}
    ds = lgb.Dataset(X, yb, params=params)
    bst = lgb.train(params, ds, num_boost_round=15, verbose_eval=False)
    assert ds._handle.bundle is not None
    p = bst.predict(X)
    ll = -np.mean(yb * np.log(p + 1e-9) + (1 - yb) * np.log(1 - p + 1e-9))
    assert ll < 0.55


def test_balanced_stripes_by_bins():
    """Stripe boundaries cut per-shard Σbins skew (the reference balances
    feature-parallel shards by #bins,
    feature_parallel_tree_learner.cpp:36-47) while the width cap bounds
    every shard's static histogram block at 2x the even split."""
    from lightgbm_tpu.parallel.learners import _balanced_stripes
    rng = np.random.RandomState(0)
    # EFB-like skew: a few fat bundled columns among many tiny ones
    cb = np.concatenate([np.full(4, 255), rng.randint(2, 8, size=60)])
    D = 8
    starts, widths, per = _balanced_stripes(cb, D)
    sums = np.asarray([cb[s:s + w].sum() for s, w in zip(starts, widths)])
    assert sums.sum() == cb.sum()           # partition covers every column
    even = -(-len(cb) // D)
    assert per <= 2 * even                   # histogram block stays bounded
    ideal = cb.sum() / D
    # a fat column alone is ~2x the ideal shard load and the width cap
    # forces the small-column tail onto few shards, so the capped optimum
    # is one fat column + a slice of tail, not perfect balance
    assert sums.max() <= 1.5 * max(cb.max(), ideal), (sums, ideal)
    # and the even split must be far WORSE on this profile
    even_sums = np.asarray([cb[i * even:(i + 1) * even].sum()
                            for i in range(D)])
    assert sums.max() < 0.5 * even_sums.max()

    # a profile the even split already handles optimally is never worsened
    s2, w2, p2 = _balanced_stripes(np.asarray([3, 5]), 2)
    assert list(w2) == [1, 1] and p2 == 1

    # degenerate: one giant column among few — no empty-shard blowup
    s3, w3, p3 = _balanced_stripes(np.asarray([10000] + [1] * 15), 4)
    assert w3.sum() == 16 and p3 <= 2 * 4


def test_feature_parallel_skewed_bundles(rng):
    """Feature-parallel over an EFB dataset whose bundles concentrate
    bins in few physical columns still matches the serial learner."""
    n = 2000
    # 3 dense high-cardinality features + 40 sparse one-hot-ish columns
    # that EFB packs into few bundles
    dense = rng.normal(size=(n, 3))
    width, blocks = 10, 4
    sparse = np.zeros((n, width * blocks))
    picks = rng.randint(0, width, size=(n, blocks))
    for b in range(blocks):
        sparse[np.arange(n), b * width + picks[:, b]] = rng.normal(2, 1, n)
    X = np.hstack([dense, sparse])
    y = dense[:, 0] * 2 + sparse[:, :width].sum(1) \
        + rng.normal(size=n) * 0.1
    serial = _train(X, y, "serial", max_bin=255, min_data_in_leaf=5)
    feat = _train(X, y, "feature", max_bin=255, min_data_in_leaf=5)
    assert feat.gbdt.train_set.bundle is not None, \
        "EFB must bundle the sparse block or this test covers nothing"
    np.testing.assert_allclose(serial.predict(X), feat.predict(X),
                               rtol=1e-3, atol=1e-4)


def test_data_parallel_frontier_matches_serial_frontier(rng):
    """Frontier grower under shard_map (rows sharded, one reduce-scatter
    per K-leaf round) == serial frontier grower, same batch width."""
    X, y = make_data(rng, n=2600, f=7)
    serial = _train(X, y, "serial", tpu_histogram_backend="pallas",
                    tpu_tree_impl="frontier", tpu_row_chunk=128,
                    tpu_frontier_width=4)
    data = _train(X, y, "data", tpu_histogram_backend="pallas",
                  tpu_tree_impl="frontier", tpu_row_chunk=128,
                  tpu_frontier_width=4)
    np.testing.assert_allclose(serial.predict(X), data.predict(X),
                               rtol=1e-3, atol=1e-4)
    for ts, td in zip(serial.gbdt.models, data.gbdt.models):
        assert ts.num_leaves == td.num_leaves


def test_seg_stats_under_data_parallel(rng, monkeypatch, capfd):
    """Under the data-parallel wrappers the per-device counters come back
    stacked (out_specs P(axis)); one printed row per device."""
    monkeypatch.setenv("LIGHTGBM_TPU_SEG_STATS", "1")
    n = 4000
    X = rng.normal(size=(n, 6))
    y = X[:, 0] + 0.5 * X[:, 1] + rng.normal(size=n) * 0.1
    bst = _train(X, y, "data", tpu_histogram_backend="pallas",
                 tpu_tree_impl="segment", tpu_row_chunk=256)
    assert bst.gbdt._use_segment
    err = capfd.readouterr().err
    rows = [ln for ln in err.splitlines() if "seg stats" in ln]
    assert len(rows) >= 8, err[:2000]
    assert any("dev7" in ln for ln in rows), rows[:9]


def test_feature_parallel_segment_matches_serial_segment(rng):
    """Feature-parallel on the O(leaf) segment grower (VERDICT r4 item
    6): data replicated, per-shard column-stripe histograms over the
    leaf's confinement interval, max-gain SplitInfo merge — same trees
    as the serial segment grower (the reference's feature-parallel
    learner inherits the serial O(leaf) machinery,
    feature_parallel_tree_learner.cpp:74-75)."""
    X, y = make_data(rng, n=3000, f=9)
    serial = _train(X, y, "serial", tpu_histogram_backend="pallas",
                    tpu_tree_impl="segment", tpu_row_chunk=256)
    assert serial.gbdt._use_segment
    feat = _train(X, y, "feature", tpu_histogram_backend="pallas",
                  tpu_tree_impl="segment", tpu_row_chunk=256)
    assert feat.gbdt._use_segment
    np.testing.assert_allclose(serial.predict(X), feat.predict(X),
                               rtol=1e-3, atol=1e-4)
    for ts, tf in zip(serial.gbdt.models, feat.gbdt.models):
        assert ts.num_leaves == tf.num_leaves


def test_feature_parallel_frontier_matches_serial_frontier(rng):
    X, y = make_data(rng, n=2600, f=7)
    serial = _train(X, y, "serial", tpu_histogram_backend="pallas",
                    tpu_tree_impl="frontier", tpu_row_chunk=128,
                    tpu_frontier_width=4)
    feat = _train(X, y, "feature", tpu_histogram_backend="pallas",
                  tpu_tree_impl="frontier", tpu_row_chunk=128,
                  tpu_frontier_width=4)
    assert feat.gbdt._use_segment
    np.testing.assert_allclose(serial.predict(X), feat.predict(X),
                               rtol=1e-3, atol=1e-4)


def test_voting_parallel_segment_full_election_matches_serial(rng):
    """With top_k >= F every feature is elected, so voting-parallel on
    the segment grower must equal the serial segment grower exactly —
    the no-subtract both-children path and the voted psum reduce under
    row sharding are the only moving parts."""
    X, y = make_data(rng, n=3000, f=9)
    serial = _train(X, y, "serial", tpu_histogram_backend="pallas",
                    tpu_tree_impl="segment", tpu_row_chunk=256)
    vote = _train(X, y, "voting", tpu_histogram_backend="pallas",
                  tpu_tree_impl="segment", tpu_row_chunk=256, top_k=20)
    assert vote.gbdt._use_segment
    np.testing.assert_allclose(serial.predict(X), vote.predict(X),
                               rtol=1e-3, atol=1e-4)


def test_voting_parallel_segment_quality_bound(rng):
    """PV-Tree's approximation quality claim, in-process: a REAL election
    (top_k < F) must stay within a few percent of the exact data-parallel
    learner on heldout loss (VERDICT r4 weak item: voting previously had
    only trains-level assertions)."""
    X, y = make_data(rng, n=3000, f=10)
    yb = (y > np.median(y)).astype(float)
    kw = dict(tpu_histogram_backend="pallas", tpu_tree_impl="segment",
              tpu_row_chunk=256, objective="binary")
    data = _train(X, yb, "data", **kw)
    vote = _train(X, yb, "voting", top_k=3, **kw)
    assert vote.gbdt._use_segment

    def ll(b):
        p = np.clip(b.predict(X), 1e-9, 1 - 1e-9)
        return -np.mean(yb * np.log(p) + (1 - yb) * np.log(1 - p))

    assert ll(vote) < ll(data) * 1.10 + 0.02


def test_voting_parallel_frontier_trains(rng):
    X, y = make_data(rng, n=2600, f=7)
    vote = _train(X, y, "voting", tpu_histogram_backend="pallas",
                  tpu_tree_impl="frontier", tpu_row_chunk=128,
                  tpu_frontier_width=4, top_k=20)
    assert vote.gbdt._use_segment
    mse = float(np.mean((vote.predict(X) - y) ** 2))
    assert mse < 0.1 * y.var()
