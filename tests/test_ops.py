import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.core.binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO
from lightgbm_tpu.ops.histogram import leaf_histogram
from lightgbm_tpu.ops.split import (FeatureMeta, SplitParams, best_split,
                                    leaf_gain, leaf_output)


def np_hist(bins, g, h, m, B):
    F = bins.shape[1]
    out = np.zeros((F, B, 3))
    for f in range(F):
        for b in range(B):
            sel = (bins[:, f] == b)
            out[f, b] = [(g * m)[sel].sum(), (h * m)[sel].sum(), m[sel].sum()]
    return out


def test_histogram_matches_bruteforce(rng):
    n, f, B = 1000, 4, 16
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.5, 1.5, size=n).astype(np.float32)
    m = (rng.uniform(size=n) < 0.7).astype(np.float32)
    got = np.asarray(leaf_histogram(jnp.asarray(bins), jnp.asarray(g),
                                    jnp.asarray(h), jnp.asarray(m), B))
    want = np_hist(bins, g, h, m, B)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_histogram_chunking_consistent(rng):
    n, f, B = 5000, 3, 8
    bins = rng.randint(0, B, size=(n, f)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    m = np.ones(n, dtype=np.float32)
    a = np.asarray(leaf_histogram(jnp.asarray(bins), jnp.asarray(g),
                                  jnp.asarray(h), jnp.asarray(m), B,
                                  row_chunk=512))
    b = np.asarray(leaf_histogram(jnp.asarray(bins), jnp.asarray(g),
                                  jnp.asarray(h), jnp.asarray(m), B,
                                  row_chunk=0))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-3)


def _meta(F, B, missing=MISSING_NONE, default_bin=0, is_cat=False, mono=0):
    return FeatureMeta(
        num_bin=jnp.full(F, B, dtype=jnp.int32),
        missing_type=jnp.full(F, missing, dtype=jnp.int32),
        default_bin=jnp.full(F, default_bin, dtype=jnp.int32),
        is_cat=jnp.full(F, is_cat, dtype=bool),
        monotone=jnp.full(F, mono, dtype=jnp.int32),
        penalty=jnp.ones(F, dtype=jnp.float32),
    )


def np_best_split_simple(hist, G, H, C, l1, l2, min_data, min_hess):
    """Brute-force numerical best split, missing=None, single feature set."""
    F, B, _ = hist.shape
    best = (-np.inf, -1, -1)

    def out(G, H):
        s = np.sign(G) * max(abs(G) - l1, 0)
        return -s / (H + l2) if H + l2 > 0 else 0.0

    def gain1(G, H):
        o = out(G, H)
        s = np.sign(G) * max(abs(G) - l1, 0)
        return -(2 * s * o + (H + l2) * o * o)

    shift = gain1(G, H)
    for f in range(F):
        for t in range(B - 1):
            lg, lh, lc = hist[f, : t + 1].sum(axis=0)
            rg, rh, rc = G - lg, H - lh, C - lc
            if lc < min_data or rc < min_data or lh < min_hess or rh < min_hess:
                continue
            gain = gain1(lg, lh) + gain1(rg, rh)
            if gain <= shift:
                continue
            if gain - shift > best[0]:
                best = (gain - shift, f, t)
    return best


def test_best_split_matches_bruteforce(rng):
    F, B = 5, 16
    n = 2000
    bins = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    # plant signal on feature 2: bins >= 8 have positive gradients
    g = rng.normal(size=n).astype(np.float32) * 0.1
    g += np.where(bins[:, 2] >= 8, 1.0, -1.0).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    m = np.ones(n, dtype=np.float32)
    hist = np.asarray(leaf_histogram(jnp.asarray(bins), jnp.asarray(g),
                                     jnp.asarray(h), jnp.asarray(m), B))
    G, H, C = g.sum(), h.sum(), float(n)
    p = SplitParams(min_data_in_leaf=20, min_sum_hessian_in_leaf=1e-3)
    info = best_split(jnp.asarray(hist), G, H, C, _meta(F, B), p,
                      jnp.ones(F))
    want_gain, want_f, want_t = np_best_split_simple(
        hist.astype(np.float64), G, H, C, 0.0, 0.0, 20, 1e-3)
    assert int(info.feature) == want_f == 2
    assert int(info.threshold) == want_t == 7
    assert float(info.gain) == pytest.approx(want_gain, rel=1e-3)
    # split stats consistency
    assert float(info.left_c) + float(info.right_c) == pytest.approx(n)
    assert float(info.left_g) + float(info.right_g) == pytest.approx(G, rel=1e-4)


def test_best_split_respects_min_data(rng):
    F, B, n = 1, 4, 100
    bins = np.zeros((n, F), dtype=np.uint8)
    bins[:5, 0] = 3  # only 5 rows on the right of any split
    g = np.where(bins[:, 0] == 3, -1.0, 1.0).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    hist = leaf_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                          jnp.ones(n), B)
    p = SplitParams(min_data_in_leaf=10)
    info = best_split(hist, float(g.sum()), float(n), float(n),
                      _meta(F, B), p, jnp.ones(F))
    assert int(info.feature) == -1  # no valid split

    p2 = SplitParams(min_data_in_leaf=2)
    info2 = best_split(hist, float(g.sum()), float(n), float(n),
                       _meta(F, B), p2, jnp.ones(F))
    assert int(info2.feature) == 0


def test_best_split_lambda_l2_shrinks_outputs(rng):
    F, B, n = 1, 8, 500
    bins = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    g = np.where(bins[:, 0] >= 4, 1.0, -1.0).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    hist = leaf_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                          jnp.ones(n), B)
    i0 = best_split(hist, float(g.sum()), float(n), float(n), _meta(F, B),
                    SplitParams(), jnp.ones(F))
    i1 = best_split(hist, float(g.sum()), float(n), float(n), _meta(F, B),
                    SplitParams(lambda_l2=100.0), jnp.ones(F))
    assert abs(float(i1.left_out)) < abs(float(i0.left_out))
    assert float(i1.gain) < float(i0.gain)


def test_best_split_missing_nan_direction(rng):
    # NaN rows (last bin) carry strong positive gradient -> NaN should go right
    F, B, n = 1, 8, 1000
    bins = rng.randint(0, B - 1, size=(n, F)).astype(np.uint8)
    bins[:200, 0] = B - 1  # NaN bin
    g = np.where(bins[:, 0] == B - 1, 2.0,
                 np.where(bins[:, 0] >= 4, 0.5, -0.5)).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    hist = leaf_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                          jnp.ones(n), B)
    meta = _meta(F, B, missing=MISSING_NAN)
    info = best_split(hist, float(g.sum()), float(n), float(n), meta,
                      SplitParams(min_data_in_leaf=1), jnp.ones(F))
    assert int(info.feature) == 0
    assert not bool(info.default_left)  # NaN goes right with the positives


def test_best_split_feature_mask(rng):
    F, B, n = 3, 8, 500
    bins = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    g = np.where(bins[:, 0] >= 4, 1.0, -1.0).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    hist = leaf_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                          jnp.ones(n), B)
    mask = jnp.asarray([0.0, 1.0, 1.0])  # best feature masked out
    info = best_split(hist, float(g.sum()), float(n), float(n), _meta(F, B),
                      SplitParams(), mask)
    assert int(info.feature) != 0


def test_best_split_categorical_onehot(rng):
    F, B, n = 1, 4, 800
    bins = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    g = np.where(bins[:, 0] == 2, 3.0, rng.normal(size=n) * 0.1).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    hist = leaf_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                          jnp.ones(n), B)
    meta = _meta(F, B, is_cat=True)
    info = best_split(hist, float(g.sum()), float(n), float(n), meta,
                      SplitParams(max_cat_to_onehot=4), jnp.ones(F))
    assert bool(info.is_cat)
    assert int(info.threshold) == 2
    # bitset has exactly bin 2 set
    bitset = np.asarray(info.cat_bitset)
    assert bitset[0] == (1 << 2)


def test_best_split_categorical_sorted(rng):
    F, B, n = 1, 12, 3000
    bins = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    hot = np.isin(bins[:, 0], [1, 5, 7])
    g = np.where(hot, 2.0, -0.5).astype(np.float32) + \
        rng.normal(size=n).astype(np.float32) * 0.05
    h = np.ones(n, dtype=np.float32)
    hist = leaf_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                          jnp.ones(n), B)
    meta = _meta(F, B, is_cat=True)
    info = best_split(hist, float(g.sum()), float(n), float(n), meta,
                      SplitParams(max_cat_to_onehot=4, min_data_in_leaf=5),
                      jnp.ones(F))
    assert bool(info.is_cat)
    bitset = int(np.asarray(info.cat_bitset)[0])
    left_set = {b for b in range(B) if bitset & (1 << b)}
    # the split should separate {1,5,7} from the rest (either side)
    assert left_set == {1, 5, 7} or left_set == set(range(B)) - {1, 5, 7}


def test_monotone_constraint_blocks_increasing(rng):
    F, B, n = 1, 8, 1000
    bins = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    # signal: higher bins -> higher target (increasing relationship)
    g = -(bins[:, 0].astype(np.float32) - B / 2)  # negative grad for high bins
    h = np.ones(n, dtype=np.float32)
    hist = leaf_histogram(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                          jnp.ones(n), B)
    up = best_split(hist, float(g.sum()), float(n), float(n),
                    _meta(F, B, mono=1), SplitParams(), jnp.ones(F))
    down = best_split(hist, float(g.sum()), float(n), float(n),
                      _meta(F, B, mono=-1), SplitParams(), jnp.ones(F))
    assert int(up.feature) == 0       # increasing split allowed
    assert int(down.feature) == -1    # decreasing constraint blocks it


def test_leaf_output_gain_formulas():
    # closed form: G=-10, H=20, l2=1 -> out = 10/21, gain = G^2/(H+l2)
    out = float(leaf_output(-10.0, 20.0, 0.0, 1.0, 0.0))
    assert out == pytest.approx(10.0 / 21.0, rel=1e-5)
    g = float(leaf_gain(-10.0, 20.0, 0.0, 1.0, 0.0))
    assert g == pytest.approx(100.0 / 21.0, rel=1e-5)
