"""Engine quality at the REFERENCE suite's own configs and thresholds.

tests/test_engine.py gates synthetic workloads; the reference's suite
gates real datasets with tight numbers
(tests/python_package_test/test_engine.py).  This file reruns those
exact configs — same sklearn datasets, same split, same params, same
thresholds — so a regression the ±5% reference-parity gate does not
cover (GOSS/DART/bagging/rf paths) still trips a reference-grade bound
(VERDICT r4: engine thresholds were loose vs the reference's own suite).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb

sklearn = pytest.importorskip("sklearn")
from sklearn.datasets import load_breast_cancer, load_digits  # noqa: E402
from sklearn.model_selection import train_test_split  # noqa: E402


def log_loss(y, p):
    p = np.clip(p, 1e-15, 1 - 1e-15)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def multi_logloss(y, p):
    return float(-np.mean(
        np.log(np.clip(p[np.arange(len(y)), y.astype(int)], 1e-15, 1.0))))


@pytest.fixture(scope="module")
def bc_split():
    X, y = load_breast_cancer(return_X_y=True)
    return train_test_split(X, y, test_size=0.1, random_state=42)


@pytest.fixture(scope="module")
def digits_split():
    X, y = load_digits(return_X_y=True)
    return train_test_split(X, y, test_size=0.1, random_state=42)


def test_binary_reference_threshold(bc_split):
    """reference test_engine.py:37-57 — logloss < 0.15 at 50 rounds."""
    X_train, X_test, y_train, y_test = bc_split
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1}
    ds = lgb.Dataset(X_train, y_train)
    evals_result = {}
    bst = lgb.train(params, ds, num_boost_round=50,
                    valid_sets=[ds.create_valid(X_test, y_test)],
                    verbose_eval=False, evals_result=evals_result)
    ret = log_loss(y_test, bst.predict(X_test))
    assert ret < 0.15
    assert evals_result["valid_0"]["binary_logloss"][-1] == \
        pytest.approx(ret, abs=1e-5)


def test_rf_reference_threshold(bc_split):
    """reference test_engine.py:59-82 — rf bagging, logloss < 0.25."""
    X_train, X_test, y_train, y_test = bc_split
    params = {"boosting_type": "rf", "objective": "binary",
              "bagging_freq": 1, "bagging_fraction": 0.5,
              "feature_fraction": 0.5, "num_leaves": 50,
              "metric": "binary_logloss", "verbose": -1}
    ds = lgb.Dataset(X_train, y_train)
    bst = lgb.train(params, ds, num_boost_round=50, verbose_eval=False)
    ret = log_loss(y_test, bst.predict(X_test))
    assert ret < 0.25


def test_multiclass_reference_threshold(digits_split):
    """reference test_engine.py:299-318 — multi_logloss < 0.2."""
    X_train, X_test, y_train, y_test = digits_split
    params = {"objective": "multiclass", "metric": "multi_logloss",
              "num_class": 10, "verbose": -1}
    ds = lgb.Dataset(X_train, y_train.astype(np.float64))
    bst = lgb.train(params, ds, num_boost_round=50, verbose_eval=False)
    ret = multi_logloss(y_test, bst.predict(X_test))
    assert ret < 0.2


def test_multiclass_rf_reference_threshold(digits_split):
    """reference test_engine.py:320-345 — rf multiclass < 0.4."""
    X_train, X_test, y_train, y_test = digits_split
    params = {"boosting_type": "rf", "objective": "multiclass",
              "metric": "multi_logloss", "bagging_freq": 1,
              "bagging_fraction": 0.6, "feature_fraction": 0.6,
              "num_class": 10, "num_leaves": 50, "min_data": 1,
              "verbose": -1}
    ds = lgb.Dataset(X_train, y_train.astype(np.float64))
    bst = lgb.train(params, ds, num_boost_round=100, verbose_eval=False)
    ret = multi_logloss(y_test, bst.predict(X_test))
    assert ret < 0.4


def test_node_level_subcol_reference_threshold(bc_split):
    """reference test_engine.py:1666-1690 — bynode subcol < 0.13, and
    feature_fraction must actually change the model."""
    X_train, X_test, y_train, y_test = bc_split
    params = {"objective": "binary", "metric": "binary_logloss",
              "feature_fraction_bynode": 0.8, "feature_fraction": 1.0,
              "verbose": -1}
    ds = lgb.Dataset(X_train, y_train)
    bst = lgb.train(params, ds, num_boost_round=25, verbose_eval=False)
    ret = log_loss(y_test, bst.predict(X_test))
    assert ret < 0.13
    params["feature_fraction"] = 0.5
    bst2 = lgb.train(params, lgb.Dataset(X_train, y_train),
                     num_boost_round=25, verbose_eval=False)
    ret2 = log_loss(y_test, bst2.predict(X_test))
    assert ret != ret2


def test_dart_reference_quality(bc_split):
    """DART at the reference's binary config must stay near the gbdt
    gate (the reference gates DART via continue_train_dart l1 < 2.5;
    breast_cancer logloss < 0.20 is the equivalent bound here)."""
    X_train, X_test, y_train, y_test = bc_split
    params = {"objective": "binary", "boosting": "dart",
              "metric": "binary_logloss", "drop_rate": 0.1,
              "verbose": -1}
    ds = lgb.Dataset(X_train, y_train)
    bst = lgb.train(params, ds, num_boost_round=50, verbose_eval=False)
    ret = log_loss(y_test, bst.predict(X_test))
    assert ret < 0.20


def test_goss_reference_quality(bc_split):
    """GOSS at the reference's binary config: the sampled-gradient
    learner must stay within the same 0.15-class gate as plain gbdt."""
    X_train, X_test, y_train, y_test = bc_split
    params = {"objective": "binary", "boosting": "goss",
              "metric": "binary_logloss", "verbose": -1}
    ds = lgb.Dataset(X_train, y_train)
    bst = lgb.train(params, ds, num_boost_round=50, verbose_eval=False)
    ret = log_loss(y_test, bst.predict(X_test))
    assert ret < 0.16
