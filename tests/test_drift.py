"""Drift plane tests (metrics schema v7).

What must hold: the PSI/JS estimators are finite, symmetric and ~0 on
matching distributions; ``extract_baseline`` recounts the training
Dataset's binned matrix exactly (numpy recount per raw column, EFB
bundles unpacked) and digests the training scores over quantile edges;
the serve-side accumulator's cumulative row accounting survives real
coalesced batches through the queue; a shifted column in live traffic
is detected AND named through the real queue path while every reply
stays bit-identical to ``Booster.predict``; the ``DriftGate`` flips
exactly at ``psi_max >= threshold``; training stays byte-identical
with the ``drift_*`` knobs in params (runtime-only); a blob from a
session that never synced a drift window keeps the v6 shape (no
``drift`` key); and the monitors render the loud ``!! DRIFT`` banner.
"""

import json
import math
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import RUNTIME_ONLY_PARAMS, Config
from lightgbm_tpu.obs import drift
from lightgbm_tpu.serve import ServeSession
from lightgbm_tpu.utils.faults import FAULTS
from lightgbm_tpu.utils.telemetry import TELEMETRY

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import fleet_monitor  # noqa: E402
import serve_monitor  # noqa: E402
import trace_report  # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    TELEMETRY.reset()
    TELEMETRY.set_config_level(1)
    TELEMETRY.install_jax_listeners()
    yield
    FAULTS.configure()


def _train(rng, rounds=8):
    X = rng.normal(size=(400, 8))
    X[:, 3] = rng.randint(0, 6, size=400)
    y = (np.nan_to_num(X[:, 0] + X[:, 1]) > 0.3).astype(np.float64)
    ds = lgb.Dataset(X, y, categorical_feature=[3])
    return lgb.train({"objective": "binary", "verbose": -1,
                      "num_leaves": 15}, ds,
                     num_boost_round=rounds), X


def _records(path):
    out = []
    with open(path, "rb") as fh:
        for raw in fh.read().split(b"\n"):
            if raw.strip():
                out.append(json.loads(raw))
    return out


# ----------------------------------------------------------- estimators
def test_psi_js_units():
    same = [100, 200, 300]
    assert drift.psi(same, same) == pytest.approx(0.0, abs=1e-9)
    assert drift.js_divergence(same, same) == pytest.approx(0.0,
                                                            abs=1e-9)
    # proportional counts are the same distribution
    assert drift.psi([1, 2, 3], [10, 20, 30]) == pytest.approx(
        0.0, abs=1e-3)
    # empty buckets stay finite (additive smoothing), disjoint mass
    # is loud, and both estimators are symmetric
    a, b = [100, 0, 0], [0, 0, 100]
    assert math.isfinite(drift.psi(a, b))
    assert drift.psi(a, b) > 1.0
    assert drift.psi(a, b) == pytest.approx(drift.psi(b, a))
    js = drift.js_divergence(a, b)
    assert 0.0 < js <= math.log(2.0) + 1e-9        # JS bounded by ln 2
    assert js == pytest.approx(drift.js_divergence(b, a))


# ------------------------------------------------------------- baseline
def test_baseline_matches_numpy_recount(rng):
    bst, X = _train(rng)
    base = drift.extract_baseline(bst)
    ds = bst.gbdt.train_set
    used = [int(f) for f in ds.used_feature_indices]
    assert base.num_features == len(used)
    assert base.rows == X.shape[0]
    B = base.bin_counts.shape[1]
    for j, f in enumerate(used):
        m = ds.bin_mappers[f]
        nb = int(m.num_bin)
        # independent recount: raw column -> value_to_bin -> bincount.
        # Exact equality proves the EFB unpack in dataset_bin_counts.
        ref = np.bincount(
            m.value_to_bin(np.asarray(X[:, f], dtype=np.float64)),
            minlength=B)[:nb]
        assert np.array_equal(base.bin_counts[j, :nb], ref), \
            f"fine counts diverge from numpy recount on feature {f}"
        assert base.bin_counts[j].sum() == X.shape[0]
        # coarse buckets are exactly the fine counts folded through the
        # published bin->bucket map
        fold = np.bincount(
            base.bucket_of[j, :nb],
            weights=base.bin_counts[j, :nb].astype(np.float64),
            minlength=drift.PSI_BUCKETS)[:drift.PSI_BUCKETS]
        assert np.allclose(base.bucket_counts[j], fold)
        # PSI of the baseline against itself is the fixed point
        assert drift.psi(base.bucket_counts[j],
                         base.bucket_counts[j]) == pytest.approx(
            0.0, abs=1e-9)
    # score digest: its source really is the training predictions — an
    # independent raw predict reproduces them up to summation order
    # (exact edge identity can't hold: quantile ties collapse
    # differently under np.unique when the last ulp moves)
    raw = bst.predict(X, raw_score=True)
    scores = np.asarray(bst.gbdt.train_score, dtype=np.float64)[0]
    assert np.allclose(scores, raw, rtol=1e-6, atol=1e-6)
    assert base.score_edges is not None
    assert np.all(np.diff(base.score_edges) > 0)
    assert 1 <= base.score_edges.size <= drift.SCORE_BUCKETS - 1
    assert base.score_counts.sum() == X.shape[0]
    # and the histogram is a numpy searchsorted recount of the scores
    ref = np.bincount(
        np.searchsorted(base.score_edges, scores, side="right"),
        minlength=base.score_edges.size + 1)
    assert np.array_equal(base.score_counts, ref)


def test_baseline_feature_names(rng):
    bst, _ = _train(rng)
    base = drift.extract_baseline(bst)
    names = bst.feature_name()
    assert all(n in names for n in base.feature_names)


# ------------------------------------------- accumulator + gate (unit)
def _uniform_baseline(nbin=10, count=100):
    counts = np.full((1, nbin), count, dtype=np.int64)
    bucket_of = np.arange(nbin, dtype=np.int64).reshape(1, nbin)
    bucket_counts = counts.astype(np.float64)
    return drift.ModelBaseline(["f0"], np.asarray([nbin]), counts,
                               bucket_of, bucket_counts, None, None,
                               nbin * count)


def test_gate_flips_exactly_at_threshold():
    acc = drift.DriftAccumulator(psi_threshold=0.2, topk=3)
    base = _uniform_baseline()
    acc.register("m", base)
    gate = drift.DriftGate(acc)
    # untracked / no-rows models never read as drifted
    assert acc.compute("m") is None
    assert not gate.drifted("m")
    assert not gate.drifted("ghost")
    # all mass into one bin: a loud shift
    skew = np.zeros((1, 10), dtype=np.int64)
    skew[0, 0] = 500
    acc.note_bins("m", skew)
    rec = acc.compute("m")
    assert rec["rows"] == 500
    assert rec["top"][0]["feature"] == "f0"
    assert rec["drifted"] is (rec["psi_max"] >= 0.2)
    # the flip is exact: >= at equality, False one epsilon above
    assert gate.drifted("m", psi_threshold=rec["psi_max"])
    assert not gate.drifted("m", psi_threshold=rec["psi_max"] + 1e-9)
    assert gate.drifted("m") == (rec["psi_max"] >= 0.2)
    # matching traffic computes ~0 and never trips
    acc2 = drift.DriftAccumulator(psi_threshold=0.2)
    acc2.register("m", _uniform_baseline())
    acc2.note_bins("m", np.full((1, 10), 50, dtype=np.int64))
    assert acc2.compute("m")["psi_max"] == pytest.approx(0.0, abs=1e-3)
    assert not drift.DriftGate(acc2).drifted("m")
    # forget() drops the model entirely
    acc.forget("m")
    assert not acc.tracks("m")
    assert not gate.drifted("m")


# --------------------------------------------------- real queue path
def test_shifted_column_detected_through_queue(rng, tmp_path):
    path = str(tmp_path / "drift.serve.health.jsonl")
    bst, X = _train(rng)
    shifted = X[:200].copy()
    shifted[:, 1] += 5.0                 # far outside the N(0,1) range
    refs = bst.predict(shifted)
    with ServeSession(max_batch=32, max_delay_ms=2.0, health_out=path,
                      health_window_s=0.3, drift_detect=True,
                      drift_psi_threshold=0.2) as sess:
        mid = sess.load(bst)
        futs = [sess.submit(mid, shifted[i:i + 1]) for i in range(200)]
        for i, f in enumerate(futs):
            res = np.asarray(f.result(timeout=30)).ravel()
            # the drift tap must not perturb a single bit
            assert np.array_equal(res, refs[i:i + 1])
        assert sess.drift_gate.drifted(mid)
        live = sess.drift_gate.stats(mid)
        assert live["rows"] == 200
        assert live["top"][0]["feature"] == "Column_1"
        assert live["psi_max"] >= 0.2
    drecs = [r for r in _records(path) if r["kind"] == "serve_drift"]
    assert drecs, "no serve_drift record in the health stream"
    last = drecs[-1]
    assert last["model"] == mid
    assert last["drifted"] is True
    assert last["top"][0]["feature"] == "Column_1"
    assert last["threshold"] == 0.2
    assert "score_js" in last and math.isfinite(last["score_js"])
    # gauges published with the records
    gauges = TELEMETRY.stats()["gauges"]
    assert gauges["serve/drift_psi_max"] >= 0.2
    assert 0.0 <= gauges["serve/score_js"] <= math.log(2.0)


def test_unshifted_traffic_stays_quiet(rng, tmp_path):
    path = str(tmp_path / "quiet.serve.health.jsonl")
    bst, X = _train(rng)
    with ServeSession(max_batch=32, max_delay_ms=2.0, health_out=path,
                      health_window_s=0.3, drift_detect=True,
                      drift_psi_threshold=0.2) as sess:
        mid = sess.load(bst)
        futs = [sess.submit(mid, X[i:i + 1]) for i in range(300)]
        for f in futs:
            f.result(timeout=30)
        assert not sess.drift_gate.drifted(mid)
    drecs = [r for r in _records(path) if r["kind"] == "serve_drift"]
    assert drecs
    assert all(not r["drifted"] for r in drecs)
    assert all(r["psi_max"] < 0.2 for r in drecs)


def test_window_accounting_across_coalesced_batches(rng, tmp_path):
    """Cumulative row accounting: mixed-size requests coalesced by the
    queue into padded device batches must count exactly the submitted
    rows — pad rows masked, nothing double-counted across windows."""
    path = str(tmp_path / "acct.serve.health.jsonl")
    bst, X = _train(rng)
    sizes = [1, 3, 7, 16, 2, 5] * 4
    total = sum(sizes)
    with ServeSession(max_batch=32, max_delay_ms=2.0, health_out=path,
                      health_window_s=0.2, drift_detect=True) as sess:
        mid = sess.load(bst)
        futs, at = [], 0
        for n in sizes:
            futs.append(sess.submit(mid, X[at:at + n]))
            at = (at + n) % (X.shape[0] - 16)
        for f in futs:
            f.result(timeout=30)
        assert sess.drift_gate.stats(mid)["rows"] == total
    drecs = [r for r in _records(path) if r["kind"] == "serve_drift"]
    assert drecs
    # records carry the CUMULATIVE count: monotone, ending at total
    rows = [r["rows"] for r in drecs]
    assert rows == sorted(rows)
    assert rows[-1] == total
    assert drecs[-1]["scores"] == total


# --------------------------------------------------------- invariants
def test_training_byte_identical_with_drift_knobs(rng):
    X = rng.normal(size=(300, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    base_params = {"objective": "binary", "verbose": -1,
                   "num_leaves": 7, "deterministic": True}

    def fit(params):
        ds = lgb.Dataset(X.copy(), y.copy())
        return lgb.train(params, ds,
                         num_boost_round=6).model_to_string()

    base = fit(base_params)
    with_drift = fit(dict(base_params, drift_detect=True,
                          drift_psi_threshold=0.5, drift_topk=3))
    assert with_drift == base
    # runtime-only by construction: never serialized into models
    assert {"drift_detect", "drift_psi_threshold",
            "drift_topk"} <= RUNTIME_ONLY_PARAMS


def test_config_knob_validation():
    assert Config(drift_detect=True).drift_psi_threshold == 0.2
    with pytest.raises(ValueError):
        Config(drift_psi_threshold=0.0)
    with pytest.raises(ValueError):
        Config(drift_psi_threshold=-1.0)
    with pytest.raises(ValueError):
        Config(drift_topk=0)


def test_blob_v6_shaped_without_synced_window(rng):
    bst, X = _train(rng, rounds=4)
    # drift off: v7 blob, no drift key
    with ServeSession(max_batch=16) as sess:
        mid = sess.load(bst)
        sess.predict(mid, X[:4])
    stats = TELEMETRY.stats()
    assert stats["version"] == 7
    assert "drift" not in stats
    # drift on, no health stream: nothing published until close
    TELEMETRY.reset()
    with ServeSession(max_batch=16, drift_detect=True,
                      drift_psi_threshold=0.2) as sess:
        mid = sess.load(bst)
        sess.predict(mid, X[:8])
        assert "drift" not in TELEMETRY.stats()     # no window synced
    stats = TELEMETRY.stats()                        # close flushed
    assert stats["drift"]["psi_threshold"] == 0.2
    entry = stats["drift"]["models"][mid]
    assert entry["rows"] == 8
    assert "model" not in entry                      # keyed by id
    # reset clears the section: the next blob is v6-shaped again
    TELEMETRY.reset()
    assert "drift" not in TELEMETRY.stats()


# ------------------------------------------------------------ monitors
def _drift_rec(drifted, model="m"):
    return {"kind": "serve_drift", "model": model, "rows": 512,
            "psi_max": 0.75 if drifted else 0.03,
            "top": [{"feature": "Column_1",
                     "psi": 0.75 if drifted else 0.03}],
            "threshold": 0.2, "drifted": drifted, "score_js": 0.01,
            "scores": 512, "t": 1.0}


def test_serve_monitor_drift_banner():
    state = serve_monitor.ServeStreamState()
    start = {"kind": "serve_start", "schema": "lightgbm_tpu.health/v1",
             "pid": 1, "max_batch": 16, "window_s": 0.5}
    for rec in (start, _drift_rec(True)):
        state.feed((json.dumps(rec) + "\n").encode())
    out = serve_monitor.render(state, "x.serve.health.jsonl")
    assert "!! DRIFT" in out
    assert "Column_1" in out
    assert "refit trigger" in out
    # a clean record renders the drift line but not the banner
    quiet = serve_monitor.ServeStreamState()
    for rec in (start, _drift_rec(False)):
        quiet.feed((json.dumps(rec) + "\n").encode())
    out = serve_monitor.render(quiet, "x.serve.health.jsonl")
    assert "drift m:" in out
    assert "!! DRIFT" not in out


def test_fleet_monitor_drift_banner(tmp_path):
    path = tmp_path / "svc.serve.health.jsonl"
    recs = [{"kind": "serve_start", "stream": "serve", "pid": 1,
             "mono_ts": 1.0}, _drift_rec(True, model="churn")]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    state = fleet_monitor.FleetStream()
    state.feed(path.read_bytes())
    out = fleet_monitor.render({str(path): state}, str(tmp_path))
    assert "!! DRIFT" in out
    assert "churn" in out
    assert "refit trigger armed" in out


def test_trace_report_drift_section():
    v6ish = {"version": 6, "phases": {}, "counters": {}, "gauges": {}}
    out = trace_report.summarize(v6ish)
    assert "drift: n/a" in out
    blob = dict(v6ish, version=7, drift={
        "psi_threshold": 0.2,
        "models": {"m": {"rows": 512, "psi_max": 0.75,
                         "top": [{"feature": "Column_1", "psi": 0.75}],
                         "threshold": 0.2, "drifted": True,
                         "score_js": 0.01}}})
    out = trace_report.summarize(blob)
    assert "psi_max=0.750" in out
    assert "Column_1" in out
    assert "!! DRIFT" in out
    d = trace_report.diff(v6ish, blob)
    assert "m.psi_max" in d
