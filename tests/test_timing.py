"""Measured device-time attribution (metrics blob v4).

Covers the ISSUE acceptance surfaces: timing parity between chunked
and per-iteration dispatch (every timed label's count matches its cost
call count, quantiles are finite and ordered), bit-identical models
with ``device_timing`` on, the windowed programmatic profiler capture
(opens/closes exactly once, exception-safe mid-training), the
``transfer/eval_fetch_*`` counters on the in-scan eval path, the
``dispatch_wall_s`` health-stream field feeding run_monitor's EWMA
pace/ETA line, trace_report's v3-blob n/a-safety, and the bench_gate
dispatch-latency verdicts.
"""

import json
import math
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.phase import GLOBAL_TIMER, PROFILE_WINDOW
from lightgbm_tpu.utils.telemetry import TELEMETRY

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import bench_gate  # noqa: E402
import run_monitor  # noqa: E402
import trace_report  # noqa: E402


@pytest.fixture(autouse=True)
def clean_telemetry():
    """TELEMETRY and PROFILE_WINDOW are process-global: start every
    test from a clean window and a disarmed profiler."""
    GLOBAL_TIMER.reset()
    TELEMETRY.reset()
    yield
    GLOBAL_TIMER.reset()
    TELEMETRY.reset()
    PROFILE_WINDOW._armed = False
    PROFILE_WINDOW.is_open = False


def make_binary(rng, n=500, f=5):
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    return X, y


def _params(**kw):
    p = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
         "min_data_in_leaf": 5, "verbose": -1}
    p.update(kw)
    return p


# -------------------------------------------------------- measured timing


def _assert_timing_matches_cost(stats):
    timing = stats["timing"]
    assert timing["enabled"] is True
    cost_labels = stats["cost"]["labels"]
    assert timing["labels"], "timing on must time at least one dispatch"
    for name, lab in timing["labels"].items():
        assert lab["count"] == cost_labels[name]["calls"], name
        for key in ("mean_s", "p50_s", "p99_s", "max_s", "total_s"):
            assert math.isfinite(lab[key]) and lab[key] >= 0.0, (name, key)
        assert lab["p50_s"] <= lab["p99_s"] <= lab["max_s"], name
    assert timing["total_s"] > 0.0


def test_timing_counts_match_cost_calls_chunked_and_not(rng):
    """Every timed label's dispatch count equals its cost call count —
    on the chunked path (one boost/chunk[4] program per 4 iterations)
    and on the per-iteration path alike."""
    X, y = make_binary(rng, n=600)
    for chunk in (4, 1):
        GLOBAL_TIMER.reset()
        TELEMETRY.reset()
        lgb.train(_params(tpu_boost_chunk=chunk, device_timing=True,
                          seed=7), lgb.Dataset(X, y), num_boost_round=8)
        stats = TELEMETRY.stats()
        _assert_timing_matches_cost(stats)
        if chunk == 4:
            assert "boost/chunk[4]" in stats["timing"]["labels"]
            assert stats["timing"]["labels"]["boost/chunk[4]"][
                "count"] == 2
            assert stats["timing"].get("measured_flops_per_s", 0) > 0


def test_timing_off_by_default_and_models_bit_identical(rng):
    """device_timing only measures: the blob has no timing section when
    it is off, and the saved model is byte-identical with it on (the
    knob is runtime-only, never serialized)."""
    X, y = make_binary(rng, n=400)
    data = lambda: lgb.Dataset(X, y)
    bst_off = lgb.train(_params(tpu_boost_chunk=4, seed=3), data(),
                        num_boost_round=6)
    assert "timing" not in TELEMETRY.stats()
    off_str = bst_off.model_to_string()

    GLOBAL_TIMER.reset()
    TELEMETRY.reset()
    bst_on = lgb.train(_params(tpu_boost_chunk=4, seed=3,
                               device_timing=True), data(),
                       num_boost_round=6)
    assert TELEMETRY.stats()["timing"]["enabled"] is True
    assert bst_on.model_to_string() == off_str


def test_timing_env_override(rng, monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_DEVICE_TIMING", "1")
    X, y = make_binary(rng, n=300)
    lgb.train(_params(seed=1), lgb.Dataset(X, y), num_boost_round=2)
    assert TELEMETRY.stats()["timing"]["enabled"] is True


# ------------------------------------------------------- profiler window


class _FakeProfiler:
    def __init__(self, monkeypatch):
        self.starts, self.stops = [], []
        import jax
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda path: self.starts.append(path))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: self.stops.append(True))


def test_profile_window_opens_and_closes_exactly_once(rng, monkeypatch):
    fake = _FakeProfiler(monkeypatch)
    X, y = make_binary(rng, n=400)
    lgb.train(_params(tpu_boost_chunk=4, profile_window="1:3", seed=5),
              lgb.Dataset(X, y), num_boost_round=8)
    assert len(fake.starts) == 1
    assert len(fake.stops) == 1
    prof = TELEMETRY.stats()["timing"]["profile"]
    assert prof["kind"] == "window"
    assert prof["window"] == [1, 3]
    assert prof["requested"] == [1, 3]
    assert not PROFILE_WINDOW.is_open


def test_profile_window_exception_safe_mid_training(rng, monkeypatch):
    """A callback raising INSIDE the window must not leak an open jax
    profiler session: the profile_session finally closes it, exactly
    once."""
    fake = _FakeProfiler(monkeypatch)
    X, y = make_binary(rng, n=400)

    def boom(env):
        if env.iteration >= 1:
            raise RuntimeError("mid-window failure")

    with pytest.raises(RuntimeError, match="mid-window"):
        lgb.train(_params(profile_window="1:6", seed=5),
                  lgb.Dataset(X, y), num_boost_round=8,
                  callbacks=[boom])
    assert len(fake.starts) == 1
    assert len(fake.stops) == 1
    assert not PROFILE_WINDOW.is_open
    prof = TELEMETRY.stats()["timing"]["profile"]
    assert prof["kind"] == "window"


def test_profile_window_bad_spec_disables(rng, monkeypatch):
    fake = _FakeProfiler(monkeypatch)
    X, y = make_binary(rng, n=300)
    lgb.train(_params(profile_window="3:1", seed=2), lgb.Dataset(X, y),
              num_boost_round=3)
    assert fake.starts == [] and fake.stops == []


# ------------------------------------------------- in-scan eval counters


def test_eval_fetch_counters_separate_from_tree_fetches(rng):
    """The in-scan eval metric-row fetch is counted under its own
    transfer/eval_fetch_* counters — the pinned tree-fetch counters
    (test_telemetry.test_fetch_counters_exact_for_two_chunk_run) are
    untouched by attaching a valid set."""
    X, y = make_binary(rng, n=600)
    Xv, yv = make_binary(rng, n=200)
    train = lgb.Dataset(X, y)
    lgb.train(_params(tpu_boost_chunk=2, seed=11), train,
              num_boost_round=4,
              valid_sets=[lgb.Dataset(Xv, yv, reference=train)])
    counters = TELEMETRY.stats()["counters"]
    assert counters["transfer/eval_fetch_calls"] == 2
    assert counters["transfer/eval_fetch_bytes"] > 0
    assert counters["transfer/fetch_calls"] == 2


# ------------------------------------------- health stream + run_monitor


def test_dispatch_wall_in_health_stream_and_monitor_eta(rng, tmp_path):
    stream = tmp_path / "run.health.jsonl"
    X, y = make_binary(rng, n=500)
    lgb.train(_params(tpu_boost_chunk=4, health_out=str(stream),
                      device_timing=True, seed=9),
              lgb.Dataset(X, y), num_boost_round=8)
    walls = [rec.get("dispatch_wall_s")
             for rec in map(json.loads, stream.read_text().splitlines())
             if rec.get("kind") == "iter"]
    assert len(walls) == 8
    # the wall window lands on each chunk's FIRST iteration only
    assert [w is not None for w in walls] == [True, False, False, False,
                                             True, False, False, False]
    assert all(w > 0 for w in walls if w is not None)

    state = run_monitor.StreamState()
    state.feed(stream.read_bytes())
    out = run_monitor.render(state, str(stream))
    assert "dispatch pace:" in out
    assert "it/s" in out


def test_monitor_eta_and_na_safety():
    """ETA appears for an unfinished stream with measured walls, and an
    older stream without dispatch_wall_s renders without the pace
    line."""
    def _stream(with_walls):
        state = run_monitor.StreamState()
        recs = [{"kind": "start", "schema": "lightgbm_tpu.health/v1",
                 "num_iterations": 100}]
        for i in range(0, 8, 4):
            rec = {"kind": "iter", "iter": i + 3, "chunk": 4, "t": i * 1.0}
            if with_walls:
                rec["dispatch_wall_s"] = 0.5
            recs.append(rec)
        state.feed(("\n".join(json.dumps(r) for r in recs) + "\n")
                   .encode())
        return run_monitor.render(state, "x.jsonl")

    out = _stream(True)
    assert "dispatch pace: 8.00 it/s" in out
    assert "ETA" in out
    out = _stream(False)
    assert "dispatch pace" not in out and "ETA" not in out


# -------------------------------------------------- report + gate tools


def test_trace_report_na_on_pre_v4_blob():
    assert "timing: n/a" in trace_report.summarize({"version": 3})


def test_trace_report_renders_timing_and_diff(rng):
    X, y = make_binary(rng, n=400)
    lgb.train(_params(tpu_boost_chunk=4, device_timing=True, seed=4),
              lgb.Dataset(X, y), num_boost_round=4)
    blob = TELEMETRY.stats()
    out = trace_report.summarize(blob)
    assert "timing (measured wall-to-ready" in out
    assert "utilization (measured):" in out
    d = trace_report.diff({"version": 3}, blob)
    assert "timing (measured)" in d


def test_bench_gate_latency_verdicts():
    hist = [{"config": "c", "value": 10.0, "unit": "s",
             "quality_ok": True, "dispatch_mean_s": 0.010}
            for _ in range(4)]
    ok = {"config": "c", "value": 10.0, "unit": "s", "quality_ok": True,
          "dispatch_mean_s": 0.0105}
    bad = dict(ok, dispatch_mean_s=0.013)
    off = dict(ok, dispatch_mean_s=None)
    assert not bench_gate.evaluate(hist + [ok])[0]
    failures, _ = bench_gate.evaluate(hist + [bad])
    assert failures and "dispatch latency" in failures[0]
    assert not bench_gate.evaluate(hist + [off])[0]
    # widening the tolerance admits the regression
    assert not bench_gate.evaluate(hist + [bad], latency_tol=0.50)[0]
    assert bench_gate.self_test() == 0
