"""Segment grower (models/grower_seg.py) end-to-end parity vs the fused
grower.

The segment grower must produce the SAME leaf-wise tree as the fused
grower up to histogram summation order (grower_seg.py docstring): same
topology, same split features/thresholds, near-same outputs (bf16 hi/lo
histogram channels vs f32).  Pallas runs in interpret mode on the CPU CI
mesh, so these tests cover the real kernel logic minus mosaic codegen.

Shapes are chosen to cross the compaction milestones (4 and 16 leaves)
and to exercise categorical splits, NaN missing routing, bagging weights,
and multi-iteration training.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.config import Config
from lightgbm_tpu.core.dataset import TpuDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objective import create_objective


def _train_pair(X, y, rng, n_iters=3, **params):
    """Train fused-onehot and segment boosters on identical data."""
    cat_feats = params.pop("categorical_feature", [])
    out = []
    for backend, impl in (("onehot", "fused"), ("pallas", "segment")):
        cfg = Config(verbosity=-1, tpu_histogram_backend=backend,
                     tpu_tree_impl=impl, **params)
        ds = TpuDataset.from_numpy(X, y, config=cfg,
                                   categorical_features=cat_feats)
        obj = create_objective(cfg)
        obj.init(ds.metadata, ds.num_data)
        bst = GBDT(cfg, ds, obj)
        for _ in range(n_iters):
            bst.train_one_iter()
        out.append(bst)
    fused, seg = out
    assert seg._use_segment, "segment grower was not selected"
    return fused, seg


def _assert_tree_parity(fused, seg, X, tol=5e-3, gain_floor=1e-2):
    """Same topology for every split whose gain is above float noise
    (zero-gain ties legitimately break differently between the f32 onehot
    and bf16 hi/lo pallas histograms), near-same predictions overall."""
    assert len(fused.models) == len(seg.models)
    compared = 0
    for i, (tf, ts) in enumerate(zip(fused.models, seg.models)):
        nf = min(tf.num_leaves, ts.num_leaves) - 1
        # leaf-wise growth is best-first, so gains are non-increasing;
        # compare the prefix of meaningful splits
        k = 0
        while (k < nf and tf.split_gain[k] > gain_floor
               and ts.split_gain[k] > gain_floor):
            k += 1
        assert np.array_equal(tf.split_feature[:k],
                              ts.split_feature[:k]), f"tree {i}"
        assert np.array_equal(tf.threshold_in_bin[:k],
                              ts.threshold_in_bin[:k]), f"tree {i}"
        compared += k
    assert compared > 0, "no meaningful splits compared"
    p_f = fused._raw_predict(X)
    p_s = seg._raw_predict(X)
    assert np.abs(p_f - p_s).max() < tol


def test_segment_parity_binary_compaction(rng):
    """31 leaves crosses the 4- and 16-leaf compaction milestones."""
    n = 3000
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.3 * X[:, 2] ** 2
         + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    fused, seg = _train_pair(X, y, rng, n_iters=3, objective="binary",
                             num_leaves=31, max_bin=63, min_data_in_leaf=5)
    _assert_tree_parity(fused, seg, X)


def test_segment_parity_packed4(rng):
    """max_bin=15 activates the 4-bit packed layout (Dense4bitsBin
    equivalent): two columns per byte, in-kernel nibble unpack.  The
    grown trees must match the unpacked fused grower."""
    n = 3000
    X = rng.normal(size=(n, 7))
    y = (X[:, 0] + 0.6 * X[:, 1] - 0.2 * X[:, 2] ** 2
         + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    fused, seg = _train_pair(X, y, rng, n_iters=3, objective="binary",
                             num_leaves=31, max_bin=15, min_data_in_leaf=5)
    assert seg.grower_params.packed4, "packed4 layout was not selected"
    # physical bin rows = ceil(columns / 2)
    assert seg.bins.shape[0] == -(-seg.train_set.num_columns // 2)
    _assert_tree_parity(fused, seg, X)


def test_segment_parity_missing_nan(rng):
    n = 2000
    X = rng.normal(size=(n, 5))
    X[rng.uniform(size=(n, 5)) < 0.15] = np.nan
    y = (np.where(np.isnan(X[:, 0]), 0.5, np.nan_to_num(X[:, 0]) > 0)
         + 0.4 * np.nan_to_num(X[:, 1]) + 0.3 * np.nan_to_num(X[:, 2]) ** 2
         + 0.05 * rng.normal(size=n)).astype(np.float64)
    fused, seg = _train_pair(X, y, rng, n_iters=2, objective="regression",
                             num_leaves=15, max_bin=31, min_data_in_leaf=10)
    _assert_tree_parity(fused, seg, X)


def test_segment_parity_categorical(rng):
    n = 2500
    Xc = rng.randint(0, 12, size=n)
    Xn = rng.normal(size=(n, 3))
    X = np.column_stack([Xc.astype(np.float64), Xn])
    effect = np.array([1.5, -2, 0.3, 2, -1, 0.8, -0.2, 1.1, -1.7, 0.5,
                       2.2, -0.9])
    y = effect[Xc] + Xn[:, 0] + 0.1 * rng.normal(size=n)
    fused, seg = _train_pair(X, y, rng, n_iters=2, objective="regression",
                             num_leaves=15, max_bin=63, min_data_in_leaf=20,
                             categorical_feature=[0])
    assert any(t.num_cat > 0 for t in fused.models), \
        "no categorical split exercised"
    _assert_tree_parity(fused, seg, X)


def test_segment_parity_bagging(rng):
    n = 2400
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] * X[:, 1] + 0.2 * rng.normal(size=n)).astype(np.float64)
    fused, seg = _train_pair(X, y, rng, n_iters=3, objective="regression",
                             num_leaves=12, max_bin=31,
                             bagging_fraction=0.7, bagging_freq=1,
                             bagging_seed=7, min_data_in_leaf=5)
    _assert_tree_parity(fused, seg, X)


def test_segment_grower_direct_leaf_id(rng):
    """Grower-level check: the segment grower's returned leaf_id (mapped
    back to original row order) matches the fused grower's."""
    from lightgbm_tpu.models.grower import GrowerParams, make_grow_tree
    from lightgbm_tpu.models.grower_seg import make_grow_tree_segment
    from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
    import jax

    n, F, B, L, rb = 1024, 4, 16, 8, 256
    bins = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    # real signal so split gains sit well above bf16 rounding noise
    g = (-(bins[:, 0] >= B // 2).astype(np.float32)
         - 0.5 * (bins[:, 1] % 3 == 0)
         + 0.25 * bins[:, 2] / B
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    h = np.ones(n, np.float32)
    member = (rng.uniform(size=n) < 0.9).astype(np.float32)
    fmeta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        is_cat=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    fmask = jnp.ones(F, jnp.float32)
    key = jax.random.PRNGKey(0)
    params = GrowerParams(num_leaves=L,
                          split=SplitParams(min_data_in_leaf=2.0))

    tree_f, lid_f = make_grow_tree(B, params)(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(member), fmeta, fmask, key)
    params_s = params._replace(hist_backend="pallas")
    tree_s, lid_s, _ = make_grow_tree_segment(B, params_s, rb)(
        jnp.asarray(bins.T.copy()), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(member), fmeta, fmask, key)

    assert int(tree_f.num_leaves) == int(tree_s.num_leaves)
    nl = int(tree_f.num_leaves) - 1
    np.testing.assert_array_equal(np.asarray(tree_f.split_feature)[:nl],
                                  np.asarray(tree_s.split_feature)[:nl])
    np.testing.assert_array_equal(np.asarray(tree_f.threshold_bin)[:nl],
                                  np.asarray(tree_s.threshold_bin)[:nl])
    # leaf assignment identical for member rows (pad/non-member rows are
    # still routed, so compare all real rows)
    np.testing.assert_array_equal(np.asarray(lid_f), np.asarray(lid_s))
    assert np.abs(np.asarray(tree_f.leaf_value)
                  - np.asarray(tree_s.leaf_value)).max() < 1e-3


def test_multiclass_batched_roots_parity(rng):
    """Multiclass: all C class-trees' root histograms computed in ONE
    kernel pass (histogram_all with stacked channel sets) must grow the
    same trees as per-class root scans (the non-fused eager path)."""
    n, C = 1500, 3
    X = rng.normal(size=(n, 5))
    y = np.argmax(X[:, :C] + rng.normal(size=(n, C)) * 0.3, axis=1)

    def train(force_eager):
        cfg = Config(verbosity=-1, objective="multiclass", num_class=C,
                     tpu_histogram_backend="pallas",
                     tpu_tree_impl="segment", num_leaves=7,
                     min_data_in_leaf=5, tpu_row_chunk=256)
        ds = TpuDataset.from_numpy(X, y.astype(np.float64), config=cfg)
        obj = create_objective(cfg)
        obj.init(ds.metadata, ds.num_data)
        bst = GBDT(cfg, ds, obj)
        if force_eager:
            bst._fused_ok = False      # per-class root scans, no batching
        for _ in range(3):
            bst.train_one_iter()
        return bst

    fused = train(False)
    eager = train(True)
    assert fused._fused_fns is not None and fused._fused_fns[2] is not None, \
        "batched roots should be active for serial multiclass segment"
    assert len(fused.models) == len(eager.models) == 9
    for i, (tf, te) in enumerate(zip(fused.models, eager.models)):
        assert tf.num_leaves == te.num_leaves, f"tree {i}"
        nsp = tf.num_leaves - 1
        assert np.array_equal(tf.split_feature[:nsp],
                              te.split_feature[:nsp]), f"tree {i}"
    np.testing.assert_allclose(fused._raw_predict(X), eager._raw_predict(X),
                               rtol=1e-4, atol=1e-5)


def test_multiclass_batched_roots_parity_packed4(rng):
    """Batched roots through the 4-bit packed layout (max_bin<=15)."""
    n, C = 1200, 3
    X = rng.normal(size=(n, 6))
    y = np.argmax(X[:, :C] + rng.normal(size=(n, C)) * 0.3, axis=1)

    def train(force_eager):
        cfg = Config(verbosity=-1, objective="multiclass", num_class=C,
                     tpu_histogram_backend="pallas", max_bin=15,
                     tpu_tree_impl="segment", num_leaves=7,
                     min_data_in_leaf=5, tpu_row_chunk=256)
        ds = TpuDataset.from_numpy(X, y.astype(np.float64), config=cfg)
        obj = create_objective(cfg)
        obj.init(ds.metadata, ds.num_data)
        bst = GBDT(cfg, ds, obj)
        assert bst.grower_params.packed4
        if force_eager:
            bst._fused_ok = False
        for _ in range(2):
            bst.train_one_iter()
        return bst

    fused = train(False)
    eager = train(True)
    assert fused._fused_fns[2] is not None
    np.testing.assert_allclose(fused._raw_predict(X),
                               eager._raw_predict(X),
                               rtol=1e-4, atol=1e-5)


def test_segment_epoch_edges(rng, monkeypatch):
    """Epoch-while edge cases: a tiny compaction budget (compact after
    nearly every split -> many epochs), a 2-leaf tree (single split,
    inner loop exits on the leaf budget), and unsplittable data (root
    only; the outer loop must terminate without a split)."""
    import lightgbm_tpu.models.grower_seg as gs
    n = 2000
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(np.float64)

    with monkeypatch.context() as mp:
        mp.setattr(gs, "COMPACT_WASTE", 0.01)
        fused, seg = _train_pair(X, y, rng, n_iters=2, objective="binary",
                                 num_leaves=15, max_bin=31,
                                 min_data_in_leaf=5)
        _assert_tree_parity(fused, seg, X)
    # context exit restores the module default for the sub-cases below

    fused2, seg2 = _train_pair(X, y, rng, n_iters=1, objective="binary",
                               num_leaves=2, max_bin=31,
                               min_data_in_leaf=5)
    assert seg2.models[0].num_leaves == 2
    _assert_tree_parity(fused2, seg2, X)

    y_const = np.zeros(n)
    _, seg3 = _train_pair(X, y_const, rng, n_iters=1,
                          objective="regression", num_leaves=15,
                          max_bin=31, min_data_in_leaf=5)
    # the all-constant iteration is dropped entirely (reference
    # semantics, gbdt.cpp:543-551) — the point here is only that the
    # epoch-while terminated without a split instead of hanging
    assert seg3.models == []


def test_segment_parity_wide_features_gather_compaction(rng):
    """60 features packs past _MAX_SORT_OPERANDS, so compaction takes the
    argsort+gather path (the variadic TPU sort's compile time explodes
    with operand count — 2026-08-01 on-chip finding); trees must match
    the fused grower exactly either way."""
    from lightgbm_tpu.models.grower_seg import _MAX_SORT_OPERANDS
    n, F = 2500, 60
    assert F // 4 + 5 > _MAX_SORT_OPERANDS  # the path under test engages
    X = rng.normal(size=(n, F))
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.3 * X[:, 2] ** 2
         + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    fused, seg = _train_pair(X, y, rng, n_iters=3, objective="binary",
                             num_leaves=31, max_bin=63, min_data_in_leaf=5)
    # 57 of the 60 features are pure noise: deep-tail splits tie at the
    # f32-vs-bf16 histogram precision floor and legitimately pick
    # different noise features (verified identical with the sort path),
    # so compare the strong-signal prefix exactly + predictions overall
    for tf, ts in zip(fused.models, seg.models):
        assert np.array_equal(np.asarray(tf.split_feature)[:16],
                              np.asarray(ts.split_feature)[:16])
        assert np.array_equal(np.asarray(tf.threshold_in_bin)[:16],
                              np.asarray(ts.threshold_in_bin)[:16])
    # rows that fall through a divergent noise-tie split land in other
    # leaves (a few % per tree); a BROKEN permutation would scramble
    # nearly every row, so bound the affected fraction, not the max
    diff = np.abs(fused._raw_predict(X) - seg._raw_predict(X))
    assert np.mean(diff > 1e-3) < 0.25
    assert np.median(diff) < 1e-4


def test_compact_state_sort_vs_gather_exact(rng, monkeypatch):
    """Deterministic parity of compact_state's two implementations: the
    multi-operand sort path and the argsort+gather path must produce the
    IDENTICAL permuted layout on the same _SegState (both are stable
    sorts on the same key, so even duplicate leaf_ids tie-break the same
    way).  This closes the 25%-tolerance window the end-to-end
    wide-feature test above has to allow for noise-feature gain ties —
    the compaction itself is exact."""
    import lightgbm_tpu.models.grower_seg as gs
    import types

    F4, n, L, rb = 8, 256, 8, 8
    assert F4 // 4 + 5 <= gs._MAX_SORT_OPERANDS  # sort path engages
    binsT = jnp.asarray(rng.randint(0, 64, size=(F4, n)), dtype=jnp.uint8)
    # channels 0-5 live, 6-7 structurally zero (pack_channels layout —
    # both compaction paths only carry the live ones)
    w8 = jnp.zeros((8, n), dtype=jnp.bfloat16).at[:6].set(
        jnp.asarray(rng.normal(size=(6, n)), dtype=jnp.bfloat16))
    st = gs.fresh_state(
        binsT, w8, n, L, G_cols=F4, B=64, F=F4, max_blocks=n // rb,
        G0=1.0, H0=float(n), C0=float(n),
        fmeta=types.SimpleNamespace(cegb_used0=None),
        p=types.SimpleNamespace(use_cegb_coupled=False))
    # scattered leaf assignment with duplicates and one empty leaf
    lid = rng.randint(0, L, size=n)
    lid[lid == L - 2] = 0  # leaf L-2 empty: exercises the empty-interval fixup
    st = st._replace(leaf_id=jnp.asarray(lid, dtype=jnp.int32))

    by_sort = gs.compact_state(st, L, rb)
    monkeypatch.setattr(gs, "_MAX_SORT_OPERANDS", 0)  # force gather path
    by_gather = gs.compact_state(st, L, rb)

    for field in ("binsT", "w8", "order", "leaf_id", "leaf_lo", "leaf_hi"):
        a = np.asarray(getattr(by_sort, field))
        b = np.asarray(getattr(by_gather, field))
        assert np.array_equal(a, b), f"compact_state paths differ on {field}"
    # sanity: the layout really is leaf-sorted and a true permutation
    assert np.all(np.diff(np.asarray(by_sort.leaf_id)) >= 0)
    assert np.array_equal(np.sort(np.asarray(by_sort.order)), np.arange(n))
