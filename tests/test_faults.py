"""Fault-tolerance tests: the injection registry, OOM-degrading chunk
retry, non-finite guardrails, snapshot resume, crash salvage, and the
collective retry — every recovery path exercised deterministically via
LIGHTGBM_TPU_FAULTS.

``FAULT_MATRIX_CHUNK`` (set by tools/fault_matrix.sh) narrows the
chunk-size parametrization to one value so the matrix runs each
configuration in a clean process.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import Application
from lightgbm_tpu.utils.faults import (ENV_FAULTS, FAULTS, InjectedFault,
                                       parse_spec)
from lightgbm_tpu.utils.log import LightGBMError
from lightgbm_tpu.utils.telemetry import TELEMETRY

_MATRIX = os.environ.get("FAULT_MATRIX_CHUNK", "")
CHUNKS = [int(_MATRIX)] if _MATRIX else [1, 4]

PARAMS = {"objective": "regression", "num_leaves": 7, "verbose": -1,
          "min_data_in_leaf": 5, "seed": 7}


@pytest.fixture(autouse=True)
def _disarm():
    """Each test starts with clean telemetry (fault counts are global and
    accumulate across runs) and leaves no armed fault sites behind."""
    TELEMETRY.reset()
    yield
    os.environ.pop(ENV_FAULTS, None)
    FAULTS.configure()


def _arm(monkeypatch, spec):
    monkeypatch.setenv(ENV_FAULTS, spec)
    FAULTS.configure()


def _make_data(rng, n=240):
    X = rng.rand(n, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.rand(n)
    return X, y


# ---------------------------------------------------------------- registry
def test_parse_spec_grammar():
    spec = parse_spec("chunk/oom@1x2, grad/nonfinite@3 ,snapshot/io@0x*")
    assert spec == {"chunk/oom": (1, 2), "grad/nonfinite": (3, 1),
                    "snapshot/io": (0, None)}
    assert parse_spec("train/kill") == {"train/kill": (0, 1)}
    assert parse_spec("") == {}
    assert parse_spec("  , ") == {}


def test_parse_spec_rejects_unknown_site():
    with pytest.raises(ValueError, match="unknown fault site"):
        parse_spec("chunk/ooom")
    with pytest.raises(ValueError, match="bad fault spec"):
        parse_spec("chunk/oom@x")


def test_registry_occurrence_counting(monkeypatch):
    _arm(monkeypatch, "snapshot/io@1x2")
    assert not FAULTS.check("snapshot/io")   # occurrence 0: before start
    assert FAULTS.check("snapshot/io")       # occurrence 1
    assert FAULTS.check("snapshot/io")       # occurrence 2
    assert not FAULTS.check("snapshot/io")   # count exhausted
    # explicit-index probing respects start/count the same way
    _arm(monkeypatch, "grad/nonfinite@3")
    assert not FAULTS.check("grad/nonfinite", n=2)
    assert FAULTS.check("grad/nonfinite", n=3)
    assert not FAULTS.check("grad/nonfinite", n=4)


def test_registry_disabled_fast_path():
    os.environ.pop(ENV_FAULTS, None)
    FAULTS.configure()
    assert not FAULTS.enabled
    assert not FAULTS.check("chunk/oom")
    FAULTS.maybe_raise("chunk/oom")          # no-op when disarmed


def test_configure_resets_counters(monkeypatch):
    _arm(monkeypatch, "train/kill")
    assert FAULTS.check("train/kill")
    assert not FAULTS.check("train/kill")
    FAULTS.configure()                        # same env spec, fresh counters
    assert FAULTS.check("train/kill")


def test_env_wins_over_config(monkeypatch):
    monkeypatch.setenv(ENV_FAULTS, "chunk/oom@5")
    FAULTS.configure("chunk/oom@1,train/kill@2")
    armed = FAULTS.armed()
    assert armed["chunk/oom"]["start"] == 5   # env beat the config value
    assert armed["train/kill"]["start"] == 2  # config-only site kept


# ------------------------------------------------- OOM-degrading chunk retry
def test_oom_degrades_and_completes(rng, monkeypatch):
    X, y = _make_data(rng)
    clean = lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                      lgb.Dataset(X, label=y), num_boost_round=8)
    _arm(monkeypatch, "chunk/oom")
    faulted = lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                        lgb.Dataset(X, label=y), num_boost_round=8)
    assert faulted.current_iteration() == 8
    counts = faulted.train_stats["faults"]["counts"]
    assert counts["oom_degrade"] == 1
    assert counts["injected"] == 1
    # sub-chunk splitting is bit-exact: the degraded run's model matches
    # the clean run byte for byte
    assert faulted.model_to_string() == clean.model_to_string()


def test_oom_exhausts_to_actionable_error(rng, monkeypatch):
    X, y = _make_data(rng)
    _arm(monkeypatch, "chunk/oom@0x*")       # allocator never heals
    with pytest.raises(LightGBMError, match="even at\\s+chunk size 1") as ei:
        lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                  lgb.Dataset(X, label=y), num_boost_round=8)
    # the ladder took every rung before giving up: it spilled to host and
    # STILL exhausted, so the error says there is no further rung
    assert "next rung: none" in str(ei.value)
    assert "out-of-core" in str(ei.value)


# ------------------------------------------- out-of-core (host-spill) rung
@pytest.mark.parametrize("chunk", CHUNKS)
def test_oocore_h2d_fault_spills_and_completes(rng, monkeypatch, chunk):
    """An OOM at the resident bin-matrix upload escalates straight to the
    host-spill tier; the run completes and the model is byte-identical to
    the clean resident run."""
    X, y = _make_data(rng)
    clean = lgb.train(dict(PARAMS, tpu_boost_chunk=chunk),
                      lgb.Dataset(X, label=y), num_boost_round=8)
    _arm(monkeypatch, "oocore/h2d")          # single-fire at the upload
    faulted = lgb.train(dict(PARAMS, tpu_boost_chunk=chunk),
                        lgb.Dataset(X, label=y), num_boost_round=8)
    assert faulted.current_iteration() == 8
    counts = faulted.train_stats["faults"]["counts"]
    assert counts["oom_spill"] == 1
    assert counts["injected"] == 1
    assert "oom_degrade" not in counts        # no chunk ladder involved
    assert faulted.train_stats["memory"]["data_tier"] == "spill"
    assert faulted.model_to_string() == clean.model_to_string()


def test_oocore_ladder_bottoms_out_into_spill(rng, monkeypatch):
    """The full recovery ladder in one run: chunk 4 OOMs -> halve to 2 ->
    OOMs -> halve to 1 -> still OOMs -> spill the bin matrix to host ->
    training completes bit-identically."""
    X, y = _make_data(rng)
    clean = lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                      lgb.Dataset(X, label=y), num_boost_round=8)
    _arm(monkeypatch, "chunk/oom@0x3")
    faulted = lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                        lgb.Dataset(X, label=y), num_boost_round=8)
    assert faulted.current_iteration() == 8
    counts = faulted.train_stats["faults"]["counts"]
    assert counts["oom_degrade"] == 2         # 4 -> 2 -> 1
    assert counts["oom_spill"] == 1           # 1 -> out-of-core
    assert counts["injected"] == 3
    assert faulted.train_stats["memory"]["data_tier"] == "spill"
    assert faulted.model_to_string() == clean.model_to_string()


def test_oocore_h2d_exhausts_to_giveup(rng, monkeypatch):
    """Persistent transfer OOMs: the upload failure spills to host, the
    per-block streaming then exhausts every rung and the give-up error
    says no further rung exists."""
    X, y = _make_data(rng)
    _arm(monkeypatch, "oocore/h2d@0x*")
    with pytest.raises(LightGBMError, match="even at\\s+chunk size 1") as ei:
        lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                  lgb.Dataset(X, label=y), num_boost_round=8)
    assert "next rung: none" in str(ei.value)
    assert "out-of-core" in str(ei.value)


def test_oocore_admit_fault_forces_spill(rng, monkeypatch):
    """The oocore/admit site makes the proactive admission check fail
    deterministically: the run starts out-of-core without a single
    RESOURCE_EXHAUSTED and still trains byte-identically."""
    X, y = _make_data(rng)
    clean = lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                      lgb.Dataset(X, label=y), num_boost_round=8)
    _arm(monkeypatch, "oocore/admit")
    faulted = lgb.train(dict(PARAMS, tpu_boost_chunk=4),
                        lgb.Dataset(X, label=y), num_boost_round=8)
    counts = faulted.train_stats["faults"]["counts"]
    assert counts["oocore_admit"] == 1
    assert counts["injected"] == 1
    assert "oom_degrade" not in counts and "oom_spill" not in counts
    assert faulted.train_stats["memory"]["data_tier"] == "spill"
    assert faulted.model_to_string() == clean.model_to_string()


def test_oocore_spill_blocked_names_reason(rng, monkeypatch):
    """Satellite 3: data_in_hbm=resident pins the matrix in HBM, so the
    bottomed-out ladder's give-up error names the rung it could not
    take — and why."""
    X, y = _make_data(rng)
    _arm(monkeypatch, "chunk/oom@0x*")
    with pytest.raises(LightGBMError, match="even at\\s+chunk size 1") as ei:
        lgb.train(dict(PARAMS, tpu_boost_chunk=4, data_in_hbm="resident"),
                  lgb.Dataset(X, label=y), num_boost_round=8)
    msg = str(ei.value)
    assert "spill unavailable" in msg
    assert "data_in_hbm=resident" in msg


# ------------------------------------------------------ non-finite guardrail
@pytest.mark.parametrize("chunk", CHUNKS)
def test_nonfinite_rolls_back_to_last_good(rng, monkeypatch, chunk):
    X, y = _make_data(rng)
    _arm(monkeypatch, "grad/nonfinite@2")
    bst = lgb.Booster(params=dict(PARAMS, tpu_boost_chunk=chunk),
                      train_set=lgb.Dataset(X, label=y))
    with pytest.raises(LightGBMError, match="Non-finite") as ei:
        for _ in range(4):
            if chunk > 1:
                bst.update_chunk(chunk)
            else:
                bst.update()
    # the error names the failing iteration (or the chunk holding it)
    msg = str(ei.value)
    assert ("iteration 2" in msg if chunk == 1 else "iterations 0..3" in msg)
    assert "regression" in msg
    # every iteration before the poisoned one survives; nothing after
    kept = bst.current_iteration()
    assert kept == (0 if chunk > 1 else 2)    # chunk 0..3 dropped whole
    counts = TELEMETRY.stats()["faults"]["counts"]
    assert counts["nonfinite_rollback"] == 1


def test_nonfinite_disabled_by_config(rng, monkeypatch):
    X, y = _make_data(rng)
    _arm(monkeypatch, "grad/nonfinite@1")
    # escape hatch: check_nonfinite=false trains through the NaNs
    bst = lgb.train(dict(PARAMS, check_nonfinite=False),
                    lgb.Dataset(X, label=y), num_boost_round=4)
    assert bst.current_iteration() >= 1


# ------------------------------------------------------ CLI snapshots/resume
def _write_csv(path, rng, n=300):
    X = rng.rand(n, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.rand(n)
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.6f")


def _cli_argv(extra=()):
    return ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "num_iterations=8", "num_leaves=7",
            "min_data_in_leaf=5", "verbosity=-1", "snapshot_freq=2",
            "output_model=model.txt", "metrics_out=metrics.json",
            *extra]


@pytest.mark.parametrize("chunk", CHUNKS)
def test_kill_and_resume_is_bitexact(tmp_path, rng, monkeypatch, chunk):
    """ISSUE acceptance: injected kill + resume=true produces a model
    byte-identical to the uninterrupted run (identical argv, so even the
    parameters section matches)."""
    seed = rng.randint(1 << 30)
    a, b = tmp_path / "a", tmp_path / "b"
    for d in (a, b):
        d.mkdir()
        _write_csv(d / "train.csv", np.random.RandomState(seed))
    argv = _cli_argv([f"tpu_boost_chunk={chunk}"])

    monkeypatch.chdir(a)
    Application(argv).run()                   # uninterrupted reference run

    monkeypatch.chdir(b)
    _arm(monkeypatch, "train/kill@4")
    with pytest.raises(InjectedFault):
        Application(argv).run()
    assert (b / "model.txt.partial").exists()
    assert not (b / "model.txt").exists()
    blob = json.loads((b / "metrics.json").read_text())
    assert blob["faults"]["counts"]["partial_save"] == 1

    monkeypatch.delenv(ENV_FAULTS)
    Application(argv + ["resume=true"]).run()
    assert (b / "model.txt").read_bytes() == (a / "model.txt").read_bytes()
    blob = json.loads((b / "metrics.json").read_text())
    assert blob["faults"]["counts"]["resume"] == 1


def test_snapshot_io_failure_does_not_abort(tmp_path, rng, monkeypatch):
    _write_csv(tmp_path / "train.csv", rng)
    monkeypatch.chdir(tmp_path)
    _arm(monkeypatch, "snapshot/io@0x*")      # every snapshot write fails
    Application(_cli_argv()).run()
    assert (tmp_path / "model.txt").exists()  # run completed regardless
    assert not list(tmp_path.glob("model.txt.snapshot_iter_*"))
    blob = json.loads((tmp_path / "metrics.json").read_text())
    assert blob["faults"]["counts"]["snapshot_io"] == 4  # 8 iters, freq 2


def test_snapshot_retention(tmp_path, rng, monkeypatch):
    _write_csv(tmp_path / "train.csv", rng)
    monkeypatch.chdir(tmp_path)
    Application(_cli_argv(["snapshot_keep=1"])).run()
    snaps = sorted(p.name for p in tmp_path.glob("model.txt.snapshot_iter_*")
                   if not p.name.endswith(".npz"))
    assert snaps == ["model.txt.snapshot_iter_8"]
    assert (tmp_path / "model.txt.snapshot_iter_8.state.npz").exists()


def test_resume_without_snapshot_starts_fresh(tmp_path, rng, monkeypatch):
    _write_csv(tmp_path / "train.csv", rng)
    monkeypatch.chdir(tmp_path)
    Application(_cli_argv(["resume=true"])).run()
    assert (tmp_path / "model.txt").exists()


def test_find_latest_requires_sidecar(tmp_path):
    from lightgbm_tpu.utils.snapshots import (find_latest_snapshot,
                                              prune_snapshots)
    model = str(tmp_path / "m.txt")
    for it in (2, 4, 6):
        (tmp_path / f"m.txt.snapshot_iter_{it}").write_text("x")
        if it != 6:                           # 6 is torn: no sidecar
            (tmp_path / f"m.txt.snapshot_iter_{it}.state.npz").write_bytes(
                b"x")
    path, it = find_latest_snapshot(model)
    assert it == 4 and path.endswith("snapshot_iter_4")
    prune_snapshots(model, keep=1)
    left = sorted(p.name for p in tmp_path.glob("m.txt.snapshot_iter_*"))
    assert left == ["m.txt.snapshot_iter_6"]  # newest kept (sidecar or not)


# ---------------------------------------------------- engine/network/atomic
def test_engine_flushes_train_stats_on_crash(rng):
    X, y = _make_data(rng)
    seen = {}

    def boom(env):
        seen["model"] = env.model
        if env.iteration >= 1:
            raise RuntimeError("callback crash")

    with pytest.raises(RuntimeError, match="callback crash"):
        lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=6,
                  callbacks=[boom])
    # engine.py's finally still bound the run's telemetry to the booster
    assert seen["model"].train_stats is not None
    assert "spans" in seen["model"].train_stats


def test_dispose_resets_collective_stats():
    from lightgbm_tpu.parallel import network
    network.record_collective("allgather_obj", 128, 0.001)
    assert network.collective_stats()
    network.dispose()
    assert network.collective_stats() == {}
    # back-to-back runs: the second starts from zeroed counters
    network.record_collective("allgather_obj", 64, 0.001)
    assert network.collective_stats()["allgather_obj"]["calls"] == 1
    network.dispose()


def test_allgather_retries_once(monkeypatch):
    from lightgbm_tpu.parallel import network
    _arm(monkeypatch, "collective/allgather")
    TELEMETRY.reset()
    assert network.allgather_obj({"rank": 0}) == [{"rank": 0}]
    counts = TELEMETRY.stats()["faults"]["counts"]
    assert counts["collective_retry"] == 1
    _arm(monkeypatch, "collective/allgather@0x*")
    with pytest.raises(InjectedFault):        # second failure propagates
        network.allgather_obj({"rank": 0})


def test_atomic_save_leaves_no_tmp(tmp_path, rng):
    X, y = _make_data(rng)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=3)
    out = tmp_path / "m.txt"
    bst.save_model(str(out))
    reread = lgb.Booster(model_file=str(out))
    assert reread.current_iteration() == 3
    bst.save_model(str(out))                  # overwrite in place
    assert [p.name for p in tmp_path.iterdir()] == ["m.txt"]


def test_fault_events_in_chrome_trace(rng, monkeypatch):
    X, y = _make_data(rng)
    _arm(monkeypatch, "chunk/oom")
    lgb.train(dict(PARAMS, tpu_boost_chunk=4),
              lgb.Dataset(X, label=y), num_boost_round=8)
    trace = TELEMETRY.chrome_trace()
    names = {ev.get("name") for ev in trace["traceEvents"]}
    assert "fault/oom_degrade" in names
    assert "fault/injected" in names
