"""Quality parity against the actual reference binary.

test_reference_interop.py proves the model FILES interchange exactly;
this file proves the TRAINING ALGORITHM matches: identical data and
parameters through both frameworks must reach the same heldout quality
(within a small tolerance absorbing bf16 hi/lo histogram precision and
tie-breaking differences).  Uses the same cached reference build.
"""

import os
import subprocess

import numpy as np
import pytest

from tests.test_reference_interop import (REFERENCE, _build_reference,
                                          _example, _load_examples_data,
                                          _run_ref)


@pytest.fixture(scope="module")
def ref_cli():
    return _build_reference()


def _logloss(y, p):
    p = np.clip(p, 1e-15, 1 - 1e-15)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def test_binary_training_quality_parity(ref_cli, tmp_path):
    import lightgbm_tpu as lgb

    ex = _example("binary_classification")
    params = dict(objective="binary", num_leaves=31, max_bin=255,
                  learning_rate=0.1, min_data_in_leaf=20)
    n_rounds = 30

    model = tmp_path / "ref.txt"
    _run_ref(ref_cli, ex, task="train", data="binary.train",
             num_trees=n_rounds, output_model=str(model), verbosity=-1,
             **params)
    pred_file = tmp_path / "ref_pred.txt"
    _run_ref(ref_cli, ex, task="predict", data="binary.test",
             input_model=str(model), output_result=str(pred_file),
             verbosity=-1)
    Xt, yt = _load_examples_data("binary_classification", "binary.test",
                                 28)
    ll_ref = _logloss(yt, np.loadtxt(pred_file))

    X, y = _load_examples_data("binary_classification", "binary.train", 28)
    bst = lgb.train({**params, "verbose": -1}, lgb.Dataset(X, y),
                    num_boost_round=n_rounds, verbose_eval=False)
    ll_ours = _logloss(yt, bst.predict(Xt))

    # same algorithm family, same data, same budget: heldout quality
    # must match closely in BOTH directions
    assert ll_ours < ll_ref * 1.05, (ll_ours, ll_ref)
    assert ll_ref < ll_ours * 1.05, (ll_ours, ll_ref)


def test_multiclass_training_quality_parity(ref_cli, tmp_path):
    import lightgbm_tpu as lgb

    ex = _example("multiclass_classification")
    n_rounds = 20
    model = tmp_path / "ref.txt"
    _run_ref(ref_cli, ex, task="train", config="train.conf",
             num_trees=n_rounds, output_model=str(model), verbosity=-1)
    pred_file = tmp_path / "ref_pred.txt"
    _run_ref(ref_cli, ex, task="predict", data="multiclass.test",
             input_model=str(model), output_result=str(pred_file),
             verbosity=-1)
    test = np.loadtxt(os.path.join(ex, "multiclass.test"), delimiter="\t")
    yt = test[:, 0].astype(int)
    ref_p = np.loadtxt(pred_file)
    ll_ref = float(-np.mean(np.log(
        np.clip(ref_p[np.arange(len(yt)), yt], 1e-15, 1))))

    train = np.loadtxt(os.path.join(ex, "multiclass.train"),
                       delimiter="\t")
    # train.conf sets the benchmark params; mirror its core values
    bst = lgb.train({"objective": "multiclass", "num_class": 5,
                     "num_leaves": 31, "learning_rate": 0.05,
                     "min_data_in_leaf": 1, "max_bin": 255,
                     "verbose": -1},
                    lgb.Dataset(train[:, 1:], train[:, 0]),
                    num_boost_round=n_rounds, verbose_eval=False)
    our_p = bst.predict(test[:, 1:])
    ll_ours = float(-np.mean(np.log(
        np.clip(our_p[np.arange(len(yt)), yt], 1e-15, 1))))
    assert ll_ours < ll_ref * 1.10, (ll_ours, ll_ref)
    assert ll_ref < ll_ours * 1.10, (ll_ours, ll_ref)


def test_lambdarank_training_quality_parity(ref_cli, tmp_path):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.core.parser import parse_file_to_matrix
    from lightgbm_tpu.utils.dcg import DCGCalculator

    ex = _example("lambdarank")
    n_rounds = 20
    model = tmp_path / "ref.txt"
    _run_ref(ref_cli, ex, task="train", config="train.conf",
             num_trees=n_rounds, output_model=str(model), verbosity=-1)
    pred_file = tmp_path / "ref_pred.txt"
    _run_ref(ref_cli, ex, task="predict", data="rank.test",
             input_model=str(model), output_result=str(pred_file),
             verbosity=-1)
    ref_scores = np.loadtxt(pred_file)

    Xt, yt = parse_file_to_matrix(os.path.join(ex, "rank.test"), False,
                                  301)
    groups_t = np.loadtxt(os.path.join(ex, "rank.test.query"),
                          dtype=np.int64)

    calc = DCGCalculator()

    def mean_ndcg(scores, k=5):
        out, pos = [], 0
        for g in groups_t:
            lab = yt[pos:pos + g]
            mx = calc.cal_maxdcg_at_k(k, lab)
            if mx > 0:
                out.append(calc.cal_dcg_at_k(k, lab,
                                             scores[pos:pos + g]) / mx)
            pos += g
        return float(np.mean(out))

    X, y = parse_file_to_matrix(os.path.join(ex, "rank.train"), False, 301)
    groups = np.loadtxt(os.path.join(ex, "rank.train.query"),
                        dtype=np.int64)
    bst = lgb.train({"objective": "lambdarank", "num_leaves": 31,
                     "learning_rate": 0.1, "min_data_in_leaf": 1,
                     "max_bin": 255, "verbose": -1},
                    lgb.Dataset(X, y, group=groups),
                    num_boost_round=n_rounds, verbose_eval=False)
    ndcg_ref = mean_ndcg(ref_scores)
    ndcg_ours = mean_ndcg(bst.predict(Xt))
    assert ndcg_ours > ndcg_ref - 0.03, (ndcg_ours, ndcg_ref)


def test_regression_training_quality_parity(ref_cli, tmp_path):
    import lightgbm_tpu as lgb

    ex = _example("regression")
    n_rounds = 30
    params = dict(objective="regression", num_leaves=31, max_bin=255,
                  learning_rate=0.1, min_data_in_leaf=20)
    model = tmp_path / "ref.txt"
    _run_ref(ref_cli, ex, task="train", data="regression.train",
             num_trees=n_rounds, output_model=str(model), verbosity=-1,
             **params)
    pred_file = tmp_path / "ref_pred.txt"
    _run_ref(ref_cli, ex, task="predict", data="regression.test",
             input_model=str(model), output_result=str(pred_file),
             verbosity=-1)
    test = np.loadtxt(os.path.join(ex, "regression.test"), delimiter="\t")
    yt = test[:, 0]
    mse_ref = float(np.mean((np.loadtxt(pred_file) - yt) ** 2))

    # train OURS from the same FILE path: the example ships a
    # regression.train.init sidecar the reference CLI auto-applies (init
    # scores replace boost-from-average and do not carry into predict),
    # and our file loader honors the same sidecar contract
    bst = lgb.train({**params, "verbose": -1},
                    lgb.Dataset(os.path.join(ex, "regression.train")),
                    num_boost_round=n_rounds, verbose_eval=False)
    mse_ours = float(np.mean((bst.predict(test[:, 1:]) - yt) ** 2))
    assert mse_ours < mse_ref * 1.05, (mse_ours, mse_ref)
    assert mse_ref < mse_ours * 1.05, (mse_ours, mse_ref)
