"""Scalable text ingestion: chunked C-tokenized reading and the two-round
low-memory mode (dataset_loader.cpp:741-840)."""

import numpy as np

import lightgbm_tpu.core.parser as parser_mod
from lightgbm_tpu.config import Config
from lightgbm_tpu.core.parser import load_file_to_dataset


def _timed(fn, *args):
    import time
    t0 = time.perf_counter()
    fn(*args)
    return time.perf_counter() - t0


def _write_csv(path, y, X, extra_cols=()):
    cols = [y] + list(extra_cols) + [X[:, j] for j in range(X.shape[1])]
    np.savetxt(path, np.column_stack(cols), delimiter=",", fmt="%.6f")
    return str(path)


def test_two_round_matches_default(rng, tmp_path, monkeypatch):
    # several chunks worth of rows; sample covers everything so the
    # two-round reservoir and the default path see identical samples
    monkeypatch.setattr(parser_mod, "_CHUNK_ROWS", 400)
    n = 1000
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] > 0).astype(float)
    f = _write_csv(tmp_path / "d.csv", y, X)

    ds_a = load_file_to_dataset(f, Config(verbosity=-1))
    ds_b = load_file_to_dataset(f, Config(verbosity=-1, two_round=True))
    assert ds_b.num_data == n
    np.testing.assert_array_equal(ds_a.binned, ds_b.binned)
    np.testing.assert_allclose(ds_a.metadata.label, ds_b.metadata.label)
    for ma, mb in zip(ds_a.bin_mappers, ds_b.bin_mappers):
        np.testing.assert_allclose(ma.bin_upper_bound, mb.bin_upper_bound)


def test_two_round_weight_and_group_columns(rng, tmp_path):
    n = 600
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] > 0).astype(float)
    w = rng.uniform(0.5, 2.0, size=n).round(4)
    qid = np.repeat(np.arange(n // 50), 50).astype(float)
    f = _write_csv(tmp_path / "d.csv", y, X, extra_cols=(w, qid))
    cfg = Config(verbosity=-1, two_round=True, weight_column="1",
                 group_column="2")
    ds = load_file_to_dataset(f, cfg)
    assert ds.num_total_features == 4
    np.testing.assert_allclose(ds.metadata.weights, w, rtol=1e-5)
    assert ds.metadata.query_boundaries is not None
    assert len(ds.metadata.query_boundaries) == n // 50 + 1


def test_two_round_valid_set_reuses_reference_bins(rng, tmp_path):
    n = 500
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] > 0).astype(float)
    ftr = _write_csv(tmp_path / "train.csv", y, X)
    fva = _write_csv(tmp_path / "valid.csv", y[:200], X[:200])
    cfg = Config(verbosity=-1, two_round=True)
    train = load_file_to_dataset(ftr, cfg)
    valid = load_file_to_dataset(fva, cfg, reference=train)
    assert valid.bin_mappers is train.bin_mappers
    assert valid.binned.shape == (200, train.num_columns)
    # quantization through the reference mappers matches direct binning
    direct = train.create_valid(X[:200], y[:200])
    np.testing.assert_array_equal(valid.binned, direct.binned)


def test_reservoir_sample_bounded(rng, tmp_path, monkeypatch):
    """When rows exceed bin_construct_sample_cnt, the reservoir holds
    exactly that many rows and binning still succeeds."""
    monkeypatch.setattr(parser_mod, "_CHUNK_ROWS", 300)
    n = 2000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(float)
    f = _write_csv(tmp_path / "d.csv", y, X)
    cfg = Config(verbosity=-1, two_round=True, bin_construct_sample_cnt=500)
    ds = load_file_to_dataset(f, cfg)
    assert ds.num_data == n
    assert ds.binned.shape[0] == n
    # bins were fit from a 500-row sample but cover the full data range
    assert all(m.num_bin >= 2 for m in ds.bin_mappers)


def test_file_io_scheme_seam(tmp_path):
    """VirtualFileReader/Writer-equivalent seam (file_io.h:20): local
    paths pass through; registered schemes route to their handler;
    unregistered schemes raise a clear error."""
    import io

    import pytest

    from lightgbm_tpu.utils import file_io
    from lightgbm_tpu.utils.log import LightGBMError

    p = tmp_path / "x.csv"
    p.write_text("1,2\n")
    with file_io.open_file(str(p)) as fh:
        assert fh.read() == "1,2\n"
    assert file_io.exists(str(p))
    assert not file_io.exists(str(tmp_path / "missing.csv"))

    store = {"mem://a.csv": b"0,1\n2,3\n"}

    def opener(path, mode="r"):
        data = store[path]
        return io.StringIO(data.decode()) if "b" not in mode \
            else io.BytesIO(data)

    file_io.register_scheme("mem", opener)
    try:
        with file_io.open_file("mem://a.csv") as fh:
            assert fh.read().startswith("0,1")
        assert file_io.exists("mem://a.csv")
        # and the dataset loader reads through the seam end-to-end
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.core.parser import load_file_to_dataset
        store["mem://train.csv"] = (
            "\n".join(f"{i % 2},{i},{i * 2}" for i in range(64)) + "\n"
        ).encode()
        ds = load_file_to_dataset("mem://train.csv",
                                  Config(verbosity=-1, min_data_in_leaf=2))
        assert ds.num_data == 64
    finally:
        file_io.unregister_scheme("mem")

    with pytest.raises(LightGBMError, match="No file-IO handler"):
        file_io.open_file("hdfs://nn/path.csv")


def test_fsspec_backend_round_trip(tmp_path):
    """A REAL filesystem backend behind the seam (reference ships HDFS,
    src/io/file_io.cpp:60,99): fsspec's in-memory filesystem plays the
    remote store, with zero egress.  Covers model save/load and binary
    dataset save/load through `memory://` URIs end-to-end, plus the
    unregistered-scheme auto-registration path."""
    import numpy as np
    import pytest

    fsspec = pytest.importorskip("fsspec")

    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils import file_io

    rng = np.random.RandomState(11)
    X = rng.normal(size=(600, 5))
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 7}, lgb.Dataset(X, y),
                    num_boost_round=3, verbose_eval=False)
    pred = bst.predict(X)

    try:
        # NOT pre-registered: open_file must auto-register via fsspec
        file_io.unregister_scheme("memory")
        bst.save_model("memory://bucket/model.txt")
        bst2 = lgb.Booster(model_file="memory://bucket/model.txt")
        np.testing.assert_array_equal(pred, bst2.predict(X))
        assert file_io.exists("memory://bucket/model.txt")
        assert not file_io.exists("memory://bucket/nope.txt")

        # binary dataset cache through the same transport
        ds = lgb.Dataset(X, y)
        ds.construct()
        ds._handle.save_binary("memory://bucket/train.bin")
        from lightgbm_tpu.core.dataset import TpuDataset
        ds2 = TpuDataset.load_binary("memory://bucket/train.bin")
        assert ds2.num_data == 600
    finally:
        file_io.unregister_scheme("memory")


def test_native_libsvm_tokenizer_parity(tmp_path):
    """src/native/textparse.cpp must reproduce the Python LibSVM parser
    (the spec) exactly — including 0/1-based indices, out-of-order
    tokens, blank lines, nan values, and skipped qid: prefixes — and be
    an order of magnitude faster on a ~100k-token file."""
    import time

    import numpy as np
    import pytest

    from lightgbm_tpu.core import parser
    from lightgbm_tpu.core.native import parse_libsvm_native, text_lib

    if text_lib() is None:
        pytest.skip("no C++ toolchain")

    rng = np.random.RandomState(5)
    lines = []
    for i in range(4000):
        feats = sorted(rng.choice(40, size=rng.randint(1, 12),
                                  replace=False))
        toks = [f"{rng.normal():.6g}"]
        if i % 7 == 0:
            toks.append(f"qid:{i // 50}")      # skipped by both parsers
        toks += [f"{f}:{rng.normal():.6g}" for f in feats]
        if i % 211 == 0:
            toks.append("5:nan")
        lines.append(" ".join(toks))
        if i % 97 == 0:
            lines.append("")                   # blank lines are dropped
    text = "\n".join(lines) + "\n"

    expected = parser._parse_libsvm(text.splitlines())
    got = parse_libsvm_native(text.encode())
    assert got is not None
    np.testing.assert_array_equal(
        np.isnan(expected), np.isnan(got))
    np.testing.assert_allclose(np.nan_to_num(got),
                               np.nan_to_num(expected), rtol=0, atol=0)

    # end-to-end through load_file_to_dataset (native path inside)
    p = tmp_path / "train.libsvm"
    p.write_text(text)
    from lightgbm_tpu.config import Config
    ds = parser.load_file_to_dataset(str(p),
                                     Config(verbosity=-1,
                                            min_data_in_leaf=2))
    assert ds.num_data == expected.shape[0]

    # throughput: the native pass must beat the interpreter loop by >=5x
    # on a larger buffer (conservative: measured ~30-60x).  INTERLEAVED
    # best-of-3: single-shot wall-clock flaked under a loaded host
    # (2026-08-01, suite alongside an on-chip bench), and interleaving
    # exposes both sides to the same sustained load instead of letting
    # one side eat a bursty phase alone.
    big = (text * 10).encode()
    big_lines = big.decode().splitlines()
    t_native, t_python = [], []
    for _ in range(3):
        t_native.append(_timed(parse_libsvm_native, big))
        t_python.append(_timed(parser._parse_libsvm, big_lines))
    assert min(t_native) * 5 < min(t_python), (t_native, t_python)


def test_native_libsvm_rejects_malformed():
    """Malformed labels/values must NOT silently parse natively — the
    Python parser is the spec and it raises; the native pass returns
    None so the caller reaches that behavior."""
    import pytest

    from lightgbm_tpu.core.native import parse_libsvm_native, text_lib

    if text_lib() is None:
        pytest.skip("no C++ toolchain")
    for bad in (b"N/A 1:2.0\n", b"1.0 3:abc\n", b"1.0 3:0x10\n",
                b"1.0 3:\n", b"1.0 -1:5\n"):
        assert parse_libsvm_native(bad) is None, bad
    # and well-formed edge tokens still parse
    ok = parse_libsvm_native(b"1.0 0:nan 2:1e5\r\n\n-2 1:+.5\n")
    assert ok is not None and ok.shape == (2, 4)

