"""Scalable text ingestion: chunked C-tokenized reading and the two-round
low-memory mode (dataset_loader.cpp:741-840)."""

import numpy as np

import lightgbm_tpu.core.parser as parser_mod
from lightgbm_tpu.config import Config
from lightgbm_tpu.core.parser import load_file_to_dataset


def _write_csv(path, y, X, extra_cols=()):
    cols = [y] + list(extra_cols) + [X[:, j] for j in range(X.shape[1])]
    np.savetxt(path, np.column_stack(cols), delimiter=",", fmt="%.6f")
    return str(path)


def test_two_round_matches_default(rng, tmp_path, monkeypatch):
    # several chunks worth of rows; sample covers everything so the
    # two-round reservoir and the default path see identical samples
    monkeypatch.setattr(parser_mod, "_CHUNK_ROWS", 400)
    n = 1000
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] > 0).astype(float)
    f = _write_csv(tmp_path / "d.csv", y, X)

    ds_a = load_file_to_dataset(f, Config(verbosity=-1))
    ds_b = load_file_to_dataset(f, Config(verbosity=-1, two_round=True))
    assert ds_b.num_data == n
    np.testing.assert_array_equal(ds_a.binned, ds_b.binned)
    np.testing.assert_allclose(ds_a.metadata.label, ds_b.metadata.label)
    for ma, mb in zip(ds_a.bin_mappers, ds_b.bin_mappers):
        np.testing.assert_allclose(ma.bin_upper_bound, mb.bin_upper_bound)


def test_two_round_weight_and_group_columns(rng, tmp_path):
    n = 600
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] > 0).astype(float)
    w = rng.uniform(0.5, 2.0, size=n).round(4)
    qid = np.repeat(np.arange(n // 50), 50).astype(float)
    f = _write_csv(tmp_path / "d.csv", y, X, extra_cols=(w, qid))
    cfg = Config(verbosity=-1, two_round=True, weight_column="1",
                 group_column="2")
    ds = load_file_to_dataset(f, cfg)
    assert ds.num_total_features == 4
    np.testing.assert_allclose(ds.metadata.weights, w, rtol=1e-5)
    assert ds.metadata.query_boundaries is not None
    assert len(ds.metadata.query_boundaries) == n // 50 + 1


def test_two_round_valid_set_reuses_reference_bins(rng, tmp_path):
    n = 500
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] > 0).astype(float)
    ftr = _write_csv(tmp_path / "train.csv", y, X)
    fva = _write_csv(tmp_path / "valid.csv", y[:200], X[:200])
    cfg = Config(verbosity=-1, two_round=True)
    train = load_file_to_dataset(ftr, cfg)
    valid = load_file_to_dataset(fva, cfg, reference=train)
    assert valid.bin_mappers is train.bin_mappers
    assert valid.binned.shape == (200, train.num_columns)
    # quantization through the reference mappers matches direct binning
    direct = train.create_valid(X[:200], y[:200])
    np.testing.assert_array_equal(valid.binned, direct.binned)


def test_reservoir_sample_bounded(rng, tmp_path, monkeypatch):
    """When rows exceed bin_construct_sample_cnt, the reservoir holds
    exactly that many rows and binning still succeeds."""
    monkeypatch.setattr(parser_mod, "_CHUNK_ROWS", 300)
    n = 2000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(float)
    f = _write_csv(tmp_path / "d.csv", y, X)
    cfg = Config(verbosity=-1, two_round=True, bin_construct_sample_cnt=500)
    ds = load_file_to_dataset(f, cfg)
    assert ds.num_data == n
    assert ds.binned.shape[0] == n
    # bins were fit from a 500-row sample but cover the full data range
    assert all(m.num_bin >= 2 for m in ds.bin_mappers)


def test_file_io_scheme_seam(tmp_path):
    """VirtualFileReader/Writer-equivalent seam (file_io.h:20): local
    paths pass through; registered schemes route to their handler;
    unregistered schemes raise a clear error."""
    import io

    import pytest

    from lightgbm_tpu.utils import file_io
    from lightgbm_tpu.utils.log import LightGBMError

    p = tmp_path / "x.csv"
    p.write_text("1,2\n")
    with file_io.open_file(str(p)) as fh:
        assert fh.read() == "1,2\n"
    assert file_io.exists(str(p))
    assert not file_io.exists(str(tmp_path / "missing.csv"))

    store = {"mem://a.csv": b"0,1\n2,3\n"}

    def opener(path, mode="r"):
        data = store[path]
        return io.StringIO(data.decode()) if "b" not in mode \
            else io.BytesIO(data)

    file_io.register_scheme("mem", opener)
    try:
        with file_io.open_file("mem://a.csv") as fh:
            assert fh.read().startswith("0,1")
        assert file_io.exists("mem://a.csv")
        # and the dataset loader reads through the seam end-to-end
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.core.parser import load_file_to_dataset
        store["mem://train.csv"] = (
            "\n".join(f"{i % 2},{i},{i * 2}" for i in range(64)) + "\n"
        ).encode()
        ds = load_file_to_dataset("mem://train.csv",
                                  Config(verbosity=-1, min_data_in_leaf=2))
        assert ds.num_data == 64
    finally:
        file_io.unregister_scheme("mem")

    with pytest.raises(LightGBMError, match="No file-IO handler"):
        file_io.open_file("hdfs://nn/path.csv")
