"""On-device chunked boosting (tpu_boost_chunk).

The chunk path JITs T boosting iterations as ONE device program
(lax.scan over the same grad/step/roots closures the per-iteration
fused path uses) and batches all tree fetches at the chunk boundary.
Two properties are load-bearing and tested here:

  * exact parity — chunked and unchunked runs re-trace the SAME
    closures with the SAME PRNG split sequence, so the model dumps
    must be bit-identical (not approximately equal);
  * zero transfers inside the chunk — the dispatch itself must not
    pull anything to the host; jax.transfer_guard("disallow") around
    the guarded seam proves the fetch really is deferred.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def make_regression(rng, n=600, f=10):
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = X[:, 0] * 2 - 0.5 * X[:, 1] + rng.normal(size=n) * 0.1
    return X, y.astype(np.float64)


def _params(chunk, **kw):
    p = {"objective": "regression", "num_leaves": 7, "max_bin": 31,
         "min_data_in_leaf": 5, "verbose": -1, "tpu_boost_chunk": chunk}
    p.update(kw)
    return p


def _strip_chunk_param(model_str: str) -> str:
    """The dump records tpu_boost_chunk itself; parity is about trees."""
    return "\n".join(line for line in model_str.splitlines()
                     if not line.startswith("[tpu_boost_chunk:"))


def test_chunked_matches_unchunked_bitexact(rng):
    X, y = make_regression(rng)
    dumps = {}
    for chunk in (1, 4):
        bst = lgb.train(_params(chunk), lgb.Dataset(X, y),
                        num_boost_round=8)
        assert bst.num_trees() == 8
        dumps[chunk] = _strip_chunk_param(bst.model_to_string())
    assert dumps[4] == dumps[1]


def test_chunk_tail_shorter_than_chunk(rng):
    # 10 rounds at chunk=4 -> steps 4,4,2; the tail re-traces at T=2
    X, y = make_regression(rng)
    b1 = lgb.train(_params(1), lgb.Dataset(X, y), num_boost_round=10)
    b4 = lgb.train(_params(4), lgb.Dataset(X, y), num_boost_round=10)
    assert b4.num_trees() == 10
    assert (_strip_chunk_param(b4.model_to_string())
            == _strip_chunk_param(b1.model_to_string()))


def test_chunk_body_makes_no_transfers(rng):
    jax = pytest.importorskip("jax")
    X, y = make_regression(rng)
    bst = lgb.Booster(_params(4), lgb.Dataset(X, y))
    g = bst.gbdt
    assert g._chunk_ok(), "plain L2 run must be chunk-eligible"
    assert g.boost_chunk_size() == 4
    # first chunk compiles (compilation may transfer constants); the
    # second runs the cached executable under a hard transfer ban
    assert bst.update_chunk(4) is False
    g._chunk_guard = lambda: jax.transfer_guard("disallow")
    try:
        assert bst.update_chunk(4) is False
    finally:
        g._chunk_guard = None
    assert g.iter_ == 8
    assert len(g.models) == 8
    pred = np.asarray(bst.predict(X[:16]))
    assert pred.shape == (16,)
    assert np.all(np.isfinite(pred))


def test_chunk_eval_keeps_per_iteration_cadence(rng):
    # in-scan eval: an explicit chunk with a valid set attached keeps
    # the chunked dispatch AND the per-iteration eval cadence — the scan
    # body scores the valid set and computes l2 each iteration
    X, y = make_regression(rng)
    Xv, yv = make_regression(rng, n=200)
    ev = {}
    bst = lgb.train(_params(4), lgb.Dataset(X, y), num_boost_round=8,
                    valid_sets=[lgb.Dataset(Xv, yv)],
                    valid_names=["v"], evals_result=ev,
                    verbose_eval=False)
    assert len(ev["v"]["l2"]) == 8
    # chunk=1 routes through the same device eval program, so the values
    # must be IDENTICAL (not approximately equal) between chunk sizes
    ev1 = {}
    lgb.train(_params(1), lgb.Dataset(X, y), num_boost_round=8,
              valid_sets=[lgb.Dataset(Xv, yv)], valid_names=["v"],
              evals_result=ev1, verbose_eval=False)
    assert ev["v"]["l2"] == ev1["v"]["l2"]
    assert bst.num_trees() == 8


def test_inscan_eval_bit_identity_with_early_stopping(rng):
    # noise labels overfit immediately: the stop fires INSIDE the chunk
    # and the surplus tail-of-chunk trees must be rolled back, leaving
    # metric values, best_iteration and the final model bit-identical
    # between chunk sizes
    rs = np.random.RandomState(7)
    X = rs.rand(200, 5); y = rs.rand(200)
    Xv = rs.rand(120, 5); yv = rs.rand(120)
    out = {}
    for chunk in (8, 1):
        ev = {}
        bst = lgb.train(_params(chunk, learning_rate=0.5, num_leaves=15,
                                 max_bin=63, min_data_in_leaf=2),
                        lgb.Dataset(X, y), num_boost_round=40,
                        valid_sets=[lgb.Dataset(Xv, yv)],
                        valid_names=["v"], evals_result=ev,
                        verbose_eval=False, early_stopping_rounds=2)
        out[chunk] = (ev["v"]["l2"], bst.best_iteration, bst.num_trees(),
                      _strip_chunk_param(bst.model_to_string()))
    assert out[8][0] == out[1][0]          # metric values bit-identical
    assert out[8][1] == out[1][1]          # same early-stop iteration
    assert out[8][2] == out[1][2]          # surplus trees discarded
    assert out[8][3] == out[1][3]          # final model bit-identical
    assert out[8][2] < 8                   # the stop really was mid-chunk


def test_inscan_eval_dispatch_drop(rng):
    # the acceptance A/B: with a valid set attached, chunk=4 must fetch
    # ~4x fewer times than chunk=1 (2 chunk fetches vs 8 for 8 rounds)
    from lightgbm_tpu.utils.telemetry import TELEMETRY
    X, y = make_regression(rng)
    Xv, yv = make_regression(rng, n=200)
    fetches = {}
    for chunk in (4, 1):
        TELEMETRY.reset()
        lgb.train(_params(chunk), lgb.Dataset(X, y), num_boost_round=8,
                  valid_sets=[lgb.Dataset(Xv, yv)], valid_names=["v"],
                  verbose_eval=False)
        fetches[chunk] = TELEMETRY.stats()["counters"].get(
            "transfer/fetch_calls", 0)
    assert fetches[4] == 2
    assert fetches[1] == 8


def test_feval_forces_per_iteration(rng):
    # a custom feval is host code: it must cleanly block in-scan eval
    # (falling back to per-iteration dispatch, still evaluating every
    # round) and name itself in the blocked gauge
    from lightgbm_tpu.utils.telemetry import TELEMETRY
    TELEMETRY.reset()
    X, y = make_regression(rng)
    Xv, yv = make_regression(rng, n=200)

    def fv(preds, ds):
        return "custom_l2", float(np.mean((preds - ds.get_label())**2)), False

    ev = {}
    lgb.train(_params(4), lgb.Dataset(X, y), num_boost_round=8,
              valid_sets=[lgb.Dataset(Xv, yv)], valid_names=["v"],
              evals_result=ev, verbose_eval=False, feval=fv)
    assert len(ev["v"]["l2"]) == 8
    assert len(ev["v"]["custom_l2"]) == 8
    gauges = TELEMETRY.stats()["gauges"]
    assert gauges.get("boost/inscan_blocked[feval]") == 1


def test_auto_chunk_preserves_eval_cadence(rng):
    # tpu_boost_chunk=0 (auto) must never change a run's eval cadence:
    # with a valid set attached the engine clamps auto back to 1
    X, y = make_regression(rng)
    Xv, yv = make_regression(rng, n=200)
    ev = {}
    lgb.train(_params(0), lgb.Dataset(X, y), num_boost_round=6,
              valid_sets=[lgb.Dataset(Xv, yv)], valid_names=["v"],
              evals_result=ev, verbose_eval=False)
    assert len(ev["v"]["l2"]) == 6


def test_before_callbacks_force_per_iteration(rng):
    # a before-iteration callback interacts with the host every round,
    # so the engine must clamp the chunk to 1 and fire it 6 times
    X, y = make_regression(rng)
    seen = []

    def before_cb(env):
        seen.append(env.iteration)
    before_cb.before_iteration = True

    bst = lgb.train(_params(4), lgb.Dataset(X, y), num_boost_round=6,
                    callbacks=[before_cb])
    assert bst.num_trees() == 6
    assert seen == list(range(6))


def test_goss_and_bagging_not_chunk_capable(rng):
    X, y = make_regression(rng)
    goss = lgb.Booster(_params(4, boosting="goss"), lgb.Dataset(X, y))
    assert goss.gbdt.boost_chunk_size() == 1
    bag = lgb.Booster(_params(4, bagging_fraction=0.5, bagging_freq=1),
                      lgb.Dataset(X, y))
    assert bag.gbdt.boost_chunk_size() == 1
    # ...and train_chunk on an ineligible booster still trains correctly
    assert bag.update_chunk(4) in (True, False)
    assert bag.gbdt.iter_ == 1  # fell back to a single iteration


def test_chunk_stops_on_constant_residuals(rng):
    # constant labels -> every tree is a constant stump; the flush must
    # detect it inside the first chunk, roll back, and stop
    X, _ = make_regression(rng, n=300)
    y = np.full(300, 3.25)
    bst = lgb.Booster(_params(4), lgb.Dataset(X, y))
    stopped = False
    for _ in range(3):
        if bst.update_chunk(4):
            stopped = True
            break
    assert stopped
    assert bst.gbdt.iter_ <= 4
    pred = np.asarray(bst.predict(X[:8]))
    np.testing.assert_allclose(pred, 3.25, rtol=1e-5)
