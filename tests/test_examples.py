"""Every shipped example must run end-to-end: gen_data -> train.conf ->
predict (the reference's examples/ are its de-facto acceptance suite)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    from lightgbm_tpu.utils import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    return env


@pytest.mark.parametrize("example,data", [
    ("binary_classification", "binary.test"),
    ("regression", "regression.test"),
    ("lambdarank", "rank.test"),
    ("multiclass_classification", "multiclass.test"),
    ("parallel_learning", "binary.test"),
])
def test_conf_example(example, data, tmp_path):
    src = os.path.join(REPO, "examples", example)
    work = tmp_path / example
    import shutil
    shutil.copytree(src, work)
    env = _env()
    r = subprocess.run([sys.executable, "gen_data.py"], cwd=work, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-500:]
    r = subprocess.run([sys.executable, "-m", "lightgbm_tpu",
                        "train.conf", "num_trees=8"], cwd=work, env=env,
                       capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, (r.stderr[-800:], r.stdout[-400:])
    assert (work / "LightGBM_model.txt").exists()
    r = subprocess.run([sys.executable, "-m", "lightgbm_tpu",
                        "task=predict", f"data={data}",
                        "input_model=LightGBM_model.txt",
                        "output_result=pred.txt"], cwd=work, env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-800:]
    assert (work / "pred.txt").exists()


@pytest.mark.parametrize("script", ["simple_example.py",
                                    "cross_validation.py"])
def test_python_guide(script, tmp_path):
    src = os.path.join(REPO, "examples", "python-guide", script)
    env = _env()
    r = subprocess.run([sys.executable, src], cwd=tmp_path, env=env,
                       capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, (r.stderr[-800:], r.stdout[-400:])
