"""R-package binding tests.

The reference ships R-package/ over src/lightgbm_R.cpp (lightgbm_R.h:528
surface).  Ours is R-package/src/lightgbm_tpu_R.c over the lightgbm_tpu
C API.  R is not in the test image, so coverage comes in two layers:

1. ALWAYS: compile the .Call shim against the functional mock R headers
   (tests/r_mock/) together with a C driver that feeds it mock SEXPs and
   runs dataset -> train -> predict -> save/load, asserting behavior.
2. WHEN R IS PRESENT: install the package with R CMD INSTALL and run an
   Rscript smoke (skipped otherwise).
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_env():
    from lightgbm_tpu.utils import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.fixture(scope="module")
def shim_driver(tmp_path_factory):
    from lightgbm_tpu.build_capi import build_capi
    so = build_capi()
    out = tmp_path_factory.mktemp("r_mock")
    exe = str(out / "driver")
    subprocess.run(
        ["gcc", "-O1", "-Wall", "-Werror=implicit-function-declaration",
         f"-I{REPO}/tests/r_mock", f"-I{REPO}/include",
         os.path.join(REPO, "R-package", "src", "lightgbm_tpu_R.c"),
         os.path.join(REPO, "tests", "r_mock", "driver.c"),
         so, f"-Wl,-rpath,{os.path.dirname(so)}", "-lm", "-o", exe],
        check=True)
    return exe


def test_r_shim_round_trip(shim_driver, tmp_path):
    """Mock-SEXP driver: dataset/metadata/train/eval/predict/save/load
    through the exact .Call entry points the R front end uses."""
    model = str(tmp_path / "model.txt")
    proc = subprocess.run([shim_driver, model], env=_cpu_env(),
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "driver OK" in proc.stdout
    assert os.path.exists(model)


def test_r_package_structure():
    """The installable package surface exists (DESCRIPTION/NAMESPACE/R/
    src/Makevars) and NAMESPACE exports match defined R functions."""
    pkg = os.path.join(REPO, "R-package")
    for f in ["DESCRIPTION", "NAMESPACE", "src/lightgbm_tpu_R.c",
              "src/Makevars", "R/lgb.Dataset.R", "R/lgb.Booster.R"]:
        assert os.path.exists(os.path.join(pkg, f)), f
    ns = open(os.path.join(pkg, "NAMESPACE")).read()
    r_src = "".join(
        open(os.path.join(pkg, "R", f)).read()
        for f in os.listdir(os.path.join(pkg, "R")))
    for export in ["lgb.Dataset", "lgb.train", "lgb.load", "lgb.save"]:
        assert f"export({export})" in ns
        assert f"{export} <- function" in r_src, export


def test_r_shim_registers_all_entry_points():
    """Every .Call made from R/ is a registered C entry point."""
    import re
    pkg = os.path.join(REPO, "R-package")
    c_src = open(os.path.join(pkg, "src", "lightgbm_tpu_R.c")).read()
    registered = set(re.findall(r"CALLDEF\((\w+),", c_src))
    r_src = "".join(
        open(os.path.join(pkg, "R", f)).read()
        for f in os.listdir(os.path.join(pkg, "R")))
    called = set(re.findall(r"\.Call\((\w+)", r_src))
    missing = called - registered
    assert not missing, f".Call targets not registered: {missing}"


@pytest.mark.skipif(shutil.which("R") is None or
                    shutil.which("Rscript") is None,
                    reason="R not installed")
def test_r_package_installs_and_trains(tmp_path):
    """Full R CMD INSTALL + Rscript train/predict smoke (real R only)."""
    lib = str(tmp_path / "rlib")
    os.makedirs(lib)
    env = _cpu_env()
    subprocess.run(["R", "CMD", "INSTALL", f"--library={lib}",
                    os.path.join(REPO, "R-package")],
                   check=True, env=env, timeout=600)
    script = tmp_path / "smoke.R"
    script.write_text(f"""
.libPaths("{lib}")
library(lightgbm.tpu)
set.seed(1)
X <- matrix(rnorm(4000), ncol = 4)
y <- as.numeric(X[, 1] > 0)
ds <- lgb.Dataset(X, label = y,
                  params = list(objective = "binary", verbosity = -1,
                                min_data_in_leaf = 5))
bst <- lgb.train(list(objective = "binary", verbosity = -1,
                      min_data_in_leaf = 5), ds, nrounds = 8)
p <- predict(bst, X)
stopifnot(mean((p > 0.5) == (y > 0.5)) > 0.9)
cat("R smoke OK\\n")
""")
    proc = subprocess.run(["Rscript", str(script)], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "R smoke OK" in proc.stdout


def _parse_trees_like_r(model_str):
    """Python mirror of R-package/R/lgb.interprete.R::lgb.model.dt.tree:
    same text-format fields, same parent reconstruction."""
    trees = []
    for block in model_str.split("\nTree=")[1:]:
        fields = {}
        for line in block.split("\n"):
            if "=" in line:
                k, v = line.split("=", 1)
                fields[k] = v.split(" ")
        num_leaves = int(fields["num_leaves"][0])
        leaf_value = [float(v) for v in fields.get("leaf_value", [0.0])]
        if num_leaves <= 1:
            trees.append({"stump": leaf_value[0]})
            continue
        t = {
            "split_feature": [int(v) for v in fields["split_feature"]],
            "internal_value": [float(v) for v in
                               fields["internal_value"]],
            "left_child": [int(v) for v in fields["left_child"]],
            "right_child": [int(v) for v in fields["right_child"]],
            "leaf_value": leaf_value,
        }
        n_nodes = num_leaves - 1
        node_parent = [-1] * n_nodes
        leaf_parent = [-1] * num_leaves
        for p in range(n_nodes):
            for child in (t["left_child"][p], t["right_child"][p]):
                if child >= 0:
                    node_parent[child] = p
                else:
                    leaf_parent[~child] = p
        t["node_parent"] = node_parent
        t["leaf_parent"] = leaf_parent
        trees.append(t)
    return trees


def test_interprete_contract():
    """The data contract R-package/R/lgb.interprete.R builds on: walking
    leaf->root through the TEXT model's split_feature/internal_value/
    child arrays, the per-feature contributions of a row must sum (with
    the root's expected value) to that row's raw prediction."""
    import numpy as np

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(3)
    X = rng.normal(size=(1200, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 15}, lgb.Dataset(X, y),
                    num_boost_round=8, verbose_eval=False)
    trees = _parse_trees_like_r(bst.model_to_string())
    leaves = bst.predict(X[:20], pred_leaf=True).astype(int)
    raw = bst.predict(X[:20], raw_score=True)
    for i in range(20):
        acc = 0.0
        per_feat = np.zeros(5)
        for t_idx, t in enumerate(trees):
            if "stump" in t:
                acc += t["stump"]
                continue
            leaf = leaves[i, t_idx]
            value = t["leaf_value"][leaf]
            deltas = np.zeros(5)
            p = t["leaf_parent"][leaf]
            while p >= 0:
                f = t["split_feature"][p]
                deltas[f] += value - t["internal_value"][p]
                value = t["internal_value"][p]
                p = t["node_parent"][p]
            acc += value + deltas.sum()
            per_feat += deltas
        assert abs(acc - raw[i]) < 1e-4, (i, acc, raw[i])
        assert np.abs(per_feat).sum() > 0
