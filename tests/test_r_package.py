"""R-package binding tests.

The reference ships R-package/ over src/lightgbm_R.cpp (lightgbm_R.h:528
surface).  Ours is R-package/src/lightgbm_tpu_R.c over the lightgbm_tpu
C API.  R is not in the test image, so coverage comes in two layers:

1. ALWAYS: compile the .Call shim against the functional mock R headers
   (tests/r_mock/) together with a C driver that feeds it mock SEXPs and
   runs dataset -> train -> predict -> save/load, asserting behavior.
2. WHEN R IS PRESENT: install the package with R CMD INSTALL and run an
   Rscript smoke (skipped otherwise).
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_env():
    from lightgbm_tpu.utils import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.fixture(scope="module")
def shim_driver(tmp_path_factory):
    from lightgbm_tpu.build_capi import build_capi
    so = build_capi()
    out = tmp_path_factory.mktemp("r_mock")
    exe = str(out / "driver")
    subprocess.run(
        ["gcc", "-O1", "-Wall", "-Werror=implicit-function-declaration",
         f"-I{REPO}/tests/r_mock", f"-I{REPO}/include",
         os.path.join(REPO, "R-package", "src", "lightgbm_tpu_R.c"),
         os.path.join(REPO, "tests", "r_mock", "driver.c"),
         so, f"-Wl,-rpath,{os.path.dirname(so)}", "-lm", "-o", exe],
        check=True)
    return exe


def test_r_shim_round_trip(shim_driver, tmp_path):
    """Mock-SEXP driver: dataset/metadata/train/eval/predict/save/load
    through the exact .Call entry points the R front end uses."""
    model = str(tmp_path / "model.txt")
    proc = subprocess.run([shim_driver, model], env=_cpu_env(),
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "driver OK" in proc.stdout
    assert os.path.exists(model)


def test_r_package_structure():
    """The installable package surface exists (DESCRIPTION/NAMESPACE/R/
    src/Makevars) and NAMESPACE exports match defined R functions."""
    pkg = os.path.join(REPO, "R-package")
    for f in ["DESCRIPTION", "NAMESPACE", "src/lightgbm_tpu_R.c",
              "src/Makevars", "R/lgb.Dataset.R", "R/lgb.Booster.R"]:
        assert os.path.exists(os.path.join(pkg, f)), f
    ns = open(os.path.join(pkg, "NAMESPACE")).read()
    r_src = "".join(
        open(os.path.join(pkg, "R", f)).read()
        for f in os.listdir(os.path.join(pkg, "R")))
    for export in ["lgb.Dataset", "lgb.train", "lgb.load", "lgb.save"]:
        assert f"export({export})" in ns
        assert f"{export} <- function" in r_src, export


def test_r_shim_registers_all_entry_points():
    """Every .Call made from R/ is a registered C entry point."""
    import re
    pkg = os.path.join(REPO, "R-package")
    c_src = open(os.path.join(pkg, "src", "lightgbm_tpu_R.c")).read()
    registered = set(re.findall(r"CALLDEF\((\w+),", c_src))
    r_src = "".join(
        open(os.path.join(pkg, "R", f)).read()
        for f in os.listdir(os.path.join(pkg, "R")))
    called = set(re.findall(r"\.Call\((\w+)", r_src))
    missing = called - registered
    assert not missing, f".Call targets not registered: {missing}"


@pytest.mark.skipif(shutil.which("R") is None or
                    shutil.which("Rscript") is None,
                    reason="R not installed")
def test_r_package_installs_and_trains(tmp_path):
    """Full R CMD INSTALL + Rscript train/predict smoke (real R only)."""
    lib = str(tmp_path / "rlib")
    os.makedirs(lib)
    env = _cpu_env()
    subprocess.run(["R", "CMD", "INSTALL", f"--library={lib}",
                    os.path.join(REPO, "R-package")],
                   check=True, env=env, timeout=600)
    script = tmp_path / "smoke.R"
    script.write_text(f"""
.libPaths("{lib}")
library(lightgbm.tpu)
set.seed(1)
X <- matrix(rnorm(4000), ncol = 4)
y <- as.numeric(X[, 1] > 0)
ds <- lgb.Dataset(X, label = y,
                  params = list(objective = "binary", verbosity = -1,
                                min_data_in_leaf = 5))
bst <- lgb.train(list(objective = "binary", verbosity = -1,
                      min_data_in_leaf = 5), ds, nrounds = 8)
p <- predict(bst, X)
stopifnot(mean((p > 0.5) == (y > 0.5)) > 0.9)
cat("R smoke OK\\n")
""")
    proc = subprocess.run(["Rscript", str(script)], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "R smoke OK" in proc.stdout
