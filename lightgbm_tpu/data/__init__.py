"""Out-of-core data tiers: host-resident bin storage streamed to HBM."""

from .hostspill import HostSpillStore

__all__ = ["HostSpillStore"]
