"""Host-spill (out-of-core) storage for the binned training matrix.

The final rung of the memory-pressure recovery ladder
(docs/ROBUSTNESS.md): when the binned matrix cannot stay resident in
HBM — the PR 4 chunk ladder bottomed out at 1, or the proactive
admission check said it never fit — the matrix moves to a
``HostSpillStore``: the exact host-side byte image that
``TpuDataset.device_binned()`` / ``device_binned_T()`` would upload,
split into fixed-order row-blocks.  Each dispatch window reassembles
the byte-identical device matrix by streaming the blocks
double-buffered: ``jax.device_put`` of block t+1 is issued before
block t is folded into the preallocated device buffer (a donated
jitted ``dynamic_update_slice_in_dim``), so the next host->device DMA
overlaps the current fold.  Because the reassembled matrix is
byte-identical to the resident upload and the bins are integers, every
downstream kernel sees identical inputs — spilled and resident
training produce **bit-identical models** by construction (this is the
"Out-of-Core GPU Gradient Boosting" posture of arxiv 2005.09148,
adapted to the lax.scan chunk loop: the matrix is resident DURING a
dispatch window and released between windows, which is what recovers
fragmentation and between-window working-set headroom).

Fault site ``oocore/h2d`` fires per block transfer (and at the
resident upload seam in models/gbdt.py), making the escalation path
deterministically testable.

Env knobs:
  LIGHTGBM_TPU_SPILL_BLOCK_MB  target block size in MiB (default 64)
  LIGHTGBM_TPU_SPILL_MMAP      directory: back the host matrix with a
                               memory-mapped .npy instead of RAM
"""

import os
import tempfile
from typing import Optional

import numpy as np

from ..utils.faults import FAULTS, oom_error
from ..utils.telemetry import TELEMETRY

DEFAULT_BLOCK_BYTES = 64 << 20

# one jitted fold per row axis; the two block shapes (full + tail)
# compile once each because the start offset enters as a traced scalar
_FOLDS = {}


def _fold_for(axis: int):
    if axis not in _FOLDS:
        import jax

        def fold(buf, blk, start):
            return jax.lax.dynamic_update_slice_in_dim(buf, blk, start,
                                                       axis=axis)

        _FOLDS[axis] = jax.jit(fold, donate_argnums=(0,))
    return _FOLDS[axis]


def _block_bytes_from_env() -> int:
    raw = os.environ.get("LIGHTGBM_TPU_SPILL_BLOCK_MB", "")
    try:
        mb = float(raw)
    except ValueError:
        mb = 0.0
    return int(mb * (1 << 20)) if mb > 0 else DEFAULT_BLOCK_BYTES


class HostSpillStore:
    """Fixed-order row-block view of one host bin matrix.

    ``mat`` is the exact array the resident path would upload (row-major
    [N, F], or the feature-major padded/packed [F', Npad] training
    layout); ``row_axis`` is the axis that indexes rows.  Blocks are
    contiguous slices along that axis in ascending order — the order is
    deterministic and the reassembled device matrix is byte-identical
    to ``jnp.asarray(mat)``, so bit-identity of the trained model needs
    no further argument.
    """

    def __init__(self, mat: np.ndarray, row_axis: int, block_rows: int,
                 mmap_path: Optional[str] = None):
        self.mat = mat
        self.row_axis = int(row_axis)
        self.shape = tuple(mat.shape)
        self.dtype = mat.dtype
        self.nbytes = int(mat.nbytes)
        self.num_rows = int(mat.shape[self.row_axis])
        self.block_rows = max(1, int(block_rows))
        self.num_blocks = -(-self.num_rows // self.block_rows)
        self.mmap_path = mmap_path

    # ------------------------------------------------------ construction
    @classmethod
    def from_matrix(cls, mat: np.ndarray, row_axis: int,
                    block_bytes: Optional[int] = None,
                    mmap_dir: Optional[str] = None) -> "HostSpillStore":
        """Build a store over ``mat``; block size targets ``block_bytes``
        (env LIGHTGBM_TPU_SPILL_BLOCK_MB, default 64MiB) per transfer.
        ``mmap_dir`` (env LIGHTGBM_TPU_SPILL_MMAP) rehomes the matrix
        into a memory-mapped .npy so the host copy is pageable too; the
        file is unlinked immediately (the mapping keeps it alive), so
        nothing leaks on any exit path."""
        if block_bytes is None:
            block_bytes = _block_bytes_from_env()
        rows = int(mat.shape[row_axis])
        row_bytes = max(1, mat.nbytes // max(1, rows))
        block_rows = min(rows, max(1, block_bytes // row_bytes))
        mmap_path = None
        if mmap_dir is None:
            mmap_dir = os.environ.get("LIGHTGBM_TPU_SPILL_MMAP") or None
        if mmap_dir:
            fd, path = tempfile.mkstemp(suffix=".npy", prefix="spill_",
                                        dir=mmap_dir)
            os.close(fd)
            np.save(path, mat)
            mat = np.load(path, mmap_mode="r")
            mmap_path = path
            try:
                os.unlink(path)
            except OSError:
                pass
        return cls(mat, row_axis, block_rows, mmap_path=mmap_path)

    # ---------------------------------------------------------- blocks
    def block_bounds(self, i: int):
        a = i * self.block_rows
        return a, min(a + self.block_rows, self.num_rows)

    def block(self, i: int) -> np.ndarray:
        """Block ``i`` as a contiguous host array (one block's copy at a
        time — the only transient the spill tier materializes)."""
        a, b = self.block_bounds(i)
        sl = [slice(None)] * self.mat.ndim
        sl[self.row_axis] = slice(a, b)
        return np.ascontiguousarray(self.mat[tuple(sl)])

    # ------------------------------------------------------- streaming
    def _put_block(self, i: int):
        """Probe the injection site, then start block ``i``'s
        host->device transfer (async on TPU; sync-but-correct on CPU)."""
        import jax
        if FAULTS.enabled:
            FAULTS.maybe_raise("oocore/h2d", oom_error)
        blk = self.block(i)
        arr = jax.device_put(blk)
        TELEMETRY.counter_add("oocore/h2d_bytes", int(blk.nbytes))
        TELEMETRY.counter_add("oocore/h2d_blocks")
        return arr

    def stream_to_device(self):
        """Reassemble the full device matrix from the host blocks.

        Double-buffered: block t+1's device_put is issued before block
        t's fold, so (on TPU) the next DMA overlaps the current
        dynamic_update_slice.  The fold donates the accumulating buffer,
        so the device never holds more than matrix + one block + one
        in-flight block.
        """
        import jax.numpy as jnp
        fold = _fold_for(self.row_axis)
        buf = jnp.zeros(self.shape, dtype=self.dtype)
        if self.num_blocks == 0:
            return buf
        pending = self._put_block(0)
        for i in range(self.num_blocks):
            cur = pending
            pending = (self._put_block(i + 1)
                       if i + 1 < self.num_blocks else None)
            start, _ = self.block_bounds(i)
            buf = fold(buf, cur, start)
        return buf
