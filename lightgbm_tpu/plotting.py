"""Plotting helpers: importance / metric / tree visualizations.

Reference: python-package/lightgbm/plotting.py — plot_importance (:21),
plot_metric (:133), plot_tree + create_tree_digraph (:242+, graphviz).
Matplotlib/graphviz are optional; functions raise ImportError lazily like
the reference's compat layer.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .basic import Booster
from .utils.log import LightGBMError


def _check_not_tuple_of_2_elements(obj, obj_name="obj"):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None, title="Feature importance",
                    xlabel="Feature importance", ylabel="Features",
                    importance_type="split", max_num_features=None,
                    ignore_zero=True, figsize=None, dpi=None, grid=True,
                    precision=3, **kwargs):
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib "
                          "to plot importance.")
    if isinstance(booster, Booster):
        b = booster
    elif hasattr(booster, "booster_"):
        b = booster.booster_
    else:
        raise TypeError("booster must be Booster or LGBMModel.")
    importance = b.feature_importance(importance_type)
    feature_name = b.feature_name()
    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("Cannot plot empty feature importances.")
    labels, values = zip(*tuples)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                f"{x:.{precision}f}" if importance_type == "gain" else str(x),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None, dpi=None,
                grid=True):
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot metric.")
    if isinstance(booster, dict):
        eval_results = booster
    elif hasattr(booster, "evals_result_"):
        eval_results = booster.evals_result_
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = dataset_names or list(eval_results.keys())
    first = eval_results[names[0]]
    if metric is None:
        metric = list(first.keys())[0]
    for name in names:
        if metric not in eval_results[name]:
            continue
        results = eval_results[name][metric]
        ax.plot(range(len(results)), results, label=name)
    ax.legend(loc="best")
    if title:
        ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(metric if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None,
                               ylim=None, title="auto",
                               xlabel="Feature split value",
                               ylabel="Count", figsize=None, dpi=None,
                               grid=True):
    """Bar plot of the model's split threshold values for one feature
    (reference plotting.plot_split_value_histogram over
    Booster.get_split_value_histogram)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot the "
                          "split value histogram.")
    b = booster.booster_ if hasattr(booster, "booster_") else booster
    counts, edges = b.get_split_value_histogram(feature, bins=bins)
    if counts.sum() == 0:
        raise ValueError(
            f"Cannot plot split value histogram: the model never splits "
            f"on feature {feature!r}")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    centers = (edges[:-1] + edges[1:]) / 2.0
    widths = np.diff(edges) * width_coef
    ax.bar(centers, counts, width=widths)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title == "auto":
        title = f"Split value histogram for feature {feature}"
    if title:
        ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index=0, show_info=None, precision=3,
                        name=None, comment=None, **kwargs):
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree.")
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    model = booster.dump_model()
    tree_infos = model["tree_info"]
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range.")
    tree_info = tree_infos[tree_index]
    feature_names = model.get("feature_names")
    show_info = show_info or []

    graph = Digraph(name=name, comment=comment, **kwargs)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            nid = f"split{node['split_index']}"
            f = node["split_feature"]
            fname = (feature_names[f] if feature_names else f"Column_{f}")
            label = f"{fname} {node['decision_type']} " \
                f"{node['threshold']}"
            for info in show_info:
                if info in node:
                    label += f"\n{info}: {node[info]}"
            graph.node(nid, label=label)
            add(node["left_child"], nid, "yes")
            add(node["right_child"], nid, "no")
        else:
            nid = f"leaf{node.get('leaf_index', 0)}"
            label = f"leaf {node.get('leaf_index', 0)}: " \
                f"{round(node['leaf_value'], precision)}"
            graph.node(nid, label=label)
        if parent is not None:
            graph.edge(parent, nid, decision)

    add(tree_info["tree_structure"])
    return graph


def plot_tree(booster, ax=None, tree_index=0, figsize=None, dpi=None,
              show_info=None, precision=3, **kwargs):
    try:
        import matplotlib.pyplot as plt
        import matplotlib.image as image
    except ImportError:
        raise ImportError("You must install matplotlib to plot tree.")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    graph = create_tree_digraph(booster=booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                **kwargs)
    import io
    s = graph.pipe(format="png")
    img = image.imread(io.BytesIO(s))
    ax.imshow(img)
    ax.axis("off")
    return ax
