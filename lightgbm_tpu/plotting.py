"""Plotting helpers: importance / metric / tree visualizations.

Reference: python-package/lightgbm/plotting.py — plot_importance (:21),
plot_metric (:133), plot_tree + create_tree_digraph (:242+, graphviz).
Matplotlib/graphviz are optional; functions raise ImportError lazily like
the reference's compat layer.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .basic import Booster
from .utils.log import LightGBMError


def _decorate_axes(ax, *, xlim, ylim, title, xlabel, ylabel, grid):
    """Shared axis cosmetics for the plot_* helpers."""
    for name, lim, setter in (("xlim", xlim, ax.set_xlim),
                              ("ylim", ylim, ax.set_ylim)):
        if lim is None:
            continue
        if not (isinstance(lim, tuple) and len(lim) == 2):
            raise TypeError(f"{name} must be a tuple of 2 elements.")
        setter(lim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _to_booster(booster) -> Booster:
    if isinstance(booster, Booster):
        return booster
    if hasattr(booster, "booster_"):  # fitted sklearn estimator
        return booster.booster_
    raise TypeError("booster must be Booster or LGBMModel.")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None, title="Feature importance",
                    xlabel="Feature importance", ylabel="Features",
                    importance_type="split", max_num_features=None,
                    ignore_zero=True, figsize=None, dpi=None, grid=True,
                    precision=3, **kwargs):
    """Horizontal bar chart of per-feature importance, least important
    at the bottom (reference signature: plotting.py:21)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib "
                          "to plot importance.")
    b = _to_booster(booster)
    values = np.asarray(b.feature_importance(importance_type))
    names = np.asarray(b.feature_name(), dtype=object)
    order = np.argsort(values, kind="stable")
    if ignore_zero:
        order = order[values[order] > 0]
    if max_num_features is not None and max_num_features > 0:
        order = order[max(len(order) - max_num_features, 0):]
    if order.size == 0:
        raise ValueError("Cannot plot empty feature importances.")
    values, names = values[order], names[order]

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    rows = np.arange(order.size)
    ax.barh(rows, values, align="center", height=height, **kwargs)
    as_text = ((lambda v: f"{v:.{precision}f}")
               if importance_type == "gain" else str)
    for row, v in enumerate(values):
        ax.text(v + 1, row, as_text(v), va="center")
    ax.set_yticks(rows)
    ax.set_yticklabels(names)
    return _decorate_axes(ax, xlim=xlim, ylim=ylim, title=title,
                          xlabel=xlabel, ylabel=ylabel, grid=grid)


def plot_metric(booster, metric=None, dataset_names=None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None, dpi=None,
                grid=True):
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot metric.")
    if isinstance(booster, dict):
        eval_results = booster
    elif hasattr(booster, "evals_result_"):
        eval_results = booster.evals_result_
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = dataset_names or list(eval_results.keys())
    first = eval_results[names[0]]
    if metric is None:
        metric = list(first.keys())[0]
    for name in names:
        if metric not in eval_results[name]:
            continue
        results = eval_results[name][metric]
        ax.plot(range(len(results)), results, label=name)
    ax.legend(loc="best")
    if title:
        ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(metric if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None,
                               ylim=None, title="auto",
                               xlabel="Feature split value",
                               ylabel="Count", figsize=None, dpi=None,
                               grid=True):
    """Bar plot of the model's split threshold values for one feature
    (reference plotting.plot_split_value_histogram over
    Booster.get_split_value_histogram)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot the "
                          "split value histogram.")
    b = booster.booster_ if hasattr(booster, "booster_") else booster
    counts, edges = b.get_split_value_histogram(feature, bins=bins)
    if counts.sum() == 0:
        raise ValueError(
            f"Cannot plot split value histogram: the model never splits "
            f"on feature {feature!r}")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    centers = (edges[:-1] + edges[1:]) / 2.0
    widths = np.diff(edges) * width_coef
    ax.bar(centers, counts, width=widths)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title == "auto":
        title = f"Split value histogram for feature {feature}"
    if title:
        ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index=0, show_info=None, precision=3,
                        name=None, comment=None, **kwargs):
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree.")
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    model = booster.dump_model()
    tree_infos = model["tree_info"]
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range.")
    tree_info = tree_infos[tree_index]
    feature_names = model.get("feature_names")
    show_info = show_info or []

    graph = Digraph(name=name, comment=comment, **kwargs)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            nid = f"split{node['split_index']}"
            f = node["split_feature"]
            fname = (feature_names[f] if feature_names else f"Column_{f}")
            label = f"{fname} {node['decision_type']} " \
                f"{node['threshold']}"
            for info in show_info:
                if info in node:
                    label += f"\n{info}: {node[info]}"
            graph.node(nid, label=label)
            add(node["left_child"], nid, "yes")
            add(node["right_child"], nid, "no")
        else:
            nid = f"leaf{node.get('leaf_index', 0)}"
            label = f"leaf {node.get('leaf_index', 0)}: " \
                f"{round(node['leaf_value'], precision)}"
            graph.node(nid, label=label)
        if parent is not None:
            graph.edge(parent, nid, decision)

    add(tree_info["tree_structure"])
    return graph


def plot_tree(booster, ax=None, tree_index=0, figsize=None, dpi=None,
              show_info=None, precision=3, **kwargs):
    try:
        import matplotlib.pyplot as plt
        import matplotlib.image as image
    except ImportError:
        raise ImportError("You must install matplotlib to plot tree.")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    graph = create_tree_digraph(booster=booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                **kwargs)
    import io
    s = graph.pipe(format="png")
    img = image.imread(io.BytesIO(s))
    ax.imshow(img)
    ax.axis("off")
    return ax
