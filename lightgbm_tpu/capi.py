"""Python side of the C API (the bridge behind lib_lightgbm_tpu.so).

src/capi/c_api.cpp marshals every LGBM_* call into this module: raw
pointers arrive as integer addresses and are wrapped with zero-copy numpy
views; handles are integer ids minted here.  Semantics follow the
reference implementation (src/c_api.cpp:98-1831): the internal Booster
wrapper (c_api.cpp:98) maps onto basic.Booster, datasets onto
basic.Dataset.

This module is also directly importable for in-process testing — the C
layer adds only the ABI, error ring and GIL handling.
"""

from __future__ import annotations

import ctypes
import json
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .utils.log import LightGBMError

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3
C_API_DTYPE_INT8 = 4

C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3

_DTYPES = {
    C_API_DTYPE_FLOAT32: np.float32,
    C_API_DTYPE_FLOAT64: np.float64,
    C_API_DTYPE_INT32: np.int32,
    C_API_DTYPE_INT64: np.int64,
    C_API_DTYPE_INT8: np.int8,
}

_handles: Dict[int, object] = {}
_next_id = [1]


def _register(obj) -> int:
    hid = _next_id[0]
    _next_id[0] += 1
    _handles[hid] = obj
    return hid


def _get(hid: int):
    if hid == 0:
        return None
    try:
        return _handles[hid]
    except KeyError:
        raise LightGBMError(f"Invalid handle {hid}")


def free_handle(hid: int) -> None:
    _handles.pop(hid, None)


def _view(addr: int, count: int, dtype_code: int) -> np.ndarray:
    """Zero-copy numpy view over caller memory."""
    dt = np.dtype(_DTYPES[dtype_code])
    if addr == 0 or count == 0:
        return np.empty(0, dtype=dt)
    buf = (ctypes.c_char * (count * dt.itemsize)).from_address(addr)
    return np.frombuffer(buf, dtype=dt, count=count)


def _params_dict(parameters: Optional[str]) -> dict:
    from .config import str2map
    return str2map(parameters or "")


# ===================================================================
# Dataset
# ===================================================================

def _finish_dataset(ds: Dataset) -> int:
    ds.construct()
    return _register(ds)


def dataset_create_from_file(filename: str, parameters: str,
                             ref_id: int) -> int:
    ref = _get(ref_id)
    ds = Dataset(filename, params=_params_dict(parameters), reference=ref)
    return _finish_dataset(ds)


def dataset_create_from_mat(addr: int, data_type: int, nrow: int, ncol: int,
                            is_row_major: int, parameters: str,
                            ref_id: int) -> int:
    flat = _view(addr, nrow * ncol, data_type)
    mat = (flat.reshape(nrow, ncol) if is_row_major
           else flat.reshape(ncol, nrow).T)
    ds = Dataset(np.array(mat, dtype=np.float64),
                 params=_params_dict(parameters), reference=_get(ref_id))
    return _finish_dataset(ds)


def dataset_create_from_mats(nmat: int, data_addr: int, data_type: int,
                             nrow_addr: int, ncol: int, is_row_major: int,
                             parameters: str, ref_id: int) -> int:
    ptrs = _view(data_addr, nmat, C_API_DTYPE_INT64)
    nrows = _view(nrow_addr, nmat, C_API_DTYPE_INT32)
    parts = []
    for i in range(nmat):
        flat = _view(int(ptrs[i]), int(nrows[i]) * ncol, data_type)
        parts.append(flat.reshape(int(nrows[i]), ncol) if is_row_major
                     else flat.reshape(ncol, int(nrows[i])).T)
    mat = np.concatenate(parts, axis=0).astype(np.float64)
    ds = Dataset(mat, params=_params_dict(parameters), reference=_get(ref_id))
    return _finish_dataset(ds)


def _csr_to_dense(indptr_addr, indptr_type, indices_addr, data_addr,
                  data_type, nindptr, nelem, num_col):
    import scipy.sparse as sp
    indptr = np.array(_view(indptr_addr, nindptr, indptr_type))
    indices = np.array(_view(indices_addr, nelem, C_API_DTYPE_INT32))
    data = np.array(_view(data_addr, nelem, data_type), dtype=np.float64)
    return sp.csr_matrix((data, indices, indptr),
                         shape=(nindptr - 1, num_col)).toarray()


def dataset_create_from_csr(indptr_addr: int, indptr_type: int,
                            indices_addr: int, data_addr: int,
                            data_type: int, nindptr: int, nelem: int,
                            num_col: int, parameters: str,
                            ref_id: int) -> int:
    import scipy.sparse as sp
    indptr = np.array(_view(indptr_addr, nindptr, indptr_type))
    indices = np.array(_view(indices_addr, nelem, C_API_DTYPE_INT32))
    data = np.array(_view(data_addr, nelem, data_type), dtype=np.float64)
    csr = sp.csr_matrix((data, indices, indptr),
                        shape=(nindptr - 1, num_col))
    ds = Dataset(csr, params=_params_dict(parameters), reference=_get(ref_id))
    return _finish_dataset(ds)


def dataset_create_from_csc(col_ptr_addr: int, col_ptr_type: int,
                            indices_addr: int, data_addr: int,
                            data_type: int, ncol_ptr: int, nelem: int,
                            num_row: int, parameters: str,
                            ref_id: int) -> int:
    import scipy.sparse as sp
    col_ptr = np.array(_view(col_ptr_addr, ncol_ptr, col_ptr_type))
    indices = np.array(_view(indices_addr, nelem, C_API_DTYPE_INT32))
    data = np.array(_view(data_addr, nelem, data_type), dtype=np.float64)
    csc = sp.csc_matrix((data, indices, col_ptr),
                        shape=(num_row, ncol_ptr - 1))
    ds = Dataset(csc, params=_params_dict(parameters), reference=_get(ref_id))
    return _finish_dataset(ds)


def dataset_create_from_sampled_column(sample_data_addr: int,
                                       sample_indices_addr: int, ncol: int,
                                       num_per_col_addr: int,
                                       num_sample_row: int,
                                       num_total_row: int,
                                       parameters: str) -> int:
    """Bin mappers from sampled columns + empty dataset awaiting PushRows
    (reference c_api.cpp:446: CostructFromSampleData)."""
    data_ptrs = _view(sample_data_addr, ncol, C_API_DTYPE_INT64)
    idx_ptrs = _view(sample_indices_addr, ncol, C_API_DTYPE_INT64)
    num_per_col = _view(num_per_col_addr, ncol, C_API_DTYPE_INT32)
    # materialize the sampled matrix (missing entries = nan so bin bounds
    # come only from present values; push fills real values later)
    sample = np.full((num_sample_row, ncol), np.nan, dtype=np.float64)
    for c in range(ncol):
        n = int(num_per_col[c])
        vals = _view(int(data_ptrs[c]), n, C_API_DTYPE_FLOAT64)
        idxs = _view(int(idx_ptrs[c]), n, C_API_DTYPE_INT32)
        sample[idxs, c] = vals
    ds = Dataset(sample, params=_params_dict(parameters))
    ds.construct()
    handle = ds._handle
    pushed = _PushTarget(handle, num_total_row, ncol,
                         _params_dict(parameters))
    return _register(pushed)


class _PushTarget:
    """Dataset under streaming construction (PushRows*).

    Bin boundaries come from the alignment source, never from the pushed
    rows themselves (reference: CostructFromSampleData builds mappers from
    the sample, c_api.cpp:446; CreateByReference aligns with the reference
    dataset) — ``reference`` is a basic.Dataset to align with, or
    ``sampled`` a TpuDataset holding mappers built from sampled columns.
    """

    def __init__(self, sampled_handle, num_total_row: int, ncol: int,
                 params: dict, reference: Optional[Dataset] = None):
        self.sampled = sampled_handle        # TpuDataset with bin mappers
        self.reference = reference
        self.num_total_row = num_total_row
        self.ncol = ncol
        self.params = params
        self.rows = np.zeros((num_total_row, ncol), dtype=np.float64)
        self.pushed = 0
        self.dataset: Optional[Dataset] = None

    def push(self, mat: np.ndarray, start_row: int) -> None:
        n = mat.shape[0]
        self.rows[start_row:start_row + n] = mat
        self.pushed += n
        if self.pushed >= self.num_total_row:
            self.finish()

    def finish(self) -> None:
        if self.reference is not None:
            ds = Dataset(self.rows, params=self.params,
                         reference=self.reference)
            ds.construct()
        else:
            from .config import Config
            from .core.dataset import TpuDataset
            handle = TpuDataset.from_numpy(
                self.rows, config=Config.from_params(self.params),
                reference=self.sampled)
            ds = Dataset(self.rows, params=self.params)
            ds._handle = handle
        self.dataset = ds

    def as_dataset(self) -> Dataset:
        if self.dataset is None:
            self.finish()
        return self.dataset


def _resolve_dataset(hid: int) -> Dataset:
    obj = _get(hid)
    if isinstance(obj, _PushTarget):
        ds = obj.as_dataset()
        _handles[hid] = ds
        return ds
    return obj


def dataset_create_by_reference(ref_id: int, num_total_row: int) -> int:
    ref = _resolve_dataset(ref_id)
    tgt = _PushTarget(ref.construct()._handle, num_total_row,
                      ref.num_feature(), dict(ref.params), reference=ref)
    return _register(tgt)


def dataset_push_rows(hid: int, data_addr: int, data_type: int, nrow: int,
                      ncol: int, start_row: int) -> None:
    tgt = _get(hid)
    if not isinstance(tgt, _PushTarget):
        raise LightGBMError("PushRows on a finished dataset")
    flat = _view(data_addr, nrow * ncol, data_type)
    tgt.push(np.array(flat.reshape(nrow, ncol), dtype=np.float64), start_row)


def dataset_push_rows_by_csr(hid: int, indptr_addr: int, indptr_type: int,
                             indices_addr: int, data_addr: int,
                             data_type: int, nindptr: int, nelem: int,
                             num_col: int, start_row: int) -> None:
    tgt = _get(hid)
    if not isinstance(tgt, _PushTarget):
        raise LightGBMError("PushRowsByCSR on a finished dataset")
    mat = _csr_to_dense(indptr_addr, indptr_type, indices_addr, data_addr,
                        data_type, nindptr, nelem, num_col)
    tgt.push(mat, start_row)


def dataset_get_subset(hid: int, indices_addr: int, num_indices: int,
                       parameters: str) -> int:
    ds = _resolve_dataset(hid)
    idx = np.array(_view(indices_addr, num_indices, C_API_DTYPE_INT32))
    sub = ds.subset(idx.tolist(), params=_params_dict(parameters))
    sub.construct()
    return _register(sub)


def dataset_set_feature_names(hid: int, names: List[str]) -> None:
    ds = _resolve_dataset(hid)
    ds.feature_name = list(names)
    if ds._handle is not None:
        ds._handle.feature_names = list(names)


def dataset_get_feature_names(hid: int) -> List[str]:
    ds = _resolve_dataset(hid)
    ds.construct()
    return list(ds._handle.feature_names)


def dataset_save_binary(hid: int, filename: str) -> None:
    _resolve_dataset(hid).save_binary(filename)


def dataset_dump_text(hid: int, filename: str) -> None:
    ds = _resolve_dataset(hid)
    ds.construct()
    h = ds._handle
    with open(filename, "w") as fh:
        fh.write(f"num_data: {h.num_data}\n")
        fh.write(f"num_feature: {h.num_total_features}\n")
        for i, bm in enumerate(h.bin_mappers):
            fh.write(f"feature {i} num_bin={bm.num_bin}\n")
        np.savetxt(fh, h.binned[: min(h.num_data, 100)], fmt="%d")


_FIELD_SET_DTYPE = {"label": np.float32, "weight": np.float32,
                    "init_score": np.float64, "group": np.int32,
                    "query": np.int32}


def dataset_set_field(hid: int, field_name: str, data_addr: int,
                      num_element: int, dtype_code: int) -> None:
    ds = _resolve_dataset(hid)
    vals = np.array(_view(data_addr, num_element, dtype_code))
    if field_name in ("group", "query"):
        ds.set_field("group", vals)
    else:
        ds.set_field(field_name, vals)


def dataset_get_field(hid: int, field_name: str):
    ds = _resolve_dataset(hid)
    vals = ds.get_field(field_name)
    if vals is None:
        return (0, 0, C_API_DTYPE_FLOAT32)
    if field_name in ("label", "weight"):
        arr = np.ascontiguousarray(np.asarray(vals), dtype=np.float32)
        code = C_API_DTYPE_FLOAT32
    elif field_name == "init_score":
        arr = np.ascontiguousarray(np.asarray(vals), dtype=np.float64)
        code = C_API_DTYPE_FLOAT64
    else:
        arr = np.ascontiguousarray(np.asarray(vals), dtype=np.int32)
        code = C_API_DTYPE_INT32
    # keep the buffer alive on the python Dataset (reference keeps the
    # pointer into Metadata's vectors, dataset.h:118)
    if not hasattr(ds, "_field_buffers"):
        ds._field_buffers = {}
    ds._field_buffers[field_name] = arr
    return (arr.ctypes.data, int(arr.size), code)


def dataset_update_param(hid: int, parameters: str) -> None:
    ds = _resolve_dataset(hid)
    ds.params.update(_params_dict(parameters))


def dataset_get_num_data(hid: int) -> int:
    return _resolve_dataset(hid).num_data()


def dataset_get_num_feature(hid: int) -> int:
    return _resolve_dataset(hid).num_feature()


def dataset_add_features_from(tgt_id: int, src_id: int) -> None:
    tgt = _resolve_dataset(tgt_id)
    src = _resolve_dataset(src_id)
    tgt.construct()
    src.construct()
    tgt._handle.add_features_from(src._handle)


# ===================================================================
# Booster
# ===================================================================

def booster_create(train_id: int, parameters: str) -> int:
    train = _resolve_dataset(train_id)
    bst = Booster(params=_params_dict(parameters), train_set=train)
    bst._valid_handles = []       # parallel to gbdt valid sets
    return _register(bst)


def booster_create_from_modelfile(filename: str):
    bst = Booster(model_file=filename)
    return (_register(bst), bst.gbdt.current_iteration())


def booster_load_model_from_string(model_str: str):
    bst = Booster(model_str=model_str)
    return (_register(bst), bst.gbdt.current_iteration())


def booster_shuffle_models(hid: int, start_iter: int, end_iter: int) -> None:
    bst = _get(hid)
    models = bst.gbdt.models
    n = len(models)
    s = max(start_iter, 0)
    e = n if end_iter <= 0 else min(end_iter, n)
    seg = models[s:e]
    rng = np.random.RandomState(bst.gbdt.config.seed)
    rng.shuffle(seg)
    bst.gbdt.models = models[:s] + list(seg) + models[e:]


def booster_merge(hid: int, other_id: int) -> None:
    bst, other = _get(hid), _get(other_id)
    bst.gbdt.models = list(bst.gbdt.models) + list(other.gbdt.models)
    bst.gbdt.iter_ += other.gbdt.current_iteration()


def booster_add_valid_data(hid: int, valid_id: int) -> None:
    bst = _get(hid)
    valid = _resolve_dataset(valid_id)
    name = f"valid_{len(bst._valid_names)}"
    bst.add_valid(valid, name)


def booster_reset_training_data(hid: int, train_id: int) -> None:
    bst = _get(hid)
    train = _resolve_dataset(train_id)
    train.construct()
    # alignment is checked inside reset_train_data; bind the objective only
    # after it succeeds so a rejected swap leaves the booster untouched
    bst.gbdt.reset_train_data(train._handle)
    if bst.objective is not None:
        bst.objective.init(train._handle.metadata, train._handle.num_data)
    bst.train_set = train
    # metrics must re-bind to the new labels/num_data
    bst._setup_metrics()


def booster_reset_parameter(hid: int, parameters: str) -> None:
    from .config import Config
    bst = _get(hid)
    merged = dict(bst.params)
    merged.update(_params_dict(parameters))
    bst.params = merged
    bst.config = Config.from_params(merged)
    bst.gbdt.config = bst.config
    bst.gbdt.shrinkage_rate = bst.config.learning_rate
    bst.gbdt._fused_fns = None    # params may change the traced step
    bst._setup_metrics()


def booster_get_num_classes(hid: int) -> int:
    return max(1, _get(hid).config.num_class)


def booster_update_one_iter(hid: int) -> int:
    return int(bool(_get(hid).update()))


def booster_update_one_iter_custom(hid: int, grad_addr: int,
                                   hess_addr: int) -> int:
    bst = _get(hid)
    n = bst.gbdt.num_data * bst.gbdt.num_tree_per_iteration
    grad = np.array(_view(grad_addr, n, C_API_DTYPE_FLOAT32))
    hess = np.array(_view(hess_addr, n, C_API_DTYPE_FLOAT32))
    return int(bool(bst.gbdt.train_one_iter(grad, hess)))


def booster_refit(hid: int, leaf_preds_addr: int, nrow: int,
                  ncol: int) -> None:
    bst = _get(hid)
    leaf_preds = np.array(_view(leaf_preds_addr, nrow * ncol,
                                C_API_DTYPE_INT32)).reshape(nrow, ncol)
    bst.gbdt.refit(leaf_preds)


def booster_rollback_one_iter(hid: int) -> None:
    _get(hid).rollback_one_iter()


def booster_get_current_iteration(hid: int) -> int:
    return _get(hid).gbdt.current_iteration()


def booster_num_model_per_iteration(hid: int) -> int:
    return _get(hid).num_model_per_iteration()


def booster_number_of_total_model(hid: int) -> int:
    return _get(hid).num_trees()


def booster_get_eval_counts(hid: int) -> int:
    return len(_get(hid)._metric_names_expanded())


def booster_get_eval_names(hid: int) -> List[str]:
    return _get(hid)._metric_names_expanded()


def booster_get_feature_names(hid: int) -> List[str]:
    return _get(hid).feature_name()


def booster_get_num_feature(hid: int) -> int:
    return _get(hid).gbdt.max_feature_idx + 1


def booster_get_eval(hid: int, data_idx: int, out_addr: int) -> int:
    bst = _get(hid)
    if data_idx == 0:
        res = bst.gbdt.eval_train()
    else:
        res = bst.gbdt.eval_valid(data_idx - 1)
    vals = np.array([v for (_, v, _) in res], dtype=np.float64)
    out = _view(out_addr, len(vals), C_API_DTYPE_FLOAT64)
    out[:] = vals
    return len(vals)


def _inner_scores(bst: Booster, data_idx: int) -> np.ndarray:
    if data_idx == 0:
        return np.asarray(bst.gbdt.train_score, dtype=np.float64)
    return np.asarray(bst.gbdt.valid_scores[data_idx - 1], dtype=np.float64)


def booster_get_num_predict(hid: int, data_idx: int) -> int:
    return int(_inner_scores(_get(hid), data_idx).size)


def booster_get_predict(hid: int, data_idx: int, out_addr: int) -> int:
    """Raw scores of train/valid set, row-major [N, C]
    (reference Booster::GetPredictAt, gbdt.cpp:GetPredictAt)."""
    bst = _get(hid)
    score = _inner_scores(bst, data_idx)        # [C, N]
    flat = score.T.reshape(-1)
    out = _view(out_addr, flat.size, C_API_DTYPE_FLOAT64)
    out[:] = flat
    return flat.size


def booster_calc_num_predict(hid: int, num_row: int, predict_type: int,
                             num_iteration: int) -> int:
    bst = _get(hid)
    C = bst.num_model_per_iteration()
    n_iter = bst.gbdt.current_iteration()
    if num_iteration > 0:
        n_iter = min(n_iter, num_iteration)
    if predict_type == C_API_PREDICT_LEAF_INDEX:
        return num_row * C * n_iter
    if predict_type == C_API_PREDICT_CONTRIB:
        return num_row * C * (bst.gbdt.max_feature_idx + 2)
    return num_row * C


def _predict_common(bst: Booster, X: np.ndarray, predict_type: int,
                    num_iteration: int, out_addr: int) -> int:
    kwargs = dict(num_iteration=num_iteration if num_iteration > 0 else -1)
    if predict_type == C_API_PREDICT_RAW_SCORE:
        res = bst.predict(X, raw_score=True, **kwargs)
    elif predict_type == C_API_PREDICT_LEAF_INDEX:
        res = bst.predict(X, pred_leaf=True, **kwargs)
    elif predict_type == C_API_PREDICT_CONTRIB:
        res = bst.predict(X, pred_contrib=True, **kwargs)
    else:
        res = bst.predict(X, **kwargs)
    flat = np.asarray(res, dtype=np.float64).reshape(-1)
    out = _view(out_addr, flat.size, C_API_DTYPE_FLOAT64)
    out[:] = flat
    return flat.size


def booster_predict_for_mat(hid: int, data_addr: int, data_type: int,
                            nrow: int, ncol: int, is_row_major: int,
                            predict_type: int, num_iteration: int,
                            parameter: str, out_addr: int) -> int:
    bst = _get(hid)
    flat = _view(data_addr, nrow * ncol, data_type)
    X = (flat.reshape(nrow, ncol) if is_row_major
         else flat.reshape(ncol, nrow).T)
    return _predict_common(bst, np.array(X, dtype=np.float64), predict_type,
                           num_iteration, out_addr)


def booster_predict_for_mats(hid: int, data_addr: int, data_type: int,
                             nrow: int, ncol: int, predict_type: int,
                             num_iteration: int, parameter: str,
                             out_addr: int) -> int:
    ptrs = _view(data_addr, nrow, C_API_DTYPE_INT64)
    X = np.zeros((nrow, ncol), dtype=np.float64)
    for i in range(nrow):
        X[i] = _view(int(ptrs[i]), ncol, data_type)
    return _predict_common(_get(hid), X, predict_type, num_iteration,
                           out_addr)


def booster_predict_for_csr(hid: int, indptr_addr: int, indptr_type: int,
                            indices_addr: int, data_addr: int,
                            data_type: int, nindptr: int, nelem: int,
                            num_col: int, predict_type: int,
                            num_iteration: int, parameter: str,
                            out_addr: int) -> int:
    X = _csr_to_dense(indptr_addr, indptr_type, indices_addr, data_addr,
                      data_type, nindptr, nelem, num_col)
    return _predict_common(_get(hid), X, predict_type, num_iteration,
                           out_addr)


def booster_predict_for_csc(hid: int, col_ptr_addr: int, col_ptr_type: int,
                            indices_addr: int, data_addr: int,
                            data_type: int, ncol_ptr: int, nelem: int,
                            num_row: int, predict_type: int,
                            num_iteration: int, parameter: str,
                            out_addr: int) -> int:
    col_ptr = _view(col_ptr_addr, ncol_ptr, col_ptr_type)
    indices = _view(indices_addr, nelem, C_API_DTYPE_INT32)
    data = _view(data_addr, nelem, data_type)
    X = np.zeros((num_row, ncol_ptr - 1), dtype=np.float64)
    for c in range(ncol_ptr - 1):
        lo, hi = int(col_ptr[c]), int(col_ptr[c + 1])
        X[indices[lo:hi], c] = data[lo:hi]
    return _predict_common(_get(hid), X, predict_type, num_iteration,
                           out_addr)


def booster_predict_for_file(hid: int, data_filename: str,
                             data_has_header: int, predict_type: int,
                             num_iteration: int, parameter: str,
                             result_filename: str) -> None:
    from .core.parser import parse_file_to_matrix
    bst = _get(hid)
    X, _ = parse_file_to_matrix(data_filename, bool(data_has_header),
                                bst.gbdt.max_feature_idx + 1)
    kwargs = dict(num_iteration=num_iteration if num_iteration > 0 else -1)
    if predict_type == C_API_PREDICT_RAW_SCORE:
        res = bst.predict(X, raw_score=True, **kwargs)
    elif predict_type == C_API_PREDICT_LEAF_INDEX:
        res = bst.predict(X, pred_leaf=True, **kwargs)
    elif predict_type == C_API_PREDICT_CONTRIB:
        res = bst.predict(X, pred_contrib=True, **kwargs)
    else:
        res = bst.predict(X, **kwargs)
    res = np.asarray(res)
    if res.ndim == 1:
        res = res[:, None]
    with open(result_filename, "w") as fh:
        for row in res:
            fh.write("\t".join(repr(float(v)) for v in row) + "\n")


def booster_save_model(hid: int, start_iteration: int, num_iteration: int,
                       filename: str) -> None:
    _get(hid).save_model(filename, num_iteration=num_iteration,
                         start_iteration=start_iteration)


def booster_save_model_to_string(hid: int, start_iteration: int,
                                 num_iteration: int) -> str:
    return _get(hid).model_to_string(num_iteration=num_iteration,
                                     start_iteration=start_iteration)


def booster_dump_model(hid: int, start_iteration: int,
                       num_iteration: int) -> str:
    return json.dumps(_get(hid).dump_model(num_iteration=num_iteration))


def booster_get_leaf_value(hid: int, tree_idx: int, leaf_idx: int) -> float:
    bst = _get(hid)
    return float(bst.gbdt.models[tree_idx].leaf_value[leaf_idx])


def booster_set_leaf_value(hid: int, tree_idx: int, leaf_idx: int,
                           val: float) -> None:
    bst = _get(hid)
    bst.gbdt.models[tree_idx].leaf_value[leaf_idx] = val


def booster_feature_importance(hid: int, num_iteration: int,
                               importance_type: int, out_addr: int) -> None:
    bst = _get(hid)
    kind = "split" if importance_type == 0 else "gain"
    imp = bst.feature_importance(kind, num_iteration)
    out = _view(out_addr, len(imp), C_API_DTYPE_FLOAT64)
    out[:] = imp


# ===================================================================
# Network
# ===================================================================

def network_init(machines: str, local_listen_port: int,
                 listen_time_out: int, num_machines: int) -> None:
    from .parallel import network
    network.init_from_machines(machines, num_machines)


def network_free() -> None:
    from .parallel import network
    network.dispose()


def network_init_with_functions(num_machines: int, rank: int,
                                reduce_scatter_addr: int,
                                allgather_addr: int) -> None:
    """External-collective seam (LGBM_NetworkInitWithFunctions,
    c_api.cpp:1572).  On the TPU build collectives are XLA ops over the
    mesh, so the function pointers are recorded for introspection and the
    logical (num_machines, rank) registered with the network layer."""
    from .parallel import network
    network.init_with_functions(reduce_scatter_addr, allgather_addr,
                                rank, num_machines)


# helper used by basic.Booster metric names
def _metric_names_expanded(self: Booster) -> List[str]:
    names = []
    for m in self.gbdt.metrics:
        if hasattr(m, "eval_multi"):
            names.extend(f"{m.name}@{k}" for k in m.eval_at)
        else:
            names.append(m.name)
    return names


Booster._metric_names_expanded = _metric_names_expanded
