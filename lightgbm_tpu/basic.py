"""Public Dataset / Booster API.

Mirrors the reference python-package surface (python-package/lightgbm/basic.py:
``Dataset`` :664 with lazy construction, ``Booster`` :1612 with
update/eval/predict/save) so user code written against LightGBM's Python API
ports over unchanged.  Instead of crossing a ctypes boundary into
lib_lightgbm.so, these classes drive the in-process TPU training stack
directly (core.dataset.TpuDataset + models.GBDT).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config
from .core.dataset import TpuDataset
from .metric import default_metric_for_objective, metric_canonical_name
from .models.gbdt import GBDT
from .utils.log import LightGBMError, check, log_info, log_warning


def _pandas_categories(data) -> Optional[List[list]]:
    """Per-category-column category lists, in column order (None when the
    frame has no category columns / is not a frame)."""
    if not (hasattr(data, "dtypes") and hasattr(data, "columns")):
        return None
    out = [list(data[c].cat.categories) for c in data.columns
           if str(data[c].dtype) == "category"]
    return out or None


def _as_2d_float(data, num_features: Optional[int] = None,
                 pandas_categorical: Optional[List[list]] = None
                 ) -> np.ndarray:
    if hasattr(data, "dtypes") and hasattr(data, "columns") and any(
            str(dt) == "category" for dt in data.dtypes):
        # pandas DataFrame with category columns -> category CODES
        # (missing/unseen -> NaN), the reference's pandas handling.
        # ``pandas_categorical`` (recorded at train time and persisted in
        # the model file) pins the value->code mapping so predict frames
        # whose inferred category ORDER differs still encode correctly.
        n_cat = sum(1 for dt in data.dtypes if str(dt) == "category")
        if (pandas_categorical is not None
                and n_cat != len(pandas_categorical)):
            # positional matching would silently mis-align the mappings
            raise LightGBMError(
                f"train and predict/valid DataFrames have different "
                f"category-column counts ({len(pandas_categorical)} at "
                f"train, {n_cat} now)")
        cols = []
        cat_i = 0
        for c in data.columns:
            s = data[c]
            if str(s.dtype) == "category":
                if pandas_categorical is not None:
                    # vectorized re-code into the TRAIN category order
                    s = s.cat.set_categories(pandas_categorical[cat_i])
                codes = s.cat.codes.to_numpy().astype(np.float64)
                codes[codes < 0] = np.nan
                cols.append(codes)
                cat_i += 1
            else:
                cols.append(s.to_numpy(dtype=np.float64))
        data = np.stack(cols, axis=1)
    if hasattr(data, "values"):       # pandas
        data = data.values
    if hasattr(data, "toarray"):      # scipy sparse
        data = data.toarray()
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        # a 1-D vector is a single ROW when its length matches the model's
        # feature count (single-row predict), else a single column
        if num_features is not None and len(arr) == num_features:
            arr = arr[None, :]
        else:
            arr = arr[:, None]
    return arr


_PANDAS_CAT_KEY = "pandas_categorical:"


def _split_pandas_categorical(model_str: str):
    """Strip the trailing ``pandas_categorical:<json>`` line the Python
    layer appends to saved models (same file contract as the reference's
    python package, so either package reads the other's files).
    Returns (model_str_without_line, categories_or_None)."""
    import json
    idx = model_str.rfind("\n" + _PANDAS_CAT_KEY)
    if idx < 0:
        return model_str, None
    line = model_str[idx + 1 + len(_PANDAS_CAT_KEY):].strip()
    try:
        cats = json.loads(line)
    except json.JSONDecodeError:
        return model_str, None
    return model_str[:idx + 1], cats


class Dataset:
    """Lazily-constructed training dataset (reference basic.py:664)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List[int], List[str]] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._handle: Optional[TpuDataset] = None
        self.used_indices: Optional[np.ndarray] = None
        self._predictor = None
        # train-time category lists for pandas category columns (the
        # reference's pandas_categorical); set at construct, persisted in
        # saved models so predict frames encode consistently
        self.pandas_categorical: Optional[List[list]] = None

    # --------------------------------------------------------- construction
    def construct(self) -> "Dataset":
        if self._handle is not None:
            return self
        if isinstance(self.data, str):
            from .core.parser import load_file_to_dataset
            cfg = Config.from_params(self.params)
            self._handle = load_file_to_dataset(
                self.data, cfg,
                reference=(self.reference.construct()._handle
                           if self.reference is not None else None))
            return self
        cfg = Config.from_params(self.params)
        # scipy sparse input never densifies (TpuDataset.from_scipy bins
        # straight from the CSC slices; under EFB the bundled matrix is
        # built directly)
        is_sparse = (hasattr(self.data, "tocsr")
                     and not hasattr(self.data, "values"))
        if not is_sparse:
            # valid sets encode with the TRAINING frame's category lists;
            # the reference must be constructed first or its lists are
            # still unset (valid .construct() can legally run first)
            if self.reference is not None:
                self.reference.construct()
            self.pandas_categorical = (
                self.reference.pandas_categorical
                if self.reference is not None
                and self.reference.pandas_categorical is not None
                else _pandas_categories(self.data))
        data = (self.data if is_sparse
                else _as_2d_float(self.data,
                                  pandas_categorical=self.pandas_categorical))
        feature_names = None
        if isinstance(self.feature_name, (list, tuple)):
            feature_names = list(self.feature_name)
        elif hasattr(self.data, "columns"):
            feature_names = [str(c) for c in self.data.columns]
        cat_idx: List[int] = []
        if isinstance(self.categorical_feature, (list, tuple)):
            for c in self.categorical_feature:
                if isinstance(c, str):
                    if feature_names and c in feature_names:
                        cat_idx.append(feature_names.index(c))
                else:
                    cat_idx.append(int(c))
        elif self.categorical_feature == "auto":
            # params-level spec first: categorical_feature /
            # categorical_column aliases in the conf dialect (the path
            # the reference resolves in its C++ Config; its own test
            # suite sets 'categorical_column': 0 this way).  A params
            # LIST str()-ifies through Config, so strip brackets too.
            spec = str(cfg.categorical_feature or "").strip("[]() ")
            for tok in spec.split(","):
                tok = tok.strip()
                if not tok:
                    continue
                name = tok[5:] if tok.startswith("name:") else tok
                if feature_names and name in feature_names:
                    cat_idx.append(feature_names.index(name))
                else:
                    try:
                        cat_idx.append(int(name))
                    except ValueError:
                        raise LightGBMError(
                            f"categorical_feature entry {tok!r} is "
                            f"neither a column index nor a feature name")
            if hasattr(self.data, "dtypes"):
                for i, dt in enumerate(self.data.dtypes):
                    if str(dt) == "category" and i not in cat_idx:
                        cat_idx.append(i)
        ref_handle = None
        if self.reference is not None:
            ref_handle = self.reference.construct()._handle
        label = np.asarray(self.label, dtype=np.float64).ravel() \
            if self.label is not None else None
        make = TpuDataset.from_scipy if is_sparse else TpuDataset.from_numpy
        self._handle = make(
            data, label=label, config=cfg,
            weights=(np.asarray(self.weight, dtype=np.float64).ravel()
                     if self.weight is not None else None),
            group=(np.asarray(self.group) if self.group is not None else None),
            init_score=(np.asarray(self.init_score, dtype=np.float64)
                        if self.init_score is not None else None),
            categorical_features=cat_idx,
            feature_names=feature_names,
            reference=ref_handle)
        if self.used_indices is not None:
            self._subset_in_place(self.used_indices)
        return self

    def _subset_in_place(self, indices: np.ndarray) -> None:
        h = self._handle
        sub = TpuDataset()
        sub.num_data = len(indices)
        sub.num_total_features = h.num_total_features
        sub.bin_mappers = h.bin_mappers
        sub.used_feature_indices = h.used_feature_indices
        sub.max_num_bin = h.max_num_bin
        sub.bundle = h.bundle
        sub.feature_names = h.feature_names
        sub.monotone_constraints = h.monotone_constraints
        sub.feature_penalty = h.feature_penalty
        sub.binned = h.binned[indices]
        sub.metadata = h.metadata.subset(indices)
        sub.metadata.num_data = len(indices)
        self._handle = sub

    def subset(self, used_indices: Sequence[int],
               params: Optional[Dict] = None) -> "Dataset":
        """Row-subset view sharing bin mappers (Dataset::CopySubset,
        dataset.cpp:503)."""
        ds = Dataset(self.data, label=self.label, reference=self,
                     weight=self.weight, group=self.group,
                     feature_name=self.feature_name,
                     categorical_feature=self.categorical_feature,
                     params=params or self.params)
        ds.used_indices = np.asarray(sorted(used_indices), dtype=np.int64)
        ds.reference = self
        return ds

    # ------------------------------------------------------------- fields
    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._handle is not None and label is not None:
            self._handle.metadata.set_label(
                np.asarray(label, dtype=np.float64).ravel())
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._handle is not None:
            self._handle.metadata.set_weights(
                np.asarray(weight, dtype=np.float64).ravel()
                if weight is not None else None)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._handle is not None and group is not None:
            self._handle.metadata.set_query(np.asarray(group))
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._handle is not None:
            self._handle.metadata.set_init_score(
                np.asarray(init_score, dtype=np.float64)
                if init_score is not None else None)
        return self

    def set_field(self, field_name: str, data) -> "Dataset":
        if field_name == "label":
            return self.set_label(data)
        if field_name == "weight":
            return self.set_weight(data)
        if field_name == "group":
            return self.set_group(data)
        if field_name == "init_score":
            return self.set_init_score(data)
        raise LightGBMError(f"Unknown field name {field_name}")

    def get_field(self, field_name: str):
        self.construct()
        md = self._handle.metadata
        if field_name == "label":
            return md.label
        if field_name == "weight":
            return md.weights
        if field_name == "group":
            return (np.diff(md.query_boundaries)
                    if md.query_boundaries is not None else None)
        if field_name == "init_score":
            return md.init_score
        raise LightGBMError(f"Unknown field name {field_name}")

    def get_label(self):
        return self.get_field("label")

    def get_weight(self):
        return self.get_field("weight")

    def get_group(self):
        return self.get_field("group")

    def get_init_score(self):
        return self.get_field("init_score")

    def num_data(self) -> int:
        self.construct()
        return self._handle.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._handle.num_total_features

    def save_binary(self, filename: str) -> "Dataset":
        self.construct()
        self._handle.save_binary(filename)
        return self

    def create_valid(self, data, label=None, **kwargs) -> "Dataset":
        return Dataset(data, label=label, reference=self, **kwargs)


class Booster:
    """Training-capable model handle (reference basic.py:1612)."""

    def __init__(self, params: Optional[Dict] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        params = dict(params or {})
        self.params = params
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._valid_names: List[str] = []
        self._valid_sets: List["Dataset"] = []
        if train_set is not None:
            check(isinstance(train_set, Dataset),
                  "Training data should be a Dataset instance")
            # merge dataset-level params under booster params
            merged = dict(train_set.params or {})
            merged.update(params)
            self.config = Config.from_params(merged)
            train_set.params = merged
            train_set.construct()
            from .objective import create_objective
            from .models.boosting_factory import create_boosting
            self.objective = create_objective(self.config)
            if self.objective is not None:
                self.objective.init(train_set._handle.metadata,
                                    train_set._handle.num_data)
            self.gbdt = create_boosting(self.config, train_set._handle,
                                        self.objective)
            self.train_set = train_set
            self.pandas_categorical = train_set.pandas_categorical
            self._setup_metrics()
        elif model_file is not None or model_str is not None:
            from .models.serialization import load_model
            if model_file is not None:
                from .utils.file_io import open_file
                with open_file(model_file) as fh:
                    model_str = fh.read()
            model_str, self.pandas_categorical = \
                _split_pandas_categorical(model_str)
            self.gbdt, self.config, self.objective = load_model(model_str)
            self.train_set = None
        else:
            raise LightGBMError(
                "Booster needs train_set, model_file or model_str")

    # ----------------------------------------------------------- internals
    def _setup_metrics(self):
        names = list(self.config.metric)
        if not names:
            d = default_metric_for_objective(self.config.objective)
            if d:
                names = [d]
        seen = []
        for n in names:
            c = metric_canonical_name(n) or n
            if c not in seen:
                seen.append(c)
        self._metric_names = seen
        self.gbdt.setup_metrics(seen)

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct()
        self.gbdt.add_valid_data(name, data._handle)
        self._valid_names.append(name)
        self._valid_sets.append(data)
        self._setup_metrics()
        return self

    # ------------------------------------------------------------ training
    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; returns True when no further splits are
        possible (LGBM_BoosterUpdateOneIter, c_api.cpp:1143).  A new
        ``train_set`` swaps the training data first
        (LGBM_BoosterResetTrainingData; bins must align)."""
        if train_set is not None and train_set is not self.train_set:
            train_set.construct()
            # alignment is checked inside reset_train_data; the objective
            # and metrics re-bind only after it succeeds (atomic swap)
            self.gbdt.reset_train_data(train_set._handle)
            if self.objective is not None:
                h = train_set._handle
                self.objective.init(h.metadata, h.num_data)
            self.train_set = train_set
            self._setup_metrics()
        if fobj is not None:
            score = self.gbdt.train_score
            grad, hess = fobj(np.asarray(score).ravel(), self.train_set)
            return self.gbdt.train_one_iter(np.asarray(grad),
                                            np.asarray(hess))
        return self.gbdt.train_one_iter()

    def update_chunk(self, chunk: int) -> bool:
        """Run up to ``chunk`` boosting iterations as one on-device
        program with tree fetches batched at the chunk boundary
        (tpu_boost_chunk); falls back to a single iteration when the
        configuration needs per-iteration host work."""
        return self.gbdt.train_chunk(int(chunk))

    def setup_inscan_eval(self, include_train: bool = False):
        """Attach the device-side in-scan eval program (metric/device.py)
        so chunked updates score the valid sets and compute the attached
        metrics per iteration on-device.  Returns None on success or a
        short blocker string when a metric/objective isn't
        device-computable."""
        return self.gbdt.setup_inscan_eval(include_train)

    def take_inscan_evals(self) -> List:
        """Pop [(iteration, metric_row)] produced by in-scan eval since
        the last call (rows appear as their chunks materialize)."""
        return self.gbdt.take_inscan_evals()

    def inscan_result_list(self, vals) -> List:
        """One in-scan metric row -> [(set, metric, value, higher_better)],
        the eval_train/eval_valid result shape."""
        return self.gbdt.inscan_result_list(vals)

    def get_stats(self) -> Dict:
        """Training telemetry snapshot (utils/telemetry.py): phase
        seconds, transfer/compile/network counters, gauges and the
        per-iteration timeline, plus (v3) top-level ``schema`` and
        ``telemetry_level`` keys — downstream tools branch on those
        instead of sniffing sections — and a ``health`` digest when the
        run wrote a health stream.  ``engine.train`` attaches the same
        dict as ``booster.train_stats`` at the end of training."""
        from .utils.telemetry import TELEMETRY
        return TELEMETRY.stats()

    def rollback_one_iter(self) -> "Booster":
        self.gbdt.rollback_one_iter()
        return self

    @property
    def current_iteration(self):
        return self.gbdt.current_iteration

    def num_trees(self) -> int:
        return len(self.gbdt.models)

    def num_model_per_iteration(self) -> int:
        return self.gbdt.num_tree_per_iteration

    def estimate_working_set(self) -> int:
        """Estimated device working set of training this booster, in
        bytes — the exact resolved-layout number the internal admission
        checks (``data_in_hbm=auto``, the sched plane's HBM gate) use
        for this run.  For a pre-construction estimate from a config and
        a ``(num_data, num_columns)`` shape alone, use module-level
        :func:`lightgbm_tpu.estimate_working_set`."""
        if self.train_set is None:
            raise LightGBMError(
                "estimate_working_set needs a training booster; for a "
                "model-only handle call lightgbm_tpu."
                "estimate_working_set(config, data_shape) instead")
        return self.gbdt._estimate_working_set()

    # ---------------------------------------------------------------- eval
    def _feval_preds(self, score) -> np.ndarray:
        """What feval receives: objective-TRANSFORMED predictions (the
        reference's GetPredictAt applies ConvertOutput for built-in
        objectives; raw margins only without one), class-major flat."""
        score = np.asarray(score)
        if self.objective is not None:
            score = np.asarray(self.objective.convert_output(score))
        return score.ravel()

    def eval_train(self, feval=None) -> List:
        out = [("training", name, val, hb)
               for name, val, hb in self.gbdt.eval_train()]
        if feval is not None:
            name, val, hb = feval(self._feval_preds(self.gbdt.train_score),
                                  self.train_set)
            out.append(("training", name, val, hb))
        return out

    def eval_valid(self, feval=None) -> List:
        out = []
        for i, name in enumerate(self._valid_names):
            out.extend([(name, mname, val, hb)
                        for mname, val, hb in self.gbdt.eval_valid(i)])
            if feval is not None and i < len(self._valid_sets):
                # custom metric on objective-transformed valid scores,
                # same contract as eval_train
                mname, val, hb = feval(
                    self._feval_preds(self.gbdt.valid_scores[i]),
                    self._valid_sets[i])
                out.append((name, mname, val, hb))
        return out

    # ------------------------------------------------------------- predict
    def predict(self, data, num_iteration: int = -1, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                **kwargs) -> np.ndarray:
        if num_iteration is None or num_iteration < 0:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        n_feat = self.gbdt.max_feature_idx + 1
        X = _as_2d_float(data, n_feat,
                         pandas_categorical=getattr(
                             self, "pandas_categorical", None))
        if X.shape[1] != n_feat:
            raise LightGBMError(
                f"The number of features in data ({X.shape[1]}) is not the "
                f"same as it was in training data ({n_feat})")
        if pred_contrib:
            from .models.shap import predict_contrib
            return predict_contrib(self.gbdt, X, num_iteration)
        return self.gbdt.predict(X, num_iteration=num_iteration,
                                 raw_score=raw_score, pred_leaf=pred_leaf)

    def refit(self, data, label, weight=None, decay_rate: float = None
              ) -> "Booster":
        """Re-estimate every leaf's output on fresh (data, label) without
        changing the tree structure (reference ``Booster.refit``): each
        leaf blends its old value with the gradient-optimal one,
        ``new = decay * old + (1 - decay) * opt``.  Lets a served model
        absorb new data without a retrain; returns self."""
        from .core.metadata import Metadata
        from .models.refit import refit_model
        if not self.gbdt.models:
            raise LightGBMError("cannot refit a model with no trees")
        leaf_preds = np.asarray(self.predict(data, pred_leaf=True),
                                dtype=np.int32)
        if leaf_preds.ndim == 1:
            leaf_preds = leaf_preds[:, None]
        md = Metadata(leaf_preds.shape[0])
        md.init(leaf_preds.shape[0])
        md.set_label(np.asarray(label))
        if weight is not None:
            md.set_weights(np.asarray(weight))
        config = self.config
        if decay_rate is not None:
            import copy
            config = copy.copy(config)
            config.refit_decay_rate = float(decay_rate)
        refit_model(self.gbdt, md, leaf_preds, config)
        return self

    def serve(self, model_id: str = None, num_iteration: int = -1,
              session=None, **overrides):
        """A compiled micro-batching prediction handle for this model
        (lightgbm_tpu/serve, docs/SERVING.md).  Knobs come from this
        booster's ``serve_*`` params unless overridden; pass an existing
        :class:`~lightgbm_tpu.serve.ServeSession` to co-host several
        models in one device pack and one queue."""
        from .serve import ServeHandle, ServeSession
        owns = session is None
        if owns:
            session = ServeSession.from_config(self.config, **overrides)
        mid = session.load(self, model_id=model_id,
                           num_iteration=num_iteration)
        return ServeHandle(session, mid, owns_session=owns)

    # ---------------------------------------------------------------- model
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        # atomic tmp + os.replace for local paths: a crash mid-write
        # leaves the previous model (or nothing), never a torn file
        from .utils.file_io import atomic_write_text
        atomic_write_text(filename,
                          self.model_to_string(num_iteration,
                                               start_iteration))
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        import json

        from .models.serialization import save_model_to_string
        s = save_model_to_string(self.gbdt, self.config,
                                 num_iteration or -1, start_iteration)
        if getattr(self, "pandas_categorical", None):
            # same trailing-line contract as the reference python package
            s += "\n" + _PANDAS_CAT_KEY \
                + json.dumps(self.pandas_categorical) + "\n"
        return s

    def dump_model(self, num_iteration: Optional[int] = None) -> Dict:
        from .models.serialization import dump_model_dict
        return dump_model_dict(self.gbdt, self.config, num_iteration or -1)

    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        return self.gbdt.feature_importance(importance_type, iteration)

    def feature_name(self) -> List[str]:
        return list(self.gbdt.feature_names)

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style: bool = False):
        """Histogram of the split threshold values the model uses for one
        feature (reference basic.py Booster.get_split_value_histogram).

        ``feature`` is a name or index; ``bins`` follows numpy.histogram
        (None = one bin per unique threshold).  Returns (counts, edges)
        like numpy, or a [k, 2] (SplitValue, Count) array of non-empty
        bins with ``xgboost_style=True``.
        """
        if isinstance(feature, str):
            names = self.feature_name()
            if feature not in names:
                raise LightGBMError(f"Unknown feature name {feature!r}")
            feature = names.index(feature)
        values = []
        for t in self.gbdt.models:
            n = t.num_leaves - 1
            for i in range(n):
                if (t.split_feature[i] == feature
                        and not (t.decision_type[i] & 1)):  # numerical only
                    values.append(float(t.threshold[i]))
        values = np.asarray(values, dtype=np.float64)
        if bins is None:
            bins = max(len(np.unique(values)), 1)
        counts, edges = np.histogram(values, bins=bins)
        if not xgboost_style:
            return counts, edges
        centers = (edges[:-1] + edges[1:]) / 2.0
        nz = counts > 0
        return np.stack([centers[nz], counts[nz].astype(np.float64)],
                        axis=1)

    # ------------------------------------------------------------- pickling
    def __getstate__(self):
        """Pickle via the text model (the reference Booster does the
        same): training state (dataset, device buffers, objective) does
        not survive — the restored Booster predicts and continues from
        the serialized trees only."""
        return {"model_str": self.model_to_string(),
                "params": self.params,
                "best_iteration": self.best_iteration,
                "best_score": self.best_score}

    def __setstate__(self, state):
        from .models.serialization import load_model
        self.params = state.get("params", {})
        self.best_iteration = state.get("best_iteration", -1)
        self.best_score = state.get("best_score", {})
        self._valid_names = []
        self._valid_sets = []
        model_str, self.pandas_categorical = \
            _split_pandas_categorical(state["model_str"])
        self.gbdt, self.config, self.objective = load_model(model_str)
        self.train_set = None

    def set_network(self, machines, local_listen_port=12400,
                    listen_time_out=120, num_machines=1) -> "Booster":
        """Distributed setup: on TPU the mesh replaces the socket ring; this
        keeps the API seam (basic.py:1771 / LGBM_NetworkInit)."""
        from .parallel import network
        network.init_from_machines(machines, num_machines)
        return self

    def free_network(self) -> "Booster":
        from .parallel import network
        network.dispose()
        return self
