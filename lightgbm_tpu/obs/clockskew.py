"""Per-rank monotonic clock-offset estimation over the KV store.

Each host's ``time.monotonic()`` has an arbitrary epoch, so per-rank
``mono_ts`` stamps and trace timestamps cannot be compared across ranks
directly.  This module estimates, for every rank, the offset that maps
its monotonic clock onto rank 0's — the classic NTP midpoint method
(RFC 5905 §8) run over the same coordination-service KV store the
barriers use:

    rank r                         rank 0 (time server)
    t1 = mono(); post ping ───────▶ t2 = mono() on receipt
                                    t3 = mono(); post pong(t2, t3)
    t4 = mono() ◀──────────────────

    offset(rank0 − rank r) = ((t2 − t1) + (t3 − t4)) / 2
    error bound            = ((t4 − t1) − (t3 − t2)) / 2   (± RTT/2)

Several exchanges are run and the minimum-RTT sample wins (queueing
delay only ever inflates the bound, never deflates it).  The resulting
offset table is allgathered so every rank can correct every other
rank's timestamps, and is emitted as a ``dist_clock`` health record —
the anchor ``tools/fleet_trace.py`` and ``obs/fleet.py`` use to build
one skew-corrected fleet timeline.

The estimator core (:func:`midpoint_offset`, :func:`combine_pings`) is
pure so the unit tests can drive it with synthetic clocks; only
:func:`measure_fleet_offsets` touches the KV store.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.log import LightGBMError

# KV namespace for ping/pong exchanges (under the coordination service's
# flat store, like lgbm/ag and lgbm/bar in parallel/distributed.py)
_CLK_PREFIX = "lgbm/clk"

# per-process exchange generation — every rank must run the same number
# of measurement rounds in the same order (same contract as the
# allgather/barrier generation counters)
_clk_gen = 0

# the last measured fleet offset table: {rank: {"offset_s", "bound_s"}}
_offsets: Optional[Dict[int, Dict[str, float]]] = None


# ------------------------------------------------------------------ estimator
def midpoint_offset(t1: float, t2: float, t3: float, t4: float,
                    ) -> Tuple[float, float]:
    """NTP midpoint estimate from one ping/pong exchange.

    ``t1``/``t4`` are the client's clock at send/receive; ``t2``/``t3``
    the server's at receive/send.  Returns ``(offset, bound)`` where
    ``offset`` is (server clock − client clock) and the true offset
    lies within ``offset ± bound`` (bound = half the one-way ambiguity,
    i.e. RTT/2 minus the server's processing time)."""
    offset = ((t2 - t1) + (t3 - t4)) / 2.0
    bound = max(0.0, ((t4 - t1) - (t3 - t2)) / 2.0)
    return offset, bound


def combine_pings(samples: Sequence[Tuple[float, float, float, float]],
                  ) -> Tuple[float, float, float]:
    """Fold several ping/pong exchanges into one estimate by taking the
    minimum-RTT sample (delay is strictly additive noise: a queued
    exchange widens the bound but cannot shrink it).  Returns
    ``(offset, bound, rtt)`` of the winning sample."""
    if not samples:
        raise ValueError("combine_pings needs at least one sample")
    best = None
    for t1, t2, t3, t4 in samples:
        rtt = max(0.0, (t4 - t1) - (t3 - t2))
        offset, bound = midpoint_offset(t1, t2, t3, t4)
        if best is None or rtt < best[2]:
            best = (offset, bound, rtt)
    return best


def correct(mono_ts: float, rank: int,
            offsets: Optional[Dict[int, Dict[str, float]]] = None,
            ) -> float:
    """Map ``rank``'s monotonic timestamp onto the fleet timeline
    (rank 0's clock).  Identity when no table is available — correct
    for single-host fleets, where every process shares one clock."""
    table = offsets if offsets is not None else _offsets
    if not table:
        return mono_ts
    entry = table.get(rank) or table.get(str(rank))
    if not entry:
        return mono_ts
    return mono_ts + float(entry["offset_s"])


def current_offsets() -> Optional[Dict[int, Dict[str, float]]]:
    """The last measured offset table, or ``None``."""
    return _offsets


def reset() -> None:
    """Drop measurement state (test windows / dispose)."""
    global _clk_gen, _offsets
    _clk_gen = 0
    _offsets = None


# ------------------------------------------------------------- KV measurement
def measure_fleet_offsets(pings: int = 5,
                          timeout_s: Optional[float] = None,
                          ) -> Dict[int, Dict[str, float]]:
    """Collective: estimate every rank's monotonic offset to rank 0.

    Every rank must call this at the same logical point (obs/fleet.py
    calls it from its synchronized window sync).  Rank 0 acts as the
    time server: for each peer rank and each of ``pings`` rounds it
    blocks on the peer's ping key, stamps ``t2``/``t3``, and posts the
    pong; peers time ``t1``/``t4`` around the exchange and keep the
    minimum-RTT sample.  The per-rank results are then allgathered so
    all ranks hold the same table, which is stored module-wide, emitted
    as a ``dist_clock`` health record, and returned.

    Single-process worlds return the trivial ``{0: 0}`` table without
    touching the KV store."""
    global _clk_gen, _offsets
    from ..parallel import distributed, network

    me, n = distributed.rank(), distributed.world()
    if not distributed.is_active():
        _offsets = {0: {"offset_s": 0.0, "bound_s": 0.0, "rtt_s": 0.0}}
        return _offsets
    c = distributed.client()
    if timeout_s is None:
        timeout_s = network.collective_policy()[1]
    gen = _clk_gen
    _clk_gen += 1
    prefix = f"{_CLK_PREFIX}/{gen}"
    deadline = time.perf_counter() + max(0.001, timeout_s)
    pings = max(1, int(pings))

    try:
        if me == 0:
            # time server: serve each peer's exchanges in rank order.
            # Waiting inflates that exchange's RTT (and so its bound) —
            # never its accuracy — and min-RTT selection discards it.
            for r in range(1, n):
                for i in range(pings):
                    c.blocking_key_value_get(
                        f"{prefix}/{r}/{i}/ping",
                        distributed._remaining_ms(deadline))
                    t2 = time.monotonic()
                    t3 = time.monotonic()
                    c.key_value_set(f"{prefix}/{r}/{i}/pong",
                                    f"{t2!r},{t3!r}",
                                    allow_overwrite=True)
            mine = {"rank": 0, "offset_s": 0.0, "bound_s": 0.0,
                    "rtt_s": 0.0}
        else:
            samples: List[Tuple[float, float, float, float]] = []
            for i in range(pings):
                t1 = time.monotonic()
                c.key_value_set(f"{prefix}/{me}/{i}/ping", "1",
                                allow_overwrite=True)
                pong = c.blocking_key_value_get(
                    f"{prefix}/{me}/{i}/pong",
                    distributed._remaining_ms(deadline))
                t4 = time.monotonic()
                t2_s, t3_s = pong.split(",")
                samples.append((t1, float(t2_s), float(t3_s), t4))
            offset, bound, rtt = combine_pings(samples)
            mine = {"rank": me, "offset_s": round(offset, 6),
                    "bound_s": round(bound, 6), "rtt_s": round(rtt, 6)}
    except LightGBMError:
        raise
    except Exception as e:  # noqa: BLE001 — deadline or service loss
        raise LightGBMError(
            f"clock-offset exchange timed out after {timeout_s:g}s "
            f"(rank {me} of world {n}, generation {gen}) — a host died "
            f"or is partitioned: {e}") from e

    table = {}
    for entry in network.allgather_obj(mine):
        table[int(entry["rank"])] = {
            "offset_s": float(entry["offset_s"]),
            "bound_s": float(entry["bound_s"]),
            "rtt_s": float(entry["rtt_s"])}
    _offsets = table
    distributed._health(
        "clock", offset_s=table.get(me, {}).get("offset_s", 0.0),
        bound_s=table.get(me, {}).get("bound_s", 0.0))
    from ..utils.telemetry import HEALTH
    if HEALTH.active:
        HEALTH.record("dist_clock", {
            "rank": me, "world": n, "pings": pings,
            "offsets": {str(r): v for r, v in sorted(table.items())}})
    return table
