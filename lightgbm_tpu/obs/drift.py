"""Model-and-data drift plane (metrics schema v7).

The systems planes (training telemetry, serve windows, fleet sync)
answer "is the process healthy" — this module answers "is the MODEL
still the right one for the traffic it serves".  The paper's design
makes the data half nearly free: features are pre-quantized into
<= 255 integer bins and serve/binning.py already bins every request
row on device against the training BinMapper bounds, so per-feature
input drift reduces to integer bin-occupancy counting with zero extra
binning work.

Three pieces:

  * :func:`extract_baseline` — at registry load time, recount the
    training Dataset's binned matrix into a per-used-feature
    ``[F, B]`` bin-occupancy histogram (EFB bundles are unpacked back
    to feature bins) and digest the training predictions
    (``gbdt.train_score``) into a fixed set of raw-score quantile
    edges.  Pure host numpy over data the booster already holds — no
    re-binning, no device work.
  * :class:`DriftAccumulator` — the serve-side sink: per-model
    cumulative ``[F, B]`` bin counts fed by the predictor's compiled
    occupancy output plus a bounded deterministic reservoir of replied
    raw scores.  ``compute()`` turns the accumulated counts into
    per-feature PSI and a score-shift Jensen–Shannon divergence
    against the baseline.
  * :class:`DriftGate` — the pollable refit trigger:
    ``drifted(model_id)`` is True exactly when the current
    ``psi_max`` is at or above ``drift_psi_threshold``.

Estimator notes.  PSI over raw fine bins is dominated by sampling
noise (E[PSI] ~ bins/rows — with 255 bins and a few hundred observed
rows that alone exceeds any sane threshold), so each feature's fine
bins are grouped into at most :data:`PSI_BUCKETS` coarse buckets of
roughly equal TRAINING mass and PSI is computed over the buckets:

    PSI  = sum_b (q_b - p_b) * ln(q_b / p_b)
    JS   = (KL(p||m) + KL(q||m)) / 2,  m = (p+q)/2   (<= ln 2)

with additive smoothing ``p_b = (c_b + eps) / (n + eps*K)`` so empty
buckets stay finite.  The fine ``[F, B]`` counts are retained — tests
recount them directly against numpy over the raw rows.

Everything here is host-side accounting over values the serve path
already produced: trained models stay byte-identical with the plane
on or off, and every reply stays bit-identical to ``Booster.predict``
(the occupancy output rides NEXT TO the leaves, never touches them).
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

import numpy as np

# coarse PSI buckets per feature (equal training mass); the noise
# floor of a window with n distinct rows is ~PSI_BUCKETS/n
PSI_BUCKETS = 10
# raw-score digest resolution (quantile edges of the training scores)
SCORE_BUCKETS = 16
# bounded reservoir of replied raw scores per model (deterministic
# replacement so runs and tests reproduce)
SCORE_RESERVOIR = 4096
# additive smoothing mass per bucket
SMOOTH_EPS = 1e-4


# ----------------------------------------------------------- estimators
def _smooth(counts, eps: float = SMOOTH_EPS) -> np.ndarray:
    c = np.asarray(counts, dtype=np.float64).ravel()
    return (c + eps) / (c.sum() + eps * c.shape[0])


def psi(expected_counts, actual_counts, eps: float = SMOOTH_EPS) -> float:
    """Population Stability Index between two count vectors over the
    same buckets.  Symmetric, >= 0, ~0 for matching distributions;
    the classic operating points are 0.1 (watch) and 0.2 (act)."""
    p = _smooth(expected_counts, eps)
    q = _smooth(actual_counts, eps)
    return float(np.sum((q - p) * np.log(q / p)))


def js_divergence(p_counts, q_counts, eps: float = SMOOTH_EPS) -> float:
    """Jensen–Shannon divergence (natural log, bounded by ln 2)."""
    p = _smooth(p_counts, eps)
    q = _smooth(q_counts, eps)
    m = 0.5 * (p + q)
    return float(0.5 * np.sum(p * np.log(p / m))
                 + 0.5 * np.sum(q * np.log(q / m)))


# ------------------------------------------------------------- baseline
def dataset_bin_counts(ds) -> np.ndarray:
    """``[F, B]`` int64 bin-occupancy of the training Dataset's binned
    matrix, per USED feature, B = max num_bin across used features.
    EFB bundles are unpacked: a bundled column stores feature f's bin
    b as ``feat_offset[f] + b`` with the shared slot 0 (or any value
    outside f's range) meaning "f at its default_bin"."""
    used = ds.used_feature_indices
    F = len(used)
    num_bin = np.asarray([ds.bin_mappers[int(f)].num_bin for f in used],
                         dtype=np.int64)
    B = int(num_bin.max()) if F else 1
    out = np.zeros((F, B), dtype=np.int64)
    binned = ds.host_binned()
    for j in range(F):
        f = int(used[j])
        default_bin = int(ds.bin_mappers[f].default_bin)
        if ds.bundle is not None:
            col = binned[:, int(ds.bundle.feat_group[j])].astype(np.int64)
            off = int(ds.bundle.feat_offset[j])
            if off:     # multi-feature group (offset 0 = single-feature)
                inside = (col >= off) & (col < off + num_bin[j])
                col = np.where(inside, col - off, default_bin)
        else:
            col = binned[:, j].astype(np.int64)
        out[j] = np.bincount(np.clip(col, 0, num_bin[j] - 1),
                             minlength=B)[:B]
    return out


def _bucketize(counts_f: np.ndarray, nbin: int,
               k: int = PSI_BUCKETS) -> np.ndarray:
    """Fine-bin -> coarse-bucket map for one feature: contiguous runs
    of fine bins holding roughly 1/k of the training mass each.  For
    categoricals the bin order is arbitrary but the map is FIXED, and
    PSI is permutation-invariant given a fixed grouping."""
    k = max(1, min(int(k), int(nbin)))
    c = counts_f[:nbin].astype(np.float64)
    total = c.sum()
    if total <= 0:
        return np.zeros(nbin, dtype=np.int64)
    before = np.cumsum(c) - c        # training mass strictly before bin i
    return np.minimum((before / (total / k)).astype(np.int64), k - 1)


class ModelBaseline:
    """Training-time reference distributions of one resident model."""

    __slots__ = ("feature_names", "num_bin", "bin_counts", "bucket_of",
                 "bucket_counts", "score_edges", "score_counts", "rows")

    def __init__(self, feature_names, num_bin, bin_counts, bucket_of,
                 bucket_counts, score_edges, score_counts, rows):
        self.feature_names = feature_names    # [F] str, per used feature
        self.num_bin = num_bin                # [F] int
        self.bin_counts = bin_counts          # [F, B] int64 fine counts
        self.bucket_of = bucket_of            # [F, B] int64 bin->bucket
        self.bucket_counts = bucket_counts    # [F, K] float64
        self.score_edges = score_edges        # [E] f64 or None
        self.score_counts = score_counts      # [E+1] int64 or None
        self.rows = rows

    @property
    def num_features(self) -> int:
        return int(self.bin_counts.shape[0])


def _score_digest(scores: np.ndarray):
    """(edges, counts) quantile digest of the training raw scores, or
    (None, None) when the scores are unusable (e.g. invalidated by a
    rollback)."""
    s = np.asarray(scores, dtype=np.float64).ravel()
    s = s[np.isfinite(s)]
    if s.size == 0:
        return None, None
    qs = np.linspace(0.0, 1.0, SCORE_BUCKETS + 1)[1:-1]
    edges = np.unique(np.quantile(s, qs))
    counts = np.bincount(np.searchsorted(edges, s, side="right"),
                         minlength=edges.size + 1)
    return edges, counts.astype(np.int64)


def extract_baseline(booster, psi_buckets: int = PSI_BUCKETS,
                     ) -> ModelBaseline:
    """Training baseline of a serve-loadable booster: fine bin counts
    from the Dataset's binned matrix, the equal-mass coarse-bucket map
    PSI runs over, and the raw-score quantile digest."""
    gbdt = booster.gbdt
    ds = gbdt.train_set
    counts = dataset_bin_counts(ds)
    used = ds.used_feature_indices
    all_names = list(getattr(ds, "feature_names", []) or [])
    names = [all_names[int(f)] if int(f) < len(all_names)
             else f"Column_{int(f)}" for f in used]
    num_bin = np.asarray([ds.bin_mappers[int(f)].num_bin for f in used],
                         dtype=np.int64)
    F, B = counts.shape
    bucket_of = np.zeros((F, B), dtype=np.int64)
    bucket_counts = np.zeros((F, PSI_BUCKETS), dtype=np.float64)
    for j in range(F):
        nb = int(num_bin[j])
        bof = _bucketize(counts[j], nb, psi_buckets)
        bucket_of[j, :nb] = bof
        bucket_counts[j] = np.bincount(
            bof, weights=counts[j, :nb].astype(np.float64),
            minlength=PSI_BUCKETS)[:PSI_BUCKETS]
    # raw-score digest over the training predictions; train_score is a
    # running SUM for RF-style ensembles, so mirror predict's averaging
    scores = np.asarray(gbdt.train_score, dtype=np.float64)[0]
    if bool(getattr(gbdt, "average_output", False)):
        C = max(int(gbdt.num_tree_per_iteration), 1)
        scores = scores / max(len(gbdt.models) // C, 1)
    edges, score_counts = _score_digest(scores)
    return ModelBaseline(names, num_bin, counts, bucket_of, bucket_counts,
                         edges, score_counts, int(counts[0].sum())
                         if F else 0)


# ---------------------------------------------------------- accumulator
class _ModelState:
    __slots__ = ("baseline", "fine", "scores", "seen_scores", "rows",
                 "rows_emitted", "rng", "generation")

    def __init__(self, baseline: ModelBaseline, seed: int,
                 generation: int = 0):
        self.baseline = baseline
        self.fine = np.zeros_like(baseline.bin_counts)
        self.scores: List[float] = []
        self.seen_scores = 0
        self.rows = 0
        self.rows_emitted = 0
        self.rng = random.Random(seed)
        self.generation = int(generation)


class DriftAccumulator:
    """Per-(model, feature) serve-side occupancy counts + score
    reservoir, compared against each model's training baseline.

    Counts are CUMULATIVE for the session — every ``compute()`` sees
    all traffic since load, so the refit signal stabilizes as rows
    accumulate instead of resetting to the noise floor each window."""

    def __init__(self, psi_threshold: float = 0.2, topk: int = 5,
                 reservoir: int = SCORE_RESERVOIR):
        self._lock = threading.Lock()
        self._models: Dict[str, _ModelState] = {}
        self.psi_threshold = float(psi_threshold)
        self.topk = max(int(topk), 1)
        self.reservoir = max(int(reservoir), 1)

    # ------------------------------------------------------- registration
    def register(self, model_id: str, baseline: ModelBaseline,
                 generation: int = 0) -> None:
        """(Re)register a model's training baseline.  A re-registration
        RESETS the accumulated counts — a hot swap passes the new pack
        epoch as ``generation`` so drift restarts against the new
        model's baseline and the refit trigger does not immediately
        re-fire on the pre-swap traffic."""
        with self._lock:
            self._models[model_id] = _ModelState(
                baseline, seed=hash(model_id) & 0x7FFFFFFF,
                generation=generation)

    def forget(self, model_id: str) -> None:
        with self._lock:
            self._models.pop(model_id, None)

    def tracks(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self._models

    # -------------------------------------------------------------- feeds
    def note_bins(self, model_id: str, counts: np.ndarray) -> None:
        """Add one dispatch's per-feature occupancy counts (rows beyond
        the model's [F, B] shape are pack padding and are dropped)."""
        with self._lock:
            st = self._models.get(model_id)
            if st is None:
                return
            F, B = st.fine.shape
            c = np.asarray(counts, dtype=np.int64)[:F, :B]
            st.fine[: c.shape[0], : c.shape[1]] += c
            st.rows += int(c[0].sum()) if c.shape[0] else 0

    def note_scores(self, model_id: str, scores) -> None:
        """Reservoir-sample one batch of replied raw scores."""
        vals = np.asarray(scores, dtype=np.float64).ravel()
        with self._lock:
            st = self._models.get(model_id)
            if st is None:
                return
            for v in vals:
                st.seen_scores += 1
                if len(st.scores) < self.reservoir:
                    st.scores.append(float(v))
                else:
                    i = st.rng.randrange(st.seen_scores)
                    if i < self.reservoir:
                        st.scores[i] = float(v)

    # ------------------------------------------------------------ compute
    def compute(self, model_id: str) -> Optional[Dict[str, Any]]:
        """Current drift statistics vs baseline, or None when the model
        is untracked or has seen no rows."""
        with self._lock:
            st = self._models.get(model_id)
            if st is None or st.rows <= 0:
                return None
            fine = st.fine.copy()
            scores = list(st.scores)
            rows = st.rows
            base = st.baseline
            generation = st.generation
        per_feature = []
        for j in range(base.num_features):
            nb = int(base.num_bin[j])
            actual = np.bincount(base.bucket_of[j, :nb],
                                 weights=fine[j, :nb].astype(np.float64),
                                 minlength=PSI_BUCKETS)[:PSI_BUCKETS]
            per_feature.append(
                (base.feature_names[j],
                 psi(base.bucket_counts[j], actual)))
        per_feature.sort(key=lambda kv: -kv[1])
        psi_max = per_feature[0][1] if per_feature else 0.0
        rec: Dict[str, Any] = {
            "model": model_id,
            "rows": int(rows),
            "psi_max": round(float(psi_max), 6),
            "top": [{"feature": n, "psi": round(float(v), 6)}
                    for n, v in per_feature[: self.topk]],
            "threshold": round(self.psi_threshold, 6),
            "drifted": bool(psi_max >= self.psi_threshold),
        }
        if generation:
            # which swap generation this drift state accumulates for
            # (0 = the originally loaded model, omitted for v7 shape)
            rec["generation"] = int(generation)
        if base.score_edges is not None and scores:
            hist = np.bincount(
                np.searchsorted(base.score_edges, np.asarray(scores),
                                side="right"),
                minlength=base.score_edges.size + 1)
            rec["score_js"] = round(
                js_divergence(base.score_counts, hist), 6)
            rec["scores"] = len(scores)
        return rec

    # -------------------------------------------------------- publication
    def window_records(self) -> List[Dict[str, Any]]:
        """Records for one serve_window close: every tracked model with
        NEW rows since the last emission (idle models stay silent, so a
        quiet stream means quiet traffic, not a wedged plane)."""
        fresh = []
        with self._lock:
            for mid, st in self._models.items():
                if st.rows > st.rows_emitted:
                    st.rows_emitted = st.rows
                    fresh.append(mid)
        return self._publish(fresh)

    def publish_all(self) -> List[Dict[str, Any]]:
        """Final flush (queue close without a health stream): publish
        every model that saw traffic, regardless of emission history."""
        with self._lock:
            fresh = [mid for mid, st in self._models.items()
                     if st.rows > 0]
            for mid in fresh:
                self._models[mid].rows_emitted = self._models[mid].rows
        return self._publish(fresh)

    def _publish(self, model_ids) -> List[Dict[str, Any]]:
        from ..utils.telemetry import TELEMETRY
        out = []
        for mid in model_ids:
            rec = self.compute(mid)
            if rec is not None:
                out.append(rec)
                _section_update(self.psi_threshold, rec)
        if out:
            TELEMETRY.gauge_set(
                "serve/drift_psi_max",
                max(r["psi_max"] for r in out))
            js = [r["score_js"] for r in out if "score_js" in r]
            if js:
                TELEMETRY.gauge_set("serve/score_js", max(js))
        return out


class DriftGate:
    """The pollable refit trigger the continuous-learning loop and the
    sched/serve arbiter consume: ``drifted()`` is True exactly when
    the model's current ``psi_max`` >= the threshold."""

    def __init__(self, accumulator: DriftAccumulator,
                 psi_threshold: Optional[float] = None):
        self._acc = accumulator
        self.psi_threshold = (accumulator.psi_threshold
                              if psi_threshold is None
                              else float(psi_threshold))

    def stats(self, model_id: str) -> Optional[Dict[str, Any]]:
        return self._acc.compute(model_id)

    def drifted(self, model_id: str,
                psi_threshold: Optional[float] = None) -> bool:
        thr = self.psi_threshold if psi_threshold is None \
            else float(psi_threshold)
        rec = self._acc.compute(model_id)
        return rec is not None and rec["psi_max"] >= thr


# --------------------------------------------------- stats-blob section
# last published per-model drift state feeding stats()["drift"]; empty
# until a window (or final flush) synced, so pre-drift blobs keep their
# v6 shape exactly
_SECTION_LOCK = threading.Lock()
_SECTION: Dict[str, Dict[str, Any]] = {}
_SECTION_THRESHOLD: Optional[float] = None


def _section_update(threshold: float, rec: Dict[str, Any]) -> None:
    global _SECTION_THRESHOLD
    with _SECTION_LOCK:
        _SECTION_THRESHOLD = round(float(threshold), 6)
        _SECTION[rec["model"]] = {
            k: v for k, v in rec.items() if k != "model"}


def drift_section() -> Optional[Dict[str, Any]]:
    """The metrics-blob ``drift`` section, or None when no drift window
    has synced (keeps older blobs byte-shaped as v6)."""
    with _SECTION_LOCK:
        if not _SECTION:
            return None
        return {"psi_threshold": _SECTION_THRESHOLD,
                "models": {mid: dict(rec)
                           for mid, rec in _SECTION.items()}}


def reset() -> None:
    """Drop the published section (test/bench windows)."""
    global _SECTION_THRESHOLD
    with _SECTION_LOCK:
        _SECTION.clear()
        _SECTION_THRESHOLD = None
