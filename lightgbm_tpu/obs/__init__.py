"""Fleet and model observability planes (metrics schema v7).

Per-rank telemetry (utils/telemetry.py) and per-subsystem health
streams answer "what did THIS process do" — this package answers the
cross-rank question those cannot: *which rank is the straggler, and is
it compute or the collective?*

  * :mod:`clockskew` — per-rank monotonic clock offsets estimated from
    KV-store ping/pong exchanges (NTP midpoint method, error bounded by
    the exchange RTT), so per-rank ``mono_ts`` stamps and trace epochs
    map onto one fleet timeline.
  * :mod:`fleet` — the attribution sync: ranks kv-allgather their
    per-collective {call, enter, seconds} windows, split collective
    wall into *wait* (skew-corrected idle before the slowest rank
    arrives) vs *work* (transfer/reduce) seconds, and name the
    straggler rank per window in the health stream.
  * :mod:`drift` — the model-and-data drift plane (v7): per-feature
    bin-occupancy PSI and raw-score Jensen–Shannon shift of serve
    traffic vs each resident model's training baseline, the
    ``serve_drift`` health records, and the pollable ``DriftGate``
    refit trigger.

Everything here is host-side timing and IO — trained models stay
byte-identical with the planes on or off.
"""

from . import clockskew, drift, fleet  # noqa: F401
