"""Cross-rank collective wait-vs-work attribution (the v6 fleet sync).

PR 2's per-collective counters reproduce the reference fork's
linkers.h byte/time accounting, but per rank: a collective's measured
wall conflates *waiting for the slowest rank to arrive* with *actually
moving bytes*.  This module completes them cross-rank.  At every sync
point (the ``fleet_obs_sync_iters`` cadence plus once at summary) all
ranks kv-allgather the per-collective ``(call_index, enter_mono,
seconds)`` windows ``parallel/network.py`` accumulated since the last
sync.  Because every rank issues collectives in the same order,
``(kind, call_index)`` names the same logical collective on every
rank; with the clock-offset table from :mod:`clockskew` the per-rank
entry times become comparable and each rank's wall splits into

    wait = min(dur, slowest corrected enter − own corrected enter)
    work = dur − wait

accumulated into the ``dist/wait_s`` / ``dist/work_s`` counter pair
and a ``dist_window`` health record naming the straggler (the rank
with the largest total lateness) per window.

The attribution core (:func:`attribute_window`) is pure.  The sync
protocol is deliberately **eager-post / lazy-collect**: at each
deterministic iteration threshold every rank *posts* its drained
window under ``lgbm/fleet/{seq}/{rank}`` (a non-blocking KV set) and
*tries* to collect peers' tables with a non-blocking directory read,
deferring attribution until all ranks' tables for a seq are present.
Mid-loop blocking gathers are forbidden here because their pairing
would race the preemption flow's notice-triggered allgather (notice
visibility differs across ranks, so blind generation counters could
cross-pair payloads or deadlock); only :func:`final_sync` — called at
the aligned end-of-training point, where no other collective can
interleave — blocks, with a bounded deadline and graceful degradation.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from . import clockskew

# KV namespace for posted windows (coordination-service flat store).
# Keys are never deleted mid-run: a peer may collect a window
# arbitrarily late, and a finite run posts a bounded number of small
# (≤ ~100KB) tables — final_sync's own-key GC reclaims them at exit.
_FLEET_PREFIX = "lgbm/fleet"

# config knobs, bound by configure(); runtime-only — they never enter a
# model's parameter section, so the plane cannot break byte-identity
_sync_iters = 0
_clock_pings = 5
_next_sync: Optional[int] = None
_sync_seq = 0                 # next window sequence number to post
_pending: List[int] = []      # posted seqs not yet fully collected

# window aggregates feeding the stats() ``fleet`` section
_windows = 0
_per_rank: Dict[int, Dict[str, float]] = {}
_straggler_hist: Dict[int, int] = {}


def configure(config=None) -> None:
    """Bind the ``fleet_obs_*`` knobs and reset window aggregates.
    Called at the top of every training run (same lifecycle as
    ``TELEMETRY.set_config_level``)."""
    global _sync_iters, _clock_pings, _next_sync
    reset()
    if config is not None:
        _sync_iters = int(getattr(config, "fleet_obs_sync_iters", 0) or 0)
        _clock_pings = max(1, int(
            getattr(config, "fleet_obs_clock_pings", 5) or 5))
    _next_sync = _sync_iters if _sync_iters > 0 else None


def reset() -> None:
    """Drop knobs and aggregates (test/bench windows)."""
    global _sync_iters, _clock_pings, _next_sync, _windows
    global _per_rank, _straggler_hist, _sync_seq, _pending
    _sync_iters = 0
    _clock_pings = 5
    _next_sync = None
    _sync_seq = 0
    _pending = []
    _windows = 0
    _per_rank = {}
    _straggler_hist = {}
    clockskew.reset()


# ---------------------------------------------------------------- attribution
def attribute_window(tables: Dict[int, Dict[str, list]],
                     offsets: Optional[Dict[int, Dict[str, float]]] = None,
                     ) -> Optional[Dict[str, Any]]:
    """Split each rank's collective wall into wait vs work seconds.

    ``tables`` maps rank -> {kind: [(call_index, enter_mono, seconds),
    ...]} as drained by ``network.take_collective_window()`` on each
    rank; ``offsets`` is the clockskew table (identity when ``None``).
    Only ``(kind, call_index)`` pairs present on EVERY rank are
    attributed — a call one rank dropped from its bounded window (or
    has not issued yet) cannot be split and is skipped.  Returns
    ``None`` when nothing pairs, else::

        {"calls": N, "per_rank": {rank: {wait_s, work_s, calls}},
         "straggler": rank-or-None, "lateness_s": {rank: total}}

    The straggler is the rank with the largest summed lateness (its
    corrected enter minus the earliest rank's, over paired calls)."""
    ranks = sorted(tables)
    if len(ranks) < 2:
        return None
    per_rank = {r: {"wait_s": 0.0, "work_s": 0.0, "calls": 0}
                for r in ranks}
    lateness = {r: 0.0 for r in ranks}
    paired = 0
    kinds = set()
    for k in tables[ranks[0]]:
        if all(k in tables[r] for r in ranks):
            kinds.add(k)
    for kind in sorted(kinds):
        by_rank = {r: {int(i): (float(e), float(s))
                       for i, e, s in tables[r][kind]} for r in ranks}
        common = set(by_rank[ranks[0]])
        for r in ranks[1:]:
            common &= set(by_rank[r])
        for idx in sorted(common):
            enters = {r: clockskew.correct(by_rank[r][idx][0], r, offsets)
                      for r in ranks}
            slowest = max(enters.values())
            earliest = min(enters.values())
            paired += 1
            for r in ranks:
                dur = by_rank[r][idx][1]
                wait = min(max(0.0, slowest - enters[r]), max(0.0, dur))
                per_rank[r]["wait_s"] += wait
                per_rank[r]["work_s"] += max(0.0, dur - wait)
                per_rank[r]["calls"] += 1
                lateness[r] += enters[r] - earliest
    if not paired:
        return None
    straggler = max(ranks, key=lambda r: lateness[r])
    if lateness[straggler] <= 0.0:
        straggler = None
    return {
        "calls": paired,
        "per_rank": {r: {"wait_s": round(v["wait_s"], 6),
                         "work_s": round(v["work_s"], 6),
                         "calls": v["calls"]}
                     for r, v in per_rank.items()},
        "straggler": straggler,
        "lateness_s": {r: round(v, 6) for r, v in lateness.items()},
    }


# ----------------------------------------------------------------- sync points
def start(config=None) -> None:
    """Bring the plane up for a training run: bind knobs and measure
    the clock-offset table.  The measurement is a COLLECTIVE (blocking
    ping/pong + allgather), so the CLI calls this at the one guaranteed
    aligned point — after data loading/resume, before the training
    loop — where no other collective can interleave.  No-op beyond
    configure() on 1-process worlds."""
    from ..parallel import distributed
    configure(config)
    if distributed.is_active():
        clockskew.measure_fleet_offsets(_clock_pings)


def maybe_sync(done: int) -> None:
    """Iteration-boundary hook (never blocks): when ``done`` crosses
    the ``fleet_obs_sync_iters`` cadence, drain-and-post this rank's
    window; then opportunistically collect any fully-posted pending
    windows.  ``done`` advances identically on every rank, so all
    ranks post the same window sequence at the same thresholds."""
    global _next_sync
    from ..parallel import distributed
    if not distributed.is_active():
        return
    if _next_sync is not None and done >= _next_sync:
        while _next_sync <= done:
            _next_sync += _sync_iters
        _post_window(done)
    if _pending:
        _collect_pending(blocking=False)


def final_sync(done: int, timeout_s: Optional[float] = None) -> None:
    """Summary sync: post the final window and collect everything
    pending, BLOCKING with a bounded deadline.  Safe to block only
    because every rank calls this at the same aligned point (normal
    end of training, never the preempt/crash path).  A peer that died
    degrades to a warning — observability must not fail a finished
    run."""
    from ..parallel import distributed, network
    from ..utils.log import log_warning
    if not distributed.is_active():
        return
    if timeout_s is None:
        timeout_s = network.collective_policy()[1]
    _post_window(done)
    try:
        _collect_pending(blocking=True, timeout_s=timeout_s)
    except Exception as e:  # noqa: BLE001 — peer death degrades
        log_warning(f"fleet final sync incomplete ({e}); "
                    f"{len(_pending)} window(s) unattributed")
    # GC own posted payloads: every peer that will ever collect them
    # has just finished its own blocking collection or died
    c = distributed.client()
    me = distributed.rank()
    if c is not None:
        for seq in range(_sync_seq):
            try:
                c.key_value_delete(f"{_FLEET_PREFIX}/{seq}/{me}")
            except Exception:  # noqa: BLE001 — GC is best-effort
                pass


def _post_window(iteration: int) -> None:
    """Drain this rank's collective window and post it (one
    non-blocking KV set) under the next window sequence number."""
    global _sync_seq
    from ..parallel import distributed, network
    from ..utils.log import log_warning
    c = distributed.client()
    if c is None:
        return
    me = distributed.rank()
    window = network.take_collective_window()
    seq = _sync_seq
    _sync_seq += 1
    payload = json.dumps({"rank": me, "iter": int(iteration),
                          "window": window}, separators=(",", ":"))
    try:
        c.key_value_set(f"{_FLEET_PREFIX}/{seq}/{me}", payload,
                        allow_overwrite=True)
    except Exception as e:  # noqa: BLE001 — coordinator loss degrades
        log_warning(f"fleet window post failed ({e}); window dropped")
        return
    _pending.append(seq)


def _collect_pending(blocking: bool,
                     timeout_s: float = 0.0) -> None:
    """Attribute every pending window whose tables are complete.
    Non-blocking mode peeks with one directory read per window and
    leaves incomplete ones pending; blocking mode waits (shared
    deadline) for every rank's table."""
    from ..parallel import distributed
    c = distributed.client()
    if c is None:
        return
    n = distributed.world()
    deadline = time.perf_counter() + max(0.001, timeout_s)
    for seq in list(_pending):
        tables: Dict[int, Dict[str, list]] = {}
        iteration = 0
        try:
            if blocking:
                vals = [c.blocking_key_value_get(
                            f"{_FLEET_PREFIX}/{seq}/{r}",
                            distributed._remaining_ms(deadline))
                        for r in range(n)]
            else:
                pairs = c.key_value_dir_get(f"{_FLEET_PREFIX}/{seq}/")
                if len(pairs) < n:
                    continue            # a rank has not posted yet
                vals = [v for _k, v in pairs]
        except Exception:  # noqa: BLE001 — absent key / deadline
            if blocking:
                raise
            continue
        for v in vals:
            entry = json.loads(v)
            tables[int(entry["rank"])] = entry["window"]
            iteration = max(iteration, int(entry["iter"]))
        _pending.remove(seq)
        _attribute_and_emit(tables, iteration, seq)


def _attribute_and_emit(tables: Dict[int, Dict[str, list]],
                        iteration: int, seq: int) -> None:
    """Run attribution over one complete window set, bump the
    ``dist/wait_s``/``dist/work_s`` counters, fold the aggregates, and
    emit the ``dist_window`` health record naming the straggler."""
    global _windows
    from ..parallel import distributed
    from ..utils.telemetry import HEALTH, TELEMETRY
    report = attribute_window(tables, clockskew.current_offsets())
    if report is None:
        return
    me, n = distributed.rank(), distributed.world()
    mine = report["per_rank"].get(me, {"wait_s": 0.0, "work_s": 0.0})
    TELEMETRY.counter_add("dist/wait_s", mine["wait_s"])
    TELEMETRY.counter_add("dist/work_s", mine["work_s"])
    _windows += 1
    for r, v in report["per_rank"].items():
        agg = _per_rank.setdefault(r, {"wait_s": 0.0, "work_s": 0.0,
                                       "calls": 0})
        agg["wait_s"] += v["wait_s"]
        agg["work_s"] += v["work_s"]
        agg["calls"] += v["calls"]
    if report["straggler"] is not None:
        _straggler_hist[report["straggler"]] = (
            _straggler_hist.get(report["straggler"], 0) + 1)
    if HEALTH.active:
        HEALTH.record("dist_window", {
            "rank": me, "world": n, "iter": int(iteration),
            "seq": int(seq), "calls": report["calls"],
            "wait_s": mine["wait_s"], "work_s": mine["work_s"],
            "straggler": report["straggler"],
            "per_rank": {str(r): v
                         for r, v in report["per_rank"].items()},
            "lateness_s": {str(r): v
                           for r, v in report["lateness_s"].items()},
        })


# -------------------------------------------------------------------- digests
def fleet_section() -> Optional[Dict[str, Any]]:
    """The ``fleet`` section of ``TELEMETRY.stats()`` — ``None`` until
    a window synced, so v6 blobs from non-fleet runs stay v5-shaped."""
    if not _windows:
        return None
    out: Dict[str, Any] = {
        "windows": _windows,
        "sync_iters": _sync_iters,
        "per_rank": {},
        "straggler_hist": {str(r): c
                           for r, c in sorted(_straggler_hist.items())},
    }
    for r, v in sorted(_per_rank.items()):
        total = v["wait_s"] + v["work_s"]
        out["per_rank"][str(r)] = {
            "wait_s": round(v["wait_s"], 6),
            "work_s": round(v["work_s"], 6),
            "calls": v["calls"],
            "wait_fraction": round(v["wait_s"] / total, 6) if total else 0.0,
        }
    offsets = clockskew.current_offsets()
    if offsets:
        out["clock_offsets"] = {str(r): v
                                for r, v in sorted(offsets.items())}
    return out


def summary_line() -> str:
    """One-line rendering for the phase summary; empty until a window
    synced."""
    if not _windows:
        return ""
    wait = sum(v["wait_s"] for v in _per_rank.values())
    work = sum(v["work_s"] for v in _per_rank.values())
    parts = [f"fleet windows={_windows} wait={wait:.3f}s work={work:.3f}s"]
    if _straggler_hist:
        top = max(_straggler_hist, key=_straggler_hist.get)
        parts.append(f"straggler=rank{top}({_straggler_hist[top]}x)")
    return " ".join(parts)
