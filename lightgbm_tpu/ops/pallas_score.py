"""Pallas score-update kernel: ``score + table[leaf_id]`` as a one-hot
MXU contraction.

The boosting score update is a [L]-table gather by a full-N index
vector; XLA lowers that gather at ~1.6 GB/s on v5e (81 ms/iter at 10.5M
rows — round-4 ``score_table_gather`` micro), while the one-hot
formulation streams the row blocks at full block bandwidth like the
histogram kernels.  It is EXACT in f32: each row's dot product has
exactly one nonzero term (1.0f * table[leaf]), so no rounding
accumulates — required for train-score/predict parity.

Covers the score side of the reference's ScoreUpdater
(src/boosting/score_updater.hpp:84-99), whose AddScore(tree, ...) loops
rows on the host threadpool.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .pallas_histogram import _interpret_default

BLOCK = 32768
CHUNK = 512


def _kernel(lv_ref, lid_ref, score_ref, out_ref, *, table_pad):
    def one_chunk(c, carry):
        sl = pl.ds(c * CHUNK, CHUNK)
        lid = lid_ref[0, sl]
        iota = lax.broadcasted_iota(jnp.int32, (table_pad, CHUNK), 0)
        onehot = (iota == lid[None, :]).astype(jnp.float32)
        v = lax.dot_general(lv_ref[...], onehot, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        out_ref[0, sl] = score_ref[0, sl] + v[0]
        return carry

    lax.fori_loop(0, BLOCK // CHUNK, one_chunk, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def score_gather_add(score_row: jax.Array, leaf_id: jax.Array,
                     table: jax.Array,
                     interpret: bool | None = None) -> jax.Array:
    """``score_row + table[leaf_id]`` — [N] f32, [N] i32, [L] f32.

    Scale factors (shrinkage, DART normalization) belong pre-applied to
    ``table``; indices >= len(table) contribute zero (all-zero one-hot
    column), and callers never produce them.
    """
    if interpret is None:
        interpret = _interpret_default()
    n = score_row.shape[0]
    L = table.shape[0]
    table_pad = -(-L // 128) * 128
    pad = (-n) % BLOCK
    sp = jnp.pad(score_row.astype(jnp.float32), (0, pad)).reshape(1, -1)
    lp = jnp.pad(leaf_id, (0, pad)).reshape(1, -1)
    tv = jnp.pad(table.astype(jnp.float32),
                 (0, table_pad - L)).reshape(1, -1)
    out = pl.pallas_call(
        functools.partial(_kernel, table_pad=table_pad),
        grid=(sp.shape[1] // BLOCK,),
        in_specs=[pl.BlockSpec((1, table_pad), lambda i: (0, 0)),
                  pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
                  pl.BlockSpec((1, BLOCK), lambda i: (0, i))],
        out_specs=pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct(sp.shape, jnp.float32),
        interpret=interpret,
    )(tv, lp, sp)
    return out[0, :n]
