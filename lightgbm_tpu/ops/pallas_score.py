"""Pallas score-update kernel: ``score + table[leaf_id]`` as a one-hot
MXU contraction.

The boosting score update is a [L]-table gather by a full-N index
vector; XLA lowers that gather at ~1.6 GB/s on v5e (81 ms/iter at 10.5M
rows — round-4 ``score_table_gather`` micro), while the one-hot
formulation streams the row blocks at full block bandwidth like the
histogram kernels.  It is EXACT in f32: each row's dot product has
exactly one nonzero term (1.0f * table[leaf]), so no rounding
accumulates — required for train-score/predict parity.

Covers the score side of the reference's ScoreUpdater
(src/boosting/score_updater.hpp:84-99), whose AddScore(tree, ...) loops
rows on the host threadpool.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from .pallas_histogram import _interpret_default

BLOCK = 32768
CHUNK = 512

_SELF_CHECK: bool | None = None


def scorer_available() -> bool:
    """Whether the one-hot scorer should replace the table gather.

    ``LIGHTGBM_TPU_SCORE_KERNEL=0/1`` forces it; the default ("auto")
    runs a one-shot self-check on the live backend: the kernel must
    lower AND reproduce ``score + table[leaf_id]`` bit-for-bit.  The
    interpret-mode parity tests run in full f32 and cannot see MXU
    rounding or Mosaic lowering failures, so the check has to happen
    here, non-interpret, on the real device.
    """
    global _SELF_CHECK
    env = os.environ.get("LIGHTGBM_TPU_SCORE_KERNEL", "auto").lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    if _SELF_CHECK is None:
        rng = np.random.default_rng(0)
        table = jnp.asarray(rng.standard_normal(255), jnp.float32)
        lid = jnp.asarray(rng.integers(0, 255, 4096), jnp.int32)
        score = jnp.asarray(rng.standard_normal(4096), jnp.float32)
        try:
            got = score_gather_add(score, lid, table)
            want = score + table[lid]
            _SELF_CHECK = bool(jnp.array_equal(got, want))
        except Exception:  # lowering/compile failure -> gather path
            _SELF_CHECK = False
    return _SELF_CHECK


def _kernel(lv_ref, lid_ref, score_ref, out_ref, *, table_pad):
    def one_chunk(c, carry):
        sl = pl.ds(c * CHUNK, CHUNK)
        lid = lid_ref[0, sl]
        iota = lax.broadcasted_iota(jnp.int32, (table_pad, CHUNK), 0)
        onehot = (iota == lid[None, :]).astype(jnp.float32)
        # Precision.HIGHEST: the MXU otherwise rounds f32 operands to
        # bf16, corrupting the leaf-value table and breaking the
        # train-score/predict exactness contract above.  The 3-pass
        # bf16 decomposition is exact here (one nonzero 1.0f term per
        # row), and the matmul is not the kernel bound.
        v = lax.dot_general(lv_ref[...], onehot, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=lax.Precision.HIGHEST)
        out_ref[0, sl] = score_ref[0, sl] + v[0]
        return carry

    lax.fori_loop(0, BLOCK // CHUNK, one_chunk, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def score_gather_add(score_row: jax.Array, leaf_id: jax.Array,
                     table: jax.Array,
                     interpret: bool | None = None) -> jax.Array:
    """``score_row + table[leaf_id]`` — [N] f32, [N] i32, [L] f32.

    Scale factors (shrinkage, DART normalization) belong pre-applied to
    ``table``; indices >= len(table) contribute zero (all-zero one-hot
    column), and callers never produce them.
    """
    if interpret is None:
        interpret = _interpret_default()
    n = score_row.shape[0]
    L = table.shape[0]
    table_pad = -(-L // 128) * 128
    pad = (-n) % BLOCK
    sp = jnp.pad(score_row.astype(jnp.float32), (0, pad)).reshape(1, -1)
    lp = jnp.pad(leaf_id, (0, pad)).reshape(1, -1)
    tv = jnp.pad(table.astype(jnp.float32),
                 (0, table_pad - L)).reshape(1, -1)
    out = pl.pallas_call(
        functools.partial(_kernel, table_pad=table_pad),
        grid=(sp.shape[1] // BLOCK,),
        in_specs=[pl.BlockSpec((1, table_pad), lambda i: (0, 0)),
                  pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
                  pl.BlockSpec((1, BLOCK), lambda i: (0, i))],
        out_specs=pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct(sp.shape, jnp.float32),
        interpret=interpret,
    )(tv, lp, sp)
    return out[0, :n]
