"""Pallas TPU histogram kernels — the performance core.

Replaces the reference's OpenCL local-atomic kernels
(src/treelearner/ocl/histogram{16,64,256}.cl) and its 4-way unrolled CPU
loop (src/io/dense_bin.hpp:69-193) with a TPU-native formulation:

  * bins live feature-major ``[F, N]`` so each feature's stream is
    contiguous on the lane axis;
  * the per-feature one-hot ``[B, rows]`` is built with int32 VPU compares
    (v5e supports only 32-bit vector compares) and *never leaves VMEM*;
  * the (grad, hess, count) contraction runs on the MXU as a bf16 matmul
    with f32 accumulation.  Gradients/hessians are carried as bf16 hi+lo
    channel pairs (``pack_channels``), giving ~16 mantissa bits — the same
    single-precision stance as the reference GPU learner's default
    ``gpu_use_dp=false`` (src/treelearner/gpu_tree_learner.cpp:677), with
    the count channel exact in f32 accumulation.

Two kernels share the inner body:

  * ``histogram_all``: every row block contributes (the root / full-data
    case);
  * ``histogram_segment``: a scalar-prefetched ``(start_block, n_blocks,
    target_leaf)`` descriptor restricts DMA *and* compute to the blocks of
    one leaf's confinement interval — the TPU equivalent of the reference's
    ordered bins (src/io/ordered_sparse_bin.hpp) whose histogram cost is
    proportional to the leaf, not the dataset.  Out-of-range grid steps
    re-map to the last in-range block, so the pipeline issues no new DMA
    for them, and ``pl.when`` skips their compute.

The 8 weight channels are ``[g_hi, g_lo, h_hi, h_lo, member, 0, 0, 0]``;
``unpack_hist`` folds them back to the ``[F, B, 3]`` (sum_grad, sum_hess,
count) layout the split scan consumes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NUM_CHANNELS = 8
DEFAULT_BLOCK_ROWS = 8192
# VMEM working-set budget for auto block sizing (bytes, of ~16MB/core)
_VMEM_BUDGET = 10 * 1024 * 1024


def supported(num_features: int, num_bins: int, dtype) -> bool:
    """Whether the kernels handle this shape (else callers fall back to the
    XLA one-hot path in ops/histogram.py)."""
    if dtype not in (jnp.uint8, jnp.int8):
        return False
    if num_bins > 256:
        return False
    # accumulator [F, 8, B] f32 must fit VMEM alongside the streams;
    # size with F rounded up to a multiple of 4 — the segment grower pads
    # features to pack them into sort words, so that is the real footprint
    F4 = -(-num_features // 4) * 4
    if F4 * NUM_CHANNELS * num_bins * 4 > 6 * 1024 * 1024:
        return False
    return True


def pick_block_rows(num_features: int, num_bins: int) -> int:
    """Largest power-of-two row block whose VMEM working set fits budget."""
    num_features = -(-num_features // 4) * 4
    acc = num_features * NUM_CHANNELS * num_bins * 4
    rb = DEFAULT_BLOCK_ROWS
    while rb > 512:
        # double-buffered input blocks + one-hot + onehot-int copy
        streams = 2 * rb * (num_features + 2 * NUM_CHANNELS + 4)
        onehot = rb * num_bins * (2 + 4)
        if acc + streams + onehot <= _VMEM_BUDGET:
            return rb
        rb //= 2
    return rb


def pack_channels(grad: jax.Array, hess: jax.Array,
                  member: jax.Array) -> jax.Array:
    """[N] f32 grad/hess/member -> [8, N] bf16 weight channels.

    ``lax.reduce_precision`` performs the hi/lo split; a plain
    f32->bf16->f32 round-trip is elided under XLA's
    ``--xla_allow_excess_precision`` and would zero the lo channel.
    """
    gm = grad * member
    hm = hess * member
    g_hi = lax.reduce_precision(gm, 8, 7)
    h_hi = lax.reduce_precision(hm, 8, 7)
    g_lo = (gm - g_hi).astype(jnp.bfloat16)
    h_lo = (hm - h_hi).astype(jnp.bfloat16)
    z = jnp.zeros(gm.shape, jnp.bfloat16)
    return jnp.stack([g_hi.astype(jnp.bfloat16), g_lo,
                      h_hi.astype(jnp.bfloat16), h_lo,
                      member.astype(jnp.bfloat16), z, z, z])


def unpack_hist(out: jax.Array) -> jax.Array:
    """[F, 8, B] channel sums -> [F, B, 3] (sum_grad, sum_hess, count)."""
    g = out[:, 0] + out[:, 1]
    h = out[:, 2] + out[:, 3]
    c = out[:, 4]
    return jnp.stack([g, h, c], axis=-1)


def _accumulate_block(binsT_ref, w, acc_ref, num_bins):
    """Shared inner body: one [F, rb] bin block x [8, rb] weights."""
    F, rb = binsT_ref.shape
    b = binsT_ref[:].astype(jnp.int32)
    iota = lax.broadcasted_iota(jnp.int32, (num_bins, rb), 0)
    for f in range(F):
        onehot = (b[f:f + 1, :] == iota).astype(jnp.bfloat16)  # [B, rb]
        acc_ref[f] += lax.dot_general(
            w, onehot, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)


def _kernel_all(binsT_ref, w_ref, out_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    _accumulate_block(binsT_ref, w_ref[:], acc_ref, acc_ref.shape[2])

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _kernel_segment(sref, binsT_ref, w_ref, lid_ref, out_ref, acc_ref):
    # sref: prefetched [3] i32 = (start_block, n_blocks, target_leaf)
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(i < sref[1])
    def _():
        mask = (lid_ref[:] == sref[2]).astype(jnp.bfloat16)    # [1, rb]
        _accumulate_block(binsT_ref, w_ref[:] * mask, acc_ref,
                          acc_ref.shape[2])

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "interpret"))
def histogram_all(binsT: jax.Array, w8: jax.Array, num_bins: int,
                  block_rows: int = 0,
                  interpret: bool | None = None) -> jax.Array:
    """Full-data histogram: [F, Npad] bins x [8, Npad] channels -> [F, 8, B].

    Npad must be a multiple of ``block_rows``; pad rows must carry zero
    weight channels (the bin values there may be anything).
    """
    F, n = binsT.shape
    if block_rows <= 0:
        block_rows = pick_block_rows(F, num_bins)
    if interpret is None:
        interpret = _interpret_default()
    assert n % block_rows == 0, (n, block_rows)
    return pl.pallas_call(
        _kernel_all,
        out_shape=jax.ShapeDtypeStruct((F, NUM_CHANNELS, num_bins),
                                       jnp.float32),
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec((F, block_rows), lambda i: (0, i)),
            pl.BlockSpec((NUM_CHANNELS, block_rows), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((F, NUM_CHANNELS, num_bins),
                               lambda i: (0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((F, NUM_CHANNELS, num_bins),
                                   jnp.float32)],
        interpret=interpret,
    )(binsT, w8)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "interpret"))
def histogram_segment(binsT: jax.Array, w8: jax.Array, leaf_id: jax.Array,
                      start_block: jax.Array, n_blocks: jax.Array,
                      target_leaf: jax.Array, num_bins: int,
                      block_rows: int = 0,
                      interpret: bool | None = None) -> jax.Array:
    """Histogram of one leaf, scanning only its confinement blocks.

    ``leaf_id`` is [Npad] i32 row->leaf; rows outside the leaf (or padding,
    which must carry zero weights) contribute nothing.  DMA and compute are
    proportional to ``n_blocks``, not N.
    """
    F, n = binsT.shape
    if block_rows <= 0:
        block_rows = pick_block_rows(F, num_bins)
    if interpret is None:
        interpret = _interpret_default()
    assert n % block_rows == 0, (n, block_rows)
    max_blocks = n // block_rows
    scalars = jnp.stack([start_block, n_blocks, target_leaf]).astype(
        jnp.int32)

    def im_data(i, s):
        blk = jnp.minimum(s[0] + jnp.minimum(i, jnp.maximum(s[1] - 1, 0)),
                          max_blocks - 1)
        return (0, blk)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(max_blocks,),
        in_specs=[
            pl.BlockSpec((F, block_rows), im_data),
            pl.BlockSpec((NUM_CHANNELS, block_rows), im_data),
            pl.BlockSpec((1, block_rows), im_data),
        ],
        out_specs=pl.BlockSpec((F, NUM_CHANNELS, num_bins),
                               lambda i, s: (0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((F, NUM_CHANNELS, num_bins),
                                   jnp.float32)],
    )
    return pl.pallas_call(
        _kernel_segment,
        out_shape=jax.ShapeDtypeStruct((F, NUM_CHANNELS, num_bins),
                                       jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(scalars, binsT, w8, leaf_id.reshape(1, -1))


def leaf_histogram_pallas(binsT: jax.Array, grad: jax.Array,
                          hess: jax.Array, member: jax.Array,
                          num_bins: int, block_rows: int = 0) -> jax.Array:
    """Drop-in [F, B, 3] leaf histogram matching ops.histogram semantics,
    computed with the full-data pallas kernel."""
    w8 = pack_channels(grad, hess, member)
    return unpack_hist(histogram_all(binsT, w8, num_bins, block_rows))
