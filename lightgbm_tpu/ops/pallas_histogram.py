"""Pallas TPU histogram kernels — the performance core.

Replaces the reference's OpenCL local-atomic kernels
(src/treelearner/ocl/histogram{16,64,256}.cl) and its 4-way unrolled CPU
loop (src/io/dense_bin.hpp:69-193) with a TPU-native formulation:

  * bins live feature-major ``[F, N]`` so each feature's stream is
    contiguous on the lane axis;
  * a COMBINED (feature, bin) one-hot ``[F*B, chunk]`` is built with int32
    VPU compares and never leaves VMEM;
  * ONE bf16 matmul per chunk contracts it against the ``[8, chunk]``
    weight channels on the MXU with f32 accumulation — all features in a
    single large-output matmul (round-2's per-feature ``[8, rb] x [rb, B]``
    loop left >90% of the MXU idle; the combined form measures ~2.9 ns/row
    for 28 features x 64 bins on v5e).  Gradients/hessians are carried as
    bf16 hi+lo channel pairs (``pack_channels``), giving ~16 mantissa
    bits — the same single-precision stance as the reference GPU learner's
    default ``gpu_use_dp=false`` (src/treelearner/gpu_tree_learner.cpp:677),
    with the count channel exact in f32 accumulation.

Two kernels share the inner body:

  * ``histogram_all``: every row block contributes (the root / full-data
    case);
  * ``histogram_segment``: a scalar-prefetched ``(start_block, n_blocks,
    target_leaf)`` descriptor restricts DMA *and* compute to the blocks of
    one leaf's confinement interval — the TPU equivalent of the reference's
    ordered bins (src/io/ordered_sparse_bin.hpp) whose histogram cost is
    proportional to the leaf, not the dataset.  Out-of-range grid steps
    re-map to the last in-range block, so the pipeline issues no new DMA
    for them, and ``pl.when`` skips their compute.

The 8 weight channels are ``[g_hi, g_lo, h_hi, h_lo, member, 0, 0, 0]``;
``unpack_hist`` folds a kernel output ``[F, B, 8]`` back to the
``[F, B, 3]`` (sum_grad, sum_hess, count) layout the split scan consumes.

Two env-gated variant fronts ride the same kernels (docs/KERNELS.md has
the full catalogue and measured verdicts):

  * ``LIGHTGBM_TPU_PACKED_ACC``: a packed int16 accumulator stream
    (``quantize_pack_channels``) — grad/hess stochastically rounded to
    int16 and packed into ONE i32 lane, halving both the weight-stream
    HBM DMA and the accumulator channel width (the arxiv 1806.11248 /
    1706.08359 lever).  Kernels detect the i32 dtype (it is part of the
    jit avals, so no new static args) and widen to ``PACKED_CHANNELS``
    bf16 lanes in VMEM; ``unpack_hist_packed`` rescales at unpack.  The
    count channel stays exact.
  * ``LIGHTGBM_TPU_ONEHOT_BUILD``: alternative one-hot constructions
    (``gather``: row-gather from an eye tile; ``twolevel``: two half-
    width compares multiplied) — bit-identical to the iota build by
    construction (same matmul, same accumulation order).

Both are auto-gated by one-shot self-checks on the live backend (the
``LIGHTGBM_TPU_FUSED_ROUTE`` pattern) with clean fallback to the f32 /
iota path, and neither flips to default without a v5e number.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across releases; accept
# either so the fused kernels lower on both sides of the rename
_TPUCompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

import os as _os

NUM_CHANNELS = 8
# channel width of the packed-accumulator stream once widened in VMEM:
# [g_q, h_q, member, 0] — half the 8-channel hi/lo path
PACKED_CHANNELS = 4
DEFAULT_BLOCK_ROWS = 16384
# inner sub-chunk of a row block: the one-hot [fblk*B, CHUNK] lives in
# VMEM only for the duration of one matmul.  Env-tunable (read at
# import) for on-chip inner-loop sweeps: the build is ~5x off its VPU
# bound and these two shape the materialized tile.
CHUNK = int(_os.environ.get("LIGHTGBM_TPU_ONEHOT_CHUNK", "512"))
# feature sub-block: keep fblk*B*CHUNK*2B (one-hot) around 2MB
_FBLK_BIN_BUDGET = int(_os.environ.get("LIGHTGBM_TPU_FBLK_BINS", "2048"))
# VMEM working-set budget for auto block sizing (bytes, of ~16MB/core)
_VMEM_BUDGET = 10 * 1024 * 1024


def _fblk(num_bins: int) -> int:
    return max(1, _FBLK_BIN_BUDGET // num_bins)


def _pick_chunk(rb: int) -> int:
    """Largest lane-aligned chunk <= CHUNK dividing the row block; falls
    back to the whole block for odd user-chosen tpu_row_chunk values."""
    for c in (CHUNK, 256, 128):
        if rb % c == 0:
            return c
    return rb


def supported(num_features: int, num_bins: int, dtype) -> bool:
    """Whether the kernels handle this shape (else callers fall back to the
    XLA one-hot path in ops/histogram.py)."""
    if dtype not in (jnp.uint8, jnp.int8):
        return False
    if num_bins > 256:
        return False
    # accumulator [F*B, 8] f32 must stay well under VMEM; size with F
    # rounded up to a multiple of 4 — the segment grower pads features to
    # pack them into sort words, so that is the real footprint
    F4 = -(-num_features // 4) * 4
    if F4 * num_bins * NUM_CHANNELS * 4 > 4 * 1024 * 1024:
        return False
    return True


def pick_block_rows(num_features: int, num_bins: int,
                    num_rows: int = 0) -> int:
    """Largest power-of-two row block whose VMEM working set fits budget.

    ``num_rows`` (when known) caps the block at the next power of two >=
    the dataset, so small datasets are not padded to a huge block.
    """
    F4 = -(-num_features // 4) * 4
    acc = F4 * num_bins * NUM_CHANNELS * 4
    # one-hot chunk (bf16) + its integer compare intermediate
    onehot = _fblk(num_bins) * num_bins * CHUNK * (2 + 4)
    rb = 4 * DEFAULT_BLOCK_ROWS
    if num_rows > 0:
        cap = 1 << max(0, (num_rows - 1).bit_length())
        rb = min(rb, max(CHUNK, cap))
    while rb > CHUNK:
        # double-buffered input blocks (bins u8, w8 bf16, leaf_id i32)
        streams = 2 * rb * (F4 + 2 * NUM_CHANNELS + 4)
        if acc + streams + onehot <= _VMEM_BUDGET:
            return rb
        rb //= 2
    return rb


def pack_channels(grad: jax.Array, hess: jax.Array,
                  member: jax.Array) -> jax.Array:
    """[N] f32 grad/hess/member -> [8, N] bf16 weight channels.

    ``lax.reduce_precision`` performs the hi/lo split; a plain
    f32->bf16->f32 round-trip is elided under XLA's
    ``--xla_allow_excess_precision`` and would zero the lo channel.
    """
    gm = grad * member
    hm = hess * member
    g_hi = lax.reduce_precision(gm, 8, 7)
    h_hi = lax.reduce_precision(hm, 8, 7)
    g_lo = (gm - g_hi).astype(jnp.bfloat16)
    h_lo = (hm - h_hi).astype(jnp.bfloat16)
    z = jnp.zeros(gm.shape, jnp.bfloat16)
    return jnp.stack([g_hi.astype(jnp.bfloat16), g_lo,
                      h_hi.astype(jnp.bfloat16), h_lo,
                      member.astype(jnp.bfloat16), z, z, z])


def unpack_hist(out: jax.Array) -> jax.Array:
    """[F, B, 8] channel sums -> [F, B, 3] (sum_grad, sum_hess, count)."""
    g = out[..., 0] + out[..., 1]
    h = out[..., 2] + out[..., 3]
    c = out[..., 4]
    return jnp.stack([g, h, c], axis=-1)


def packed_acc_bits() -> int:
    """Quantization width for the packed accumulator
    (``LIGHTGBM_TPU_PACKED_BITS``, default 8, clamped to [2, 15]).

    8 bits is the exactness sweet spot: quantized ints up to +-127 are
    EXACT in the bf16 lanes the MXU contracts (8 mantissa bits), so the
    only error is the stochastic rounding itself.  Widths above 8 trade
    that in-matmul exactness for resolution (bf16 rounds ints > 256) —
    the self-check bound still holds but the verdict belongs on-chip."""
    try:
        bits = int(_os.environ.get("LIGHTGBM_TPU_PACKED_BITS", "8"))
    except ValueError:
        bits = 8
    return max(2, min(bits, 15))


def quantize_pack_channels(grad: jax.Array, hess: jax.Array,
                           member: jax.Array, key=None, bits: int = 8):
    """[N] f32 grad/hess/member -> ``([2, N] i32, [2] f32 scales, clips)``
    packed weight stream for the packed-accumulator kernels.

    Row 0 packs the stochastically-rounded int16 pair — grad*member in
    the high halfword, hess*member in the low — so the weight stream is
    8 bytes/row instead of 16; row 1 carries the member bits (f32
    bitcast) so the count channel stays exact.  ``scales`` rescales the
    summed quantized lanes back to real units at unpack: quantization is
    per CALL, so the rescale is per tree (segment/frontier growers, one
    quantize per grow) or per leaf (plain grower).  Stochastic rounding
    keeps every per-bin sum unbiased; ``clips`` counts saturated lanes
    (|q| == qmax, the rows quantized at the coarsest step) for the
    ``hist/quant_clips`` telemetry counter.
    """
    gm = grad * member
    hm = hess * member
    qmax = float(2 ** (bits - 1) - 1)
    gscale = jnp.maximum(jnp.max(jnp.abs(gm)), 1e-30) / qmax
    hscale = jnp.maximum(jnp.max(jnp.abs(hm)), 1e-30) / qmax
    if key is None:
        # deterministic data-derived key: the rounding only needs per-row
        # uniforms decorrelated from the values, and deriving the fold
        # from the gradient bits gives fresh draws every tree without
        # threading a PRNG key through the growers
        seed = jnp.sum(lax.bitcast_convert_type(
            gm[:8].astype(jnp.float32), jnp.int32).astype(jnp.uint32))
        key = jax.random.fold_in(jax.random.PRNGKey(0x517CC1B7), seed)
    kg, kh = jax.random.split(key)

    def _q(x, scale, k):
        t = x / scale
        fl = jnp.floor(t)
        up = jax.random.uniform(k, t.shape) < (t - fl)
        return jnp.clip(fl + up.astype(jnp.float32),
                        -qmax, qmax).astype(jnp.int32)

    gq = _q(gm, gscale, kg)
    hq = _q(hm, hscale, kh)
    clips = (jnp.sum((jnp.abs(gq) >= qmax).astype(jnp.int32))
             + jnp.sum((jnp.abs(hq) >= qmax).astype(jnp.int32)))
    w2 = jnp.stack([
        (gq << 16) | (hq & 0xFFFF),
        lax.bitcast_convert_type(member.astype(jnp.float32), jnp.int32)])
    return w2, jnp.stack([gscale, hscale]), clips


def unpack_hist_packed(out: jax.Array, scales: jax.Array) -> jax.Array:
    """[..., B, PACKED_CHANNELS] packed-accumulator sums -> [..., B, 3]
    real-unit (sum_grad, sum_hess, count); ``scales`` is
    quantize_pack_channels's [2] rescale pair."""
    g = out[..., 0] * scales[0]
    h = out[..., 1] * scales[1]
    return jnp.stack([g, h, out[..., 2]], axis=-1)


def _packed_wrows(wb: jax.Array) -> jax.Array:
    """[2, chunk] i32 packed stream block -> [PACKED_CHANNELS, chunk]
    bf16 rows [g_q, h_q, member, 0] for the shared matmul.

    Arithmetic shifts sign-extend the int16 halves (v5e-safe: plain i32
    VPU ops, no narrow iota/compare); i32 -> f32 -> bf16 are supported
    single-step converts, and the member lane takes the same f32 -> bf16
    rounding as pack_channels so counts match the 8-channel path
    bitwise."""
    wq = wb[0:1]
    gq = (wq >> 16).astype(jnp.float32).astype(jnp.bfloat16)
    hq = ((wq << 16) >> 16).astype(jnp.float32).astype(jnp.bfloat16)
    m = lax.bitcast_convert_type(wb[1:2], jnp.float32).astype(jnp.bfloat16)
    return jnp.concatenate([gq, hq, m, jnp.zeros_like(m)], axis=0)


def _accumulate_block(binsT_ref, wfn, acc_ref, num_bins, packed4=False,
                      onehot_build="iota"):
    """Shared inner body: one [F, rb] bin block into the [F*B, 8]
    accumulator, one combined-one-hot matmul per (chunk, fblock).

    ``wfn(c)`` returns the [8, chunk] weight channels of chunk ``c``.
    Chunks are walked with an in-kernel ``fori_loop`` so the Mosaic program
    size is independent of the row-block size (a fully unrolled 64-chunk
    body made kernel compilation a large share of the jit time).

    ``packed4``: the bin block holds TWO <=16-bin features per byte
    (feature 2i in the low nibble of row i, 2i+1 in the high) — the TPU
    equivalent of the reference's Dense4bitsBin (dense_nbits_bin.hpp:42):
    half the HBM bin-stream DMA for narrow-bin datasets; unpacking is two
    VPU ops per block.

    ``onehot_build`` picks the one-hot construction (the measured ~18 ms
    VPU bound of the 12.4 ms/pass baseline).  All three builds produce
    the SAME [nf*B, chunk] matrix feeding the SAME dot_general, so the
    f32 accumulation order — and therefore the output bits — cannot
    differ:

      * ``iota``  — compare-vs-broadcasted-iota (the baseline);
      * ``gather``— one eye(B) bf16 tile built in VMEM, one row-gather
        of the chunk's bin indices, one sublane transpose (nf*chunk
        gather rows instead of nf*B*chunk compares);
      * ``twolevel`` — split the bin index into high/low halves and
        multiply two half-width compare one-hots (nf*(Bh+Bl)*chunk
        compares instead of nf*B*chunk; power-of-two B only, falls
        back to iota statically otherwise).
    """
    Fp, rb = binsT_ref.shape
    F = Fp * 2 if packed4 else Fp
    B = num_bins
    fblk = max(1, _fblk(B) // (2 if packed4 else 1))
    chunk = _pick_chunk(rb)

    # LIGHTGBM_TPU_ONEHOT_DTYPE picks the compare dtype for the one-hot
    # build — the kernel's measured bound (~18 ms of the ~27 ms full-N
    # pass at i32).  v5e VERDICT (2026-08-01 on-chip): narrow compares
    # are DEAD on this hardware — u8 iota doesn't lower, 16-bit iota is
    # "not supported by hardware", and even with the i32-iota+downcast
    # construction below both i16 and bf16 fail Mosaic compile with
    # "Target does not support this comparison".  i32 is the default
    # and the only mode known to compile on v5e; the narrow paths stay
    # for backends whose VPU does support them.
    import os as _os
    _env = _os.environ.get("LIGHTGBM_TPU_ONEHOT_DTYPE", "")
    if _env == "u8":
        # no u8 iota on Mosaic and no u8 vector compare on v5e — route
        # to i16 (itself v5e-dead but the nearest requested intent)
        # instead of crashing deep in kernel compilation
        from ..utils.log import log_warning
        log_warning("LIGHTGBM_TPU_ONEHOT_DTYPE=u8 does not lower on "
                    "this backend; using i16")
        _env = "i16"
    cmp_dtype = {"bf16": jnp.bfloat16, "i16": jnp.int16}.get(
        _env, jnp.int32)

    build = onehot_build
    if build == "twolevel" and (B & (B - 1) or B < 4):
        build = "iota"   # two-level needs a power-of-two bin count

    def one_chunk(c, carry):
        wc = wfn(c, chunk)                                  # [8, chunk]
        for p0 in range(0, Fp, fblk):
            np_ = min(fblk, Fp - p0)
            b = binsT_ref[p0:p0 + np_, pl.ds(c * chunk, chunk)]
            if packed4:
                # unpack nibbles in integer space (bitwise ops are not
                # defined for the bf16 compare dtype), then cast
                bi = b.astype(jnp.int32)
                b = jnp.stack([bi & 15, bi >> 4], axis=1).reshape(
                    2 * np_, chunk)
            nf = b.shape[0]
            if build == "gather":
                eye = jnp.eye(B, dtype=jnp.bfloat16)
                oh = jnp.take(eye, b.astype(jnp.int32).reshape(-1),
                              axis=0)                  # [nf*chunk, B]
                onehot = oh.reshape(nf, chunk, B).transpose(
                    0, 2, 1).reshape(nf * B, chunk)
            elif build == "twolevel":
                s = (B.bit_length() - 1) // 2
                Bl = 1 << s
                Bh = B // Bl
                bi = b.astype(jnp.int32)
                ih = lax.broadcasted_iota(jnp.int32, (nf, Bh, chunk), 1)
                il = lax.broadcasted_iota(jnp.int32, (nf, Bl, chunk), 1)
                oh_hi = ((bi >> s)[:, None, :] == ih).astype(jnp.bfloat16)
                oh_lo = ((bi & (Bl - 1))[:, None, :] == il).astype(
                    jnp.bfloat16)
                onehot = (oh_hi[:, :, None, :]
                          * oh_lo[:, None, :, :]).reshape(nf * B, chunk)
            else:
                # narrow compare dtypes: v5e has no 16-bit iota ("16-bit
                # iota not supported by hardware") and no direct u8->bf16
                # convert — build both sides from i32/f32 with supported
                # single-step converts
                iota32 = lax.broadcasted_iota(jnp.int32, (nf, B, chunk), 1)
                if cmp_dtype == jnp.bfloat16:
                    b = b.astype(jnp.int32).astype(jnp.float32).astype(
                        jnp.bfloat16)
                    iota = iota32.astype(jnp.float32).astype(jnp.bfloat16)
                elif cmp_dtype == jnp.int16:
                    b = b.astype(jnp.int32).astype(jnp.int16)
                    iota = iota32.astype(jnp.int16)
                else:
                    b = b.astype(cmp_dtype)
                    iota = iota32
                onehot = (b[:, None, :] == iota).astype(
                    jnp.bfloat16).reshape(nf * B, chunk)
            f0 = (2 * p0 if packed4 else p0)
            acc_ref[f0 * B:(f0 + nf) * B] += lax.dot_general(
                onehot, wc, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        return carry

    lax.fori_loop(0, rb // chunk, one_chunk, 0)


def _kernel_all(binsT_ref, w_ref, out_ref, acc_ref, *, num_bins, packed4,
                onehot_build="iota"):
    # w_ref may carry MULTIPLE 8-channel sets ([8*C, rb]): the matmul
    # output widens to 8*C and each set accumulates independently — used
    # to histogram all C class-trees' roots in one pass (multiclass).
    # An i32 w_ref is the packed-accumulator stream ([2, rb] -> 4 lanes).
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def wfn(c, chunk):
        wc = w_ref[:, pl.ds(c * chunk, chunk)]
        return _packed_wrows(wc) if w_ref.dtype == jnp.int32 else wc

    _accumulate_block(binsT_ref, wfn, acc_ref, num_bins, packed4,
                      onehot_build)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _kernel_segment(sref, binsT_ref, w_ref, lid_ref, out_ref, acc_ref, *,
                    num_bins, packed4, onehot_build="iota"):
    # sref: prefetched [3] i32 = (start_block, n_blocks, target_leaf)
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(i < sref[1])
    def _():
        def wfn(c, chunk):
            wc = w_ref[:, pl.ds(c * chunk, chunk)]
            if w_ref.dtype == jnp.int32:
                wc = _packed_wrows(wc)
            lc = lid_ref[:, pl.ds(c * chunk, chunk)]
            return wc * (lc == sref[2]).astype(jnp.bfloat16)

        _accumulate_block(binsT_ref, wfn, acc_ref, num_bins, packed4,
                          onehot_build)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "interpret",
                                    "packed4", "onehot_build"))
def _histogram_all(binsT: jax.Array, w8: jax.Array, num_bins: int,
                   block_rows: int = 0,
                   interpret: bool | None = None,
                   packed4: bool = False,
                   onehot_build: str = "iota") -> jax.Array:
    F, n = binsT.shape
    F_log = 2 * F if packed4 else F
    CH = int(w8.shape[0])
    if w8.dtype == jnp.int32:
        # packed-accumulator stream: single channel set only (the
        # multiclass batched-roots path keeps the f32 channels)
        assert CH == 2, CH
        C, och = 1, PACKED_CHANNELS
    else:
        assert CH % NUM_CHANNELS == 0, CH
        C = CH // NUM_CHANNELS
        och = CH
    if block_rows <= 0:
        block_rows = pick_block_rows(F_log, num_bins)
    if interpret is None:
        interpret = _interpret_default()
    assert n % block_rows == 0, (n, block_rows)
    out = pl.pallas_call(
        functools.partial(_kernel_all, num_bins=num_bins, packed4=packed4,
                          onehot_build=onehot_build),
        out_shape=jax.ShapeDtypeStruct((F_log * num_bins, och),
                                       jnp.float32),
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec((F, block_rows), lambda i: (0, i)),
            pl.BlockSpec((CH, block_rows), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((F_log * num_bins, och),
                               lambda i: (0, 0)),
        scratch_shapes=[pltpu.VMEM((F_log * num_bins, och), jnp.float32)],
        interpret=interpret,
    )(binsT, w8)
    if C == 1:
        return out.reshape(F_log, num_bins, och)
    # [F*B, C*8] -> [C, F, B, 8]
    return out.reshape(F_log, num_bins, C, NUM_CHANNELS).transpose(
        2, 0, 1, 3)


def histogram_all(binsT: jax.Array, w8: jax.Array, num_bins: int,
                  block_rows: int = 0,
                  interpret: bool | None = None,
                  packed4: bool = False) -> jax.Array:
    """Full-data histogram: [F, Npad] bins x [8*C, Npad] channels ->
    [C, F, B, 8] (squeezed to [F, B, 8] for the common C == 1).

    ``w8`` may stack C independent 8-channel sets (multiclass batched
    roots: every class-tree's root histogram in ONE pass — C x fewer
    full-data scans, and 8*C output columns fill more of the MXU tile),
    or be the [2, Npad] i32 packed-accumulator stream
    (quantize_pack_channels; output [F, B, PACKED_CHANNELS], rescale via
    unpack_hist_packed).  Npad must be a multiple of ``block_rows``; pad
    rows must carry zero weight channels (the bin values there may be
    anything).  With ``packed4`` the bins hold two <=16-bin features per
    byte and F here means PHYSICAL rows; the output has 2F logical
    features.  The one-hot build (LIGHTGBM_TPU_ONEHOT_BUILD) is resolved
    HERE, outside the jitted dispatch, so an env change can never be
    masked by a stale jit cache entry.
    """
    return _histogram_all(binsT, w8, num_bins, block_rows, interpret,
                          packed4, onehot_build_mode())


def _segment_buckets(max_blocks: int) -> list:
    """Static grid-size ladder for histogram_segment.

    A pallas grid is static, but a leaf's confinement interval is data-
    dependent: one kernel sized for max_blocks pays a skipped-but-not-free
    grid step for every block outside the interval, which dominates late-
    tree splits (intervals of a few blocks under a 300+-step grid burned
    >1s/iter at 10.5M rows).  Instead the caller lax.switches between a
    few size variants and runs the smallest one that covers the interval.

    Every variant is a separate Mosaic compile on the backend, so the
    ladder step trades per-iter skipped-step waste against remote-compile
    warmup; LIGHTGBM_TPU_BUCKET_STEP (default 8) tunes it on-chip.
    """
    import os
    step = max(2, int(os.environ.get("LIGHTGBM_TPU_BUCKET_STEP", "8")))
    buckets = []
    b = max_blocks
    while b > 1:
        buckets.append(b)
        b = max(1, b // step)
    buckets.append(1)
    return sorted(set(buckets))


def bucket_index(bucket_list, n_blocks) -> jax.Array:
    """Index of the smallest ladder bucket covering an ``n_blocks``-long
    interval — THE smallest-covering rule.  Shared by the kernels'
    ``lax.switch`` dispatch, ``segment_grid_size`` accounting, and the
    growers' windowed routing so the three can never drift."""
    nb = jnp.asarray(n_blocks, jnp.int32).reshape(())
    return jnp.minimum(jnp.sum(jnp.asarray(bucket_list, jnp.int32) < nb),
                       len(bucket_list) - 1)


def segment_grid_size(bucket_arr: jax.Array, n_blocks) -> jax.Array:
    """Grid steps the bucketed dispatch runs for an ``n_blocks``-long
    interval — the same smallest-covering-bucket rule histogram_segment
    and histogram_frontier apply (``bucket_arr`` is
    ``jnp.asarray(_segment_buckets(max_blocks))``).  Lives here so the
    growers' seg-stats grid accounting can never drift from the actual
    dispatch."""
    if dyn_grid_enabled():
        # dynamic grids are sized exactly to the interval (min 1 step)
        return jnp.maximum(jnp.asarray(n_blocks, jnp.int32), 1)
    return bucket_arr[bucket_index(bucket_arr, n_blocks)]


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "grid_blocks",
                                    "interpret", "packed4", "onehot_build"))
def _histogram_segment_fixed(binsT: jax.Array, w8: jax.Array,
                             leaf_id: jax.Array, start_block: jax.Array,
                             n_blocks: jax.Array, target_leaf: jax.Array,
                             num_bins: int, block_rows: int,
                             grid_blocks: int,
                             interpret: bool | None = None,
                             packed4: bool = False,
                             onehot_build: str = "iota") -> jax.Array:
    """One static-grid variant; grid_blocks must be >= n_blocks."""
    F, n = binsT.shape
    F_log = 2 * F if packed4 else F
    CHW = int(w8.shape[0])
    och = PACKED_CHANNELS if w8.dtype == jnp.int32 else NUM_CHANNELS
    if interpret is None:
        interpret = _interpret_default()
    max_blocks = n // block_rows
    scalars = jnp.stack([start_block, n_blocks, target_leaf]).astype(
        jnp.int32)

    def im_data(i, s):
        blk = jnp.minimum(s[0] + jnp.minimum(i, jnp.maximum(s[1] - 1, 0)),
                          max_blocks - 1)
        return (0, blk)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid_blocks,),
        in_specs=[
            pl.BlockSpec((F, block_rows), im_data),
            pl.BlockSpec((CHW, block_rows), im_data),
            pl.BlockSpec((1, block_rows), im_data),
        ],
        out_specs=pl.BlockSpec((F_log * num_bins, och),
                               lambda i, s: (0, 0)),
        scratch_shapes=[pltpu.VMEM((F_log * num_bins, och),
                                   jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_kernel_segment, num_bins=num_bins,
                          packed4=packed4, onehot_build=onehot_build),
        out_shape=jax.ShapeDtypeStruct((F_log * num_bins, och),
                                       jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(scalars, binsT, w8, leaf_id.reshape(1, -1))
    return out.reshape(F_log, num_bins, och)


# Validated on-chip 2026-07-31 (ONCHIP_LOG.md "dyn-grid lowering check"
# rc=0; strict 10.5M probe 1.53 s/iter dyn vs 1.81-1.91 ladder): Mosaic
# accepts traced grid dims on the axon backend, so exact grids are the
# default — one kernel compile instead of a bucket ladder, zero skipped
# steps.  LIGHTGBM_TPU_DYN_GRID=0 restores the ladder.
_DYN_GRID_DEFAULT = True


def dyn_grid_enabled() -> bool:
    """LIGHTGBM_TPU_DYN_GRID=1 dispatches segment/frontier histograms on
    a DYNAMIC pallas grid sized exactly to the interval: one Mosaic
    compile instead of a bucket-ladder of variants (less remote-compile
    warmup) and zero skipped grid steps.  =0 forces the bucket ladder."""
    import os
    env = os.environ.get("LIGHTGBM_TPU_DYN_GRID", "")
    if env == "1":
        return True
    if env == "0":
        return False
    return _DYN_GRID_DEFAULT


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "interpret",
                                    "packed4", "onehot_build"))
def _histogram_segment_dyn(binsT: jax.Array, w8: jax.Array,
                           leaf_id: jax.Array, start_block: jax.Array,
                           n_blocks: jax.Array, target_leaf: jax.Array,
                           num_bins: int, block_rows: int,
                           interpret: bool | None = None,
                           packed4: bool = False,
                           onehot_build: str = "iota") -> jax.Array:
    """Dynamic-grid variant: the grid is the traced interval length, so
    every step is in-range (no remapping, no skipped steps)."""
    F, n = binsT.shape
    F_log = 2 * F if packed4 else F
    CHW = int(w8.shape[0])
    och = PACKED_CHANNELS if w8.dtype == jnp.int32 else NUM_CHANNELS
    if interpret is None:
        interpret = _interpret_default()
    max_blocks = n // block_rows
    # grid 0 would leave the output unwritten; a 1-step grid with
    # n_blocks == 0 masks all compute and writes zeros (sref[1] == 0)
    grid_n = jnp.clip(n_blocks, 1, max_blocks).astype(jnp.int32)
    scalars = jnp.stack([start_block, n_blocks, target_leaf]).astype(
        jnp.int32)

    def im_data(i, s):
        return (0, jnp.minimum(s[0] + i, max_blocks - 1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid_n,),
        in_specs=[
            pl.BlockSpec((F, block_rows), im_data),
            pl.BlockSpec((CHW, block_rows), im_data),
            pl.BlockSpec((1, block_rows), im_data),
        ],
        out_specs=pl.BlockSpec((F_log * num_bins, och),
                               lambda i, s: (0, 0)),
        scratch_shapes=[pltpu.VMEM((F_log * num_bins, och),
                                   jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_kernel_segment, num_bins=num_bins,
                          packed4=packed4, onehot_build=onehot_build),
        out_shape=jax.ShapeDtypeStruct((F_log * num_bins, och),
                                       jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(scalars, binsT, w8, leaf_id.reshape(1, -1))
    return out.reshape(F_log, num_bins, och)


def histogram_segment(binsT: jax.Array, w8: jax.Array, leaf_id: jax.Array,
                      start_block: jax.Array, n_blocks: jax.Array,
                      target_leaf: jax.Array, num_bins: int,
                      block_rows: int = 0,
                      interpret: bool | None = None,
                      packed4: bool = False) -> jax.Array:
    """Histogram of one leaf, scanning only its confinement blocks.

    ``leaf_id`` is [Npad] i32 row->leaf; rows outside the leaf (or padding,
    which must carry zero weights) contribute nothing.  DMA, compute AND
    grid length are proportional to ``n_blocks``, not N: the call
    dispatches to the smallest static-grid variant covering the interval
    (``_segment_buckets``).  Returns [F, B, 8] (logical features when
    ``packed4``).
    """
    F, n = binsT.shape
    if block_rows <= 0:
        block_rows = pick_block_rows(2 * F if packed4 else F, num_bins)
    assert n % block_rows == 0, (n, block_rows)
    max_blocks = n // block_rows
    ob = onehot_build_mode()
    if dyn_grid_enabled():
        return _histogram_segment_dyn(binsT, w8, leaf_id,
                                      jnp.asarray(start_block, jnp.int32),
                                      jnp.asarray(n_blocks, jnp.int32),
                                      target_leaf, num_bins, block_rows,
                                      interpret, packed4, ob)
    buckets = _segment_buckets(max_blocks)
    if len(buckets) == 1:
        return _histogram_segment_fixed(binsT, w8, leaf_id, start_block,
                                        n_blocks, target_leaf, num_bins,
                                        block_rows, buckets[0], interpret,
                                        packed4, ob)
    n_blocks = jnp.asarray(n_blocks, jnp.int32)
    idx = bucket_index(buckets, n_blocks)
    branches = [
        (lambda gb: lambda b, w, l, s0, nb, tl: _histogram_segment_fixed(
            b, w, l, s0, nb, tl, num_bins, block_rows, gb, interpret,
            packed4, ob))(gb)
        for gb in buckets
    ]
    return jax.lax.switch(idx, branches, binsT, w8, leaf_id, start_block,
                          n_blocks, target_leaf)


_FRONTIER_K = 16   # leaves per batched kernel call: 8 channels x 16 = 128


def frontier_width(num_features: int, num_bins: int) -> int:
    """Batched-frontier width K for this shape: 8*K output channels fill
    the 128-wide MXU tile at K=16; shrink K when the [F*B, 8K] f32
    accumulator would blow the VMEM budget (wide-bin datasets)."""
    F4 = -(-num_features // 4) * 4
    k = _FRONTIER_K
    while k > 1 and F4 * num_bins * NUM_CHANNELS * k * 4 > 6 * 1024 * 1024:
        k //= 2
    return k


def channel_set_capacity(num_features: int, num_bins: int,
                         block_rows: int = 0) -> int:
    """Max stacked 8-channel sets histogram_all can take for this shape
    before VMEM blows: bounds BOTH the [F*B, 8*C] f32 scratch and the
    double-buffered [8*C, block_rows] bf16 weight stream (pick_block_rows
    sized the block for 8 channels, so a wide stack would otherwise
    overrun on narrow-bin datasets with many classes).  Callers batching
    more sets (multiclass roots with large num_class) must chunk."""
    F4 = -(-num_features // 4) * 4
    if block_rows <= 0:
        block_rows = pick_block_rows(num_features, num_bins)
    per_set = (F4 * num_bins * NUM_CHANNELS * 4          # scratch
               + 2 * block_rows * NUM_CHANNELS * 2)      # streamed w8
    return max(1, (6 * 1024 * 1024) // max(per_set, 1))


def _kernel_frontier(sref, binsT_ref, w_ref, lid_ref, out_ref, acc_ref, *,
                     num_bins, K, packed4, onehot_build="iota"):
    """K-leaf batched histogram: one [F*B, 8K] accumulator, the one-hot
    matmul's output dim carries K leaves' channel sets — the structural
    fix for the 8-wide output that capped MXU utilization at ~6%
    (PERF_NOTES round 3): 8*K = 128 fills the MXU lane tile.

    sref layout: [2 + K + n_grid] i32 =
      (n_blocks, pad, targets[K], block_list[n_grid]) — ``block_list``
    holds the union of the K leaves' confinement blocks, so DMA is
    proportional to the union, not to N and not to K separate interval
    scans (siblings share blocks; after compaction the union is small).
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(i < sref[0])
    def _():
        def wfn(c, chunk):
            wc = w_ref[:, pl.ds(c * chunk, chunk)]          # [8, chunk]
            if w_ref.dtype == jnp.int32:
                wc = _packed_wrows(wc)   # packed stream -> [4, chunk]
            lc = lid_ref[:, pl.ds(c * chunk, chunk)]        # [1, chunk]
            # K is static, so the target loads unroll into K SCALAR reads
            # (Mosaic rejects vector loads from SMEM — sref[2:2+K] lowers
            # on the CPU interpreter but not on the chip) and the [8K,
            # chunk] weight block is a K-way concat of masked channels
            rows = []
            for k in range(K):
                mask = (lc == sref[2 + k]).astype(jnp.bfloat16)
                rows.append(mask * wc)                      # [8, chunk]
            return jnp.concatenate(rows, axis=0)            # [8K, chunk]

        _accumulate_block(binsT_ref, wfn, acc_ref, num_bins, packed4,
                          onehot_build)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "grid_blocks",
                                    "K", "interpret", "packed4",
                                    "onehot_build"))
def _histogram_frontier_fixed(binsT: jax.Array, w8: jax.Array,
                              leaf_id: jax.Array, block_list: jax.Array,
                              n_blocks: jax.Array, targets: jax.Array,
                              num_bins: int, block_rows: int,
                              grid_blocks: int, K: int,
                              interpret: bool | None = None,
                              packed4: bool = False,
                              onehot_build: str = "iota") -> jax.Array:
    F, n = binsT.shape
    F_log = 2 * F if packed4 else F
    CHW = int(w8.shape[0])
    och = PACKED_CHANNELS if w8.dtype == jnp.int32 else NUM_CHANNELS
    if interpret is None:
        interpret = _interpret_default()
    max_blocks = n // block_rows
    bl = jnp.pad(block_list.astype(jnp.int32),
                 (0, max(0, grid_blocks - block_list.shape[0])))[:grid_blocks]
    scalars = jnp.concatenate([
        jnp.stack([n_blocks.astype(jnp.int32), jnp.int32(0)]),
        targets.astype(jnp.int32), bl])

    def im_data(i, s):
        # out-of-range grid steps re-read the last in-range block (no new
        # DMA); pl.when skips their compute
        idx = jnp.minimum(i, jnp.maximum(s[0] - 1, 0))
        return (0, jnp.minimum(s[2 + K + idx], max_blocks - 1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid_blocks,),
        in_specs=[
            pl.BlockSpec((F, block_rows), im_data),
            pl.BlockSpec((CHW, block_rows), im_data),
            pl.BlockSpec((1, block_rows), im_data),
        ],
        out_specs=pl.BlockSpec((F_log * num_bins, K * och),
                               lambda i, s: (0, 0)),
        scratch_shapes=[pltpu.VMEM((F_log * num_bins, K * och),
                                   jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_kernel_frontier, num_bins=num_bins, K=K,
                          packed4=packed4, onehot_build=onehot_build),
        out_shape=jax.ShapeDtypeStruct((F_log * num_bins, K * och),
                                       jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(scalars, binsT, w8, leaf_id.reshape(1, -1))
    # [F*B, K*8] -> [K, F, B, 8]
    return out.reshape(F_log, num_bins, K, och).transpose(
        2, 0, 1, 3)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "K",
                                    "interpret", "packed4", "onehot_build"))
def _histogram_frontier_dyn(binsT: jax.Array, w8: jax.Array,
                            leaf_id: jax.Array, block_list: jax.Array,
                            n_blocks: jax.Array, targets: jax.Array,
                            num_bins: int, block_rows: int, K: int,
                            interpret: bool | None = None,
                            packed4: bool = False,
                            onehot_build: str = "iota") -> jax.Array:
    """Dynamic-grid frontier variant: grid == union size, one compile."""
    F, n = binsT.shape
    F_log = 2 * F if packed4 else F
    CHW = int(w8.shape[0])
    och = PACKED_CHANNELS if w8.dtype == jnp.int32 else NUM_CHANNELS
    if interpret is None:
        interpret = _interpret_default()
    max_blocks = n // block_rows
    grid_n = jnp.clip(n_blocks, 1, max_blocks).astype(jnp.int32)
    bl = block_list.astype(jnp.int32)[:max_blocks]
    scalars = jnp.concatenate([
        jnp.stack([n_blocks.astype(jnp.int32), jnp.int32(0)]),
        targets.astype(jnp.int32), bl])

    def im_data(i, s):
        idx = jnp.minimum(i, jnp.maximum(s[0] - 1, 0))
        return (0, jnp.minimum(s[2 + K + idx], max_blocks - 1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid_n,),
        in_specs=[
            pl.BlockSpec((F, block_rows), im_data),
            pl.BlockSpec((CHW, block_rows), im_data),
            pl.BlockSpec((1, block_rows), im_data),
        ],
        out_specs=pl.BlockSpec((F_log * num_bins, K * och),
                               lambda i, s: (0, 0)),
        scratch_shapes=[pltpu.VMEM((F_log * num_bins, K * och),
                                   jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_kernel_frontier, num_bins=num_bins, K=K,
                          packed4=packed4, onehot_build=onehot_build),
        out_shape=jax.ShapeDtypeStruct((F_log * num_bins, K * och),
                                       jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(scalars, binsT, w8, leaf_id.reshape(1, -1))
    return out.reshape(F_log, num_bins, K, och).transpose(
        2, 0, 1, 3)


def histogram_frontier(binsT: jax.Array, w8: jax.Array, leaf_id: jax.Array,
                       block_list: jax.Array, n_blocks: jax.Array,
                       targets: jax.Array, num_bins: int,
                       block_rows: int = 0,
                       interpret: bool | None = None,
                       packed4: bool = False) -> jax.Array:
    """Histograms of K frontier leaves in ONE kernel pass.

    ``block_list`` [M] i32 lists the row blocks to scan (union of the K
    leaves' confinement intervals; entries past ``n_blocks`` are ignored);
    ``targets`` [K] i32 are the leaf ids (-1 entries produce zero
    histograms — masks never match, since real leaf ids are >= 0).
    Returns [K, F, B, 8] (logical features when ``packed4``).
    """
    F, n = binsT.shape
    K = int(targets.shape[0])
    if block_rows <= 0:
        block_rows = pick_block_rows(2 * F if packed4 else F, num_bins)
    assert n % block_rows == 0, (n, block_rows)
    max_blocks = n // block_rows
    ob = onehot_build_mode()
    if dyn_grid_enabled():
        return _histogram_frontier_dyn(binsT, w8, leaf_id, block_list,
                                       jnp.asarray(n_blocks, jnp.int32),
                                       targets, num_bins, block_rows, K,
                                       interpret, packed4, ob)
    cap = min(int(block_list.shape[0]), max_blocks)
    buckets = _segment_buckets(cap)
    n_blocks = jnp.asarray(n_blocks, jnp.int32)
    if len(buckets) == 1:
        return _histogram_frontier_fixed(
            binsT, w8, leaf_id, block_list, n_blocks, targets, num_bins,
            block_rows, buckets[0], K, interpret, packed4, ob)
    idx = jnp.sum(jnp.asarray(buckets, jnp.int32) < n_blocks)
    branches = [
        (lambda gb: lambda b, w, l, bl, nb, tg: _histogram_frontier_fixed(
            b, w, l, bl, nb, tg, num_bins, block_rows, gb, K, interpret,
            packed4, ob))(gb)
        for gb in buckets
    ]
    return jax.lax.switch(idx, branches, binsT, w8, leaf_id, block_list,
                          n_blocks, targets)


# ---------------------------------------------------------------------------
# Fused route + histogram (PERF_NOTES "Designed, not yet built", landed r5).
#
# The windowed route (grower_seg.route_split_windowed) runs as separate XLA
# slice/where/update passes over the SAME blocks the smaller-child histogram
# kernel DMAs anyway.  These kernels fold the split routing into the
# histogram pass: per block, update the leaf_id VMEM block with the split's
# route, THEN accumulate the target leaf's histogram from the UPDATED ids.
# The split feature's bin row is pre-sliced host-side into its own [1, n]
# (frontier: [K, n]) operand: dynamic sublane indexing of the u8 block is
# not safely supported on Mosaic, and a row-selecting index map over the
# [F, n] array needs an (F-misaligned) [1, rb] block that Mosaic rejects
# (sublane dim must be 8-divisible or whole) — the slice is one row of HBM
# traffic per call, noise next to the pass itself.  leaf_id is
# an aliased input/output: blocks outside the interval are never written and
# keep their values; the route update is idempotent (rows moved to new_leaf
# stop matching leaf), so out-of-range grid-step remapping to the last
# in-range block stays correct even when a revisited block re-reads
# post-write data.  Reference analog: routing rides the partition work the
# histogram already pays for (src/treelearner/data_partition.hpp:111).
# ---------------------------------------------------------------------------

_ROUTE_WORDS = 19  # leaf,new_leaf,row,col,thr,dl,cat,mt,dbin,nbf,off + 8 bitset
_MISSING_ZERO = 1  # core/binning.py:24-26 (kept literal: kernels must not
_MISSING_NAN = 2   # import the host-side binning module)


def pack_route(leaf, new_leaf, f, t, dl, cat, bitset, fmeta,
               packed4: bool) -> jax.Array:
    """[_ROUTE_WORDS] i32 route descriptor for the fused kernels.

    ``f`` is the LOGICAL feature; the descriptor carries the physical
    bin row, the group column (for the packed4 nibble parity) and the
    EFB reconstruction scalars so the kernel can reproduce
    reconstruct_feature_column + routed_left exactly."""
    f = jnp.asarray(f, jnp.int32)
    col = (fmeta.feat_group[f] if fmeta.feat_group is not None else f)
    row = col // 2 if packed4 else col
    off = (fmeta.feat_offset[f] if fmeta.feat_group is not None
           else jnp.int32(0))
    head = jnp.stack([
        jnp.asarray(leaf, jnp.int32), jnp.asarray(new_leaf, jnp.int32),
        row, col, jnp.asarray(t, jnp.int32),
        jnp.asarray(dl, jnp.int32), jnp.asarray(cat, jnp.int32),
        fmeta.missing_type[f], fmeta.default_bin[f], fmeta.num_bin[f],
        off]).astype(jnp.int32)
    return jnp.concatenate([head, lax.bitcast_convert_type(
        jnp.asarray(bitset, jnp.uint32), jnp.int32)])


def null_route() -> jax.Array:
    """Route that matches nothing (leaf == -1): the root-histogram case."""
    return (jnp.zeros(_ROUTE_WORDS, jnp.int32).at[0].set(-1))


def _route_block_ids(sref, o: int, frow, lid, packed4: bool):
    """[1, rb] updated leaf ids from the route descriptor at scalar
    offset ``o`` (all sref reads are static-offset SMEM scalars);
    ``frow`` is the split feature's [1, rb] bin-row block (a value).

    All mask logic is i32 0/1 arithmetic and every select predicate is
    a single fresh compare: Mosaic materializes composed bool vectors
    (scalar-bool broadcasts, i1 & / ~ chains) through i8 and then fails
    to compile the i8->i1 trunci ("Unsupported target bitwidth for
    truncation", v5e)."""
    g = frow.astype(jnp.int32)                          # [1, rb]
    if packed4:
        par = sref[o + 3] % 2                           # 0/1 i32 scalar
        g = par * (g >> 4) + (1 - par) * (g & 15)
    thr, dl = sref[o + 4], sref[o + 5]                  # dl: 0/1 i32
    cat, mt = sref[o + 6], sref[o + 7]                  # cat: 0/1 i32
    dbin, nbf, off = sref[o + 8], sref[o + 9], sref[o + 10]
    in_range = ((g >= off).astype(jnp.int32)
                * (g < off + nbf).astype(jnp.int32))
    fcol = jnp.where(in_range == 1, g - off, dbin)
    miss_z = ((mt == _MISSING_ZERO).astype(jnp.int32)
              * (fcol == dbin).astype(jnp.int32))
    miss_n = ((mt == _MISSING_NAN).astype(jnp.int32)
              * (fcol == nbf - 1).astype(jnp.int32))
    is_missing = jnp.minimum(miss_z + miss_n, 1)
    num_left = (is_missing * dl
                + (1 - is_missing) * (fcol <= thr).astype(jnp.int32))
    idx = jnp.clip(fcol, 0, 255)
    # cat bitset membership: 8 unrolled word selects (no vector SMEM loads)
    word = jnp.zeros_like(g)
    for k in range(8):
        word = jnp.where(idx // 32 == k, sref[o + 11 + k], word)
    cat_left = (word >> (idx % 32)) & 1
    go_left = cat * cat_left + (1 - cat) * num_left
    take = (lid == sref[o]).astype(jnp.int32) * (1 - go_left)
    return jnp.where(take == 1, sref[o + 1], lid)


def _kernel_segment_routed(sref, binsT_ref, w_ref, frow_ref, lid_ref,
                           lid_out_ref, out_ref, acc_ref, *,
                           num_bins, packed4, onehot_build="iota"):
    # sref: [3 + _ROUTE_WORDS] = (start_block, n_blocks, target_leaf, route)
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # 1) route this block — unconditional: skipped steps revisit an
    # in-range block and the update is idempotent
    lid_out_ref[...] = _route_block_ids(sref, 3, frow_ref[...],
                                        lid_ref[...], packed4)

    # 2) accumulate the target's histogram from the UPDATED ids
    @pl.when(i < sref[1])
    def _():
        def wfn(c, chunk):
            wc = w_ref[:, pl.ds(c * chunk, chunk)]
            if w_ref.dtype == jnp.int32:
                wc = _packed_wrows(wc)
            lc = lid_out_ref[:, pl.ds(c * chunk, chunk)]
            return wc * (lc == sref[2]).astype(jnp.bfloat16)

        _accumulate_block(binsT_ref, wfn, acc_ref, num_bins, packed4,
                          onehot_build)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "interpret",
                                    "packed4", "onehot_build"))
def _histogram_segment_routed(binsT: jax.Array, w8: jax.Array,
                              leaf_id: jax.Array, start_block: jax.Array,
                              n_blocks: jax.Array, target_leaf: jax.Array,
                              route: jax.Array, num_bins: int,
                              block_rows: int = 0,
                              interpret: bool | None = None,
                              packed4: bool = False,
                              onehot_build: str = "iota"):
    F, n = binsT.shape
    F_log = 2 * F if packed4 else F
    CHW = int(w8.shape[0])
    och = PACKED_CHANNELS if w8.dtype == jnp.int32 else NUM_CHANNELS
    if block_rows <= 0:
        block_rows = pick_block_rows(F_log, num_bins)
    assert n % block_rows == 0, (n, block_rows)
    if interpret is None:
        interpret = _interpret_default()
    max_blocks = n // block_rows
    grid_n = jnp.clip(n_blocks, 1, max_blocks).astype(jnp.int32)
    scalars = jnp.concatenate([
        jnp.stack([start_block, n_blocks, target_leaf]).astype(jnp.int32),
        route.astype(jnp.int32)])
    # split feature's physical bin row (route[2]), as its own [1, n] operand
    frow = lax.dynamic_slice(binsT, (route[2].astype(jnp.int32), 0), (1, n))

    def im_data(i, s):
        return (0, jnp.minimum(s[0] + i, max_blocks - 1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid_n,),
        in_specs=[
            pl.BlockSpec((F, block_rows), im_data),
            pl.BlockSpec((CHW, block_rows), im_data),
            pl.BlockSpec((1, block_rows), im_data),
            pl.BlockSpec((1, block_rows), im_data),
        ],
        out_specs=[
            pl.BlockSpec((1, block_rows), im_data),
            pl.BlockSpec((F_log * num_bins, och),
                         lambda i, s: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((F_log * num_bins, och),
                                   jnp.float32)],
    )
    lid_out, hist = pl.pallas_call(
        functools.partial(_kernel_segment_routed, num_bins=num_bins,
                          packed4=packed4, onehot_build=onehot_build),
        out_shape=[jax.ShapeDtypeStruct((1, n), jnp.int32),
                   jax.ShapeDtypeStruct((F_log * num_bins, och),
                                        jnp.float32)],
        grid_spec=grid_spec,
        # alias indices include the scalar operand: input 4 is leaf_id
        input_output_aliases={4: 0},
        # the extra frow/lid streams push the double-buffered working
        # set past Mosaic's 16 MB default scoped-vmem limit at
        # production shapes (measured 17.14 MB, v5e) — auto-sized from
        # the computed need instead of a hand-set override
        compiler_params=_TPUCompilerParams(
            vmem_limit_bytes=fused_vmem_limit(F, num_bins, 1, block_rows,
                                              packed4)),
        interpret=interpret,
    )(scalars, binsT, w8, frow, leaf_id.reshape(1, -1))
    return lid_out[0], hist.reshape(F_log, num_bins, och)


def histogram_segment_routed(binsT: jax.Array, w8: jax.Array,
                             leaf_id: jax.Array, start_block: jax.Array,
                             n_blocks: jax.Array, target_leaf: jax.Array,
                             route: jax.Array, num_bins: int,
                             block_rows: int = 0,
                             interpret: bool | None = None,
                             packed4: bool = False):
    """Apply one split's route to ``leaf_id`` AND histogram ``target_leaf``
    in a single pass over the confinement interval.

    ``route`` is a [_ROUTE_WORDS] i32 descriptor (pack_route /
    null_route).  Returns ``(leaf_id', [F, B, 8] hist)`` where the ids
    are post-route over the whole array (blocks outside the interval
    keep their values via input/output aliasing); a [2, Npad] i32
    ``w8`` runs the packed-accumulator stream ([F, B, 4] output).
    Dynamic-grid only — callers needing the bucket ladder use the
    unfused pair.
    """
    return _histogram_segment_routed(binsT, w8, leaf_id, start_block,
                                     n_blocks, target_leaf, route,
                                     num_bins, block_rows, interpret,
                                     packed4, onehot_build_mode())


def _kernel_frontier_routed(sref, binsT_ref, w_ref, frows_ref, lid_ref,
                            lid_out_ref, out_ref, acc_ref, *, num_bins, K,
                            packed4, onehot_build="iota", n_targets=0):
    # frows_ref: [K, rb] — the K split features' bin-row blocks
    # sref: [2 + KT + K*_ROUTE_WORDS + n_grid] =
    #   (n_blocks, pad, targets[KT], routes[K*19], block_list[n_grid])
    # KT (n_targets) decouples the histogram width from the route count:
    # the round-pass fusion histograms the K smaller children (KT == K),
    # the fused-K kernel histograms ALL 2K children of the K routes
    # (KT == 2K) so no parent gather / subtraction survives the round
    KT = n_targets or K
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # 1) K route updates — leaves are disjoint and new ids exceed every
    # routed leaf, so at most one route matches a row and application
    # order is irrelevant; invalid slots carry leaf == -1
    lid = lid_ref[...]
    frows = frows_ref[...]
    for k in range(K):
        lid = _route_block_ids(sref, 2 + KT + k * _ROUTE_WORDS,
                               frows[k:k + 1], lid, packed4)
    lid_out_ref[...] = lid

    # 2) batched accumulate of the KT targets from the UPDATED ids
    @pl.when(i < sref[0])
    def _():
        def wfn(c, chunk):
            wc = w_ref[:, pl.ds(c * chunk, chunk)]
            if w_ref.dtype == jnp.int32:
                wc = _packed_wrows(wc)
            lc = lid_out_ref[:, pl.ds(c * chunk, chunk)]
            rows = []
            for k in range(KT):
                mask = (lc == sref[2 + k]).astype(jnp.bfloat16)
                rows.append(mask * wc)
            return jnp.concatenate(rows, axis=0)

        _accumulate_block(binsT_ref, wfn, acc_ref, num_bins, packed4,
                          onehot_build)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "K",
                                    "interpret", "packed4", "onehot_build",
                                    "n_targets"))
def _histogram_frontier_routed(binsT: jax.Array, w8: jax.Array,
                               leaf_id: jax.Array, block_list: jax.Array,
                               n_blocks: jax.Array, targets: jax.Array,
                               routes: jax.Array, num_bins: int,
                               block_rows: int = 0, K: int = 0,
                               interpret: bool | None = None,
                               packed4: bool = False,
                               onehot_build: str = "iota",
                               n_targets: int = 0):
    F, n = binsT.shape
    K = K or int(routes.shape[0])
    KT = n_targets or K
    assert int(targets.shape[0]) == KT, (targets.shape, KT)
    F_log = 2 * F if packed4 else F
    CHW = int(w8.shape[0])
    och = PACKED_CHANNELS if w8.dtype == jnp.int32 else NUM_CHANNELS
    if block_rows <= 0:
        block_rows = pick_block_rows(F_log, num_bins)
    assert n % block_rows == 0, (n, block_rows)
    if interpret is None:
        interpret = _interpret_default()
    max_blocks = n // block_rows
    grid_n = jnp.clip(n_blocks, 1, max_blocks).astype(jnp.int32)
    bl = block_list.astype(jnp.int32)[:max_blocks]
    scalars = jnp.concatenate([
        jnp.stack([n_blocks.astype(jnp.int32), jnp.int32(0)]),
        targets.astype(jnp.int32), routes.astype(jnp.int32).reshape(-1),
        bl])
    blk_base = 2 + KT + K * _ROUTE_WORDS
    # the K split features' physical bin rows (routes[:, 2]), pre-sliced
    # into one [K, n] operand (whole-sublane block: Mosaic-legal)
    frows = jnp.take(binsT, routes[:, 2].astype(jnp.int32), axis=0,
                     mode="clip")

    def im_data(i, s):
        idx = jnp.minimum(i, jnp.maximum(s[0] - 1, 0))
        return (0, jnp.minimum(s[blk_base + idx], max_blocks - 1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid_n,),
        in_specs=[
            pl.BlockSpec((F, block_rows), im_data),
            pl.BlockSpec((CHW, block_rows), im_data),
            pl.BlockSpec((K, block_rows), im_data),
            pl.BlockSpec((1, block_rows), im_data),
        ],
        out_specs=[
            pl.BlockSpec((1, block_rows), im_data),
            pl.BlockSpec((F_log * num_bins, KT * och),
                         lambda i, s: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((F_log * num_bins, KT * och),
                                   jnp.float32)],
    )
    lid_out, hist = pl.pallas_call(
        functools.partial(_kernel_frontier_routed, num_bins=num_bins, K=K,
                          packed4=packed4, onehot_build=onehot_build,
                          n_targets=KT),
        out_shape=[jax.ShapeDtypeStruct((1, n), jnp.int32),
                   jax.ShapeDtypeStruct((F_log * num_bins,
                                         KT * och), jnp.float32)],
        grid_spec=grid_spec,
        # inputs: scalars, binsT, w8, frows, leaf_id
        input_output_aliases={4: 0},
        # see _histogram_segment_routed: the K frow rows + lid streams
        # exceed the 16 MB default scoped-vmem limit at K=16 production
        # shapes — auto-sized from the computed need (the fused-K call
        # carries a KT == 2K wide accumulator, so the limit follows KT)
        compiler_params=_TPUCompilerParams(
            vmem_limit_bytes=fused_vmem_limit(F, num_bins, K, block_rows,
                                              packed4, targets_k=KT)),
        interpret=interpret,
    )(scalars, binsT, w8, frows, leaf_id.reshape(1, -1))
    return lid_out[0], hist.reshape(F_log, num_bins, KT,
                                    och).transpose(2, 0, 1, 3)


def histogram_frontier_routed(binsT: jax.Array, w8: jax.Array,
                              leaf_id: jax.Array, block_list: jax.Array,
                              n_blocks: jax.Array, targets: jax.Array,
                              routes: jax.Array, num_bins: int,
                              block_rows: int = 0, K: int = 0,
                              interpret: bool | None = None,
                              packed4: bool = False):
    """Frontier variant: apply K splits' routes and histogram the K
    target leaves in one pass over the union block list.

    ``routes`` is [K, _ROUTE_WORDS] i32 (invalid slots: null_route()).
    The K split features' bin rows are pre-sliced into one [K, n]
    operand (see the fused-route header comment).  Returns
    ``(leaf_id', [K, F, B, 8])`` ([K, F, B, 4] for a packed i32 ``w8``).
    """
    return _histogram_frontier_routed(binsT, w8, leaf_id, block_list,
                                      n_blocks, targets, routes, num_bins,
                                      block_rows, K, interpret, packed4,
                                      onehot_build_mode())


def histogram_frontier_fusedk(binsT: jax.Array, w8: jax.Array,
                              leaf_id: jax.Array, block_list: jax.Array,
                              n_blocks: jax.Array, targets2: jax.Array,
                              routes: jax.Array, num_bins: int,
                              block_rows: int = 0, K: int = 0,
                              interpret: bool | None = None,
                              packed4: bool = False):
    """Frontier-K fusion: apply the round's K routes AND histogram all
    2K children in ONE pass over the union block list.

    ``routes`` is [K, _ROUTE_WORDS] i32 (invalid slots: null_route());
    ``targets2`` is [2K] i32 = (left children = the K routed parents,
    which keep their leaf id, then right children = the K new leaves),
    -1 skipping a slot.  Returns ``(leaf_id', [2K, F, B, 8])``
    ([2K, F, B, 4] for a packed i32 ``w8``), child order matching
    ``targets2`` — so the round needs NO parent histogram: both
    children come straight off the data pass and the subtraction trick
    plus both ``[L, G, B, 3]`` leaf_hist staging copies disappear.
    Bit-identical to the unfused pair (route, then
    ``histogram_frontier`` over the same 2K targets): the accumulator
    columns per channel set are independent dot products of the same
    one-hot blocks in the same chunk order.  Dynamic-grid only, like
    every fused variant.
    """
    K = K or int(routes.shape[0])
    assert int(targets2.shape[0]) == 2 * K, (targets2.shape, K)
    return _histogram_frontier_routed(binsT, w8, leaf_id, block_list,
                                      n_blocks, targets2, routes, num_bins,
                                      block_rows, K, interpret, packed4,
                                      onehot_build_mode(), n_targets=2 * K)


_FUSED_VMEM_CAP = 64 * 1024 * 1024  # ceiling for the auto-sized limit


@functools.lru_cache(maxsize=None)
def _fused_vmem_est_cached(F_phys: int, num_bins: int, K: int, KT: int,
                           block_rows: int, packed4: bool) -> int:
    F_log = 2 * F_phys if packed4 else F_phys
    streams = block_rows * (F_phys + K + 2 * NUM_CHANNELS + 8)
    out = F_log * num_bins * KT * NUM_CHANNELS * 4
    return 2 * (3 * streams + 3 * out)


def _fused_vmem_est(F_phys: int, num_bins: int, K: int = 1,
                    block_rows: int = 0, packed4: bool = False,
                    targets_k: int | None = None) -> int:
    """Scoped-VMEM working-set estimate (bytes) for the fused kernels.

    DELIBERATELY conservative: ~2x the plain double-buffered sum,
    calibrated so the measured K=16/F=28/rb=32768 case lands near its
    real 17.14 MB (v5e).  Shared by the ``fused_route_fits`` veto and
    the ``fused_vmem_limit`` auto-sizing so the two can never drift.
    ``targets_k`` widens the accumulator term independently of the
    route count (the fused-K kernel carries 2K channel sets over K
    routes); default = K, the round-pass fusion.  Memoized per
    (K, KT, F, row_block) shape — policy + dispatch consult it on
    every grower build and the shape set per process is tiny."""
    F_log = 2 * F_phys if packed4 else F_phys
    if block_rows <= 0:
        block_rows = pick_block_rows(F_log, num_bins)
    return _fused_vmem_est_cached(F_phys, num_bins, K, targets_k or K,
                                  block_rows, bool(packed4))


def fused_vmem_limit(F_phys: int, num_bins: int, K: int = 1,
                     block_rows: int = 0, packed4: bool = False,
                     targets_k: int | None = None) -> int:
    """Auto-sized ``vmem_limit_bytes`` for the fused kernels: 2x the
    conservative working-set estimate, MB-rounded, clamped to
    [16 MB, 64 MB] — the derived replacement for the former hand-set
    64 MB override (the K=16/F=28 case gets ~34 MB; small shapes keep
    Mosaic's 16 MB default).  Recorded as the ``hist/vmem_limit_bytes``
    gauge at dispatch so traces show what the compiler was given."""
    mb = 1024 * 1024
    est = 2 * _fused_vmem_est(F_phys, num_bins, K, block_rows, packed4,
                              targets_k)
    limit = int(min(max(-(-est // mb) * mb, 16 * mb), _FUSED_VMEM_CAP))
    try:
        from ..utils.telemetry import TELEMETRY
        TELEMETRY.gauge_set("hist/vmem_limit_bytes", limit)
    except Exception:
        pass
    return limit


def fused_route_fits(F_phys: int, num_bins: int, K: int = 1,
                     block_rows: int = 0, packed4: bool = False,
                     targets_k: int | None = None) -> bool:
    """Whether the fused kernels' scoped-VMEM working set fits at this
    shape.  The small-shape self-check can't see production-shape OOMs
    (measured: K=16, F=28, rb=32768 needs 17.14 MB against Mosaic's
    16 MB default), so the auto policy consults this conservative
    estimate against the auto-limit ceiling; LIGHTGBM_TPU_FUSED_ROUTE=1
    / LIGHTGBM_TPU_FUSED_K=force bypass it for A/Bs on shapes it
    vetoes."""
    est = _fused_vmem_est(F_phys, num_bins, K, block_rows, packed4,
                          targets_k)
    return est <= int(0.9 * _FUSED_VMEM_CAP)


# build-time decisions, keyed "segment"/"frontier" — benches read this to
# report the kernel that actually ran (the env gate + fits veto make the
# bare self-check result misleading).  Values: False, True (K-target
# round-pass fusion) or the string "fusedk" (2K-children fused-K kernel).
fused_route_decisions: dict = {}


def fused_packed_optin() -> bool:
    """``LIGHTGBM_TPU_FUSED_PACKED=1``: allow the fused route+histogram
    kernels to ride the packed int16-accumulator stream.  Default OFF —
    the growers force the unfused pair whenever packed_acc is on so the
    on-chip A/B isolates one variant at a time (docs/KERNELS.md); this
    opt-in makes the combined variant reachable for its own A/B instead
    of structurally excluded."""
    import os
    return (os.environ.get("LIGHTGBM_TPU_FUSED_PACKED", "").lower()
            in ("1", "on", "true", "force"))


def fused_k_mode() -> str:
    """Raw ``LIGHTGBM_TPU_FUSED_K`` ladder: '' (off, the default) |
    'on' (self-check gated) | 'force' ('force' or a trailing '!'
    bypasses the check for on-chip A/B plumbing)."""
    import os
    env = os.environ.get("LIGHTGBM_TPU_FUSED_K", "").lower()
    if env in ("", "0", "off", "false"):
        return ""
    if env == "force" or env.endswith("!"):
        return "force"
    return "on"


def fused_k_enabled() -> bool:
    """Whether the frontier grower may use the fused-K kernel
    (``histogram_frontier_fusedk``): route + ALL-2K-children histogram
    in one pass, no parent gather / subtraction.

    Default OFF — no variant flips to default without a v5e number
    (the expected win — the route passes' ~0.07-0.2 s/iter plus one of
    the two 0.17 s/iter leaf_hist staging copies — lands in PERF_NOTES
    round 7 first).  ``1/on`` runs the one-shot bit-identity self-check
    vs the unfused pair on the live backend, memoized, with clean
    fallback; ``force``/trailing '!' bypasses.  Dynamic-grid only,
    like every fused variant."""
    global _FUSED_K_CHECK
    mode = fused_k_mode()
    if not mode:
        return False
    if not dyn_grid_enabled():
        return False
    if mode == "force":
        return True
    if _FUSED_K_CHECK is None:
        try:
            _FUSED_K_CHECK = _fused_k_self_check()
        except Exception:
            import sys
            import traceback
            sys.stderr.write("fused-K self-check raised:\n"
                             + traceback.format_exc()[-2000:] + "\n")
            _FUSED_K_CHECK = False
    return _FUSED_K_CHECK


def _fused_k_fallback(reason: str) -> None:
    """Requested-but-vetoed fused-K build: count it so A/B drivers can
    tell a measured off leg from a silently fallen-back force leg."""
    import sys
    try:
        from ..utils.telemetry import TELEMETRY
        TELEMETRY.counter_add("hist/fused_k_fallbacks", 1)
    except Exception:
        pass
    sys.stderr.write(f"fused-K requested but fell back: {reason}\n")


def fused_route_policy(K: int, F_log: int, num_bins: int,
                       block_rows: int, packed4: bool) -> str:
    """The growers' single dispatch policy for the fused route+histogram
    kernels.  Returns a tier: "off" | "k1" (K-target round-pass fusion,
    the kernel the unfused pair's targets match) | "fusedk" (2K-children
    fused-K kernel, frontier K > 1).

    LIGHTGBM_TPU_FUSED_K (off by default) owns the K > 1 tier: 'on'
    self-checks + consults the vmem fit at the 2K-wide carry, 'force'
    bypasses both, and a requested-but-vetoed build counts a
    ``hist/fused_k_fallbacks`` event before falling through to the
    LIGHTGBM_TPU_FUSED_ROUTE handling below.

    LIGHTGBM_TPU_FUSED_ROUTE keeps its meaning: =1 -> the K-target
    fusion wherever the kernels lower (bypasses the K policy and the
    vmem fit veto, for A/Bs); =0 -> off.  Auto: K == 1 only — on-chip
    (v5e, 2026-08-01) the K=16 K-target fusion measured 1.43 s/iter vs
    1.02-1.04 unfused at the HIGGS shape (K serial in-block route
    updates plus K frow streams cost more than the ONE union-pass
    windowed route they replace, and the subtraction still ran) while
    the K=1 segment fusion won 1.28 vs 1.43 — plus the self-check and
    the vmem fit estimate.  The fused-K tier is the re-cut that also
    deletes the parent gather + subtraction; its verdict slot is
    PERF_NOTES round 7."""
    import os
    F_phys = (F_log + 1) // 2 if packed4 else F_log
    if K > 1 and fused_k_mode():
        if not fused_k_enabled():
            _fused_k_fallback("self-check failed or dyn-grid off")
        elif (fused_k_mode() == "force"
              or fused_route_fits(F_phys, num_bins, K, block_rows,
                                  packed4, targets_k=2 * K)):
            return "fusedk"
        else:
            _fused_k_fallback("2K-wide carry fails the vmem fit veto")
    env = os.environ.get("LIGHTGBM_TPU_FUSED_ROUTE", "auto").lower()
    if env in ("0", "off", "false"):
        return "off"
    if env in ("1", "on", "true"):
        return "k1" if fused_route_available() else "off"
    if K > 1:
        return "off"
    return ("k1" if (fused_route_available()
                     and fused_route_fits(F_phys, num_bins, K, block_rows,
                                          packed4))
            else "off")


def _kernel_route_window(sref, frow_ref, lid_ref, lid_out_ref, *, packed4):
    # sref: [2 + _ROUTE_WORDS] = (start_block, n_blocks, route)
    lid_out_ref[...] = _route_block_ids(sref, 2, frow_ref[...],
                                        lid_ref[...], packed4)


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "interpret", "packed4"))
def route_window(binsT: jax.Array, leaf_id: jax.Array,
                 start_block: jax.Array, n_blocks: jax.Array,
                 route: jax.Array, block_rows: int,
                 interpret: bool | None = None,
                 packed4: bool = False) -> jax.Array:
    """Apply one split's route to ``leaf_id`` over the parent's block
    window, writing ONLY those blocks through an aliased input/output.

    The XLA windowed route (grower_seg.route_split_windowed) confines
    the READ side but its bucket lax.switch still materializes a fresh
    full-N leaf_id every call — the v5e trace shows 254 s32[10.5M]
    conditional copies per iteration ≈ 0.18 s/iter at the HIGGS shape.
    Here blocks outside the window are never touched (same aliasing
    contract as histogram_segment_routed).  Dynamic-grid only."""
    F, n = binsT.shape
    if interpret is None:
        interpret = _interpret_default()
    max_blocks = n // block_rows
    grid_n = jnp.clip(n_blocks, 1, max_blocks).astype(jnp.int32)
    scalars = jnp.concatenate([
        jnp.stack([start_block, n_blocks]).astype(jnp.int32),
        route.astype(jnp.int32)])
    frow = lax.dynamic_slice(binsT, (route[2].astype(jnp.int32), 0), (1, n))

    def im(i, s):
        return (0, jnp.minimum(s[0] + i, max_blocks - 1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid_n,),
        in_specs=[pl.BlockSpec((1, block_rows), im),
                  pl.BlockSpec((1, block_rows), im)],
        out_specs=pl.BlockSpec((1, block_rows), im),
    )
    lid_out = pl.pallas_call(
        functools.partial(_kernel_route_window, packed4=packed4),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        grid_spec=grid_spec,
        # operands: scalars, frow, leaf_id — leaf_id aliases the output
        input_output_aliases={2: 0},
        interpret=interpret,
    )(scalars, frow, leaf_id.reshape(1, -1))
    return lid_out[0]


_ROUTE_KERNEL_CHECK: bool | None = None


def route_kernel_available() -> bool:
    """Whether the growers should route through the aliased pallas
    window kernel instead of the XLA switch path.  =0/1 forces; auto
    runs a one-shot on-device parity check (numeric + categorical +
    missing + out-of-window retention) against the XLA route.  Needs
    the dynamic-grid dispatch."""
    global _ROUTE_KERNEL_CHECK
    import os
    env = os.environ.get("LIGHTGBM_TPU_ROUTE_KERNEL", "auto").lower()
    if env in ("0", "off", "false"):
        return False
    if not dyn_grid_enabled():
        return False
    if env in ("1", "on", "true"):
        return True
    # auto engages only on a real accelerator: the kernel exists to
    # avoid a TPU conditional copy; on the CPU interpret path it's one
    # interpreted pallas call per split, a pure slowdown
    if jax.default_backend() == "cpu":
        return False
    if _ROUTE_KERNEL_CHECK is None:
        try:
            _ROUTE_KERNEL_CHECK = _route_kernel_self_check()
        except Exception:
            import sys
            import traceback
            sys.stderr.write("route-kernel self-check raised:\n"
                             + traceback.format_exc()[-2000:] + "\n")
            _ROUTE_KERNEL_CHECK = False
    return _ROUTE_KERNEL_CHECK


def _route_kernel_self_check() -> bool:
    """Tiny multi-block parity run of route_window against a NumPy
    re-derivation (numeric fwd/bwd-missing, categorical bitset,
    untouched blocks outside the window)."""
    import numpy as np
    rng = np.random.default_rng(11)
    F, B, rb, nblk = 4, 16, 512, 6
    n = rb * nblk
    binsT = jnp.asarray(rng.integers(0, B, (F, n)), jnp.uint8)
    lid = np.full(n, 7, np.int32)
    lid[rb:4 * rb] = np.where(rng.random(3 * rb) < 0.5, 3, 5)
    lid = jnp.asarray(lid)
    bitset = jnp.asarray(rng.integers(0, 2**32, 8, dtype=np.uint64)
                         .astype(np.uint32))

    class _M:
        feat_group = None
        feat_offset = None
        missing_type = jnp.asarray([1, 2, 2, 0], jnp.int32)
        default_bin = jnp.asarray([3, 0, 0, 0], jnp.int32)
        num_bin = jnp.full((4,), B, jnp.int32)

    # f=2 exercises the numeric MISSING_NAN branch (bin B-1 routed by
    # default_left, here False); the categorical case ignores missing
    for f, cat, dl in ((0, False, True), (1, True, True),
                       (2, False, False)):
        route = pack_route(3, 9, f, B // 2, dl, cat, bitset, _M, False)
        lid2 = route_window(binsT, lid, jnp.int32(1), jnp.int32(3),
                            route, rb)
        fcol = np.asarray(binsT[f]).astype(np.int64)
        mt = int(_M.missing_type[f])
        miss = ((mt == 1) & (fcol == int(_M.default_bin[f]))
                | (mt == 2) & (fcol == B - 1))
        if cat:
            w = np.asarray(bitset)[np.clip(fcol, 0, 255) // 32]
            go_left = (w >> (np.clip(fcol, 0, 255) % 32)) & 1 > 0
        else:
            go_left = np.where(miss, dl, fcol <= B // 2)
        exp = np.asarray(lid).copy()
        win = np.zeros(n, bool)
        win[rb:4 * rb] = True
        exp[(exp == 3) & ~go_left & win] = 9
        if not np.array_equal(np.asarray(lid2), exp):
            return False
    # packed4: the in-kernel route must unpack the split column by
    # nibble parity (both parities), on 4-bit bins
    bins4 = jnp.asarray(rng.integers(0, 15, (F, n)), jnp.uint8)
    packedT = jnp.asarray(pack_bins_4bit(bins4))

    class _M4(_M):
        num_bin = jnp.full((4,), 15, jnp.int32)
        missing_type = jnp.zeros(4, jnp.int32)
        default_bin = jnp.zeros(4, jnp.int32)

    for f in (1, 2):   # odd = high nibble, even = low
        route = pack_route(3, 9, f, 7, False, False,
                           jnp.zeros(8, jnp.uint32), _M4, True)
        lid4 = route_window(packedT, lid, jnp.int32(1), jnp.int32(3),
                            route, rb, packed4=True)
        fcol = np.asarray(bins4[f]).astype(np.int64)
        exp4 = np.asarray(lid).copy()
        win = np.zeros(n, bool)
        win[rb:4 * rb] = True
        exp4[(exp4 == 3) & (fcol > 7) & win] = 9
        if not np.array_equal(np.asarray(lid4), exp4):
            return False
    return True


_FUSED_ROUTE_CHECK: bool | None = None


def fused_route_available() -> bool:
    """Whether the growers should use the fused route+histogram kernels.

    ``LIGHTGBM_TPU_FUSED_ROUTE=0/1`` forces; default ("auto") runs a
    one-shot self-check on the live backend — the kernels must lower
    AND reproduce the separate route+histogram pair exactly, including
    untouched-block retention through the input/output alias.  Requires
    the dynamic-grid dispatch (the bucket ladder keeps the unfused
    pair).
    """
    global _FUSED_ROUTE_CHECK
    import os
    env = os.environ.get("LIGHTGBM_TPU_FUSED_ROUTE", "auto").lower()
    if env in ("0", "off", "false"):
        return False
    if not dyn_grid_enabled():
        return False
    if env in ("1", "on", "true"):
        return True
    if _FUSED_ROUTE_CHECK is None:
        try:
            _FUSED_ROUTE_CHECK = _fused_route_self_check()
        except Exception:
            import sys
            import traceback
            sys.stderr.write("fused-route self-check raised:\n"
                             + traceback.format_exc()[-2000:] + "\n")
            _FUSED_ROUTE_CHECK = False
    return _FUSED_ROUTE_CHECK


def _fused_route_self_check() -> bool:
    """Tiny multi-block parity run of the fused kernels vs the unfused
    pair on the real backend (numerical + categorical + missing routes,
    out-of-window retention)."""
    import numpy as np
    rng = np.random.default_rng(7)

    def _fail(leg):
        import sys
        sys.stderr.write(f"fused-route self-check FAILED leg: {leg}\n")
        return False

    F, B, rb, nblk = 4, 16, 512, 6
    n = rb * nblk
    binsT = jnp.asarray(rng.integers(0, B, (F, n)), jnp.uint8)
    grad = jnp.asarray(rng.standard_normal(n), jnp.float32)
    hess = jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32)
    member = jnp.ones(n, jnp.float32)
    w8 = pack_channels(grad, hess, member)
    # two leaves confined to blocks [1, 4); leaf 7 elsewhere
    lid = np.full(n, 7, np.int32)
    lid[rb:4 * rb] = np.where(rng.random(3 * rb) < 0.5, 3, 5)
    lid = jnp.asarray(lid)
    bitset = jnp.asarray(rng.integers(0, 2**32, 8, dtype=np.uint64)
                         .astype(np.uint32))

    class _M:  # minimal FeatureMeta-alike for pack_route
        feat_group = None
        feat_offset = None
        missing_type = jnp.asarray([1, 2, 2, 0], jnp.int32)
        default_bin = jnp.asarray([3, 0, 0, 0], jnp.int32)
        num_bin = jnp.full((4,), B, jnp.int32)

    # f=2 exercises the numeric MISSING_NAN branch (bin B-1 routed by
    # default_left, here False); the categorical case ignores missing
    for f, cat, dl in ((0, False, True), (1, True, True),
                       (2, False, False)):
        route = pack_route(3, 9, f, B // 2, dl, cat, bitset, _M, False)
        lid2, hist = histogram_segment_routed(
            binsT, w8, lid, jnp.int32(1), jnp.int32(3), jnp.int32(9),
            route, B, rb)
        # reference: separate route + segment histogram
        fcol = np.asarray(binsT[f]).astype(np.int64)
        mt = int(_M.missing_type[f])
        miss = ((mt == 1) & (fcol == int(_M.default_bin[f]))
                | (mt == 2) & (fcol == B - 1))
        if cat:
            w = np.asarray(bitset)[np.clip(fcol, 0, 255) // 32]
            go_left = (w >> (np.clip(fcol, 0, 255) % 32)) & 1 > 0
        else:
            go_left = np.where(miss, dl, fcol <= B // 2)
        exp = np.asarray(lid).copy()
        win = np.zeros(n, bool)
        win[rb:4 * rb] = True
        exp[(exp == 3) & ~go_left & win] = 9
        if not np.array_equal(np.asarray(lid2), exp):
            return _fail(f"segment lid (cat={cat})")
        ref = histogram_segment(binsT, w8, jnp.asarray(exp), jnp.int32(1),
                                jnp.int32(3), jnp.int32(9), B, rb)
        if not np.allclose(np.asarray(hist), np.asarray(ref), atol=1e-5):
            return _fail(f"segment hist (cat={cat})")
    # packed4: the in-kernel route must unpack the split column by
    # nibble parity (both parities), on 4-bit bins
    bins4 = jnp.asarray(rng.integers(0, 15, (F, n)), jnp.uint8)
    packedT = jnp.asarray(pack_bins_4bit(bins4))

    class _M4(_M):
        num_bin = jnp.full((4,), 15, jnp.int32)
        missing_type = jnp.zeros(4, jnp.int32)
        default_bin = jnp.zeros(4, jnp.int32)

    for f in (1, 2):   # odd = high nibble, even = low
        route = pack_route(3, 9, f, 7, False, False,
                           jnp.zeros(8, jnp.uint32), _M4, True)
        lid4, hist4 = histogram_segment_routed(
            packedT, w8, lid, jnp.int32(1), jnp.int32(3), jnp.int32(9),
            route, 16, rb, packed4=True)
        fcol = np.asarray(bins4[f]).astype(np.int64)
        exp4 = np.asarray(lid).copy()
        win = np.zeros(n, bool)
        win[rb:4 * rb] = True
        exp4[(exp4 == 3) & (fcol > 7) & win] = 9
        if not np.array_equal(np.asarray(lid4), exp4):
            return _fail(f"packed4 lid (f={f})")
        ref4 = histogram_segment(packedT, w8, jnp.asarray(exp4),
                                 jnp.int32(1), jnp.int32(3), jnp.int32(9),
                                 16, rb, packed4=True)
        if not np.allclose(np.asarray(hist4), np.asarray(ref4),
                           atol=1e-5):
            return _fail(f"packed4 hist (f={f})")

    # EFB: group column carries feature at offset; out-of-range bins
    # reconstruct to the feature default
    class _ME(_M):
        feat_group = jnp.asarray([0, 0, 1, 1], jnp.int32)
        feat_offset = jnp.asarray([0, 6, 0, 6], jnp.int32)
        num_bin = jnp.full((4,), 6, jnp.int32)
        missing_type = jnp.zeros(4, jnp.int32)
        default_bin = jnp.zeros(4, jnp.int32)

    route = pack_route(3, 9, 1, 2, False, False, jnp.zeros(8, jnp.uint32),
                       _ME, False)  # feature 1 -> col 0, offset 6
    lid5, _h5 = histogram_segment_routed(
        binsT, w8, lid, jnp.int32(1), jnp.int32(3), jnp.int32(9), route,
        B, rb)
    g = np.asarray(binsT[0]).astype(np.int64)
    fcol = np.where((g >= 6) & (g < 12), g - 6, 0)
    exp5 = np.asarray(lid).copy()
    win = np.zeros(n, bool)
    win[rb:4 * rb] = True
    exp5[(exp5 == 3) & (fcol > 2) & win] = 9
    if not np.array_equal(np.asarray(lid5), exp5):
        return _fail("efb lid")

    # frontier: one real route + one null slot
    K = 2
    routes = jnp.stack([pack_route(5, 10, 2, 4, False, False,
                                   jnp.zeros(8, jnp.uint32), _M, False),
                        null_route()])
    targets = jnp.asarray([10, -1], jnp.int32)
    # union = leaf 5's confinement blocks [1, 4)
    bl = jnp.asarray([1, 2, 3, 0, 0, 0], jnp.int32)
    lid3, hist3 = histogram_frontier_routed(
        binsT, w8, lid, bl, jnp.int32(3), targets, routes, B, rb, K)
    fcol = np.asarray(binsT[2]).astype(np.int64)
    exp3 = np.asarray(lid).copy()
    exp3[(exp3 == 5) & (fcol > 4)] = 10
    if not np.array_equal(np.asarray(lid3), exp3):
        return _fail("frontier lid")
    ref3 = histogram_frontier(binsT, w8, jnp.asarray(exp3), bl,
                              jnp.int32(3), targets, B, rb)
    if not np.allclose(np.asarray(hist3[0]), np.asarray(ref3[0]),
                       atol=1e-5):
        return _fail("frontier hist")

    # frontier + packed4: K routes over nibble-packed rows (both
    # parities — frows are picked as col//2 and sliced per k in-kernel)
    routes4 = jnp.stack([pack_route(3, 9, 1, 7, False, False,
                                    jnp.zeros(8, jnp.uint32), _M4, True),
                         pack_route(5, 10, 2, 7, False, False,
                                    jnp.zeros(8, jnp.uint32), _M4, True)])
    lid6, hist6 = histogram_frontier_routed(
        packedT, w8, lid, bl, jnp.int32(3), jnp.asarray([9, 10], jnp.int32),
        routes4, 16, rb, 2, packed4=True)
    f1 = np.asarray(bins4[1]).astype(np.int64)
    f2 = np.asarray(bins4[2]).astype(np.int64)
    exp6 = np.asarray(lid).copy()
    exp6[(exp6 == 3) & (f1 > 7)] = 9
    exp6[(exp6 == 5) & (f2 > 7)] = 10
    if not np.array_equal(np.asarray(lid6), exp6):
        return _fail("frontier packed4 lid")
    ref6 = histogram_frontier(packedT, w8, jnp.asarray(exp6), bl,
                              jnp.int32(3), jnp.asarray([9, 10], jnp.int32),
                              16, rb, packed4=True)
    if not np.allclose(np.asarray(hist6), np.asarray(ref6), atol=1e-5):
        return _fail("frontier packed4 hist")
    return True


_FUSED_K_CHECK: bool | None = None


def _fused_k_self_check() -> bool:
    """Bit-identity of the fused-K kernel (route + ALL 2K children in
    one pass) vs the unfused pair: numpy-route the ids, then
    ``histogram_frontier`` over the SAME 2K targets.  Exact equality is
    the contract — both kernels concat the same masked channel sets
    into the same one-hot matmul in the same chunk order, so every
    accumulator column is the identical f32 dot product.  Legs:
    numeric zero-missing / NaN-missing / categorical-bitset routes,
    packed4 nibble rows (both parities), EFB group reconstruction."""
    import numpy as np
    rng = np.random.default_rng(11)

    def _fail(leg):
        import sys
        sys.stderr.write(f"fused-K self-check FAILED leg: {leg}\n")
        return False

    F, B, rb, nblk = 4, 16, 512, 6
    n = rb * nblk
    binsT = jnp.asarray(rng.integers(0, B, (F, n)), jnp.uint8)
    grad = jnp.asarray(rng.standard_normal(n), jnp.float32)
    hess = jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32)
    w8 = pack_channels(grad, hess, jnp.ones(n, jnp.float32))
    # two leaves confined to blocks [1, 4); leaf 7 elsewhere
    lid_np = np.full(n, 7, np.int32)
    lid_np[rb:4 * rb] = np.where(rng.random(3 * rb) < 0.5, 3, 5)
    lid = jnp.asarray(lid_np)
    bitset = jnp.asarray(rng.integers(0, 2**32, 8, dtype=np.uint64)
                         .astype(np.uint32))
    bl = jnp.asarray([1, 2, 3, 0, 0, 0], jnp.int32)
    nb = jnp.int32(3)

    class _M:  # minimal FeatureMeta-alike for pack_route
        feat_group = None
        feat_offset = None
        missing_type = jnp.asarray([1, 2, 2, 0], jnp.int32)
        default_bin = jnp.asarray([3, 0, 0, 0], jnp.int32)
        num_bin = jnp.full((4,), B, jnp.int32)

    def _np_go_left(f, thr, dl, cat):
        fcol = np.asarray(binsT[f]).astype(np.int64)
        mt = int(_M.missing_type[f])
        miss = ((mt == 1) & (fcol == int(_M.default_bin[f]))
                | (mt == 2) & (fcol == B - 1))
        if cat:
            w = np.asarray(bitset)[np.clip(fcol, 0, 255) // 32]
            return (w >> (np.clip(fcol, 0, 255) % 32)) & 1 > 0
        return np.where(miss, dl, fcol <= thr)

    # K=2: route flavor under test on leaf 3 + a plain numeric route on
    # leaf 5 riding along, so the 2K=4-wide accumulate always runs;
    # f=0 is the zero-missing branch, f=2 the NaN branch (bin B-1
    # routed by default_left, here False), f=1 the categorical bitset
    for f, cat, dl in ((0, False, True), (1, True, True),
                       (2, False, False)):
        routes = jnp.stack([
            pack_route(3, 9, f, B // 2, dl, cat, bitset, _M, False),
            pack_route(5, 10, 3, B // 3, False, False,
                       jnp.zeros(8, jnp.uint32), _M, False)])
        targets2 = jnp.asarray([3, 5, 9, 10], jnp.int32)
        lid2, hist = histogram_frontier_fusedk(
            binsT, w8, lid, bl, nb, targets2, routes, B, rb, 2)
        exp = lid_np.copy()
        exp[(exp == 3) & ~_np_go_left(f, B // 2, dl, cat)] = 9
        exp[(exp == 5) & ~_np_go_left(3, B // 3, False, False)] = 10
        if not np.array_equal(np.asarray(lid2), exp):
            return _fail(f"lid (f={f}, cat={cat})")
        ref = histogram_frontier(binsT, w8, jnp.asarray(exp), bl, nb,
                                 targets2, B, rb)
        if not np.array_equal(np.asarray(hist), np.asarray(ref)):
            return _fail(f"hist (f={f}, cat={cat})")

    # packed4: both nibble parities across the K routes
    bins4 = rng.integers(0, 15, (F, n))
    packedT = jnp.asarray(pack_bins_4bit(bins4))

    class _M4(_M):
        num_bin = jnp.full((4,), 15, jnp.int32)
        missing_type = jnp.zeros(4, jnp.int32)
        default_bin = jnp.zeros(4, jnp.int32)

    routes4 = jnp.stack([pack_route(3, 9, 1, 7, False, False,
                                    jnp.zeros(8, jnp.uint32), _M4, True),
                         pack_route(5, 10, 2, 7, False, False,
                                    jnp.zeros(8, jnp.uint32), _M4, True)])
    targets2 = jnp.asarray([3, 5, 9, 10], jnp.int32)
    lid4, hist4 = histogram_frontier_fusedk(
        packedT, w8, lid, bl, nb, targets2, routes4, 16, rb, 2,
        packed4=True)
    exp4 = lid_np.copy()
    exp4[(exp4 == 3) & (bins4[1].astype(np.int64) > 7)] = 9
    exp4[(exp4 == 5) & (bins4[2].astype(np.int64) > 7)] = 10
    if not np.array_equal(np.asarray(lid4), exp4):
        return _fail("packed4 lid")
    ref4 = histogram_frontier(packedT, w8, jnp.asarray(exp4), bl, nb,
                              targets2, 16, rb, packed4=True)
    if not np.array_equal(np.asarray(hist4), np.asarray(ref4)):
        return _fail("packed4 hist")

    # EFB: group column carries feature 1 at offset 6; K=1 keeps the
    # KT=2 > K corner covered (one route, both children accumulated)
    class _ME(_M):
        feat_group = jnp.asarray([0, 0, 1, 1], jnp.int32)
        feat_offset = jnp.asarray([0, 6, 0, 6], jnp.int32)
        num_bin = jnp.full((4,), 6, jnp.int32)
        missing_type = jnp.zeros(4, jnp.int32)
        default_bin = jnp.zeros(4, jnp.int32)

    routes_e = pack_route(3, 9, 1, 2, False, False,
                          jnp.zeros(8, jnp.uint32), _ME, False)[None]
    targets_e = jnp.asarray([3, 9], jnp.int32)
    lid5, hist5 = histogram_frontier_fusedk(
        binsT, w8, lid, bl, nb, targets_e, routes_e, B, rb, 1)
    g = np.asarray(binsT[0]).astype(np.int64)
    fcol = np.where((g >= 6) & (g < 12), g - 6, 0)
    exp5 = lid_np.copy()
    exp5[(exp5 == 3) & (fcol > 2)] = 9
    if not np.array_equal(np.asarray(lid5), exp5):
        return _fail("efb lid")
    ref5 = histogram_frontier(binsT, w8, jnp.asarray(exp5), bl, nb,
                              targets_e, B, rb)
    if not np.array_equal(np.asarray(hist5), np.asarray(ref5)):
        return _fail("efb hist")
    return True


# build-time decisions, keyed "segment"/"frontier"/"plain" — benches and
# telemetry read this to report whether the packed stream actually ran
# (the env gate + self-check fallback make the bare env value misleading)
packed_acc_decisions: dict = {}

_PACKED_ACC_CHECK: bool | None = None


def packed_acc_enabled() -> bool:
    """Whether histogram passes should run the packed int16 accumulator
    stream (``LIGHTGBM_TPU_PACKED_ACC``).

    Default OFF — no variant flips to default without a v5e number.
    ``1/on`` runs the one-shot quantization-parity self-check on the
    live backend and falls back to the f32 channel path when it fails
    (or fails to lower); ``force`` bypasses the check for on-chip A/B
    plumbing; ``0/off``/empty disables."""
    global _PACKED_ACC_CHECK
    import os
    env = os.environ.get("LIGHTGBM_TPU_PACKED_ACC", "").lower()
    if env in ("", "0", "off", "false"):
        return False
    if env == "force":
        return True
    if _PACKED_ACC_CHECK is None:
        try:
            _PACKED_ACC_CHECK = _packed_acc_self_check()
        except Exception:
            import sys
            import traceback
            sys.stderr.write("packed-acc self-check raised:\n"
                             + traceback.format_exc()[-2000:] + "\n")
            _PACKED_ACC_CHECK = False
    return _PACKED_ACC_CHECK


def _packed_acc_self_check() -> bool:
    """One-shot parity run of the packed-accumulator stream against the
    f32 channel path on the live backend: count channel EXACT, grad/hess
    bin sums within the stochastic-rounding bound (scale x (count + 1)
    per bin), across the all/segment/frontier and packed4 legs — with a
    fractional-member leg so GOSS/bagging weights stay covered."""
    import numpy as np
    rng = np.random.default_rng(13)

    def _fail(leg):
        import sys
        sys.stderr.write(f"packed-acc self-check FAILED leg: {leg}\n")
        return False

    F, B, rb, nblk = 4, 16, 512, 4
    n = rb * nblk
    bits = packed_acc_bits()
    binsT = jnp.asarray(rng.integers(0, B, (F, n)), jnp.uint8)
    grad = jnp.asarray(rng.standard_normal(n), jnp.float32)
    hess = jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32)
    # fractional members exercise the f32-bitcast count lane (GOSS)
    member = jnp.asarray(np.where(rng.random(n) < 0.2, 0.0,
                                  np.where(rng.random(n) < 0.3, 0.25, 1.0)
                                  ).astype(np.float32))
    w8 = pack_channels(grad, hess, member)
    w2, scales, _clips = quantize_pack_channels(grad, hess, member,
                                                bits=bits)
    sc = np.asarray(scales)

    def _bound(leg, got, ref):
        got, ref = np.asarray(got), np.asarray(ref)
        if not np.array_equal(got[..., 2], ref[..., 2]):
            return _fail(f"{leg} count")
        cnt = ref[..., 2]
        for ch, s in ((0, sc[0]), (1, sc[1])):
            if np.any(np.abs(got[..., ch] - ref[..., ch])
                      > s * (cnt + 1.0) + 1e-4):
                return _fail(f"{leg} ch{ch} bound")
        return True

    ref = unpack_hist(histogram_all(binsT, w8, B, rb))
    got = unpack_hist_packed(histogram_all(binsT, w2, B, rb), scales)
    if not _bound("all", got, ref):
        return False

    lid_np = np.full(n, 7, np.int32)
    lid_np[rb:3 * rb] = np.where(rng.random(2 * rb) < 0.5, 3, 5)
    lid = jnp.asarray(lid_np)
    refs = unpack_hist(histogram_segment(
        binsT, w8, lid, jnp.int32(1), jnp.int32(2), jnp.int32(3), B, rb))
    gots = unpack_hist_packed(histogram_segment(
        binsT, w2, lid, jnp.int32(1), jnp.int32(2), jnp.int32(3), B, rb),
        scales)
    if not _bound("segment", gots, refs):
        return False

    targets = jnp.asarray([3, 5], jnp.int32)
    bl = jnp.arange(nblk, dtype=jnp.int32)
    reff = unpack_hist(histogram_frontier(
        binsT, w8, lid, bl, jnp.int32(nblk), targets, B, rb))
    gotf = unpack_hist_packed(histogram_frontier(
        binsT, w2, lid, bl, jnp.int32(nblk), targets, B, rb), scales)
    if not _bound("frontier", gotf, reff):
        return False

    bins4 = rng.integers(0, 15, (F, n))
    packedT = jnp.asarray(pack_bins_4bit(bins4))
    ref4 = unpack_hist(histogram_all(packedT, w8, 16, rb, packed4=True))
    got4 = unpack_hist_packed(histogram_all(packedT, w2, 16, rb,
                                            packed4=True), scales)
    if not _bound("packed4", got4, ref4):
        return False
    return True


_ONEHOT_BUILD_CHECKS: dict = {}


def onehot_build_mode() -> str:
    """Resolved one-hot construction for the histogram kernels
    (``LIGHTGBM_TPU_ONEHOT_BUILD``).

    ''/'iota' -> the compare-vs-iota baseline.  'gather'/'twolevel' ->
    the alternative build, gated by a one-shot BIT-identity self-check
    against iota on the live backend (all builds feed the same matmul,
    so identity is the contract — any difference means the build is
    wrong or does not lower, and the mode falls back to iota with a
    stderr note).  A trailing '!' ('gather!') bypasses the check for
    on-chip A/Bs.  Resolved in the NON-jit public wrappers, never
    inside a jitted dispatcher, so an env change is never masked by a
    stale jit cache entry."""
    import os
    env = os.environ.get("LIGHTGBM_TPU_ONEHOT_BUILD", "").lower()
    if env in ("", "iota"):
        return "iota"
    force = env.endswith("!")
    mode = env.rstrip("!")
    if mode not in ("gather", "twolevel"):
        return "iota"
    if force:
        return mode
    if mode not in _ONEHOT_BUILD_CHECKS:
        try:
            _ONEHOT_BUILD_CHECKS[mode] = _onehot_build_self_check(mode)
        except Exception:
            import sys
            import traceback
            sys.stderr.write(f"one-hot build self-check ({mode}) raised:\n"
                             + traceback.format_exc()[-2000:] + "\n")
            _ONEHOT_BUILD_CHECKS[mode] = False
    if not _ONEHOT_BUILD_CHECKS[mode]:
        return "iota"
    return mode


def _onehot_build_self_check(mode: str) -> bool:
    """Bit-identity of an alternative one-hot build vs the iota baseline
    (same [nf*B, chunk] matrix, same dot_general, same accumulation
    order => bitwise-equal f32 sums) on full/segment/frontier and
    packed4 legs."""
    import numpy as np
    rng = np.random.default_rng(17)

    def _fail(leg):
        import sys
        sys.stderr.write(f"one-hot build self-check ({mode}) FAILED "
                         f"leg: {leg}\n")
        return False

    F, B, rb, nblk = 4, 16, 512, 4
    n = rb * nblk
    binsT = jnp.asarray(rng.integers(0, B, (F, n)), jnp.uint8)
    grad = jnp.asarray(rng.standard_normal(n), jnp.float32)
    hess = jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32)
    member = jnp.ones(n, jnp.float32)
    w8 = pack_channels(grad, hess, member)

    a = _histogram_all(binsT, w8, B, rb, onehot_build="iota")
    b = _histogram_all(binsT, w8, B, rb, onehot_build=mode)
    if not np.array_equal(np.asarray(a), np.asarray(b)):
        return _fail("all")

    lid_np = np.full(n, 7, np.int32)
    lid_np[rb:3 * rb] = np.where(rng.random(2 * rb) < 0.5, 3, 5)
    lid = jnp.asarray(lid_np)
    sa = _histogram_segment_dyn(binsT, w8, lid, jnp.int32(1), jnp.int32(2),
                                jnp.int32(3), B, rb, onehot_build="iota")
    sb = _histogram_segment_dyn(binsT, w8, lid, jnp.int32(1), jnp.int32(2),
                                jnp.int32(3), B, rb, onehot_build=mode)
    if not np.array_equal(np.asarray(sa), np.asarray(sb)):
        return _fail("segment")

    targets = jnp.asarray([3, 5], jnp.int32)
    bl = jnp.arange(nblk, dtype=jnp.int32)
    fa = _histogram_frontier_dyn(binsT, w8, lid, bl, jnp.int32(nblk),
                                 targets, B, rb, 2, onehot_build="iota")
    fb = _histogram_frontier_dyn(binsT, w8, lid, bl, jnp.int32(nblk),
                                 targets, B, rb, 2, onehot_build=mode)
    if not np.array_equal(np.asarray(fa), np.asarray(fb)):
        return _fail("frontier")

    bins4 = rng.integers(0, 15, (F, n))
    packedT = jnp.asarray(pack_bins_4bit(bins4))
    pa = _histogram_all(packedT, w8, 16, rb, packed4=True,
                        onehot_build="iota")
    pb = _histogram_all(packedT, w8, 16, rb, packed4=True,
                        onehot_build=mode)
    if not np.array_equal(np.asarray(pa), np.asarray(pb)):
        return _fail("packed4")
    return True


def run_kernel_self_checks(verbose: bool = True) -> int:
    """Run every kernel variant self-check on the current backend and
    print a pass/fail line per check — the ``verify_t1.sh
    --with-kernel-checks`` leg (CPU CI runs the interpret path; on-chip
    runs catch lowering drift the interpreter cannot).  Returns a
    process exit code (0 = all green)."""
    checks = [
        ("fused_route", _fused_route_self_check),
        ("fused_k", _fused_k_self_check),
        ("route_kernel", _route_kernel_self_check),
        ("packed_acc", _packed_acc_self_check),
        ("onehot_gather", lambda: _onehot_build_self_check("gather")),
        ("onehot_twolevel", lambda: _onehot_build_self_check("twolevel")),
    ]
    try:
        from ..models.grower_frontier import _hist_stage_self_check
        checks.append(("hist_stage", _hist_stage_self_check))
    except Exception:
        pass
    bad = []
    for name, fn in checks:
        try:
            ok = bool(fn())
        except Exception:
            import sys
            import traceback
            sys.stderr.write(f"kernel self-check {name} raised:\n"
                             + traceback.format_exc()[-2000:] + "\n")
            ok = False
        if verbose:
            print(f"kernel self-check: {'ok' if ok else 'FAIL'} {name}")
        if not ok:
            bad.append(name)
    if verbose:
        print(f"kernel self-checks: {'FAIL' if bad else 'PASS'}")
    return 1 if bad else 0


def leaf_histogram_pallas(binsT: jax.Array, grad: jax.Array,
                          hess: jax.Array, member: jax.Array,
                          num_bins: int, block_rows: int = 0,
                          packed4: bool = False,
                          packed_acc: bool = False,
                          bits: int = 8) -> jax.Array:
    """Drop-in [F, B, 3] leaf histogram matching ops.histogram semantics,
    computed with the full-data pallas kernel.  ``packed_acc`` runs the
    quantized int16 stream instead of the 8-channel hi/lo split — the
    per-call quantize gives this path natural per-leaf scales."""
    if packed_acc:
        w2, scales, _clips = quantize_pack_channels(grad, hess, member,
                                                    bits=bits)
        return unpack_hist_packed(
            histogram_all(binsT, w2, num_bins, block_rows,
                          packed4=packed4), scales)
    w8 = pack_channels(grad, hess, member)
    return unpack_hist(histogram_all(binsT, w8, num_bins, block_rows,
                                     packed4=packed4))


def pack_bins_4bit(binsT):
    """[F, N] u8 (bins <= 15) -> [ceil(F/2), N] u8 with feature 2i in the
    low nibble and 2i+1 in the high (Dense4bitsBin::Push layout idea,
    dense_nbits_bin.hpp:96, re-cut for the feature-major TPU stream)."""
    import numpy as np
    binsT = np.asarray(binsT)
    F = binsT.shape[0]
    if F % 2:
        binsT = np.concatenate(
            [binsT, np.zeros((1, binsT.shape[1]), binsT.dtype)])
    return (binsT[0::2] | (binsT[1::2] << 4)).astype(np.uint8)


def unpack_nibble(byte, col):
    """Logical column ``col``'s 4-bit bins from its packed byte row — the
    single place that knows the nibble convention (odd logical column =
    high nibble; inverse of pack_bins_4bit)."""
    b = byte.astype(jnp.int32)
    return jnp.where(col % 2 == 1, b >> 4, b & 15)


def slice_packed_column(binsT, col):
    """One logical column [N] i32 out of a 4-bit packed feature-major
    matrix (for a single, possibly traced, column index)."""
    byte = lax.dynamic_slice_in_dim(binsT, col // 2, 1, axis=0)[0, :]
    return unpack_nibble(byte, col)
