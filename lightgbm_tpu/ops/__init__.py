from .histogram import histogram_chunked, leaf_histogram
from .split import (FeatureMeta, SplitInfo, SplitParams, best_split,
                    leaf_gain, leaf_output)

__all__ = ["histogram_chunked", "leaf_histogram", "FeatureMeta", "SplitInfo",
           "SplitParams", "best_split", "leaf_gain", "leaf_output"]
