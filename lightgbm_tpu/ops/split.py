"""Best-split search over histograms, vectorized across (feature, threshold).

Re-expresses the reference's sequential two-direction scans
(FeatureHistogram::FindBestThresholdSequence,
src/treelearner/feature_histogram.hpp:508-650) as cumulative sums over the
bin axis with validity masks, so every (feature, threshold, direction)
candidate is evaluated in parallel on the VPU and the winner picked by one
argmax.  Gain math matches GetSplitGains / CalculateSplittedLeafOutput /
GetLeafSplitGainGivenOutput (feature_histogram.hpp:451-506): L1 soft
thresholding, L2, max_delta_step clamp, monotone-direction rejection.

Missing-value semantics (feature_histogram.hpp:91-116):
  * MissingType::None  — single right-to-left scan (missing impossible).
  * MissingType::Zero  — the zero bin is excluded from both running sums and
    from the candidate thresholds; its mass implicitly follows the default
    direction (default_left = True for the right-to-left scan).
  * MissingType::NaN   — the trailing NaN bin is excluded from the running
    sums; two scans try NaN-left and NaN-right.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO

K_EPSILON = 1e-15
NEG_INF = -jnp.inf


class FeatureMeta(NamedTuple):
    """Per-used-feature metadata as device arrays [F]."""
    num_bin: jax.Array       # i32
    missing_type: jax.Array  # i32 (0 none / 1 zero / 2 nan)
    default_bin: jax.Array   # i32
    is_cat: jax.Array        # bool
    monotone: jax.Array      # i32 (-1/0/+1)
    penalty: jax.Array       # f32 (feature_contri)
    # CEGB per-feature penalties (config cegb_penalty_feature_coupled /
    # _lazy, serial_tree_learner.cpp:582-618); None when CEGB unused
    cegb_coupled: jax.Array = None   # f32
    cegb_lazy: jax.Array = None      # f32
    # features already used by any split of the model so far (coupled
    # penalty waived; is_feature_used_in_split_, serial_tree_learner.h:169)
    cegb_used0: jax.Array = None     # f32 0/1
    # EFB bundling (core/bundle.py): physical bin-matrix column and bin
    # offset of each logical feature, plus the static [F, Bf] gather map
    # from the flattened [G*Bg] group histogram.  All None when the dataset
    # is unbundled (column == feature).
    feat_group: jax.Array = None     # i32 [F]
    feat_offset: jax.Array = None    # i32 [F]
    gather_idx: jax.Array = None     # i32 [F, Bf]; -1 = empty slot


class SplitParams(NamedTuple):
    """Static split hyper-parameters (python floats -> folded into jit)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    max_delta_step: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    # static: dataset has categorical features at all.  False lets jit drop
    # the categorical candidate scans (incl. a per-call [F, B] argsort) from
    # the traced program — a large per-split saving on numerical datasets.
    has_cat: bool = True


class SplitInfo(NamedTuple):
    """Best split of one leaf — all scalars (reference SplitInfo,
    src/treelearner/split_info.hpp:22)."""
    gain: jax.Array
    feature: jax.Array        # i32 index into used features; -1 = no split
    threshold: jax.Array      # i32 bin threshold (numerical) or category bin set id
    default_left: jax.Array   # bool
    is_cat: jax.Array         # bool
    cat_bitset: jax.Array     # u32[8] bitset of left-going bins (categorical)
    left_g: jax.Array
    left_h: jax.Array
    left_c: jax.Array
    right_g: jax.Array
    right_h: jax.Array
    right_c: jax.Array
    left_out: jax.Array
    right_out: jax.Array


def expand_group_hist(hist, fmeta: FeatureMeta, parent_g, parent_h,
                      parent_c):
    """[G, Bg, 3] group histogram -> [F, Bf, 3] per-feature histogram.

    Identity when the dataset is unbundled.  For bundled features the
    stored slots are gathered out of the group column and the default-bin
    slot — which bundling never stores (core/bundle.py) — is reconstructed
    as ``leaf_total - sum(stored slots)``, the reference's
    Dataset::FixHistogram (src/io/dataset.cpp:948-967).  For unbundled
    features the same fix is a numerical no-op, so one uniform path
    serves both.
    """
    if fmeta.gather_idx is None:
        return hist
    gi = fmeta.gather_idx                                     # [F, Bf]
    flat = hist.reshape(-1, hist.shape[-1])                   # [G*Bg, 3]
    fh = flat[jnp.clip(gi, 0)] * (gi >= 0)[..., None]         # [F, Bf, 3]
    total = jnp.stack([parent_g, parent_h, parent_c]).astype(fh.dtype)
    Bf = fh.shape[1]
    db_onehot = (jnp.arange(Bf, dtype=jnp.int32)[None, :]
                 == fmeta.default_bin[:, None])               # [F, Bf]
    stored = jnp.sum(fh * (~db_onehot)[..., None], axis=1)    # [F, 3]
    fix = total[None, :] - stored                             # [F, 3]
    return jnp.where(db_onehot[..., None], fix[:, None, :], fh)


def reconstruct_feature_column(gcol, f, fmeta: FeatureMeta):
    """Per-row bin of logical feature ``f`` from its group's raw column
    (inverse of core/bundle.quantize_bundled for one feature)."""
    gcol = gcol.astype(jnp.int32)
    if fmeta.feat_group is None:
        return gcol
    off = fmeta.feat_offset[f]
    nb = fmeta.num_bin[f]
    in_range = (gcol >= off) & (gcol < off + nb)
    return jnp.where(in_range, gcol - off, fmeta.default_bin[f])


def threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(G, H, l1, l2, max_delta_step):
    """-ThresholdL1(G)/(H+l2), clamped to max_delta_step
    (CalculateSplittedLeafOutput, feature_histogram.hpp:453-460)."""
    out = -threshold_l1(G, l1) / (H + l2 + K_EPSILON)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


def leaf_gain_given_output(G, H, l1, l2, out):
    sg = threshold_l1(G, l1)
    return -(2.0 * sg * out + (H + l2) * out * out)


def leaf_gain(G, H, l1, l2, max_delta_step):
    return leaf_gain_given_output(G, H, l1, l2,
                                  leaf_output(G, H, l1, l2, max_delta_step))


def _split_gain(Gl, Hl, Gr, Hr, p: SplitParams, mono, lo, hi,
                extra_l2: float = 0.0):
    l2 = p.lambda_l2 + extra_l2
    out_l = jnp.clip(leaf_output(Gl, Hl, p.lambda_l1, l2, p.max_delta_step), lo, hi)
    out_r = jnp.clip(leaf_output(Gr, Hr, p.lambda_l1, l2, p.max_delta_step), lo, hi)
    gain = (leaf_gain_given_output(Gl, Hl, p.lambda_l1, l2, out_l)
            + leaf_gain_given_output(Gr, Hr, p.lambda_l1, l2, out_r))
    mono_bad = ((mono > 0) & (out_l > out_r)) | ((mono < 0) & (out_l < out_r))
    return jnp.where(mono_bad, 0.0, gain)


def _numerical_candidates(hist, parent, fmeta: FeatureMeta, p: SplitParams,
                          lo, hi):
    """Gains for every (feature, threshold, direction) numerical candidate.

    Returns (gain [F, T, 2], left [F, T, 2, 3]) with T = B-1 thresholds;
    direction 0 = missing/default LEFT (the reference's dir=-1 scan),
    direction 1 = missing RIGHT (dir=+1).
    """
    F, B, _ = hist.shape
    b_idx = jnp.arange(B, dtype=jnp.int32)[None, :]              # [1, B]
    nb = fmeta.num_bin[:, None]
    mt = fmeta.missing_type[:, None]
    # the reference only applies missing-direction handling when num_bin > 2;
    # 2-bin features fall back to one plain scan (feature_histogram.hpp:96-110)
    use_missing = (mt != MISSING_NONE) & (nb > 2)
    nan_bin = jnp.where(mt == MISSING_NAN, nb - 1, -1)
    zero_skip = jnp.where(mt == MISSING_ZERO, fmeta.default_bin[:, None], -1)
    in_range = b_idx < nb
    excluded = ((b_idx == nan_bin) | (b_idx == zero_skip)) & use_missing
    eff = hist * (in_range & ~excluded)[:, :, None].astype(hist.dtype)
    cum = jnp.cumsum(eff, axis=1)                                 # [F, B, 3]
    total_eff = cum[:, -1:, :]
    cum_t = cum[:, :-1, :]                                        # [F, T, 3]

    parent = parent[None, None, :]                                # [1, 1, 3]
    # dir 0 (missing left): right side accumulated from the top, missing mass
    # falls to the left as parent - right.
    right0 = total_eff - cum_t
    left0 = parent - right0
    # dir 1 (missing right): left side accumulated from the bottom.
    left1 = cum_t
    right1 = parent - left1

    left = jnp.stack([left0, left1], axis=2)                      # [F, T, 2, 3]
    right = jnp.stack([right0, right1], axis=2)

    Gl, Hl, Cl = left[..., 0], left[..., 1] + K_EPSILON, left[..., 2]
    Gr, Hr, Cr = right[..., 0], right[..., 1] + K_EPSILON, right[..., 2]
    mono = fmeta.monotone[:, None, None]
    gain = _split_gain(Gl, Hl, Gr, Hr, p, mono, lo, hi)

    t_idx = jnp.arange(B - 1, dtype=jnp.int32)[None, :, None]     # [1, T, 1]
    nb3 = nb[:, :, None]
    mt3 = mt[:, :, None]
    um3 = use_missing[:, :, None]
    dir_idx = jnp.arange(2, dtype=jnp.int32)[None, None, :]
    valid = t_idx < nb3 - 1
    # NaN bin cannot be a left-inclusive threshold when NaN defaults left
    valid &= ~(um3 & (mt3 == MISSING_NAN) & (dir_idx == 0)
               & (t_idx >= nb3 - 2))
    # zero-type: the skipped zero bin is not a candidate threshold
    valid &= ~(um3 & (mt3 == MISSING_ZERO)
               & (t_idx == zero_skip[:, :, None]))
    # second direction only scanned for missing-capable features with >2 bins
    valid &= ~((dir_idx == 1) & ~um3)
    valid &= ~fmeta.is_cat[:, None, None]
    valid &= (Cl >= p.min_data_in_leaf) & (Cr >= p.min_data_in_leaf)
    valid &= (Hl >= p.min_sum_hessian_in_leaf) & (Hr >= p.min_sum_hessian_in_leaf)

    gain = jnp.where(valid, gain, NEG_INF)
    return gain, left


def _cat_used_bin_mask(hist, fmeta: FeatureMeta):
    """Bins a categorical scan may use: in range, and excluding the trailing
    NaN bin unless the feature is fully categorical
    (used_bin = num_bin - 1 + is_full_categorical,
    feature_histogram.hpp:130-131)."""
    B = hist.shape[1]
    b_idx = jnp.arange(B, dtype=jnp.int32)[None, :]
    nb = fmeta.num_bin[:, None]
    used = jnp.where(fmeta.missing_type[:, None] == MISSING_NAN, nb - 1, nb)
    return b_idx < used


def _categorical_onehot_candidates(hist, parent, fmeta: FeatureMeta,
                                   p: SplitParams, lo, hi):
    """One-hot categorical candidates: bin b alone goes left
    (FindBestThresholdCategorical one-hot branch, feature_histogram.hpp:139-170;
    note the one-hot branch uses plain lambda_l2, not cat_l2)."""
    F, B, _ = hist.shape
    left = hist                                                   # [F, B, 3]
    right = parent[None, None, :] - left
    Gl, Hl, Cl = left[..., 0], left[..., 1] + K_EPSILON, left[..., 2]
    Gr, Hr, Cr = right[..., 0], right[..., 1] + K_EPSILON, right[..., 2]
    gain = _split_gain(Gr, Hr, Gl, Hl, p, 0, lo, hi)

    valid = fmeta.is_cat[:, None] & _cat_used_bin_mask(hist, fmeta)
    valid &= (Cl >= p.min_data_in_leaf) & (Cr >= p.min_data_in_leaf)
    valid &= (Hl >= p.min_sum_hessian_in_leaf) & (Hr >= p.min_sum_hessian_in_leaf)
    gain = jnp.where(valid, gain, NEG_INF)
    return gain, left


def _categorical_sorted_candidates(hist, parent, fmeta: FeatureMeta,
                                   p: SplitParams, lo, hi):
    """Sorted-subset categorical scan: order bins by grad/hess ratio, take a
    prefix or suffix of the order as the left set
    (feature_histogram.hpp:118-300: sort by sum_gradients/(sum_hessians +
    cat_smooth), scan both directions up to max_cat_threshold, cat_l2).

    Returns (gain [F, B, 2], left [F, B, 2, 3], order [F, B]) where candidate
    (f, k, d) means: order positions <= k go LEFT (d=0), or order positions
    >= k go LEFT (d=1).
    """
    F, B, _ = hist.shape
    b_idx = jnp.arange(B, dtype=jnp.int32)[None, :]
    in_range = _cat_used_bin_mask(hist, fmeta)
    cnt = hist[..., 2]
    # only bins with cnt >= cat_smooth enter the order
    # (feature_histogram.hpp:172-175); excluded bins sort to the end with 0
    # contribution
    usable = in_range & (cnt >= p.cat_smooth)
    ratio = hist[..., 0] / (hist[..., 1] + p.cat_smooth)
    ratio = jnp.where(usable, ratio, jnp.inf)
    order = jnp.argsort(ratio, axis=1).astype(jnp.int32)          # [F, B]
    sorted_hist = jnp.take_along_axis(hist, order[:, :, None], axis=1)
    sorted_valid = jnp.take_along_axis(usable, order, axis=1)
    sorted_hist = sorted_hist * sorted_valid[:, :, None]

    pre = jnp.cumsum(sorted_hist, axis=1)                         # prefix sums
    total_eff = pre[:, -1:, :]
    suf = total_eff - pre + sorted_hist                           # suffix sums
    left = jnp.stack([pre, suf], axis=2)                          # [F, B, 2, 3]
    right = parent[None, None, None, :] - left

    Gl, Hl, Cl = left[..., 0], left[..., 1] + K_EPSILON, left[..., 2]
    Gr, Hr, Cr = right[..., 0], right[..., 1] + K_EPSILON, right[..., 2]
    # categorical splits ignore monotone constraints (GetSplitGains called
    # with monotone_type=0, feature_histogram.hpp:226)
    gain = _split_gain(Gl, Hl, Gr, Hr, p, 0, lo, hi, extra_l2=p.cat_l2)

    num_valid = sorted_valid.sum(axis=1).astype(jnp.int32)[:, None, None]
    k_idx = b_idx[:, :, None]
    left_size = jnp.where(jnp.arange(2)[None, None, :] == 0,
                          k_idx + 1, num_valid - k_idx)
    valid = fmeta.is_cat[:, None, None] & sorted_valid[:, :, None]
    # the moved set is capped at min(max_cat_threshold, (used_bin+1)/2)
    # categories (feature_histogram.hpp:192: max_num_cat).  Taking EVERY
    # usable category left is legal — rows in unlisted bins (the NaN
    # category, zero-count bins) still route right, so validity is
    # gated on DATA counts like the reference's scan, not on a strict
    # category subset (its test_categorical_handle_na isolates {0} left
    # with the NaN rows falling right by default).
    max_num_cat = jnp.minimum(int(p.max_cat_threshold), (num_valid + 1) // 2)
    valid &= (left_size >= 1) & (Cl > 0) & (Cr > 0)
    valid &= left_size <= max_num_cat
    valid &= (Cl >= p.min_data_in_leaf) & (Cr >= p.min_data_in_leaf)
    # the right (unmoved) side must keep at least min_data_per_group rows
    # (feature_histogram.hpp:216); the reference's cnt_cur_group run-length
    # gate thins candidates WITHIN the scan — omitted here (vectorized scan
    # evaluates each prefix independently), which can only consider more
    # candidates, never fewer.
    valid &= Cr >= float(p.min_data_per_group)
    valid &= (Hl >= p.min_sum_hessian_in_leaf) & (Hr >= p.min_sum_hessian_in_leaf)
    gain = jnp.where(valid, gain, NEG_INF)
    return gain, left, order


def build_cat_bitset(selected_bins_mask: jax.Array) -> jax.Array:
    """[B] bool -> u32[8] bitset (supports max_bin <= 256)."""
    B = selected_bins_mask.shape[0]
    pad = (-B) % 32
    m = jnp.pad(selected_bins_mask.astype(jnp.uint32), (0, pad)).reshape(-1, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :]
    words = (m * weights).sum(axis=1).astype(jnp.uint32)
    out = jnp.zeros(8, dtype=jnp.uint32)
    return out.at[: words.shape[0]].set(words[:8])


def _all_candidates(hist, parent_g, parent_h, parent_c, fmeta: FeatureMeta,
                    p: SplitParams, lo, hi):
    """Shared candidate evaluation: per-feature family winners + gains."""
    F = hist.shape[0]
    parent = jnp.stack([parent_g, parent_h, parent_c]).astype(hist.dtype)

    gain_shift = leaf_gain(parent_g, parent_h + 2 * K_EPSILON,
                           p.lambda_l1, p.lambda_l2, p.max_delta_step)
    min_gain_shift = gain_shift + p.min_gain_to_split

    def fam_best(gain_flat):
        idx = jnp.argmax(gain_flat, axis=1)
        return idx, jnp.take_along_axis(gain_flat, idx[:, None], axis=1)[:, 0]

    num_gain, num_left = _numerical_candidates(hist, parent, fmeta, p, lo, hi)
    ni, ng = fam_best(num_gain.reshape(F, -1))

    if not p.has_cat:
        z = jnp.zeros(F, dtype=jnp.int32)
        fgain_out = jnp.where(ng > min_gain_shift,
                              (ng - min_gain_shift) * fmeta.penalty, NEG_INF)
        return dict(parent=parent, num_left=num_left, oh_left=None,
                    so_left=None, so_order=None, ni=ni, oi=z, si=z,
                    fam=z, fgain_out=fgain_out)

    oh_gain, oh_left = _categorical_onehot_candidates(hist, parent, fmeta,
                                                      p, lo, hi)
    so_gain, so_left, so_order = _categorical_sorted_candidates(
        hist, parent, fmeta, p, lo, hi)

    # categorical one-hot only for small-arity features (max_cat_to_onehot)
    use_onehot = (fmeta.num_bin[:, None] <= int(p.max_cat_to_onehot))
    oh_gain = jnp.where(use_onehot, oh_gain, NEG_INF)
    so_gain = jnp.where(use_onehot[:, :, None], NEG_INF, so_gain)

    oi, og = fam_best(oh_gain)
    si, sg = fam_best(so_gain.reshape(F, -1))

    fam_gains = jnp.stack([ng, og, sg], axis=1)                    # [F, 3]
    fam = jnp.argmax(fam_gains, axis=1)
    fgain = jnp.max(fam_gains, axis=1)
    splittable = fgain > min_gain_shift
    fgain_out = jnp.where(splittable,
                          (fgain - min_gain_shift) * fmeta.penalty, NEG_INF)
    return dict(parent=parent, num_left=num_left, oh_left=oh_left,
                so_left=so_left, so_order=so_order, ni=ni, oi=oi, si=si,
                fam=fam, fgain_out=fgain_out)


def per_feature_gains(hist: jax.Array, parent_g, parent_h, parent_c,
                      fmeta: FeatureMeta, params: SplitParams) -> jax.Array:
    """[F] best gain per feature (NEG_INF where unsplittable) — used by the
    voting-parallel learner's local vote
    (voting_parallel_tree_learner.cpp:170-201)."""
    c = _all_candidates(hist, parent_g, parent_h, parent_c, fmeta, params,
                        -jnp.inf, jnp.inf)
    return c["fgain_out"]


def best_split(hist: jax.Array, parent_g, parent_h, parent_c,
               fmeta: FeatureMeta, params: SplitParams,
               feature_mask: jax.Array, mono_lo=None, mono_hi=None,
               gain_adjust=None) -> SplitInfo:
    """Find the best split of one leaf from its [F, B, 3] histogram.

    Mirrors SerialTreeLearner::FindBestSplitsFromHistograms
    (serial_tree_learner.cpp:549-640): per-feature best threshold, then the
    per-leaf argmax over features with feature-fraction masking and penalty.
    ``gain_adjust`` is an optional [F] additive penalty subtracted from the
    per-feature gains before the argmax (CEGB, :582-618).
    """
    p = params
    F, B, _ = hist.shape
    lo = -jnp.inf if mono_lo is None else mono_lo
    hi = jnp.inf if mono_hi is None else mono_hi

    c = _all_candidates(hist, parent_g, parent_h, parent_c, fmeta, p, lo, hi)
    parent = c["parent"]
    num_left, oh_left = c["num_left"], c["oh_left"]
    so_left, so_order = c["so_left"], c["so_order"]
    ni, oi, si, fam = c["ni"], c["oi"], c["si"], c["fam"]
    fgain_out = jnp.where(feature_mask > 0, c["fgain_out"], NEG_INF)
    if gain_adjust is not None:
        fgain_out = jnp.where(fgain_out > NEG_INF, fgain_out - gain_adjust,
                              NEG_INF)

    best_f = jnp.argmax(fgain_out).astype(jnp.int32)
    best_gain = fgain_out[best_f]
    has_split = best_gain > NEG_INF

    fam_f = fam[best_f]
    T = B - 1
    # decode winner coordinates
    n_t = (ni[best_f] // 2).astype(jnp.int32)
    n_dir = (ni[best_f] % 2).astype(jnp.int32)
    left_num = num_left[best_f, n_t, n_dir]
    if p.has_cat:
        left_oh = oh_left[best_f, oi[best_f]]
        s_k = (si[best_f] // 2).astype(jnp.int32)
        s_dir = (si[best_f] % 2).astype(jnp.int32)
        left_so = so_left[best_f, s_k, s_dir]
        left_stats = jnp.where(fam_f == 0, left_num,
                               jnp.where(fam_f == 1, left_oh, left_so))
        threshold = jnp.where(
            fam_f == 0, n_t,
            jnp.where(fam_f == 1, oi[best_f], s_k)).astype(jnp.int32)
    else:
        left_stats = left_num
        threshold = n_t
    is_cat = fam_f > 0
    # default_left: numerical dir 0 = missing left; 2-bin NaN edge forces right
    dl = (fam_f == 0) & (n_dir == 0)
    nb_f = fmeta.num_bin[best_f]
    mt_f = fmeta.missing_type[best_f]
    dl = jnp.where((fam_f == 0) & (nb_f <= 2) & (mt_f == MISSING_NAN), False, dl)

    if p.has_cat:
        # categorical bitset of left-going bins
        b_idx = jnp.arange(B, dtype=jnp.int32)
        onehot_mask = b_idx == threshold
        order_f = so_order[best_f]
        pos = jnp.arange(B, dtype=jnp.int32)
        cnt_row = hist[best_f, :, 2]
        used_mask_f = _cat_used_bin_mask(hist, fmeta)[best_f]
        valid_bins = used_mask_f & (cnt_row >= p.cat_smooth)
        nvalid = valid_bins.sum().astype(jnp.int32)
        sel_sorted = jnp.where(s_dir == 0, pos <= s_k,
                               (pos >= s_k) & (pos < nvalid))
        sorted_mask = jnp.zeros(B, dtype=bool).at[order_f].set(sel_sorted)
        cat_mask = jnp.where(fam_f == 1, onehot_mask, sorted_mask & valid_bins)
        cat_bitset = build_cat_bitset(jnp.where(is_cat, cat_mask, False))
    else:
        cat_bitset = jnp.zeros(8, dtype=jnp.uint32)

    Gl, Hl, Cl = left_stats[0], left_stats[1], left_stats[2]
    Gr, Hr, Cr = parent[0] - Gl, parent[1] - Hl, parent[2] - Cl
    # cat_l2 applies only to the sorted-subset branch (fam 2); same clip
    # order as candidate scoring: max_delta_step inside, then constraints
    extra_l2 = jnp.where(fam_f == 2, p.cat_l2, 0.0)
    out_l = jnp.clip(leaf_output(Gl, Hl, p.lambda_l1,
                                 p.lambda_l2 + extra_l2, p.max_delta_step),
                     lo, hi)
    out_r = jnp.clip(leaf_output(Gr, Hr, p.lambda_l1,
                                 p.lambda_l2 + extra_l2, p.max_delta_step),
                     lo, hi)

    return SplitInfo(
        gain=jnp.where(has_split, best_gain, NEG_INF),
        feature=jnp.where(has_split, best_f, -1).astype(jnp.int32),
        threshold=threshold,
        default_left=dl,
        is_cat=is_cat,
        cat_bitset=cat_bitset,
        left_g=Gl, left_h=Hl, left_c=Cl,
        right_g=Gr, right_h=Hr, right_c=Cr,
        left_out=out_l, right_out=out_r,
    )
