"""Histogram construction: the hottest op in histogram GBDT.

Replaces the reference's three implementations — the 4-way unrolled CPU loop
(src/io/dense_bin.hpp:69-193), the sparse/ordered bins, and the OpenCL
local-atomic kernels (src/treelearner/ocl/histogram256.cl) — with a single
TPU-idiomatic formulation: per row-chunk, a one-hot expansion of the bin ids
contracted against the (grad, hess, count) weights on the MXU, accumulated
across chunks with ``lax.scan``.  TPUs have no cheap atomic scatter-add, but
bins <= 256 make ``one_hot(bin)^T @ weights`` an MXU-friendly matmul
(SURVEY.md §7 "hard parts").  A Pallas kernel with the one-hot kept in VMEM
slots in behind the same signature (ops/pallas_histogram.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _pick_row_chunk(num_data: int, num_features: int, num_bins: int) -> int:
    """Choose a row-chunk size keeping the transient one-hot under ~64MB."""
    budget = 64 * 1024 * 1024 // 4
    chunk = max(256, budget // max(num_features * num_bins, 1))
    chunk = 1 << (chunk.bit_length() - 1)   # round DOWN to a power of two
    return int(min(chunk, max(256, num_data)))


def histogram_chunked(bins: jax.Array, weights: jax.Array, num_bins: int,
                      row_chunk: int = 0) -> jax.Array:
    """Accumulate per-feature histograms.

    Args:
      bins: ``[N, F]`` integer bin ids (uint8/uint16/int32).
      weights: ``[K, N]`` float32 per-row weight channels — typically
        ``[grad*m, hess*m, m]`` where ``m`` is the row's inclusion weight
        (leaf membership x bagging).
      num_bins: global bin budget B (max over features).
      row_chunk: rows per accumulation step; 0 = auto.

    Returns:
      ``[F, B, K]`` float32 histogram.
    """
    n, f = bins.shape
    k = weights.shape[0]
    if row_chunk <= 0:
        row_chunk = _pick_row_chunk(n, f, num_bins)
    if row_chunk >= n:
        return _hist_one_chunk(bins, weights, num_bins)

    num_full = n // row_chunk
    rem = n - num_full * row_chunk

    def body(acc, args):
        bc, wc = args
        return acc + _hist_one_chunk(bc, wc, num_bins), None

    bins_main = bins[: num_full * row_chunk].reshape(num_full, row_chunk, f)
    w_main = (weights[:, : num_full * row_chunk]
              .reshape(k, num_full, row_chunk).transpose(1, 0, 2))
    init = jnp.zeros((f, num_bins, k), dtype=jnp.float32)
    acc, _ = lax.scan(body, init, (bins_main, w_main))
    if rem:
        acc = acc + _hist_one_chunk(bins[num_full * row_chunk:],
                                    weights[:, num_full * row_chunk:], num_bins)
    return acc


def _hist_one_chunk(bins: jax.Array, weights: jax.Array,
                    num_bins: int) -> jax.Array:
    """[R,F] bins x [K,R] weights -> [F,B,K] via one-hot matmul."""
    onehot = jax.nn.one_hot(bins.astype(jnp.int32), num_bins,
                            dtype=jnp.float32)          # [R, F, B]
    # contract rows on the MXU; HIGHEST keeps f32 gradient mantissas intact
    # (the reference accumulates in f64, gpu_use_dp toggles the same concern
    # for the OpenCL kernels — gpu_tree_learner.cpp:677)
    return jnp.einsum("rfb,kr->fbk", onehot, weights,
                      precision=lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("num_bins", "row_chunk"))
def leaf_histogram(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                   member: jax.Array, num_bins: int,
                   row_chunk: int = 0) -> jax.Array:
    """Histogram of (sum_grad, sum_hess, count) for one leaf.

    ``member`` is a float mask/weight per row (0 outside the leaf; bagging
    weights fold in here).  Equivalent to the reference's ordered-gradient
    gather + per-group ConstructHistogram (src/io/dataset.cpp:778-946) but as
    one dense masked pass.
    """
    weights = jnp.stack([grad * member, hess * member, member])
    return histogram_chunked(bins, weights, num_bins, row_chunk)
