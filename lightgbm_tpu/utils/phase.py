"""Per-phase wall-clock accounting for the training loop.

The reference ships three tracing mechanisms — easy_profiler blocks
(src/main.cpp:13-39), TIMETAG per-phase accumulators printed at learner
destruction (src/treelearner/serial_tree_learner.cpp:20-47), and network
byte/time counters (src/network/linkers.h:114-117).  This module is the
TPU build's equivalent of the TIMETAG accumulators: named phases
accumulate wall-clock across iterations and are printed on demand
(bench.py prints them every run; ``Log`` prints at verbosity>=debug).

Because device work is dispatched asynchronously, a phase's wall time
normally measures only host-side dispatch.  Set
``LIGHTGBM_TPU_SYNC_TIMERS=1`` to block on device results at each phase
boundary — slower, but attributes device time to the phase that spent it
(the jax-profiler trace, ``LIGHTGBM_TPU_PROFILE_DIR``, is the zero-skew
alternative).
"""

from __future__ import annotations

import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional


def _sync_enabled() -> bool:
    return os.environ.get("LIGHTGBM_TPU_SYNC_TIMERS", "") not in ("", "0")


class PhaseTimer:
    """Accumulates (count, seconds) per named phase."""

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextmanager
    def phase(self, name: str, sync_obj=None):
        sync = _sync_enabled()
        if sync and sync_obj is not None:
            import jax
            jax.block_until_ready(sync_obj)
        t0 = time.perf_counter()
        box = [None]
        try:
            yield box
        finally:
            if sync and box[0] is not None:
                import jax
                jax.block_until_ready(box[0])
            self.seconds[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def reset(self) -> None:
        self.seconds.clear()
        self.counts.clear()

    def summary(self) -> str:
        total = sum(self.seconds.values())
        parts = []
        for name, sec in sorted(self.seconds.items(), key=lambda kv: -kv[1]):
            n = self.counts[name]
            parts.append(f"{name}={sec:.3f}s/{n}")
        mode = "sync" if _sync_enabled() else "dispatch"
        return f"phases[{mode}] total={total:.3f}s " + " ".join(parts)


# process-global timer used by GBDT unless one is injected
GLOBAL_TIMER = PhaseTimer()

_profile_session: Optional[object] = None


def maybe_start_profile() -> None:
    """Start a jax-profiler trace if LIGHTGBM_TPU_PROFILE_DIR is set."""
    global _profile_session
    path = os.environ.get("LIGHTGBM_TPU_PROFILE_DIR")
    if path and _profile_session is None:
        import jax
        jax.profiler.start_trace(path)
        _profile_session = path


def maybe_stop_profile() -> None:
    global _profile_session
    if _profile_session is not None:
        import jax
        jax.profiler.stop_trace()
        _profile_session = None
