"""Per-phase wall-clock accounting for the training loop.

The reference ships three tracing mechanisms — easy_profiler blocks
(src/main.cpp:13-39), TIMETAG per-phase accumulators printed at learner
destruction (src/treelearner/serial_tree_learner.cpp:20-47), and network
byte/time counters (src/network/linkers.h:114-117).  This module is the
TPU build's equivalent of the TIMETAG accumulators: named phases
accumulate wall-clock across iterations and are printed on demand
(bench.py prints them every run; ``Log`` prints at verbosity>=debug).
Each finished phase is also recorded as a span in the telemetry
registry (utils/telemetry.py), which adds counters, a per-iteration
timeline and Chrome trace export on top.

Because device work is dispatched asynchronously, a phase's wall time
normally measures only host-side dispatch.  Set
``LIGHTGBM_TPU_SYNC_TIMERS=1`` to block on device results at each phase
boundary — slower, but attributes device time to the phase that spent it
(the jax-profiler trace, ``LIGHTGBM_TPU_PROFILE_DIR``, is the zero-skew
alternative).

Profiler capture comes in two shapes: the original all-or-nothing
session (``LIGHTGBM_TPU_PROFILE_DIR`` wraps the whole train loop) and
the windowed programmatic capture (``profile_window=START:END`` config
parameter / ``LIGHTGBM_TPU_PROFILE_WINDOW`` env), which opens the
``jax.profiler`` trace only for that boosting-iteration span — a
multi-hour run yields a viewable-sized artifact of exactly the steady
state (or exactly the suspect iterations).  While either capture is
open, phases are wrapped in ``jax.profiler.TraceAnnotation`` and chunk
dispatches in ``StepTraceAnnotation`` (models/gbdt.py), so the device
trace aligns with the host-side Chrome trace.  The artifact path and
actual window land in the metrics blob's ``timing`` section.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager, nullcontext
from typing import Dict, Optional, Tuple


def _sync_enabled() -> bool:
    return os.environ.get("LIGHTGBM_TPU_SYNC_TIMERS", "") not in ("", "0")


class PhaseTimer:
    """Accumulates (count, seconds) per named phase.  Thread-safe: the
    accumulators are guarded by a lock (phases themselves may overlap
    freely across threads; each contributes its own wall window)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.seconds: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextmanager
    def phase(self, name: str, sync_obj=None):
        sync = _sync_enabled()
        if sync and sync_obj is not None:
            import jax
            jax.block_until_ready(sync_obj)
        ann = None
        if profiler_active():
            # align host phase structure with the device profiler trace
            import jax
            ann = jax.profiler.TraceAnnotation(f"lgbm:{name}")
            ann.__enter__()
        t0 = time.perf_counter()
        box = [None]
        try:
            yield box
        finally:
            if sync and box[0] is not None:
                import jax
                jax.block_until_ready(box[0])
            if ann is not None:
                ann.__exit__(None, None, None)
            dur = time.perf_counter() - t0
            with self._lock:
                self.seconds[name] += dur
                self.counts[name] += 1
            from .telemetry import TELEMETRY
            TELEMETRY.record_span(name, t0, dur)
            TELEMETRY.sample_memory(name)

    def reset(self) -> None:
        with self._lock:
            self.seconds.clear()
            self.counts.clear()

    def snapshot(self) -> Dict[str, Tuple[float, int]]:
        """Consistent {name: (seconds, count)} copy."""
        with self._lock:
            return {name: (sec, self.counts[name])
                    for name, sec in self.seconds.items()}

    def summary(self) -> str:
        snap = self.snapshot()
        total = sum(sec for sec, _ in snap.values())
        parts = []
        for name, (sec, n) in sorted(snap.items(), key=lambda kv: -kv[1][0]):
            parts.append(f"{name}={sec:.3f}s/{n}")
        mode = "sync" if _sync_enabled() else "dispatch"
        out = f"phases[{mode}] total={total:.3f}s " + " ".join(parts)
        # append the network collective counters (linkers.h:114-117
        # equivalent) when the parallel machinery has been used
        import sys
        net = sys.modules.get("lightgbm_tpu.parallel.network")
        if net is not None and hasattr(net, "collective_summary"):
            net_line = net.collective_summary()
            if net_line:
                out += " | " + net_line
        # and the fleet plane's cross-rank wait/work split when it
        # attributed at least one window this run
        fleet = sys.modules.get("lightgbm_tpu.obs.fleet")
        if fleet is not None and hasattr(fleet, "summary_line"):
            fleet_line = fleet.summary_line()
            if fleet_line:
                out += " | " + fleet_line
        return out


# process-global timer used by GBDT unless one is injected
GLOBAL_TIMER = PhaseTimer()

_profile_session: Optional[object] = None

WINDOW_ENV = "LIGHTGBM_TPU_PROFILE_WINDOW"
DEFAULT_PROFILE_DIR = "lightgbm_tpu.profile"


class ProfileWindow:
    """Windowed programmatic jax-profiler capture.

    ``profile_window=START:END`` (env ``LIGHTGBM_TPU_PROFILE_WINDOW``
    wins) arms ONE capture per training run over the half-open boosting-
    iteration span ``[START, END)``.  The train loops call
    ``clamp_step`` (so a chunk dispatch never straddles a window
    boundary — chunk size never changes the model, PR 1 parity, so the
    clamp only affects dispatch granularity) and then ``step(i)`` before
    dispatching iteration ``i``; the window opens/closes itself at the
    boundaries.  ``close()`` in the profile_session finally guarantees
    an exception mid-window cannot leak an open jax profiler session
    (which would poison every later ``start_trace`` in the process).
    The artifact dir comes from ``LIGHTGBM_TPU_PROFILE_DIR`` when set,
    else ``lightgbm_tpu.profile``; the dir + actual captured span are
    recorded into the metrics blob's ``timing`` section.
    """

    def __init__(self) -> None:
        self.start = 0
        self.end = 0
        self.dir = ""
        self.is_open = False
        self._armed = False
        self._done = False
        self._opened_at = 0
        self._last_iter = 0

    def configure(self, config=None) -> bool:
        """(Re-)arm from the env/config spec; returns True when a
        window is armed.  A malformed spec warns and disables the
        window rather than failing the run."""
        self._armed = False
        self._done = False
        self.is_open = False
        spec = os.environ.get(WINDOW_ENV, "")
        if not spec and config is not None:
            spec = str(getattr(config, "profile_window", "") or "")
        if not spec:
            return False
        try:
            a, _, b = spec.partition(":")
            start, end = int(a), int(b)
        except ValueError:
            start, end = 0, 0
        if end <= start or start < 0:
            from .log import log_warning
            log_warning(f"bad profile_window spec {spec!r} (want "
                        "START:END with END > START >= 0); profiler "
                        "window disabled")
            return False
        self.start, self.end = start, end
        self.dir = (os.environ.get("LIGHTGBM_TPU_PROFILE_DIR")
                    or DEFAULT_PROFILE_DIR)
        self._armed = True
        return True

    def clamp_step(self, iteration: int, step: int) -> int:
        """Clamp a chunk step so the next dispatch stops at the nearest
        upcoming window boundary."""
        if not self._armed or self._done:
            return step
        for boundary in (self.start, self.end):
            if iteration < boundary:
                return min(step, boundary - iteration)
        return step

    def step(self, iteration: int) -> None:
        """Advance to ``iteration`` (about to be dispatched): opens the
        trace entering the window, closes it leaving."""
        if not self._armed or self._done:
            return
        self._last_iter = iteration
        if self.is_open:
            if iteration >= self.end:
                self._close(iteration)
        elif self.start <= iteration < self.end:
            import jax
            jax.profiler.start_trace(self.dir)
            self.is_open = True
            self._opened_at = iteration

    def _close(self, iteration: int) -> None:
        # clear the open marker FIRST: if stop_trace raises, the finally
        # close() must not call it again on an already-broken session
        self.is_open = False
        self._done = True
        import jax
        jax.profiler.stop_trace()
        from .telemetry import TELEMETRY
        TELEMETRY.record_profile_capture({
            "dir": self.dir, "kind": "window",
            "window": [int(self._opened_at), int(iteration)],
            "requested": [int(self.start), int(self.end)]})

    def close(self) -> None:
        """Force-close an open window and disarm (profile_session
        finally): the capture then covers up to the last stepped
        iteration."""
        if self.is_open:
            self._close(min(self.end, self._last_iter + 1))
        self._armed = False


PROFILE_WINDOW = ProfileWindow()


def profiler_active() -> bool:
    """True while ANY jax-profiler capture (whole-run session or
    window) is open — gates the Trace/StepTraceAnnotation wrappers so
    the un-profiled path stays annotation-free."""
    return _profile_session is not None or PROFILE_WINDOW.is_open


def step_annotation(name: str, step: int):
    """``jax.profiler.StepTraceAnnotation`` while a capture is open
    (the profiler's per-step grouping for chunk dispatches), else a
    zero-overhead null context."""
    if not profiler_active():
        return nullcontext()
    import jax
    return jax.profiler.StepTraceAnnotation(name, step_num=int(step))


def maybe_start_profile() -> None:
    """Start a jax-profiler trace if LIGHTGBM_TPU_PROFILE_DIR is set."""
    global _profile_session
    path = os.environ.get("LIGHTGBM_TPU_PROFILE_DIR")
    if path and _profile_session is None:
        import jax
        jax.profiler.start_trace(path)
        _profile_session = path


def maybe_stop_profile() -> None:
    global _profile_session
    if _profile_session is not None:
        # clear the session marker FIRST: if stop_trace raises, a retry
        # must not call it again on an already-broken session
        path, _profile_session = _profile_session, None
        import jax
        jax.profiler.stop_trace()
        from .telemetry import TELEMETRY
        TELEMETRY.record_profile_capture({"dir": path, "kind": "session"})


@contextmanager
def profile_session(config=None):
    """Exception-safe profiler window: an error mid-training must not
    leak an open jax profiler trace session (which would poison every
    later start_trace in the process).  A configured
    ``profile_window=START:END`` span takes over from the all-or-nothing
    LIGHTGBM_TPU_PROFILE_DIR session — the window owns the capture and
    the train loop drives it via PROFILE_WINDOW.step()."""
    windowed = PROFILE_WINDOW.configure(config)
    if not windowed:
        maybe_start_profile()
    try:
        yield
    finally:
        if windowed:
            PROFILE_WINDOW.close()
        else:
            maybe_stop_profile()
