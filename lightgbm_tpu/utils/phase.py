"""Per-phase wall-clock accounting for the training loop.

The reference ships three tracing mechanisms — easy_profiler blocks
(src/main.cpp:13-39), TIMETAG per-phase accumulators printed at learner
destruction (src/treelearner/serial_tree_learner.cpp:20-47), and network
byte/time counters (src/network/linkers.h:114-117).  This module is the
TPU build's equivalent of the TIMETAG accumulators: named phases
accumulate wall-clock across iterations and are printed on demand
(bench.py prints them every run; ``Log`` prints at verbosity>=debug).
Each finished phase is also recorded as a span in the telemetry
registry (utils/telemetry.py), which adds counters, a per-iteration
timeline and Chrome trace export on top.

Because device work is dispatched asynchronously, a phase's wall time
normally measures only host-side dispatch.  Set
``LIGHTGBM_TPU_SYNC_TIMERS=1`` to block on device results at each phase
boundary — slower, but attributes device time to the phase that spent it
(the jax-profiler trace, ``LIGHTGBM_TPU_PROFILE_DIR``, is the zero-skew
alternative).
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional, Tuple


def _sync_enabled() -> bool:
    return os.environ.get("LIGHTGBM_TPU_SYNC_TIMERS", "") not in ("", "0")


class PhaseTimer:
    """Accumulates (count, seconds) per named phase.  Thread-safe: the
    accumulators are guarded by a lock (phases themselves may overlap
    freely across threads; each contributes its own wall window)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.seconds: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextmanager
    def phase(self, name: str, sync_obj=None):
        sync = _sync_enabled()
        if sync and sync_obj is not None:
            import jax
            jax.block_until_ready(sync_obj)
        t0 = time.perf_counter()
        box = [None]
        try:
            yield box
        finally:
            if sync and box[0] is not None:
                import jax
                jax.block_until_ready(box[0])
            dur = time.perf_counter() - t0
            with self._lock:
                self.seconds[name] += dur
                self.counts[name] += 1
            from .telemetry import TELEMETRY
            TELEMETRY.record_span(name, t0, dur)
            TELEMETRY.sample_memory(name)

    def reset(self) -> None:
        with self._lock:
            self.seconds.clear()
            self.counts.clear()

    def snapshot(self) -> Dict[str, Tuple[float, int]]:
        """Consistent {name: (seconds, count)} copy."""
        with self._lock:
            return {name: (sec, self.counts[name])
                    for name, sec in self.seconds.items()}

    def summary(self) -> str:
        snap = self.snapshot()
        total = sum(sec for sec, _ in snap.values())
        parts = []
        for name, (sec, n) in sorted(snap.items(), key=lambda kv: -kv[1][0]):
            parts.append(f"{name}={sec:.3f}s/{n}")
        mode = "sync" if _sync_enabled() else "dispatch"
        out = f"phases[{mode}] total={total:.3f}s " + " ".join(parts)
        # append the network collective counters (linkers.h:114-117
        # equivalent) when the parallel machinery has been used
        import sys
        net = sys.modules.get("lightgbm_tpu.parallel.network")
        if net is not None and hasattr(net, "collective_summary"):
            net_line = net.collective_summary()
            if net_line:
                out += " | " + net_line
        return out


# process-global timer used by GBDT unless one is injected
GLOBAL_TIMER = PhaseTimer()

_profile_session: Optional[object] = None


def maybe_start_profile() -> None:
    """Start a jax-profiler trace if LIGHTGBM_TPU_PROFILE_DIR is set."""
    global _profile_session
    path = os.environ.get("LIGHTGBM_TPU_PROFILE_DIR")
    if path and _profile_session is None:
        import jax
        jax.profiler.start_trace(path)
        _profile_session = path


def maybe_stop_profile() -> None:
    global _profile_session
    if _profile_session is not None:
        # clear the session marker FIRST: if stop_trace raises, a retry
        # must not call it again on an already-broken session
        _profile_session = None
        import jax
        jax.profiler.stop_trace()


@contextmanager
def profile_session():
    """Exception-safe profiler window: an error mid-training must not
    leak an open jax profiler trace session (which would poison every
    later start_trace in the process)."""
    maybe_start_profile()
    try:
        yield
    finally:
        maybe_stop_profile()
