"""DCG/NDCG computation shared by the lambdarank objective and rank metrics.

Reference: src/metric/dcg_calculator.cpp (DCGCalculator: label gains
2^l - 1, position discounts 1/log2(2+i), DCG@k, max DCG@k).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

DEFAULT_LABEL_GAIN_SIZE = 31


def default_label_gain(size: int = DEFAULT_LABEL_GAIN_SIZE) -> np.ndarray:
    return (2.0 ** np.arange(size)) - 1.0


class DCGCalculator:
    def __init__(self, label_gain: Optional[Sequence[float]] = None):
        if label_gain is None or len(label_gain) == 0:
            self.label_gain = default_label_gain()
        else:
            self.label_gain = np.asarray(label_gain, dtype=np.float64)

    def check_labels(self, labels: np.ndarray) -> None:
        lab = labels.astype(np.int64)
        if lab.min() < 0 or lab.max() >= len(self.label_gain):
            raise ValueError(
                f"Rank labels must be in [0, {len(self.label_gain)}); "
                "set label_gain to extend")

    def discount(self, positions: np.ndarray) -> np.ndarray:
        return 1.0 / np.log2(2.0 + positions)

    def cal_dcg_at_k(self, k: int, labels: np.ndarray,
                     scores: np.ndarray) -> float:
        """DCG@k of documents ranked by score descending (stable)."""
        order = np.argsort(-scores, kind="stable")
        top = labels[order[:k]].astype(np.int64)
        pos = np.arange(len(top))
        return float(np.sum(self.label_gain[top] * self.discount(pos)))

    def cal_maxdcg_at_k(self, k: int, labels: np.ndarray) -> float:
        top = np.sort(labels.astype(np.int64))[::-1][:k]
        pos = np.arange(len(top))
        return float(np.sum(self.label_gain[top] * self.discount(pos)))
