"""Shared retry policy: attempts / exponential backoff / jitter /
per-attempt timeout.

The reference hand-rolls its retry loops per call site (socket connect
retries in linkers_socket.cpp:116-143, allreduce re-sends); here the
policy lives in one place so the collective layer
(``parallel/network.py``), the distributed init handshake
(``parallel/distributed.py``) and snapshot IO (``utils/snapshots.py``)
share identical, *testable* semantics:

  * ``attempts`` total tries (1 = no retry).
  * exponential backoff between tries (``backoff_s * mult**k``) with a
    DETERMINISTIC jitter — hashed from the label and attempt index, not
    drawn from a global RNG, so armed fault specs replay identically
    and a retrying run's model stays byte-identical.
  * optional per-attempt wall timeout.  Python cannot cancel a stuck
    call, so the timed-out worker thread is abandoned (daemonized) —
    acceptable for the collective paths this guards, where a
    genuinely wedged DCN call means the process is about to die
    anyway, and the alternative (hanging forever on a dead host) is
    the exact failure mode this layer exists to remove.

Failures the caller knows to be non-transient (config/topology errors)
are excluded via ``fatal`` and propagate immediately.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable, Optional, Tuple, Type

from .log import log_warning


class RetryTimeout(RuntimeError):
    """One attempt exceeded its per-attempt wall timeout."""

    def __init__(self, label: str, timeout_s: float):
        self.label = label
        self.timeout_s = timeout_s
        super().__init__(
            f"{label} timed out after {timeout_s:g}s (per-attempt limit)")


def _deterministic_jitter(label: str, attempt: int, frac: float,
                          delay: float) -> float:
    """Jitter in [0, frac * delay), derived from (label, attempt) so two
    runs of the same spec sleep identically."""
    if frac <= 0 or delay <= 0:
        return 0.0
    h = hashlib.sha256(f"{label}#{attempt}".encode()).digest()
    unit = int.from_bytes(h[:8], "big") / float(1 << 64)
    return unit * frac * delay


def call_with_timeout(fn: Callable, timeout_s: Optional[float],
                      label: str = "call"):
    """Run ``fn()`` with a wall timeout.  ``None``/``<= 0`` runs inline
    (no thread).  On timeout raises :class:`RetryTimeout`; the stuck
    worker thread is abandoned (see module docstring)."""
    if not timeout_s or timeout_s <= 0:
        return fn()
    box: list = [None, None]          # [result, exception]
    done = threading.Event()

    def run():
        try:
            box[0] = fn()
        except BaseException as e:    # noqa: BLE001 — re-raised below
            box[1] = e
        finally:
            done.set()

    t = threading.Thread(target=run, name=f"retry-{label}", daemon=True)
    t.start()
    if not done.wait(timeout_s):
        raise RetryTimeout(label, timeout_s)
    if box[1] is not None:
        raise box[1]
    return box[0]


def retry_call(fn: Callable, *,
               attempts: int = 2,
               backoff_s: float = 0.05,
               backoff_mult: float = 2.0,
               jitter_frac: float = 0.25,
               timeout_s: Optional[float] = None,
               fatal: Tuple[Type[BaseException], ...] = (),
               on_retry: Optional[Callable] = None,
               label: str = "call",
               sleep: Callable[[float], None] = time.sleep):
    """Call ``fn()`` up to ``attempts`` times.

    ``fatal`` exception types propagate immediately (config/topology
    errors are not transient).  Between tries the loop sleeps
    ``backoff_s * backoff_mult**k`` plus deterministic jitter, and
    ``on_retry(attempt_index, exception)`` is invoked once per retry —
    the hook where call sites record their ``collective_retry`` /
    ``snapshot_retry`` fault events.  Each attempt is bounded by
    ``timeout_s`` when given (see :func:`call_with_timeout`).  The last
    failure propagates unchanged.
    """
    attempts = max(1, int(attempts))
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return call_with_timeout(fn, timeout_s, label=label)
        except fatal:
            raise
        except BaseException as e:    # noqa: BLE001 — policy layer
            last = e
            if attempt + 1 >= attempts:
                raise
            delay = backoff_s * (backoff_mult ** attempt)
            delay += _deterministic_jitter(label, attempt, jitter_frac,
                                           delay)
            log_warning(
                f"{label} failed ({type(e).__name__}: {e}); retrying in "
                f"{delay:.3f}s (attempt {attempt + 2}/{attempts})")
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                sleep(delay)
    raise last  # pragma: no cover — loop always returns or raises
