"""Logging with LightGBM-style levels gated by verbosity.

Reference: include/LightGBM/utils/log.h:30-120 (`Log` static class with
Fatal/Warning/Info/Debug and a redirectable callback).
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Optional

_FATAL, _WARNING, _INFO, _DEBUG = -1, 0, 1, 2

_verbosity = 1
_callback: Optional[Callable[[str], None]] = None


class LightGBMError(Exception):
    """Raised on fatal errors (reference Log::Fatal throws std::runtime_error)."""


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = level


def get_verbosity() -> int:
    return _verbosity


def register_log_callback(cb: Optional[Callable[[str], None]]) -> None:
    global _callback
    _callback = cb


def _write(level_str: str, msg: str) -> None:
    line = f"[LightGBM-TPU] [{level_str}] {msg}\n"
    if _callback is not None:
        _callback(line)
    else:
        sys.stdout.write(line)
        sys.stdout.flush()


def log_debug(msg: str) -> None:
    if _verbosity >= _DEBUG:
        _write("Debug", msg)


def log_info(msg: str) -> None:
    if _verbosity >= _INFO:
        _write("Info", msg)


def log_warning(msg: str) -> None:
    if _verbosity >= _WARNING:
        _write("Warning", msg)


def log_fatal(msg: str) -> None:
    raise LightGBMError(msg)


def check(cond: bool, msg: str = "check failed") -> None:
    if not cond:
        log_fatal(msg)


class Timer:
    """Scoped wall-clock timer (reference: Common::Timer, utils/common.h:32-60)."""

    def __init__(self, name: str = "", print_on_exit: bool = False):
        self.name = name
        self.print_on_exit = print_on_exit
        self.start = 0.0
        self.elapsed = 0.0

    def __enter__(self) -> "Timer":
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.perf_counter() - self.start
        if self.print_on_exit:
            log_info(f"{self.name}: {self.elapsed:.3f}s")
