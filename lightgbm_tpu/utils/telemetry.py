"""Process-global training telemetry: spans, counters, gauges, a
per-iteration timeline, and Chrome trace-event export.

The reference fork's defining additions over stock LightGBM are
observability: easy_profiler trace blocks (src/main.cpp:13-39), TIMETAG
per-phase accumulators (serial_tree_learner.cpp:20-47) and network
byte/time counters (linkers.h:114-117).  This module is the TPU build's
superset of all three, layered on top of the existing ``PhaseTimer``
(utils/phase.py), which keeps its role as the per-phase accumulator and
additionally feeds every finished phase into the span ring buffer here.

Three telemetry levels gate the overhead:

  * ``0`` — off.  Every record call is a single attribute compare.
  * ``1`` — default.  Counters, gauges and the per-iteration timeline
    accumulate; phase seconds keep accruing in ``PhaseTimer``.
  * ``2`` — adds timestamped spans in a bounded ring buffer, exportable
    as Chrome trace-event JSON (load in Perfetto / chrome://tracing).

The effective level resolves lazily (env vars are read at refresh time,
not import time, so the test harness's env scrubbing and monkeypatching
behave): ``LIGHTGBM_TPU_TELEMETRY`` wins if set, else the
``telemetry_level`` config parameter, else 1; a set
``LIGHTGBM_TPU_TRACE_JSON=<path>`` forces the effective level to >= 2
and exports the trace there at the end of training (plus an atexit
backstop).

Timing caveat: device work is dispatched asynchronously, so spans and
phase seconds measure host-side dispatch unless
``LIGHTGBM_TPU_SYNC_TIMERS=1`` (see utils/phase.py).  The ``mode`` field
of ``stats()`` records which one a blob was collected under.

Compile visibility comes from ``jax.monitoring`` listeners
(install_jax_listeners): retrace counts/seconds, backend compile
counts/seconds and compilation-cache hits/misses — cold-vs-warm cache
behavior is measurable instead of inferred from wall-clock cliffs.

The v2 schema adds two DEVICE-side sections on top of the host view:

  * ``memory`` — HBM gauges from ``device.memory_stats()`` (bytes in
    use, peak, largest allocation, the device byte limit), sampled at
    phase boundaries (utils/phase.py) and optionally by a low-rate
    background thread (``LIGHTGBM_TPU_MEM_SAMPLE_MS``, off by default)
    whose samples feed a ``mem/*`` counter track in the Chrome trace.
    Backends whose ``memory_stats()`` returns ``None`` (CPU) cleanly
    omit the section.  Reading allocator stats never syncs the device.
  * ``cost`` — static XLA ``Compiled.cost_analysis()`` (flops, bytes
    accessed, transcendentals) harvested once per compiled executable
    at the jit seams (utils/jitcost.py), keyed by function label and
    multiplied out by call counts, so ``stats()`` can report
    estimated FLOPs/s and bytes/s for the measured window.

The v4 schema adds MEASURED device time: an opt-in ``timing`` section
(``device_timing=`` config parameter / ``LIGHTGBM_TPU_DEVICE_TIMING``
env) fed by utils/jitcost.py, which times every instrumented jit
dispatch wall-to-ready (sync on the returned buffers) and accumulates
per-label count/total/mean/p50/p99 plus the dispatch GAP (host overhead
between consecutive dispatches of the same label).  Dividing the v2
``cost`` section's static FLOPs/bytes by the measured seconds yields
real utilization next to the estimated one.  The section also records
the jax-profiler capture artifact (path + iteration window) when a
``profile_window=START:END`` capture ran (utils/phase.py).

The v3 schema adds the STREAMING run-health layer: every blob carries
top-level ``schema`` and ``telemetry_level`` keys (so tools can branch
without sniffing sections), and — when a run writes a health stream —
a ``health`` digest section.  The stream itself (``HealthStream``, one
process-global ``HEALTH``) is an append-only JSONL file
(``health_out=`` config parameter / ``LIGHTGBM_TPU_HEALTH_JSONL`` env)
written at eval/chunk cadence while training runs, so a 5-hour job is
legible while it is alive, not only after its ``finally`` flush.  Each
record is a single ``os.write`` to an ``O_APPEND`` descriptor, so
records never tear even when a signal kills the process mid-run;
``resume=true`` compacts records past the snapshot iteration and keeps
appending, yielding ONE contiguous stream across kill+resume.  Consume
it live with ``tools/run_monitor.py``.

The v5 schema adds the SERVE observability plane: every request through
the micro-batching queue (serve/queue.py) records its lifecycle stage
walls (``serve/t_queue`` → ``serve/t_coalesce`` → ``serve/t_dispatch``
→ ``serve/t_reply``) through :meth:`record_dispatch`, feeds one
completed-request sample into a bounded sliding window here
(:meth:`serve_request_done`), and ``stats()`` gains a ``serve`` section
with the last-10s QPS and end-to-end p50/p99
(:meth:`serve_window_stats`).  The serve plane additionally streams its
own health JSONL (``serve/health.py``, the same O_APPEND never-torn
writer as training, ``serve_start``/``serve_window``/``serve_admit``/
``serve_fault``/``serve_summary`` record kinds) — deliberately a
SEPARATE ``HealthStream`` instance, so serving a model can never touch
a training run's stream or its models.

The v6 schema adds the FLEET observability plane (lightgbm_tpu/obs/):
every health record carries a paired ``{wall_ts, mono_ts}`` clock stamp
(:func:`clock_pair`), traces embed ``mono_epoch``/``wall_epoch``/rank
anchors so ``tools/fleet_trace.py`` can merge per-rank traces onto one
skew-corrected timeline, and ``obs/fleet.py`` kv-allgathers per-rank
per-collective enter/duration tables to split collective wall into
*wait* (skew-corrected idle before the slowest rank arrives) vs *work*
(transfer/reduce) seconds — the ``dist/wait_s``/``dist/work_s`` counter
pair, a named straggler rank per window (``dist_window`` records), and
the ``fleet`` stats section.  All of it is host-side timing and IO:
trained models stay byte-identical with the plane on or off.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Any, Dict, Optional

METRICS_SCHEMA = "lightgbm_tpu.metrics/v7"
METRICS_VERSION = 7
HEALTH_SCHEMA = "lightgbm_tpu.health/v1"
HEALTH_ENV = "LIGHTGBM_TPU_HEALTH_JSONL"
TIMING_ENV = "LIGHTGBM_TPU_DEVICE_TIMING"
SPAN_CAPACITY = 65536
TIMELINE_CAPACITY = 8192
MEM_TRACK_CAPACITY = 16384
FAULT_CAPACITY = 512
# bounded per-label reservoir backing the p50/p99 dispatch quantiles
TIMING_SAMPLE_CAPACITY = 4096
# serve sliding window: width of the stats() serve section and the
# capacity of the (t_done, latency) completed-request ring behind it
SERVE_WINDOW_S = 10.0
SERVE_SAMPLE_CAPACITY = 65536

# jax.monitoring event name -> (count counter, seconds counter)
_JAX_DURATION_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration":
        ("compile/retraces", "compile/retrace_seconds"),
    "/jax/core/compile/backend_compile_duration":
        ("compile/backend_compiles", "compile/backend_compile_seconds"),
}
# jax.monitoring count-only event -> counter
_JAX_COUNT_EVENTS = {
    "/jax/compilation_cache/cache_hits": "compile/cache_hits",
    "/jax/compilation_cache/cache_misses": "compile/cache_misses",
}


def clock_pair() -> Dict[str, float]:
    """The v6 record timestamp pair: ``wall_ts`` (``time.time()``, for
    humans and cross-restart ordering) and ``mono_ts``
    (``time.monotonic()``, for merge ordering — NTP steps and clock
    slew never reorder it).  Cross-rank, ``mono_ts`` values live on
    per-host clocks with arbitrary epochs; ``obs/clockskew.py``
    estimates the per-rank offsets that map them onto one timeline."""
    return {"wall_ts": round(time.time(), 6),
            "mono_ts": round(time.monotonic(), 6)}


class HealthStream:
    """Append-only JSONL run-health stream (schema ``HEALTH_SCHEMA``).

    Record kinds:

      * ``start`` / ``resume`` — stream (re)opened; ``resume`` carries
        the snapshot iteration the run continues from.
      * ``iter`` — one boosting iteration: dispatched chunk size,
        per-tree shape stats (leaves, depth, split-gain sum/max),
        per-class gradient/hessian stats (min/max/l2/nonfinite — folded
        into the chunk scan, zero extra dispatches), and the HBM gauge
        when the backend reports allocator stats.
      * ``eval`` — train/valid metric values at the eval cadence.
      * ``snapshot`` — a resumable snapshot was written.
      * ``fault`` — mirror of every ``TELEMETRY.fault_event``.
      * ``summary`` — stream closed (``aborted`` marks a crash/signal).

    Every record is one ``os.write`` to an ``O_APPEND`` descriptor —
    atomic on POSIX regular files at these sizes, so a SIGKILL between
    records never leaves a torn line.  On resume the existing file is
    compacted first (iteration-scoped records at/past the snapshot
    iteration are dropped via tmp + ``os.replace``), so a killed run
    whose pipeline had materialized past the snapshot re-emits those
    iterations exactly once and the stream stays contiguous.
    """

    # record kinds scoped to an iteration index: these are dropped at/
    # past the snapshot iteration when a resumed run compacts the file
    _ITER_SCOPED = ("iter", "eval", "snapshot")

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._path = ""
        self._fd: Optional[int] = None
        self._t0 = time.perf_counter()
        self._records = 0
        self._by_kind: Dict[str, int] = defaultdict(int)
        self._last_iter: Optional[Dict[str, Any]] = None
        self._nonfinite_total = 0

    # ------------------------------------------------------------- config
    @staticmethod
    def resolve_path(config=None) -> str:
        """Stream destination: the env var wins over the ``health_out``
        config parameter; "" = no stream."""
        env = os.environ.get(HEALTH_ENV, "")
        if env:
            return env
        if config is not None:
            return str(getattr(config, "health_out", "") or "")
        return ""

    @property
    def active(self) -> bool:
        return self._fd is not None

    # ---------------------------------------------------------- lifecycle
    def open(self, path: str, resume_iter: Optional[int] = None,
             meta: Optional[Dict[str, Any]] = None,
             start_kind: Optional[str] = None) -> None:
        """Open (or, with ``resume_iter``, compact-and-continue) the
        stream and write the ``start``/``resume`` record.  An IO failure
        is survivable: logged, and the stream stays inactive.
        ``start_kind`` renames the opening record (the serve plane's
        private stream opens with ``serve_start``)."""
        from .log import log_warning
        with self._lock:
            if self._fd is not None:
                self.close(summary=False)
            self._path = ""
            self._records = 0
            self._by_kind = defaultdict(int)
            self._last_iter = None
            self._nonfinite_total = 0
            self._t0 = time.perf_counter()
            try:
                resuming = (resume_iter is not None
                            and os.path.exists(path))
                if resuming:
                    self._compact_for_resume(path, int(resume_iter))
                flags = os.O_WRONLY | os.O_CREAT | os.O_APPEND
                if not resuming:
                    flags |= os.O_TRUNC
                self._fd = os.open(path, flags, 0o644)
            except OSError as e:
                self._fd = None
                log_warning(f"could not open health stream {path}: {e}")
                return
            self._path = path
            rec: Dict[str, Any] = {
                "kind": ("resume" if resuming
                         else (start_kind or "start")),
                "schema": HEALTH_SCHEMA,
                "ts": round(time.time(), 3),
                "pid": os.getpid(),
            }
            rec.update(clock_pair())
            if resuming:
                rec["iter"] = int(resume_iter)
            if meta:
                rec.update(meta)
            self._ingest(rec)
            self._write(rec)

    def _compact_for_resume(self, path: str, resume_iter: int) -> None:
        """Drop iteration-scoped records at/past the snapshot iteration
        (the resumed run re-emits them) and any stale ``summary``;
        re-ingest the survivors so the digest covers the whole run."""
        kept = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue                    # torn/corrupt line
                kind = rec.get("kind")
                if kind == "summary":
                    continue
                if (kind in self._ITER_SCOPED
                        and int(rec.get("iter", -1)) >= resume_iter):
                    continue
                kept.append((line, rec))
        d = os.path.dirname(os.path.abspath(path))
        tmp = os.path.join(
            d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
        with open(tmp, "w") as fh:
            for line, _ in kept:
                fh.write(line + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        for _, rec in kept:
            self._ingest(rec)

    def close(self, summary: bool = True, aborted: bool = False,
              extra: Optional[Dict[str, Any]] = None) -> None:
        """Write the ``summary`` record (unless suppressed) and release
        the descriptor.  ``extra`` fields are merged into the summary
        (e.g. the trainer's top-K feature importances).  The digest
        state stays readable afterwards so a post-run ``stats()`` still
        carries the ``health`` section."""
        with self._lock:
            if self._fd is None:
                return
            if summary:
                rec: Dict[str, Any] = {
                    "kind": "summary",
                    "ts": round(time.time(), 3),
                    "records": self._records + 1,
                    "aborted": bool(aborted),
                }
                rec.update(clock_pair())
                if self._last_iter is not None:
                    rec["iterations"] = int(self._last_iter["iter"]) + 1
                if self._nonfinite_total:
                    rec["nonfinite_total"] = self._nonfinite_total
                if extra:
                    rec.update(extra)
                self._ingest(rec)
                self._write(rec)
            fd, self._fd = self._fd, None
            try:
                os.close(fd)
            except OSError:
                pass

    def reset(self) -> None:
        """Drop the stream and the digest state (test/bench windows)."""
        with self._lock:
            self.close(summary=False)
            self._path = ""
            self._records = 0
            self._by_kind = defaultdict(int)
            self._last_iter = None
            self._nonfinite_total = 0

    # ------------------------------------------------------------ records
    def record(self, kind: str, fields: Optional[Dict[str, Any]] = None,
               ) -> None:
        """Append one record; no-op when the stream is closed.  ``t`` is
        stamped as seconds since the stream opened unless provided."""
        with self._lock:
            if self._fd is None:
                return
            rec: Dict[str, Any] = {"kind": kind}
            if fields:
                rec.update(fields)
            rec.setdefault("t", round(time.perf_counter() - self._t0, 6))
            for k, v in clock_pair().items():
                rec.setdefault(k, v)
            self._ingest(rec)
            self._write(rec)

    def _ingest(self, rec: Dict[str, Any]) -> None:
        self._records += 1
        self._by_kind[rec.get("kind", "?")] += 1
        if rec.get("kind") == "iter":
            self._last_iter = rec
            for sec in ("grad", "hess"):
                nf = (rec.get(sec) or {}).get("nonfinite")
                if nf:
                    self._nonfinite_total += int(sum(nf))

    def _write(self, rec: Dict[str, Any]) -> None:
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        try:
            os.write(self._fd, line.encode())
        except OSError as e:
            # a full disk must degrade the stream, not kill training
            from .log import log_warning
            log_warning(f"health stream write to {self._path} failed "
                        f"({e}); stream disabled for the rest of the run")
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    # ------------------------------------------------------------- digest
    def summary_section(self) -> Optional[Dict[str, Any]]:
        """The ``health`` section of ``stats()``: stream path, record
        counts by kind, the last ``iter`` record, and nonfinite totals.
        ``None`` when this process never opened a stream."""
        with self._lock:
            if not self._path:
                return None
            out: Dict[str, Any] = {
                "schema": HEALTH_SCHEMA,
                "path": self._path,
                "active": self._fd is not None,
                "records": self._records,
                "by_kind": dict(self._by_kind),
            }
            if self._last_iter is not None:
                out["last_iter"] = dict(self._last_iter)
            if self._nonfinite_total:
                out["nonfinite_total"] = self._nonfinite_total
            return out


HEALTH = HealthStream()


class TelemetryRegistry:
    """Thread-safe registry of counters, gauges, spans and the
    per-iteration timeline.  One process-global instance (``TELEMETRY``)
    exists; tests may construct private ones."""

    def __init__(self, span_capacity: int = SPAN_CAPACITY) -> None:
        self._lock = threading.RLock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        # (ts_us, dur_us, name, tid_label, args|None)
        self._spans: deque = deque(maxlen=span_capacity)
        self._spans_recorded = 0
        self._timeline: deque = deque(maxlen=TIMELINE_CAPACITY)
        self._iter_snapshot: Dict[str, float] = {}
        self._epoch = time.perf_counter()
        self._config_level: Optional[int] = None
        self._jax_listeners_installed = False
        # single-writer race check, analogous to the reference Network's
        # single-thread CHECK: the first writer thread claims the stream;
        # a second one is recorded (and warned about) once, not fatal
        self._writer: Optional[int] = None
        self._race_flagged = False
        # ------ device memory (HBM) accounting ------
        # tri-state support flag: None = unknown, False = backend has no
        # memory_stats (CPU) — once False, sampling short-circuits
        self._mem_supported: Optional[bool] = None
        self._mem_device = None
        self._mem_last: Optional[int] = None
        self._mem_peak = 0
        self._mem_largest = 0
        self._mem_limit: Optional[int] = None
        self._mem_phase: Dict[str, Dict[str, int]] = {}
        # (t_offset_s, bytes_in_use) from the background sampler, for
        # the Chrome-trace mem/* counter track
        self._mem_track: deque = deque(maxlen=MEM_TRACK_CAPACITY)
        self._mem_thread: Optional[threading.Thread] = None
        self._mem_stop: Optional[threading.Event] = None
        self._mem_interval_ms = 0.0
        # which tier the binned training matrix lives in ("resident" /
        # "spill", models/gbdt.py); None until a run resolves it
        self._data_tier: Optional[str] = None
        # ------ XLA cost analysis (per jit-seam label) ------
        self._costs: Dict[str, Dict[str, float]] = {}
        # ------ measured per-dispatch timing (opt-in, v4) ------
        # label -> {count, total_s, samples, last_end, gap_count,
        # gap_total_s}; fed by utils/jitcost.py only when ``timing_on``
        self._timing: Dict[str, Dict[str, Any]] = {}
        self._config_timing = False
        # the jax-profiler capture artifact (utils/phase.py): path and,
        # for windowed captures, the iteration span
        self._profile_capture: Optional[Dict[str, Any]] = None
        # ------ serve sliding window (v5) ------
        # (t_done rel epoch, end-to-end latency) of completed serve
        # requests; serve/queue.py appends one sample per reply and
        # serve_window_stats() folds the trailing SERVE_WINDOW_S into
        # live QPS/p50/p99 — the bound makes a long-lived server's
        # memory flat no matter how much traffic it absorbs
        self._serve_done: deque = deque(maxlen=SERVE_SAMPLE_CAPACITY)
        # ------ fault / recovery narration ------
        # every injected fault, rollback, retry and salvage lands here so
        # the metrics blob can explain a degraded run; recorded at EVERY
        # level (faults are rare and load-bearing, unlike hot-path spans)
        self._faults: deque = deque(maxlen=FAULT_CAPACITY)
        self._fault_counts: Dict[str, float] = defaultdict(float)
        self._level = self._resolve_level()
        # plain attribute (not a property): the hot-path off-switch in
        # utils/jitcost.py stays one attribute compare
        self.timing_on = self._resolve_timing()

    # ------------------------------------------------------------- level
    def _resolve_level(self) -> int:
        env = os.environ.get("LIGHTGBM_TPU_TELEMETRY", "")
        if env != "":
            try:
                lvl = int(env)
            except ValueError:
                lvl = 1
        elif self._config_level is not None:
            lvl = self._config_level
        else:
            lvl = 1
        if os.environ.get("LIGHTGBM_TPU_TRACE_JSON"):
            lvl = max(lvl, 2)
        return max(0, min(2, lvl))

    def refresh_level(self) -> int:
        """Re-read env/config into the cached level (the hot-path gate is
        one attribute compare; refresh happens at setup boundaries)."""
        self._level = self._resolve_level()
        self.timing_on = self._resolve_timing()
        return self._level

    @property
    def level(self) -> int:
        return self._level

    def set_config_level(self, level) -> None:
        """Bind the ``telemetry_level`` config parameter (env wins)."""
        try:
            self._config_level = int(level)
        except (TypeError, ValueError):
            self._config_level = None
        self.refresh_level()

    def _resolve_timing(self) -> bool:
        """Measured-dispatch timing is an opt-in on TOP of level >= 1
        (jitcost's level gate already short-circuits below that):
        ``LIGHTGBM_TPU_DEVICE_TIMING`` wins over the ``device_timing``
        config parameter."""
        if self._level < 1:
            return False
        env = os.environ.get(TIMING_ENV, "")
        if env != "":
            return env.strip().lower() not in ("0", "false", "off", "no")
        return bool(self._config_timing)

    def set_config_timing(self, flag) -> None:
        """Bind the ``device_timing`` config parameter (env wins)."""
        self._config_timing = bool(flag)
        self.timing_on = self._resolve_timing()

    # ----------------------------------------------------- writer check
    def _note_writer(self) -> None:
        ident = threading.get_ident()
        if self._writer is None:
            self._writer = ident
        elif self._writer != ident and not self._race_flagged:
            self._race_flagged = True
            self._counters["telemetry/writer_races"] += 1
            from .log import log_warning
            log_warning("telemetry written from multiple threads; counts "
                        "stay consistent (locked) but span/timeline "
                        "ordering may interleave")

    # -------------------------------------------------- counters/gauges
    def counter_add(self, name: str, value: float = 1) -> None:
        if self._level < 1:
            return
        with self._lock:
            self._note_writer()
            self._counters[name] += value

    def gauge_set(self, name: str, value: float) -> None:
        if self._level < 1:
            return
        with self._lock:
            self._note_writer()
            self._gauges[name] = value

    def gauge_get(self, name: str, default=None):
        with self._lock:
            return self._gauges.get(name, default)

    # -------------------------------------------------------------- spans
    def record_span(self, name: str, t0: float, dur: float,
                    args: Optional[dict] = None,
                    tid: Optional[str] = None) -> None:
        """Record one finished span; ``t0`` is a time.perf_counter()
        value, ``dur`` seconds.  No-op below level 2."""
        if self._level < 2:
            return
        label = tid or threading.current_thread().name
        with self._lock:
            self._note_writer()
            self._spans_recorded += 1
            self._spans.append(((t0 - self._epoch) * 1e6, dur * 1e6,
                                name, label, args or None))

    @contextmanager
    def span(self, name: str, **args):
        """Context-managed span (host-side dispatch window; see module
        docstring for the async caveat)."""
        if self._level < 2:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record_span(name, t0, time.perf_counter() - t0,
                             args or None)

    # ----------------------------------------------------------- timeline
    def mark_iteration(self, iteration: int, count: int = 1) -> None:
        """Close one timeline entry: iteration index (the last iteration
        when ``count`` > 1, i.e. a boosting chunk), the wall offset since
        reset, and the counter deltas since the previous mark."""
        if self._level < 1:
            return
        with self._lock:
            self._note_writer()
            deltas = {}
            for k, v in self._counters.items():
                d = v - self._iter_snapshot.get(k, 0)
                if d:
                    deltas[k] = round(d, 9) if isinstance(d, float) else d
            self._iter_snapshot = dict(self._counters)
            self._timeline.append(
                {"iter": int(iteration), "count": int(count),
                 "t": round(time.perf_counter() - self._epoch, 6),
                 "counters": deltas})

    # -------------------------------------------------------------- faults
    def fault_event(self, kind: str, site: str = "", detail: str = "",
                    iteration: Optional[int] = None) -> None:
        """Record one fault/recovery event (``injected``, ``oom_degrade``,
        ``nonfinite_rollback``, ``snapshot_io``, ``resume``,
        ``collective_retry``, ``partial_save`` ...).  Unlike counters and
        spans this records at every telemetry level: faults are rare and
        explain why a run degraded, so they must never be gated away."""
        with self._lock:
            self._note_writer()
            self._fault_counts[kind] += 1
            ev: Dict[str, Any] = {
                "kind": kind,
                "t": round(time.perf_counter() - self._epoch, 6),
            }
            if site:
                ev["site"] = site
            if detail:
                ev["detail"] = detail
            if iteration is not None:
                ev["iter"] = int(iteration)
            self._faults.append(ev)
        # mirror into the health stream (its own lock; no nesting back
        # into this registry) so a live monitor sees faults as they land
        if HEALTH.active:
            fields: Dict[str, Any] = {"fault": kind}
            if site:
                fields["site"] = site
            if detail:
                fields["detail"] = detail
            if iteration is not None:
                fields["iter"] = int(iteration)
            HEALTH.record("fault", fields)

    def _faults_section(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            if not self._faults and not self._fault_counts:
                return None
            return {"counts": dict(self._fault_counts),
                    "events": [dict(e) for e in self._faults]}

    # ------------------------------------------------------ jax.monitoring
    def install_jax_listeners(self) -> None:
        """Register jax.monitoring listeners for compile/retrace/cache
        events.  Idempotent; jax offers no unregistration, so callbacks
        stay bound to this (process-global) registry and self-gate on the
        current level."""
        if self._jax_listeners_installed:
            return
        self._jax_listeners_installed = True
        try:
            from jax import monitoring
        except ImportError:      # pragma: no cover - jax is a hard dep
            return

        def on_event(event, **kw):
            name = _JAX_COUNT_EVENTS.get(event)
            if name is not None:
                self.counter_add(name)

        def on_duration(event, duration, **kw):
            names = _JAX_DURATION_EVENTS.get(event)
            if names is None:
                return
            self.counter_add(names[0])
            self.counter_add(names[1], float(duration))
            if self._level >= 2:
                now = time.perf_counter()
                self.record_span(event.rsplit("/", 1)[-1],
                                 now - float(duration), float(duration),
                                 tid="jax-compile")

        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)

    # ------------------------------------------------------- device memory
    def _device_memory_stats(self) -> Optional[Dict[str, Any]]:
        """Raw ``memory_stats()`` of the default device, or ``None`` on
        backends that do not report allocator stats (CPU).  The first
        ``None`` latches ``_mem_supported = False`` so later samples are
        a single attribute compare.  Reading allocator stats is a local
        runtime query — it never blocks on in-flight device work."""
        if self._mem_supported is False:
            return None
        try:
            if self._mem_device is None:
                import jax
                self._mem_device = jax.local_devices()[0]
            ms = self._mem_device.memory_stats()
        except Exception:
            ms = None
        if not ms:
            self._mem_supported = False
            return None
        self._mem_supported = True
        return ms

    def sample_memory(self, phase: Optional[str] = None) -> None:
        """Fold one allocator snapshot into the memory gauges; ``phase``
        attributes the bytes-in-use high-water mark to a named phase
        (called at phase boundaries by utils/phase.py).  No-op below
        level 1 or on backends without memory stats."""
        if self._level < 1 or self._mem_supported is False:
            return
        ms = self._device_memory_stats()
        if ms is None:
            return
        in_use = int(ms.get("bytes_in_use", 0))
        peak = int(ms.get("peak_bytes_in_use", in_use))
        # no _note_writer here: the background sampler is an EXPECTED
        # second thread; gauges are simple maxes under the lock
        with self._lock:
            if "bytes_limit" in ms:
                self._mem_limit = int(ms["bytes_limit"])
            self._mem_largest = max(self._mem_largest,
                                    int(ms.get("largest_alloc_size", 0)))
            self._mem_last = in_use
            self._mem_peak = max(self._mem_peak, peak, in_use)
            if phase:
                e = self._mem_phase.setdefault(
                    phase, {"bytes_in_use_max": 0, "samples": 0})
                e["bytes_in_use_max"] = max(e["bytes_in_use_max"], in_use)
                e["samples"] += 1

    def start_mem_sampler(self) -> None:
        """Start the background HBM sampler thread when
        ``LIGHTGBM_TPU_MEM_SAMPLE_MS`` requests one (off by default).
        Idempotent; the thread is a daemon and additionally bounded by
        stop_mem_sampler, so it can never outlive the training window
        it was started for."""
        if self._level < 1 or self._mem_thread is not None:
            return
        raw = os.environ.get("LIGHTGBM_TPU_MEM_SAMPLE_MS", "")
        try:
            interval_ms = float(raw)
        except ValueError:
            interval_ms = 0.0
        if interval_ms <= 0:
            return
        stop = threading.Event()

        def run() -> None:
            while not stop.wait(interval_ms / 1000.0):
                if self._mem_supported is False:
                    return          # nothing to sample; exit quietly
                self.sample_memory()
                ms = self._mem_last
                if ms is not None:
                    with self._lock:
                        self._mem_track.append(
                            (time.perf_counter() - self._epoch, ms))

        self._mem_stop = stop
        self._mem_interval_ms = interval_ms
        self._mem_thread = threading.Thread(target=run, name="mem-sampler",
                                            daemon=True)
        self._mem_thread.start()

    def stop_mem_sampler(self) -> None:
        """Stop and join the background sampler (idempotent)."""
        t, stop = self._mem_thread, self._mem_stop
        self._mem_thread = None
        self._mem_stop = None
        if stop is not None:
            stop.set()
        if t is not None:
            t.join(timeout=5.0)

    @contextmanager
    def memory_session(self):
        """Device-memory window around a training run: one boundary
        sample on entry and exit, plus the opt-in background sampler —
        exception-safe, so an error mid-training never leaks the
        sampler thread."""
        self.sample_memory("session")
        self.start_mem_sampler()
        try:
            yield
        finally:
            self.stop_mem_sampler()
            self.sample_memory("session")

    def device_memory_budget(self) -> Optional[int]:
        """The device allocator's reported capacity (``bytes_limit``) or
        None on backends without memory stats — the denominator of the
        out-of-core admission check (models/gbdt.py)."""
        ms = self._device_memory_stats()
        if not ms:
            return None
        limit = ms.get("bytes_limit")
        return int(limit) if limit else None

    def set_data_tier(self, tier: Optional[str]) -> None:
        """Record which tier the binned matrix lives in ("resident" /
        "spill").  Like fault_event this records at every level: a tier
        transition explains a run's performance cliff and must never be
        gated away."""
        with self._lock:
            self._data_tier = tier

    def data_tier(self) -> Optional[str]:
        with self._lock:
            return self._data_tier

    def memory_gauges(self) -> Optional[Dict[str, int]]:
        """Cheap HBM gauge for per-iteration health records: the last
        and peak bytes-in-use already sampled at phase boundaries — no
        fresh allocator query, so the hot path stays untouched.  None on
        backends without memory stats."""
        with self._lock:
            if self._mem_last is None:
                return None
            return {"bytes_in_use": self._mem_last,
                    "peak_bytes_in_use": self._mem_peak}

    def _memory_section(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            # a spilled run surfaces its tier even on backends without
            # allocator stats (CPU tests); a resident run on such a
            # backend keeps the section cleanly absent, as before
            if self._mem_last is None and self._data_tier != "spill":
                return None
            out: Dict[str, Any] = {}
            if self._mem_last is not None:
                out.update({
                    "bytes_in_use": self._mem_last,
                    "peak_bytes_in_use": self._mem_peak,
                    "largest_alloc": self._mem_largest,
                    "phases": {k: dict(v)
                               for k, v in self._mem_phase.items()},
                })
                if self._mem_limit is not None:
                    out["bytes_limit"] = self._mem_limit
                if self._mem_interval_ms > 0:
                    out["sampler"] = {"interval_ms": self._mem_interval_ms,
                                      "samples": len(self._mem_track)}
            if self._data_tier is not None:
                out["data_tier"] = self._data_tier
            return out

    # --------------------------------------------------- XLA cost analysis
    def record_cost(self, label: str, analysis: Dict[str, float]) -> None:
        """Bind one compiled executable's static cost analysis to a jit
        seam label (utils/jitcost.py harvests it once per compile).  The
        per-call numbers become the increment applied by cost_call."""
        if self._level < 1:
            return
        with self._lock:
            e = self._costs.setdefault(label, {
                "flops": 0.0, "bytes_accessed": 0.0,
                "transcendentals": 0.0, "calls": 0, "compiles": 0,
                "flops_total": 0.0, "bytes_total": 0.0})
            e["flops"] = float(analysis.get("flops", 0.0))
            e["bytes_accessed"] = float(analysis.get("bytes_accessed", 0.0))
            e["transcendentals"] = float(
                analysis.get("transcendentals", 0.0))
            # executable working set (memory_analysis), when available
            for k in ("temp_bytes", "argument_bytes", "output_bytes"):
                if k in analysis:
                    e[k] = float(analysis[k])
            e["compiles"] += 1

    def cost_working_set(self) -> int:
        """Largest per-executable working set (argument + temp + output
        bytes) among the cost-instrumented seams, from XLA's
        memory_analysis — 0 when nothing compiled yet.  Feeds the
        out-of-core admission check alongside the bin-matrix bytes."""
        with self._lock:
            best = 0
            for e in self._costs.values():
                ws = int(e.get("argument_bytes", 0)
                         + e.get("temp_bytes", 0)
                         + e.get("output_bytes", 0))
                best = max(best, ws)
            return best

    def cost_call(self, label: str, count: int = 1) -> None:
        """Count ``count`` dispatches of a cost-instrumented seam; the
        running totals use the label's CURRENT per-call cost, so they
        stay exact across recompiles at new shapes."""
        if self._level < 1:
            return
        with self._lock:
            e = self._costs.get(label)
            if e is None:
                return
            e["calls"] += count
            e["flops_total"] += e["flops"] * count
            e["bytes_total"] += e["bytes_accessed"] * count

    def _cost_section(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            if not self._costs:
                return None
            labels = {k: dict(v) for k, v in self._costs.items()}
            elapsed = time.perf_counter() - self._epoch
        flops_total = sum(e["flops_total"] for e in labels.values())
        bytes_total = sum(e["bytes_total"] for e in labels.values())
        out: Dict[str, Any] = {
            "labels": labels,
            "window_seconds": round(elapsed, 6),
            "flops_total": flops_total,
            "bytes_total": bytes_total,
        }
        if elapsed > 0:
            out["est_flops_per_s"] = flops_total / elapsed
            out["est_bytes_per_s"] = bytes_total / elapsed
        return out

    # ------------------------------------------- measured dispatch timing
    def record_dispatch(self, label: str, start: float, end: float) -> None:
        """Fold one measured wall-to-ready dispatch window (two
        ``time.perf_counter()`` values) into the per-label timing
        accumulators.  utils/jitcost.py calls this only when
        ``timing_on`` — the sync that produced ``end`` already happened.
        The gap accumulators measure host overhead between consecutive
        dispatches of the SAME label (end of one to start of the next)."""
        wall = max(0.0, end - start)
        with self._lock:
            e = self._timing.get(label)
            if e is None:
                e = self._timing[label] = {
                    "count": 0, "total_s": 0.0,
                    "samples": deque(maxlen=TIMING_SAMPLE_CAPACITY),
                    "last_end": None, "gap_count": 0, "gap_total_s": 0.0}
            e["count"] += 1
            e["total_s"] += wall
            e["samples"].append(wall)
            last_end = e["last_end"]
            if last_end is not None and start > last_end:
                e["gap_count"] += 1
                e["gap_total_s"] += start - last_end
            e["last_end"] = end

    def dispatch_seconds_total(self) -> float:
        """Sum of every label's measured wall-to-ready dispatch seconds.
        Zero until ``device_timing`` ran; deltas of this around a work
        window (the sched plane brackets each time slice with it) give
        that window's measured device-seconds without walking the
        per-label ``timing`` section."""
        with self._lock:
            return float(sum(e["total_s"] for e in self._timing.values()))

    def record_profile_capture(self, info: Dict[str, Any]) -> None:
        """Attach a jax-profiler capture's artifact location (and, for
        windowed captures, the iteration span) to the ``timing`` section.
        Recorded at every level: a capture the user asked for must be
        findable from the blob."""
        with self._lock:
            self._profile_capture = dict(info)

    @staticmethod
    def _quantile(sorted_vals, q: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = min(len(sorted_vals) - 1,
                  int(round(q * (len(sorted_vals) - 1))))
        return float(sorted_vals[idx])

    # --------------------------------------------- serve sliding window
    def serve_request_done(self, latency_s: float,
                           end: Optional[float] = None) -> None:
        """Fold one completed serve request (end-to-end enqueue→reply
        latency) into the sliding window.  ``end`` is the reply's
        ``time.perf_counter()`` stamp (defaults to now)."""
        if self._level < 1:
            return
        t = (end if end is not None else time.perf_counter()) \
            - self._epoch
        with self._lock:
            self._serve_done.append((t, max(0.0, float(latency_s))))

    def serve_window_stats(self, window_s: float = SERVE_WINDOW_S,
                           now: Optional[float] = None,
                           ) -> Optional[Dict[str, Any]]:
        """Live serve rates over the trailing ``window_s`` seconds:
        request count, QPS and end-to-end p50/p99.  ``None`` when no
        request completed inside the window (distinguishes an idle
        server from one that never served)."""
        t_now = (now if now is not None else time.perf_counter()) \
            - self._epoch
        cutoff = t_now - window_s
        with self._lock:
            lat = sorted(lt for (t, lt) in self._serve_done
                         if t >= cutoff)
        if not lat:
            return None
        return {"window_s": float(window_s),
                "requests": len(lat),
                "qps": round(len(lat) / window_s, 3),
                "p50_s": round(self._quantile(lat, 0.50), 9),
                "p99_s": round(self._quantile(lat, 0.99), 9)}

    def _timing_section(self) -> Optional[Dict[str, Any]]:
        """The v4 ``timing`` section: per-label measured dispatch wall
        (count/total/mean/p50/p99/max + gap stats) and, for labels with
        cost analysis, measured FLOP/s and B/s — static work divided by
        MEASURED seconds, next to the blob-level estimated rates.  The
        quantiles come from a bounded per-label sample reservoir
        (``TIMING_SAMPLE_CAPACITY`` newest samples).  ``None`` when
        timing never ran and no profiler capture was taken."""
        with self._lock:
            if not self._timing and self._profile_capture is None:
                return None
            entries = {k: (dict(v), sorted(v["samples"]))
                       for k, v in self._timing.items()}
            costs = {k: dict(v) for k, v in self._costs.items()}
            capture = (dict(self._profile_capture)
                       if self._profile_capture is not None else None)
            enabled = bool(self.timing_on)
        labels: Dict[str, Any] = {}
        total_s = 0.0
        flops_timed = bytes_timed = 0.0
        have_cost = False
        for name, (e, samples) in entries.items():
            n = e["count"]
            lab: Dict[str, Any] = {
                "count": n,
                "total_s": round(e["total_s"], 6),
                "mean_s": round(e["total_s"] / n, 9) if n else 0.0,
                "p50_s": round(self._quantile(samples, 0.50), 9),
                "p99_s": round(self._quantile(samples, 0.99), 9),
                "max_s": round(samples[-1], 9) if samples else 0.0,
            }
            if e["gap_count"]:
                lab["gap_count"] = e["gap_count"]
                lab["gap_total_s"] = round(e["gap_total_s"], 6)
                lab["gap_mean_s"] = round(
                    e["gap_total_s"] / e["gap_count"], 9)
            c = costs.get(name)
            if c is not None and e["total_s"] > 0:
                lab["measured_flops_per_s"] = \
                    c["flops_total"] / e["total_s"]
                lab["measured_bytes_per_s"] = \
                    c["bytes_total"] / e["total_s"]
                flops_timed += c["flops_total"]
                bytes_timed += c["bytes_total"]
                have_cost = True
            labels[name] = lab
            total_s += e["total_s"]
        out: Dict[str, Any] = {"enabled": enabled or bool(labels)}
        if labels:
            out["labels"] = labels
            out["total_s"] = round(total_s, 6)
            if have_cost and total_s > 0:
                out["measured_flops_per_s"] = flops_timed / total_s
                out["measured_bytes_per_s"] = bytes_timed / total_s
        if capture is not None:
            out["profile"] = capture
        return out

    # ------------------------------------------------------------- output
    def stats(self) -> Dict[str, Any]:
        """Versioned stats dict: phases (from the global PhaseTimer),
        counters, gauges, network collective counters, the per-iteration
        timeline, span-buffer occupancy, and — when available — the
        device-side ``memory`` (HBM gauges) and ``cost`` (XLA cost
        analysis) sections.  ``memory`` is omitted on backends whose
        ``memory_stats()`` returns None; ``cost`` is omitted when no
        instrumented seam compiled in the window.  v3 adds top-level
        ``schema``/``telemetry_level`` keys and, when the run wrote a
        health stream, its ``health`` digest section.  v4 adds the
        ``timing`` section (measured per-dispatch wall + profiler
        capture info), present only when device timing ran or a
        profiler capture was taken.  v5 adds the ``serve`` section:
        the sliding-window QPS/p50/p99 of the serve plane, present
        only when a request completed inside the window.  v6 adds the
        ``fleet`` section — cross-rank collective wait-vs-work
        attribution (per-rank wait seconds, slowest-rank histogram,
        clock-offset table) — present only when the fleet observability
        plane synced at least one window.  v7 adds the ``drift``
        section — per-model serve-traffic drift vs training baseline
        (per-feature PSI, score-shift JS, the gate threshold) — present
        only when a drift window synced, so earlier blobs keep their
        v6 shape."""
        import sys
        from .phase import GLOBAL_TIMER, _sync_enabled
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timeline = list(self._timeline)
            recorded = self._spans_recorded
            kept = len(self._spans)
            capacity = self._spans.maxlen
        phases = {name: {"seconds": round(sec, 6), "count": cnt}
                  for name, (sec, cnt) in GLOBAL_TIMER.snapshot().items()}
        network: Dict[str, Any] = {}
        net = sys.modules.get("lightgbm_tpu.parallel.network")
        if net is not None and hasattr(net, "collective_stats"):
            network = net.collective_stats()
        out: Dict[str, Any] = {
            "schema": METRICS_SCHEMA,
            "version": METRICS_VERSION,
            "level": self._level,
            "telemetry_level": self._level,
            "mode": "sync" if _sync_enabled() else "dispatch",
            "phases": phases,
            "counters": counters,
            "gauges": gauges,
            "network": network,
            "timeline": timeline,
            "spans": {"recorded": recorded, "kept": kept,
                      "dropped": recorded - kept, "capacity": capacity},
        }
        memory = self._memory_section()
        if memory is not None:
            out["memory"] = memory
        cost = self._cost_section()
        if cost is not None:
            out["cost"] = cost
        timing = self._timing_section()
        if timing is not None:
            out["timing"] = timing
        serve = self.serve_window_stats()
        if serve is not None:
            out["serve"] = serve
        faults = self._faults_section()
        if faults is not None:
            out["faults"] = faults
        health = HEALTH.summary_section()
        if health is not None:
            out["health"] = health
        fleet_mod = sys.modules.get("lightgbm_tpu.obs.fleet")
        if fleet_mod is not None and hasattr(fleet_mod, "fleet_section"):
            fleet = fleet_mod.fleet_section()
            if fleet is not None:
                out["fleet"] = fleet
        drift_mod = sys.modules.get("lightgbm_tpu.obs.drift")
        if drift_mod is not None and hasattr(drift_mod, "drift_section"):
            drift = drift_mod.drift_section()
            if drift is not None:
                out["drift"] = drift
        return out

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (the ``{"traceEvents": [...]}`` object
        form): one complete ("X") event per span, one counter ("C") event
        per timeline counter delta, plus thread-name metadata."""
        with self._lock:
            spans = list(self._spans)
            timeline = list(self._timeline)
            mem_track = list(self._mem_track)
            faults = [dict(e) for e in self._faults]
        pid = os.getpid()
        events = []
        tids: Dict[str, int] = {}

        def tid_of(label: str) -> int:
            if label not in tids:
                tids[label] = len(tids) + 1
                events.append({"name": "thread_name", "ph": "M",
                               "pid": pid, "tid": tids[label],
                               "args": {"name": label}})
            return tids[label]

        for ts, dur, name, label, args in spans:
            ev = {"name": name, "cat": "lightgbm_tpu", "ph": "X",
                  "ts": round(ts, 3), "dur": round(dur, 3),
                  "pid": pid, "tid": tid_of(label)}
            if args:
                ev["args"] = args
            events.append(ev)
        for entry in timeline:
            ts = entry["t"] * 1e6
            for cname, delta in entry["counters"].items():
                events.append({"name": cname, "ph": "C", "pid": pid,
                               "tid": 0, "ts": round(ts, 3),
                               "args": {"value": delta}})
        # background HBM samples as their own counter track
        for t_off, in_use in mem_track:
            events.append({"name": "mem/bytes_in_use", "ph": "C",
                           "pid": pid, "tid": 0,
                           "ts": round(t_off * 1e6, 3),
                           "args": {"value": in_use}})
        # fault/recovery events as globally-scoped instants, so a
        # degradation is visible at a glance on the trace timeline
        for ev in faults:
            args = {k: v for k, v in ev.items() if k not in ("kind", "t")}
            events.append({"name": f"fault/{ev['kind']}",
                           "cat": "lightgbm_tpu", "ph": "i", "s": "g",
                           "pid": pid, "tid": 0,
                           "ts": round(ev["t"] * 1e6, 3),
                           "args": args})
        # clock anchors: event ``ts`` values are µs since ``_epoch`` (a
        # perf_counter instant).  ``mono_epoch``/``wall_epoch`` pin that
        # instant on the monotonic and wall clocks so fleet_trace.py can
        # map per-rank traces onto one skew-corrected timeline.
        now_pc = time.perf_counter()
        other: Dict[str, Any] = {
            "schema": METRICS_SCHEMA,
            "mono_epoch": round(time.monotonic() - (now_pc - self._epoch),
                                6),
            "wall_epoch": round(time.time() - (now_pc - self._epoch), 6),
        }
        import sys
        dist = sys.modules.get("lightgbm_tpu.parallel.distributed")
        if dist is not None and getattr(dist, "is_active", lambda: False)():
            other["rank"] = dist.rank()
            other["world"] = dist.world()
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": other}

    def export_trace(self, path: str) -> None:
        try:
            with open(path, "w") as fh:
                json.dump(self.chrome_trace(), fh)
        except OSError as e:
            from .log import log_warning
            log_warning(f"could not write trace JSON to {path}: {e}")

    def maybe_export_trace(self) -> None:
        """Write the Chrome trace to ``LIGHTGBM_TPU_TRACE_JSON`` if set.
        Called at the end of training and (backstop) at process exit."""
        path = os.environ.get("LIGHTGBM_TPU_TRACE_JSON")
        if path:
            self.export_trace(path)

    def metrics_blob(self) -> Dict[str, Any]:
        """The versioned JSON blob written by the CLI ``metrics_out=``
        parameter and embedded in bench results."""
        blob = {"schema": METRICS_SCHEMA}
        blob.update(self.stats())
        return blob

    # -------------------------------------------------------------- reset
    def reset(self) -> None:
        """Clear all recorded data (not the config level or installed
        listeners) and re-zero the time base; also resets the network
        collective counters so a measurement window starts clean."""
        import sys
        self.stop_mem_sampler()
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._spans.clear()
            self._spans_recorded = 0
            self._timeline.clear()
            self._iter_snapshot = {}
            self._epoch = time.perf_counter()
            self._writer = None
            self._race_flagged = False
            self._mem_supported = None
            self._mem_device = None
            self._mem_last = None
            self._mem_peak = 0
            self._mem_largest = 0
            self._mem_limit = None
            self._mem_phase = {}
            self._mem_track.clear()
            self._mem_interval_ms = 0.0
            self._data_tier = None
            self._costs = {}
            self._timing = {}
            self._serve_done.clear()
            self._profile_capture = None
            self._faults.clear()
            self._fault_counts.clear()
        net = sys.modules.get("lightgbm_tpu.parallel.network")
        if net is not None and hasattr(net, "reset_collective_stats"):
            net.reset_collective_stats()
        drift_mod = sys.modules.get("lightgbm_tpu.obs.drift")
        if drift_mod is not None and hasattr(drift_mod, "reset"):
            drift_mod.reset()
        HEALTH.reset()
        self.refresh_level()


TELEMETRY = TelemetryRegistry()

# an exception that unwinds past the training loop must not lose an
# almost-complete trace: export whatever was recorded at process exit
atexit.register(TELEMETRY.maybe_export_trace)
