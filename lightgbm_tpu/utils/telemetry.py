"""Process-global training telemetry: spans, counters, gauges, a
per-iteration timeline, and Chrome trace-event export.

The reference fork's defining additions over stock LightGBM are
observability: easy_profiler trace blocks (src/main.cpp:13-39), TIMETAG
per-phase accumulators (serial_tree_learner.cpp:20-47) and network
byte/time counters (linkers.h:114-117).  This module is the TPU build's
superset of all three, layered on top of the existing ``PhaseTimer``
(utils/phase.py), which keeps its role as the per-phase accumulator and
additionally feeds every finished phase into the span ring buffer here.

Three telemetry levels gate the overhead:

  * ``0`` — off.  Every record call is a single attribute compare.
  * ``1`` — default.  Counters, gauges and the per-iteration timeline
    accumulate; phase seconds keep accruing in ``PhaseTimer``.
  * ``2`` — adds timestamped spans in a bounded ring buffer, exportable
    as Chrome trace-event JSON (load in Perfetto / chrome://tracing).

The effective level resolves lazily (env vars are read at refresh time,
not import time, so the test harness's env scrubbing and monkeypatching
behave): ``LIGHTGBM_TPU_TELEMETRY`` wins if set, else the
``telemetry_level`` config parameter, else 1; a set
``LIGHTGBM_TPU_TRACE_JSON=<path>`` forces the effective level to >= 2
and exports the trace there at the end of training (plus an atexit
backstop).

Timing caveat: device work is dispatched asynchronously, so spans and
phase seconds measure host-side dispatch unless
``LIGHTGBM_TPU_SYNC_TIMERS=1`` (see utils/phase.py).  The ``mode`` field
of ``stats()`` records which one a blob was collected under.

Compile visibility comes from ``jax.monitoring`` listeners
(install_jax_listeners): retrace counts/seconds, backend compile
counts/seconds and compilation-cache hits/misses — cold-vs-warm cache
behavior is measurable instead of inferred from wall-clock cliffs.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Any, Dict, Optional

METRICS_SCHEMA = "lightgbm_tpu.metrics/v1"
SPAN_CAPACITY = 65536
TIMELINE_CAPACITY = 8192

# jax.monitoring event name -> (count counter, seconds counter)
_JAX_DURATION_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration":
        ("compile/retraces", "compile/retrace_seconds"),
    "/jax/core/compile/backend_compile_duration":
        ("compile/backend_compiles", "compile/backend_compile_seconds"),
}
# jax.monitoring count-only event -> counter
_JAX_COUNT_EVENTS = {
    "/jax/compilation_cache/cache_hits": "compile/cache_hits",
    "/jax/compilation_cache/cache_misses": "compile/cache_misses",
}


class TelemetryRegistry:
    """Thread-safe registry of counters, gauges, spans and the
    per-iteration timeline.  One process-global instance (``TELEMETRY``)
    exists; tests may construct private ones."""

    def __init__(self, span_capacity: int = SPAN_CAPACITY) -> None:
        self._lock = threading.RLock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        # (ts_us, dur_us, name, tid_label, args|None)
        self._spans: deque = deque(maxlen=span_capacity)
        self._spans_recorded = 0
        self._timeline: deque = deque(maxlen=TIMELINE_CAPACITY)
        self._iter_snapshot: Dict[str, float] = {}
        self._epoch = time.perf_counter()
        self._config_level: Optional[int] = None
        self._jax_listeners_installed = False
        # single-writer race check, analogous to the reference Network's
        # single-thread CHECK: the first writer thread claims the stream;
        # a second one is recorded (and warned about) once, not fatal
        self._writer: Optional[int] = None
        self._race_flagged = False
        self._level = self._resolve_level()

    # ------------------------------------------------------------- level
    def _resolve_level(self) -> int:
        env = os.environ.get("LIGHTGBM_TPU_TELEMETRY", "")
        if env != "":
            try:
                lvl = int(env)
            except ValueError:
                lvl = 1
        elif self._config_level is not None:
            lvl = self._config_level
        else:
            lvl = 1
        if os.environ.get("LIGHTGBM_TPU_TRACE_JSON"):
            lvl = max(lvl, 2)
        return max(0, min(2, lvl))

    def refresh_level(self) -> int:
        """Re-read env/config into the cached level (the hot-path gate is
        one attribute compare; refresh happens at setup boundaries)."""
        self._level = self._resolve_level()
        return self._level

    @property
    def level(self) -> int:
        return self._level

    def set_config_level(self, level) -> None:
        """Bind the ``telemetry_level`` config parameter (env wins)."""
        try:
            self._config_level = int(level)
        except (TypeError, ValueError):
            self._config_level = None
        self.refresh_level()

    # ----------------------------------------------------- writer check
    def _note_writer(self) -> None:
        ident = threading.get_ident()
        if self._writer is None:
            self._writer = ident
        elif self._writer != ident and not self._race_flagged:
            self._race_flagged = True
            self._counters["telemetry/writer_races"] += 1
            from .log import log_warning
            log_warning("telemetry written from multiple threads; counts "
                        "stay consistent (locked) but span/timeline "
                        "ordering may interleave")

    # -------------------------------------------------- counters/gauges
    def counter_add(self, name: str, value: float = 1) -> None:
        if self._level < 1:
            return
        with self._lock:
            self._note_writer()
            self._counters[name] += value

    def gauge_set(self, name: str, value: float) -> None:
        if self._level < 1:
            return
        with self._lock:
            self._note_writer()
            self._gauges[name] = value

    # -------------------------------------------------------------- spans
    def record_span(self, name: str, t0: float, dur: float,
                    args: Optional[dict] = None,
                    tid: Optional[str] = None) -> None:
        """Record one finished span; ``t0`` is a time.perf_counter()
        value, ``dur`` seconds.  No-op below level 2."""
        if self._level < 2:
            return
        label = tid or threading.current_thread().name
        with self._lock:
            self._note_writer()
            self._spans_recorded += 1
            self._spans.append(((t0 - self._epoch) * 1e6, dur * 1e6,
                                name, label, args or None))

    @contextmanager
    def span(self, name: str, **args):
        """Context-managed span (host-side dispatch window; see module
        docstring for the async caveat)."""
        if self._level < 2:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record_span(name, t0, time.perf_counter() - t0,
                             args or None)

    # ----------------------------------------------------------- timeline
    def mark_iteration(self, iteration: int, count: int = 1) -> None:
        """Close one timeline entry: iteration index (the last iteration
        when ``count`` > 1, i.e. a boosting chunk), the wall offset since
        reset, and the counter deltas since the previous mark."""
        if self._level < 1:
            return
        with self._lock:
            self._note_writer()
            deltas = {}
            for k, v in self._counters.items():
                d = v - self._iter_snapshot.get(k, 0)
                if d:
                    deltas[k] = round(d, 9) if isinstance(d, float) else d
            self._iter_snapshot = dict(self._counters)
            self._timeline.append(
                {"iter": int(iteration), "count": int(count),
                 "t": round(time.perf_counter() - self._epoch, 6),
                 "counters": deltas})

    # ------------------------------------------------------ jax.monitoring
    def install_jax_listeners(self) -> None:
        """Register jax.monitoring listeners for compile/retrace/cache
        events.  Idempotent; jax offers no unregistration, so callbacks
        stay bound to this (process-global) registry and self-gate on the
        current level."""
        if self._jax_listeners_installed:
            return
        self._jax_listeners_installed = True
        try:
            from jax import monitoring
        except ImportError:      # pragma: no cover - jax is a hard dep
            return

        def on_event(event, **kw):
            name = _JAX_COUNT_EVENTS.get(event)
            if name is not None:
                self.counter_add(name)

        def on_duration(event, duration, **kw):
            names = _JAX_DURATION_EVENTS.get(event)
            if names is None:
                return
            self.counter_add(names[0])
            self.counter_add(names[1], float(duration))
            if self._level >= 2:
                now = time.perf_counter()
                self.record_span(event.rsplit("/", 1)[-1],
                                 now - float(duration), float(duration),
                                 tid="jax-compile")

        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)

    # ------------------------------------------------------------- output
    def stats(self) -> Dict[str, Any]:
        """Versioned stats dict: phases (from the global PhaseTimer),
        counters, gauges, network collective counters, the per-iteration
        timeline and span-buffer occupancy."""
        import sys
        from .phase import GLOBAL_TIMER, _sync_enabled
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timeline = list(self._timeline)
            recorded = self._spans_recorded
            kept = len(self._spans)
            capacity = self._spans.maxlen
        phases = {name: {"seconds": round(sec, 6), "count": cnt}
                  for name, (sec, cnt) in GLOBAL_TIMER.snapshot().items()}
        network: Dict[str, Any] = {}
        net = sys.modules.get("lightgbm_tpu.parallel.network")
        if net is not None and hasattr(net, "collective_stats"):
            network = net.collective_stats()
        return {
            "version": 1,
            "level": self._level,
            "mode": "sync" if _sync_enabled() else "dispatch",
            "phases": phases,
            "counters": counters,
            "gauges": gauges,
            "network": network,
            "timeline": timeline,
            "spans": {"recorded": recorded, "kept": kept,
                      "dropped": recorded - kept, "capacity": capacity},
        }

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (the ``{"traceEvents": [...]}`` object
        form): one complete ("X") event per span, one counter ("C") event
        per timeline counter delta, plus thread-name metadata."""
        with self._lock:
            spans = list(self._spans)
            timeline = list(self._timeline)
        pid = os.getpid()
        events = []
        tids: Dict[str, int] = {}

        def tid_of(label: str) -> int:
            if label not in tids:
                tids[label] = len(tids) + 1
                events.append({"name": "thread_name", "ph": "M",
                               "pid": pid, "tid": tids[label],
                               "args": {"name": label}})
            return tids[label]

        for ts, dur, name, label, args in spans:
            ev = {"name": name, "cat": "lightgbm_tpu", "ph": "X",
                  "ts": round(ts, 3), "dur": round(dur, 3),
                  "pid": pid, "tid": tid_of(label)}
            if args:
                ev["args"] = args
            events.append(ev)
        for entry in timeline:
            ts = entry["t"] * 1e6
            for cname, delta in entry["counters"].items():
                events.append({"name": cname, "ph": "C", "pid": pid,
                               "tid": 0, "ts": round(ts, 3),
                               "args": {"value": delta}})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"schema": METRICS_SCHEMA}}

    def export_trace(self, path: str) -> None:
        try:
            with open(path, "w") as fh:
                json.dump(self.chrome_trace(), fh)
        except OSError as e:
            from .log import log_warning
            log_warning(f"could not write trace JSON to {path}: {e}")

    def maybe_export_trace(self) -> None:
        """Write the Chrome trace to ``LIGHTGBM_TPU_TRACE_JSON`` if set.
        Called at the end of training and (backstop) at process exit."""
        path = os.environ.get("LIGHTGBM_TPU_TRACE_JSON")
        if path:
            self.export_trace(path)

    def metrics_blob(self) -> Dict[str, Any]:
        """The versioned JSON blob written by the CLI ``metrics_out=``
        parameter and embedded in bench results."""
        blob = {"schema": METRICS_SCHEMA}
        blob.update(self.stats())
        return blob

    # -------------------------------------------------------------- reset
    def reset(self) -> None:
        """Clear all recorded data (not the config level or installed
        listeners) and re-zero the time base; also resets the network
        collective counters so a measurement window starts clean."""
        import sys
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._spans.clear()
            self._spans_recorded = 0
            self._timeline.clear()
            self._iter_snapshot = {}
            self._epoch = time.perf_counter()
            self._writer = None
            self._race_flagged = False
        net = sys.modules.get("lightgbm_tpu.parallel.network")
        if net is not None and hasattr(net, "reset_collective_stats"):
            net.reset_collective_stats()
        self.refresh_level()


TELEMETRY = TelemetryRegistry()

# an exception that unwinds past the training loop must not lose an
# almost-complete trace: export whatever was recorded at process exit
atexit.register(TELEMETRY.maybe_export_trace)
