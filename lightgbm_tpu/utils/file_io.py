"""Virtual file IO seam — pluggable readers/writers by URI scheme.

The reference abstracts file access behind VirtualFileReader /
VirtualFileWriter (include/LightGBM/utils/file_io.h:20, src/io/
file_io.cpp:19,60) so an HDFS build can swap the transport without
touching the loaders.  The TPU-native equivalent is scheme-dispatching
``open``: local paths go straight to the builtin, and any registered
scheme (``hdfs://``, ``gs://``, ...) routes to its handler.  Handlers
are opener callables ``(path, mode) -> file object``, so fsspec-style
libraries plug in with one line:

    from lightgbm_tpu.utils import file_io
    file_io.register_scheme("gs", gcsfs.GCSFileSystem().open)

Nothing in the repo hard-depends on a remote FS (the test image has no
egress); an unregistered scheme raises a clear error instead of a
cryptic builtin-open failure.
"""

from __future__ import annotations

from typing import Callable, Dict

from .log import LightGBMError

_SCHEME_HANDLERS: Dict[str, Callable] = {}


def register_scheme(scheme: str, opener: Callable) -> None:
    """Register ``opener(path, mode)`` for ``scheme://`` URIs."""
    _SCHEME_HANDLERS[scheme.lower()] = opener


def unregister_scheme(scheme: str) -> None:
    _SCHEME_HANDLERS.pop(scheme.lower(), None)


def uri_scheme(path: str) -> str:
    """'hdfs://nn/x' -> 'hdfs'; plain paths (and Windows drives) -> ''."""
    idx = path.find("://")
    if idx <= 1:      # -1 = no scheme; 0/1 also covers 'C:/...' drives
        return ""
    return path[:idx].lower()


def register_fsspec(scheme: str, **fs_kwargs) -> None:
    """Back ``scheme://`` with an fsspec filesystem — the concrete
    transport behind the seam (the reference ships HDFS read/write the
    same way, src/io/file_io.cpp:60,99; here one registration line
    covers gs/s3/hdfs/memory/... for whatever fsspec drivers are
    installed)."""
    import fsspec
    fs = fsspec.filesystem(scheme, **fs_kwargs)
    register_scheme(scheme, lambda path, mode="r": fs.open(path, mode))


def open_file(path: str, mode: str = "r"):
    """Open ``path`` through the scheme seam (VirtualFile{Reader,Writer}
    ::Make equivalent: file_io.cpp:19,60 picks the transport from the
    filename; here the registry does).  Unregistered schemes fall back
    to fsspec when it knows the protocol, so ``gs://...`` works out of
    the box wherever gcsfs/s3fs/... are installed."""
    scheme = uri_scheme(path)
    if not scheme:
        return open(path, mode)
    opener = _SCHEME_HANDLERS.get(scheme)
    if opener is None:
        try:
            import fsspec
            from fsspec.registry import known_implementations
            if scheme in known_implementations or \
                    scheme in fsspec.available_protocols():
                register_fsspec(scheme)
                opener = _SCHEME_HANDLERS[scheme]
        except LightGBMError:
            raise
        except Exception:
            opener = None
    if opener is None:
        raise LightGBMError(
            f"No file-IO handler registered for scheme '{scheme}://' "
            f"({path}); register one with "
            f"lightgbm_tpu.utils.file_io.register_scheme")
    return opener(path, mode)


def atomic_write_text(path: str, text: str) -> None:
    """Write ``text`` to ``path`` so readers never see a torn file.

    Local paths get the classic durable rename: write a sibling temp
    file, flush + fsync, then ``os.replace`` onto the destination — a
    crash mid-write leaves either the old file or nothing, never a
    truncated model.  Scheme'd paths (``gs://`` ...) fall back to a
    plain ``open_file`` write; object stores commit on close, so the
    torn-file window does not exist there in the first place.
    """
    import os
    if uri_scheme(path):
        with open_file(path, "w") as fh:
            fh.write(text)
        return
    d = os.path.dirname(os.path.abspath(path))
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w") as fh:
            fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def exists(path: str) -> bool:
    """Existence probe that understands registered schemes (remote
    handlers are queried by opening; local paths use os.path).

    A handler may signal a missing object with any exception type
    (KeyError from an in-memory store, botocore errors, ...), so
    anything the opener raises — except an unregistered-scheme
    LightGBMError — reads as "does not exist"."""
    import os
    if not uri_scheme(path):
        return os.path.exists(path)
    try:
        with open_file(path, "rb"):
            return True
    except LightGBMError:
        raise
    except (FileNotFoundError, KeyError, IndexError):
        return False                 # not-found-shaped: quietly missing
    except Exception as e:
        # auth/network failures must not masquerade silently as a
        # missing file — report what actually happened, then treat as
        # missing so the caller's diagnostic still names the path
        from .log import log_warning
        log_warning(f"treating {path} as missing after "
                    f"{type(e).__name__}: {e}")
        return False
