from .log import (LightGBMError, Timer, check, log_debug, log_fatal, log_info,
                  log_warning, register_log_callback, set_verbosity)

__all__ = ["LightGBMError", "Timer", "check", "log_debug", "log_fatal",
           "log_info", "log_warning", "register_log_callback", "set_verbosity"]
