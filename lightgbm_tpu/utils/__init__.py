from .log import (LightGBMError, Timer, check, log_debug, log_fatal, log_info,
                  log_warning, register_log_callback, set_verbosity)

__all__ = ["LightGBMError", "Timer", "check", "log_debug", "log_fatal",
           "log_info", "log_warning", "register_log_callback",
           "set_verbosity", "cpu_subprocess_env",
           "enable_jax_compilation_cache", "maybe_enable_compile_cache"]


def cpu_subprocess_env(n_virtual_devices: int = 0) -> dict:
    """Environment for a child process that must run JAX on the CPU
    platform, immune to the axon TPU sitecustomize (which registers the
    TPU backend at interpreter start and pins JAX_PLATFORMS).

    The child should additionally run ``jax.config.update('jax_platforms',
    'cpu')`` before first backend use.  Shared by bench.py and
    __graft_entry__.dryrun_multichip so the recipe lives in one place.
    """
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip axon sitecustomize registration
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    if n_virtual_devices > 0:
        flags = (flags + " --xla_force_host_platform_device_count="
                 f"{n_virtual_devices}").strip()
    env["XLA_FLAGS"] = flags
    return env


def enable_jax_compilation_cache(repo_root: str | None = None,
                                 cache_dir: str | None = None) -> None:
    """Persistent executable cache: the ~3min remote TPU compile amortizes
    across bench/probe runs instead of recurring (the driver's bench and
    the perf tools share one cache under <repo>/.jax_cache).  An explicit
    ``cache_dir`` overrides the in-repo default (the CLI/engine
    ``compile_cache=`` knob routes a path here)."""
    import os

    import jax
    if cache_dir is None:
        if repo_root is None:
            # utils/ -> lightgbm_tpu/ -> repo root
            repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        cache_dir = os.path.join(repo_root, ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache EVERY executable: the warmup budget is dominated by many
        # medium-size compiles (bucketed kernels, fused_step variants),
        # and the round-4 on-chip runs still paid ~200s warm — so no
        # compile is too small to keep
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # jax checks-and-latches cache usability at the FIRST compile of
        # the process and initializes the cache at most once, so enabling
        # (or re-pointing) it after any earlier compile would silently do
        # nothing without a reset here
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — the cache is an optimization only
        pass


def maybe_enable_compile_cache(config) -> None:
    """Honor the ``compile_cache=`` config knob: off by default; a truthy
    value ("1"/"true"/"on"/"default") turns on the persistent XLA
    compilation cache at its in-repo default location, any other
    non-empty string is taken as the cache directory.  Hits and misses
    land in the compile/cache_hits|cache_misses telemetry counters (the
    jax monitoring bridge already subscribes to them)."""
    cc = str(getattr(config, "compile_cache", "") or "").strip()
    if not cc or cc.lower() in ("0", "false", "off", "no"):
        return
    if cc.lower() in ("1", "true", "on", "yes", "default"):
        enable_jax_compilation_cache()
    else:
        enable_jax_compilation_cache(cache_dir=cc)
