"""Resumable training snapshots: model file + exact-state sidecar.

The reference CLI's ``save_period`` snapshots (config.h) are plain
model files — enough to *continue* training, not to resume it
bit-exactly: reloading a model text replays f64 per-tree deltas into
the f32 score buffer (not the bytes the run actually held), and the
PRNG key / bagging / feature-sampling RNG streams are not
fast-forwarded.  Each snapshot here therefore pairs the model file
(``<output_model>.snapshot_iter_N``) with a ``.state.npz`` sidecar
holding the exact device/host training state at iteration N:

  * the f32 ``train_score`` buffer and the JAX PRNG key, byte-for-byte
  * the bagging mask and both host RNG (MT19937) states
  * the per-valid-set score buffers

Resume (cli.py, ``resume=true``) loads the trees through the existing
``load_trees_into`` path, then overwrites the replayed approximate
state with the sidecar's exact one — iterations N.. then proceed with
the same key stream, scores and masks as an uninterrupted run, so the
final model file is byte-identical.

Every write is atomic (sibling tmp + ``os.replace``), the sidecar is
written BEFORE the model text, and discovery requires BOTH files: a
crash at any point mid-snapshot leaves the previous snapshot fully
discoverable and never a torn file.
"""

from __future__ import annotations

import os
import re
from typing import Optional, Tuple

import numpy as np

from .file_io import atomic_write_text
from .log import LightGBMError, log_warning

STATE_SUFFIX = ".state.npz"
STATE_VERSION = 1

_SNAP_RE = re.compile(r"\.snapshot_iter_(\d+)$")


def state_path(snapshot_file: str) -> str:
    return snapshot_file + STATE_SUFFIX


def _atomic_savez(path: str, **arrays) -> None:
    d = os.path.dirname(os.path.abspath(path))
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrays)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _rng_state(rng: np.random.RandomState):
    name, keys, pos, has_gauss, cached = rng.get_state()
    if name != "MT19937":          # pragma: no cover - numpy default
        raise LightGBMError(f"unsupported RNG {name} in snapshot state")
    return (np.asarray(keys, dtype=np.uint32),
            np.asarray([pos, has_gauss], dtype=np.int64),
            np.asarray([cached], dtype=np.float64))


def _set_rng_state(rng: np.random.RandomState, keys, meta, cached) -> None:
    rng.set_state(("MT19937", np.asarray(keys, dtype=np.uint32),
                   int(meta[0]), int(meta[1]), float(cached[0])))


def save_snapshot(gbdt, snapshot_file: str, model_text: str) -> None:
    """Write one resumable snapshot: exact-state sidecar first, then the
    model text — both atomically.  ``gbdt.iter_`` must equal the
    iteration the snapshot file name claims.

    The write is retried once through the shared retry policy
    (``utils/retry.py``): a transient IO failure (NFS hiccup, full-then-
    pruned disk) costs a ``snapshot_retry`` fault event instead of the
    snapshot; a persistent one propagates ``OSError`` to the caller,
    whose job is to decide whether a lost snapshot aborts the run (the
    CLI continues).  The deterministic ``snapshot/io`` fault site is
    probed per attempt."""
    bag_keys, bag_meta, bag_cached = _rng_state(gbdt._bag_rng)
    feat_keys, feat_meta, feat_cached = _rng_state(gbdt._feat_rng)
    arrays = {
        "version": np.asarray(STATE_VERSION, dtype=np.int64),
        "iteration": np.asarray(gbdt.iter_, dtype=np.int64),
        "train_score": np.asarray(gbdt.train_score),
        "prng_key": np.asarray(gbdt._key),
        "bag_weight": np.asarray(gbdt.bag_weight),
        "init_scores": np.asarray(gbdt.init_scores, dtype=np.float64),
        "bag_keys": bag_keys, "bag_meta": bag_meta,
        "bag_cached": bag_cached,
        "feat_keys": feat_keys, "feat_meta": feat_meta,
        "feat_cached": feat_cached,
        "valid_count": np.asarray(len(gbdt.valid_scores), dtype=np.int64),
    }
    for i, vs in enumerate(gbdt.valid_scores):
        arrays[f"valid_score_{i}"] = np.asarray(vs, dtype=np.float64)

    def _write():
        from .faults import FAULTS
        FAULTS.maybe_raise(
            "snapshot/io",
            lambda site: OSError(f"injected IO failure at {site}"))
        _atomic_savez(state_path(snapshot_file), **arrays)
        atomic_write_text(snapshot_file, model_text)

    def _on_retry(_k, e):
        from .telemetry import TELEMETRY
        TELEMETRY.fault_event("snapshot_retry", site="snapshot/io",
                              iteration=int(gbdt.iter_), detail=str(e))

    from .retry import retry_call
    retry_call(_write, attempts=2, backoff_s=0.02,
               fatal=(LightGBMError,), on_retry=_on_retry,
               label="snapshot_write")
    # narrate the durable point into the run-health stream: a live
    # monitor can tell how much work a kill would lose
    from .telemetry import HEALTH
    if HEALTH.active:
        HEALTH.record("snapshot", {
            "iter": int(gbdt.iter_),
            "file": os.path.basename(snapshot_file)})


def restore_snapshot_state(gbdt, snapshot_file: str) -> int:
    """Overwrite a tree-loaded GBDT's replayed (approximate) state with
    the sidecar's exact one; returns the snapshot iteration.  Call AFTER
    ``load_trees_into`` and after the valid sets are attached."""
    import jax.numpy as jnp
    with np.load(state_path(snapshot_file)) as data:
        it = int(data["iteration"])
        if gbdt.iter_ != it:
            raise LightGBMError(
                f"snapshot state at iteration {it} does not match the "
                f"loaded model's {gbdt.iter_} iterations "
                f"({snapshot_file})")
        gbdt.train_score = jnp.asarray(data["train_score"])
        gbdt._key = jnp.asarray(data["prng_key"])
        gbdt.bag_weight = jnp.asarray(data["bag_weight"])
        _set_rng_state(gbdt._bag_rng, data["bag_keys"], data["bag_meta"],
                       data["bag_cached"])
        _set_rng_state(gbdt._feat_rng, data["feat_keys"],
                       data["feat_meta"], data["feat_cached"])
        # init_scores stay [0.0]: the loaded first tree already carries
        # the folded bias (serialization._tree_for_save), and train_score
        # above includes it once — restoring the original values would
        # fold it a second time on the next save.  The sidecar keeps them
        # for inspection only.
        nv = int(data["valid_count"])
        restored = (nv == len(gbdt.valid_scores))
        if restored:
            for i in range(nv):
                saved = np.asarray(data[f"valid_score_{i}"])
                if saved.shape != np.shape(gbdt.valid_scores[i]):
                    restored = False
                    break
                gbdt.valid_scores[i] = saved.copy()
    if not restored and gbdt.valid_sets:
        # the valid sets changed since the snapshot: fall back to the
        # replay path (approximate but complete)
        log_warning("snapshot valid-set state does not match the current "
                    "valid sets; recomputing valid scores by replay")
        gbdt.valid_scores = [
            np.asarray(gbdt._replay_model_scores(vset), dtype=np.float64)
            for _, vset in gbdt.valid_sets]
    # CEGB coupled penalties track which features the model split on;
    # rebuild that from the loaded trees
    if gbdt.grower_params.use_cegb_coupled:
        gbdt._note_trees(gbdt.models)
    return it


def find_latest_snapshot(output_model: str) -> Tuple[Optional[str], int]:
    """Newest resumable snapshot for ``output_model``: the highest
    ``.snapshot_iter_N`` that has BOTH the model file and its state
    sidecar.  Returns (path, N), or (None, 0) when none qualify."""
    d = os.path.dirname(os.path.abspath(output_model))
    base = os.path.basename(output_model)
    best: Tuple[Optional[str], int] = (None, 0)
    if not os.path.isdir(d):
        return best
    for name in os.listdir(d):
        if not name.startswith(base + ".snapshot_iter_"):
            continue
        m = _SNAP_RE.search(name)
        if m is None:
            continue
        path = os.path.join(d, name)
        if not os.path.exists(state_path(path)):
            continue                 # torn snapshot: model without state
        n = int(m.group(1))
        if n > best[1]:
            best = (path, n)
    return best


def prune_snapshots(output_model: str, keep: int) -> None:
    """Retention: delete all but the newest ``keep`` snapshots (model +
    sidecar).  ``keep <= 0`` keeps everything (the reference
    save_period behavior)."""
    if keep <= 0:
        return
    d = os.path.dirname(os.path.abspath(output_model))
    base = os.path.basename(output_model)
    found = []
    if not os.path.isdir(d):
        return
    for name in os.listdir(d):
        if not name.startswith(base + ".snapshot_iter_"):
            continue
        m = _SNAP_RE.search(name)
        if m is not None:
            found.append((int(m.group(1)), os.path.join(d, name)))
    found.sort(reverse=True)
    for _, path in found[keep:]:
        for victim in (path, state_path(path)):
            try:
                if os.path.exists(victim):
                    os.remove(victim)
            except OSError as e:
                log_warning(f"could not prune snapshot {victim}: {e}")
