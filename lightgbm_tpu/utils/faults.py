"""Deterministic fault injection for robustness testing.

The reference LightGBM survives long runs with ``save_period``
snapshots and socket retry; to *test* the equivalent recovery paths
here (non-finite rollback, OOM-degrading chunk retry, snapshot resume)
we need failures that fire at a chosen site and iteration,
deterministically, from the environment — without littering the hot
path with conditionals.

Spec grammar (env ``LIGHTGBM_TPU_FAULTS`` or config
``fault_injection``), comma-separated::

    SITE[@START][xCOUNT]

``SITE`` is a registered site name (``chunk/oom``, ``grad/nonfinite``,
``snapshot/io``, ``train/kill``, ``collective/allgather``,
``collective/reduce_scatter``, ``collective/barrier``, ``dist/init``,
``dist/preempt``, ``oocore/h2d``, ``oocore/admit``, ``serve/swap``,
``serve/shed``, ``serve/refit``, ``serve/oom``).  ``@START``
is the 0-based occurrence (or explicit index, e.g. iteration) at which
the fault starts firing; default 0.  ``xCOUNT`` is how many
occurrences fire; default 1, ``x*`` means every occurrence from START
on.  Examples::

    chunk/oom                  # first chunk dispatch raises OOM once
    grad/nonfinite@3           # poison scores at iteration 3
    snapshot/io@1x2            # 2nd and 3rd snapshot writes fail
    train/kill@4               # kill the CLI loop after iteration 4
    chunk/oom@0x*              # every chunk dispatch OOMs (never heals)

Mirroring telemetry level 0, a disabled registry costs one truthiness
check per site probe (``if not self._sites: return False``).  Sites
count occurrences per-site: each ``check(site)`` call without an
explicit ``n=`` advances that site's occurrence counter, so ``@START``
means "the START-th time this site is reached".  Callers that have a
natural index (the boosting iteration) pass ``n=`` instead and the
spec's ``@START`` compares against that index directly.

The registry is process-global (``FAULTS``), configured from the env
at import and re-configured (env spec + config spec merged, counters
reset) whenever a training run binds its config — the same lifecycle
as ``TELEMETRY.set_config_level``.
"""

import os
import re
import threading

ENV_FAULTS = "LIGHTGBM_TPU_FAULTS"

# sites the training stack probes; parse rejects unknown names so a
# typo in the env fails loudly instead of silently injecting nothing
KNOWN_SITES = frozenset([
    "chunk/oom",         # chunk dispatch raises RESOURCE_EXHAUSTED
    "grad/nonfinite",    # scores poisoned with NaN before the boost step
    "snapshot/io",       # snapshot write raises OSError
    "train/kill",        # CLI training loop dies between iterations
    "collective/allgather",  # one attempt of allgather_obj fails
    "collective/reduce_scatter",  # grower collective dispatch fails
    "collective/barrier",    # cross-host barrier entry fails
    "dist/init",         # jax.distributed.initialize handshake fails
    "dist/preempt",      # host receives a preemption notice (SIGTERM)
    "dist/slow",         # rank sleeps before collective entry (straggler)
    "oocore/h2d",        # bin-matrix host->device transfer raises OOM
    "oocore/admit",      # admission check decides the matrix won't fit
    "serve/compile",     # serve executable build fails (named give-up)
    "serve/enqueue",     # serve request rejected at enqueue
    "serve/swap",        # hot-swap flip aborts; the old model keeps serving
    "serve/shed",        # submit is force-shed as if the queue were full
    "serve/refit",       # one refit-loop attempt fails (loop continues)
    "serve/oom",         # serve dispatch raises RESOURCE_EXHAUSTED
    "sched/slice",       # one scheduler time slice fails before dispatch
    "sched/snapshot",    # preemption snapshot write fails
])


class InjectedFault(RuntimeError):
    """Raised by an injected fault site (never by real failures)."""

    def __init__(self, site: str, message: str = ""):
        self.site = site
        super().__init__(message or f"injected fault at {site}")


def oom_error(site: str) -> InjectedFault:
    """An injected error shaped like an XLA allocation failure.

    The message carries the ``RESOURCE_EXHAUSTED`` marker the chunk
    retry path matches on, so injected and real OOMs take the same
    recovery branch.
    """
    return InjectedFault(
        site, f"RESOURCE_EXHAUSTED: injected device OOM at {site} "
              "(fault injection)")


_SPEC_RE = re.compile(r"^(?P<name>[^@]+?)(?:@(?P<start>\d+))?"
                      r"(?:x(?P<count>\d+|\*))?$")


class _Site:
    __slots__ = ("name", "start", "count", "seen", "fired")

    def __init__(self, name, start, count):
        self.name = name
        self.start = start          # first occurrence index that fires
        self.count = count          # None = unlimited
        self.seen = 0               # occurrences observed so far
        self.fired = 0              # occurrences that fired

    def hit(self, n):
        """Advance and decide whether occurrence ``n`` fires."""
        if n is None:
            n = self.seen
            self.seen += 1
        if n < self.start:
            return False
        if self.count is not None and self.fired >= self.count:
            return False
        self.fired += 1
        return True


def parse_spec(spec: str) -> dict:
    """Parse a fault spec string into {site: (start, count|None)}.

    Raises ``ValueError`` on grammar errors or unknown site names.
    """
    out = {}
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        m = _SPEC_RE.match(tok)
        if not m:
            raise ValueError(f"bad fault spec token: {tok!r} "
                             "(expected SITE[@START][xCOUNT])")
        name = m.group("name")
        if name not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {name!r}; known sites: "
                + ", ".join(sorted(KNOWN_SITES)))
        start = int(m.group("start") or 0)
        count = m.group("count")
        count = None if count == "*" else int(count or 1)
        out[name] = (start, count)
    return out


class FaultRegistry:
    """Process-global registry of armed fault sites."""

    def __init__(self):
        self._lock = threading.RLock()
        self._sites = {}
        self.configure()

    # -------------------------------------------------- configuration
    def configure(self, config_spec: str = "") -> None:
        """(Re)arm from the env + an optional config spec.

        The env spec wins on per-site conflicts (same precedence as
        ``LIGHTGBM_TPU_TELEMETRY`` over ``telemetry_level``).  All
        occurrence counters reset, so each training run replays its
        faults deterministically.
        """
        merged = dict(parse_spec(config_spec))
        merged.update(parse_spec(os.environ.get(ENV_FAULTS, "")))
        with self._lock:
            self._sites = {name: _Site(name, start, count)
                           for name, (start, count) in merged.items()}

    # ------------------------------------------------------- probing
    @property
    def enabled(self) -> bool:
        """True when any site is armed (one truthiness check; lets hot
        paths skip per-occurrence probing loops entirely)."""
        return bool(self._sites)

    def check(self, site: str, n=None) -> bool:
        """True if ``site`` should fire on this occurrence.

        ``n`` pins the occurrence index (e.g. the boosting iteration);
        without it the site's own counter advances by one per call.
        A firing is recorded into telemetry as an ``injected`` fault
        event so recoveries are attributable in the metrics blob.
        """
        if not self._sites:
            return False
        with self._lock:
            entry = self._sites.get(site)
            if entry is None or not entry.hit(n):
                return False
        from .telemetry import TELEMETRY
        TELEMETRY.fault_event("injected", site=site,
                              detail=(f"n={n}" if n is not None
                                      else f"occurrence={entry.seen - 1}"))
        return True

    def maybe_raise(self, site: str, exc_factory=None, n=None) -> None:
        """Raise the site's fault if armed for this occurrence."""
        if not self._sites:
            return
        if self.check(site, n=n):
            raise (exc_factory(site) if exc_factory is not None
                   else InjectedFault(site))

    # ----------------------------------------------------- inspection
    def armed(self) -> dict:
        """{site: {"start", "count", "seen", "fired"}} for tests/docs."""
        with self._lock:
            return {s.name: {"start": s.start, "count": s.count,
                             "seen": s.seen, "fired": s.fired}
                    for s in self._sites.values()}


FAULTS = FaultRegistry()
