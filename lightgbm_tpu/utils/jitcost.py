"""Cost-instrumented jit dispatch for the training hot-path seams.

``cost_jit(label, jitted)`` wraps an already-``jax.jit``-ed callable so
that the first dispatch at each input signature goes through the AOT
path (``jitted.lower(*args).compile()``): the resulting executable's
static XLA ``cost_analysis()`` — flops, bytes accessed, transcendentals
— is harvested ONCE into the telemetry registry under ``label``, and
the compiled executable itself is cached and used for every later call
at that signature, so nothing compiles twice.  Every dispatch bumps the
label's call count, which multiplies the per-call cost out into the
``cost`` section of the metrics blob (telemetry.stats()).

With measured device timing enabled (``device_timing=`` config knob /
``LIGHTGBM_TPU_DEVICE_TIMING`` env), each dispatch is additionally
timed wall-to-ready: the wrapper blocks on the returned buffers and
records the window into the telemetry ``timing`` section (per-label
count/total/mean/p50/p99 + the host gap between consecutive dispatches
of the same label).  ``block_until_ready`` only synchronizes — values,
and therefore models, are unchanged — but it does serialize the async
pipeline, so timing is an opt-in measurement mode, never a default.
Under an outer trace the tracer passthrough below returns before the
timing gate, so timing latches off exactly like the AOT fallback; with
timing off the extra cost is one attribute compare.

Gating and fallbacks keep the wrapper invisible when it cannot help:

  * telemetry level 0 — one attribute compare, then the plain jitted
    call (identical to the uninstrumented seam);
  * called under an outer trace (the fused/chunked paths close over the
    grower INSIDE a jit) — tracers pass straight through to the wrapped
    function, which inlines as usual;
  * keyword arguments, non-array leaves, or a backend/executable that
    rejects AOT compile or cost analysis — the plain jitted call, with
    the failure latched so it is not retried per iteration.

The wrapped callable's attributes (e.g. the parallel growers'
``_collective_kind`` tags) remain reachable through ``__getattr__``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

# sentinel distinct from None (None caches "AOT failed; use plain jit")
_UNSEEN = object()


def _leaf_sig(leaf) -> Optional[Tuple]:
    """Hashable signature of one flattened argument leaf, or None when
    the leaf is not a committed array-like (a varying Python scalar
    would otherwise mint a new executable per call).  Sharding is part
    of the signature: a compiled executable only accepts the shardings
    it was lowered with (the distributed learners call the same seams
    with mesh-sharded operands)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return None
    try:
        sharding = hash(getattr(leaf, "sharding", None))
    except TypeError:
        return None
    return (tuple(shape), str(dtype),
            bool(getattr(leaf, "weak_type", False)), sharding)


def harvest_cost(compiled) -> Dict[str, float]:
    """Normalize ``Compiled.cost_analysis()`` (a dict, or a list with
    one dict per module on older jax) into the keys the telemetry
    registry stores.  Also folds in ``memory_analysis()`` sizes when
    the executable exposes them (argument/output/temp bytes — the
    executable's working set, distinct from traffic)."""
    analysis = compiled.cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    analysis = analysis or {}
    out = {
        "flops": float(analysis.get("flops", 0.0)),
        "bytes_accessed": float(analysis.get("bytes accessed", 0.0)),
        "transcendentals": float(analysis.get("transcendentals", 0.0)),
    }
    try:
        mem = compiled.memory_analysis()
        out["temp_bytes"] = float(getattr(mem, "temp_size_in_bytes", 0.0))
        out["argument_bytes"] = float(
            getattr(mem, "argument_size_in_bytes", 0.0))
        out["output_bytes"] = float(
            getattr(mem, "output_size_in_bytes", 0.0))
    except Exception:
        pass
    return out


class CostJit:
    """See module docstring.  One instance per jit seam."""

    def __init__(self, label: str, jitted) -> None:
        self._label = label
        self._fn = jitted
        self._can_aot = hasattr(jitted, "lower")
        # signature -> compiled executable (None = AOT failed, use the
        # plain jitted dispatch for this signature)
        self._compiled: Dict[Any, Any] = {}

    def __getattr__(self, name: str):
        return getattr(self._fn, name)

    def _aot_compile(self, args, key):
        from .telemetry import TELEMETRY
        try:
            compiled = self._fn.lower(*args).compile()
            TELEMETRY.record_cost(self._label, harvest_cost(compiled))
        except Exception:
            compiled = None
        self._compiled[key] = compiled
        return compiled

    def __call__(self, *args, **kwargs):
        from .telemetry import TELEMETRY
        if TELEMETRY.level < 1 or not self._can_aot or kwargs:
            return self._fn(*args, **kwargs)
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sigs = []
        for leaf in leaves:
            if isinstance(leaf, jax.core.Tracer):
                # under an outer trace: inline into the caller's jaxpr
                return self._fn(*args)
            sig = _leaf_sig(leaf)
            if sig is None:
                return self._fn(*args)
            sigs.append(sig)
        key = (treedef, tuple(sigs))
        entry = self._compiled.get(key, _UNSEEN)
        if entry is _UNSEEN:
            entry = self._aot_compile(args, key)
        TELEMETRY.cost_call(self._label)
        if not TELEMETRY.timing_on:
            if entry is None:
                return self._fn(*args)
            try:
                return entry(*args)
            except (TypeError, ValueError):
                # executable rejected the call (e.g. a sharding/layout
                # facet the signature key missed) BEFORE running —
                # nothing was donated; latch plain-jit dispatch for
                # this signature
                self._compiled[key] = None
                return self._fn(*args)
        # measured dispatch timing: wall from dispatch to buffers ready
        # (the plain-jit fallback is a real dispatch too, so it is timed
        # under the same label)
        import time
        t0 = time.perf_counter()
        if entry is None:
            out = self._fn(*args)
        else:
            try:
                out = entry(*args)
            except (TypeError, ValueError):
                self._compiled[key] = None
                out = self._fn(*args)
        jax.block_until_ready(out)
        TELEMETRY.record_dispatch(self._label, t0, time.perf_counter())
        return out


def cost_jit(label: str, jitted) -> CostJit:
    """Wrap a jitted callable for per-label cost accounting."""
    return CostJit(label, jitted)
