"""Training callbacks.

Reference: python-package/lightgbm/callback.py — print_evaluation (:55),
record_evaluation (:80), reset_parameter (:107), early_stopping (:154).
The callback env protocol (CallbackEnv namedtuple) matches the reference so
user callbacks port unchanged.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List

from .utils.log import log_info, log_warning


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    last_seen = [-1]

    def _callback(env: CallbackEnv) -> None:
        if period <= 0 or not env.evaluation_result_list:
            return
        # fire when a period boundary was crossed since the previous call:
        # identical to (iteration + 1) % period == 0 under per-iteration
        # stepping, and never skips a boundary under chunked stepping,
        # where env.iteration advances several rounds at a time
        crossed = ((env.iteration + 1) // period
                   > (last_seen[0] + 1) // period)
        last_seen[0] = env.iteration
        if crossed:
            result = "\t".join(
                _format_eval_result(x, show_stdv)
                for x in env.evaluation_result_list)
            log_info(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    return _callback


log_evaluation = print_evaluation  # modern alias


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")
    eval_result.clear()

    def _init(env: CallbackEnv) -> None:
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for item in env.evaluation_result_list:
            data_name, eval_name, result = item[0], item[1], item[2]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal "
                        "num_boost_round")
                new_param = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_param = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are "
                                 "supported as a mapping from boosting round "
                                 "index to new parameter value")
            if new_param != env.params.get(key, None):
                new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    best_score: List = []
    best_iter: List = []
    best_score_list: List = []
    cmp_op: List = []
    enabled: List = [True]
    first_metric: List = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            log_warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        if verbose:
            log_info(f"Training until validation scores don't improve for "
                     f"{stopping_rounds} rounds")
        first_metric[0] = env.evaluation_result_list[0][1].split(" ")[-1]
        for eval_ret in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:  # higher is better
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _final_iteration_check(env, eval_name_splitted, i) -> None:
        if env.iteration == env.end_iteration - 1:
            if verbose:
                log_info("Did not meet early stopping. Best iteration is:\n"
                         f"[{best_iter[i] + 1}]\t"
                         + "\t".join(_format_eval_result(x)
                                     for x in best_score_list[i]))
            raise EarlyStopException(best_iter[i], best_score_list[i])

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, eval_ret in enumerate(env.evaluation_result_list):
            score = eval_ret[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            eval_name_splitted = eval_ret[1].split(" ")
            if first_metric_only and first_metric[0] != eval_name_splitted[-1]:
                continue
            if eval_ret[0] == "training":
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log_info("Early stopping, best iteration is:\n"
                             f"[{best_iter[i] + 1}]\t"
                             + "\t".join(_format_eval_result(x)
                                         for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            _final_iteration_check(env, eval_name_splitted, i)
    _callback.order = 30
    return _callback
