"""Multi-tenant training scheduler: N jobs time-sliced on one device set.

The scheduler composes the substrates that landed one PR at a time —
chunk-boundary draining, byte-exact snapshots, the jitcost working-set
estimate, the shared persistent compile cache, the health-stream writer
— into a training-as-a-service loop:

* **Admission** (:meth:`Scheduler.submit`): a job whose estimated
  working set (public ``estimate_working_set``) alone exceeds
  ``admit_fraction`` x the HBM budget is REJECTED with a named
  :class:`SchedAdmissionError` and a ``sched_admit`` record; an
  admitted job runs immediately when it fits next to the resident set,
  otherwise it queues.  Backends without allocator stats (CPU) skip
  the budget check unless an explicit ``hbm_budget_bytes`` is given.
* **Slicing** (:meth:`Scheduler.step`): the policy picks a runnable
  job and advances it one quantum of chunk dispatches; per-slice wall,
  measured device-seconds (``device_timing`` deltas, slice wall as the
  fallback weight) and telemetry-counter deltas are attributed to that
  job.  Making a job resident may preempt the least-recently-sliced
  resident tenant to a snapshot (``sched_preempt_job``).
* **Policies**: ``round_robin`` rotates tenants per quantum;
  ``fair`` is the deficit policy — always slice the runnable job with
  the least ``device_seconds / weight``.
* **Per-tenant fault isolation**: the ``sched/slice`` fault site is
  probed at every slice start (occurrence index = global slice count)
  and ``sched/snapshot`` before every preemption snapshot; one retry
  per incident, then the JOB fails — never the scheduler or siblings.
* **Health stream**: ``sched_start`` / ``sched_admit`` /
  ``sched_slice`` / ``sched_preempt_job`` / ``job_done`` /
  ``sched_summary`` JSONL records through the same never-torn
  O_APPEND writer training uses, tailed by ``tools/sched_monitor.py``.
* **Cross-tenant compile cache**: ``compile_cache=`` arms the
  persistent XLA cache before the first tenant compiles; cache-hit
  counter deltas observed in a slice of a job that started after
  another tenant already ran are counted as ``cross_job_cache_hits``
  (the proof that same-shape tenants share compilations).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from ..utils.faults import FAULTS, InjectedFault
from ..utils.log import LightGBMError, log_info, log_warning
from ..utils.telemetry import TELEMETRY, HealthStream
from .job import (DONE, FAILED, PENDING, PREEMPTED, RESIDENT, Job,
                  JobSpec)

POLICIES = ("round_robin", "fair")


class SchedAdmissionError(LightGBMError):
    """A submitted job's estimated working set can never fit the HBM
    budget; raised at submit, mirrored as a rejected ``sched_admit``."""


class Scheduler:
    """Cooperative time-slicing of independent training jobs on this
    process's device set.  Drive it with :meth:`submit` + :meth:`run`,
    or :meth:`step` for slice-at-a-time control (tests interleave
    :meth:`preempt_job` between steps)."""

    def __init__(self, quantum_chunks: int = 4,
                 policy: str = "round_robin",
                 max_jobs: int = 8,
                 health_out: str = "",
                 compile_cache: str = "",
                 admit_fraction: float = 0.9,
                 hbm_budget_bytes: Optional[int] = None,
                 fault_spec: str = ""):
        if policy not in POLICIES:
            raise LightGBMError(
                f"sched_policy must be one of {', '.join(POLICIES)}, "
                f"got {policy!r}")
        if quantum_chunks < 1:
            raise LightGBMError("sched_quantum_chunks must be >= 1")
        if max_jobs < 1:
            raise LightGBMError("sched_max_jobs must be >= 1")
        self.quantum_chunks = int(quantum_chunks)
        self.policy = policy
        self.max_jobs = int(max_jobs)
        self.admit_fraction = float(admit_fraction)
        self._explicit_budget = (int(hbm_budget_bytes)
                                 if hbm_budget_bytes else None)
        self.jobs: List[Job] = []
        self._by_name: Dict[str, Job] = {}
        self._rr_next = 0               # round-robin rotation pointer
        self._slice_idx = 0             # global slice counter (fault n=)
        self._last_sliced: Dict[str, int] = {}   # name -> slice index
        self._ran_before: List[str] = []         # first-slice order
        self.cross_job_cache_hits = 0
        self._fault_spec = str(fault_spec or "")
        from ..utils.faults import parse_spec
        self._fault_sites = frozenset(parse_spec(self._fault_spec))
        # sched/* incidents consumed at this layer: booster
        # construction re-arms the process-global registry (resetting
        # its fired counts), so count-limited sched specs are capped
        # here to keep per-slice injection deterministic across tenants
        self._faults_consumed: Dict[str, int] = {}
        self._stream = HealthStream()
        self._health_out = str(health_out or "")
        self._t0: Optional[float] = None
        self._closed = False
        if compile_cache:
            from ..utils import enable_jax_compilation_cache
            cc = str(compile_cache).strip()
            if cc.lower() in ("1", "true", "on", "yes", "default"):
                enable_jax_compilation_cache()
            else:
                enable_jax_compilation_cache(cache_dir=cc)
        if self._fault_spec:
            FAULTS.configure(self._fault_spec)

    @classmethod
    def from_config(cls, config, **overrides) -> "Scheduler":
        """Build from the ``sched_*`` knobs of a resolved Config (the
        CLI entry point and tools/submit_jobs.py route through here)."""
        kw: Dict[str, Any] = dict(
            quantum_chunks=int(config.sched_quantum_chunks),
            policy=str(config.sched_policy),
            max_jobs=int(config.sched_max_jobs),
            health_out=str(config.sched_health_out),
            compile_cache=str(getattr(config, "compile_cache", "") or ""),
            fault_spec=str(getattr(config, "fault_injection", "") or ""))
        kw.update(overrides)
        return cls(**kw)

    # -------------------------------------------------------------- budget
    def hbm_budget(self) -> Optional[int]:
        if self._explicit_budget is not None:
            return self._explicit_budget
        return TELEMETRY.device_memory_budget()

    def _limit(self) -> Optional[int]:
        budget = self.hbm_budget()
        return int(self.admit_fraction * budget) if budget else None

    def _resident(self) -> List[Job]:
        return [j for j in self.jobs if j.state == RESIDENT]

    def _resident_bytes(self) -> int:
        return sum(j.estimate for j in self._resident())

    # ------------------------------------------------------------ admission
    def submit(self, spec: JobSpec) -> Job:
        """Admission-check and enqueue one job.  Raises
        :class:`SchedAdmissionError` when the job can never fit the
        budget; otherwise the job is admitted (runs at its first slice)
        or queued behind the resident set."""
        job = Job(spec)
        if job.name in self._by_name:
            raise LightGBMError(
                f"duplicate scheduled job name {job.name!r}")
        for other in self.jobs:
            if str(other.config.output_model) == \
                    str(job.config.output_model):
                raise LightGBMError(
                    f"job {job.name}: output_model "
                    f"{job.config.output_model!r} collides with job "
                    f"{other.name}")
        from ..engine import estimate_working_set
        job.estimate = int(estimate_working_set(job.config,
                                                job.data_shape()))
        job.submit_t = time.perf_counter()
        limit = self._limit()
        if limit is not None and job.estimate > limit:
            budget = self.hbm_budget()
            detail = (f"rejected {job.name}: estimated working set "
                      f"~{job.estimate} B exceeds {limit} B "
                      f"({self.admit_fraction:.0%} of the {budget} B "
                      "HBM budget)")
            self._admit_record(job, "rejected", detail)
            raise SchedAdmissionError(
                f"sched admission: {detail}; shrink the job (max_bin, "
                "data) or raise the budget")
        # admitted = a slice can run it without preempting anyone;
        # queued = it contends with the live tenants (the scheduler
        # will preempt to make room when its turn comes)
        live = [j for j in self.jobs
                if j.state in (PENDING, RESIDENT, PREEMPTED)]
        live_bytes = sum(j.estimate for j in live)
        can_run = (len(live) < self.max_jobs
                   and (limit is None
                        or live_bytes + job.estimate <= limit))
        decision = "admitted" if can_run else "queued"
        if limit is None:
            detail = (f"{decision} {job.name} (~{job.estimate} B); no "
                      "allocator stats on this backend — budget check "
                      "skipped")
        else:
            detail = (f"{decision} {job.name}: working set "
                      f"~{job.estimate} B, live "
                      f"~{live_bytes} B of {limit} B")
        self._admit_record(job, decision, detail)
        self.jobs.append(job)
        self._by_name[job.name] = job
        return job

    def _admit_record(self, job: Job, decision: str, detail: str) -> None:
        TELEMETRY.fault_event("sched_admit", site="sched/admit",
                              iteration=self._slice_idx, detail=detail)
        TELEMETRY.counter_add(f"sched/admit_{decision}")
        self._record("sched_admit", {
            "job": job.name, "decision": decision,
            "estimate_bytes": int(job.estimate), "detail": detail})
        (log_warning if decision == "rejected" else log_info)(
            f"sched admission: {detail}")

    # --------------------------------------------------------------- stream
    def _open_stream(self) -> None:
        if self._t0 is not None:
            return
        self._t0 = time.perf_counter()
        if self._health_out:
            budget = self.hbm_budget()
            self._stream.open(self._health_out, meta={
                "stream": "sched",
                "policy": self.policy,
                "quantum_chunks": self.quantum_chunks,
                "max_jobs": self.max_jobs,
                "admit_fraction": self.admit_fraction,
                "hbm_budget_bytes": (int(budget) if budget else None),
            }, start_kind="sched_start")

    def _record(self, kind: str,
                fields: Optional[Dict[str, Any]] = None) -> None:
        self._open_stream()
        if self._stream.active:
            self._stream.record(kind, fields)

    # --------------------------------------------------------------- faults
    def _probe(self, site: str) -> None:
        """Probe a sched fault site at the global slice index.  Every
        tenant booster construction re-arms the process-global registry
        from the TENANT's (empty) fault spec, wiping the scheduler's —
        so the scheduler restores its own spec before probing, and caps
        count-limited specs at this layer (``_faults_consumed``; the
        registry's own fired counters reset on every re-arm).  Pinned
        ``n`` keeps the re-arm deterministic: a site fires iff
        n >= start, up to its count, regardless of re-arm churn."""
        if site in self._fault_sites and site not in FAULTS.armed():
            FAULTS.configure(self._fault_spec)
        if not FAULTS.enabled:
            return
        armed = FAULTS.armed().get(site)
        if armed is None:
            return
        count = armed.get("count")
        if count is not None and \
                self._faults_consumed.get(site, 0) >= count:
            return
        try:
            FAULTS.maybe_raise(site, n=self._slice_idx)
        except InjectedFault:
            self._faults_consumed[site] = \
                self._faults_consumed.get(site, 0) + 1
            raise

    # ------------------------------------------------------------ residency
    def _make_room_for(self, job: Job) -> bool:
        """Preempt least-recently-sliced residents until ``job`` fits
        the resident set (count and byte caps).  True when it fits."""
        limit = self._limit()

        def fits() -> bool:
            return (len(self._resident()) < self.max_jobs
                    and (limit is None
                         or self._resident_bytes() + job.estimate
                         <= limit))

        while not fits():
            victims = [j for j in self._resident() if j is not job]
            if not victims:
                return False
            victim = min(victims,
                         key=lambda j: self._last_sliced.get(j.name, -1))
            self.preempt_job(victim.name, reason="make room for "
                             f"{job.name}")
            if victim.state == FAILED:
                continue        # snapshot failed; its estimate is freed
        return True

    def preempt_job(self, name: str, reason: str = "explicit") -> None:
        """Deschedule one tenant to a byte-exact snapshot (its next
        slice resumes from it).  A ``sched/snapshot`` injection gets
        one retry; a second failure fails the JOB only."""
        job = self._by_name[name]
        if job.state not in (RESIDENT,):
            return
        snap = None
        for attempt in (0, 1):
            try:
                self._probe("sched/snapshot")
                snap = job.preempt()
                break
            except Exception as e:
                TELEMETRY.fault_event(
                    "sched_snapshot_fault", site="sched/snapshot",
                    iteration=self._slice_idx,
                    detail=f"job {job.name} attempt {attempt}: {e}")
                if attempt == 0:
                    job.slice_retries += 1
                    continue
                job.fail(e)
                self._record("sched_preempt_job", {
                    "job": job.name, "reason": reason,
                    "iter": int(job.iters_done), "failed": True,
                    "error": job.error})
                return
        self._record("sched_preempt_job", {
            "job": job.name, "reason": reason,
            "iter": int(job.iters_done),
            "snapshot": (os.path.basename(snap) if snap else None)})
        TELEMETRY.counter_add("sched/preemptions")

    # -------------------------------------------------------------- picking
    def _runnable(self) -> List[Job]:
        return [j for j in self.jobs
                if j.state in (PENDING, RESIDENT, PREEMPTED)]

    def _pick(self) -> Optional[Job]:
        runnable = self._runnable()
        if not runnable:
            return None
        if self.policy == "fair":
            return min(runnable,
                       key=lambda j: (j.device_s / j.weight,
                                      self._last_sliced.get(j.name, -1)))
        # round_robin: next unfinished job at or after the pointer, in
        # submit order
        order = [j for j in self.jobs if j in runnable]
        for off in range(len(self.jobs)):
            cand = self.jobs[(self._rr_next + off) % len(self.jobs)]
            if cand in order:
                self._rr_next = (self.jobs.index(cand) + 1) \
                    % len(self.jobs)
                return cand
        return None

    # --------------------------------------------------------------- slicing
    def step(self) -> Optional[Job]:
        """Run one time slice: pick a tenant, give it a quantum of
        chunk dispatches, attribute the slice's wall/device-seconds/
        counter deltas to it.  Returns the sliced job, or None when no
        job is runnable (all done/failed)."""
        self._open_stream()
        job = self._pick()
        if job is None:
            return None
        if not self._make_room_for(job):
            # can't fit even after preempting everyone else: the job
            # was admissible alone, so this is transient only when
            # another tenant cannot be preempted; fail it loudly
            job.fail(LightGBMError(
                f"job {job.name} (~{job.estimate} B) cannot fit the "
                "resident budget even alone"))
            return job
        n_slice = self._slice_idx
        self._slice_idx += 1
        if job.first_slice_t is None:
            job.first_slice_t = time.perf_counter()
        counters0 = dict(TELEMETRY.stats()["counters"])
        dev0 = TELEMETRY.dispatch_seconds_total()
        wall0 = time.perf_counter()
        status = "running"
        try:
            try:
                self._probe("sched/slice")
            except InjectedFault as e:
                # retry-once at the slice boundary: nothing was
                # dispatched yet, so the job state is untouched
                job.slice_retries += 1
                TELEMETRY.counter_add("sched/slice_retries")
                TELEMETRY.fault_event(
                    "sched_slice_fault", site="sched/slice",
                    iteration=n_slice,
                    detail=f"job {job.name} retry after: {e}")
                self._probe("sched/slice")
            status = job.run_chunks(self.quantum_chunks)
        except Exception as e:
            job.fail(e)
            status = FAILED
            TELEMETRY.fault_event(
                "sched_slice_fault", site="sched/slice",
                iteration=n_slice,
                detail=f"job {job.name} failed: {e}")
        wall = time.perf_counter() - wall0
        dev = TELEMETRY.dispatch_seconds_total() - dev0
        counters1 = TELEMETRY.stats()["counters"]
        deltas = {k: int(v - counters0.get(k, 0))
                  for k, v in counters1.items()
                  if v != counters0.get(k, 0)}
        job.slices += 1
        job.wall_s += wall
        # fairness weight: measured device-seconds when device_timing
        # is on, slice wall otherwise (documented fallback)
        job.device_s += dev if dev > 0 else wall
        for k, v in deltas.items():
            job.counters[k] = job.counters.get(k, 0) + v
        hits = deltas.get("compile/cache_hits", 0)
        if hits > 0 and any(n != job.name for n in self._ran_before):
            self.cross_job_cache_hits += hits
            TELEMETRY.counter_add("sched/cross_job_cache_hits", hits)
        if job.name not in self._ran_before:
            self._ran_before.append(job.name)
        self._last_sliced[job.name] = n_slice
        rec: Dict[str, Any] = {
            "job": job.name, "slice": n_slice, "status": status,
            "iter": int(job.iters_done),
            "total": job.total_iterations,
            "wall_s": round(wall, 6),
            "device_s": round(dev if dev > 0 else wall, 6),
        }
        if job.last_eval:
            rec["metrics"] = dict(job.last_eval)
        self._record("sched_slice", rec)
        TELEMETRY.counter_add("sched/slices")
        if status == DONE:
            self._record("job_done", {
                "job": job.name, "iter": int(job.iters_done),
                "slices": job.slices,
                "wall_s": round(job.wall_s, 6),
                "device_s": round(job.device_s, 6),
                "queue_wait_s": round(job.queue_wait_s, 6),
                "preemptions": job.preemptions,
                "model": os.path.basename(
                    str(job.config.output_model))})
            TELEMETRY.counter_add("sched/jobs_done")
        elif status == FAILED:
            self._record("job_done", {
                "job": job.name, "iter": int(job.iters_done),
                "failed": True, "error": job.error})
            TELEMETRY.counter_add("sched/jobs_failed")
        return job

    # ------------------------------------------------------------------ run
    def run(self, max_slices: Optional[int] = None) -> Dict[str, Any]:
        """Slice until every job is done or failed (or ``max_slices``
        elapsed — the scheduler stays resumable), then write the
        ``sched_summary`` record and return it."""
        self._open_stream()
        n = 0
        while self.step() is not None:
            n += 1
            if max_slices is not None and n >= max_slices:
                break
        return self.close()

    def summary(self) -> Dict[str, Any]:
        total_dev = sum(j.device_s for j in self.jobs) or 1.0
        per_job = {}
        for j in self.jobs:
            per_job[j.name] = {
                "state": j.state,
                "iterations": int(j.iters_done),
                "slices": j.slices,
                "wall_s": round(j.wall_s, 6),
                "device_s": round(j.device_s, 6),
                "share": round(j.device_s / total_dev, 6),
                "weight": j.weight,
                "queue_wait_s": round(j.queue_wait_s, 6),
                "preemptions": j.preemptions,
                "retries": j.slice_retries,
                "estimate_bytes": int(j.estimate),
            }
            if j.error:
                per_job[j.name]["error"] = j.error
        # Jain's fairness index over weighted device-seconds: 1.0 =
        # perfectly proportional shares, 1/N = one tenant got it all
        xs = [j.device_s / j.weight for j in self.jobs
              if j.slices > 0]
        fairness = (round((sum(xs) ** 2)
                          / (len(xs) * sum(x * x for x in xs)), 6)
                    if xs and sum(x * x for x in xs) > 0 else None)
        return {
            "policy": self.policy,
            "quantum_chunks": self.quantum_chunks,
            "slices": self._slice_idx,
            "jobs": per_job,
            "done": sum(1 for j in self.jobs if j.state == DONE),
            "failed": sum(1 for j in self.jobs if j.state == FAILED),
            "fairness_index": fairness,
            "cross_job_cache_hits": int(self.cross_job_cache_hits),
            "wall_s": round((time.perf_counter() - self._t0)
                            if self._t0 else 0.0, 6),
        }

    def close(self) -> Dict[str, Any]:
        """Write ``sched_summary`` and release the stream; idempotent.
        Unfinished resident jobs are preempted to snapshots first so no
        work is lost."""
        for j in self._resident():
            self.preempt_job(j.name, reason="scheduler close")
        out = self.summary()
        if not self._closed:
            self._record("sched_summary", out)
            if self._stream.active:
                self._stream.close(summary=False)
            self._closed = True
        return out
