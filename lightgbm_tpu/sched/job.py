"""One tenant of the multi-tenant training scheduler.

A :class:`Job` wraps the CLI/engine training loop as a resumable
generator that yields at chunk boundaries — the natural preemption
point the chunked boosting path (``tpu_boost_chunk``) already drains
at.  The scheduler advances a job a quantum of chunk dispatches at a
time (:meth:`Job.run_chunks`); between quanta the job can be
descheduled to a snapshot (:meth:`Job.preempt`) through
``utils/snapshots.py`` and later rebuilt from it, byte-identically:
the chunk step sequence is bit-exact at any split (PR 1 invariant) and
the snapshot sidecar restores the exact PRNG/score/bagging state
(PR 4 invariant), so a job trained under arbitrary slice interleaving
produces the same model file as an uninterrupted standalone run.

A job's ``health_out``/``snapshot_freq`` knobs are ignored under the
scheduler: observability is the scheduler's JSONL stream (one stream
per scheduler, not per tenant) and snapshots are preemption-driven.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..config import Config
from ..metric import default_metric_for_objective
from ..utils.log import LightGBMError, log_info, log_warning

# job lifecycle states
PENDING = "pending"        # admitted, no device state yet
RESIDENT = "resident"      # booster + dataset live on the device set
PREEMPTED = "preempted"    # descheduled to a snapshot, device state freed
DONE = "done"              # final model written
FAILED = "failed"          # slice/snapshot failure exhausted its retry


def peek_data_shape(path: str) -> Tuple[int, int]:
    """Cheap ``(rows, columns)`` of a text data file for pre-load
    admission estimates: the first line's delimiter-separated field
    count and the file's line count.  No parsing, no binning."""
    if not os.path.exists(path):
        raise LightGBMError(f"Data file {path} doesn't exist")
    with open(path, "rb") as fh:
        first = fh.readline()
        sep = b"\t" if b"\t" in first else b","
        cols = len(first.rstrip(b"\r\n").split(sep))
        rows = 1 if first.strip() else 0
        while True:
            block = fh.read(1 << 20)
            if not block:
                break
            rows += block.count(b"\n")
    return max(rows, 1), max(cols, 1)


class JobSpec:
    """A named training job: CLI-style params (``data=``,
    ``objective=``, ``output_model=``, ...) plus a fair-share weight."""

    def __init__(self, name: str, params: Dict[str, Any],
                 weight: float = 1.0):
        self.name = str(name)
        self.params = dict(params)
        self.weight = float(weight)
        if not self.name:
            raise LightGBMError("every scheduled job needs a name")
        if self.weight <= 0:
            raise LightGBMError(
                f"job {self.name}: weight must be > 0, got {weight}")


class Job:
    """One admitted tenant: its resolved config, device state when
    resident, and the scheduler's per-job accounting."""

    def __init__(self, spec: JobSpec):
        self.name = spec.name
        self.weight = spec.weight
        self.config = Config.from_params(spec.params)
        if not str(self.config.data):
            raise LightGBMError(f"job {self.name}: set data=...")
        if not str(self.config.output_model):
            raise LightGBMError(f"job {self.name}: set output_model=...")
        self.state = PENDING
        self.error = ""
        # accounting the scheduler folds per slice
        self.estimate = 0              # admission working-set bytes
        self.iters_done = 0
        self.slices = 0
        self.wall_s = 0.0
        self.device_s = 0.0
        self.counters: Dict[str, int] = {}
        self.last_eval: Dict[str, float] = {}
        self.slice_retries = 0
        self.preemptions = 0
        self.submit_t: Optional[float] = None
        self.first_slice_t: Optional[float] = None
        # device/host training state (None unless RESIDENT)
        self._booster = None
        self._train = None
        self._valids: List = []
        self._names: List[str] = []
        self._gen = None
        self._metric_names: List[str] = []
        self._resume_snap: Optional[str] = None
        self._snapshots: List[str] = []

    # ------------------------------------------------------------ admission
    def data_shape(self) -> Tuple[int, int]:
        """(num_data, num_features) estimate for admission: file peek
        minus the label column."""
        rows, cols = peek_data_shape(str(self.config.data))
        if bool(self.config.header):
            rows = max(rows - 1, 1)
        return rows, max(cols - 1, 1)

    @property
    def queue_wait_s(self) -> float:
        if self.submit_t is None:
            return 0.0
        end = self.first_slice_t if self.first_slice_t is not None \
            else time.perf_counter()
        return max(0.0, end - self.submit_t)

    @property
    def total_iterations(self) -> int:
        return int(self.config.num_iterations)

    # ------------------------------------------------------------- lifecycle
    def _build(self) -> None:
        """Construct (or reconstruct from the preemption snapshot) the
        dataset + booster, mirroring the CLI train setup order so a
        scheduled run is byte-identical to a standalone one."""
        from ..core.parser import load_file_to_dataset
        from ..models.boosting_factory import create_boosting
        from ..objective import create_objective

        cfg = self.config
        train = load_file_to_dataset(str(cfg.data), cfg)
        valids, names = [], []
        for vf in cfg.valid or []:
            valids.append(load_file_to_dataset(str(vf), cfg,
                                               reference=train))
            names.append(os.path.basename(str(vf)))
        objective = create_objective(cfg)
        if objective is not None:
            objective.init(train.metadata, train.num_data)
        booster = create_boosting(cfg, train, objective)
        if cfg.input_model and self._resume_snap is None:
            from ..basic import Booster as PyBooster
            from ..models.serialization import load_trees_into
            load_trees_into(booster,
                            PyBooster(model_file=str(cfg.input_model)))
        for name, vset in zip(names, valids):
            booster.add_valid_data(name, vset)
        metric_names = list(cfg.metric)
        if not metric_names:
            d = default_metric_for_objective(cfg.objective)
            metric_names = [d] if d else []
        booster.setup_metrics(metric_names)
        if self._resume_snap is not None:
            from ..basic import Booster as PyBooster
            from ..models.serialization import load_trees_into
            from ..utils.snapshots import restore_snapshot_state
            load_trees_into(booster,
                            PyBooster(model_file=self._resume_snap))
            it = restore_snapshot_state(booster, self._resume_snap)
            if it != self.iters_done:
                raise LightGBMError(
                    f"job {self.name}: preemption snapshot at iteration "
                    f"{it} does not match the accounted {self.iters_done}")
        self._booster, self._train = booster, train
        self._valids, self._names = valids, names
        self._metric_names = metric_names
        self._gen = self._steps()
        self.state = RESIDENT

    def _steps(self):
        """The train loop as a generator: one chunk dispatch per
        ``next()``, StopIteration on the call that completes (or
        early-stops) the run.  Step clamping mirrors cli.py so the
        dispatch sequence is identical to a standalone run."""
        cfg, booster = self.config, self._booster
        chunk = booster.boost_chunk_size()
        freqs = [f for f in (
            (cfg.metric_freq if self._metric_names else 0),) if f > 0]
        total = self.total_iterations
        while True:
            if self.iters_done >= total:
                return
            step = min(chunk, total - self.iters_done)
            for f in freqs:
                step = min(step, f - self.iters_done % f)
            stop = (booster.train_chunk(step) if step > 1
                    else booster.train_one_iter())
            it = self.iters_done + step - 1
            self.iters_done += step
            if (cfg.metric_freq > 0 and (it + 1) % cfg.metric_freq == 0
                    and self._metric_names):
                self._eval(it)
            if stop or self.iters_done >= total:
                return
            yield step

    def _eval(self, it: int) -> None:
        cfg, booster = self.config, self._booster
        rec: Dict[str, float] = {}
        if cfg.is_provide_training_metric:
            for mname, val, _ in booster.eval_train():
                rec[f"training/{mname}"] = float(val)
        for vi, _vname in enumerate(self._names):
            for mname, val, _ in booster.eval_valid(vi):
                rec[f"valid_{vi + 1}/{mname}"] = float(val)
        if rec:
            self.last_eval = rec

    # ---------------------------------------------------------- scheduling
    def run_chunks(self, n: int) -> str:
        """Advance up to ``n`` chunk boundaries; returns ``"done"``
        when the run completed (final model written) else
        ``"running"``.  Builds/rebuilds device state on demand."""
        if self.state in (DONE, FAILED):
            return self.state
        if self._gen is None:
            self._build()
        for _ in range(max(1, int(n))):
            try:
                next(self._gen)
            except StopIteration:
                self._finish()
                return DONE
        return "running"

    def preempt(self) -> Optional[str]:
        """Deschedule: flush pending trees, write a resumable snapshot
        (model + exact-state sidecar) and free the device state.
        Returns the snapshot path, or None when the job held no device
        state worth persisting.  The caller (scheduler) owns the
        ``sched/snapshot`` fault probe and its retry."""
        if self.state != RESIDENT or self._booster is None:
            self._drop()
            if self.state not in (DONE, FAILED):
                self.state = PREEMPTED if self._resume_snap else PENDING
            return None
        if int(self._booster.current_iteration()) == 0:
            # nothing trained yet: dropping device state loses nothing
            self._drop()
            self.state = PENDING
            return None
        from ..models.serialization import save_model_to_string
        from ..utils.snapshots import save_snapshot
        it = int(self._booster.current_iteration())
        snap = f"{self.config.output_model}.snapshot_iter_{it}"
        save_snapshot(self._booster, snap,
                      save_model_to_string(self._booster, self.config))
        if snap not in self._snapshots:
            self._snapshots.append(snap)
        self._resume_snap = snap
        self._drop()
        self.state = PREEMPTED
        self.preemptions += 1
        return snap

    def fail(self, exc: BaseException) -> None:
        """Per-tenant failure: record the cause and free device state;
        sibling jobs and the scheduler keep running."""
        self.error = f"{type(exc).__name__}: {exc}"
        self._drop()
        self.state = FAILED
        log_warning(f"scheduled job {self.name} failed: {self.error}")

    def _finish(self) -> None:
        from ..models.serialization import save_model_to_string
        from ..utils.file_io import atomic_write_text
        from ..utils.snapshots import state_path
        atomic_write_text(str(self.config.output_model),
                          save_model_to_string(self._booster, self.config))
        log_info(f"scheduled job {self.name}: finished "
                 f"{self.iters_done} iterations, saved model to "
                 f"{self.config.output_model}")
        self._drop()
        # the final model supersedes this job's preemption snapshots
        for snap in self._snapshots:
            for victim in (snap, state_path(snap)):
                try:
                    if os.path.exists(victim):
                        os.remove(victim)
                except OSError:
                    pass
        self._snapshots = []
        self._resume_snap = None
        self.state = DONE

    def _drop(self) -> None:
        if self._gen is not None:
            self._gen.close()
        self._gen = None
        self._booster = None
        self._train = None
        self._valids, self._names = [], []
